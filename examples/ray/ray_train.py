"""Ray executor example (reference ``examples/ray/ray_train.py``
lineage). Requires a ray installation:

    python examples/ray/ray_train.py
"""

import numpy as np


def train_fn():
    import horovod_tpu as hvt

    val = hvt.allreduce(np.array([float(hvt.rank())]), name="x",
                        average=True)
    return float(np.asarray(val)[0]), hvt.rank(), hvt.size()


if __name__ == "__main__":
    import ray

    from horovod_tpu.ray import RayExecutor

    ray.init()
    executor = RayExecutor(num_workers=2, cpus_per_worker=1)
    executor.start()
    print(executor.run(train_fn))
    executor.shutdown()
