"""GPT training with combined data/tensor/sequence parallelism — the
flagship multi-dimensional-mesh example (no reference counterpart: the
reference is data-parallel only; this is the TPU-native capability the
pjit design adds for free, SURVEY.md §2.6).

    python examples/jax/jax_gpt_train.py --dp 2 --tp 2 --sp 2
(on a virtual mesh: XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.models import GPT, GPTConfig
from horovod_tpu.models.transformer import param_partition_spec
from horovod_tpu.parallel.mesh import make_parallel_mesh


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=256)
    args = p.parse_args()

    hvt.init()
    mesh = make_parallel_mesh(dp=args.dp, sp=args.sp, tp=args.tp)
    cfg = GPTConfig(vocab_size=32000, n_layers=4, d_model=512, n_heads=8,
                    d_ff=2048, max_seq_len=args.seq, dtype=jnp.bfloat16)
    model = GPT(cfg)

    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (args.batch, args.seq)))
    params = model.init(jax.random.PRNGKey(0), tokens[:2, :8])["params"]

    pspecs = param_partition_spec(params, tp_axis="tp")
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, pspecs, is_leaf=lambda x: isinstance(x, P))
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))

    tx = hvt.DistributedOptimizer(optax.adamw(3e-4), axis_name=None)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            tgt = jnp.roll(tokens, -1, axis=-1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1].astype(jnp.float32), tgt[:, :-1]).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for step in range(args.steps):
        params, opt_state, loss = train_step(params, opt_state, tokens)
        if hvt.rank() == 0:
            print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
