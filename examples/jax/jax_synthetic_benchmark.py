"""Synthetic ResNet-50 benchmark (the reference's
``examples/pytorch/pytorch_synthetic_benchmark.py`` /
``examples/tensorflow2/tensorflow2_synthetic_benchmark.py``, TPU-native).

Data-parallel over every visible chip; the gradient allreduce is compiled
into the step by XLA. Run:

    python examples/jax/jax_synthetic_benchmark.py --batch-size 128
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu.models import ResNet50


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-chip batch size")
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--fp32", action="store_true")
    p.add_argument("--image-size", type=int, default=224,
                   help="square input resolution (small for CPU smoke)")
    args = p.parse_args()

    hvt.init()
    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    n_dev = jax.local_device_count()
    model = ResNet50(num_classes=1000, dtype=dtype)

    global_batch = args.batch_size * n_dev
    rs = np.random.RandomState(0)
    images = jnp.asarray(
        rs.randn(global_batch, args.image_size, args.image_size, 3).astype(np.float32),
        dtype=dtype)
    labels = jnp.asarray(rs.randint(0, 1000, (global_batch,)))

    variables = model.init(jax.random.PRNGKey(0), images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = hvt.DistributedOptimizer(optax.sgd(0.01), axis_name=None)
    opt_state = tx.init(params)

    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel.mesh import WORLD_AXIS, global_mesh

    mesh = global_mesh()
    images = jax.device_put(images, NamedSharding(mesh, P(WORLD_AXIS)))
    labels = jax.device_put(labels, NamedSharding(mesh, P(WORLD_AXIS)))

    @jax.jit
    def step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_stats,
                opt_state, loss)

    params, batch_stats, opt_state, _ = step(params, batch_stats,
                                             opt_state, images, labels)
    jax.block_until_ready(params)      # compile + warm

    if hvt.rank() == 0:
        print(f"Model: ResNet50, batch {args.batch_size}/chip × "
              f"{n_dev} chips, dtype {dtype.__name__}")
    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, images, labels)
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        rate = global_batch * args.num_batches_per_iter / dt
        img_secs.append(rate)
        if hvt.rank() == 0:
            print(f"Iter: {rate:.1f} img/sec")
    if hvt.rank() == 0:
        print(f"Img/sec: {np.mean(img_secs):.1f} "
              f"+- {1.96 * np.std(img_secs):.1f}")


if __name__ == "__main__":
    main()
