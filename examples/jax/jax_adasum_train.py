"""Adasum training example (the reference's
``examples/adasum/adasum_small_model.py``, TPU-native).

Adasum combines gradients scale-invariantly — robust to the effective
learning-rate inflation of plain averaging at large world sizes. Run on
any chip count that is a power of two:

    python examples/jax/jax_adasum_train.py
    HVT_ADASUM_START_LEVEL=local python examples/jax/jax_adasum_train.py
        # GPU-style hierarchical composition: host-local average, adasum
        # across hosts
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.parallel.mesh import WORLD_AXIS, global_mesh


def main():
    hvt.init()
    mesh = global_mesh()
    n = len(jax.devices())
    if n & (n - 1):
        raise SystemExit(f"Adasum needs a power-of-two chip count, got {n}")

    rs = np.random.RandomState(0)
    w_true = rs.randn(32, 4).astype(np.float32)
    X = rs.randn(n * 64, 32).astype(np.float32)
    Y = X @ w_true

    tx = optax.sgd(0.2)

    def step(params, opt_state, x, y):
        def loss_fn(p):
            return ((x @ p - y) ** 2).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        g = hvt.allreduce(g, op=hvt.Adasum)   # scale-invariant combine
        updates, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    pstep = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(WORLD_AXIS), P(WORLD_AXIS)),
        out_specs=(P(), P(), P()), check_vma=False))

    params = jnp.zeros((32, 4), jnp.float32)
    opt_state = tx.init(params)
    for i in range(200):
        params, opt_state, loss = pstep(params, opt_state,
                                        jnp.asarray(X), jnp.asarray(Y))
        if i % 50 == 0 or i == 199:
            print(f"step {i:4d}  loss {float(loss):.6f}")
    assert float(loss) < 1e-4


if __name__ == "__main__":
    main()
