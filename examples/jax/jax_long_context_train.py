"""Long-context training with ring attention + the fused flash kernel —
the sequence-parallel capability the reference framework does not have
(SURVEY.md §5.7: its only relevant primitive is alltoall).

Each device owns a sequence shard of Q/K/V; K/V shards stream around the
ring with ``lax.ppermute`` while each step runs the pallas flash kernel
on the resident block and merges via its differentiable logsumexp output
(``parallel/sequence.py``). Peak attention memory per device is
O(seq/N · seq/N) score tiles inside VMEM — never the full [seq × seq]
matrix.

    python examples/jax/jax_long_context_train.py --sp 4 --seq 2048
(on a virtual mesh: XLA_FLAGS=--xla_force_host_platform_device_count=4)
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.parallel.mesh import make_parallel_mesh
from horovod_tpu.parallel.sequence import ring_attention


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sp", type=int, default=4,
                   help="sequence-parallel axis size")
    p.add_argument("--seq", type=int, default=2048,
                   help="global sequence length")
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query attention: K/V head count "
                        "(default = --heads, i.e. MHA; must divide it)")
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--no-flash", action="store_true",
                   help="einsum block step instead of the pallas kernel")
    p.add_argument("--fp32", action="store_true")
    args = p.parse_args()
    if args.steps < 1:
        p.error("--steps must be >= 1")
    if args.seq % args.sp != 0:
        p.error(f"--seq ({args.seq}) must be divisible by --sp "
                f"({args.sp}) — each device owns one sequence shard")
    kv = args.heads if args.kv_heads is None else args.kv_heads
    if kv < 1 or args.heads % kv:
        p.error(f"--kv-heads ({kv}) must be >= 1 and divide --heads "
                f"({args.heads})")

    hvt.init()
    mesh = make_parallel_mesh(sp=args.sp)
    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    b, s, h, d = args.batch, args.seq, args.heads, args.head_dim
    dm = h * d

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, s, dm) * 0.3, dtype)
    target = jnp.asarray(rng.randn(b, s, dm) * 0.3, dtype)
    spec = P(None, "sp", None)
    x = jax.device_put(x, NamedSharding(mesh, spec))
    target = jax.device_put(target, NamedSharding(mesh, spec))

    kv_dim = kv * d
    params = {
        "wq": jnp.asarray(rng.randn(dm, dm) / np.sqrt(dm), jnp.float32),
        "wk": jnp.asarray(rng.randn(dm, kv_dim) / np.sqrt(dm), jnp.float32),
        "wv": jnp.asarray(rng.randn(dm, kv_dim) / np.sqrt(dm), jnp.float32),
        "wo": jnp.asarray(rng.randn(dm, dm) / np.sqrt(dm), jnp.float32),
    }
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    def attn_block(p, x):
        proj = lambda w, nh: (x @ w.astype(x.dtype)).reshape(b, s, nh, d)
        q = proj(p["wq"], h)
        k = proj(p["wk"], kv)
        v = proj(p["wv"], kv)
        if kv != h:
            # the ring schedule streams full head sets; broadcast K/V
            # (GQA still shrinks the projections and their grads)
            k = jnp.repeat(k, h // kv, axis=-2)
            v = jnp.repeat(v, h // kv, axis=-2)
        o = ring_attention(q, k, v, mesh=mesh, causal=True,
                           use_flash=not args.no_flash)
        return o.reshape(b, s, dm) @ p["wo"].astype(x.dtype)

    @jax.jit
    def step(params, opt, x, target):
        def loss_fn(p):
            out = attn_block(p, x).astype(jnp.float32)
            return ((out - target.astype(jnp.float32)) ** 2).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt)
        return optax.apply_updates(params, updates), opt, loss

    for i in range(args.steps):
        params, opt, loss = step(params, opt, x, target)
        if i == 0 or (i + 1) % 5 == 0:
            print(f"step {i + 1}: loss {float(loss):.5f}", flush=True)
    final = float(loss)
    assert np.isfinite(final), "training diverged"
    print(f"final loss {final:.5f} (seq={s} over {args.sp}-way ring, "
          f"flash={'off' if args.no_flash else 'on'}, "
          f"heads={h}/{kv} kv)", flush=True)


if __name__ == "__main__":
    main()
