"""PyTorch elastic training — the analog of reference
``examples/elastic/pytorch/pytorch_mnist_elastic.py`` (one of
BASELINE.json's benchmark configs):

    hvtrun --min-np 2 --max-np 4 -np 2 \
        --host-discovery-script ./discover.sh \
        python examples/elastic/pytorch_elastic_train.py

``TorchState`` snapshots model + optimizer + progress scalars; on worker
loss the surviving ranks roll back to the last ``commit()`` and the job
continues at the reduced (or grown) world size — reference
``torch/elastic/state.py`` semantics.
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F
import torch.optim as optim

import jax

jax.config.update("jax_platforms", "cpu")   # one engine proc per slot

import horovod_tpu.torch as hvd               # noqa: E402


def make_model():
    torch.manual_seed(0)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batches-per-epoch", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    hvd.init()

    model = make_model()
    optimizer = optim.SGD(model.parameters(),
                          lr=args.lr * hvd.size(), momentum=0.9)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    # synthetic regression task with a fixed ground truth so the loss
    # decreases monotonically across elastic events
    rs = np.random.RandomState(1234)
    w_true = rs.randn(8, 4).astype(np.float32)

    @hvd.elastic.run
    def train(state):
        while state.epoch < args.epochs:
            # resume mid-epoch after a restart (state.batch > 0)
            for batch in range(state.batch, args.batches_per_epoch):
                rs_b = np.random.RandomState(
                    1000 * state.epoch + batch + hvd.rank())
                x = torch.from_numpy(
                    rs_b.randn(args.batch_size, 8).astype(np.float32))
                y = x @ torch.from_numpy(w_true)
                optimizer.zero_grad()
                loss = F.mse_loss(model(x), y)
                loss.backward()
                optimizer.step()
                state.batch = batch + 1
                state.commit()    # snapshot + host-update check
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss {loss.item():.4f} "
                      f"size={hvd.size()}")
            state.epoch += 1
            state.batch = 0
            state.commit()
        return model

    state = hvd.elastic.TorchState(model=model, optimizer=optimizer,
                                   epoch=0, batch=0)
    train(state)
    if hvd.rank() == 0:
        print(f"done: epochs={args.epochs} final size={hvd.size()}")


if __name__ == "__main__":
    main()
