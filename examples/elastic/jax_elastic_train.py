"""Elastic training example (reference
``examples/elastic/pytorch/pytorch_mnist_elastic.py`` shape):

    hvtrun --min-np 2 --max-np 4 -np 2 \
        --host-discovery-script ./discover.sh \
        python examples/elastic/jax_elastic_train.py

The job survives worker loss (restore from last commit) and picks up new
hosts at the next commit boundary."""

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")   # one engine proc per slot

import horovod_tpu as hvt                     # noqa: E402
from horovod_tpu.elastic.state import ObjectState  # noqa: E402

hvt.init()


@hvt.elastic.run
def train(state):
    rs = np.random.RandomState(0)
    w_true = np.arange(4, dtype=np.float32)
    while state.epoch < 20:
        X = rs.randn(32, 4).astype(np.float32)
        y = X @ w_true
        grad = -2 * X.T @ (y - X @ state.w) / len(X)
        # gradient allreduce across the current world
        grad = np.asarray(hvt.allreduce(grad, name="grad", average=True))
        state.w = state.w - 0.05 * grad
        state.epoch += 1
        state.commit()     # snapshot + host-update check
    return state.w


if __name__ == "__main__":
    state = ObjectState(w=np.zeros(4, np.float32), epoch=0)
    w = train(state)
    print(f"rank {hvt.rank()}/{hvt.size()} final w={np.round(w, 3)}")
