"""Keras synthetic-data throughput benchmark — the analog of reference
``examples/tensorflow2/tensorflow2_keras_synthetic_benchmark.py``:

    hvtrun -np 2 python examples/keras/keras_synthetic_benchmark.py \
        --model ResNet50 --batch-size 32

Measures img/sec through ``model.fit``-style training with the
distributed optimizer. ``--model`` accepts any ``tf.keras.applications``
architecture name (constructed with ``weights=None`` — no downloads);
``--small`` swaps in a compact CNN for smoke tests and CPU machines.
"""

import argparse
import time

import numpy as np
import tensorflow as tf

import horovod_tpu.keras as hvd


def small_cnn(num_classes=1000):
    return tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, strides=2, activation="relu"),
        tf.keras.layers.Conv2D(32, 3, strides=2, activation="relu"),
        tf.keras.layers.GlobalAveragePooling2D(),
        tf.keras.layers.Dense(num_classes),
    ])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="ResNet50",
                   help="tf.keras.applications model name")
    p.add_argument("--small", action="store_true",
                   help="compact CNN instead of a keras.applications "
                        "model (smoke tests)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-warmup-batches", type=int, default=2)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--num-iters", type=int, default=3)
    args = p.parse_args()

    hvd.init()

    if args.small:
        model = small_cnn()
    else:
        model = getattr(tf.keras.applications, args.model)(
            weights=None, input_shape=(args.image_size, args.image_size,
                                       3))
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(0.01 * hvd.size(), momentum=0.9))
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    rng = np.random.RandomState(hvd.rank())
    data = tf.constant(rng.randn(args.batch_size, args.image_size,
                                 args.image_size, 3).astype(np.float32))
    target = tf.constant(rng.randint(0, 1000, args.batch_size))

    # broadcast initial weights so ranks agree (build via one forward)
    model(data[:1])
    hvd.broadcast_global_variables(0, model=model)

    def benchmark_step():
        with tf.GradientTape() as tape:
            loss = loss_fn(target, model(data, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.perf_counter() - t0
        img_secs.append(args.batch_size * args.num_batches_per_iter / dt)

    if hvd.rank() == 0:
        mean, std = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per proc: {mean:.1f} +- {std:.1f}")
        print(f"Total img/sec on {hvd.size()} proc(s): "
              f"{mean * hvd.size():.1f}")


if __name__ == "__main__":
    main()
