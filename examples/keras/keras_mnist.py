"""Keras MNIST training with the full distributed callback set — the
analog of reference ``examples/tensorflow2/tensorflow2_keras_mnist.py``
(one of BASELINE.json's benchmark configs):

    hvtrun -np 2 python examples/keras/keras_mnist.py --epochs 2

Differences from the reference, by design:
- Synthetic MNIST-shaped data (this image has no dataset egress); swap in
  ``tf.keras.datasets.mnist.load_data()`` on a connected machine.
- No GPU pinning block: XLA owns TPU device placement, and the eager
  collective path runs one engine process per slot.
Everything else mirrors the reference flow line for line: scaled LR,
``DistributedOptimizer``, broadcast + metric-average + LR-warmup
callbacks, rank-0-only checkpointing, size-scaled steps_per_epoch.
"""

import argparse
import os

import numpy as np
import tensorflow as tf

import horovod_tpu.keras as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="default: 500 // size (the reference's scaling)")
    p.add_argument("--checkpoint-dir", default=".")
    args = p.parse_args()

    hvd.init()

    # Synthetic stand-in for mnist.load_data(): label-dependent means so
    # the model has signal to fit (loss visibly decreases).
    rng = np.random.RandomState(hvd.rank())
    n = 4096
    labels = rng.randint(0, 10, n).astype(np.int64)
    images = (rng.rand(n, 28, 28).astype(np.float32) * 0.5
              + labels[:, None, None] / 20.0)

    dataset = tf.data.Dataset.from_tensor_slices(
        (tf.cast(images[..., tf.newaxis], tf.float32),
         tf.cast(labels, tf.int64)))
    dataset = dataset.repeat().shuffle(10000).batch(args.batch_size)

    mnist_model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(32, [3, 3], activation="relu"),
        tf.keras.layers.Conv2D(64, [3, 3], activation="relu"),
        tf.keras.layers.MaxPooling2D(pool_size=(2, 2)),
        tf.keras.layers.Dropout(0.25),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dropout(0.5),
        tf.keras.layers.Dense(10, activation="softmax"),
    ])

    # Scale the learning rate by the worker count (linear scaling rule),
    # then warm it up over the first epochs — reference lines 52-80.
    scaled_lr = 0.001 * hvd.size()
    opt = hvd.DistributedOptimizer(tf.optimizers.Adam(scaled_lr))

    mnist_model.compile(
        loss=tf.losses.SparseCategoricalCrossentropy(),
        optimizer=opt, metrics=["accuracy"],
        # gradients must flow through the wrapper, not a fused train_function
        run_eagerly=True)

    steps = args.steps_per_epoch or max(1, 500 // hvd.size())
    callbacks = [
        hvd.BroadcastGlobalVariablesCallback(0),
        hvd.MetricAverageCallback(),
        hvd.LearningRateWarmupCallback(initial_lr=scaled_lr,
                                       warmup_epochs=3,
                                       steps_per_epoch=steps),
    ]
    # Checkpoint on rank 0 only so workers don't corrupt each other's
    # files (reference line 83).
    if hvd.rank() == 0:
        callbacks.append(tf.keras.callbacks.ModelCheckpoint(
            os.path.join(args.checkpoint_dir,
                         "checkpoint-{epoch}.weights.h5"),
            save_weights_only=True))

    verbose = 1 if hvd.rank() == 0 else 0
    history = mnist_model.fit(dataset, steps_per_epoch=steps,
                              callbacks=callbacks, epochs=args.epochs,
                              verbose=verbose)
    if hvd.rank() == 0:
        print(f"final loss {history.history['loss'][-1]:.4f} "
              f"(size={hvd.size()})")


if __name__ == "__main__":
    main()
