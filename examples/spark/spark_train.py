"""Horovod-on-Spark example (reference ``examples/spark/pytorch/``
lineage). Requires pyspark:

    spark-submit examples/spark/spark_train.py
"""

import numpy as np


def train_fn():
    import horovod_tpu as hvt

    val = hvt.allreduce(np.array([float(hvt.rank() + 1)]), name="s",
                        average=False)
    return float(np.asarray(val)[0]), hvt.rank(), hvt.size()


if __name__ == "__main__":
    import horovod_tpu.spark as hvt_spark

    results = hvt_spark.run(train_fn, num_proc=2)
    print(results)
