"""Spark Estimator example (reference ``examples/spark/keras/``
lineage: DataFrame → distributed fit → Transformer, with Store-backed
checkpointing). Requires pyspark:

    spark-submit examples/spark/spark_estimator.py
"""

import numpy as np
import torch


class PrintLoss:
    def on_epoch_end(self, epoch, logs):
        print(f"epoch {epoch}: loss {logs['loss']:.4f}")


def main():
    from pyspark.sql import SparkSession

    from horovod_tpu.spark import Store, TorchEstimator, TorchModel

    spark = SparkSession.builder.master("local[2]").getOrCreate()
    rs = np.random.RandomState(0)
    X = rs.randn(512, 3).astype(np.float32)
    y = X @ np.asarray([0.5, -1.0, 2.0], np.float32)
    df = spark.createDataFrame(
        [(float(a), float(b), float(c), float(t))
         for (a, b, c), t in zip(X, y)],
        ["a", "b", "c", "y"])

    store = Store.create("/tmp/hvt_spark_store")
    est = TorchEstimator(
        model=torch.nn.Linear(3, 1),
        optimizer_fn=lambda p: torch.optim.SGD(p, lr=0.1),
        feature_cols=["a", "b", "c"], label_col="y",
        num_proc=2, epochs=5, batch_size=32,
        store=store, run_id="example-run", callbacks=[PrintLoss()])
    model = est.fit(df)

    scored = model.transform(df)
    scored.select("y", "prediction").show(5)

    # restore from the store anywhere
    restored = TorchModel.load(store, "example-run", torch.nn.Linear(3, 1))
    print("restored prediction[0]:",
          float(restored._predict_arrays(X[:1])[0]))


if __name__ == "__main__":
    main()
