"""Elastic TF/Keras training with TensorFlowKerasState — the reference's
``examples/elastic/tensorflow2_mnist_elastic.py`` pattern.

Launch with an elastic world; the job survives worker loss (rollback to
the last commit) and absorbs added hosts (re-rendezvous at commit
points):

    hvtrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh \
        python examples/tensorflow/tf_elastic_train.py
"""

import numpy as np

import horovod_tpu as hvt
import horovod_tpu.tensorflow as hvt_tf
import horovod_tpu.tensorflow.elastic as tfe


def main():
    import tensorflow as tf

    hvt.init()
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(32, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    model(tf.zeros([1, 20]))
    opt = tf.keras.optimizers.SGD(0.05)
    opt.build(model.trainable_variables)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    state = tfe.TensorFlowKerasState(model, opt, epoch=0, batch=0)

    @tfe.run
    def train(state):
        rs = np.random.RandomState(1234)
        data = rs.randn(512, 20).astype(np.float32)
        labels = rs.randint(0, 10, (512,))
        n_batches = 16
        while state.epoch < 5:
            loss = None   # a rollback can resume at the epoch boundary
            for b in range(state.batch, n_batches):
                lo = b * 32
                x = tf.constant(data[lo:lo + 32])
                y = tf.constant(labels[lo:lo + 32])
                with hvt_tf.DistributedGradientTape(
                        tf.GradientTape()) as tape:
                    loss = loss_fn(y, model(x, training=True))
                grads = tape.gradient(loss, model.trainable_variables)
                opt.apply_gradients(
                    zip(grads, model.trainable_variables))
                state.batch = b + 1
                state.commit()      # snapshot + host-update check
            if hvt.rank() == 0 and loss is not None:
                print(f"epoch {state.epoch}  loss {float(loss):.4f}  "
                      f"world {hvt.size()}", flush=True)
            state.epoch += 1
            state.batch = 0
            state.commit()

    train(state)


if __name__ == "__main__":
    main()
