"""TF eager training with DistributedGradientTape (the reference's
``examples/tensorflow2/tensorflow2_mnist.py`` pattern).

Requires a TensorFlow install; launch one process per slot:

    hvtrun -np 4 python examples/tensorflow/tf_tape_train.py

The binding bridges tensors through numpy (see README "Known limits") —
compiled TPU training belongs to horovod_tpu.jax; this surface exists
for porting eager TF code with minimal changes.
"""

import numpy as np

import horovod_tpu.tensorflow as hvt_tf


def main():
    import tensorflow as tf

    hvt_tf.init()
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    opt = tf.keras.optimizers.SGD(0.05)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    rs = np.random.RandomState(hvt_tf.rank())
    for step in range(200):
        x = tf.constant(rs.randn(64, 20), tf.float32)
        y = tf.constant(rs.randint(0, 10, (64,)))
        with hvt_tf.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = loss_fn(y, model(x, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if step == 0:
            # rank 0's initial weights everywhere (reference
            # BroadcastGlobalVariablesCallback)
            hvt_tf.broadcast_variables(model.variables, root_rank=0)
        if step % 50 == 0 and hvt_tf.rank() == 0:
            print(f"step {step:4d}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
