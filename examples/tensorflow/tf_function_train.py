"""Compiled TF training through the native custom ops — the analog of the
reference's ``examples/tensorflow2/tensorflow2_synthetic_benchmark.py``
``@tf.function`` path. The collectives are real graph ops
(``csrc/tf_ops.cc``), so the whole step stays inside one traced function.

Build the op library once, then launch one process per slot:

    make -C horovod_tpu/csrc tf_ops
    hvtrun -np 4 python examples/tensorflow/tf_function_train.py
"""

import numpy as np

import horovod_tpu.tensorflow as hvt_tf


def main():
    import tensorflow as tf

    hvt_tf.init()
    if hvt_tf._native() is None:
        print("native op library not active (single process or not "
              "built); falling back to the eager numpy bridge")

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    opt = hvt_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)
    model(tf.zeros([1, 20]))  # build weights
    hvt_tf.broadcast_variables(model.variables, root_rank=0)

    @tf.function
    def train_step(x, y):
        with tf.GradientTape() as tape:
            loss = loss_fn(y, model(x, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        # DistributedOptimizer allreduces inside the traced graph
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    rs = np.random.RandomState(hvt_tf.rank())
    for step in range(200):
        x = tf.constant(rs.randn(64, 20), tf.float32)
        y = tf.constant(rs.randint(0, 10, (64,)))
        loss = train_step(x, y)
        if step % 50 == 0 and hvt_tf.rank() == 0:
            print(f"step {step:4d}  loss {float(loss):.4f}  "
                  f"world {int(hvt_tf.size_op())}")


if __name__ == "__main__":
    main()
