"""PyTorch synthetic benchmark through the eager engine path (reference
``examples/pytorch/pytorch_synthetic_benchmark.py``):

    hvtrun -np 2 python examples/torch/pytorch_synthetic_benchmark.py
"""

import argparse
import time

import numpy as np
import torch
import torch.nn as nn
import torch.optim as optim

import jax

jax.config.update("jax_platforms", "cpu")

import horovod_tpu.torch as hvd               # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(0)
    model = nn.Sequential(
        nn.Conv2d(3, 32, 3, stride=2), nn.ReLU(),
        nn.Conv2d(32, 64, 3, stride=2), nn.ReLU(),
        nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(64, 10))
    optimizer = optim.SGD(model.parameters(), lr=0.01)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 3, 64, 64)
    target = torch.randint(0, 10, (args.batch_size,))
    loss_fn = nn.CrossEntropyLoss()

    def benchmark_step():
        optimizer.zero_grad()
        loss = loss_fn(model(data), target)
        loss.backward()
        optimizer.step()

    benchmark_step()    # warm up
    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.perf_counter() - t0
        img_secs.append(args.batch_size * args.num_batches_per_iter / dt)
    if hvd.rank() == 0:
        print(f"Img/sec per proc: {np.mean(img_secs):.1f} "
              f"+- {1.96 * np.std(img_secs):.1f}")
        print(f"Total img/sec on {hvd.size()} proc(s): "
              f"{hvd.size() * np.mean(img_secs):.1f}")


if __name__ == "__main__":
    main()
