"""ImageNet-style ResNet-50 training with fp16 gradient compression and
grouped (fused) allreduce — the analog of reference
``examples/pytorch/pytorch_imagenet_resnet50.py`` (one of BASELINE.json's
benchmark configs):

    hvtrun -np 2 python examples/torch/pytorch_imagenet_resnet50.py \
        --batch-size 32 --batches-per-allreduce 2

Reference features carried over:
- ``compression=hvd.Compression.fp16`` (reference ``--fp16-allreduce``)
- ``num_groups`` grouped fusion (reference's tensor-fusion knob surfaced
  as an explicit group count)
- ``backward_passes_per_step`` local gradient aggregation
  (``--batches-per-allreduce``)
- linear-scaling LR with gradual warmup over the first 5 epochs
- ``broadcast_parameters`` + ``broadcast_optimizer_state`` from rank 0

Differences, by design: synthetic ImageNet-shaped data (no dataset
egress here; plug a ``DataLoader`` over ImageFolder on a real cluster),
and a compact in-repo bottleneck ResNet-50 (torchvision is not in this
image; same stage layout [3, 4, 6, 3], same parameter scale).
"""

import argparse
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F
import torch.optim as optim

import jax

jax.config.update("jax_platforms", "cpu")   # one engine proc per slot

import horovod_tpu.torch as hvd               # noqa: E402


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * self.expansion
        self.conv1 = nn.Conv2d(cin, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride=stride, padding=1,
                               bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, cout, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.proj = None
        if stride != 1 or cin != cout:
            self.proj = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride=stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        r = x if self.proj is None else self.proj(x)
        y = F.relu(self.bn1(self.conv1(x)))
        y = F.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return F.relu(y + r)


def resnet50(num_classes=1000, width=64):
    """torchvision-equivalent stage layout; ``width`` shrinks the model
    for smoke tests."""
    stages, cin = [], width

    def stage(n_blocks, w, stride):
        nonlocal cin
        blocks = []
        for i in range(n_blocks):
            blocks.append(Bottleneck(cin, w, stride if i == 0 else 1))
            cin = w * Bottleneck.expansion
        return nn.Sequential(*blocks)

    stem = nn.Sequential(
        nn.Conv2d(3, width, 7, stride=2, padding=3, bias=False),
        nn.BatchNorm2d(width), nn.ReLU(),
        nn.MaxPool2d(3, stride=2, padding=1))
    for n, w, s in [(3, width, 1), (4, width * 2, 2), (6, width * 4, 2),
                    (3, width * 8, 2)]:
        stages.append(stage(n, w, s))
    return nn.Sequential(stem, *stages, nn.AdaptiveAvgPool2d(1),
                         nn.Flatten(), nn.Linear(cin, num_classes))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--steps-per-epoch", type=int, default=4)
    p.add_argument("--base-lr", type=float, default=0.0125)
    p.add_argument("--warmup-epochs", type=float, default=5)
    p.add_argument("--batches-per-allreduce", type=int, default=1,
                   help="local gradient aggregation before the exchange "
                        "(reference --batches-per-allreduce)")
    p.add_argument("--no-fp16-allreduce", action="store_true",
                   help="disable fp16 gradient compression (reference "
                        "--fp16-allreduce, inverted: on by default here "
                        "to exercise the headline config)")
    p.add_argument("--num-groups", type=int, default=2,
                   help="gradient fusion groups (reference tensor-fusion)")
    p.add_argument("--width", type=int, default=64,
                   help="channel width; 8 gives a smoke-test-sized model")
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(0)

    model = resnet50(width=args.width)
    # Linear LR scaling by total batch count (reference: lr * size *
    # batches_per_allreduce), warmed up below.
    scaled_lr = (args.base_lr * hvd.size() * args.batches_per_allreduce)
    optimizer = optim.SGD(model.parameters(), lr=scaled_lr,
                          momentum=0.9, weight_decay=5e-5)

    compression = (hvd.Compression.none if args.no_fp16_allreduce
                   else hvd.Compression.fp16)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression,
        backward_passes_per_step=args.batches_per_allreduce,
        num_groups=args.num_groups)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    # synthetic ImageNet-shaped batch, one per rank
    rng = np.random.RandomState(hvd.rank())
    data = torch.from_numpy(
        rng.randn(args.batch_size, 3, args.image_size,
                  args.image_size).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, 1000, args.batch_size))

    def warmup_lr(epoch_frac):
        # gradual warmup (Goyal et al.): ramp from base_lr to scaled_lr
        if epoch_frac >= args.warmup_epochs:
            return scaled_lr
        ramp = epoch_frac / args.warmup_epochs
        return args.base_lr * hvd.size() * args.batches_per_allreduce \
            * ramp + args.base_lr * (1 - ramp)

    model.train()
    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        for step in range(args.steps_per_epoch):
            lr = warmup_lr(epoch + step / args.steps_per_epoch)
            for g in optimizer.param_groups:
                g["lr"] = lr
            optimizer.zero_grad()
            # accumulate locally; the exchange fires on the Nth backward
            for _ in range(args.batches_per_allreduce):
                loss = F.cross_entropy(model(data), target)
                loss.backward()
            optimizer.step()
        dt = time.perf_counter() - t0
        imgs = (args.batch_size * args.batches_per_allreduce
                * args.steps_per_epoch * hvd.size())
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {loss.item():.3f} "
                  f"lr {lr:.4f} {imgs / dt:.1f} img/sec total")


if __name__ == "__main__":
    main()
