#!/usr/bin/env bash
# CI entry point — the single command rounds/reviewers run to validate the
# tree (the reference pins its matrix in .buildkite/gen-pipeline.sh; this
# is the same intent for one TPU/CPU host).
#
#   ./ci.sh            # full: build + lint + tests + dryrun + bench smoke
#   ./ci.sh --fast     # inner loop: quick-marked tests only (~minutes
#                      # vs ~37 min full on the 1-core host), skip the
#                      # bench smoke
#   ./ci.sh --chaos    # build + the fault-injection / failure-
#                      # containment suite only (SIGKILL/SIGSTOP gangs,
#                      # deadline bounds, abort metrics)
#   ./ci.sh --lint     # cross-language contract linter only (~1 s, no
#                      # build): C API parity, stats-slot ABI, event
#                      # kinds / frame flags, env-var docs coverage
#   ./ci.sh --sanitize # TSan + UBSan engine builds + the sanitizer
#                      # gang suite (one command instead of the
#                      # hand-assembled HVT_CORE_LIB/LD_PRELOAD dance)
#   ./ci.sh --loadtest # build + a tiny loopback ReplicaGang replay
#                      # (horovod_tpu.serving.loadgen --smoke) + the
#                      # artifact schema check
#   ./ci.sh --perfgate # build + perf-regression gate: loopback sweep +
#                      # flight-recorded gang, analyzed and diffed
#                      # against benchmarks/perf_baseline.json (fails
#                      # on >2x p50 regressions; band overridable via
#                      # HVT_PERFGATE_MAX_RATIO)
#   ./ci.sh --perfgate-rebaseline  # refresh the committed baseline
#   ./ci.sh --scale    # build + the simulated-gang control-plane
#                      # harness at a small rank count (star vs tree
#                      # over loopback) + the artifact schema check
#   ./ci.sh --codec    # build + a quick wire-codec sweep over a faked
#                      # 2-host gang (every registry codec, exact byte
#                      # counters + relerr + EF convergence A/B) +
#                      # schema --check of the fresh AND committed
#                      # benchmarks/r09_codec_sweep.json artifacts
#   ./ci.sh --soak     # build + the self-healing chaos campaign
#                      # (benchmarks/soak_transient.py + the reconnect
#                      # gang suite): seeded randomized transient
#                      # faults over a 4-proc gang, asserting
#                      # bit-identical results and zero aborts
#   ./ci.sh --servesoak # build + the serving gang suite (batching
#                      # determinism, lane-pool parity) + an 8-rank
#                      # mixed-tenant serving soak smoke (chaos + host
#                      # kill + autoscaler re-shard over MiniEngine
#                      # workers) + schema/claim --check of the fresh
#                      # AND committed benchmarks/r15_serving_soak.json
#   ./ci.sh --elastic  # build + the checkpointless-recovery gangs
#                      # (kill-a-rank peer rebuild + restart-from-
#                      # checkpoint baseline over a REAL ElasticDriver)
#                      # + a 16-rank kill-a-host smoke capture and
#                      # schema --check of the fresh AND committed
#                      # benchmarks/r14_elastic_recovery.json
#   ./ci.sh --uring    # build + a quick transport-level link-backend
#                      # A/B (tcp vs io_uring ping-pong through the
#                      # PumpDuplex seam, syscalls-per-step column) +
#                      # claim --check of the fresh AND committed
#                      # benchmarks/r18_uring_sweep.json artifacts
#   ./ci.sh --obs      # build + the fleet-telemetry smoke: an 8-rank
#                      # direct-vs-leader-aggregated push pair over a
#                      # live /statusz rendezvous server, incl. the
#                      # hvt_top --once --json round-trip, plus schema
#                      # --check of the fresh AND committed
#                      # benchmarks/r13_telemetry_scaling.json
#   ./ci.sh --fuzz     # wire-protocol lane: the hvt_lint proto pass
#                      # (grammar extraction gate), a UBSan decoder
#                      # build, the seeded deterministic frame-fuzz
#                      # campaign (fixed mutant count per decoder
#                      # family) and the committed tests/corpus replay
#                      # through hvt_decode_probe
#
# Stages:
#   1. build the C++ core engine (csrc -> libhvt_core.so) + the clang
#      -Wthread-safety `tidy` gate (skips when clang is absent)
#   2. contract lint (hvt_lint; also emits the C-API symbol list the
#      nm export check consumes)
#   3. full test suite (8-device virtual CPU mesh; includes the
#      multi-process engine/launcher/elastic integration suites)
#   4. driver multi-chip dryrun: dp/sp/tp + MoE ep + GPipe pp on an
#      8-device mesh with exact single-device parity checks
#   5. bench smoke: tiny ResNet block through bench.py end to end
#      (CPU shapes; validates the harness, not the numbers)
set -euo pipefail
cd "$(dirname "$0")"

FAST=0
CHAOS=0
SANITIZE=0
LOADTEST=0
PERFGATE=0
REBASELINE=0
SCALE=0
CODEC=0
SOAK=0
OBS=0
ELASTIC=0
SERVESOAK=0
URING_LANE=0
FUZZ=0
[[ "${1:-}" == "--fast" ]] && FAST=1
[[ "${1:-}" == "--chaos" ]] && CHAOS=1
[[ "${1:-}" == "--sanitize" ]] && SANITIZE=1
[[ "${1:-}" == "--loadtest" ]] && LOADTEST=1
[[ "${1:-}" == "--perfgate" ]] && PERFGATE=1
[[ "${1:-}" == "--perfgate-rebaseline" ]] && REBASELINE=1
[[ "${1:-}" == "--scale" ]] && SCALE=1
[[ "${1:-}" == "--codec" ]] && CODEC=1
[[ "${1:-}" == "--soak" ]] && SOAK=1
[[ "${1:-}" == "--obs" ]] && OBS=1
[[ "${1:-}" == "--elastic" ]] && ELASTIC=1
[[ "${1:-}" == "--servesoak" ]] && SERVESOAK=1
[[ "${1:-}" == "--uring" ]] && URING_LANE=1
[[ "${1:-}" == "--fuzz" ]] && FUZZ=1

if [[ "${1:-}" == "--lint" ]]; then
  # pure text analysis — no build, no jax session, ~1 s
  python -m horovod_tpu.tools.hvt_lint
  echo "CI OK (lint)"
  exit 0
fi

# Hard wall-clock guard around every pytest stage: a failure-containment
# regression must FAIL CI (timeout rc 124), never stall it — the gang
# tests hold raw subprocesses that a hung collective would otherwise
# park forever.
PYTEST_GUARD_SEC=${PYTEST_GUARD_SEC:-3600}
run_pytest() {
  timeout -k 30 "$PYTEST_GUARD_SEC" python -m pytest "$@"
}

echo "=== [1/5] build C++ engine ==="
make -C horovod_tpu/csrc -j
make -C horovod_tpu/csrc tf_ops   # no-op when TF is not importable
make -C horovod_tpu/csrc tidy    # clang -Wthread-safety (skips w/o clang)

# Post-build link smoke check: the seed shipped a .so with an unresolved
# shm_open that silently skipped every engine test until PR 1 (see
# CHANGES.md NOTE). A dlopen via ctypes catches load-time breakage;
# `ldd -r` catches lazily-bound undefined symbols dlopen won't touch.
CORE_SO=horovod_tpu/csrc/build/libhvt_core.so
python -c "import ctypes; ctypes.CDLL('$CORE_SO'); print('ctypes load OK')"
if command -v ldd >/dev/null 2>&1; then
  UNDEF=$(ldd -r "$CORE_SO" 2>&1 | grep -i "undefined symbol" || true)
  if [[ -n "$UNDEF" ]]; then
    echo "FATAL: undefined symbols in $CORE_SO:" >&2
    echo "$UNDEF" >&2
    exit 1
  fi
  echo "ldd -r OK (no undefined symbols)"
fi

# The rebuilt .so must export the full C API surface — a stale build
# dir can silently serve an old .so whose missing symbols make the
# Python bridge degrade to zeros. The symbol list comes from the lint's
# c_api.cc parse (single source of truth), so adding a C API in a
# future PR can never silently skip this check.
REQUIRED_SYMS="$(python -m horovod_tpu.tools.hvt_lint --emit-symbols)"
[[ -n "$REQUIRED_SYMS" ]] || { echo "FATAL: --emit-symbols came back empty" >&2; exit 1; }
# snapshot nm once: `nm | grep -q` under pipefail races SIGPIPE (grep -q
# exits on first match, nm dies 141, the pipeline "fails" on a hit)
NM_OUT="$(nm -D "$CORE_SO" 2>/dev/null || true)"
for sym in $REQUIRED_SYMS; do
  if ! grep -q " T $sym\$" <<<"$NM_OUT"; then
    echo "FATAL: $CORE_SO does not export $sym (stale build?)" >&2
    exit 1
  fi
done
echo "C API symbol check OK ($(echo $REQUIRED_SYMS | wc -w) symbols)"

# io_uring kernel-capability probe (PR 18): decides whether the chaos /
# soak lanes can run their specs under BOTH link backends. A failed
# probe (old kernel, seccomp, container policy) is not an error — the
# engine falls back to tcp and the io_uring arms are skipped.
URING_OK=$(python -c "from horovod_tpu.engine import native; \
print(1 if native.uring_supported() else 0)")
if [[ "$URING_OK" == "1" ]]; then
  echo "io_uring kernel probe: supported (chaos/soak run both backends)"
else
  echo "io_uring kernel probe: unsupported (tcp-only)"
fi

if [[ "$CHAOS" == "1" ]]; then
  echo "=== [2/2] chaos / failure-containment suite ==="
  run_pytest tests/test_failure_containment.py \
    tests/test_transport_backends.py -q
  if [[ "$URING_OK" == "1" ]]; then
    echo "--- chaos pass 2: HVT_LINK_BACKEND=io_uring ---"
    HVT_LINK_BACKEND=io_uring run_pytest \
      tests/test_failure_containment.py -q
  fi
  echo "CI OK (chaos)"
  exit 0
fi

if [[ "$SOAK" == "1" ]]; then
  echo "=== [2/3] self-healing reconnect gang suite ==="
  # the session-layer specs are parameterized over both link backends
  # inside the suite (io_uring variants self-skip on a failed probe)
  run_pytest tests/test_self_healing.py -q
  echo "=== [3/3] seeded transient-fault soak ==="
  ART=$(mktemp /tmp/hvt_soak_XXXX.json)
  timeout -k 30 "$PYTEST_GUARD_SEC" \
    python benchmarks/soak_transient.py --rounds 4 --out "$ART"
  echo "soak artifact: $ART"
  if [[ "$URING_OK" == "1" ]]; then
    echo "--- soak pass 2: HVT_LINK_BACKEND=io_uring ---"
    ART2=$(mktemp /tmp/hvt_soak_uring_XXXX.json)
    HVT_LINK_BACKEND=io_uring timeout -k 30 "$PYTEST_GUARD_SEC" \
      python benchmarks/soak_transient.py --rounds 2 --out "$ART2"
    echo "io_uring soak artifact: $ART2"
  fi
  echo "CI OK (soak)"
  exit 0
fi

if [[ "$URING_LANE" == "1" ]]; then
  echo "=== [2/2] link-backend sweep smoke (transport-level A/B) ==="
  ART=$(mktemp /tmp/hvt_uring_XXXX.json)
  timeout -k 30 "$PYTEST_GUARD_SEC" \
    python benchmarks/engine_scaling.py --uring --quick --out "$ART"
  python benchmarks/engine_scaling.py --check "$ART"
  # the committed artifact must also still satisfy its claim gates
  python benchmarks/engine_scaling.py --check \
    benchmarks/r18_uring_sweep.json
  echo "CI OK (uring)"
  exit 0
fi

if [[ "$FUZZ" == "1" ]]; then
  echo "=== [2/4] wire-protocol grammar gate (hvt_lint proto) ==="
  python -m horovod_tpu.tools.hvt_lint proto
  echo "=== [3/4] UBSan decoder build ==="
  make -C horovod_tpu/csrc ubsan
  FUZZ_CORE="$PWD/horovod_tpu/csrc/build-ubsan/libhvt_core.so"
  UBSAN_LIB="$(gcc -print-file-name=libubsan.so 2>/dev/null || true)"
  FUZZ_ENV=()
  if [[ "$UBSAN_LIB" == /* && -e "$UBSAN_LIB" ]]; then
    # halt_on_error: any UB report inside a decoder aborts the
    # campaign — a typed rejection must come from C++ control flow,
    # never from UB that happened to not crash
    FUZZ_ENV=(LD_PRELOAD="$UBSAN_LIB"
              UBSAN_OPTIONS="halt_on_error=1 print_stacktrace=1")
  else
    echo "WARN: libubsan not found — campaign runs on the" \
         "uninstrumented production build" >&2
    FUZZ_CORE="$PWD/horovod_tpu/csrc/build/libhvt_core.so"
  fi
  echo "=== [4/4] deterministic frame-fuzz campaign + corpus replay ==="
  # fixed mutant count + fixed seed: the lane is byte-reproducible, a
  # red run replays exactly with the same command
  timeout -k 30 "$PYTEST_GUARD_SEC" \
    env HVT_CORE_LIB="$FUZZ_CORE" "${FUZZ_ENV[@]}" \
    python -m horovod_tpu.tools.hvt_fuzz --campaign 2500 --seed 20 \
    --replay tests/corpus/proto_frames.jsonl
  echo "CI OK (fuzz)"
  exit 0
fi

if [[ "$PERFGATE" == "1" || "$REBASELINE" == "1" ]]; then
  echo "=== [2/2] perf-regression gate ==="
  if [[ "$REBASELINE" == "1" ]]; then
    timeout -k 30 "$PYTEST_GUARD_SEC" python benchmarks/perf_gate.py \
      --rebaseline
    echo "CI OK (perfgate baseline refreshed — commit benchmarks/perf_baseline.json)"
    exit 0
  fi
  # fixed path, kept after the run: on a FAILED gate this is exactly
  # the report the developer needs to inspect (a mktemp name would
  # leak per failure and scroll out of view)
  ART=/tmp/hvt_perfgate_report.json
  timeout -k 30 "$PYTEST_GUARD_SEC" python benchmarks/perf_gate.py \
    --out "$ART"
  # ratio-based bands (default 2x on p50s, HVT_PERFGATE_MAX_RATIO to
  # override) — generous enough for a shared box, tight enough that a
  # real data/control-plane regression cannot land green
  python -m horovod_tpu.tools.hvt_analyze --diff \
    benchmarks/perf_baseline.json "$ART"
  echo "CI OK (perfgate; report kept at $ART)"
  exit 0
fi

if [[ "$SERVESOAK" == "1" ]]; then
  echo "=== [2/3] serving gang suite (batching + lane pool) ==="
  run_pytest tests/test_serving.py -q
  echo "=== [3/3] 8-rank mixed-tenant serving soak + artifact checks ==="
  # chaos (flaky_conn + partition) + one host SIGKILL + autoscaler
  # re-shard over MiniEngine workers; --check gates the claims
  # (mode-aware: the smoke runs looser timing bounds than the
  # committed 64-rank capture — see benchmarks/serving_soak.py)
  ART=$(mktemp /tmp/hvt_servesoak_XXXX.json)
  timeout -k 30 "$PYTEST_GUARD_SEC" \
    python benchmarks/serving_soak.py --smoke --out "$ART"
  python benchmarks/serving_soak.py --check "$ART"
  # the committed 64-rank artifact must stay schema- and claim-valid
  python benchmarks/serving_soak.py --check \
    benchmarks/r15_serving_soak.json
  rm -f "$ART"
  echo "CI OK (servesoak)"
  exit 0
fi

if [[ "$ELASTIC" == "1" ]]; then
  echo "=== [2/3] checkpointless-recovery gang suite ==="
  # 4-proc fault-injected kill + respawn-rebuild, the restore
  # baseline, and the artifact gates — real ElasticDriver + rendezvous,
  # featherweight MiniEngine workers
  run_pytest tests/test_elastic_recovery.py -q -m "not slow"
  echo "=== [3/3] 16-rank kill-a-host smoke capture + artifact checks ==="
  ART=$(mktemp /tmp/hvt_elastic_XXXX.json)
  timeout -k 30 "$PYTEST_GUARD_SEC" \
    python benchmarks/elastic_recovery.py --smoke --out "$ART"
  python benchmarks/elastic_recovery.py --check "$ART"
  # the committed 128-rank artifact must stay schema-valid too
  python benchmarks/elastic_recovery.py --check \
    benchmarks/r14_elastic_recovery.json
  rm -f "$ART"
  echo "CI OK (elastic)"
  exit 0
fi

if [[ "$OBS" == "1" ]]; then
  echo "=== [2/2] fleet-telemetry smoke (direct vs leader-aggregated) ==="
  # 8-rank / 2-host pair over a live /statusz rendezvous server. Byte
  # metrics are workload-determined, so the reduction claim is stable
  # on a loaded box; the run itself asserts the hvt_top --once --json
  # round-trip and the clean-gang (no-alerts) pin, and --check gates
  # both on the fresh AND the committed artifact. The committed
  # benchmarks/r13_telemetry_scaling.json comes from the full 64-rank
  # --capture matrix — see BENCH_NOTES r13.
  ART=$(mktemp /tmp/hvt_telemetry_XXXX.json)
  timeout -k 30 "$PYTEST_GUARD_SEC" \
    python benchmarks/telemetry_scaling.py --smoke --out "$ART"
  python benchmarks/telemetry_scaling.py --check "$ART"
  python benchmarks/telemetry_scaling.py --check \
    benchmarks/r13_telemetry_scaling.json
  rm -f "$ART"
  echo "CI OK (obs)"
  exit 0
fi

if [[ "$SCALE" == "1" ]]; then
  echo "=== [2/2] control-plane scaling smoke (simulated gangs) ==="
  # star-vs-tree pair at a small rank count over loopback; byte metrics
  # are workload-determined, so the smoke is stable on a loaded box.
  # The committed artifact (benchmarks/r08_controlplane_scaling.json)
  # comes from the full --capture matrix — see BENCH_NOTES r9.
  ART=$(mktemp /tmp/hvt_ctrlscale_XXXX.json)
  timeout -k 30 "$PYTEST_GUARD_SEC" \
    python benchmarks/ctrl_plane_scaling.py --smoke --out "$ART"
  python benchmarks/ctrl_plane_scaling.py --check "$ART"
  # the committed artifact must stay schema-valid too
  python benchmarks/ctrl_plane_scaling.py --check \
    benchmarks/r08_controlplane_scaling.json
  rm -f "$ART"
  echo "CI OK (scale)"
  exit 0
fi

if [[ "$CODEC" == "1" ]]; then
  echo "=== [2/2] wire-codec sweep smoke (faked 2-host gang) ==="
  # quick mode: one size per codec plane + a short convergence A/B.
  # Byte counters are workload-determined (exact), so the reduction
  # claims are stable even on a loaded box; only the p50 columns are
  # noisy, and --check never gates on those. The committed artifact
  # (benchmarks/r09_codec_sweep.json) comes from the full sweep — see
  # BENCH_NOTES r10.
  ART=$(mktemp /tmp/hvt_codecsweep_XXXX.json)
  timeout -k 30 "$PYTEST_GUARD_SEC" \
    python benchmarks/engine_scaling.py --codec --quick --out "$ART"
  python benchmarks/engine_scaling.py --check "$ART"
  # the committed artifact must stay schema-valid too
  python benchmarks/engine_scaling.py --check \
    benchmarks/r09_codec_sweep.json
  rm -f "$ART"
  echo "CI OK (codec)"
  exit 0
fi

if [[ "$LOADTEST" == "1" ]]; then
  echo "=== [2/2] serving loadtest smoke (loopback ReplicaGang) ==="
  # bounded like every pytest stage: a wedged lane must fail CI, not
  # park it (see PYTEST_GUARD_SEC above)
  ART=$(mktemp /tmp/hvt_loadtest_XXXX.json)
  timeout -k 30 "${PYTEST_GUARD_SEC}" env JAX_PLATFORMS=cpu \
    python -m horovod_tpu.runner.launch -np 4 --master-port 29631 \
    python -m horovod_tpu.serving.loadgen --smoke --replicas 2 \
    --window 8 --burst 2 --sync-every 8 --output "$ART"
  python -m horovod_tpu.serving.loadgen --check "$ART"
  rm -f "$ART"
  echo "CI OK (loadtest)"
  exit 0
fi

if [[ "$SANITIZE" == "1" ]]; then
  echo "=== [2/2] sanitizer suite (TSan + UBSan gangs) ==="
  SAN_LOG=$(mktemp)
  run_pytest tests/test_sanitizers.py -q -ra 2>&1 | tee "$SAN_LOG"
  # skip-if-unavailable must not make the gate vacuous: at least one
  # sanitizer gang has to have actually run (gcc<11 skips TSan, a
  # missing libubsan would skip UBSan — all-skipped means nothing was
  # checked, which is a failed gate, not a green one)
  if ! grep -qE "[1-9][0-9]* passed" "$SAN_LOG"; then
    echo "FATAL: no sanitizer test actually ran (all skipped?)" >&2
    rm -f "$SAN_LOG"
    exit 1
  fi
  rm -f "$SAN_LOG"
  echo "CI OK (sanitize)"
  exit 0
fi

echo "=== [2/5] contract lint ==="
python -m horovod_tpu.tools.hvt_lint

echo "=== [3/5] test suite ==="
if [[ "$FAST" == "1" ]]; then
  # quick subset: modules outside tests/conftest.py's known-slow list
  # (subprocess gangs, TF imports, pallas interpret). Full suite stays
  # the round gate.
  run_pytest tests/ -x -q -m quick
else
  run_pytest tests/ -x -q
fi

echo "=== [4/5] multi-chip dryrun (8 virtual devices) ==="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

if [[ "$FAST" == "0" ]]; then
  echo "=== [5/5] bench smoke (CPU harness validation) ==="
  # --force-cpu applies the in-process platform override; the env var
  # alone does not beat platform-pinning site plugins, and CI must never
  # depend on (or collide over) the single-process TPU tunnel
  python bench.py --force-cpu --model resnet50 --batch-size 2 \
    --num-iters 1 --num-batches-per-iter 2 --image-size 32 --no-scaling
else
  echo "=== [5/5] bench smoke skipped (--fast) ==="
fi

echo "CI OK"
