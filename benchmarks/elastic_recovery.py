#!/usr/bin/env python
"""Simulated kill-a-host elastic recovery harness
(``python benchmarks/elastic_recovery.py``).

Proves checkpointless recovery (``horovod_tpu/elastic/state.py
ReplicatedState`` + the leader-routed KV relay) at 128 simulated ranks
on 16 fake hosts: a REAL :class:`ElasticDriver` + ``RendezvousServer``
drive featherweight MiniEngine workers (bare ctypes over
``libhvt_core.so`` — no jax/numpy per worker, same harness family as
``ctrl_plane_scaling.py`` / ``telemetry_scaling.py``), one host is
SIGKILLed mid-training, and the gang recovers through the real elastic
code paths (``elastic/run.py`` slot sync + failure/READY/recovery
reports, driver blacklist + round fold, ``state.sync()`` peer rebuild).

Two arms, identical workload:

- **peer** — ``ReplicatedState`` commits replicate shards to K peers
  every step; recovery rebuilds the lost ranks' state from survivors
  and resumes from the LAST COMMIT. KV reports ride the per-host
  leader relay (``HVT_KV_RELAY=1``).
- **restore** — replication off; every rank checkpoints to disk every
  ``ckpt_every`` steps and recovery restarts the WHOLE gang from the
  last checkpoint (the Horovod-paper elastic story), replaying the
  lost steps. KV reports go direct (the pre-relay wire shape).

Measured claims (committed as ``benchmarks/r14_elastic_recovery.json``):

- **time-to-recovered-throughput** — SIGKILL to the first completed
  post-recovery training step, per arm; the headline gate is peer
  ≥3x faster at the full 128-rank shape (the baseline pays checkpoint
  reload + replay of every step since the last checkpoint; commits
  are per-step, so the peer arm replays at most one).
- **bit-identity** — the final state of EVERY owner lineage (including
  the killed host's, adopted by survivors) must equal an uninterrupted
  run's, byte-for-byte (CRC of the canonical snapshot). The workload
  is world-size-invariant by construction: the per-step gradient is
  identical on every rank and deterministic, so the reference
  trajectory is computable exactly and any rebuild corruption breaks
  the CRC; the per-step avg-allreduce result is asserted against the
  expected value as the engine-correctness probe.
- **driver KV fan-in** — HTTP PUT requests hitting the driver on the
  recovery-path scopes (failure/state/recovery) during the recovery
  window: O(hosts) with the relay (leaders debounce the report burst
  into one /kvbulk each), O(ranks) direct.

Timing columns are wall-clock on a shared box, but the two arms run
back-to-back under the identical workload, so the RATIO is the stable
claim (BENCH_NOTES r8 methodology); byte/request counts are
workload-determined and exact.

Modes:
    --smoke [--out X.json]   16 ranks / 4 hosts pair (ci.sh --elastic)
    --capture [--out ...]    the full 128-rank / 16-host r14 matrix
    --check X.json           artifact schema + claims validation
Worker mode is selected internally via HVT_ER_WORKER.
"""

from __future__ import annotations

import json
import os
import pickle
import signal
import subprocess
import sys
import threading
import time
import types
import zlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = "hvt-elastic-recovery-r1"
RECOVERY_SCOPES = ("failure", "state", "recovery")


def _stub_package():
    """Register a bare ``horovod_tpu`` package root so submodule
    imports work WITHOUT executing the real package ``__init__`` (which
    imports jax — the weight this harness exists to avoid)."""
    if "horovod_tpu" not in sys.modules:
        pkg = types.ModuleType("horovod_tpu")
        pkg.__path__ = [os.path.join(REPO, "horovod_tpu")]
        sys.modules["horovod_tpu"] = pkg
    if REPO not in sys.path:
        sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# the deterministic workload (shared by workers + the reference model)
# ---------------------------------------------------------------------------

def grad_value(step: int) -> float:
    """The step's gradient component — identical on every rank, so the
    avg-allreduce must return ~v at any world size. State evolution
    uses this DETERMINISTIC value (not the wire result, which can be
    an ULP off through the hierarchical reduction at 128 ranks), which
    is what makes the trajectory world-size-invariant and the
    reference computable exactly; the wire result is asserted against
    it as the per-step engine-correctness probe."""
    return float(1 + step % 7)


def apply_step(params: list, moment: float, owner: int, step: int,
               avg: float):
    """One lineage's state transition. params follow the shared
    trajectory; moment is per-owner, so a rebuilt shard that lost or
    swapped a lineage cannot CRC-match."""
    params[step % len(params)] += avg
    return moment + (owner + 1) * avg


def lineage_crc(params: list, moment: float, step: int) -> int:
    """Canonical snapshot CRC — the bit-identity probe."""
    return zlib.crc32(pickle.dumps((params, moment, step),
                                   protocol=4)) & 0xFFFFFFFF


def simulate_reference(np_: int, numel: int, total_steps: int) -> dict:
    """owner -> final CRC of an uninterrupted run, computed exactly."""
    finals = {}
    for owner in range(np_):
        params = [0.0] * numel
        moment = 0.0
        for step in range(total_steps):
            moment = apply_step(params, moment, owner, step,
                                grad_value(step))
        finals[owner] = lineage_crc(params, moment, total_steps)
    return finals


# ---------------------------------------------------------------------------
# MiniEngine-backed collectives for ReplicatedState
# ---------------------------------------------------------------------------

class MiniCollectives:
    """The four-method collectives backend ``ReplicatedState`` needs,
    over a MiniEngine gang: object allgather = sizes allgather +
    pad-to-max uint8 allgather (the engine's own object-collective
    mechanism, jax/numpy-free). Call names are sequence-tagged so every
    exchange negotiates fresh — shard sizes change across commits and
    rounds."""

    def __init__(self, eng, rank: int, size: int, host: str):
        self.eng = eng
        self._rank = rank
        self._size = size
        self._host = host
        self._seq = {}

    def rebind(self, eng, rank: int, size: int):
        self.eng, self._rank, self._size = eng, rank, size
        # fresh engine = fresh name space. The per-name sequence tags
        # MUST reset with it: re-planned replication groups mix ranks
        # with different historical call counts, and a group whose
        # members tag the same exchange ".35" and ".0" never matches —
        # a silent name-desync wedge (found live at 16 ranks)
        self._seq = {}

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._size

    def host(self) -> str:
        return self._host

    def allgather(self, obj, name: str, ranks=None) -> list:
        members = sorted(ranks) if ranks is not None else None
        if members is not None and len(members) == self._size:
            members = None
        seq = self._seq.get(name, 0)
        self._seq[name] = seq + 1
        tag = f"{name}.{seq}"
        payload = pickle.dumps(obj, protocol=4)
        sizes = self.eng.collective(f"{tag}.sz",
                                    [float(len(payload))],
                                    op="allgather", members=members)
        mx = max(1, int(max(sizes)))
        padded = payload + b"\0" * (mx - len(payload))
        data = self.eng.collective(f"{tag}.data", list(padded),
                                   op="allgather", dtype="uint8",
                                   members=members)
        out = []
        for i, sz in enumerate(sizes):
            chunk = bytes(bytearray(data[i * mx:i * mx + int(sz)]))
            out.append(pickle.loads(chunk))
        return out


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def _worker():
    _stub_package()
    import importlib

    from benchmarks.ctrl_plane_scaling import MiniEngine

    # the package exports `run` (the decorator) under the same name as
    # the module; import the MODULE explicitly
    erun = importlib.import_module("horovod_tpu.elastic.run")
    from horovod_tpu.elastic.state import ReplicatedState
    from horovod_tpu.metrics import telemetry as T
    from horovod_tpu.runner.http_client import get_json, put_bytes

    spec = json.loads(os.environ["HVT_ER_SPEC"])
    kv = os.environ["HVT_RENDEZVOUS_ADDR"]
    # identity toward the driver = the fake host, not the dialable one
    erun._identity = (os.environ["HVT_ER_HOST"],
                      os.environ.get("HVT_LOCAL_PROCESS_ID", "0"))
    replicated = os.environ.get("HVT_STATE_REPLICATION", "1") != "0"
    ckpt_dir = spec.get("ckpt_dir")
    numel = spec["numel"]
    total_steps = spec["total_steps"]
    debug = os.environ.get("HVT_ER_DEBUG")

    def trace(msg):
        if debug:
            print(f"[er {os.environ.get('HVT_HOSTNAME')}/"
                  f"{os.environ.get('HVT_LOCAL_PROCESS_ID')}] {msg}",
                  file=sys.stderr, flush=True)

    def progress(body):
        try:
            put_bytes(kv, "/kv/progress/0", json.dumps(body).encode(),
                      timeout=2, retries=0)
        except Exception:
            pass

    def init_engine(eng, rank, size, port):
        import ctypes

        try:
            eng.init(rank, size, port=port,
                     cycle_ms=spec.get("cycle_ms", 2))
        except RuntimeError:
            err = ctypes.create_string_buffer(4096)
            eng.lib.hvt_error_message(err, 4096)
            raise RuntimeError(
                f"hvt_init failed (rank {rank}/{size} port {port}): "
                f"{err.value.decode(errors='replace')}")

    round_ = erun._sync_slot_from_rendezvous(0)
    rank = int(os.environ["HVT_PROCESS_ID"])
    size = int(os.environ["HVT_NUM_PROCESSES"])
    world = get_json(kv, "/world", retries=2)
    eng = MiniEngine()
    init_engine(eng, rank, size, int(world["master_port"]))
    coll = MiniCollectives(eng, rank, size,
                           os.environ.get("HVT_TOPO_HOST", "h?"))
    state = ReplicatedState(collectives=coll, params=[0.0] * numel,
                            moment=0.0, step=0, adopted_lineages={})
    orig_rank = rank
    trace(f"up rank={rank}/{size} round={round_}")

    # the telemetry pusher provides the host-leader endpoint the KV
    # relay routes through (and the /statusz feed); direct-mode arms
    # run it too so both arms carry the same background load
    stop = threading.Event()
    pusher = T.TelemetryPusher(
        kv, rank, lambda: {"rank": rank, "engine": {"running": True}},
        stop, period_sec=spec.get("push_sec", 1.0))
    threading.Thread(target=pusher.run, daemon=True).start()

    def write_ckpt():
        for o, st in [(state.owner if state.owner is not None else rank,
                       {"params": state.params, "moment": state.moment,
                        "step": state.step})] + \
                [(o, dict(st)) for o, st in
                 state.adopted_lineages.items()]:
            path = os.path.join(ckpt_dir, f"owner_{o}.pkl")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump({"owner": o, "step": state.step, "st": st},
                            f, protocol=4)
            os.replace(tmp, path)

    def restore_from_ckpt():
        """The baseline arm's gang restart-from-checkpoint: every rank
        loads its lineage's last checkpoint (one consistent cut — all
        ranks checkpoint on the same step boundaries) and orphaned
        lineages are adopted round-robin, exactly mirroring the peer
        arm's adoption rule."""
        metas = coll.allgather({"rank": coll.rank(),
                                "owner": state.owner
                                if state.owner is not None else rank},
                               name="er.ckpt_meta")
        claimed = {int(m["owner"]) for m in metas}
        orphans = sorted(set(range(spec["np"])) - claimed)
        ranks_sorted = sorted(int(m["rank"]) for m in metas)
        mine = [o for i, o in enumerate(orphans)
                if ranks_sorted[i % len(ranks_sorted)] == coll.rank()]
        my_owner = state.owner if state.owner is not None else rank
        with open(os.path.join(ckpt_dir,
                               f"owner_{my_owner}.pkl"), "rb") as f:
            rec = pickle.load(f)
        state.params = rec["st"]["params"]
        state.moment = rec["st"]["moment"]
        state.step = rec["st"]["step"]
        state.adopted_lineages = {}
        for o in mine:
            try:
                with open(os.path.join(ckpt_dir,
                                       f"owner_{o}.pkl"), "rb") as f:
                    orec = pickle.load(f)
                state.adopted_lineages[int(o)] = dict(orec["st"])
            except OSError:
                pass
        state._owner = my_owner
        state.save()

    recovered_t = None
    pending_recovered = False
    high_water = 0  # highest step ever completed — "recovered
    # throughput" means training progressed PAST it, so the baseline's
    # checkpoint replay is on the clock, exactly as a user experiences
    while state.step < total_steps:
        try:
            step = state.step
            v = grad_value(step)
            out = eng.collective("step.grad", [v] * numel,
                                 reduce="avg")
            assert abs(out[0] - v) < 1e-3, (out[0], v)
            state.moment = apply_step(state.params, state.moment,
                                      state.owner if state.owner
                                      is not None else rank,
                                      step, v)
            for o, st in state.adopted_lineages.items():
                st["moment"] = apply_step(st["params"], st["moment"],
                                          int(o), step, v)
                st["step"] = step + 1
            state.step = step + 1
            state.commit()
            if pending_recovered and state.step > high_water:
                # throughput is recovered when a post-recovery step
                # completes BEYOND the pre-failure high-water mark —
                # replayed steps are lost work, not recovered work
                recovered_t = time.monotonic()
                pending_recovered = False
            high_water = max(high_water, state.step)
            if not replicated and ckpt_dir and \
                    state.step % spec["ckpt_every"] == 0:
                write_ckpt()
            if orig_rank == 0:
                body = {"step": state.step, "round": round_,
                        "t": time.monotonic()}
                if recovered_t is not None:
                    body["recovered_t"] = recovered_t
                progress(body)
            if spec.get("step_sleep"):
                time.sleep(spec["step_sleep"])
        except RuntimeError as e:
            trace(f"failure at step {state.step}: {e}")
            rec = erun._Recovery("failure")
            if not replicated:
                # the pre-r14 baseline had no per-phase recovery
                # reports; buffering them (only the final "recovered"
                # report PUTs) keeps the restore arm's wire load
                # honest — 120 ranks x 6 phase PUTs would be
                # self-inflicted measurement traffic
                rec.phase = lambda name, seconds, outcome="ok": \
                    rec.phases.append((name, seconds, outcome))
            t0 = time.monotonic()
            erun._report_failure(round_, e)
            rec.phase("report_failure", time.monotonic() - t0)
            t0 = time.monotonic()
            state.restore()
            rec.phase("restore", time.monotonic() - t0)
            t0 = time.monotonic()
            erun._report_state("READY", round_)
            rec.phase("report_ready", time.monotonic() - t0)
            t0 = time.monotonic()
            eng.shutdown()
            rec.phase("shutdown", time.monotonic() - t0)
            t0 = time.monotonic()
            round_ = erun._sync_slot_from_rendezvous(round_)
            rec.phase("rendezvous", time.monotonic() - t0)
            rank = int(os.environ["HVT_PROCESS_ID"])
            size = int(os.environ["HVT_NUM_PROCESSES"])
            world = get_json(kv, "/world", retries=2)
            t0 = time.monotonic()
            init_engine(eng, rank, size, int(world["master_port"]))
            rec.phase("reinit", time.monotonic() - t0)
            coll.rebind(eng, rank, size)
            t0 = time.monotonic()
            if replicated:
                state.sync()
                # fold freshly adopted lineages into the live set the
                # training loop evolves (and future commits replicate)
                for o, snap in state.adopted.items():
                    state.adopted_lineages[int(o)] = {
                        "params": snap["params"],
                        "moment": snap["moment"],
                        "step": snap["step"]}
                rec.phase("rebuild", time.monotonic() - t0,
                          outcome=erun._sync_outcome(state))
            else:
                restore_from_ckpt()
                rec.phase("restore_ckpt", time.monotonic() - t0)
            rec.finish(round_)
            recovered_t = None
            pending_recovered = orig_rank == 0
            trace(f"recovered rank={rank}/{size} at step "
                  f"{state.step}")

    # final barrier, then publish every lineage's CRC
    eng.allreduce("er.final", [1.0])
    finals = {state.owner if state.owner is not None else rank:
              lineage_crc(state.params, state.moment, state.step)}
    for o, st in state.adopted_lineages.items():
        finals[int(o)] = lineage_crc(st["params"], st["moment"],
                                     st["step"])
    for o, crc in finals.items():
        try:
            put_bytes(kv, f"/kv/final/{o}",
                      json.dumps({"crc": crc, "rank": rank}).encode(),
                      timeout=5, retries=2)
        except Exception:
            pass
    eng.allreduce("er.finals_published", [1.0])
    stop.set()
    pusher.close()
    eng.shutdown()


# ---------------------------------------------------------------------------
# driver harness
# ---------------------------------------------------------------------------

class _Gang:
    """Process bookkeeping for one arm's gang: the ElasticDriver's
    create_worker_fn spawns through here so the harness can SIGKILL a
    whole host."""

    def __init__(self, spec, kv_addr, arm):
        self.spec = spec
        self.kv_addr = kv_addr
        self.arm = arm
        self.lock = threading.Lock()
        self.by_host = {}
        self.rank0_out = None
        self._injected = False
        import tempfile

        self.log_dir = tempfile.mkdtemp(prefix="hvt_er_logs_")

    def crash_logs(self, limit=3, tail=1200):
        """Tails of worker logs containing a traceback — the first
        crasher is usually the root cause of a gang-wide wedge."""
        out = []
        try:
            for name in sorted(os.listdir(self.log_dir)):
                path = os.path.join(self.log_dir, name)
                try:
                    with open(path, "rb") as f:
                        data = f.read().decode(errors="replace")
                except OSError:
                    continue
                if "Traceback" in data or "ERROR" in data:
                    out.append(f"--- {name} ---\n{data[-tail:]}")
                if len(out) >= limit:
                    break
        except OSError:
            pass
        return "\n".join(out)

    def spawn(self, slot_info):
        host = slot_info.hostname
        env = dict(os.environ)
        env.update({
            "HVT_ER_WORKER": "1",
            "HVT_ER_SPEC": json.dumps(self.spec),
            "HVT_RENDEZVOUS_ADDR": self.kv_addr,
            # HVT_HOSTNAME is the engine's DIALABLE endpoint host —
            # the fake host name lives in HVT_ER_HOST (driver-facing
            # identity) and HVT_TOPO_HOST (topology identity)
            "HVT_HOSTNAME": "127.0.0.1",
            "HVT_ER_HOST": host,
            "HVT_LOCAL_PROCESS_ID": str(slot_info.local_rank),
            "HVT_TOPO_HOST": host,
            "HVT_TELEMETRY_ROLE": ("leader" if slot_info.local_rank == 0
                                   else "member"),
            "HVT_KV_RELAY": "1" if self.arm == "peer" else "0",
            "HVT_STATE_REPLICATION": "1" if self.arm == "peer" else "0",
            "HVT_REPLICA_GROUP_SIZE": str(self.spec.get("replicas", 2)),
            "HVT_DEBUGZ_INTERVAL_MS": "1000",
            "HVT_RELAY_FLUSH_MS": "700",
            "HVT_KV_TTL_SEC": "600",
            "HVT_CTRL_TOPOLOGY": "star",
            "HVT_CONNECT_TIMEOUT": "240",
            "HVT_LOG_LEVEL": "error",
            # fast, deterministic failure detection: SIGKILL produces
            # RSTs, one short reconnect attempt escalates to the PR 4
            # containment path in well under a second. The op deadline
            # stays WIDE — it only backstops silent wedges, and a
            # 128-rank endpoint exchange on a loaded box can take >15 s
            # (a worker timing out mid-rendezvous kills its listener
            # and wedges everyone else's dials — found live)
            "HVT_LINK_RETRIES": "1",
            "HVT_LINK_RETRY_WINDOW_MS": "800",
            "HVT_OP_TIMEOUT_MS": "60000",
            "PYTHONUNBUFFERED": "1",
        })
        if self.spec.get("fault_inject") and \
                slot_info.rank == self.spec["fault_inject"]["rank"]:
            with self.lock:
                arm_fault = not self._injected
                self._injected = True
            if arm_fault:  # a respawned replacement must not re-die
                env["HVT_FAULT_INJECT"] = \
                    self.spec["fault_inject"]["spec"]
        first = slot_info.rank == 0
        log = None
        if self.log_dir and not first:
            log = open(os.path.join(
                self.log_dir,
                f"{host}_{slot_info.local_rank}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            cwd=REPO,
            stdout=subprocess.PIPE if first else
            (log or subprocess.DEVNULL),
            stderr=subprocess.STDOUT if first else
            (log or subprocess.DEVNULL),
            text=first)
        if log is not None:
            log.close()
        with self.lock:
            self.by_host.setdefault(host, []).append(proc)
            if first:
                self.rank0_out = proc
        return proc.wait()

    def kill_host(self, host):
        with self.lock:
            procs = list(self.by_host.get(host, []))
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGKILL)
                except OSError:
                    pass

    def kill_all(self):
        with self.lock:
            procs = [p for ps in self.by_host.values() for p in ps]
        for p in procs:
            if p.poll() is None:
                p.kill()


def _scope_requests(store, scopes=RECOVERY_SCOPES):
    stats = store.ingest_stats()["put_requests"]
    return {s: stats.get(s, 0) for s in scopes}


def run_arm(arm, spec, timeout=900):
    """One full elastic round-trip for one arm; returns the metrics
    dict. The ElasticDriver, rendezvous server, discovery, registry and
    blacklist logic are the REAL ones — only the workers are
    featherweight."""
    _stub_package()
    from benchmarks.ctrl_plane_scaling import _next_port
    from horovod_tpu.runner.elastic.discovery import FixedHostDiscovery
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.settings import ElasticSettings
    from horovod_tpu.runner.http_server import RendezvousServer

    np_, hosts = spec["np"], spec["hosts"]
    per_host = np_ // hosts
    target_host = f"h{hosts - 1}"
    rendezvous = RendezvousServer()
    rendezvous.master_port_fn = lambda slots, rnd: _next_port()
    kv_port = rendezvous.start(0)
    kv_addr = f"127.0.0.1:{kv_port}"
    gang = _Gang(spec, kv_addr, arm)
    settings = ElasticSettings(
        min_np=np_ - per_host, max_np=np_, elastic_timeout=180.0,
        reset_limit=6, discovery_interval=0.25)
    driver = ElasticDriver(
        rendezvous,
        FixedHostDiscovery({f"h{i}": per_host for i in range(hosts)}),
        settings, create_worker_fn=gang.spawn)
    result = {"arm": arm, "np": np_, "hosts": hosts}
    deadline = time.monotonic() + timeout
    try:
        driver.start(np_)

        def prog():
            raw = rendezvous.store.get("progress", "0")
            try:
                return json.loads(raw) if raw else {}
            except ValueError:
                return {}

        # phase 1: training reaches the kill step
        t_start = time.monotonic()
        while True:
            p = prog()
            if p.get("step", 0) >= spec["kill_at_step"]:
                break
            if time.monotonic() > deadline or driver.finished():
                raise RuntimeError(
                    f"{arm}: gang never reached kill step "
                    f"(progress={p}, finished={driver.finished()}, "
                    f"err={driver.error})")
            time.sleep(0.05)
        steps_pre = p.get("step", 0)
        result["prekill_steps_per_sec"] = round(
            steps_pre / max(p.get("t", 1) - t_start + 1e-9, 1e-9), 2) \
            if p.get("t") else None
        req0 = _scope_requests(rendezvous.store)
        if spec.get("fault_inject"):
            t_kill = time.monotonic()  # the armed fault fires itself
        else:
            t_kill = time.monotonic()
            gang.kill_host(target_host)
        result["killed_host"] = target_host

        # phase 2: recovery — rank 0 stamps recovered_t (same
        # CLOCK_MONOTONIC domain: all processes share one machine)
        while True:
            p = prog()
            if p.get("recovered_t") and p.get("round", 1) >= 2:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(f"{arm}: gang never recovered "
                                   f"(progress={p})")
            if driver.finished() and driver.error:
                raise RuntimeError(f"{arm}: driver failed mid-"
                                   f"recovery: {driver.error}")
            time.sleep(0.05)
        result["time_to_recovered_sec"] = round(
            p["recovered_t"] - t_kill, 3)
        req1 = _scope_requests(rendezvous.store)
        result["kv_requests_recovery"] = {
            s: req1[s] - req0[s] for s in req1}
        result["kv_requests_recovery_total"] = sum(
            result["kv_requests_recovery"].values())

        # phase 3: run to completion; every surviving worker exits 0
        while not driver.finished():
            if time.monotonic() > deadline:
                raise RuntimeError(f"{arm}: gang never finished")
            time.sleep(0.2)
        if driver.error:
            raise RuntimeError(f"{arm}: driver error: {driver.error}")
        results = driver.get_results()
        bad = {r: rc for r, rc in results.items() if rc != 0}
        if bad:
            raise RuntimeError(f"{arm}: nonzero worker exits {bad}")

        # recovery phase breakdown from rank 0's final /kv/recovery
        # report (the "recovered" report carries per-phase seconds)
        breakdown = {}
        raw = rendezvous.store.get("recovery", "h0/0")
        if raw:
            try:
                body = json.loads(raw)
                breakdown = dict(body.get("phases") or {},
                                 total=body.get("seconds"))
            except (ValueError, TypeError):
                pass
        result["recovery_phases_rank0"] = breakdown

        # bit-identity: every lineage's final CRC vs the reference
        reference = simulate_reference(np_, spec["numel"],
                                       spec["total_steps"])
        finals = {}
        for key in rendezvous.store.keys("final"):
            try:
                finals[int(key)] = json.loads(
                    rendezvous.store.get("final", key))["crc"]
            except (ValueError, TypeError, KeyError):
                pass
        missing = sorted(set(reference) - set(finals))
        mismatched = sorted(o for o in finals
                            if reference.get(o) != finals[o])
        result["lineages_reported"] = len(finals)
        result["lineages_missing"] = missing
        result["lineages_mismatched"] = mismatched
        result["bit_identical"] = not missing and not mismatched
        if arm == "peer":
            doc = rendezvous.statusz_snapshot()
            rec = doc.get("recovery") or {}
            result["statusz_recovery_reports"] = rec.get("reports", 0)
        result["ok"] = True
        return result
    except Exception as e:
        gang.kill_all()  # before reading rank 0's pipe: a live worker
        out = ""         # would block the read forever
        if gang.rank0_out is not None:
            try:
                out = gang.rank0_out.communicate(timeout=10)[0] or ""
            except Exception:
                pass
        result["ok"] = False
        result["error"] = (f"{e}\n--- rank0 output ---\n{out[-3000:]}"
                           f"\n{gang.crash_logs()}")
        return result
    finally:
        gang.kill_all()
        driver.stop()
        rendezvous.stop()


def capture(out_path, smoke=False):
    import tempfile

    if smoke:
        base = {"np": 16, "hosts": 4, "numel": 128, "total_steps": 60,
                "kill_at_step": 34, "ckpt_every": 25, "replicas": 2,
                "step_sleep": 0.05, "cycle_ms": 2, "push_sec": 0.8}
        timeout = 420
    else:
        # checkpoint cadence: 200 steps between checkpoints vs a
        # commit+replication EVERY step — the real-world shape (a
        # checkpoint costs serialize+IO minutes apart; replication is
        # an in-memory exchange), scaled to simulation step time. The
        # kill lands ~198 steps past the last checkpoint, so the
        # baseline replays what its cadence cost it.
        base = {"np": 128, "hosts": 16, "numel": 256,
                "total_steps": 410, "kill_at_step": 398,
                "ckpt_every": 200, "replicas": 2, "step_sleep": 0.1,
                "cycle_ms": 2, "push_sec": 1.0}
        timeout = 1500
    record = {"schema": SCHEMA, "mode": "smoke" if smoke else "full",
              "spec": dict(base), "configs": [], "claims": {}}
    results = {}
    for arm in ("restore", "peer"):
        spec = dict(base)
        if arm == "restore":
            spec["ckpt_dir"] = tempfile.mkdtemp(prefix="hvt_er_ckpt_")
        t0 = time.monotonic()
        res = run_arm(arm, spec, timeout=timeout)
        res["total_sec"] = round(time.monotonic() - t0, 1)
        results[arm] = res
        record["configs"].append(res)
        print(json.dumps({k: res.get(k) for k in
                          ("arm", "ok", "time_to_recovered_sec",
                           "kv_requests_recovery_total",
                           "bit_identical", "total_sec", "error")}),
              flush=True)
        if not res.get("ok"):
            break

    record["claims"] = build_claims(base, results)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        print(f"wrote {out_path}")
    print("claims: " + json.dumps(record["claims"]))
    return record


def build_claims(base, results):
    """The gated claims, a pure function of the measured arm configs
    (kept separate so a re-gate never needs a re-run)."""
    r, p = results.get("restore", {}), results.get("peer", {})
    if r.get("ok") and p.get("ok"):
        survivors = base["np"] - base["np"] // base["hosts"]

        def round_reqs(res):
            # the per-ROUND report wave: failure + READY. The
            # `recovery` scope is a continuous phase stream (one
            # batched request per host per tick while recovering), so
            # it scales with hosts x duration, not with ranks — it is
            # recorded above but gated separately.
            kr = res["kv_requests_recovery"]
            return kr.get("failure", 0) + kr.get("state", 0)

        return {
            "ranks": base["np"], "hosts": base["hosts"],
            "recovered_both": True,
            "time_to_recovered_restore_sec":
                r["time_to_recovered_sec"],
            "time_to_recovered_peer_sec": p["time_to_recovered_sec"],
            "speedup_x": round(r["time_to_recovered_sec"]
                               / max(p["time_to_recovered_sec"],
                                     1e-9), 2),
            "bit_identical_peer": p["bit_identical"],
            "bit_identical_restore": r["bit_identical"],
            "kv_round_requests_peer": round_reqs(p),
            "kv_round_requests_restore": round_reqs(r),
            "kv_requests_recovery_peer":
                p["kv_requests_recovery_total"],
            "kv_requests_recovery_restore":
                r["kv_requests_recovery_total"],
            # the O(hosts) gate: the relayed arm's per-round report
            # wave is bounded by a PER-HOST constant (8 — detection
            # skew on an oversubscribed sim box spreads a host's
            # burst across several debounce windows; real clusters
            # cluster within one or two), independent of how many
            # ranks each host carries; the direct arm scales with
            # survivors (>= one failure + one READY each)
            "kv_round_requests_peer_bound": 8 * base["hosts"],
            "kv_requests_o_hosts": round_reqs(p) <= 8 * base["hosts"],
            "kv_requests_o_ranks_direct": round_reqs(r) >= survivors,
            "statusz_recovery_rows":
                (p.get("statusz_recovery_reports") or 0) > 0,
        }
    return {"recovered_both": False}


def check(path):
    """Artifact schema + claims validation (ci.sh --elastic). The full
    artifact gates the headline ≥3x time-to-recovered speedup; the
    smoke pair gates ≥1.2x (smaller replay window, shared-box noise)
    plus every structural claim at full strength."""
    with open(path) as f:
        rec = json.load(f)
    errs = []
    if rec.get("schema") != SCHEMA:
        errs.append(f"schema != {SCHEMA}")
    cfgs = rec.get("configs", [])
    arms = {c.get("arm") for c in cfgs}
    if arms != {"restore", "peer"}:
        errs.append(f"configs must cover restore+peer, got {arms}")
    for c in cfgs:
        if not c.get("ok"):
            errs.append(f"arm {c.get('arm')}: not ok: "
                        f"{str(c.get('error'))[:300]}")
        for key in ("time_to_recovered_sec", "bit_identical",
                    "kv_requests_recovery_total"):
            if key not in c:
                errs.append(f"arm {c.get('arm')} missing {key}")
    cl = rec.get("claims") or {}
    if not cl.get("recovered_both"):
        errs.append("claims: recovered_both is not true")
    else:
        floor = 3.0 if rec.get("mode") == "full" else 1.2
        if (cl.get("speedup_x") or 0) < floor:
            errs.append(f"speedup_x {cl.get('speedup_x')} < {floor}")
        for k in ("bit_identical_peer", "bit_identical_restore",
                  "kv_requests_o_hosts", "kv_requests_o_ranks_direct",
                  "statusz_recovery_rows"):
            if cl.get(k) is not True:
                errs.append(f"claim {k} is {cl.get(k)!r}, want true")
    for e in errs:
        print(f"elastic_recovery --check: {e}", file=sys.stderr)
    if errs:
        return 1
    print(f"elastic_recovery --check: OK ({len(cfgs)} arms, claims: "
          f"{json.dumps(cl)})")
    return 0


def main():
    if os.environ.get("HVT_ER_WORKER"):
        _worker()
        return 0
    _stub_package()
    args = sys.argv[1:]

    def argval(flag, dflt):
        if flag not in args:
            return dflt
        i = args.index(flag) + 1
        if i >= len(args):
            sys.exit(f"elastic_recovery: {flag} requires a value")
        return args[i]

    if "--check" in args:
        return check(argval("--check", ""))
    out = argval("--out", "" if "--smoke" in args
                 else os.path.join(REPO, "benchmarks",
                                   "r14_elastic_recovery.json"))
    capture(out, smoke="--smoke" in args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
