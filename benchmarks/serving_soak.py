#!/usr/bin/env python
"""Mixed-tenant serving soak under production failures
(``python benchmarks/serving_soak.py``).

ISSUE 15's composition gate: the serving machinery (PR 6 ReplicaGang +
this PR's request-level batching and per-lane execution pool), the
transient-fault chaos (PR 10 flaky_conn/partition), the telemetry plane
(PR 13 /statusz health rules), and the elastic driver/autoscaler (PR
6/14) all running AT ONCE over a simulated 64-rank / 8-host gang of
featherweight MiniEngine workers (bare ctypes over ``libhvt_core.so`` —
no jax/numpy per worker; same harness family as
``ctrl_plane_scaling.py`` / ``elastic_recovery.py``).

**The tenant grid.** Every rank serves TWO lanes: its host's "row" lane
(contiguous ranks, one replica per host) and a "column" lane striding
one rank per host. A row lane and a column lane share exactly ONE rank,
which is precisely the shape the engine's per-lane worker pool
(``HVT_LANE_WORKERS``) isolates: a saturated row lane's data-plane time
no longer head-of-line-blocks the column lane crossing it on the shared
rank.

**The storyline** (one "pool" arm, phases separated by engine barriers,
all traffic deterministic step counts — wall-clock-bounded loops
deadlock gangs, see BENCH_NOTES r13):

- ``warm``/``baseline`` — every lane carries light traffic; the
  /statusz health plane must stay ALERT-FREE (the clean-gang pin).
- ``fire`` — one host's row lane goes hot (bigger payloads, more
  requests) while ``flaky_conn`` cuts a hot-host rank's links
  mid-transfer; the idle COLUMN lanes' exec-start overlap with the
  hot lane's open exec spans is the lane-isolation gate (impossible
  without the pool — see ``_col_ov_frac``; measured over column lanes
  not containing the flaky rank), and the reconnects must surface as
  a ``reconnect_storm`` alert.
- ``storm`` — a ``partition`` fault splits two hosts away for ~600 ms
  mid-traffic; the links heal (zero engine aborts — the transient-fault
  gate) and traffic completes.
- ``endure`` → host SIGKILL → re-shard — the driver kills the last
  host; survivors abort into the PR 4 containment path, report
  failures, and re-rendezvous into a smaller world (the autoscaler
  records the shed; ``push_stale`` alerts must name only killed ranks);
  lanes are re-planned for the new world and a ``recovered`` phase
  completes clean.

A second, shorter "nopool" arm (``HVT_LANE_WORKERS=0``, no chaos, no
kill) replays warm/baseline/fire for the per-lane worker pool A/B: the
single-thread engine's column-lane inflation under the same hot
neighbor is the denominator of the isolation claim.

Member-identical (admitted, shed, batch-boundary) decision CRCs are
asserted per lane per phase — the PR 6 invariant extended to batching.

Artifact: ``benchmarks/r15_serving_soak.json`` (committed from
``--capture``); ``ci.sh --servesoak`` runs ``--smoke`` (8 ranks /
4 hosts) + ``--check`` of both.

Modes:
    --smoke [--out X.json]     8-rank / 4-host soak (ci.sh --servesoak)
    --capture [--out ...]      the full 64-rank / 8-host r15 matrix
    --check X.json             artifact schema + mode-aware claim gates
Worker mode is selected internally via HVT_SSK_WORKER.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import types
import zlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = "hvt-serving-soak-r2"

# health-alert rules that injected faults may legitimately fire; any
# OTHER rule in a chaos phase fails the run, and baseline must be empty
ALLOWED_ALERTS = {"reconnect_storm", "push_stale", "straggler",
                  "serving_backlog"}


def lane_slot(members) -> int:
    """Python mirror of engine.h LaneId/LaneSlot: the stats bucket a
    process-set lane's exec telemetry lands in (FNV-1a over the sorted
    member list, 8 LE bytes per rank; bucket 0 is the global lane)."""
    if not members:
        return 0
    h = 1469598103934665603
    for m in sorted(int(x) for x in members):
        for b in range(8):
            h ^= (m >> (b * 8)) & 0xFF
            h = (h * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    if h == 0:
        h = 1
    return 1 + (h % 7)


def _stub_package():
    """Register a bare ``horovod_tpu`` package root so submodule
    imports work WITHOUT executing the real package ``__init__`` (which
    imports jax — the weight this harness exists to avoid)."""
    if "horovod_tpu" not in sys.modules:
        pkg = types.ModuleType("horovod_tpu")
        pkg.__path__ = [os.path.join(REPO, "horovod_tpu")]
        sys.modules["horovod_tpu"] = pkg
    if REPO not in sys.path:
        sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# lane plans (shared by workers + the driver's expectations)
# ---------------------------------------------------------------------------

def row_partition(size: int, per_host: int):
    """One contiguous lane per host (the driver packs ranks host-major)."""
    return [list(range(h * per_host, (h + 1) * per_host))
            for h in range(size // per_host)]


def col_partition(size: int, per_host: int):
    """per_host lanes, each striding one rank per host."""
    return [list(range(i, size, per_host)) for i in range(per_host)]


# ---------------------------------------------------------------------------
# MiniEngine adapter for ReplicaGang (the serving engine seam)
# ---------------------------------------------------------------------------

class MiniServingEngine:
    """The five-method serving-engine seam over a MiniEngine, jax-free.

    Batches ride the engine's native fusion groups; group ids must be
    identical across a lane's members and globally unique across
    concurrently-open lanes, so they derive from (lane_base, per-lane
    flush sequence) — never from a per-process counter, which would
    drift across members once one lane runs hotter than another."""

    def __init__(self, eng, rank: int, size: int, lane_base: int):
        self.eng = eng
        self._rank, self._size = rank, size
        self._lane_base = int(lane_base)
        self._flush_seq = 0

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._size

    def submit(self, name, tensor, members, op="sum"):
        return [self.eng.submit(name, tensor, reduce=op,
                                members=list(members))]

    def submit_batch(self, name, tensors, members, op="sum"):
        self._flush_seq += 1
        gid = (self._lane_base * 65536 + (self._flush_seq % 32768)) \
            & 0x7FFFFFFF
        n = len(tensors)
        return [self.eng.submit(f"{name}.{i}", t, reduce=op,
                                members=list(members), group_id=gid,
                                group_size=n)
                for i, t in enumerate(tensors)]

    def wait(self, handle, timeout=None):
        from horovod_tpu.common.exceptions import HorovodTimeoutError

        hs = handle
        if timeout is not None and not self.eng.wait_timeout(
                hs[0], max(1, int(timeout * 1e3))):
            raise HorovodTimeoutError(
                f"serving wait exceeded {timeout:.3f}s")
        outs = [self.eng.wait(h) for h in hs]
        return outs if len(outs) > 1 else outs[0]


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def _lane_record(gang) -> dict:
    s = gang.stats
    return {
        "members": list(gang.my_members),
        "admitted": s.admitted, "shed": s.shed, "batches": s.batches,
        "completed": s.completed, "deadline_miss": s.deadline_miss,
        "p50_ms": round(s.percentile(50), 4),
        "p99_ms": round(s.percentile(99), 4),
        # the member-identity probe: the full (admit, shed, batch)
        # tuple sequence, CRC'd
        "dec_crc": zlib.crc32(repr(gang.decisions).encode())
                   & 0xFFFFFFFF,
    }


def _worker():
    _stub_package()
    import importlib

    from benchmarks.ctrl_plane_scaling import MiniEngine

    erun = importlib.import_module("horovod_tpu.elastic.run")
    from horovod_tpu.metrics import telemetry as T
    from horovod_tpu.runner.http_client import get_json, put_bytes
    from horovod_tpu.serving.replica_gang import ReplicaGang

    spec = json.loads(os.environ["HVT_SSK_SPEC"])
    kv = os.environ["HVT_RENDEZVOUS_ADDR"]
    host = os.environ["HVT_SSK_HOST"]
    erun._identity = (host, os.environ.get("HVT_LOCAL_PROCESS_ID", "0"))
    per_host = spec["per_host"]
    window = spec["window"]
    batch = spec["batch"]
    admission = spec["admission_ms"] / 1e3
    burst = spec["burst"]
    debug = os.environ.get("HVT_SSK_DEBUG")

    def trace(msg):
        if debug:
            print(f"[ssk {host}/{os.environ.get('HVT_LOCAL_PROCESS_ID')}]"
                  f" {msg}", file=sys.stderr, flush=True)

    def init_engine(eng, rank, size, port):
        import ctypes

        try:
            eng.init(rank, size, port=port,
                     cycle_ms=spec.get("cycle_ms", 2))
        except RuntimeError:
            err = ctypes.create_string_buffer(4096)
            eng.lib.hvt_error_message(err, 4096)
            raise RuntimeError(
                f"hvt_init failed (rank {rank}/{size} port {port}): "
                f"{err.value.decode(errors='replace')}")

    round_ = erun._sync_slot_from_rendezvous(0)
    rank = int(os.environ["HVT_PROCESS_ID"])
    size = int(os.environ["HVT_NUM_PROCESSES"])
    world = get_json(kv, "/world", retries=2)
    eng = MiniEngine()
    init_engine(eng, rank, size, int(world["master_port"]))
    trace(f"up rank={rank}/{size} round={round_}")

    # telemetry: real compact snapshots off the engine stats block, so
    # /statusz sees queue/link/reconnect state (the health rules' food)
    stop = threading.Event()

    def snap_fn():
        return T.build_snapshot(
            rank, host,
            {"rank": rank, "engine": {"running": True,
                                      "cycles":
                                          eng.stats().get("cycles", 0)}},
            eng.stats())

    pusher = T.TelemetryPusher(kv, rank, snap_fn, stop,
                               period_sec=spec.get("push_sec", 1.0))
    threading.Thread(target=pusher.run, daemon=True).start()

    def progress(body):
        if rank != 0:
            return
        try:
            put_bytes(kv, "/kv/progress/0", json.dumps(body).encode(),
                      timeout=2, retries=0)
        except Exception:
            pass

    def barrier(tag):
        out = eng.allreduce(f"ssk.bar.{tag}", [1.0])
        assert int(out[0]) == size, (tag, out)

    def make_gangs(phase):
        """Fresh per-phase gangs over the CURRENT world: one row lane
        (this host's ranks) and one column lane (this rank's stride)."""
        rows = row_partition(size, per_host)
        cols = col_partition(size, per_host)
        my_row = next(i for i, g in enumerate(rows) if rank in g)
        my_col = next(i for i, g in enumerate(cols) if rank in g)
        # a hot tenant runs a DEEPER window (hot_window): the realistic
        # hot-lane shape, and what makes the nopool arm's head-of-line
        # blocking visible — with the default window only ~2 fused row
        # ops are ever outstanding, so the column op rarely queues
        # behind one. Row members all live on one host, so the
        # per-host parameter keeps every member's program identical
        # (decision CRCs must still match).
        row_window = (spec.get("hot_window", window)
                      if host == spec["hot_host"] else window)
        # the row lane is named to sort BEFORE the column lane: the
        # coordinator completes cold negotiations in name-lexicographic
        # order (engine.cc counts_ iteration), so head-of-line blocking
        # only exists for the neighbor BEHIND the hot tenant in that
        # deterministic order. The observer column lane is deliberately
        # placed on the unlucky side — production tenants do not get to
        # choose their side, so the bench bounds the worst case — and
        # BOTH iso arms see the identical order, keeping the A/B fair.
        row = ReplicaGang(
            len(rows), admission_timeout=admission,
            max_backlog=row_window,
            batch_window=batch, name=f"{phase}.arow", partition=rows,
            engine=MiniServingEngine(eng, rank, size, 1 + my_row))
        col = ReplicaGang(
            len(cols), admission_timeout=admission, max_backlog=window,
            batch_window=batch, name=f"{phase}.col", partition=cols,
            engine=MiniServingEngine(eng, rank, size, 101 + my_col))
        return row, col

    def make_bufs(elems, salt):
        """Prebuilt payloads, one per salt value (hvt_submit copies
        synchronously). Built with array-module C-level repeat — a
        python list comprehension at 1M elems burns SECONDS of GIL on
        the hot ranks, long enough that the other tenant's whole phase
        program drains before the hot lane submits anything and the
        phases never actually contend (found via the exec-span
        timeline: the hot lane's first exec began 3.2 s into fire)."""
        import ctypes as C
        from array import array

        out = []
        for s in range(salt):
            a = array("f", [float(s + 1)]) * elems
            out.append((C.c_float * elems).from_buffer(a))
        return out

    def drive_lane(gang, n, bufs, lane_burst=None):
        """One tenant's serving loop: burst-submit, reap at the window,
        flush + drain. A pure function of the request index, so every
        member of the lane plays the identical program."""
        salt = len(bufs)
        b = lane_burst or burst
        k = 0
        while k < n:
            for _ in range(min(b, n - k)):
                gang.submit_request(bufs[k % salt])
                k += 1
            while gang.backlog() >= gang.max_backlog:
                gang.reap()
        gang.flush()
        while gang.backlog():
            gang.reap()

    def serve_phase(phase, row_n, col_n, row_elems, col_elems):
        """Drive one phase with one thread PER TENANT — the production
        shape (each tenant has its own serving loop), and the shape the
        per-lane pool isolates: without it the hot row tenant's engine
        executions head-of-line-block the column tenant's on the shared
        rank. Then barrier + publish the per-lane record. Lane programs
        stay deterministic per member, so decision CRCs must match."""
        row, col = make_gangs(phase)
        # per-phase delta of the engine's in-rank, per-lane exec
        # telemetry — the robust isolation metric (data-plane wall time
        # per executed response on THIS rank, no python-thread or
        # admission noise). Lanes hash onto 8 stats buckets; a rank
        # whose row and col lanes collide marks its sample unusable.
        slot_row = lane_slot(row.my_members)
        slot_col = lane_slot(col.my_members)
        # prebuild BOTH tenants' payloads, then re-sync the gang: the
        # hot ranks' (bigger) build must not let the other tenants
        # race ahead — the phases measure CONCURRENT traffic
        row_bufs = make_bufs(row_elems, 13)
        col_bufs = make_bufs(col_elems, 11)
        barrier(f"pre.{phase}")
        eng.drain_exec_events()  # clear pre-phase exec spans
        s0 = eng.stats()
        errs = []

        def run(gang, n, bufs, lane_burst=None):
            try:
                drive_lane(gang, n, bufs, lane_burst)
            except BaseException as e:  # noqa: B036 — re-raised below
                errs.append(e)

        row_burst = (spec.get("hot_burst")
                     if host == spec["hot_host"] else None)
        t_row = threading.Thread(target=run,
                                 args=(row, row_n, row_bufs, row_burst))
        t_col = threading.Thread(target=run,
                                 args=(col, col_n, col_bufs))
        t_row.start()
        t_col.start()
        t_row.join()
        t_col.join()
        if errs:
            raise RuntimeError(f"serving thread failed: {errs[0]!r}")
        s1 = eng.stats()

        def lane_us(slot, group):
            dn = (s1.get(f"{group}_ns[{slot}]", 0)
                  - s0.get(f"{group}_ns[{slot}]", 0))
            dc = (s1.get(f"{group}_count[{slot}]", 0)
                  - s0.get(f"{group}_count[{slot}]", 0))
            return (round(dn / 1e3 / max(dc, 1), 2), dc)

        # lane_exec = data-plane wall time per executed response;
        # lane_hol = submit → engine-pickup queue wait (the in-rank
        # service-start delay a hot inline neighbor causes; both ends
        # stamp on THIS rank, so peer skew cannot leak in)
        exec_stats = {
            "row": lane_us(slot_row, "lane_exec"),
            "col": lane_us(slot_col, "lane_exec"),
            "collision": slot_row == slot_col,
        }
        hol_stats = {
            "row": lane_us(slot_row, "lane_hol"),
            "col": lane_us(slot_col, "lane_hol"),
        }
        # the GATED isolation probe: from the flight recorder's
        # lane-stamped EXEC spans, how many of each tenant's exec
        # STARTS happened while the OTHER tenant's exec span was open
        # on this rank. Event-ordering, not wall-clock: a single-thread
        # engine can never have two spans open (LaneBarrier quiesces
        # the pool before every inline execution), so a nonzero
        # overlapped count is constructive proof the pool decoupled the
        # lanes — and an oversubscribed 1-core harness box cannot fake
        # or hide it the way it skews latency ratios.
        ov = {"row": [0, 0], "col": [0, 0]}  # [starts, overlapped]
        busy_us = {"row": 0, "col": 0}  # span-open wall time (duty)
        if slot_row != slot_col:
            ev_stream = eng.drain_exec_events()
            dump_dir = os.environ.get("HVT_SSK_EV_DUMP")
            if dump_dir:
                with open(os.path.join(
                        dump_dir, f"ev_{phase}_{rank}.json"), "w") as f:
                    json.dump({"slot_row": slot_row,
                               "slot_col": slot_col,
                               "events": ev_stream}, f)
            open_n = {}
            open_t0 = {}
            for ts, kind, lane in ev_stream:
                tenant = ("row" if lane == slot_row else
                          "col" if lane == slot_col else None)
                if kind == 5:  # EXEC_BEGIN
                    if tenant:
                        other = slot_col if tenant == "row" else slot_row
                        ov[tenant][0] += 1
                        if open_n.get(other, 0) > 0:
                            ov[tenant][1] += 1
                        if not open_n.get(lane):
                            open_t0[lane] = ts
                    open_n[lane] = open_n.get(lane, 0) + 1
                else:  # EXEC_END
                    open_n[lane] = max(0, open_n.get(lane, 0) - 1)
                    if tenant and not open_n[lane] and lane in open_t0:
                        busy_us[tenant] += ts - open_t0.pop(lane)
        # the autoscaler's serving signal + the /statusz serving block
        # (and its ghost-lane staleness handling after the re-shard)
        row.push_stats()
        col.push_stats()
        barrier(phase)
        st = eng.stats()
        lanes_rec = {"row": _lane_record(row), "col": _lane_record(col)}
        for tenant in ("row", "col"):
            us, cnt = exec_stats[tenant]
            lanes_rec[tenant]["exec_us_mean"] = us
            lanes_rec[tenant]["exec_count"] = cnt
            hus, hcnt = hol_stats[tenant]
            lanes_rec[tenant]["hol_us_mean"] = hus
            lanes_rec[tenant]["hol_count"] = hcnt
            lanes_rec[tenant]["ov_starts"] = ov[tenant][0]
            lanes_rec[tenant]["ov_overlapped"] = ov[tenant][1]
            lanes_rec[tenant]["busy_us"] = busy_us[tenant]
            lanes_rec[tenant]["slot_collision"] = \
                exec_stats["collision"]
        rec = {
            "rank": rank, "host": host, "round": round_, "size": size,
            "lanes": lanes_rec,
            "engine": {
                "aborts": sum(v for k, v in st.items()
                              if k.startswith("aborts[")),
                "pool_tasks": st.get("lane_pool_tasks", 0),
                "lane_workers": st.get("lane_workers", 0),
                "reconnects": (st.get("link_reconnects[ctrl]", 0)
                               + st.get("link_reconnects[data]", 0)),
                "data_ops": eng.lib.hvt_data_ops()
                if hasattr(eng.lib, "hvt_data_ops") else 0,
            },
        }
        try:
            put_bytes(kv, f"/kv/ssk/{phase}/{rank}",
                      json.dumps(rec).encode(), timeout=5, retries=2)
        except Exception:
            pass
        progress({"phase_done": phase, "round": round_,
                  "size": size, "t": time.monotonic()})
        trace(f"phase {phase} done (aborts={rec['engine']['aborts']})")
        return rec

    hot_host = spec["hot_host"]
    ph = spec["phases"]

    def hot(n):
        return n * spec["hot_factor"] if host == hot_host else n

    def hot_elems(n):
        return spec["hot_elems"] if host == hot_host else n

    t_kill_seen = None
    recovered_round = None
    try:
        serve_phase("warm", ph["warm"], ph["warm"],
                    spec["row_elems"], spec["col_elems"])
        serve_phase("baseline", ph["baseline"], ph["baseline"],
                    spec["row_elems"], spec["col_elems"])
        serve_phase("fire", hot(ph["fire"]), ph["fire"],
                    hot_elems(spec["row_elems"]), spec["col_elems"])
        if ph.get("storm"):
            serve_phase("storm", hot(ph["storm"]), ph["storm"],
                        hot_elems(spec["row_elems"]), spec["col_elems"])
        if spec.get("kill"):
            # endure: keep serving until the driver kills a host and
            # the containment path fires; bounded by a step count so a
            # missed kill fails loudly instead of wedging
            killed = False
            try:
                serve_phase("endure", ph["endure"], ph["endure"],
                            spec["row_elems"], spec["col_elems"])
            except RuntimeError as e:
                killed = True
                trace(f"failure during endure: {e}")
                t_kill_seen = time.monotonic()
                erun._report_failure(round_, e)
                erun._report_state("READY", round_)
                eng.shutdown()
                round_ = erun._sync_slot_from_rendezvous(round_)
                rank = int(os.environ["HVT_PROCESS_ID"])
                size = int(os.environ["HVT_NUM_PROCESSES"])
                world = get_json(kv, "/world", retries=2)
                init_engine(eng, rank, size, int(world["master_port"]))
                trace(f"recovered rank={rank}/{size} round={round_}")
            if not killed:
                raise RuntimeError(
                    "endure phase completed without the host kill — "
                    "the driver never injected it")
            serve_phase("recovered", ph["recovered"], ph["recovered"],
                        spec["row_elems"], spec["col_elems"])
            progress({"phase_done": "recovered", "round": round_,
                      "size": size,
                      "recover_sec": (time.monotonic()
                                      - (t_kill_seen or 0)),
                      "t": time.monotonic()})
    finally:
        stop.set()
        pusher.close()
    barrier("fin")
    eng.shutdown()


# ---------------------------------------------------------------------------
# driver harness
# ---------------------------------------------------------------------------

class _Gang:
    """Worker bookkeeping: the ElasticDriver spawns through here so the
    harness can SIGKILL a whole host (same shape as
    elastic_recovery._Gang)."""

    def __init__(self, spec, kv_addr, lane_workers):
        self.spec = spec
        self.kv_addr = kv_addr
        self.lane_workers = lane_workers
        self.lock = threading.Lock()
        self.by_host = {}
        self.rank0_out = None
        import tempfile

        self.log_dir = tempfile.mkdtemp(prefix="hvt_ssk_logs_")

    def crash_logs(self, limit=3, tail=1500):
        out = []
        try:
            for name in sorted(os.listdir(self.log_dir)):
                path = os.path.join(self.log_dir, name)
                try:
                    with open(path, "rb") as f:
                        data = f.read().decode(errors="replace")
                except OSError:
                    continue
                if "Traceback" in data or "ERROR" in data:
                    out.append(f"--- {name} ---\n{data[-tail:]}")
                if len(out) >= limit:
                    break
        except OSError:
            pass
        return "\n".join(out)

    def spawn(self, slot_info):
        host = slot_info.hostname
        spec = self.spec
        env = dict(os.environ)
        env.update({
            "HVT_SSK_WORKER": "1",
            "HVT_SSK_SPEC": json.dumps(spec),
            "HVT_RENDEZVOUS_ADDR": self.kv_addr,
            "HVT_HOSTNAME": "127.0.0.1",
            "HVT_SSK_HOST": host,
            "HVT_LOCAL_PROCESS_ID": str(slot_info.local_rank),
            # flat_topo (iso arms): every rank its own topology host,
            # so the hot row lane negotiates a cross-host RING group —
            # same-host groups take the shm/hierarchical backends,
            # which are not ConcurrentGroupsSafe and execute inline on
            # the engine thread in BOTH arms, nulling the pool A/B the
            # iso pair exists to measure (ROADMAP follow-on 4b)
            "HVT_TOPO_HOST": (f"{host}.s{slot_info.local_rank}"
                              if spec.get("flat_topo") else host),
            "HVT_TELEMETRY_ROLE": ("leader" if slot_info.local_rank == 0
                                   else "member"),
            "HVT_KV_RELAY": "1",
            "HVT_LANE_WORKERS": str(self.lane_workers),
            "HVT_DEBUGZ_INTERVAL_MS": "1000",
            "HVT_RELAY_FLUSH_MS": "400",
            "HVT_KV_TTL_SEC": "600",
            "HVT_CTRL_TOPOLOGY": "star",
            "HVT_CONNECT_TIMEOUT": "240",
            "HVT_LOG_LEVEL": "error",
            # reconnect budgets sized for BOTH chaos classes at gang
            # scale: a partition between two 8-rank hosts breaks 64
            # data links at once, and on a 1-core box the acceptor
            # sides drain their re-dial herd over whole seconds — the
            # window must absorb hold + herd. A SIGKILLed peer still
            # escalates fast: its dials are REFUSED instantly, so the
            # retry count (not the window) bounds dead-peer detection
            # to a few seconds of backoff.
            "HVT_LINK_RETRIES": "12",
            "HVT_LINK_RETRY_WINDOW_MS": "10000",
            "HVT_OP_TIMEOUT_MS": "60000",
            "PYTHONUNBUFFERED": "1",
        })
        faults = spec.get("faults") or {}
        fr = faults.get("flaky_rank")
        if fr is not None and slot_info.rank == int(fr):
            env["HVT_FAULT_INJECT"] = (
                f"flaky_conn:rank={fr}:count={faults['flaky_count']}"
                f":after_ops={faults['flaky_after_ops']}")
        part = faults.get("partition")
        if part and host in part["hosts"]:
            env["HVT_FAULT_INJECT"] = (
                f"partition:hosts={part['a']}|{part['b']}"
                f":ms={part['ms']}:after_ops={part['after_ops']}")
        first = slot_info.rank == 0
        log = None
        if self.log_dir and not first:
            log = open(os.path.join(
                self.log_dir,
                f"{host}_{slot_info.local_rank}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            cwd=REPO,
            stdout=subprocess.PIPE if first else
            (log or subprocess.DEVNULL),
            stderr=subprocess.STDOUT if first else
            (log or subprocess.DEVNULL),
            text=first)
        if log is not None:
            log.close()
        with self.lock:
            self.by_host.setdefault(host, []).append(proc)
            if first:
                self.rank0_out = proc
        return proc.wait()

    def kill_host(self, host):
        with self.lock:
            procs = list(self.by_host.get(host, []))
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGKILL)
                except OSError:
                    pass

    def kill_all(self):
        with self.lock:
            procs = [p for ps in self.by_host.values() for p in ps]
        for p in procs:
            if p.poll() is None:
                p.kill()


def _agg_phase(records: list) -> dict:
    """Fold per-rank phase records into per-lane rows with the
    member-identity verdicts."""
    lanes = {}
    engine = {"aborts": 0, "pool_tasks": 0, "reconnects": 0,
              "lane_workers": 0}
    for rec in records:
        engine["aborts"] += rec["engine"]["aborts"]
        engine["pool_tasks"] += rec["engine"]["pool_tasks"]
        engine["reconnects"] += rec["engine"]["reconnects"]
        engine["lane_workers"] = max(engine["lane_workers"],
                                     rec["engine"]["lane_workers"])
        for tenant, lr in rec["lanes"].items():
            key = f"{tenant}:{min(lr['members'])}"
            row = lanes.setdefault(key, {
                "tenant": tenant, "members": lr["members"],
                "member_rows": [], "p99_ms_max": 0.0,
                "p50_samples": [], "exec_us_samples": [],
                "hol_us_samples": [], "ov_samples": []})
            row["member_rows"].append(
                (lr["admitted"], lr["shed"], lr["batches"],
                 lr["dec_crc"]))
            row["p99_ms_max"] = max(row["p99_ms_max"], lr["p99_ms"])
            row["p50_samples"].append(lr["p50_ms"])
            if not lr.get("slot_collision") and lr.get("exec_count"):
                row["exec_us_samples"].append(lr["exec_us_mean"])
            if not lr.get("slot_collision") and lr.get("hol_count"):
                row["hol_us_samples"].append(lr["hol_us_mean"])
            if not lr.get("slot_collision") and lr.get("ov_starts"):
                row["ov_samples"].append(
                    (lr["ov_starts"], lr.get("ov_overlapped", 0)))
            row["admitted"] = lr["admitted"]
            row["shed"] = lr["shed"]
            row["batches"] = lr["batches"]
    for key, row in lanes.items():
        uniq = set(row.pop("member_rows"))
        row["member_identical"] = len(uniq) == 1
        samples = row.pop("exec_us_samples")
        row["exec_us_mean"] = (round(sum(samples) / len(samples), 2)
                               if samples else None)
        row["exec_members"] = len(samples)
        # the lane's head-of-line wait carries the isolation signal on
        # its hot-host member only — keep the MAX over members (the
        # blocked member), not the mean: the idle members' ~0 waits
        # would dilute a per-host effect by the host count
        hol = row.pop("hol_us_samples")
        row["hol_us_max"] = round(max(hol), 2) if hol else None
        row["hol_us_mean"] = (round(sum(hol) / len(hol), 2)
                              if hol else None)
        row["hol_members"] = len(hol)
        # overlapped-exec-starts fraction, worst (= most overlapped)
        # member: the member sharing a rank with the hot tenant is the
        # one whose executions the pool decouples — the others' spans
        # barely intersect and would dilute a lane-sum
        ovs = row.pop("ov_samples")
        row["ov_frac_max"] = (round(max(o / s for s, o in ovs), 4)
                              if ovs else None)
        row["ov_starts"] = sum(s for s, _ in ovs)
        row["ov_overlapped"] = sum(o for _, o in ovs)
        p50s = sorted(row.pop("p50_samples"))
        row["p50_ms_med"] = (round(p50s[len(p50s) // 2], 4)
                             if p50s else None)
    return {"lanes": lanes, "engine": engine, "ranks": len(records)}


def run_arm(arm, spec, lane_workers, timeout=1200):
    """One full soak for one arm; returns the arm record. The
    ElasticDriver, rendezvous server, /statusz plane and autoscaler are
    REAL — only the serving workers are featherweight."""
    _stub_package()
    from benchmarks.ctrl_plane_scaling import _next_port
    from horovod_tpu.runner.elastic.autoscaler import (Autoscaler,
                                                       AutoscalePolicy)
    from horovod_tpu.runner.elastic.discovery import FixedHostDiscovery
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.settings import ElasticSettings
    from horovod_tpu.runner.http_server import RendezvousServer

    np_, hosts = spec["np"], spec["hosts"]
    per_host = spec["per_host"]
    kill_host = f"h{hosts - 1}"
    # push_stale must mean "dead", not "descheduled": on a 1-core box
    # running np_ worker processes, a LIVE worker can easily miss a few
    # 1 s push slots under load — 12 intervals keeps the rule a kill
    # detector while the clean-gang phases stay alert-free
    os.environ["HVT_HEALTH_STALE_INTERVALS"] = "12"
    rendezvous = RendezvousServer()
    rendezvous.master_port_fn = lambda slots, rnd: _next_port()
    kv_port = rendezvous.start(0)
    kv_addr = f"127.0.0.1:{kv_port}"
    gang = _Gang(spec, kv_addr, lane_workers)
    settings = ElasticSettings(
        min_np=np_ - per_host, max_np=np_, elastic_timeout=240.0,
        reset_limit=6, discovery_interval=0.25)
    driver = ElasticDriver(
        rendezvous,
        FixedHostDiscovery({f"h{i}": per_host for i in range(hosts)}),
        settings, create_worker_fn=gang.spawn)
    scaler = Autoscaler(driver, rendezvous,
                        policy=AutoscalePolicy(interval_sec=0.5))
    # failure reports live in a scope the recovery round's store reset
    # clears — a polled step() can miss the window, so chain the
    # driver's put hook and step the policy the moment a report lands
    # (the driver's own handler still runs first)
    driver_hook = rendezvous._on_put

    def _hook(scope, key, value):
        if driver_hook is not None:
            driver_hook(scope, key, value)
        if scope == "failure":
            try:
                scaler.step()
            except Exception:
                pass

    rendezvous.set_put_hook(_hook)

    result = {"arm": arm, "np": np_, "hosts": hosts,
              "lane_workers": lane_workers, "phases": {},
              "alerts_by_phase": {}, "killed_host": None}
    phase_names = ["warm", "baseline", "fire"]
    if spec["phases"].get("storm"):
        phase_names.append("storm")
    deadline = time.monotonic() + timeout
    harvested = {}

    def prog():
        raw = rendezvous.store.get("progress", "0")
        try:
            return json.loads(raw) if raw else {}
        except ValueError:
            return {}

    # building /statusz parses every pushed blob; at 64 ranks that is
    # tens of ms of GIL per build, and this process also serves the
    # gang's whole KV/rendezvous plane — polling it every loop
    # iteration starves the HTTP threads and wedges the gang it is
    # supposed to observe (found live at 64 ranks). Throttle to the
    # push cadence; the health engine self-gates ingestion anyway.
    _last_poll = [0.0]
    _last_scale = [0.0]

    def scaler_tick():
        # fast enough to catch failure reports before the recovery
        # round's store reset clears the failure scope, slow enough not
        # to hog the GIL the HTTP plane needs
        now = time.monotonic()
        if now - _last_scale[0] < 0.3:
            return
        _last_scale[0] = now
        try:
            scaler.step()
        except Exception:
            pass

    def poll_statusz(phase):
        now = time.monotonic()
        if now - _last_poll[0] < 0.8:
            return
        _last_poll[0] = now
        try:
            snap = rendezvous.statusz_snapshot()
        except Exception:
            return
        bucket = result["alerts_by_phase"].setdefault(phase, {})
        for a in snap.get("alerts") or ():
            bucket.setdefault(a["rule"], set()).add(a.get("subject"))

    def harvest(phase, expect, wait_sec=30):
        """Collect every rank's /kv/ssk/<phase>/ record (the relay may
        deliver a beat after the barrier)."""
        t_end = time.monotonic() + wait_sec
        while time.monotonic() < t_end:
            keys = rendezvous.store.keys("ssk")
            mine = [k for k in keys if k.startswith(f"{phase}/")]
            if len(mine) >= expect:
                break
            time.sleep(0.2)
        recs = []
        for k in rendezvous.store.keys("ssk"):
            if not k.startswith(f"{phase}/"):
                continue
            try:
                recs.append(json.loads(rendezvous.store.get("ssk", k)))
            except (ValueError, TypeError):
                pass
        if len(recs) < expect:
            raise RuntimeError(
                f"{arm}: phase {phase}: {len(recs)}/{expect} records"
                f"\n{gang.crash_logs()}")
        harvested[phase] = recs
        return recs

    try:
        driver.start(np_)
        seen = set()
        # alerts observed before the first phase marker land in "boot":
        # a 64-rank bring-up is a re-dial herd (listen backlogs
        # overflow, refused dials retry as reconnects), so the window
        # between driver.start and the end of `warm` is NOT a
        # clean-gang observation — only `baseline` is gated alert-free
        cur_phase = "boot"
        while True:
            p = prog()
            done = p.get("phase_done")
            poll_statusz(cur_phase)
            scaler_tick()
            if done and done not in seen and done in phase_names:
                # the progress marker is one key — a fast phase's
                # marker can be overwritten before this loop polls, so
                # harvest every phase up to `done` (the records persist
                # in the ssk scope until the next round reset)
                idx = phase_names.index(done)
                for p_name in phase_names[:idx + 1]:
                    if p_name not in seen:
                        seen.add(p_name)
                        harvest(p_name, np_)
                cur_phase = (phase_names[idx + 1]
                             if idx + 1 < len(phase_names) else done)
            if phase_names[-1] in seen:
                break
            if time.monotonic() > deadline or (driver.finished()
                                               and driver.error):
                raise RuntimeError(
                    f"{arm}: phases stalled at {sorted(seen)} "
                    f"(progress={p}, err={driver.error})"
                    f"\n{gang.crash_logs()}")
            time.sleep(0.15)

        if spec.get("kill"):
            # the workers are now in `endure`; kill the last host and
            # watch the elastic plane re-shard mid-traffic
            time.sleep(spec.get("kill_delay_sec", 1.0))
            t_kill = time.monotonic()
            gang.kill_host(kill_host)
            result["killed_host"] = kill_host
            cur_phase = "endure"
            while True:
                p = prog()
                poll_statusz(cur_phase)
                scaler_tick()
                if p.get("phase_done") == "recovered":
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"{arm}: gang never recovered (progress={p})"
                        f"\n{gang.crash_logs()}")
                if driver.finished() and driver.error:
                    raise RuntimeError(
                        f"{arm}: driver failed mid-recovery: "
                        f"{driver.error}\n{gang.crash_logs()}")
                time.sleep(0.15)
            result["time_to_recovered_sec"] = round(
                time.monotonic() - t_kill, 2)
            result["world_after"] = int(prog().get("size") or 0)
            harvest("recovered", np_ - per_host)
            cur_phase = "recovered"
            # the killed ranks' last pushes age into push_stale after
            # HVT_HEALTH_STALE_INTERVALS x 1 s — keep watching until
            # the alert lands (bounded)
            t_stale = t_kill + 45
            while time.monotonic() < min(t_stale, deadline):
                poll_statusz(cur_phase)
                rules = result["alerts_by_phase"].get(cur_phase) or {}
                all_rules = {r for b in
                             result["alerts_by_phase"].values()
                             for r in b}
                if "push_stale" in all_rules:
                    break
                del rules
                time.sleep(0.5)

        # let every worker publish + exit
        t_end = time.monotonic() + 120
        while not driver.finished() and time.monotonic() < t_end:
            poll_statusz(cur_phase)
            time.sleep(0.2)
        results = driver.get_results() or {}
        # killed-host workers legitimately die by SIGKILL; every
        # surviving worker must exit 0
        bad = {r: rc for r, rc in results.items()
               if rc not in (0, -signal.SIGKILL)}
        if bad:
            raise RuntimeError(f"{arm}: nonzero worker exits {bad}"
                               f"\n{gang.crash_logs()}")
        for phase, recs in harvested.items():
            result["phases"][phase] = _agg_phase(recs)
        result["alerts_by_phase"] = {
            ph: {rule: sorted(x for x in subs if x is not None)
                 for rule, subs in rules.items()}
            for ph, rules in result["alerts_by_phase"].items()}
        result["autoscaler_decisions"] = sorted(
            {a for _, a, _ in scaler.decisions})
        return result
    finally:
        scaler.stop()
        gang.kill_all()
        try:
            driver.stop()
        except Exception:
            pass
        rendezvous.stop()


# ---------------------------------------------------------------------------
# capture / claims / check
# ---------------------------------------------------------------------------

def _spec(smoke: bool) -> dict:
    """The chaos-soak shape: the composition gate (clean phases, hot +
    flaky fire, partition storm, host kill, re-shard). The soak arm
    runs a MODERATE lane-worker count in capture: 64 ranks on the
    1-core harness box are already fully oversubscribed, so the pool's
    benefit cannot show there (that is the iso pair's job) while its
    extra threads would only slow the box — see BENCH_NOTES r15."""
    if smoke:
        return {
            "np": 8, "hosts": 4, "per_host": 2, "window": 8,
            "batch": 4, "burst": 4, "admission_ms": 2000.0,
            "cycle_ms": 2, "push_sec": 1.0, "lane_workers": 4,
            "row_elems": 256, "col_elems": 256,
            "hot_elems": 16384, "hot_factor": 4, "hot_host": "h1",
            "phases": {"warm": 8, "baseline": 48, "fire": 48,
                       "storm": 32, "endure": 4000, "recovered": 24},
            "kill": True, "kill_delay_sec": 1.0,
            "faults": {
                "flaky_rank": 3, "flaky_count": 2,
                "flaky_after_ops": 0,  # filled by capture()
                "partition": {"hosts": ["h2", "h3"], "a": "h2",
                              "b": "h3", "ms": 600,
                              "after_ops": 0},
            },
        }
    return {
        "np": 64, "hosts": 8, "per_host": 8, "window": 8,
        "batch": 4, "burst": 4, "admission_ms": 4000.0,
        "cycle_ms": 2, "push_sec": 1.0, "lane_workers": 2,
        "row_elems": 256, "col_elems": 256,
        "hot_elems": 32768, "hot_factor": 4, "hot_host": "h1",
        "phases": {"warm": 8, "baseline": 64, "fire": 64,
                   "storm": 32, "endure": 4000, "recovered": 24},
        "kill": True, "kill_delay_sec": 1.5,
        "faults": {
            "flaky_rank": 9, "flaky_count": 2,
            "flaky_after_ops": 0,
            "partition": {"hosts": ["h2", "h3"], "a": "h2",
                          "b": "h3", "ms": 600, "after_ops": 0},
        },
    }


def _iso_spec(smoke: bool) -> dict:
    """The lane-isolation A/B shape: CLEAN (no chaos, no kill), small
    enough that the 1-core harness box has actual concurrency headroom
    for the pool to exploit — the in-rank head-of-line effect is an
    engine-THREAD property, not a gang-size property, so it is
    measured where the hardware can express it."""
    return {
        "np": 8 if smoke else 16, "hosts": 4, "per_host": 2 if smoke
        else 4, "window": 8, "batch": 4, "burst": 4,
        "admission_ms": 4000.0, "cycle_ms": 2, "push_sec": 1.0,
        "row_elems": 256, "col_elems": 256,
        # hot tenant = FEW, HUGE requests (hot_factor 1, 4 MB
        # payloads): the in-rank blocking the pool removes scales with
        # the hot op's DURATION, while the python harness's own
        # artifacts (the hot serving thread's GIL share delays the
        # SAME rank's other-tenant submits, identically in both arms)
        # scale with the request COUNT — probed span-level with the
        # flight recorder, BENCH_NOTES r15. Deep hot_window/hot_burst
        # keep several fused ops outstanding so the nopool engine
        # thread is continuously busy
        "hot_elems": 1048576, "hot_factor": 1,
        "hot_host": "h1", "hot_window": 24, "hot_burst": 12,
        "phases": {"warm": 16, "baseline": 64, "fire": 64, "storm": 0},
        "kill": False, "faults": {}, "flat_topo": True,
    }


def _ops_before(spec, phase: str) -> int:
    """Data-plane ops a NON-hot, non-killed rank has executed before
    `phase`'s traffic starts: per completed phase, each lane
    contributes requests/batch fused collectives plus the pre- and
    post-phase barrier allreduces; `phase`'s own pre-barrier has also
    run by the time its traffic flows."""
    order = ["warm", "baseline", "fire", "storm"]
    ops = 0
    for name in order[:order.index(phase)]:
        n = spec["phases"][name]
        ops += 2 * (n // spec["batch"])  # row + col lanes
        ops += 2  # pre- + post-phase barriers
    return ops + 1  # the current phase's pre-barrier


def _fill_fault_ops(spec):
    """Arm the transient faults by op count so they fire INSIDE their
    phase: flaky_conn ~25% into `fire` (the flaky rank is on the hot
    host, whose row lane runs hot_factor x requests — but its op
    counter is also fed by the same inflated stream, so anchoring at
    the phase floor plus a small margin keeps the cuts inside fire),
    partition ~30% into `storm` (its hosts are non-hot, so the plain
    per-rank count applies)."""
    f = spec["faults"]
    fire_slots = spec["phases"]["fire"] // spec["batch"]
    f["flaky_after_ops"] = _ops_before(spec, "fire") + \
        max(2, fire_slots // 4)
    storm_slots = spec["phases"]["storm"] // spec["batch"]
    f["partition"]["after_ops"] = _ops_before(spec, "storm") + \
        max(2, storm_slots // 3)


def _col_ratio(arm_rec, spec, metric="exec_us_mean"):
    """Worst observer column lane's fire/baseline ratio of `metric` —
    the idle-lane isolation number. The gated metric is the engine's
    in-rank data-plane exec latency (`exec_us_mean`): it measures
    exactly the head-of-line blocking the lane pool removes and is
    stable on an oversubscribed 1-core harness box, where end-to-end
    p99s at ms scale are scheduler-quantum noise (reported as
    `p99_ms_max` per lane but not gated — BENCH_NOTES r15). Column
    lanes containing the flaky rank are excluded: their spikes are the
    injected fault, not the hot neighbor."""
    flaky = (spec.get("faults") or {}).get("flaky_rank")
    base = arm_rec["phases"].get("baseline", {}).get("lanes", {})
    fire = arm_rec["phases"].get("fire", {}).get("lanes", {})
    ratios = []
    for key, row in fire.items():
        if row["tenant"] != "col":
            continue
        if flaky is not None and int(flaky) in row["members"]:
            continue
        b = base.get(key)
        if not b or not b.get(metric) or not row.get(metric):
            continue
        ratios.append(row[metric] / b[metric])
    # mean over the observer lanes: each lane's ratio carries shared-box
    # jitter, and a worst-of gate would gate on that jitter instead of
    # the systematic head-of-line effect
    return round(sum(ratios) / len(ratios), 3) if ratios else 0.0


def _col_hol_us(arm_rec, spec, phase="fire"):
    """Mean over observer column lanes of each lane's WORST-member
    head-of-line wait (µs) in `phase`. The worst member is the one
    sharing a rank with the hot row tenant — the rank where the
    single-thread engine serializes the idle lane behind the hot one.
    Column lanes containing the flaky rank are excluded (their waits
    are the injected fault)."""
    flaky = (spec.get("faults") or {}).get("flaky_rank")
    lanes = arm_rec["phases"].get(phase, {}).get("lanes", {})
    vals = []
    for row in lanes.values():
        if row["tenant"] != "col":
            continue
        if flaky is not None and int(flaky) in row["members"]:
            continue
        if row.get("hol_us_max"):
            vals.append(row["hol_us_max"])
    return round(sum(vals) / len(vals), 2) if vals else 0.0


def _col_ov_frac(arm_rec, spec, phase="fire"):
    """Mean over observer column lanes of each lane's most-overlapped
    member's exec-start overlap fraction in `phase`: the share of the
    column lane's executions that STARTED while the crossing row
    lane's execution span was still open on the same rank. The gated
    isolation metric — pure event ordering. A single-thread engine
    (HVT_LANE_WORKERS=0) can never hold two exec spans open, so its
    fraction is structurally 0; the pool arm's is direct proof the
    idle lane executes DURING the saturated neighbor's executions
    instead of queueing behind them. Column lanes containing the flaky
    rank are excluded (their schedule is the injected fault's)."""
    flaky = (spec.get("faults") or {}).get("flaky_rank")
    lanes = arm_rec["phases"].get(phase, {}).get("lanes", {})
    vals = []
    for row in lanes.values():
        if row["tenant"] != "col":
            continue
        if flaky is not None and int(flaky) in row["members"]:
            continue
        if row.get("ov_frac_max") is not None:
            vals.append(row["ov_frac_max"])
    return round(sum(vals) / len(vals), 4) if vals else 0.0


def _hot_row_exec_us(arm_rec, spec, phase="fire"):
    """The hot host's row lane data-plane exec mean (µs) in `phase` —
    the natural scale of the head-of-line blocking: an idle lane
    serialized behind the hot tenant waits a large fraction of this;
    an isolated one, a small fraction. Normalizing by it makes the
    HOL gates dimensionless (box-speed independent)."""
    hot_i = int(str(spec["hot_host"])[1:])
    key = f"row:{hot_i * spec['per_host']}"
    row = arm_rec["phases"].get(phase, {}).get("lanes", {}).get(key)
    return (row or {}).get("exec_us_mean") or 0.0


def capture(out_path, smoke=False):
    spec = _spec(smoke)
    _fill_fault_ops(spec)
    iso_spec = _iso_spec(smoke)
    record = {"schema": SCHEMA, "mode": "smoke" if smoke else "capture",
              "created_unix": int(time.time()),
              "config": spec, "iso_config": iso_spec,
              "arms": {}, "claims": {}}

    def arm(name, arm_spec, workers, timeout):
        t0 = time.monotonic()
        rec = run_arm(name, arm_spec, workers, timeout=timeout)
        rec["total_sec"] = round(time.monotonic() - t0, 1)
        record["arms"][name] = rec
        print(f"{name} arm done in {rec['total_sec']}s", flush=True)
        return rec

    # the lane-isolation A/B pair: clean, small, pool on vs off
    iso_nopool = arm("iso_nopool", iso_spec, 0, 900)
    iso_pool = arm("iso_pool", iso_spec, 4, 900)
    # the chaos soak: full storyline at gang scale
    soak = arm("soak", spec, spec.get("lane_workers", 2),
               900 if smoke else 1800)

    ratio_pool = _col_ratio(iso_pool, iso_spec)
    ratio_nopool = _col_ratio(iso_nopool, iso_spec)
    # the A/B bound rides the per-lane MEDIAN latency: the hot
    # neighbor shifts every idle-lane request's latency (not just the
    # tail), so p50 carries the head-of-line signal with far less
    # scheduler noise than p99 on the shared harness box — probed at
    # 1.63-1.89x across repeated runs vs 1.0-2.6x for exec-based and
    # 1.0-1.24x for p99-based (BENCH_NOTES r15)
    p50_pool = _col_ratio(iso_pool, iso_spec, metric="p50_ms_med")
    p50_nopool = _col_ratio(iso_nopool, iso_spec, metric="p50_ms_med")
    # `baseline` is the gated clean-gang observation; `boot` (driver
    # start → end of warm, the 64-link dial herd) and `warm` roll into
    # the ungated boot bucket — see run_arm's cur_phase comment
    baseline_alerts = sorted(
        (soak["alerts_by_phase"].get("baseline") or {}).keys())
    observed = sorted({r for rules in soak["alerts_by_phase"].values()
                       for r in rules})
    per_host = spec["per_host"]
    killed_ranks = set(range(spec["np"] - per_host, spec["np"]))
    stale_subjects = {
        s for ph_rules in soak["alerts_by_phase"].values()
        for s in ph_rules.get("push_stale", ())}
    ident = all(
        row["member_identical"]
        for arm_rec in record["arms"].values()
        for phase in arm_rec["phases"].values()
        for row in phase["lanes"].values())
    # transient-fault abort gate: cumulative engine aborts at the end
    # of the LAST pre-kill phase must be zero on every rank
    last_transient = "storm" if spec["phases"].get("storm") else "fire"
    soak_tr = soak["phases"][last_transient]["engine"]
    batches_ok = all(
        0 < row["batches"] <= row["admitted"]
        and row["admitted"] >= spec["batch"] * (row["batches"] - 1)
        for phase in soak["phases"].values()
        for row in phase["lanes"].values() if row["admitted"])
    # lane-isolation A/B, gated on exec-span overlap: the fraction of
    # the idle column lane's executions that START while the hot row
    # lane's execution is still mid-flight on the shared rank. Pure
    # event ordering, so the oversubscribed 1-core harness box cannot
    # fake OR hide it: without the pool the engine thread can never
    # hold two exec spans open (the idle op literally queues behind
    # the hot one → fraction structurally 0); with the pool the idle
    # lane's worker starts it mid-span (fraction ~ the hot lane's duty
    # cycle). Wall-clock exec/hol/p50/p99 ratios stay recorded but
    # ungated — on this box they are scheduler noise in BOTH
    # directions (BENCH_NOTES r15).
    ov_pool = _col_ov_frac(iso_pool, iso_spec)
    ov_nopool = _col_ov_frac(iso_nopool, iso_spec)
    hol_pool = _col_hol_us(iso_pool, iso_spec)
    hol_nopool = _col_hol_us(iso_nopool, iso_spec)
    hot_exec_pool = _hot_row_exec_us(iso_pool, iso_spec)
    hot_exec_nopool = _hot_row_exec_us(iso_nopool, iso_spec)
    record["claims"] = {
        "idle_col_overlap_frac_pool": ov_pool,
        "idle_col_overlap_frac_nopool": ov_nopool,
        "idle_col_hol_us_fire_pool": hol_pool,
        "idle_col_hol_us_fire_nopool": hol_nopool,
        "nopool_hol_over_pool_hol": round(
            hol_nopool / max(hol_pool, 1e-9), 2),
        "hot_row_exec_us_fire_pool": hot_exec_pool,
        "hot_row_exec_us_fire_nopool": hot_exec_nopool,
        # report-only wall-clock ratios (see the gate comment above)
        "idle_col_exec_fire_over_baseline_pool": ratio_pool,
        "idle_col_exec_fire_over_baseline_nopool": ratio_nopool,
        "idle_col_p50_fire_over_baseline_pool": p50_pool,
        "idle_col_p50_fire_over_baseline_nopool": p50_nopool,
        "nopool_over_pool": round(
            p50_nopool / max(p50_pool, 1e-9), 2),
        # end-to-end p99 ratios: reported, not gated (ms-scale
        # scheduler noise on the 1-core harness box — BENCH_NOTES r15)
        "idle_col_p99_fire_over_baseline_pool": _col_ratio(
            iso_pool, iso_spec, metric="p99_ms_max"),
        "idle_col_p99_fire_over_baseline_nopool": _col_ratio(
            iso_nopool, iso_spec, metric="p99_ms_max"),
        "soak_col_exec_fire_over_baseline": _col_ratio(soak, spec),
        "zero_aborts_transient": soak_tr["aborts"] == 0,
        "pool_engaged_tasks": soak_tr["pool_tasks"],
        "iso_pool_engaged_tasks":
            iso_pool["phases"]["fire"]["engine"]["pool_tasks"],
        "member_identical_decisions": ident,
        "batching_coalesced": batches_ok,
        "baseline_alert_rules": baseline_alerts,
        "observed_alert_rules": observed,
        "push_stale_subjects_killed_only": all(
            s in {f"rank {r}" for r in killed_ranks}
            for s in stale_subjects),
        "reconnect_storm_seen": any(
            "reconnect_storm" in rules
            for rules in soak["alerts_by_phase"].values()),
        "push_stale_seen": bool(stale_subjects),
        "autoscaler_shed": "shed" in soak.get("autoscaler_decisions",
                                              ()),
        "reshard_world": soak.get("world_after"),
        "reshard_expected": spec["np"] - per_host,
        "time_to_recovered_sec": soak.get("time_to_recovered_sec"),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        print(f"wrote {out_path}")
    print("claims: " + json.dumps(record["claims"], sort_keys=True))
    rc = check_record(record)
    if rc:
        print("serving_soak: CAPTURE FAILED ITS OWN GATES",
              file=sys.stderr)
    return record, rc


def check_record(rec: dict) -> int:
    errs = []

    def need(cond, msg):
        if not cond:
            errs.append(msg)

    need(rec.get("schema") == SCHEMA, f"schema != {SCHEMA}")
    mode = rec.get("mode")
    need(mode in ("smoke", "capture"), f"bad mode {mode!r}")
    arms = rec.get("arms") or {}
    need({"iso_pool", "iso_nopool", "soak"} <= set(arms),
         "missing arms")
    claims = rec.get("claims") or {}
    for arm_name, arm_rec in arms.items():
        for phase in ("warm", "baseline", "fire"):
            need(phase in (arm_rec.get("phases") or {}),
                 f"{arm_name}: phase {phase} missing")
        for pname, ph in (arm_rec.get("phases") or {}).items():
            need(ph.get("lanes"), f"{arm_name}/{pname}: no lanes")
    if errs:
        for e in errs:
            print(f"serving_soak --check: {e}", file=sys.stderr)
        return 1
    # mode-aware gates: the committed capture pins the ISSUE numbers;
    # the CI smoke runs the same machinery at a smaller shape with
    # looser bounds — the CORRECTNESS gates stay strict in both modes.
    # The isolation pair is gated on exec-span overlap (see the claims
    # comment in capture()): with the pool, a meaningful share of the
    # idle column lane's executions must START while the hot row
    # lane's execution span is still open on the shared rank; without
    # the pool that is structurally impossible (one engine thread, one
    # span at a time), so the nopool fraction must be exactly 0.
    # Wall-clock exec/hol/p50/p99 ratios are recorded report-only.
    ov_pool_gate = 0.3 if mode == "capture" else 0.15
    hol_ab_gate = 4.0 if mode == "capture" else 2.0
    need(claims.get("idle_col_overlap_frac_pool", 0) >= ov_pool_gate,
         f"pool arm: only {claims.get('idle_col_overlap_frac_pool')} "
         f"of idle-lane exec starts overlapped the hot lane's exec "
         f"span (< {ov_pool_gate}) — the pool is not decoupling the "
         f"lanes")
    need(claims.get("idle_col_overlap_frac_nopool", 1) == 0.0,
         f"nopool arm: idle-lane exec starts overlapped the hot "
         f"lane's span ({claims.get('idle_col_overlap_frac_nopool')})"
         f" — impossible for a single-thread engine; the A/B arms are "
         f"mislabeled or the pool env leaked")
    # the pinned latency-ratio bound: the idle lane's submit →
    # engine-pickup wait on the blocked member, nopool over pool. Both
    # ends stamp on the same rank, so this survives the shared harness
    # box far better than end-to-end percentiles (still recorded
    # report-only below)
    need(claims.get("nopool_hol_over_pool_hol", 0) >= hol_ab_gate,
         f"pool A/B: nopool/pool idle-lane head-of-line wait "
         f"{claims.get('nopool_hol_over_pool_hol')} < {hol_ab_gate}")
    need(claims.get("zero_aborts_transient") is True,
         "engine aborts under transient chaos")
    need(claims.get("pool_engaged_tasks", 0) > 0,
         "lane pool executed no tasks in the soak arm")
    need(claims.get("iso_pool_engaged_tasks", 0) > 0,
         "lane pool executed no tasks in the iso_pool arm")
    need(claims.get("member_identical_decisions") is True,
         "replica members disagreed on (admit, shed, batch) decisions")
    need(claims.get("batching_coalesced") is True,
         "request batching did not coalesce")
    need(claims.get("baseline_alert_rules") == [],
         f"clean-gang phases raised alerts: "
         f"{claims.get('baseline_alert_rules')}")
    need(set(claims.get("observed_alert_rules") or ())
         <= ALLOWED_ALERTS,
         f"unexpected alert rules: {claims.get('observed_alert_rules')}")
    need(claims.get("reconnect_storm_seen") is True,
         "flaky_conn chaos never surfaced as a reconnect_storm alert")
    need(claims.get("push_stale_seen") is True,
         "the host kill never surfaced as push_stale alerts")
    need(claims.get("push_stale_subjects_killed_only") is True,
         "push_stale alerts named ranks outside the killed host")
    need(claims.get("autoscaler_shed") is True,
         "the autoscaler never recorded the shed decision")
    need(claims.get("reshard_world")
         == claims.get("reshard_expected"),
         f"re-shard world {claims.get('reshard_world')} != expected "
         f"{claims.get('reshard_expected')}")
    for e in errs:
        print(f"serving_soak --check: {e}", file=sys.stderr)
    if not errs:
        print(f"serving_soak --check: OK (mode={mode}, claims: "
              + json.dumps(claims, sort_keys=True) + ")")
    return 1 if errs else 0


def check(path: str) -> int:
    with open(path) as f:
        rec = json.load(f)
    return check_record(rec)


def main():
    if os.environ.get("HVT_SSK_WORKER"):
        _worker()
        return 0
    args = sys.argv[1:]

    def argval(flag, dflt):
        if flag not in args:
            return dflt
        i = args.index(flag) + 1
        if i >= len(args):
            sys.exit(f"serving_soak: {flag} requires a value")
        return args[i]

    if "--check" in args:
        return check(argval("--check", ""))
    out = argval("--out", "" if "--smoke" in args
                 else os.path.join(REPO, "benchmarks",
                                   "r15_serving_soak.json"))
    _, rc = capture(out, smoke="--smoke" in args)
    return rc


if __name__ == "__main__":
    sys.exit(main())
