#!/bin/bash
# Round-7 capture: the serving-gang lane-isolation loadtest
# (benchmarks/r07_serving_loadtest.json).
#
# Experiment: a 4-proc loopback gang split into two 2-rank replica
# lanes. Replica 1 runs a fixed light closed loop (burst 2, window 4)
# in BOTH phases; replica 0 runs the same load in `baseline` and a
# full-window saturation loop (burst 4 = window) in `contended`. The
# artifact's `isolation` block compares replica 1's p99 across phases —
# the lane-isolation acceptance is ratio ≤ 1.25. Phases run
# contended-FIRST so any engine/OS warmth advantage accrues to the
# idle baseline (the conservative direction for the claim).
#
# Methodology notes for this 1-core host:
#   - gap-ms 0: open-loop pacing gaps let the engine's coalescing
#     waits dominate the idle phase and would make "idle" look SLOWER
#     than "contended" for the wrong reason;
#   - window 4: the measured latency includes window residency, and a
#     deep window amplifies scheduler-noise tails (p99 swings of 3x
#     were observed at window 8 with 4 procs on 1 core);
#   - --warmup 64: first-touch costs stay out of both phases.
cd "$(dirname "$0")/.." || exit 1
set -euo pipefail

make -C horovod_tpu/csrc -j

timeout -k 30 600 env JAX_PLATFORMS=cpu \
  python -m horovod_tpu.runner.launch -np 4 --master-port 29771 \
  python -m horovod_tpu.serving.loadgen \
    --replicas 2 --requests 800 --bytes 8192 --burst 2 --window 4 \
    --admission-ms 250 --gap-ms 0 --sync-every 50 --warmup 64 \
    --saturate-replica 0 --saturate-factor 2 \
    --phases contended,baseline \
    --output benchmarks/r07_serving_loadtest.json

python -m horovod_tpu.serving.loadgen \
  --check benchmarks/r07_serving_loadtest.json
python - <<'EOF'
import json
d = json.load(open("benchmarks/r07_serving_loadtest.json"))
iso = d["isolation"]
print(f"lane isolation: replica {iso['observed_replica']} p99 "
      f"{iso['idle_p99_ms']:.2f} ms idle vs "
      f"{iso['contended_p99_ms']:.2f} ms contended "
      f"(ratio {iso['ratio']:.2f}; acceptance ≤ 1.25)")
assert iso["ratio"] <= 1.25, iso
EOF
