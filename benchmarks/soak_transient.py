#!/usr/bin/env python
"""Transient-fault soak: seeded randomized chaos over a 4-proc gang.

The acceptance story of the self-healing links (csrc/transport.h) at
campaign scale: N rounds of the SAME deterministic workload, round 0
with injection off (the reference CRC), every later round with a
seeded random TRANSIENT fault spec (flaky_conn / delay_ms / partition /
reset_storm) injected mid-run. Every round must produce the
bit-identical result CRC on every rank with ZERO aborts, and the soak
as a whole must have actually exercised ≥1 reconnect — otherwise the
schedule was a no-op and the run fails rather than vacuously passing.

Usage:
  python benchmarks/soak_transient.py [--rounds 4] [--seed 5]
      [--np 4] [--ops 16] [--numel 65536] [--out artifact.json]

Wired as `./ci.sh --soak` (non-tier-1, like --chaos). Exit 0 = every
invariant held.
"""

import argparse
import json
import os
import random
import socket
import subprocess
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import os, sys, zlib
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvt
from horovod_tpu.engine import native
hvt.init()
r, n = hvt.rank(), hvt.size()
crc = 0
for i in range({ops}):
    # deterministic mixed-size payloads; every rank contributes
    numel = {numel} if i % 3 else {numel} * 4
    x = (np.arange(numel, dtype=np.float32) * (r + 1) + i).astype(np.float32)
    res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"soak.{{i}}"))
    crc = zlib.crc32(res.tobytes(), crc)
st = native.engine_stats()
broken, info = native.engine_broken()
out = {{
    "rank": r,
    "crc": crc,
    "aborts": sum(st["aborts"].values()),
    "broken": bool(broken),
    "reconnects": sum(st["link_reconnects"].values()),
    "replay_bytes": st["replay_bytes"],
    "frames_replayed": st["frames_replayed"],
}}
print("SOAK-RESULT " + __import__("json").dumps(out), flush=True)
hvt.shutdown()
"""


def _next_port():
    p = 24000 + (os.getpid() * 577) % 8000
    while True:
        p += 1
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", p))
                return p
            except OSError:
                continue


def _fault_schedule(rng, np_, rounds):
    """One transient spec per fault round, drawn from the seeded RNG.
    Every spec here must be SURVIVABLE: the gang heals, zero aborts."""
    specs = []
    for _ in range(rounds):
        kind = rng.choice(["flaky_conn", "delay_ms", "partition",
                           "reset_storm"])
        if kind == "flaky_conn":
            specs.append("flaky_conn:rank=%d:count=%d:after_ops=%d"
                         % (rng.randrange(np_), rng.randint(1, 2),
                            rng.randint(2, 5)))
        elif kind == "delay_ms":
            specs.append("delay_ms:rank=%d:%d"
                         % (rng.randrange(np_), rng.randint(20, 60)))
        elif kind == "partition":
            specs.append("partition:hosts=hA|hB:ms=%d"
                         % rng.randint(200, 500))
        else:
            specs.append("reset_storm:every_ops=%d:rank=%d"
                         % (rng.randint(3, 5), rng.randrange(np_)))
    return specs


def _run_round(script_path, np_, spec, timeout_sec, logdir, tag):
    port = _next_port()
    procs, logs = [], []
    for rank in range(np_):
        env = dict(os.environ)
        env.update({
            "HVT_MASTER_ADDR": "127.0.0.1",
            "HVT_MASTER_PORT": str(port),
            "HVT_PROCESS_ID": str(rank),
            "HVT_NUM_PROCESSES": str(np_),
            "HVT_SHM_ALLREDUCE": "0",      # the TCP plane is under test
            "HVT_HIERARCHICAL_ALLREDUCE": "0",
            # fake a 2-host split so partition specs have a boundary
            "HVT_TOPO_HOST": "hA" if rank < np_ // 2 else "hB",
            "HVT_OP_TIMEOUT_MS": "30000",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "PALLAS_AXON_POOL_IPS": "",
        })
        if spec:
            env["HVT_FAULT_INJECT"] = spec
        else:
            env.pop("HVT_FAULT_INJECT", None)
        log = open(os.path.join(logdir, f"soak_{tag}_r{rank}.log"), "w+")
        procs.append(subprocess.Popen(
            [sys.executable, script_path], env=env, cwd=REPO,
            stdout=log, stderr=subprocess.STDOUT))
        logs.append(log)
    deadline = time.time() + timeout_sec
    codes = []
    for p in procs:
        try:
            codes.append(p.wait(timeout=max(1, deadline - time.time())))
        except subprocess.TimeoutExpired:
            p.kill()
            codes.append("TIMEOUT")
    results = []
    for log in logs:
        log.flush()
        log.seek(0)
        text = log.read()
        log.close()
        res = None
        for ln in text.splitlines():
            if ln.startswith("SOAK-RESULT "):
                res = json.loads(ln[len("SOAK-RESULT "):])
        results.append((res, text))
    return codes, results


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=4,
                    help="fault rounds after the baseline (default 4)")
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument("--np", type=int, default=4, dest="nproc")
    ap.add_argument("--ops", type=int, default=16)
    ap.add_argument("--numel", type=int, default=65536)
    ap.add_argument("--timeout", type=int, default=180,
                    help="per-round hard timeout (seconds)")
    ap.add_argument("--out", default=None,
                    help="write the soak artifact JSON here")
    ap.add_argument("--logdir", default="/tmp")
    args = ap.parse_args(argv)

    rng = random.Random(args.seed)
    specs = [None] + _fault_schedule(rng, args.nproc, args.rounds)
    script = os.path.join(args.logdir, f"hvt_soak_{os.getpid()}.py")
    with open(script, "w") as f:
        f.write(textwrap.dedent(_WORKER.format(
            repo=REPO, ops=args.ops, numel=args.numel)))

    failures = []
    rounds_out = []
    ref_crc = None
    total_reconnects = 0
    for i, spec in enumerate(specs):
        tag = "base" if spec is None else f"f{i}"
        codes, results = _run_round(script, args.nproc, spec,
                                    args.timeout, args.logdir, tag)
        row = {"round": i, "spec": spec or "none", "codes": codes}
        crcs, recon, aborts = [], 0, 0
        for rank, (res, text) in enumerate(results):
            if codes[rank] != 0 or res is None:
                failures.append(
                    f"round {i} ({spec or 'baseline'}): rank {rank} "
                    f"rc={codes[rank]}\n{text[-2000:]}")
                continue
            crcs.append(res["crc"])
            recon += res["reconnects"]
            aborts += res["aborts"]
            if res["broken"]:
                failures.append(f"round {i}: rank {rank} engine broken")
        row.update(crcs=crcs, reconnects=recon, aborts=aborts)
        rounds_out.append(row)
        if len(crcs) == args.nproc:
            if len(set(crcs)) != 1:
                failures.append(f"round {i}: ranks disagree on the "
                                f"result CRC: {crcs}")
            elif ref_crc is None:
                ref_crc = crcs[0]
            elif crcs[0] != ref_crc:
                failures.append(
                    f"round {i} ({spec}): CRC {crcs[0]:#x} != "
                    f"injection-off baseline {ref_crc:#x} — the healed "
                    f"run is NOT bit-identical")
        if aborts:
            failures.append(f"round {i} ({spec or 'baseline'}): "
                            f"{aborts} abort(s) — a transient fault "
                            f"escalated")
        if spec is not None:
            total_reconnects += recon
        print(f"[soak] round {i} spec={spec or 'none':<44} "
              f"crc={'%08x' % crcs[0] if crcs else '????'} "
              f"reconnects={recon} aborts={aborts}", flush=True)

    if total_reconnects < 1:
        failures.append("the whole soak recorded ZERO reconnects — the "
                        "fault schedule never bit (seed too tame?)")

    artifact = {
        "schema": "hvt-soak-r1",
        "seed": args.seed,
        "np": args.nproc,
        "ops": args.ops,
        "baseline_crc": ref_crc,
        "rounds": rounds_out,
        "total_reconnects": total_reconnects,
        "ok": not failures,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=2)
        print(f"[soak] artifact -> {args.out}")
    if failures:
        print("\n[soak] FAILED:", file=sys.stderr)
        for fl in failures:
            print(" - " + fl, file=sys.stderr)
        return 1
    print(f"[soak] OK: {len(specs) - 1} fault rounds bit-identical to "
          f"baseline, {total_reconnects} reconnects, zero aborts")
    return 0


if __name__ == "__main__":
    sys.exit(main())
