#!/usr/bin/env python
"""Simulated fleet-telemetry scaling harness
(``python benchmarks/telemetry_scaling.py``).

Proves the leader-aggregated telemetry plane
(``horovod_tpu/metrics/telemetry.py``) at 64 simulated ranks on 8 fake
hosts, reusing the featherweight MiniEngine worker of
``benchmarks/ctrl_plane_scaling.py`` (bare ctypes over
``libhvt_core.so`` — no jax/numpy per worker; the ``horovod_tpu``
package root is stubbed so the import-light telemetry/metrics modules
load without pulling jax into 64 processes).

Each run spins up one REAL engine gang over loopback plus the real
driver-side ``RendezvousServer`` (with ``/statusz``), runs the real
:class:`TelemetryPusher` per rank in either mode, and measures:

- **driver-scraped telemetry bytes per push window** — the rendezvous
  store's server-side ingest accounting (``_Store.put_bytes``) over the
  ``debugz`` + ``telemetry`` scopes: ~64 per-rank snapshots/window
  direct vs ~8 merged host frames/window with leader aggregation. The
  committed claim (``benchmarks/r13_telemetry_scaling.json``) gates
  ≥4x reduction.
- **rollup equivalence** — ``/statusz`` covers the same 64 ranks in
  both modes, and in leader mode the merged
  ``hvt_ctrl_tx_bytes_total`` equals the per-rank compact-record sum
  exactly (counters sum-identical; the merge algebra on real data).
- **/statusz latency** (GET p50) and **clean-gang alerts** (the
  health-rule false-positive pin at 64 ranks).
- **hvt_top round-trip** — ``python -m horovod_tpu.tools.hvt_top
  --once --json`` against the live server must return the same
  schema-valid view (the ``ci.sh --obs`` assert).

Byte metrics are workload-determined, so the reduction claim is stable
on a loaded shared box; only the latency column is noisy and ``--check``
never gates on it (BENCH_NOTES r8 methodology).

Modes:
    --smoke [--out X.json]   8 ranks / 2 hosts pair (ci.sh --obs)
    --capture [--out ...]    the full 64-rank / 8-host r13 matrix
    --check X.json           artifact schema + claims validation
Worker mode is selected internally via HVT_TS_WORKER.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import threading
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build",
                   "libhvt_core.so")

SCHEMA = "hvt-telemetry-scale-r1"
MEASURED_SCOPES = ("debugz", "telemetry")


def _stub_package():
    """Register a bare ``horovod_tpu`` package root so submodule
    imports (``horovod_tpu.metrics.telemetry``,
    ``horovod_tpu.runner.http_server``) work WITHOUT executing the real
    package ``__init__`` — which imports jax, and 64 workers importing
    jax is exactly the weight this harness exists to avoid."""
    if "horovod_tpu" not in sys.modules:
        pkg = types.ModuleType("horovod_tpu")
        pkg.__path__ = [os.path.join(REPO, "horovod_tpu")]
        sys.modules["horovod_tpu"] = pkg
    if REPO not in sys.path:
        sys.path.insert(0, REPO)


def mini_diagnostics(eng):
    """``hvt_diagnostics`` over the MiniEngine's ctypes handle — the
    same JSON ``hvt.diagnostics()`` returns, without importing the
    numpy-backed bridge."""
    import ctypes

    lib = eng.lib
    lib.hvt_diagnostics.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.hvt_diagnostics.restype = ctypes.c_int
    n = int(lib.hvt_diagnostics(None, 0))
    buf = ctypes.create_string_buffer(n + 16)
    lib.hvt_diagnostics(buf, n + 16)
    try:
        return json.loads(buf.value.decode("utf-8", "replace"))
    except ValueError:
        return {"engine": {"running": True}}


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def make_snapshot_fn(eng, rank, telemetry_mod):
    def snapshot():
        diag = mini_diagnostics(eng)
        diag["process_rank"] = rank
        return telemetry_mod.build_snapshot(
            rank, telemetry_mod.host_name(), diag, eng.stats())
    return snapshot


def _worker():
    _stub_package()
    from benchmarks.ctrl_plane_scaling import MiniEngine
    from horovod_tpu.metrics import telemetry as T

    spec = json.loads(os.environ["HVT_TS_SPEC"])
    rank = int(os.environ["HVT_TS_RANK"])
    size = int(os.environ["HVT_TS_SIZE"])
    port = int(os.environ["HVT_TS_PORT"])
    kv = os.environ["HVT_TS_KV"]
    debug = os.environ.get("HVT_TS_DEBUG")

    def trace(msg):
        if debug:
            print(f"[ts r{rank}] {msg}", file=sys.stderr, flush=True)

    eng = MiniEngine()
    eng.init(rank, size, port=port, cycle_ms=spec.get("cycle_ms", 2))
    trace("engine up")
    numel = spec.get("numel", 64)
    values = [float(rank + 1)] * numel

    def barrier(tag):
        out = eng.allreduce(f"sync.{tag}", [1.0])
        assert int(out[0]) == size, (tag, out)
        trace(f"barrier {tag}")

    stop = threading.Event()
    pusher = T.TelemetryPusher(
        kv, rank, make_snapshot_fn(eng, rank, T), stop,
        period_sec=spec["interval_sec"])

    barrier("init")

    def loop():
        while True:
            pusher.step()
            if stop.wait(T.jittered(pusher.period_sec)):
                return

    th = threading.Thread(target=loop, daemon=True)
    th.start()

    # work phase: a light steady-state collective trickle — enough to
    # keep counters moving and negotiations real without saturating the
    # shared box (the telemetry plane, not the data plane, is under
    # test; ctrl_plane_scaling owns the data-plane load story).
    # DETERMINISTIC step count, never wall-clock bounded: with a
    # time-bounded loop one rank crosses the deadline an iteration
    # before the rest, stops submitting the shared names, and the other
    # N-1 wedge inside the allreduce while it waits at the barrier — a
    # name-desync deadlock the stall inspector reports but (correctly)
    # never aborts, because control traffic keeps the progress
    # deadlines re-armed. Found live at 64 ranks.
    tensors = spec.get("tensors", 4)
    step_sleep = spec.get("step_sleep", 0.25)
    steps = spec.get("steps") or max(
        1, int(spec["work_sec"] / max(step_sleep, 0.05)))
    # submit-side straggler injection (tests): rank `straggler_rank`
    # lags `straggler_sleep_sec` before each step's submissions — the
    # slow-host shape rank 0's arrival table actually sees. (An
    # engine-level delay_ms fault alone slows the GANG in lockstep:
    # the sleep sits between negotiation and the ring transfer, and a
    # ring collective is gang-synchronous, so no announce skew ever
    # reaches the arrival table — found live writing the acceptance
    # test.)
    lag = (spec.get("straggler_sleep_sec", 0.0)
           if spec.get("straggler_rank") == rank else 0.0)
    for _ in range(steps):
        if lag:
            time.sleep(lag)
        for j in range(tensors):
            eng.allreduce(f"s.{j:03d}.grad/layer_weight", values)
        time.sleep(step_sleep)
    barrier("work")

    # deterministic final pushes: counters are static after the barrier
    # (no submissions in flight; the idle heartbeat is 30 s away), so
    # the leader's merged counters can be checked sum-identical against
    # the per-rank records of the same frame.
    stop.set()
    th.join(timeout=10)
    if pusher.role != "leader":
        pusher.step()          # member → leader, or direct → server
    barrier("final_members")
    if pusher.role == "leader":
        pusher.step()          # fold members' final snaps, publish
    barrier("final_frames")
    if rank == 0:
        try:
            from horovod_tpu.runner.http_client import put_bytes
            put_bytes(kv, "/kv/ctl/done", b"1", timeout=5)
        except Exception:
            pass
        # hold the gang until the driver finishes its final reads (the
        # done/teardown handshake) so statusz latency is measured
        # against a live store
        deadline = time.monotonic() + spec.get("teardown_wait_sec", 30)
        from horovod_tpu.runner.http_client import get_json
        while time.monotonic() < deadline:
            try:
                if get_json(kv, "/kv/ctl/exit", timeout=2,
                            retries=0) is not None:
                    break
            except Exception:
                pass
            time.sleep(0.2)
    barrier("exit")
    pusher.close()
    eng.shutdown()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

class _Slot:
    def __init__(self, hostname, rank, local_rank, local_size, size,
                 hosts):
        self.hostname = hostname
        self.rank = rank
        self.local_rank = local_rank
        self.local_size = local_size
        self.size = size
        self.cross_rank = int(hostname[1:]) if hostname[1:].isdigit() \
            else 0
        self.cross_size = hosts


def _get_json(addr, path, timeout=10):
    from horovod_tpu.runner.http_client import get_json

    return get_json(addr, path, timeout=timeout, retries=0)


def start_driver(np_, hosts):
    """RendezvousServer with /statusz, initialized with the fake
    host/slot layout. Returns (server, 'host:port')."""
    _stub_package()
    from horovod_tpu.runner.http_server import RendezvousServer

    per_host = max(1, np_ // hosts)
    slots = [_Slot(f"h{min(r // per_host, hosts - 1)}", r,
                   r % per_host, per_host, np_, hosts)
             for r in range(np_)]
    server = RendezvousServer()
    server.init(slots)
    port = server.start(0)
    return server, f"127.0.0.1:{port}"


def spawn_workers(np_, hosts, mode, spec, engine_port, kv_addr,
                  extra_env=None):
    """One featherweight worker process per rank; ranks pack
    contiguously onto `hosts` fake hosts. ``mode`` is ``direct`` or
    ``leader`` (leader = lowest rank of each host aggregates)."""
    per_host = max(1, np_ // hosts)
    procs = []
    for r in range(np_):
        host_i = min(r // per_host, hosts - 1)
        if mode == "leader":
            role = "leader" if r % per_host == 0 and r // per_host < hosts \
                else "member"
        else:
            role = "direct"
        env = dict(os.environ)
        env.update({
            "HVT_TS_WORKER": "1",
            "HVT_TS_RANK": str(r),
            "HVT_TS_SIZE": str(np_),
            "HVT_TS_PORT": str(engine_port),
            "HVT_TS_KV": kv_addr,
            "HVT_TS_SPEC": json.dumps(spec),
            "HVT_TELEMETRY_ROLE": role,
            "HVT_TOPO_HOST": f"h{host_i}",
            "HVT_HOSTNAME": "127.0.0.1",
            "HVT_CTRL_TOPOLOGY": "star",
            "HVT_CONNECT_TIMEOUT": "240",
            "HVT_LOG_LEVEL": "error",
            "PYTHONUNBUFFERED": "1",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            cwd=REPO,
            stdout=subprocess.PIPE if r == 0 else subprocess.DEVNULL,
            stderr=subprocess.PIPE if r == 0 else subprocess.DEVNULL,
            text=True))
    return procs


def check_statusz_doc(doc, np_):
    """Schema assertions shared by the artifact capture, the hvt_top
    round-trip, and tests."""
    errs = []
    if not isinstance(doc, dict):
        return ["statusz: not a JSON object"]
    if doc.get("schema") != "hvt-statusz-r1":
        errs.append(f"statusz schema {doc.get('schema')!r}")
    for key in ("ranks", "hosts", "alerts", "rates", "mode",
                "ranks_covered", "ranks_expected", "stragglers",
                "serving"):
        if key not in doc:
            errs.append(f"statusz missing {key}")
    if np_ is not None and doc.get("ranks_covered") != np_:
        errs.append(f"statusz covers {doc.get('ranks_covered')} of "
                    f"{np_} ranks")
    return errs


def _consistency(doc):
    """Leader-mode merge equivalence: the per-host merged counter must
    equal the sum of the same frame's per-rank compact records, and the
    frame rank sets must tile the covered set."""
    merged = 0.0
    compact_sum = 0.0
    covered = set()
    for h in (doc.get("hosts") or {}).values():
        fr = h.get("metrics") or {}
        fam = (fr.get("metrics") or {}).get("hvt_ctrl_tx_bytes_total") \
            or {}
        merged += sum(s.get("value", 0) for s in fam.get("samples", ()))
        covered.update(h.get("ranks") or ())
    for r, rec in (doc.get("ranks") or {}).items():
        compact_sum += (rec.get("bytes") or {}).get("ctrl_tx", 0)
    return {
        "merged_ctrl_tx": merged,
        "compact_sum_ctrl_tx": compact_sum,
        "identical": abs(merged - compact_sum) < 0.5,
        "frame_ranks": len(covered),
    }


def run_config(np_, hosts, mode, spec, port, timeout=600,
               extra_env=None, hvt_top_probe=False):
    server, kv_addr = start_driver(np_, hosts)
    procs = []
    result = {"np": np_, "hosts": hosts, "mode": mode,
              "interval_sec": spec["interval_sec"]}
    try:
        procs = spawn_workers(np_, hosts, mode, spec, port, kv_addr,
                              extra_env=extra_env)
        deadline = time.monotonic() + timeout

        def check_rank0_alive():
            if procs and procs[0].poll() is not None:
                out, err = procs[0].communicate(timeout=5)
                raise RuntimeError(
                    f"rank 0 exited rc={procs[0].returncode} "
                    f"mid-run:\n{out}\n{err}")

        # readiness: every rank visible in the rollup
        while True:
            check_rank0_alive()
            doc = server.statusz_snapshot()
            if doc.get("ranks_covered", 0) >= np_:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"gang never became visible: "
                    f"{doc.get('ranks_covered')}/{np_} ranks")
            time.sleep(0.3)

        # measurement window: ingest bytes over N push windows
        windows = spec.get("measure_windows", 3)
        w_sec = windows * spec["interval_sec"]
        i0 = server.store.ingest_stats()
        t0 = time.monotonic()
        lat_ms = []
        alerts_seen = []
        while time.monotonic() - t0 < w_sec:
            g0 = time.monotonic()
            doc = _get_json(kv_addr, "/statusz")
            lat_ms.append((time.monotonic() - g0) * 1e3)
            alerts_seen.extend(a.get("rule") for a in
                               doc.get("alerts") or ())
            time.sleep(max(0.2, spec["interval_sec"] / 3))
        elapsed = time.monotonic() - t0
        i1 = server.store.ingest_stats()
        bytes_total = sum(
            i1["put_bytes"].get(s, 0) - i0["put_bytes"].get(s, 0)
            for s in MEASURED_SCOPES)
        puts_total = sum(
            i1["put_count"].get(s, 0) - i0["put_count"].get(s, 0)
            for s in MEASURED_SCOPES)
        per_window = bytes_total * spec["interval_sec"] / elapsed
        result.update({
            "measure_sec": round(elapsed, 2),
            "ingest_bytes": bytes_total,
            "ingest_puts": puts_total,
            "bytes_per_window": round(per_window, 1),
            "puts_per_window": round(
                puts_total * spec["interval_sec"] / elapsed, 1),
            "statusz_get_ms_p50": round(statistics.median(lat_ms), 2),
            "alerts_during_run": sorted(set(alerts_seen)),
        })

        # wait for the gang's deterministic final frames
        while server.store.get("ctl", "done") is None:
            check_rank0_alive()
            if time.monotonic() > deadline:
                raise RuntimeError("gang never reached the done key")
            time.sleep(0.2)
        final = server.statusz_snapshot()
        errs = check_statusz_doc(final, np_)
        result["statusz_errors"] = errs
        result["ranks_covered"] = final.get("ranks_covered")
        result["statusz_mode"] = final.get("mode")
        if mode == "leader":
            result["consistency"] = _consistency(final)

        if hvt_top_probe:
            # the CI round-trip: the tool, as shipped, against the live
            # server (full package import — jax — hence driver-side and
            # once, not per worker)
            out = subprocess.run(
                [sys.executable, "-m", "horovod_tpu.tools.hvt_top",
                 "--addr", kv_addr, "--once", "--json"],
                cwd=REPO, capture_output=True, text=True, timeout=300,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            try:
                top_doc = json.loads(out.stdout)
                top_errs = check_statusz_doc(top_doc, np_)
            except ValueError:
                top_errs = [f"hvt_top emitted no JSON: "
                            f"{out.stdout[:200]!r} / "
                            f"{out.stderr[-300:]!r}"]
            result["hvt_top_errors"] = top_errs
        server.store.put("ctl", "exit", b"1")
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
    return result


def capture(out_path, smoke=False):
    from benchmarks.ctrl_plane_scaling import _next_port

    if smoke:
        np_, hosts = 8, 2
        spec = {"interval_sec": 0.8, "work_sec": 14.0, "tensors": 2,
                "numel": 32, "step_sleep": 0.3, "measure_windows": 3,
                "cycle_ms": 2}
    else:
        np_, hosts = 64, 8
        spec = {"interval_sec": 1.25, "work_sec": 30.0, "tensors": 2,
                "numel": 32, "step_sleep": 0.5, "measure_windows": 4,
                "cycle_ms": 2}
    # loaded-1-core-box allowance: a push delayed by CPU contention
    # must read as late, not dead (the committed false-positive pin is
    # "no alerts on a clean gang", with the stale threshold at 6
    # intervals instead of the production default 3)
    extra_env = {"HVT_HEALTH_STALE_INTERVALS": "6",
                 "HVT_KV_TTL_SEC": "300"}
    os.environ.update(extra_env)
    record = {"schema": SCHEMA, "mode": "smoke" if smoke else "full",
              "lib": os.path.relpath(LIB, REPO),
              "spec": spec, "configs": [], "claims": {}}
    results = {}
    for mode in ("direct", "leader"):
        t0 = time.monotonic()
        res = run_config(np_, hosts, mode, spec, _next_port(),
                         extra_env=extra_env,
                         hvt_top_probe=(mode == "leader"))
        res["total_sec"] = round(time.monotonic() - t0, 1)
        results[mode] = res
        record["configs"].append(res)
        print(json.dumps({k: res.get(k) for k in
                          ("mode", "bytes_per_window",
                           "puts_per_window", "statusz_get_ms_p50",
                           "ranks_covered", "total_sec")}), flush=True)

    d, l = results["direct"], results["leader"]
    cons = l.get("consistency") or {}
    record["claims"] = {
        "ranks": np_, "hosts": hosts,
        "scrape_bytes_per_window_direct": d["bytes_per_window"],
        "scrape_bytes_per_window_leader": l["bytes_per_window"],
        "scrape_puts_per_window_direct": d["puts_per_window"],
        "scrape_puts_per_window_leader": l["puts_per_window"],
        "reduction_x": round(
            d["bytes_per_window"] / max(l["bytes_per_window"], 1), 2),
        "statusz_get_ms_p50": l["statusz_get_ms_p50"],
        "ranks_covered_direct": d["ranks_covered"],
        "ranks_covered_leader": l["ranks_covered"],
        "counter_sum_identical": bool(cons.get("identical")),
        "alerts_clean": not (d["alerts_during_run"]
                             or l["alerts_during_run"]),
        "hvt_top_roundtrip": not l.get("hvt_top_errors", ["missing"]),
    }
    for res in results.values():
        if res.get("statusz_errors"):
            record["claims"]["alerts_clean"] = False
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        print(f"wrote {out_path}")
    print("claims: " + json.dumps(record["claims"]))
    return record


def check(path):
    """Artifact schema + claims validation (ci.sh --obs). The full
    artifact gates the headline ≥4x scrape-byte reduction; the smoke
    pair (2 hosts — less to aggregate) gates a looser 1.5x so the CI
    smoke still proves direction without a 64-proc spawn."""
    with open(path) as f:
        rec = json.load(f)
    errs = []
    if rec.get("schema") != SCHEMA:
        errs.append(f"schema != {SCHEMA}")
    cfgs = rec.get("configs", [])
    modes = {c.get("mode") for c in cfgs}
    if modes != {"direct", "leader"}:
        errs.append(f"configs must cover direct+leader, got {modes}")
    for c in cfgs:
        for key in ("np", "hosts", "bytes_per_window", "puts_per_window",
                    "statusz_get_ms_p50", "ranks_covered"):
            if key not in c:
                errs.append(f"config {c.get('mode')} missing {key}")
        if c.get("statusz_errors"):
            errs.append(f"{c.get('mode')}: statusz errors "
                        f"{c['statusz_errors']}")
    cl = rec.get("claims") or {}
    if not cl:
        errs.append("no claims block")
    else:
        floor = 4.0 if rec.get("mode") == "full" else 1.5
        if (cl.get("reduction_x") or 0) < floor:
            errs.append(f"reduction_x {cl.get('reduction_x')} < {floor}")
        for k in ("ranks_covered_direct", "ranks_covered_leader"):
            if cl.get(k) != cl.get("ranks"):
                errs.append(f"{k}={cl.get(k)} != ranks {cl.get('ranks')}")
        for k in ("counter_sum_identical", "alerts_clean",
                  "hvt_top_roundtrip"):
            if cl.get(k) is not True:
                errs.append(f"claim {k} is {cl.get(k)!r}, want true")
    for e in errs:
        print(f"telemetry_scaling --check: {e}", file=sys.stderr)
    if errs:
        return 1
    print(f"telemetry_scaling --check: OK ({len(cfgs)} configs, "
          f"claims: {json.dumps(cl)})")
    return 0


def main():
    if os.environ.get("HVT_TS_WORKER"):
        _worker()
        return 0
    _stub_package()
    args = sys.argv[1:]

    def argval(flag, dflt):
        if flag not in args:
            return dflt
        i = args.index(flag) + 1
        if i >= len(args):
            sys.exit(f"telemetry_scaling: {flag} requires a value")
        return args[i]

    if "--check" in args:
        return check(argval("--check", ""))
    out = argval("--out", "" if "--smoke" in args
                 else os.path.join(REPO, "benchmarks",
                                   "r13_telemetry_scaling.json"))
    capture(out, smoke="--smoke" in args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
