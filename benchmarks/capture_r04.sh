#!/bin/bash
# Round-4 capture: full armed benchmark set, sequential (one chip), one
# JSON line per run under benchmarks/r04/. Every heavy run is gated
# behind the cheap data-plane probe (benchmarks/tpu_sanity.py): the
# round-2/3 outages showed jax.devices() can answer while every
# compile/execute RPC blocks, so a device listing is not a gate.
cd "$(dirname "$0")/.." || exit 1
OUT=benchmarks/r04
mkdir -p "$OUT"

# Single-pilot rule, newest-starter-wins: disarm any earlier capture
# (and its in-flight bench) before touching the chip — two capture
# loops sharing the one chip corrupt each other's timings. A PIDFILE
# identifies the incumbent precisely; name-pattern pgrep is NOT safe
# here — it also matches launching shells and non-exec wrappers whose
# cmdline merely contains the script name (observed killing the
# launcher twice in round 4).
PIDFILE=/tmp/hvt_capture.pid
if [ -f "$PIDFILE" ]; then
  old=$(cat "$PIDFILE" 2>/dev/null)
  # identity-check the incumbent before killing: a recycled PID must
  # not take down an unrelated process tree
  if [ -n "$old" ] && [ "$old" != "$$" ] && kill -0 "$old" 2>/dev/null \
     && grep -qa "capture_r0" "/proc/$old/cmdline" 2>/dev/null; then
    pkill -TERM -P "$old" 2>/dev/null
    kill "$old" 2>/dev/null
  fi
fi
echo $$ > "$PIDFILE"
trap 'rm -f "$PIDFILE"' EXIT
# legacy generations (r03/r03b) predate the pidfile; their names can't
# match our own launch wrappers
for pid in $(pgrep -f "capture_r0[0-3]b?\.sh" | grep -vw $$); do
  pkill -TERM -P "$pid" 2>/dev/null
  kill "$pid" 2>/dev/null
done
pkill -f "timeout 2400 .*python bench\.py" 2>/dev/null
echo "=== capture_r04 started $(date -u) ===" >> "$OUT/capture.log"

sane() {
  timeout 180 python benchmarks/tpu_sanity.py >> "$OUT/capture.log" 2>&1
}

wait_sane() {
  # Probe until the data plane answers, 9-minute spacing; bounded at
  # ~11h (55 x (180s probe + 540s sleep)). tpu_sanity rc=2 is a
  # deterministic local failure (import error) — bail immediately.
  for i in $(seq 1 55); do
    sane; rc=$?
    if [ "$rc" -eq 0 ]; then return 0; fi
    if [ "$rc" -eq 2 ]; then
      echo "=== local failure (sanity rc=2), bailing $(date -u) ===" >> "$OUT/capture.log"
      exit 2
    fi
    echo "probe $i: data plane wedged/down $(date -u)" >> "$OUT/capture.log"
    sleep 540
  done
  echo "=== gave up waiting for data plane $(date -u) ===" >> "$OUT/capture.log"
  exit 1
}

run() {
  local name="$1"; shift
  wait_sane
  echo "=== $name: $* ($(date -u +%H:%M:%S)) ===" >> "$OUT/capture.log"
  # wait_sane just gated the data plane; skip bench.py's own probe loop
  HVT_SKIP_DEVICE_PROBE=1 timeout 2400 "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  echo "rc=$? $name done $(date -u +%H:%M:%S)" >> "$OUT/capture.log"
}

# Ordered by information value: headline ResNet + BN A/B, the rest of
# the reference benchmark trio + ResNet-101 (the one head-to-head
# absolute number), GPT einsum vs compiled-pallas flash across the
# measured crossover (1024/2048/4096; batch scaled for HBM fit), the
# fused chunked-CE runs, the seq-8192 flash-only point (einsum crashes
# the TPU worker there — do NOT add an einsum_8192 run), and GQA.
run resnet_tpu_bn   python bench.py
run resnet_flax_bn  python bench.py --bn-impl flax
run resnet101       python bench.py --model resnet101
run vgg16           python bench.py --model vgg16
run inception_v3    python bench.py --model inception_v3
run gpt_einsum      python bench.py --model gpt
run gpt_flash       env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --flash
run gpt_flash_2048  env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --flash --seq-len 2048 --batch-size 4
run gpt_einsum_2048 python bench.py --model gpt --seq-len 2048 --batch-size 4
run gpt_chunked_ce  python bench.py --model gpt --chunked-ce
run gpt_chunked_2x  python bench.py --model gpt --chunked-ce --batch-size 16
# long-context frontier: at 4096 flash's HBM advantage crosses over;
# at 8192 it is the only path that runs at all
run gpt_einsum_4096 python bench.py --model gpt --seq-len 4096 --batch-size 2
run gpt_flash_4096  env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --seq-len 4096 --batch-size 2 --flash
run gpt_flash_8192  env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --seq-len 8192 --batch-size 1 --flash
run gpt_gqa_4096    env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --seq-len 4096 --batch-size 2 --flash --n-kv-heads 2
echo "=== capture_r04 done $(date -u) ===" >> "$OUT/capture.log"
