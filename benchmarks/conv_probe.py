#!/usr/bin/env python
"""Per-shape convolution roofline probe (ResNet-50 MFU investigation).

The matmul calibration (bench.py) gives the rig's MXU ceiling; this
probe measures what fraction of that ceiling each ResNet-50 conv SHAPE
reaches, fwd-only. The step-level MFU (0.426 in r4.3) is a blend —
attribution needs per-shape rates: if the 3-channel stem runs at a few
TFLOP/s while the 3x3 body convs run near the matmul ceiling, the stem
is the lever (→ --conv0-s2d); if the small-spatial deep convs lag, the
ceiling story is HBM/arithmetic-intensity instead.

Protocol: K independent convs per timed block (stacked inputs walked by
lax.scan, means accumulated into the carry so nothing is dead-code
eliminated), forced scalar readback (tunnel protocol, see bench.py).
Prints one JSON line per shape.
"""

import json
import sys
import time

K = 4  # independent convs per timed block
REPS = 5


def probe_shape(name, in_shape, w_shape, strides, padding):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(K, *in_shape), jnp.bfloat16)
    w = jnp.asarray(rng.randn(*w_shape), jnp.bfloat16)

    def body(acc, x):
        y = lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return acc + jnp.mean(y.astype(jnp.float32)), None

    @jax.jit
    def block(xs, w):
        acc, _ = lax.scan(body, jnp.float32(0), xs)
        return acc

    out = lax.conv_general_dilated(
        jnp.zeros(in_shape, jnp.bfloat16), w, window_strides=strides,
        padding=padding, dimension_numbers=("NHWC", "HWIO", "NHWC"))
    b, ho, wo, co = out.shape
    kh, kw, ci, _ = w_shape
    flops = 2.0 * b * ho * wo * co * kh * kw * ci

    float(block(xs, w))  # compile + settle
    rates = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(block(xs, w))  # forced readback
        dt = time.perf_counter() - t0
        tf = K * flops / dt / 1e12
        if tf < 1000.0:
            rates.append(tf)
    med = float(np.median(rates)) if rates else None
    rec = {"probe": "conv", "name": name, "in": list(in_shape),
           "w": list(w_shape), "strides": list(strides),
           "gflop": round(flops / 1e9, 2),
           "tflops_median": round(med, 2) if med else None,
           "tflops_all": [round(r, 1) for r in rates]}
    print(json.dumps(rec))
    sys.stdout.flush()
    return rec


def main():
    import jax

    platform = jax.devices()[0].platform
    small = platform == "cpu"
    bs = 4 if small else 128
    res = 32 if small else 224
    r2, r4, r8, r32 = res // 2, res // 4, res // 8, res // 32

    shapes = [
        # the 3-input-channel stem, standard vs space-to-depth form
        ("stem_7x7_s2", (bs, res, res, 3), (7, 7, 3, 64), (2, 2),
         ((3, 3), (3, 3))),
        ("stem_s2d_4x4", (bs, r2, r2, 12), (4, 4, 12, 64), (1, 1),
         ((2, 1), (2, 1))),
        # body convs, one per stage (stage-1 spatial = res/4)
        ("s1_1x1_64", (bs, r4, r4, 64), (1, 1, 64, 64), (1, 1),
         ((0, 0), (0, 0))),
        ("s1_1x1_expand", (bs, r4, r4, 64), (1, 1, 64, 256), (1, 1),
         ((0, 0), (0, 0))),
        ("s1_3x3_64", (bs, r4, r4, 64), (3, 3, 64, 64), (1, 1),
         ((1, 1), (1, 1))),
        ("s2_3x3_128", (bs, r8, r8, 128), (3, 3, 128, 128), (1, 1),
         ((1, 1), (1, 1))),
        ("s3_3x3_256", (bs, r8 // 2, r8 // 2, 256), (3, 3, 256, 256),
         (1, 1), ((1, 1), (1, 1))),
        ("s4_3x3_512", (bs, r32, r32, 512), (3, 3, 512, 512), (1, 1),
         ((1, 1), (1, 1))),
    ]
    recs = [probe_shape(*s) for s in shapes]
    stem = next(r for r in recs if r["name"] == "stem_7x7_s2")
    s2d = next(r for r in recs if r["name"] == "stem_s2d_4x4")
    # The two stems produce the SAME outputs but execute different FLOP
    # counts (s2d's zero-padded taps: 4*4*12=192 vs 7*7*3=147 MACs per
    # output), so the honest comparison is wall-time per block, not
    # TFLOP/s: time = gflop / tflops.
    speedup = None
    if stem["tflops_median"] and s2d["tflops_median"]:
        t_std = stem["gflop"] / stem["tflops_median"]
        t_s2d = s2d["gflop"] / s2d["tflops_median"]
        speedup = round(t_std / t_s2d, 2)
    print(json.dumps({"probe": "conv_summary", "platform": platform,
                      "stem_s2d_time_speedup": speedup}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
