#!/bin/bash
# Round-3 TPU capture: waits for the axon tunnel, then immediately runs
# the full benchmark set (VERDICT r2 #1-3) and appends everything to the
# log. Designed to run detached so no human latency sits between tunnel
# recovery and capture — the round-2 outage ate the capture window.
LOG=${1:-/tmp/r03_capture.log}
cd "$(dirname "$0")/.." || exit 1
echo "=== capture_r03 started $(date -u) ===" >> "$LOG"
for i in $(seq 1 60); do
  if timeout 120 python -c "import jax; print(jax.devices())" >> "$LOG" 2>&1; then
    echo "=== TUNNEL UP $(date -u) — capturing ===" >> "$LOG"
    break
  fi
  echo "capture probe $i: tunnel down $(date -u)" >> "$LOG"
  if [ "$i" = 60 ]; then echo "=== gave up ===" >> "$LOG"; exit 1; fi
  sleep 540
done
run() {
  echo "--- $* ($(date -u)) ---" >> "$LOG"
  timeout 2400 "$@" >> "$LOG" 2>&1
  echo "--- rc=$? ---" >> "$LOG"
}
# 1. ResNet-50, new TpuBatchNorm (the MFU>=0.5 attempt)
run python bench.py --no-scaling
# 2. A/B: stock flax BN (the round-2 0.394 configuration)
run python bench.py --no-scaling --bn-impl flax
# 3. GPT einsum baseline
run python bench.py --model gpt --no-scaling
# 4. GPT with the COMPILED pallas flash kernel (first compiled run on axon)
HVT_FLASH_INTERPRET=0 run env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --no-scaling --flash
# 5. flash at longer context where the win should grow
run env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --no-scaling --flash --seq-len 2048 --batch-size 4
run python bench.py --model gpt --no-scaling --seq-len 2048 --batch-size 4
# 6. chunked fused CE: logits never materialized -> room for bigger batch
run python bench.py --model gpt --no-scaling --chunked-ce
run python bench.py --model gpt --no-scaling --chunked-ce --batch-size 16
echo "=== capture_r03 done $(date -u) ===" >> "$LOG"
