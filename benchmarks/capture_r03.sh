#!/bin/bash
# Superseded by capture_r03b.sh (data-plane-gated capture): the v1 gate
# — jax.devices() answering — proved insufficient on 2026-07-31, when
# the control plane listed the chip while every compile/execute RPC
# blocked forever (BENCH_NOTES.md). v2 gates each run on an end-to-end
# tiny matmul instead. This shim keeps old invocations working; the
# benchmark run list lives in ONE place (capture_r03b.sh).
exec bash "$(dirname "$0")/capture_r03b.sh" "$@"
