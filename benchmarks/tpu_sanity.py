#!/usr/bin/env python
"""Data-plane sanity probe for the tunneled TPU.

``jax.devices()`` answering does NOT mean the chip can run work: during
the round-2/3 outages the control plane kept listing the device while
every compile/execute RPC blocked forever. This probe jits one tiny
matmul end-to-end (compile + execute + readback) and exits 0 only if the
result comes back. Run it under ``timeout`` — a wedged tunnel blocks
here, not 40 minutes into a benchmark.
"""

import sys
import time


def main():
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp

    d = jax.devices()
    t1 = time.perf_counter()
    if d[0].platform == "cpu":
        # Silent CPU fallback (TPU plugin failed fast): the matmul would
        # succeed instantly and open the gate onto a dead TPU.
        print(f"sanity FAIL: backend fell back to cpu ({d})")
        return 1

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = jnp.ones((256, 256), jnp.bfloat16)
    v = float(f(x))
    t2 = time.perf_counter()
    print(f"sanity ok: {d[0].platform} devices={len(d)} "
          f"init {t1 - t0:.1f}s exec {t2 - t1:.1f}s value {v:.0f}")
    return 0


if __name__ == "__main__":
    # Exit codes: 0 = data plane sane; 1 = wedged/fallback (retryable —
    # the tunnel may recover); 2 = local deterministic failure (import
    # error, broken env — retrying cannot help, callers should bail).
    # Only import/syntax errors are deterministic: a flapping tunnel can
    # surface as OSError subclasses (ConnectionReset/Refused, Timeout)
    # during jax init, and those are exactly the retryable class.
    try:
        sys.exit(main())
    except (ImportError, SyntaxError) as e:
        print(f"sanity LOCAL-FAIL: {type(e).__name__}: {e}")
        sys.exit(2)
