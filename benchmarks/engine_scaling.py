#!/usr/bin/env python
"""Eager data-plane scaling curve: allreduce latency/bandwidth vs process
count, payload size, backend (shm vs ring), and cache state.

The eager-plane analog of the reference's published scaling tables
(``/root/reference/docs/benchmarks.rst:13-14`` — its whole pitch is
fusion/cache behavior at scale). Results are committed to
docs/performance.md; ``tests/test_engine_scaling.py`` pins the shm ≥ ring
invariant at 16 MB.

Run as a driver (spawns launcher jobs over the sweep):
    python benchmarks/engine_scaling.py [--quick]
Worker mode is selected internally via HVT_BENCH_WORKER.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

SIZES = {"4KB": 1 << 10 >> 2 << 2, "1MB": 1 << 18, "16MB": 1 << 22,
         "64MB": 1 << 24}  # float32 element counts


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvt

    hvt.init()
    r = hvt.rank()
    sizes = json.loads(os.environ["HVT_BENCH_SIZES"])
    iters = int(os.environ.get("HVT_BENCH_ITERS", "8"))
    out = {}
    for label, numel in sizes.items():
        x = np.arange(numel, dtype=np.float32) % 1001 + r

        # cold: first submission of each name pays a full negotiation
        # round trip (no response-cache entry)
        cold = []
        for i in range(3):
            t0 = time.perf_counter()
            hvt.allreduce(x, op=hvt.Sum, name=f"cold.{label}.{i}")
            cold.append(time.perf_counter() - t0)

        # hit: repeated name rides the position-synced cache fast path
        hvt.allreduce(x, op=hvt.Sum, name=f"hot.{label}")  # prime
        hot = []
        for _ in range(iters):
            t0 = time.perf_counter()
            res = hvt.allreduce(x, op=hvt.Sum, name=f"hot.{label}")
            hot.append(time.perf_counter() - t0)
        res = np.asarray(res)
        expected = sum(np.arange(numel, dtype=np.float32) % 1001 + i
                       for i in range(hvt.size()))
        np.testing.assert_allclose(res, expected)
        out[label] = {"cold_ms": round(float(np.median(cold)) * 1e3, 2),
                      "hit_ms": round(float(np.median(hot)) * 1e3, 2)}
    if r == 0:
        print("HVT_BENCH_RESULT " + json.dumps(out), flush=True)


def run_job(np_, shm, sizes, iters, repo):
    env = dict(os.environ)
    env.update({
        "HVT_BENCH_WORKER": "1",
        "HVT_BENCH_SIZES": json.dumps(sizes),
        "HVT_BENCH_ITERS": str(iters),
        "HVT_SHM_ALLREDUCE": "1" if shm else "0",
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np",
         str(np_), sys.executable, os.path.abspath(__file__)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(f"np={np_} shm={shm} failed:\n"
                           f"{proc.stdout}\n{proc.stderr}")
    for line in proc.stdout.splitlines():
        # launcher prefixes worker output with "[rank] "
        if "HVT_BENCH_RESULT" in line:
            return json.loads(line.split("HVT_BENCH_RESULT ", 1)[1])
    raise RuntimeError(f"no result line:\n{proc.stdout}")


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    quick = "--quick" in sys.argv
    sizes = ({"4KB": 1024, "16MB": 1 << 22} if quick else
             {k: v for k, v in SIZES.items()})
    nps = [2, 4] if quick else [1, 2, 4, 8]
    iters = 4 if quick else 8
    rows = []
    for np_ in nps:
        for shm in ([True] if np_ == 1 else [True, False]):
            res = run_job(np_, shm, sizes, iters, repo)
            for label, v in res.items():
                mb = SIZES[label] * 4 / (1 << 20)
                hit_bw = mb / (v["hit_ms"] / 1e3) if v["hit_ms"] else 0
                rows.append({"np": np_,
                             "backend": "shm" if shm else "ring",
                             "size": label, **v,
                             "hit_MBps": round(hit_bw, 1)})
                print(json.dumps(rows[-1]), flush=True)
    print("\n| np | backend | size | cold ms | hit ms | hit MB/s |")
    print("|---|---|---|---|---|---|")
    for row in rows:
        print(f"| {row['np']} | {row['backend']} | {row['size']} | "
              f"{row['cold_ms']} | {row['hit_ms']} | {row['hit_MBps']} |")


if __name__ == "__main__":
    if os.environ.get("HVT_BENCH_WORKER"):
        worker()
    else:
        main()
