#!/usr/bin/env python
"""Eager data-plane scaling curve: allreduce latency/bandwidth vs process
count, payload size, backend (shm vs ring), and cache state.

The eager-plane analog of the reference's published scaling tables
(``/root/reference/docs/benchmarks.rst:13-14`` — its whole pitch is
fusion/cache behavior at scale). Results are committed to
docs/performance.md; ``tests/test_engine_scaling.py`` pins the shm ≥ ring
invariant at 16 MB.

Run as a driver (spawns launcher jobs over the sweep):
    python benchmarks/engine_scaling.py [--quick]
Worker mode is selected internally via HVT_BENCH_WORKER.

Data-plane size sweep (PR 3 artifact): p50/p99 per-op latency + GB/s
from 4 KB to 64 MB on the TCP ring (HVT_SHM_ALLREDUCE=0), A/B'ing the
event-driven pipelined plane against the legacy sleep-loop serialized
ring (HVT_EVENT_DRIVEN=0 + HVT_RING_PIPELINE=0) and the wire codecs:
    python benchmarks/engine_scaling.py --sweep [--np 2] [--iters 30]
                                        [--out sweep.json] [--quick]

Wire-codec sweep (PR 9 artifact, ``ci.sh --codec``): every registry
codec on a faked 2-host pair (inter-host link class), recording exact
per-codec wire byte counters, relative error vs the exact sum, and the
``bench.py --codec-ab`` convergence probe; ``--check`` validates an
artifact (fresh or committed) against the schema + the committed
claims (int8 ≥3.5x inter-host wire-byte reduction, per-codec relerr
bounds, EF recovering the int8 convergence bias):
    python benchmarks/engine_scaling.py --codec [--quick] [--out r.json]
    python benchmarks/engine_scaling.py --check r.json

Link-backend sweep (PR 18 artifact): transport-level full-duplex
ping-pong through the exact PumpDuplex seam the engine uses
(``hvt_transport_bench``), A/B'ing the io_uring data plane against the
poll+sendmsg TCP baseline per payload size — p50/mean latency plus the
measured syscalls-per-step column the io_uring plane exists to shrink.
``--check`` dispatches on the artifact's ``harness`` field, so the same
flag validates r09 and r18 artifacts:
    python benchmarks/engine_scaling.py --uring [--quick] [--out r.json]
    python benchmarks/engine_scaling.py --check benchmarks/r18_uring_sweep.json
``--sweep`` additionally takes ``--link-backend {tcp,io_uring,both}``
to pin (or A/B) the engine-level sweep's transport backend, and its
per-size rows carry a syscalls-per-op column from the engine's pump
counters.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

SIZES = {"4KB": 1 << 10 >> 2 << 2, "1MB": 1 << 18, "16MB": 1 << 22,
         "64MB": 1 << 24}  # float32 element counts

# --sweep element counts (float32), 4 KB → 64 MB
SWEEP_SIZES = {"4KB": 1 << 10, "64KB": 1 << 14, "1MB": 1 << 18,
               "16MB": 1 << 22, "64MB": 1 << 24}

# --sweep planes: env deltas on top of HVT_SHM_ALLREDUCE=0
SWEEP_PLANES = {
    # the rebuilt data plane, all defaults
    "event_pipelined": {},
    # the pre-PR-3 plane: unconditional cycle_ms sleep + blocking
    # serialized ring
    "sleep_serialized": {"HVT_EVENT_DRIVEN": "0", "HVT_RING_PIPELINE": "0"},
    # rebuilt plane + bf16 wire compression (fp32 allreduce only)
    "event_pipelined_bf16wire": {"HVT_WIRE_COMPRESSION": "bf16"},
    # block-scaled quantized codecs (PR 9; ~3.94x wire bytes on fp32)
    "event_pipelined_int8wire": {"HVT_WIRE_COMPRESSION": "int8"},
    "event_pipelined_fp8wire": {"HVT_WIRE_COMPRESSION": "fp8"},
}

# --codec sweep: one plane per registry codec, run on a FAKED 2-host
# layout (HVT_BENCH_FAKE_HOSTS → per-rank HVT_TOPO_HOST) with the
# EQuARX pair form, so the measured link class is inter-host — the hop
# the codecs exist to compress. relerr tolerances double as the
# artifact's documented per-codec error bounds.
CODEC_PLANES = {
    "none": {"env": "", "tol": 1e-6},
    "bf16": {"env": "none,bf16", "tol": 2e-2},
    "int8": {"env": "none,int8", "tol": 5e-2},
    "fp8": {"env": "none,fp8", "tol": 2e-1},
}

# --uring payload BYTES per direction per step (the transport bench
# moves raw bytes, not fp32 elements) and per-size step counts — 16 MB
# full-duplex steps move 32 MB each, so fewer iterations suffice for a
# stable median
URING_SIZES = {"4KB": 4096, "64KB": 65536, "1MB": 1 << 20,
               "16MB": 1 << 24}
URING_ITERS = {"4KB": 400, "64KB": 300, "1MB": 100, "16MB": 20}


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvt

    hvt.init()
    r = hvt.rank()
    sizes = json.loads(os.environ["HVT_BENCH_SIZES"])
    iters = int(os.environ.get("HVT_BENCH_ITERS", "8"))
    out = {}
    for label, numel in sizes.items():
        x = np.arange(numel, dtype=np.float32) % 1001 + r

        # cold: first submission of each name pays a full negotiation
        # round trip (no response-cache entry)
        cold = []
        for i in range(3):
            t0 = time.perf_counter()
            hvt.allreduce(x, op=hvt.Sum, name=f"cold.{label}.{i}")
            cold.append(time.perf_counter() - t0)

        # hit: repeated name rides the position-synced cache fast path
        hvt.allreduce(x, op=hvt.Sum, name=f"hot.{label}")  # prime
        hot = []
        for _ in range(iters):
            t0 = time.perf_counter()
            res = hvt.allreduce(x, op=hvt.Sum, name=f"hot.{label}")
            hot.append(time.perf_counter() - t0)
        res = np.asarray(res)
        expected = sum(np.arange(numel, dtype=np.float32) % 1001 + i
                       for i in range(hvt.size()))
        np.testing.assert_allclose(res, expected)
        out[label] = {"cold_ms": round(float(np.median(cold)) * 1e3, 2),
                      "hit_ms": round(float(np.median(hot)) * 1e3, 2)}
    if r == 0:
        print("HVT_BENCH_RESULT " + json.dumps(out), flush=True)


def sweep_worker():
    """HVT_BENCH_SWEEP mode: per-op latency samples for each size, on
    the hot (cached-name) path — the steady-state train-loop shape."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    # --codec driver fakes one host per rank so the flat ring is the
    # inter-host link class (must be set before hvt.init reads it)
    if os.environ.get("HVT_BENCH_FAKE_HOSTS"):
        os.environ["HVT_TOPO_HOST"] = \
            "h" + os.environ.get("HVT_PROCESS_ID", "0")

    import horovod_tpu as hvt

    hvt.init()
    r = hvt.rank()
    from horovod_tpu.engine import native

    sizes = json.loads(os.environ["HVT_BENCH_SIZES"])
    iters = int(os.environ.get("HVT_BENCH_ITERS", "30"))
    out = {}
    relerr = {}
    syscalls_per_op = {}
    for label, numel in sizes.items():
        x = (np.arange(numel, dtype=np.float32) % 1001) * 0.5 + r
        # small payloads: more warmup + 5x the samples — µs-scale p50s
        # on a shared box are dominated by scheduler warmup otherwise
        small = numel <= (1 << 18)
        warmup, timed = (5, iters * 5) if small else (1, iters)
        # per-size pump-syscall delta (local counters, rank 0's view):
        # poll/sendmsg/recv from the generic loop plus io_uring_enter
        # calls — the column the io_uring backend exists to shrink.
        # Includes whatever CTRL-plane chatter lands inside the window,
        # which is why it's quoted per-op, not as an absolute.
        st0 = native.engine_stats() if r == 0 else None
        for _ in range(1 + warmup):
            hvt.allreduce(x, op=hvt.Sum, name=f"sweep.{label}")
        samples = []
        for _ in range(timed):
            t0 = time.perf_counter()
            res = hvt.allreduce(x, op=hvt.Sum, name=f"sweep.{label}")
            samples.append(time.perf_counter() - t0)
        # correctness guard: a benchmark that returns garbage is not a
        # benchmark (lossy codecs → their documented tolerance; raw is
        # exact). Block-scaled codecs bound ABSOLUTE error by the block
        # scale (≈ blockmax/127 per quantization event), not each
        # element's magnitude — so the error metric is normalized by
        # the tensor's max |value| (how EQuARX-style relerr is quoted),
        # never elementwise-relative (a near-zero element next to a
        # large one would read as O(1) relerr by construction). The
        # inter token of a pair spec governs a faked-host sweep; single
        # tokens apply everywhere.
        expected = sum((np.arange(numel, dtype=np.float32) % 1001) * 0.5
                       + i for i in range(hvt.size()))
        spec = os.environ.get("HVT_WIRE_COMPRESSION", "")
        inter = spec.split(",")[-1] if spec else ""
        tol = CODEC_PLANES.get(inter, CODEC_PLANES["none"])["tol"]
        res = np.asarray(res)
        err = float(np.max(np.abs(res - expected))
                    / max(float(np.max(np.abs(expected))), 1e-9))
        if err > tol:
            raise AssertionError(
                f"{label}: max|err|/max|expected| {err:.6f} exceeds the "
                f"documented {inter or 'none'} bound {tol}")
        relerr[label] = err
        out[label] = sorted(samples)
        if r == 0:
            st1 = native.engine_stats()
            ops = 1 + warmup + timed
            delta = sum(st1.get(k, 0) - st0.get(k, 0)
                        for k in ("pump_syscalls", "uring_enters"))
            syscalls_per_op[label] = round(delta / ops, 1)
    if r == 0:
        st = native.engine_stats()
        print("HVT_BENCH_RESULT " + json.dumps(
            {"samples_s": out,
             "relerr": relerr,
             "syscalls_per_op": syscalls_per_op,
             "link_backend": st.get("link_backend", 0),
             "wire_tx_bytes": st.get("wire_tx_bytes", {}),
             "wire_tx_comp_bytes": st.get("wire_tx_comp_bytes", {}),
             "codec_tx_bytes": st.get("codec_tx_bytes", {})}),
            flush=True)


def run_sweep_job(np_, extra_env, sizes, iters, repo):
    env = dict(os.environ)
    env.update({
        "HVT_BENCH_WORKER": "1",
        "HVT_BENCH_SWEEP": "1",
        "HVT_BENCH_SIZES": json.dumps(sizes),
        "HVT_BENCH_ITERS": str(iters),
        "HVT_SHM_ALLREDUCE": "0",  # the sweep measures the TCP ring
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np",
         str(np_), sys.executable, os.path.abspath(__file__)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=2400)
    if proc.returncode != 0:
        raise RuntimeError(f"sweep np={np_} env={extra_env} failed:\n"
                           f"{proc.stdout}\n{proc.stderr}")
    for line in proc.stdout.splitlines():
        if "HVT_BENCH_RESULT" in line:
            return json.loads(line.split("HVT_BENCH_RESULT ", 1)[1])
    raise RuntimeError(f"no result line:\n{proc.stdout}")


def _pctl(sorted_s, q):
    i = min(len(sorted_s) - 1, int(round(q * (len(sorted_s) - 1))))
    return sorted_s[i]


def sweep_main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    quick = "--quick" in sys.argv

    def argval(flag, dflt):
        return (sys.argv[sys.argv.index(flag) + 1]
                if flag in sys.argv else dflt)

    np_ = int(argval("--np", "2"))
    iters = int(argval("--iters", "10" if quick else "20"))
    rounds = int(argval("--rounds", "1" if quick else "3"))
    out_path = argval("--out", "")
    sizes = ({"4KB": 1 << 10, "16MB": 1 << 22} if quick
             else dict(SWEEP_SIZES))
    planes = dict(SWEEP_PLANES)
    # --link-backend: pin every plane's transport backend, or "both" to
    # collapse the sweep into a tcp-vs-io_uring A/B of the default plane
    lb = argval("--link-backend", "")
    if lb == "both":
        planes = {"link_tcp": {"HVT_LINK_BACKEND": "tcp"},
                  "link_io_uring": {"HVT_LINK_BACKEND": "io_uring"}}
    elif lb:
        planes = {p: dict(e, HVT_LINK_BACKEND=lb)
                  for p, e in planes.items()}
    # optional: measure a pre-PR-3 libhvt_core.so (built from the seed
    # commit) through the same harness — the honest tentpole baseline,
    # since HVT_EVENT_DRIVEN/HVT_RING_PIPELINE only unwind part of it
    seed_lib = argval("--seed-lib", "")
    if seed_lib:
        planes["seed_so"] = {"HVT_CORE_LIB": seed_lib}
    record = {"np": np_, "iters": iters, "rounds": rounds,
              "transport": "tcp ring (HVT_SHM_ALLREDUCE=0)",
              "planes": {}}
    # Interleave planes round-robin: ambient machine state (CPU
    # frequency, co-tenants) drifts on minute scales, so back-to-back
    # whole-plane jobs bias the comparison; rotating jobs and pooling
    # samples spreads the drift across every plane alike.
    pooled = {p: {label: [] for label in sizes} for p in planes}
    by_round = {p: {label: [] for label in sizes} for p in planes}
    sysc = {p: {label: [] for label in sizes} for p in planes}
    wire = {p: {} for p in planes}
    for rnd in range(rounds):
        for plane, extra in planes.items():
            res = run_sweep_job(np_, extra, sizes, iters, repo)
            for label, samples in res["samples_s"].items():
                pooled[plane][label].extend(samples)
                by_round[plane][label].append(
                    round(_pctl(sorted(samples), 0.50) * 1e3, 3))
                spo = res.get("syscalls_per_op", {}).get(label)
                if spo is not None:
                    sysc[plane][label].append(spo)
            wire[plane] = {
                "wire_tx_bytes": res.get("wire_tx_bytes", {}),
                "wire_tx_comp_bytes": res.get("wire_tx_comp_bytes", {}),
            }
            print(f"round {rnd + 1}/{rounds} plane {plane} done",
                  flush=True)
    for plane, extra in planes.items():
        rows = {}
        for label, samples in pooled[plane].items():
            samples = sorted(samples)
            mb = sizes[label] * 4 / (1 << 20)
            p50, p99 = _pctl(samples, 0.50), _pctl(samples, 0.99)
            rounds_p50 = by_round[plane][label]
            rows[label] = {
                "p50_ms": round(p50 * 1e3, 3),
                "p99_ms": round(p99 * 1e3, 3),
                "gbps": round(mb / 1024 / p50, 3) if p50 else 0.0,
                # per-round medians + their min: the host is a shared
                # box whose spare CPU drifts on minute scales, so the
                # quietest round is the least-interference estimate
                # (pooled p50 includes whatever co-tenant noise each
                # round absorbed)
                "round_p50_ms": rounds_p50,
                "best_p50_ms": min(rounds_p50),
            }
            if sysc[plane][label]:
                rows[label]["syscalls_per_op"] = sorted(
                    sysc[plane][label])[len(sysc[plane][label]) // 2]
            print(json.dumps({"plane": plane, "size": label,
                              **rows[label]}), flush=True)
        record["planes"][plane] = {"env": extra, "sizes": rows,
                                   **wire[plane]}
    print("\n| plane | size | p50 ms | p99 ms | GB/s | syscalls/op |")
    print("|---|---|---|---|---|---|")
    for plane, pr in record["planes"].items():
        for label, row in pr["sizes"].items():
            print(f"| {plane} | {label} | {row['p50_ms']} | "
                  f"{row['p99_ms']} | {row['gbps']} | "
                  f"{row.get('syscalls_per_op', '-')} |")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        print(f"wrote {out_path}")
    return record


def codec_main():
    """--codec: the PR 9 wire-codec sweep. Every registry codec over a
    faked 2-host pair (inter-host link class), exact per-codec byte
    counters + relerr + p50s, plus the bench.py --codec-ab convergence
    probe; writes the r09 artifact schema."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    quick = "--quick" in sys.argv

    def argval(flag, dflt):
        return (sys.argv[sys.argv.index(flag) + 1]
                if flag in sys.argv else dflt)

    np_ = 2  # one rank per faked host: every ring hop is inter-host
    iters = int(argval("--iters", "6" if quick else "20"))
    out_path = argval("--out", "")
    sizes = ({"64KB": 1 << 14} if quick
             else {"64KB": 1 << 14, "1MB": 1 << 18, "4MB": 1 << 20})
    record = {"harness": "r09 codec sweep r1", "np": np_, "iters": iters,
              "fake_hosts": True, "link_class": "inter",
              "transport": "tcp ring (HVT_SHM_ALLREDUCE=0, "
                           "HVT_TOPO_HOST per rank)",
              "sizes_elems": dict(sizes), "planes": {}}
    for codec, cfg in CODEC_PLANES.items():
        # EF off for the sweep planes: the relerr column documents the
        # PURE per-shot codec bound. With EF on, repeated same-name
        # allreduces oscillate around the true value by up to ~2
        # quantization steps per iteration (unbiased across time, by
        # design) — the convergence A/B below is where EF is measured.
        # the codec spec is pinned even for the raw plane ("" parses as
        # raw) — an ambient HVT_WIRE_COMPRESSION in the caller's shell
        # must not leak into the baseline and flatten every
        # wire_reduction toward 1.0
        extra = {"HVT_BENCH_FAKE_HOSTS": "1", "HVT_ERROR_FEEDBACK": "0",
                 "HVT_WIRE_COMPRESSION": cfg["env"]}
        res = run_sweep_job(np_, extra, sizes, iters, repo)
        rows = {}
        for label, samples in res["samples_s"].items():
            samples = sorted(samples)
            rows[label] = {
                "p50_ms": round(_pctl(samples, 0.50) * 1e3, 3),
                "p99_ms": round(_pctl(samples, 0.99) * 1e3, 3),
                "relerr": res["relerr"][label],
            }
        record["planes"][codec] = {
            "env": cfg["env"] or "(unset)",
            "tol": cfg["tol"],
            "sizes": rows,
            # EXACT counters off the engine stats block, rank 0's view
            # of an identical op sequence per plane — the byte-reduction
            # claim divides these, never estimates
            "wire_tx_bytes_allreduce":
                res["wire_tx_bytes"].get("allreduce", 0),
            "codec_tx_bytes_allreduce":
                {c: ops.get("allreduce", 0)
                 for c, ops in res.get("codec_tx_bytes", {}).items()},
        }
        print(f"codec plane {codec} done "
              f"(tx={record['planes'][codec]['wire_tx_bytes_allreduce']})",
              flush=True)
    raw = record["planes"]["none"]["wire_tx_bytes_allreduce"]
    record["claims"] = {
        codec: {
            "wire_reduction": round(
                raw / p["wire_tx_bytes_allreduce"], 3),
            "max_relerr": max(r["relerr"] for r in p["sizes"].values()),
        }
        for codec, p in record["planes"].items() if codec != "none"
    }
    # convergence A/B (bench.py --codec-ab): int8+EF vs fp32 vs int8−EF
    import subprocess
    # the A/B is deterministic (fixed seeds/problem) and ~seconds per
    # config, so --quick never shortens it: at 80 steps the EF arm has
    # not yet closed to within the 10%-of-bias gate and --check would
    # fail deterministically
    # budget: bench.py allows each of its 3 launch configs 600 s, so
    # the wrapper must not undercut the aggregate on a co-tenant-loaded
    # box — a mid-config TimeoutExpired here would eat the per-config
    # diagnostics bench.py prints on its own failures
    ab = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--codec-ab"],
        cwd=repo, capture_output=True, text=True, timeout=3 * 600 + 120)
    if ab.returncode != 0:
        raise RuntimeError(f"codec-ab failed:\n{ab.stdout}\n{ab.stderr}")
    record["convergence_ab"] = json.loads(
        [ln for ln in ab.stdout.splitlines()
         if ln.startswith("{")][-1])
    print(json.dumps(record["claims"], indent=1))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        print(f"wrote {out_path}")
    return record


def codec_check(path):
    """--check: schema + committed-claim gates for an r09 artifact.
    Gates: int8 inter-host wire-byte reduction ≥ 3.5x (exact counters),
    per-codec relerr within its documented tolerance, and the
    convergence A/B showing EF recovering ≥ 90% of the int8 bias
    (int8−EF measurably biased, int8+EF within noise of fp32)."""
    with open(path) as f:
        rec = json.load(f)
    errs = []
    for key in ("harness", "np", "planes", "claims", "convergence_ab"):
        if key not in rec:
            errs.append(f"missing key {key!r}")
    planes = rec.get("planes", {})
    for codec in ("none", "bf16", "int8", "fp8"):
        if codec not in planes:
            errs.append(f"missing plane {codec!r}")
            continue
        p = planes[codec]
        for key in ("sizes", "wire_tx_bytes_allreduce",
                    "codec_tx_bytes_allreduce"):
            if key not in p:
                errs.append(f"plane {codec}: missing {key!r}")
        if p.get("wire_tx_bytes_allreduce", 0) <= 0:
            errs.append(f"plane {codec}: no allreduce wire bytes")
    claims = rec.get("claims", {})
    int8_red = claims.get("int8", {}).get("wire_reduction", 0)
    if int8_red < 3.5:
        errs.append(f"int8 inter-host wire-byte reduction {int8_red} "
                    f"< 3.5x gate")
    for codec, claim in claims.items():
        tol = planes.get(codec, {}).get("tol", 0)
        if claim.get("max_relerr", 1) > tol:
            errs.append(f"{codec}: relerr {claim.get('max_relerr')} "
                        f"exceeds documented bound {tol}")
    ab = rec.get("convergence_ab", {})
    d_ef = ab.get("delta_int8_ef")
    d_noef = ab.get("delta_int8_noef")
    if d_ef is None or d_noef is None:
        errs.append("convergence_ab missing delta_int8_ef/noef")
    else:
        if d_noef < 2e-3:
            errs.append(f"int8−EF bias {d_noef} not measurable "
                        f"(< 2e-3) — the A/B lost its teeth")
        if not d_ef <= 0.1 * d_noef:
            errs.append(f"int8+EF delta {d_ef} not within noise of "
                        f"fp32 (> 10% of the no-EF bias {d_noef})")
    if errs:
        for e in errs:
            print(f"codec-check: {e}")
        print(f"codec-check: FAILED ({len(errs)} problem(s)) — {path}")
        return 1
    print(f"codec-check: OK — {path} (int8 reduction {int8_red}x, "
          f"EF recovers {100 * (1 - d_ef / d_noef):.1f}% of the bias)")
    return 0


def tbench_worker():
    """HVT_TBENCH_ROLE mode: one side of the transport-level ping-pong.
    Calls straight into ``hvt_transport_bench`` — no engine, no control
    plane, just the PumpDuplex seam over a fresh socket pair."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from horovod_tpu.engine import native

    role = int(os.environ["HVT_TBENCH_ROLE"])
    res = native.transport_bench(
        role, "127.0.0.1", int(os.environ["HVT_TBENCH_PORT"]),
        int(os.environ["HVT_TBENCH_PAYLOAD"]),
        int(os.environ["HVT_TBENCH_ITERS"]),
        int(os.environ["HVT_TBENCH_BACKEND"]))
    if res is None:
        print("HVT_TBENCH_FAILED", flush=True)
        sys.exit(3)
    p50_ns, mean_ns, syscalls, steps = res
    print("HVT_TBENCH_RESULT " + json.dumps(
        {"role": role, "p50_ns": p50_ns, "mean_ns": mean_ns,
         "syscalls": syscalls, "steps": steps}), flush=True)


def run_tbench_cell(backend_id, payload, iters, port, repo):
    """Spawn the listener (role 0) then the dialer (role 1) for one
    (backend, payload) cell; returns role 0's result dict."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "HVT_TBENCH_PORT": str(port),
        "HVT_TBENCH_PAYLOAD": str(payload),
        "HVT_TBENCH_ITERS": str(iters),
        "HVT_TBENCH_BACKEND": str(backend_id),
    })
    procs = []
    for role in (0, 1):
        e = dict(env, HVT_TBENCH_ROLE=str(role))
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=e, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        if role == 0:
            time.sleep(0.3)  # let the listener bind before the dial
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        if p.returncode != 0:
            for q in procs:
                q.kill()
            raise RuntimeError(
                f"tbench backend={backend_id} payload={payload} "
                f"port={port} failed:\n{out}\n{err}")
        outs.append(out)
    for line in outs[0].splitlines():
        if line.startswith("HVT_TBENCH_RESULT "):
            return json.loads(line.split(" ", 1)[1])
    raise RuntimeError(f"no tbench result line:\n{outs[0]}")


def uring_main():
    """--uring: the PR 18 link-backend artifact. Transport-level
    full-duplex ping-pong (hvt_transport_bench) per backend x payload,
    medians over interleaved repetitions; claims are the per-size
    syscall-reduction ratios plus latency/bandwidth parity bands —
    the honest shape of the win on a host where turnaround latency is
    scheduler-bound (see docs/performance.md §transport backends)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from horovod_tpu.engine import native

    quick = "--quick" in sys.argv

    def argval(flag, dflt):
        return (sys.argv[sys.argv.index(flag) + 1]
                if flag in sys.argv else dflt)

    out_path = argval("--out", "")
    reps = int(argval("--reps", "3" if quick else "5"))
    sizes = ({"4KB": URING_SIZES["4KB"], "16MB": URING_SIZES["16MB"]}
             if quick else dict(URING_SIZES))
    supported = native.uring_supported()
    backends = [("tcp", 0)] + ([("io_uring", 1)] if supported else [])
    if not supported:
        print("uring: kernel probe failed — measuring tcp plane only",
              flush=True)
    record = {"harness": "r18 uring sweep r1", "reps": reps,
              "host_cpus": os.cpu_count(),
              "uring_supported": supported,
              "payload_bytes": dict(sizes), "planes": {}}
    cells = {name: {label: [] for label in sizes} for name, _ in backends}
    port_base = 19000 + (os.getpid() % 400)
    # interleave backends within each rep (same rationale as --sweep:
    # machine-state drift must hit both planes alike)
    for rep in range(reps):
        for name, bid in backends:
            for j, (label, payload) in enumerate(sizes.items()):
                port = port_base + rep * 37 + bid * 13 + j
                it = URING_ITERS[label] // (4 if quick else 1)
                res = run_tbench_cell(bid, payload, it, port, repo)
                cells[name][label].append(res)
            print(f"rep {rep + 1}/{reps} plane {name} done", flush=True)

    def med(vals):
        vals = sorted(vals)
        return vals[len(vals) // 2]

    for name, _ in backends:
        rows = {}
        for label, rs in cells[name].items():
            p50_ns = med([r["p50_ns"] for r in rs])
            spo = med([r["syscalls"] / max(r["steps"], 1) for r in rs])
            # full-duplex: each step moves payload bytes BOTH ways
            gbps = (2 * sizes[label] / (p50_ns / 1e9) / 1e9
                    if p50_ns else 0.0)
            rows[label] = {
                "p50_us": round(p50_ns / 1e3, 2),
                "mean_us": round(
                    med([r["mean_ns"] for r in rs]) / 1e3, 2),
                "syscalls_per_step": round(spo, 2),
                "gbps": round(gbps, 3),
            }
            print(json.dumps({"plane": name, "size": label,
                              **rows[label]}), flush=True)
        record["planes"][name] = {"sizes": rows}
    if supported:
        t, u = (record["planes"]["tcp"]["sizes"],
                record["planes"]["io_uring"]["sizes"])
        record["claims"] = {
            label: {
                "syscall_reduction": round(
                    t[label]["syscalls_per_step"]
                    / max(u[label]["syscalls_per_step"], 1e-9), 2),
                "p50_ratio": round(
                    t[label]["p50_us"]
                    / max(u[label]["p50_us"], 1e-9), 2),
                "bw_ratio": round(
                    u[label]["gbps"]
                    / max(t[label]["gbps"], 1e-9), 2),
            }
            for label in sizes
        }
        print(json.dumps(record["claims"], indent=1))
    print("\n| plane | size | p50 us | syscalls/step | GB/s |")
    print("|---|---|---|---|---|")
    for name, pr in record["planes"].items():
        for label, row in pr["sizes"].items():
            print(f"| {name} | {label} | {row['p50_us']} | "
                  f"{row['syscalls_per_step']} | {row['gbps']} |")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        print(f"wrote {out_path}")
    return record


def uring_check(path):
    """--check (r18 artifacts): schema + committed-claim gates. The
    gates pin what the io_uring plane actually delivers on this class
    of host — fewer kernel crossings at latency/bandwidth parity:
    syscalls/step reduction >= 1.25x at 4KB and >= 1.7x at 16MB, p50
    within 2x of tcp everywhere, 16MB bandwidth within [0.5x, 2.5x].
    (Turnaround latency itself is scheduler-bound on shared/1-CPU hosts
    — two context switches per step dwarf the syscall cost — so a
    latency-multiple gate would pin noise, not the transport.)"""
    with open(path) as f:
        rec = json.load(f)
    errs = []
    for key in ("harness", "planes", "payload_bytes", "uring_supported",
                "host_cpus"):
        if key not in rec:
            errs.append(f"missing key {key!r}")
    planes = rec.get("planes", {})
    labels = list(rec.get("payload_bytes", {}))
    if "tcp" not in planes:
        errs.append("missing plane 'tcp'")
    for name, p in planes.items():
        for label in labels:
            row = p.get("sizes", {}).get(label)
            if not row:
                errs.append(f"plane {name}: missing size {label!r}")
                continue
            if row.get("p50_us", 0) <= 0:
                errs.append(f"plane {name}/{label}: no p50")
            if row.get("syscalls_per_step", 0) <= 0:
                errs.append(f"plane {name}/{label}: no syscall count")
    if not rec.get("uring_supported"):
        # a tcp-only artifact from an unsupported kernel is schema-valid
        # but carries no claims to gate
        if errs:
            for e in errs:
                print(f"uring-check: {e}")
            print(f"uring-check: FAILED ({len(errs)} problem(s)) — {path}")
            return 1
        print(f"uring-check: OK (tcp-only, io_uring unsupported) — {path}")
        return 0
    if "io_uring" not in planes:
        errs.append("uring_supported but no io_uring plane")
    claims = rec.get("claims", {})
    small = min(labels, key=lambda l: rec["payload_bytes"].get(l, 0)) \
        if labels else None
    big = max(labels, key=lambda l: rec["payload_bytes"].get(l, 0)) \
        if labels else None
    for label in labels:
        c = claims.get(label)
        if not c:
            errs.append(f"missing claims for {label!r}")
            continue
        floor = 1.7 if label == big else 1.25
        if c.get("syscall_reduction", 0) < floor:
            errs.append(
                f"{label}: syscall reduction {c.get('syscall_reduction')} "
                f"< {floor}x gate")
        if c.get("p50_ratio", 0) < 0.5:
            errs.append(f"{label}: io_uring p50 more than 2x tcp "
                        f"(ratio {c.get('p50_ratio')})")
    if big and claims.get(big, {}).get("bw_ratio") is not None:
        bw = claims[big]["bw_ratio"]
        if not 0.5 <= bw <= 2.5:
            errs.append(f"{big}: bandwidth ratio {bw} outside parity "
                        f"band [0.5, 2.5]")
    if errs:
        for e in errs:
            print(f"uring-check: {e}")
        print(f"uring-check: FAILED ({len(errs)} problem(s)) — {path}")
        return 1
    reds = {l: claims[l]["syscall_reduction"] for l in labels}
    print(f"uring-check: OK — {path} (syscall reduction {reds}, "
          f"{small} p50 ratio {claims[small]['p50_ratio']})")
    return 0


def run_job(np_, shm, sizes, iters, repo):
    env = dict(os.environ)
    env.update({
        "HVT_BENCH_WORKER": "1",
        "HVT_BENCH_SIZES": json.dumps(sizes),
        "HVT_BENCH_ITERS": str(iters),
        "HVT_SHM_ALLREDUCE": "1" if shm else "0",
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np",
         str(np_), sys.executable, os.path.abspath(__file__)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(f"np={np_} shm={shm} failed:\n"
                           f"{proc.stdout}\n{proc.stderr}")
    for line in proc.stdout.splitlines():
        # launcher prefixes worker output with "[rank] "
        if "HVT_BENCH_RESULT" in line:
            return json.loads(line.split("HVT_BENCH_RESULT ", 1)[1])
    raise RuntimeError(f"no result line:\n{proc.stdout}")


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    quick = "--quick" in sys.argv
    sizes = ({"4KB": 1024, "16MB": 1 << 22} if quick else
             {k: v for k, v in SIZES.items()})
    nps = [2, 4] if quick else [1, 2, 4, 8]
    iters = 4 if quick else 8
    rows = []
    for np_ in nps:
        for shm in ([True] if np_ == 1 else [True, False]):
            res = run_job(np_, shm, sizes, iters, repo)
            for label, v in res.items():
                mb = SIZES[label] * 4 / (1 << 20)
                hit_bw = mb / (v["hit_ms"] / 1e3) if v["hit_ms"] else 0
                rows.append({"np": np_,
                             "backend": "shm" if shm else "ring",
                             "size": label, **v,
                             "hit_MBps": round(hit_bw, 1)})
                print(json.dumps(rows[-1]), flush=True)
    print("\n| np | backend | size | cold ms | hit ms | hit MB/s |")
    print("|---|---|---|---|---|---|")
    for row in rows:
        print(f"| {row['np']} | {row['backend']} | {row['size']} | "
              f"{row['cold_ms']} | {row['hit_ms']} | {row['hit_MBps']} |")


if __name__ == "__main__":
    if os.environ.get("HVT_TBENCH_ROLE") is not None:
        tbench_worker()
    elif os.environ.get("HVT_BENCH_WORKER"):
        sweep_worker() if os.environ.get("HVT_BENCH_SWEEP") else worker()
    elif "--check" in sys.argv:
        path = sys.argv[sys.argv.index("--check") + 1]
        with open(path) as f:
            harness = json.load(f).get("harness", "")
        sys.exit(uring_check(path) if harness.startswith("r18 uring")
                 else codec_check(path))
    elif "--uring" in sys.argv:
        uring_main()
    elif "--codec" in sys.argv:
        codec_main()
    elif "--sweep" in sys.argv:
        sweep_main()
    else:
        main()
