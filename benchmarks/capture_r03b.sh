#!/bin/bash
# Round-3 TPU capture, v2: every heavy benchmark is gated behind a cheap
# DATA-PLANE sanity probe (benchmarks/tpu_sanity.py). The round-2/3
# outages showed jax.devices() can answer while compile/execute RPCs
# block forever — v1 would then burn a 40-minute timeout per run against
# a wedged tunnel. v2 probes (3-minute bound) before each run, re-probes
# on failure, and keeps a per-run ledger so partial captures survive.
LOG=${1:-/tmp/r03_capture.log}
cd "$(dirname "$0")/.." || exit 1
# Single-pilot rule: disarm any v1 pipeline (and its in-flight bench)
# still probing from an earlier session — two capture loops sharing the
# one chip would corrupt each other's timings.
# Exclude our whole ancestor chain, not just $$: a non-exec wrapper
# (nohup timeout ... capture_r03b.sh) matches the pattern too, and
# killing it would tear down this very instance at startup.
self_and_ancestors=$$
p=$$
while [ "$p" -gt 1 ]; do
  p=$(awk '{print $4}' "/proc/$p/stat" 2>/dev/null) || break
  [ -n "$p" ] || break
  self_and_ancestors="$self_and_ancestors|$p"
done
for pid in $(pgrep -f "capture_r03b?\.sh" | grep -Evw "$self_and_ancestors"); do
  pkill -TERM -P "$pid" 2>/dev/null
  kill "$pid" 2>/dev/null
done
# loose match: also catches env-wrapped runs (timeout 2400 env HVT_... python bench.py)
pkill -f "timeout 2400 .*python bench\.py" 2>/dev/null
echo "=== capture_r03b started $(date -u) ===" >> "$LOG"

sane() {
  timeout 180 python benchmarks/tpu_sanity.py >> "$LOG" 2>&1
}

wait_sane() {
  # Probe until the data plane answers; 9-minute spacing like the
  # round-2 watcher. Bounded at ~11h (55 x (180s probe + 540s sleep))
  # so the script eventually exits. A deterministic LOCAL failure
  # (tpu_sanity exit 2: import error, broken env) bails immediately —
  # retrying cannot fix those.
  for i in $(seq 1 55); do
    sane; rc=$?
    if [ "$rc" -eq 0 ]; then return 0; fi
    if [ "$rc" -eq 2 ]; then
      echo "=== local failure (sanity rc=2), bailing $(date -u) ===" >> "$LOG"
      exit 2
    fi
    echo "probe $i: data plane wedged/down $(date -u)" >> "$LOG"
    sleep 540
  done
  echo "=== gave up waiting for data plane $(date -u) ===" >> "$LOG"
  exit 1
}

run() {
  wait_sane
  echo "--- $* ($(date -u)) ---" >> "$LOG"
  timeout 2400 "$@" >> "$LOG" 2>&1
  echo "--- rc=$? ($(date -u)) ---" >> "$LOG"
}

# Ordered by information value: headline ResNet first (VERDICT #1/#2),
# then the BN A/B, then GPT einsum vs compiled flash (VERDICT #3), then
# long-context flash, then the fused chunked-CE runs.
run python bench.py --no-scaling
run python bench.py --no-scaling --bn-impl flax
run python bench.py --model gpt --no-scaling
run env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --no-scaling --flash
run env HVT_FLASH_INTERPRET=0 python bench.py --model gpt --no-scaling --flash --seq-len 2048 --batch-size 4
run python bench.py --model gpt --no-scaling --seq-len 2048 --batch-size 4
run python bench.py --model gpt --no-scaling --chunked-ce
run python bench.py --model gpt --no-scaling --chunked-ce --batch-size 16
echo "=== capture_r03b done $(date -u) ===" >> "$LOG"
