#!/usr/bin/env python
"""Simulated large-gang control-plane scaling harness
(``python benchmarks/ctrl_plane_scaling.py``).

Spins up dozens of REAL engine processes over loopback — each worker is
a bare-python ctypes shim around ``libhvt_core.so`` (no jax, no numpy:
a 64-rank gang costs ~1 GB and spawns in seconds) — with
``HVT_TOPO_HOST`` faking the multi-host layout, and measures the
control-plane cost the hierarchical tree + steady-state bypass exist to
remove:

- **rank-0 control bytes per working cycle**, from the CTRL_BYTES
  flight-recorder events (the same counters behind
  ``hvt_ctrl_{tx,rx}_bytes_total`` and ``hvt_analyze``'s
  ``cycles.ctrl_by_role``), split into a COLD phase (unique tensor
  names every step — pure negotiation) and a STEADY phase (repeated
  names — the cache-hit bypass's home turf);
- **idle keepalive traffic** at rank 0 (bytes/sec while the gang parks);
- **cycles/sec** and the **fan-in** (``ctrl_peers``) per config.

Drives the two committed claims of
``benchmarks/r08_controlplane_scaling.json`` (BENCH_NOTES r9):
(a) tree mode cuts rank-0 cold-negotiation bytes/cycle ≥4x at 64
simulated ranks on 8 simulated hosts vs star, and (b) steady-state
bypass holds control bytes/cycle flat (within 2x) from 8→64 ranks.

Modes:
    --smoke [--out X.json]   tiny star-vs-tree pair (ci.sh --scale)
    --capture [--out ...]    the full r08 matrix (~minutes)
    --check X.json           artifact schema validation
Worker mode is selected internally via HVT_CPS_WORKER.

Byte metrics are workload-determined, not timing-determined, so the
numbers are stable on a loaded shared box (unlike latency sweeps — see
BENCH_NOTES r8 on host co-tenancy).
"""

from __future__ import annotations

import ctypes
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build",
                   "libhvt_core.so")
STATS_SLOTS_H = os.path.join(REPO, "horovod_tpu", "csrc",
                             "stats_slots.h")

SCHEMA = "hvt-ctrlscale-r1"

# EventKind wire ids this harness reads (csrc/events.h)
_KIND_CTRL_BYTES = 12


def _slot_index():
    """name -> slot index, parsed from the stats_slots.h X-macro — the
    harness tracks the append-only ABI without importing horovod_tpu
    (whose package import pulls jax into every worker)."""
    text = open(STATS_SLOTS_H).read()
    return {name: int(idx)
            for idx, name in re.findall(r'X\((\d+),\s*"([^"]+)"\)', text)}


class _Event(ctypes.Structure):
    # mirror of hvt::EventView (csrc/events.h, 96-byte ABI)
    _fields_ = [("ts_us", ctypes.c_longlong),
                ("arg2", ctypes.c_longlong),
                ("kind", ctypes.c_int),
                ("op", ctypes.c_int),
                ("arg", ctypes.c_int),
                ("lane", ctypes.c_int),
                ("name", ctypes.c_char * 64)]


class MiniEngine:
    """Minimal ctypes shim over the C++ engine — just enough surface to
    drive control-plane workloads from a featherweight worker process.
    Reused by tests/test_ctrl_plane.py for fast no-jax gang tests."""

    def __init__(self, lib_path=None):
        self.lib = ctypes.CDLL(lib_path or
                               os.environ.get("HVT_CORE_LIB", LIB))
        self.lib.hvt_init.argtypes = [ctypes.c_int, ctypes.c_int,
                                      ctypes.c_char_p, ctypes.c_int,
                                      ctypes.c_int]
        self.lib.hvt_submit.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong)]
        self.lib.hvt_result_bytes.restype = ctypes.c_longlong
        self.lib.hvt_result_read.argtypes = [ctypes.c_int,
                                             ctypes.c_void_p,
                                             ctypes.c_longlong]
        self.lib.hvt_wait_timeout.argtypes = [ctypes.c_int,
                                              ctypes.c_longlong]
        self.lib.hvt_engine_stats.argtypes = [
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
        self.lib.hvt_events_drain.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int]
        self.lib.hvt_error_message.argtypes = [ctypes.c_char_p,
                                               ctypes.c_int]
        self.slots = _slot_index()
        self.rank = 0
        self.size = 1
        # eager, not lazy: two client threads (the serving soak runs
        # one per tenant) racing the lazy getattr-init would each
        # create their own dict and drop the loser's handle entries
        self._dtype_of = {}
        self._ready = {}  # handle → payload collected by wait_timeout

    def init(self, rank, size, addr="127.0.0.1", port=29640, cycle_ms=1):
        rc = self.lib.hvt_init(rank, size, addr.encode(), port, cycle_ms)
        if rc != 0:
            raise RuntimeError(f"hvt_init failed (rank {rank}/{size})")
        self.rank, self.size = rank, size

    def shutdown(self):
        self.lib.hvt_shutdown()

    # wire ids: csrc/common.h OpType / ReduceKind / DataType
    OPS = {"allreduce": 0, "allgather": 1, "broadcast": 2,
           "alltoall": 3, "reducescatter": 4}
    REDUCE = {"sum": 0, "avg": 1, "min": 2, "max": 3, "prod": 4}
    DTYPES = {"uint8": (0, ctypes.c_uint8), "int8": (1, ctypes.c_int8),
              "int32": (4, ctypes.c_int32),
              "int64": (5, ctypes.c_int64),
              "float32": (7, ctypes.c_float),
              "float64": (8, ctypes.c_double)}

    def submit(self, name, values, op="allreduce", reduce="sum",
               dtype="float32", root=0, members=None, group_id=-1,
               group_size=0):
        """Async submit of a single-dim collective; returns the handle
        (pair with wait()). Lets tests land several submissions in one
        engine cycle. group_id/group_size join the submission into an
        engine-side fusion group (negotiated atomically, fused into ONE
        collective — the serving soak's request batches ride this)."""
        wire_dt, ct = self.DTYPES[dtype]
        n = len(values)
        # a preconstructed ctypes array is used as-is: hvt_submit copies
        # the payload synchronously, so callers may reuse one buffer
        # across submits (the serving soak's request payloads cycle over
        # a few values — rebuilding a 16K-element array per request was
        # pure python overhead)
        buf = values if isinstance(values, ctypes.Array) \
            else (ct * n)(*values)
        dims = (ctypes.c_longlong * 1)(n)
        splits = (ctypes.c_longlong * 1)(0)
        mem = members or []
        mem_arr = (ctypes.c_longlong * max(len(mem), 1))(*mem)
        h = self.lib.hvt_submit(
            name.encode(), self.OPS[op], self.REDUCE[reduce], wire_dt,
            1, dims, ctypes.cast(buf, ctypes.c_void_p),
            ctypes.c_longlong(n * ctypes.sizeof(ct)), root, 1.0, 1.0,
            0, splits, group_id, group_size, len(mem), mem_arr)
        if h < 0:
            raise RuntimeError("hvt_submit rejected")
        self._dtype_of[h] = ct
        return h

    def wait_timeout(self, h, timeout_ms) -> bool:
        """Bounded poll of a pending handle: False while still pending
        after timeout_ms (the handle stays waitable), True when done —
        pair with wait() to collect. rc<0 surfaces through wait()'s
        error path.

        On success the payload is read out IMMEDIATELY and stashed for
        that wait(): hvt_wait_timeout shares hvt_wait's move-out
        semantics (handles are waited at most once), so deferring the
        hvt_result_* calls to a later hvt_wait would find an empty
        output. Error status persists on the handle, so rc<0 just
        falls through to wait()'s hvt_wait."""
        rc = int(self.lib.hvt_wait_timeout(h, int(timeout_ms)))
        if rc == 1:
            return False
        if rc == 0:
            ct = self._dtype_of[h]
            nbytes = int(self.lib.hvt_result_bytes(h))
            out = (ct * (nbytes // ctypes.sizeof(ct)))()
            if nbytes:
                self.lib.hvt_result_read(
                    h, ctypes.cast(out, ctypes.c_void_p),
                    ctypes.c_longlong(nbytes))
            self._ready[h] = list(out)
        return True

    def wait(self, h, name="?"):
        ct = self._dtype_of.pop(h)
        if h in self._ready:
            out = self._ready.pop(h)
            self.lib.hvt_release(h)
            return out
        rc = self.lib.hvt_wait(h)
        if rc != 0:
            err = ctypes.create_string_buffer(4096)
            self.lib.hvt_error_message(err, 4096)
            self.lib.hvt_release(h)
            raise RuntimeError(
                f"collective '{name}' failed (rc={rc}): "
                f"{err.value.decode(errors='replace')}")
        nbytes = int(self.lib.hvt_result_bytes(h))
        out = (ct * (nbytes // ctypes.sizeof(ct)))()
        if nbytes:
            self.lib.hvt_result_read(h, ctypes.cast(out, ctypes.c_void_p),
                                     ctypes.c_longlong(nbytes))
        self.lib.hvt_release(h)
        return list(out)

    def collective(self, name, values, op="allreduce", reduce="sum",
                   dtype="float32", root=0, members=None):
        """Generic single-dim collective over a python list; returns
        the result as a list of the same dtype."""
        h = self.submit(name, values, op=op, reduce=reduce, dtype=dtype,
                        root=root, members=members)
        return self.wait(h, name)

    def allreduce(self, name, values, members=None):
        """Float32 sum-allreduce; values is a python list; returns the
        reduced list. members: ascending global ranks (None = world)."""
        return self.collective(name, values, members=members)

    def stats(self):
        """All hvt_engine_stats slots by manifest name."""
        want = max(self.slots.values()) + 1
        buf = (ctypes.c_longlong * want)()
        n = min(int(self.lib.hvt_engine_stats(buf, want)), want)
        return {name: (int(buf[i]) if i < n else 0)
                for name, i in self.slots.items()}

    def drain_ctrl_events(self):
        """Sum of CTRL_BYTES events since the last drain:
        (working_cycles, tx_bytes, rx_bytes)."""
        cycles = tx = rx = 0
        buf = (_Event * 2048)()
        while True:
            n = int(self.lib.hvt_events_drain(buf, len(buf)))
            for i in range(n):
                if int(buf[i].kind) == _KIND_CTRL_BYTES:
                    cycles += 1
                    tx += int(buf[i].arg)
                    rx += int(buf[i].arg2)
            if n < len(buf):
                return cycles, tx, rx

    def drain_exec_events(self):
        """Drain the flight recorder and return the EXEC span stream:
        a ring-ordered list of (ts_us, kind, lane) for EXEC_BEGIN (5) /
        EXEC_END (6) events — enough to reconstruct which lanes were
        mid-execution when another lane's execution started (the
        serving soak's pool-concurrency probe). Events come back in
        RING order, not timestamp order: the ring's atomic head
        preserves each thread's true record order, while sorting on
        the microsecond-truncated stamps would shuffle the several
        events a fast span records within one microsecond — phantom
        overlaps a single-thread engine cannot actually produce.
        Non-exec events are consumed and discarded."""
        out = []
        buf = (_Event * 2048)()
        while True:
            n = int(self.lib.hvt_events_drain(buf, len(buf)))
            for i in range(n):
                k = int(buf[i].kind)
                if k in (5, 6):  # EXEC_BEGIN / EXEC_END (csrc/events.h)
                    out.append((int(buf[i].ts_us), k,
                                int(buf[i].lane)))
            if n < len(buf):
                return out


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def _worker():
    spec = json.loads(os.environ["HVT_CPS_SPEC"])
    rank = int(os.environ["HVT_CPS_RANK"])
    size = int(os.environ["HVT_CPS_SIZE"])
    port = int(os.environ["HVT_CPS_PORT"])
    eng = MiniEngine()
    eng.init(rank, size, port=port, cycle_ms=spec.get("cycle_ms", 1))
    tensors = spec.get("tensors", 16)
    numel = spec.get("numel", 64)
    values = [float(rank + 1)] * numel

    def barrier(tag):
        out = eng.allreduce(f"sync.{tag}", [1.0])
        assert int(out[0]) == size, (tag, out)

    barrier("init")
    if rank == 0:
        eng.drain_ctrl_events()  # discard init-phase traffic
    phases = {}
    for ph in spec["phases"]:
        pname = ph["name"]
        t0 = time.monotonic()
        s0 = eng.stats() if rank == 0 else None
        if "sleep" in ph:
            time.sleep(ph["sleep"])
        else:
            for step in range(ph["steps"]):
                for i in range(tensors):
                    # realistic gradient-style names: negotiation cost
                    # scales with name length on the cold path
                    nm = (f"c{pname}.{step}.{i:03d}.grad/layer_weight"
                          if ph.get("unique") else
                          f"s.{i:03d}.grad/layer_weight")
                    out = eng.allreduce(nm, values)
                # cheap correctness guard: sum of (r+1) over ranks
                expect = size * (size + 1) / 2
                assert abs(out[0] - expect) < 1e-3, (out[0], expect)
        barrier(pname)
        if rank == 0:
            s1 = eng.stats()
            wall = time.monotonic() - t0
            wcycles, etx, erx = eng.drain_ctrl_events()
            phases[pname] = {
                "wall_sec": round(wall, 3),
                "cycles": s1["cycles"] - s0["cycles"],
                "ctrl_tx_bytes": s1["ctrl_tx_bytes"] - s0["ctrl_tx_bytes"],
                "ctrl_rx_bytes": s1["ctrl_rx_bytes"] - s0["ctrl_rx_bytes"],
                "bypass_cycles": (s1["ctrl_bypass_cycles"]
                                  - s0["ctrl_bypass_cycles"]),
                # CTRL_BYTES-event view: bytes on cycles that did work
                "working_cycles": wcycles,
                "event_tx_bytes": etx,
                "event_rx_bytes": erx,
            }
    if rank == 0:
        st = eng.stats()
        print("HVT_CPS_RESULT " + json.dumps(
            {"phases": phases, "ctrl_peers": st["ctrl_peers"],
             "cache_hits": st["cache_hits"]}), flush=True)
    eng.shutdown()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run_config(np_, hosts, topology, spec, port, bypass=True,
               timeout=900, extra_env=None):
    """Launch one simulated gang; returns rank 0's result dict plus the
    config echo. Ranks pack contiguously onto `hosts` fake hosts."""
    per_host = max(1, np_ // hosts)
    env_base = {
        "HVT_CPS_WORKER": "1",
        "HVT_CPS_SIZE": str(np_),
        "HVT_CPS_PORT": str(port),
        "HVT_CPS_SPEC": json.dumps(spec),
        "HVT_CTRL_TOPOLOGY": topology,
        "HVT_CTRL_BYPASS": "1" if bypass else "0",
        "HVT_HOSTNAME": "127.0.0.1",
        "HVT_CONNECT_TIMEOUT": "240",
        "HVT_LOG_LEVEL": "error",
        "PYTHONUNBUFFERED": "1",
    }
    env_base.update(extra_env or {})
    procs = []
    try:
        for r in range(np_):
            env = dict(os.environ)
            env.update(env_base)
            env["HVT_CPS_RANK"] = str(r)
            env["HVT_TOPO_HOST"] = f"h{min(r // per_host, hosts - 1)}"
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE if r == 0 else subprocess.DEVNULL,
                stderr=subprocess.PIPE if r == 0 else subprocess.DEVNULL,
                text=True))
        out, err = procs[0].communicate(timeout=timeout)
        deadline = time.monotonic() + 60
        fails = []
        for r, p in enumerate(procs):
            try:
                rc = p.wait(timeout=max(1.0,
                                        deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                rc = -9
            if rc != 0:
                fails.append((r, rc))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if procs[0].returncode != 0 or fails:
        raise RuntimeError(
            f"gang np={np_} hosts={hosts} topo={topology} failed "
            f"(ranks {fails}):\n{out}\n{err}")
    phase_ops = {ph["name"]: ph.get("steps", 0) * spec.get("tensors", 0)
                 for ph in spec.get("phases", [])}
    for line in out.splitlines():
        if line.startswith("HVT_CPS_RESULT "):
            res = json.loads(line[len("HVT_CPS_RESULT "):])
            res.update({"np": np_, "hosts": hosts,
                        "topology": topology, "bypass": bypass})
            for pname, ph in res["phases"].items():
                bytes_ = ph["event_tx_bytes"] + ph["event_rx_bytes"]
                ph["bytes_per_cycle"] = round(
                    bytes_ / max(ph["working_cycles"], 1), 1)
                # per-op normalization: how many tensors a working
                # cycle coalesces varies with gang size and box load,
                # so per-cycle ratios mix coalescing into the scaling
                # story — bytes per collective op does not
                if phase_ops.get(pname):
                    ph["bytes_per_op"] = round(
                        bytes_ / phase_ops[pname], 1)
            return res
    raise RuntimeError(f"no result line:\n{out}\n{err}")


_PORT = [26000 + (os.getpid() * 131) % 4000]


def _next_port(base=None):
    import socket
    if base is not None:
        _PORT[0] = base
    while True:
        # stateful: never re-offer a port this process already used —
        # back-to-back gangs would otherwise collide on rendezvous
        # leftovers (TIME_WAIT sockets bind fine under SO_REUSEADDR)
        _PORT[0] += 1
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", _PORT[0]))
                return _PORT[0]
            except OSError:
                continue


def _spec(cold_steps, steady_steps, tensors, idle_sec=0.0):
    phases = [{"name": "cold", "steps": cold_steps, "unique": True},
              {"name": "prime", "steps": 1},
              {"name": "steady", "steps": steady_steps}]
    if idle_sec:
        phases.append({"name": "idle", "sleep": idle_sec})
    return {"tensors": tensors, "numel": 64, "phases": phases}


def capture(out_path, smoke=False):
    record = {"schema": SCHEMA,
              "lib": os.path.relpath(LIB, REPO),
              "configs": [], "claims": {}}
    if smoke:
        matrix = [(8, 4, "star", True), (8, 4, "tree", True)]
        spec = _spec(2, 4, 8, idle_sec=1.0)
    else:
        matrix = [
            # claim (a): star vs tree at 64 ranks / 8 hosts, cold path
            (64, 8, "star", True),
            (64, 8, "tree", True),
            # claim (b): steady-state flatness 8 -> 64 ranks, 8 hosts
            (8, 8, "tree", True),
            (16, 8, "tree", True),
            # bypass A/B at the big config
            (64, 8, "tree", False),
            # idle-traffic satellite: 16-rank gang, 4 hosts
            (16, 4, "star", True),
            (16, 4, "tree", True),
        ]
        spec = _spec(4, 24, 16, idle_sec=3.0)
    for np_, hosts, topo, bypass in matrix:
        t0 = time.monotonic()
        res = run_config(np_, hosts, topo, spec, _next_port(),
                         bypass=bypass)
        res["total_sec"] = round(time.monotonic() - t0, 1)
        # leader fan-in: direct peers by role, derivable from layout
        per_host = max(1, np_ // hosts)
        res["leader_fanin"] = {
            "root": res["ctrl_peers"],
            "leader": per_host if topo == "tree" else None,
            "star_root_would_be": np_ - 1,
        }
        record["configs"].append(res)
        print(json.dumps({k: res[k] for k in
                          ("np", "hosts", "topology", "bypass",
                           "ctrl_peers", "total_sec")}), flush=True)
        for pname, ph in res["phases"].items():
            print(f"  {pname}: {ph['bytes_per_cycle']} B/cycle over "
                  f"{ph['working_cycles']} working cycles "
                  f"(bypass cycles: {ph['bypass_cycles']})", flush=True)

    def cfg(np_, hosts, topo, bypass=True):
        for c in record["configs"]:
            if (c["np"], c["hosts"], c["topology"],
                    c["bypass"]) == (np_, hosts, topo, bypass):
                return c
        return None

    big, bh = (8, 4) if smoke else (64, 8)
    star_big, tree_big = cfg(big, bh, "star"), cfg(big, bh, "tree")
    if star_big and tree_big:
        # claim (a): cold-negotiation bytes at rank 0, star vs tree.
        # Per-op == per-cycle on the cold path (unique names negotiate
        # one per cycle); per-op is reported as the primary number
        # because it is coalescing- and load-independent.
        a = (star_big["phases"]["cold"]["bytes_per_op"]
             / max(tree_big["phases"]["cold"]["bytes_per_op"], 1))
        record["claims"]["cold_bytes_per_op_star_over_tree"] = \
            round(a, 2)
        # idle-gang satellite: keepalive bytes per cycle at rank 0
        # (direct peers 15 -> 4 on the 16-rank/4-host layout)
        s16, t16 = cfg(16, 4, "star"), cfg(16, 4, "tree")
        if smoke:
            s16, t16 = star_big, tree_big
        idle_ratio = None
        if s16 and t16 and "idle" in s16["phases"]:
            si, ti = s16["phases"]["idle"], t16["phases"]["idle"]
            sb = (si["ctrl_tx_bytes"] + si["ctrl_rx_bytes"]) \
                / max(si["cycles"], 1)
            tb = (ti["ctrl_tx_bytes"] + ti["ctrl_rx_bytes"]) \
                / max(ti["cycles"], 1)
            idle_ratio = round(sb / max(tb, 1), 2)
        record["claims"]["idle_rank0_bytes_per_cycle_star_over_tree"] = \
            idle_ratio
    tree_small = cfg(8, bh, "tree")
    if tree_small and tree_big and not smoke:
        # claim (b): steady-state (cache-hit bypass) control bytes per
        # collective op, 8 -> 64 ranks on the same 8 hosts — flat means
        # the bitmask/positions encodings hold per-op cost ~constant
        b = (tree_big["phases"]["steady"]["bytes_per_op"]
             / max(tree_small["phases"]["steady"]["bytes_per_op"], 1))
        record["claims"]["steady_bytes_per_op_64_over_8"] = round(b, 2)
        nb = cfg(64, 8, "tree", bypass=False)
        if nb:
            record["claims"]["steady_bytes_per_op_bypass_off_over_on"] \
                = round(nb["phases"]["steady"]["bytes_per_op"]
                        / max(tree_big["phases"]["steady"]
                              ["bytes_per_op"], 1), 2)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        print(f"wrote {out_path}")
    print("claims: " + json.dumps(record["claims"]))
    return record


def check(path):
    """Artifact schema validation (ci.sh --scale)."""
    with open(path) as f:
        rec = json.load(f)
    errs = []
    if rec.get("schema") != SCHEMA:
        errs.append(f"schema != {SCHEMA}")
    cfgs = rec.get("configs", [])
    if not cfgs:
        errs.append("no configs")
    for c in cfgs:
        for key in ("np", "hosts", "topology", "bypass", "ctrl_peers",
                    "phases"):
            if key not in c:
                errs.append(f"config missing {key}")
        for pname, ph in c.get("phases", {}).items():
            for key in ("ctrl_tx_bytes", "ctrl_rx_bytes",
                        "working_cycles", "bytes_per_cycle"):
                if key not in ph:
                    errs.append(f"phase {pname} missing {key}")
    if "claims" not in rec:
        errs.append("no claims block")
    for e in errs:
        print(f"ctrl_plane_scaling --check: {e}", file=sys.stderr)
    if errs:
        return 1
    ncfg = len(cfgs)
    print(f"ctrl_plane_scaling --check: OK ({ncfg} configs, claims: "
          f"{json.dumps(rec.get('claims'))})")
    return 0


def main():
    if os.environ.get("HVT_CPS_WORKER"):
        _worker()
        return 0
    args = sys.argv[1:]

    def argval(flag, dflt):
        if flag not in args:
            return dflt
        i = args.index(flag) + 1
        if i >= len(args):
            sys.exit(f"ctrl_plane_scaling: {flag} requires a value")
        return args[i]

    if "--check" in args:
        return check(argval("--check", ""))
    out = argval("--out", "" if "--smoke" in args
                 else os.path.join(REPO, "benchmarks",
                                   "r08_controlplane_scaling.json"))
    capture(out, smoke="--smoke" in args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
