#!/usr/bin/env python
"""Perf-regression gate capture (``ci.sh --perfgate``).

Produces ONE self-describing perf report by running

1. the existing loopback microbench (``engine_scaling.py --sweep``
   internals: hot-path allreduce p50 per payload size on the TCP ring,
   interleaved rounds, best-round p50 as the least-interference
   estimate), and
2. a short **flight-recorded 2-proc gang** (``--timeline`` +
   ``HVT_TIMELINE_MARK_CYCLES=1``, shm off so the TCP duplex pump's
   WIRE spans are exercised), analyzed by
   ``horovod_tpu.tools.hvt_analyze`` into the queue / negotiate / wire /
   reduce phase breakdown.

The report's ``metrics`` block carries the CURATED gate set — sweep
p50s plus the gang's queue/wire/exec/e2e p50s. Noisy low-sample series
(cold-negotiation p50, stragglers, p99s) stay in the report for humans
but never gate: the contract is *fail only on >2x p50 regressions*
(``hvt_analyze --diff``, band overridable via
``HVT_PERFGATE_MAX_RATIO``), with bands generous enough for a shared CI
box.

Usage:
    python benchmarks/perf_gate.py --out /tmp/perf.json   # capture
    python benchmarks/perf_gate.py --rebaseline           # refresh
        benchmarks/perf_baseline.json (commit the result)
    python -m horovod_tpu.tools.hvt_analyze --diff \\
        benchmarks/perf_baseline.json /tmp/perf.json      # the gate
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)  # benchmarks/ is not a package

from horovod_tpu.tools.hvt_analyze import _pctl  # noqa: E402

SCHEMA = "hvt-perfgate-r1"

# fp32 element counts: latency floor, mid, bandwidth-bound
SWEEP_SIZES = {"4KB": 1 << 10, "1MB": 1 << 18, "16MB": 1 << 22}

GANG_WORKER = """\
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvt
hvt.init()
x = np.arange(1 << 14, dtype=np.float32)  # 64 KB
for i in range({iters}):
    hvt.allreduce(x, name="gate.hot")
# a small async window so the overlap metric sees in-flight work
hs = [hvt.allreduce_async(x, name=f"gate.async.{{j}}") for j in range(4)]
for h in hs:
    hvt.synchronize(h)
hvt.shutdown()
"""

# gang phase p50s that gate (negotiate/stragglers are low-sample noise
# on a quick run and stay report-only)
GANG_GATE_PHASES = ("queue", "wire", "exec", "e2e")


def _free_port():
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_sweep(np_, iters, rounds, sizes):
    """Best-round p50 (ms) per size via the engine_scaling harness; the
    worker subprocesses measure the hot cached-name path on the TCP
    ring (HVT_SHM_ALLREDUCE=0, set inside run_sweep_job)."""
    import engine_scaling

    pooled = {label: [] for label in sizes}
    round_p50 = {label: [] for label in sizes}
    for rnd in range(rounds):
        res = engine_scaling.run_sweep_job(np_, {}, sizes, iters, REPO)
        for label, samples in res["samples_s"].items():
            pooled[label].extend(samples)
            round_p50[label].append(
                _pctl(sorted(samples), 0.50) * 1e3)
        print(f"perf-gate: sweep round {rnd + 1}/{rounds} done",
              flush=True)
    out = {}
    for label in sizes:
        s = sorted(pooled[label])
        out[label] = {
            "p50_ms": round(_pctl(s, 0.50) * 1e3, 3),
            "p99_ms": round(_pctl(s, 0.99) * 1e3, 3),
            "round_p50_ms": [round(v, 3) for v in round_p50[label]],
            "best_p50_ms": round(min(round_p50[label]), 3),
        }
    return out


def run_recorded_gang(np_, iters, timeout_sec=240):
    """Launch a flight-recorded gang and analyze the merged timeline."""
    from horovod_tpu.tools import hvt_analyze

    with tempfile.TemporaryDirectory(prefix="hvt_perfgate_") as td:
        worker = os.path.join(td, "worker.py")
        with open(worker, "w") as f:
            f.write(GANG_WORKER.format(repo=REPO, iters=iters))
        merged = os.path.join(td, "timeline.json")
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "",
            # the TCP duplex pump is what the WIRE spans cover; shm
            # would hide the wire phase on a single-host gang
            "HVT_SHM_ALLREDUCE": "0",
            "HVT_TIMELINE_MARK_CYCLES": "1",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner.launch",
             "-np", str(np_), "--master-port", str(_free_port()),
             "--timeline", merged, sys.executable, worker],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=timeout_sec)
        if proc.returncode != 0 or not os.path.exists(merged):
            raise RuntimeError(
                f"perf-gate gang failed (rc={proc.returncode}):\n"
                f"{proc.stdout}\n{proc.stderr}")
        return hvt_analyze.analyze_paths([merged])


def capture(np_=2, sweep_iters=10, sweep_rounds=3, gang_runs=3,
            gang_iters=40, quick=False):
    """Best-of-N everywhere: each measurement is min over repeated
    runs, because on a shared box the quietest run is the
    least-interference estimate (a co-tenant can only make you slower).
    The gate then compares best-of vs best-of, which is what keeps a
    2x band honest on noisy CI hardware."""
    if quick:
        sweep_iters, sweep_rounds, gang_runs, gang_iters = 5, 1, 1, 15
    sweep = run_sweep(np_, sweep_iters, sweep_rounds, SWEEP_SIZES)
    gangs = []
    for i in range(gang_runs):
        gangs.append(run_recorded_gang(np_, gang_iters))
        print(f"perf-gate: gang run {i + 1}/{gang_runs} done",
              flush=True)
    gang = gangs[0]  # full report from the first run; p50s gate best-of
    metrics = {}
    for label, row in sweep.items():
        metrics[f"sweep_{label}_p50_ms"] = row["best_p50_ms"]
    for phase in GANG_GATE_PHASES:
        p50s = [g["phases"][phase]["p50"] for g in gangs
                if phase in g["phases"]]
        if p50s:
            metrics[f"gang_{phase}_us_p50"] = min(p50s)
    return {
        "schema": SCHEMA,
        "np": np_,
        "sweep_iters": sweep_iters,
        "sweep_rounds": sweep_rounds,
        "gang_runs": gang_runs,
        "gang_iters": gang_iters,
        "transport": "tcp ring (HVT_SHM_ALLREDUCE=0)",
        "sweep": sweep,
        "gang": gang,
        "metrics": metrics,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="capture the perf-gate report (microbench sweep + "
                    "flight-recorded gang breakdown)")
    ap.add_argument("--out", default="/tmp/hvt_perf_gate.json",
                    help="report path (default /tmp/hvt_perf_gate.json)")
    ap.add_argument("--rebaseline", action="store_true",
                    help="write benchmarks/perf_baseline.json instead "
                         "(commit the result)")
    ap.add_argument("--np", type=int, default=2)
    ap.add_argument("--quick", action="store_true",
                    help="fewer iterations (smoke runs, not baselines)")
    args = ap.parse_args(argv)
    rep = capture(np_=args.np, quick=args.quick)
    out = (os.path.join(HERE, "perf_baseline.json")
           if args.rebaseline else args.out)
    with open(out, "w") as f:
        json.dump(rep, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"perf-gate: report written to {out}")
    for k, v in sorted(rep["metrics"].items()):
        print(f"  {k} = {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
