#!/bin/bash
# Round-5 capture: QUEUE-DRIVEN armed benchmark pipeline, sequential
# (one chip), one JSON line per run under benchmarks/r05/.
#
# Differences from capture_r04.sh (fixed run list):
#   - Runs come from benchmarks/r05/queue.txt ("name<TAB>command..."),
#     processed in order; completed names are recorded in done.txt.
#     When the queue is exhausted the pipeline idles and re-polls, so
#     new runs (e.g. post-optimization ResNet re-measures) are APPENDED
#     to the queue instead of restarting the pipeline — restarting
#     meant killing an in-flight TPU benchmark, and the round-2
#     17-hour outage started right after exactly that.
#   - Every heavy run stays gated behind the end-to-end data-plane
#     probe (benchmarks/tpu_sanity.py): jax.devices() answering is NOT
#     a gate — during the round-2/3/4 outages the control plane listed
#     the device while every compile/execute RPC blocked forever.
cd "$(dirname "$0")/.." || exit 1
OUT=benchmarks/r05
mkdir -p "$OUT"
QUEUE="$OUT/queue.txt"
DONE="$OUT/done.txt"
touch "$QUEUE" "$DONE"

# Single-pilot rule. PIDFILE identifies the incumbent precisely;
# name-pattern pgrep is NOT safe (it also matches launching shells
# whose cmdline contains the script name — observed self-kills in
# round 4). Takeover policy (round-5 revision):
#   - A live capture_r05 incumbent → REFUSE to start: the queue design
#     makes relaunch unnecessary (append to queue.txt instead), and
#     killing it could kill an in-flight TPU benchmark — the round-2
#     17-hour tunnel wedge started right after exactly that.
#   - An older-generation incumbent (fixed run list, no queue) → kill
#     ONLY the supervisor script (never its children: an in-flight
#     bench child becomes an orphan that finishes and writes its
#     output), then DRAIN below before touching the chip.
PIDFILE=/tmp/hvt_capture.pid
if [ -f "$PIDFILE" ]; then
  old=$(cat "$PIDFILE" 2>/dev/null)
  if [ -n "$old" ] && [ "$old" != "$$" ] && kill -0 "$old" 2>/dev/null \
     && grep -qa "capture_r0" "/proc/$old/cmdline" 2>/dev/null; then
    if grep -qa "capture_r05" "/proc/$old/cmdline" 2>/dev/null; then
      echo "capture_r05 already running (pid $old); append runs to" \
           "$QUEUE instead of relaunching" >&2
      exit 3
    fi
    kill "$old" 2>/dev/null
  fi
fi
# Wait for the incumbent to actually die before claiming the pidfile:
# its EXIT trap removes the pidfile, and firing AFTER our write would
# delete OUR claim (observed once). The trap below also only removes
# the file while it still holds our own pid, for the same race.
for _ in 1 2 3 4 5; do
  [ -n "${old:-}" ] && kill -0 "$old" 2>/dev/null || break
  sleep 1
done
echo $$ > "$PIDFILE"
trap '[ "$(cat "$PIDFILE" 2>/dev/null)" = "$$" ] && rm -f "$PIDFILE"' EXIT
# DRAIN, don't kill: any orphaned heavy run (bench.py, calib_probe, or
# any future queue entry — they all launch as `timeout 2400 env ...`)
# keeps the chip; wait for it to finish or hit its own timeout before
# probing. 2400 s timeout + margin bounds this at ~45 min.
for _ in $(seq 1 90); do
  pgrep -f "timeout 2400 env" >/dev/null 2>&1 || break
  echo "draining in-flight heavy run before takeover $(date -u)" >> "$OUT/capture.log"
  sleep 30
done
echo "=== capture_r05 started $(date -u) ===" >> "$OUT/capture.log"

sane() {
  timeout 180 python benchmarks/tpu_sanity.py >> "$OUT/capture.log" 2>&1
}

wait_sane() {
  # Probe until the data plane answers, 9-minute spacing, bounded at
  # ~11h. tpu_sanity rc=2 = deterministic local failure — bail.
  for i in $(seq 1 66); do
    sane; rc=$?
    if [ "$rc" -eq 0 ]; then return 0; fi
    if [ "$rc" -eq 2 ]; then
      echo "=== local failure (sanity rc=2), bailing $(date -u) ===" >> "$OUT/capture.log"
      exit 2
    fi
    echo "probe $i: data plane wedged/down $(date -u)" >> "$OUT/capture.log"
    sleep 540
  done
  echo "=== gave up waiting for data plane $(date -u) ===" >> "$OUT/capture.log"
  exit 1
}

run_one() {
  local name="$1"; shift
  wait_sane
  echo "=== $name: $* ($(date -u +%H:%M:%S)) ===" >> "$OUT/capture.log"
  # wait_sane just gated the data plane; skip bench.py's own probe loop
  HVT_SKIP_DEVICE_PROBE=1 timeout 2400 env "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  echo "rc=$? $name done $(date -u +%H:%M:%S)" >> "$OUT/capture.log"
  echo "$name" >> "$DONE"
}

# Queue loop: process entries not yet in done.txt, in order; idle-poll
# for appended work. Names must be unique — re-measuring a config
# means appending a NEW name (e.g. resnet50_v2).
idle_logged=0
while true; do
  next_name=""
  while IFS=$'\t' read -r name cmd; do
    [ -z "$name" ] && continue
    case "$name" in \#*) continue ;; esac
    if ! grep -qxF "$name" "$DONE" 2>/dev/null; then
      next_name="$name"; next_cmd="$cmd"; break
    fi
  done < "$QUEUE"
  if [ -n "$next_name" ]; then
    idle_logged=0
    # shellcheck disable=SC2086
    run_one "$next_name" $next_cmd
  else
    if [ "$idle_logged" -eq 0 ]; then
      echo "=== queue drained, idling $(date -u) ===" >> "$OUT/capture.log"
      idle_logged=1
    fi
    sleep 120
  fi
done
