#!/usr/bin/env python
"""Calibration-gap probe: why does the in-harness matmul ceiling read
~75 TFLOP/s when a v5-lite's paper bf16 peak is 197?

Hypotheses swept here (VERDICT r4 #3):
  H1 chain too short — each timed block is k_steps dependent 8192^3
     matmuls (~45 ms at paper peak); the tunnel's per-dispatch RPC
     latency is tens of ms, so short chains under-read badly. Sweep
     k_steps 8..256: if the rate climbs with chain length and
     asymptotes, the gap is dispatch overhead, not the chip.
  H2 matrix too small/large for the MXU tiling — sweep m.
  H3 accumulation dtype — bf16 operands accumulate in fp32 on the MXU
     regardless; preferred_element_type=bf16 on the output would show
     whether an output-convert pass taxes the chain.
  H4 sustained throttling — a long run's per-block rates trending DOWN
     over time would indicate clocks, not harness.

Prints one JSON line per config plus a summary line. Run via the
capture queue (gated behind tpu_sanity) — takes a few minutes.
"""

import json
import sys
import time


def time_chain(m, k_steps, reps, out_dtype=None):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    x = jnp.asarray(np.random.RandomState(0).randn(m, m), jnp.bfloat16)
    w = jnp.asarray(np.random.RandomState(1).randn(m, m), jnp.bfloat16)

    if out_dtype is None:
        def body(i, h):
            return h @ w
    else:
        def body(i, h):
            return lax.dot_general(
                h, w, (((1,), (0,)), ((), ())),
                preferred_element_type=out_dtype).astype(jnp.bfloat16)

    @jax.jit
    def chain(x, w):
        return lax.fori_loop(0, k_steps, body, x)

    float(jnp.sum(chain(x, w)))  # compile + settle
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(jnp.sum(chain(x, w)))  # forced readback (tunnel protocol)
        dt = time.perf_counter() - t0
        tflops = k_steps * 2 * m ** 3 / dt / 1e12
        if tflops < 1000.0:
            rates.append(tflops)
    return rates


def main():
    import jax

    platform = jax.devices()[0].platform
    small = platform == "cpu"  # harness validation only

    configs = []
    # H1: chain length at the r4 calibration point (m=8192)
    for k in ([2, 4] if small else [8, 32, 128, 256]):
        configs.append({"m": 512 if small else 8192, "k_steps": k,
                        "tag": "chain_len"})
    # H2: matrix size at a long chain (dispatch amortized)
    for m in ([256] if small else [2048, 4096, 16384]):
        configs.append({"m": m, "k_steps": 4 if small else 64,
                        "tag": "matrix_size"})
    # H3: output dtype (fp32 accumulate + convert vs native)
    configs.append({"m": 512 if small else 8192,
                    "k_steps": 4 if small else 64,
                    "out_dtype": "float32", "tag": "accum_out_fp32"})

    results = []
    for cfg in configs:
        import jax.numpy as jnp

        out_dtype = getattr(jnp, cfg["out_dtype"]) \
            if "out_dtype" in cfg else None
        reps = 2 if small else 5
        rates = time_chain(cfg["m"], cfg["k_steps"], reps, out_dtype)
        import numpy as np

        rec = {"probe": "calib", "tag": cfg["tag"], "m": cfg["m"],
               "k_steps": cfg["k_steps"],
               "out_dtype": cfg.get("out_dtype", "default"),
               "tflops_median": round(float(np.median(rates)), 2)
               if rates else None,
               "tflops_all": [round(r, 1) for r in rates],
               "platform": platform}
        results.append(rec)
        print(json.dumps(rec))
        sys.stdout.flush()

    # H4: sustained run — 12 consecutive blocks at the best chain config;
    # a downward trend = throttling, flat = no.
    if not small:
        sus = time_chain(8192, 128, 12)
        print(json.dumps({"probe": "calib", "tag": "sustained_trend",
                          "tflops_blocks": [round(r, 1) for r in sus],
                          "platform": platform}))

    best = max((r for r in results if r["tflops_median"]),
               key=lambda r: r["tflops_median"], default=None)
    print(json.dumps({"probe": "calib_summary",
                      "best": best, "paper_peak_tflops": 197.0}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
