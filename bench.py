#!/usr/bin/env python
"""Synthetic ResNet-50 benchmark — the TPU-native analog of the reference's
``examples/pytorch/pytorch_synthetic_benchmark.py`` (prints img/sec ± stdev;
reference lines :110,:117) and ``examples/tensorflow2/tensorflow2_synthetic_benchmark.py``.

Data-parallel over every visible chip via the global mesh; the gradient
reduction is compiled into the step (XLA ICI allreduce), which is the whole
point of the TPU-native design.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

vs_baseline denominator: the reference's only published absolute number,
1656.82 img/sec for ResNet-101 on 16 GPUs (``docs/benchmarks.rst:43``)
= 103.55 img/sec/device.
"""

import argparse
import json
import sys
import time

BASELINE_IMG_SEC_PER_DEVICE = 1656.82 / 16.0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet101"])
    p.add_argument("--batch-size", type=int, default=128,
                   help="per-chip batch size")
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--fp32", action="store_true",
                   help="use float32 instead of bfloat16")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvt
    from horovod_tpu.models import ResNet50, ResNet101
    from horovod_tpu.parallel import mesh as M

    hvt.init()
    mesh = M.global_mesh()
    n = hvt.size()

    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    model_cls = ResNet50 if args.model == "resnet50" else ResNet101
    model = model_cls(num_classes=1000, dtype=dtype)

    global_batch = args.batch_size * n
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(global_batch, 224, 224, 3),
                         dtype=dtype)
    labels = jnp.asarray(rng.randint(0, 1000, (global_batch,)))
    data_sharding = NamedSharding(mesh, P(M.WORLD_AXIS))
    images = jax.device_put(images, data_sharding)
    labels = jax.device_put(labels, data_sharding)

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3), dtype), train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    repl = NamedSharding(mesh, P())
    params = jax.device_put(params, repl)
    batch_stats = jax.device_put(batch_stats, repl)

    # reference benchmark uses SGD momentum 0.9 via hvd.DistributedOptimizer
    tx = hvt.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                  axis_name=None)  # pjit: XLA reduces
    opt_state = jax.device_put(tx.init(params), repl)

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, mutated["batch_stats"]

    @jax.jit
    def train_step(params, batch_stats, opt_state, images, labels):
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_bs, opt_state, loss

    # warmup / compile
    params, batch_stats, opt_state, loss = train_step(
        params, batch_stats, opt_state, images, labels)
    jax.block_until_ready(loss)

    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, images, labels)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        img_secs.append(global_batch * args.num_batches_per_iter / dt)

    img_sec_mean = float(np.mean(img_secs))
    img_sec_std = float(np.std(img_secs))
    per_chip = img_sec_mean / n
    print(f"# {args.model} bs={args.batch_size}/chip chips={n} "
          f"dtype={'fp32' if args.fp32 else 'bf16'}: "
          f"{img_sec_mean:.1f} +- {img_sec_std:.1f} img/sec total, "
          f"{per_chip:.1f} img/sec/chip, final loss {float(loss):.3f}",
          file=sys.stderr)
    print(json.dumps({
        "metric": f"{args.model}_synthetic_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_DEVICE, 3),
    }))


if __name__ == "__main__":
    main()
