#!/usr/bin/env python
"""Synthetic ResNet benchmark — the TPU-native analog of the reference's
``examples/pytorch/pytorch_synthetic_benchmark.py`` (prints img/sec ± stdev;
reference lines :110,:117) and the tf_cnn_benchmarks recipe the reference's
published numbers use (``docs/benchmarks.rst:28-43``).

Data-parallel over the visible chips; the gradient reduction is compiled
into the step (XLA ICI allreduce). Each timed block runs
``--num-batches-per-iter`` training steps inside ONE compiled program
(``lax.fori_loop``) so host dispatch latency is amortized the way a real
TPU input pipeline would.

Anchoring (metric-of-record support, BASELINE.md):
- ``calib_tflops``: bf16 matmul chain timed through the SAME harness —
  the rig-local compute ceiling (absolute wall-clock on tunneled rigs is
  dilated; only same-harness ratios are meaningful).
- ``mfu``: achieved model FLOP/s (theoretical per-image training FLOPs ×
  throughput) divided by that in-harness ceiling; XLA's own cost-analysis
  count is reported alongside as ``xla_flops_per_img``.
- ``scaling``: 1→N chip sweep, per-chip efficiency vs the 1-chip run —
  the reference's headline metric (``docs/benchmarks.rst:13-14``).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...,
   "mfu": ..., "calib_tflops": ..., "achieved_tflops": ..., "scaling": ...}

vs_baseline denominator: the reference's only published absolute number,
1656.82 img/sec for ResNet-101 on 16 GPUs (``docs/benchmarks.rst:43``)
= 103.55 img/sec/device.

Profile notes (real v5-lite chip, bs=512/step trace): convolutions run at
~89% of the in-harness matmul ceiling; the residual is the fp32
BatchNorm statistics passes (convert+reduce over activations, ~18% of
step — measured by comparing against running-stats-only execution).
Keeping fp32 statistics is a deliberate accuracy/parity choice (the
reference's fp16 recipes also keep BN in fp32).
"""

import argparse
import json
import os
import sys
import time

BASELINE_IMG_SEC_PER_DEVICE = 1656.82 / 16.0

# Bump whenever a change makes numbers incomparable with earlier records
# (harness restructure, different measurement protocol, new defaults).
# r4: config embedded in the JSON line, robust median calibration.
# r4.1: calibration reps force a scalar readback (block_until_ready can
#       return early on the tunneled backend); zero blocks excluded.
# r4.2: model timing loops force the same readback + median-anchored
#       implausible-iter filter (_sane_rates).
# r4.3: default steps-per-iter 10 -> 32 (amortizes the param-copy
#       critical path; +4-5% on both models) and echoed in config.
# r5.0: dual MFU — `mfu` stays calib-relative (rig-local ceiling),
#       `mfu_vs_peak` divides by the chip's PAPER bf16 peak so records
#       are comparable to external efficiency tables; `suspect` flag
#       propagated into the record when every timing iter tripped the
#       plausibility bound (previously stderr-only). Numbers themselves
#       are comparable with r4.3.
# r5.1: `engine_metrics` — horovod_tpu.metrics JSON snapshot (engine
#       counters + dispatch histograms) embedded in every record; the
#       final loss is eager-allreduced across processes first. Schema
#       addition only; numbers remain comparable with r5.0.
HARNESS_VERSION = "r5.1"

# Paper bf16 peak per chip for mfu_vs_peak. The tunneled rig identifies
# as a v5-lite (TPU v5e): 197 TFLOP/s bf16. The in-harness measured
# ceiling (calib_tflops) sits well below this — see BENCH_NOTES.md
# "Calibration-vs-paper gap" — so both ratios are reported: `mfu`
# (achieved / measured rig ceiling) and `mfu_vs_peak` (achieved /
# paper peak). Override with HVT_PEAK_TFLOPS for a different chip.
PAPER_PEAK_TFLOPS = 197.0

# Theoretical training FLOPs (fwd+bwd+update ≈ 3x forward; ResNet-50 fwd ≈
# 4.1 GFLOP/img @224², ResNet-101 ≈ 7.8) — the MFU numerator.
# Training FLOPs (3x forward, forward = 2x MACs), algorithmic counts at
# the model's native resolution (224; inception_v3 scales from 299).
FLOPS_PER_IMG = {"resnet50": 12.3e9, "resnet101": 23.4e9,
                 "resnet152": 34.5e9, "vgg16": 46.5e9,
                 "inception_v3": 17.1e9}
NATIVE_IMG_SIZE = {"resnet50": 224, "resnet101": 224, "resnet152": 224,
                   "vgg16": 224, "inception_v3": 299}


def _compiled_flops(lowered_compiled):
    """Total FLOPs of a compiled executable per XLA's cost analysis, or
    None if the backend doesn't report them."""
    try:
        cost = lowered_compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _sane_rates(rates, flops_per_item=None, n_chips=1):
    """Drop timing iters that are physically implausible: the tunneled
    backend's async layer occasionally lets a dispatch 'complete' in
    sub-ms even with a forced readback racing a prior in-flight block.

    Two guards compose: an ABSOLUTE bound (implied >1000 TFLOP/s/chip
    when ``flops_per_item`` is known — rates are job-wide items/sec, so
    the bound scales by ``n_chips``; no current chip exceeds it),
    because a majority-artifact sample makes any median-anchored cut
    blind; then a >50x-median cut for the minority-artifact case. A
    genuinely fast run trips neither.

    Returns ``(rates, suspect)``: ``suspect`` is True when EVERY iter
    tripped the absolute bound — the record built from these rates is
    not a measurement, and callers must stamp that into the emitted
    JSON (a stderr warning alone is invisible to record consumers)."""
    import numpy as np

    n0 = len(rates)
    if flops_per_item:
        cap = 1000e12 * max(1, n_chips)
        plausible = [r for r in rates if r * flops_per_item <= cap]
        if not plausible:
            # EVERY iter implies an impossible rate: the backend is
            # wedged past what any filter can repair — say so loudly
            # AND flag the record itself as non-physical
            print("# WARNING: every timing iter implies >1000 TFLOP/s/"
                  "chip — the backend did not actually execute the "
                  "work; this record is NOT a measurement",
                  file=sys.stderr)
            return rates, True
        rates = plausible
    med = float(np.median(rates))
    sane = [r for r in rates if r <= 50 * med]
    if len(sane) != n0:
        print(f"# dropped {n0 - len(sane)} implausible timing "
              f"iter(s) (absolute 1000-TFLOP/s/chip bound / >50x median "
              f"{med:.1f})", file=sys.stderr)
    return sane or rates, False


def calibrate_matmul_tflops(platform):
    """Rig-local bf16 compute ceiling: a dependent matmul chain timed
    through the same perf_counter harness as the model benchmark."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    # CPU (test harness validation) can't chew 8192³; keep it tiny there.
    m, k_steps, reps = (8192, 8, 3) if platform != "cpu" else (512, 4, 2)
    x = jnp.asarray(np.random.RandomState(0).randn(m, m), jnp.bfloat16)
    w = jnp.asarray(np.random.RandomState(1).randn(m, m), jnp.bfloat16)

    @jax.jit
    def chain(x, w):
        return lax.fori_loop(0, k_steps, lambda i, h: h @ w, x)

    # Timing protocol: force a scalar READBACK, not just
    # block_until_ready() — on the tunneled backend the latter has
    # returned before execution finished (the r04 capture recorded a
    # 104,000 TFLOP/s "rep" and then a whole block where every rep
    # finished in sub-ms: physically impossible). A device-to-host
    # transfer of the reduced scalar cannot complete before the chain
    # has. Take the MEDIAN of plausible reps; max-of-reps would crown
    # exactly the artifact.
    float(jnp.sum(chain(x, w)))  # compile + settle
    samples = []
    for _ in range(reps * 3):
        t0 = time.perf_counter()
        float(jnp.sum(chain(x, w)))  # forced readback
        dt = time.perf_counter() - t0
        tflops = k_steps * 2 * m ** 3 / dt / 1e12
        if tflops < 1000.0:  # no current chip exceeds this; drop artifacts
            samples.append(tflops)
        if len(samples) >= reps:
            break
    return float(np.median(samples)) if samples else 0.0


def measure_gpt(devices, per_chip_batch, num_iters, num_batches_per_iter,
                dtype_name, seq_len=1024, use_flash=False,
                chunked_ce=False, n_kv_heads=None, unroll=1):
    """GPT train-step throughput on a dp mesh (tokens/sec/chip) — the
    flagship-model counterpart of the ResNet measurement. FLOPs/token by
    the standard training estimate 6N + 12·L·d_model·seq (dense matmuls
    fwd+bwd plus attention score/value matmuls)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import GPT, GPTConfig
    from horovod_tpu.parallel.mesh import make_parallel_mesh

    n = len(devices)
    mesh = make_parallel_mesh(devices=devices, dp=n)
    dtype = jnp.float32 if dtype_name == "fp32" else jnp.bfloat16
    cfg = GPTConfig(vocab_size=32768, n_layers=12, d_model=768, n_heads=12,
                    n_kv_heads=n_kv_heads, d_ff=3072, max_seq_len=seq_len,
                    dtype=dtype, use_flash=use_flash)
    model = GPT(cfg)
    global_batch = per_chip_batch * n
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                     (global_batch, seq_len)))
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, seq_len), jnp.int32))["params"]
    repl = NamedSharding(mesh, P())
    params = jax.device_put(params, repl)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    tx = optax.adamw(1e-4)
    opt_state = jax.device_put(tx.init(params), repl)

    def loss_fn(params):
        targets = jnp.roll(tokens, -1, axis=-1)
        if chunked_ce:
            # fuse the vocab projection into a sequence-chunked CE: the
            # [B, S, V] logits tensor is never materialized (losses.py)
            from horovod_tpu.ops.losses import softmax_cross_entropy_fused

            hidden = model.apply({"params": params}, tokens,
                                 return_hidden=True)
            return softmax_cross_entropy_fused(
                hidden[:, :-1], params["embedding"], targets[:, :-1])
        logits = model.apply({"params": params}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], targets[:, :-1]).mean()

    def train_step(carry, _):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), loss

    def block_fn(params, opt_state):
        # unroll > 1 removes the while-loop barrier between consecutive
        # steps so the scheduler can overlap step i's optimizer/stats
        # tail with step i+1's matmuls (A/B lever; see --unroll)
        (params, opt_state), loss = lax.fori_loop(
            0, num_batches_per_iter, lambda i, c: train_step(c[0], None),
            ((params, opt_state), jnp.float32(0)), unroll=unroll)
        return params, opt_state, loss

    block = jax.jit(block_fn, donate_argnums=(0, 1))
    params, opt_state, loss = block(params, opt_state)
    float(loss)  # warmup/compile; forced readback (see _sane_rates)
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq_len
    tok_secs = []
    for _ in range(num_iters):
        t0 = time.perf_counter()
        params, opt_state, loss = block(params, opt_state)
        float(loss)  # readback: block_until_ready can return early on
        # the tunneled backend (a 65M tok/s "iter" was recorded)
        dt = time.perf_counter() - t0
        tok_secs.append(
            global_batch * seq_len * num_batches_per_iter / dt)
    tok_secs, suspect = _sane_rates(tok_secs, flops_per_item=flops_per_token,
                                    n_chips=n)
    tok_mean = float(np.mean(tok_secs))
    return (tok_mean / n, tok_mean, float(np.std(tok_secs)),
            flops_per_token, None, float(loss), suspect)


def measure(model_name, devices, per_chip_batch, num_iters,
            num_batches_per_iter, dtype_name, image_size=224,
            norm_impl="tpu", conv0_s2d=False, unroll=1):
    """Train-step throughput on a dp mesh over ``devices``.

    Returns (per_chip_img_sec, img_sec_mean, img_sec_std, flops_per_img,
    xla_flops_per_img, final_loss, suspect)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvt
    from horovod_tpu.models import (InceptionV3, ResNet50, ResNet101,
                                    ResNet152, VGG16)
    from horovod_tpu.parallel.mesh import make_parallel_mesh

    n = len(devices)
    mesh = make_parallel_mesh(devices=devices, dp=n)
    dtype = jnp.float32 if dtype_name == "fp32" else jnp.bfloat16
    model_cls = {"resnet50": ResNet50, "resnet101": ResNet101,
                 "resnet152": ResNet152, "vgg16": VGG16,
                 "inception_v3": InceptionV3}[model_name]
    extra = ({"conv0_space_to_depth": True}
             if conv0_s2d and model_name.startswith("resnet") else {})
    model = model_cls(num_classes=1000, dtype=dtype, norm_impl=norm_impl,
                      **extra)

    global_batch = per_chip_batch * n
    rng = np.random.RandomState(0)
    images = jnp.asarray(
        rng.randn(global_batch, image_size, image_size, 3), dtype)
    labels = jnp.asarray(rng.randint(0, 1000, (global_batch,)))
    data_sharding = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    images = jax.device_put(images, data_sharding)
    labels = jax.device_put(labels, data_sharding)

    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, image_size, image_size, 3), dtype), train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    params = jax.device_put(params, repl)
    batch_stats = jax.device_put(batch_stats, repl)

    # reference benchmark uses SGD momentum 0.9 via hvd.DistributedOptimizer
    tx = hvt.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                  axis_name=None)  # pjit: XLA reduces
    opt_state = jax.device_put(tx.init(params), repl)

    def loss_fn(params, batch_stats):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, mutated["batch_stats"]

    def train_step(carry, _):
        params, batch_stats, opt_state = carry
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, new_bs, opt_state), loss

    def train_block_fn(params, batch_stats, opt_state):
        # num_batches_per_iter steps in one compiled program: one host
        # dispatch per timed block
        (params, batch_stats, opt_state), loss = lax.fori_loop(
            0, num_batches_per_iter,
            lambda i, c: train_step(c[0], None),
            ((params, batch_stats, opt_state), jnp.float32(0)),
            unroll=unroll)
        return params, batch_stats, opt_state, loss

    train_block = jax.jit(train_block_fn, donate_argnums=(0, 1, 2))

    lowered = train_block.lower(params, batch_stats, opt_state)
    compiled = lowered.compile()
    # MFU convention: theoretical model FLOPs (literature value, scaled by
    # resolution), not compiler accounting. XLA's cost analysis counts the
    # fori_loop body ONCE (verified) and uses its own conv accounting
    # (~1.9x the algorithmic count), so it is reported separately as a
    # cross-check, never fed into mfu.
    flops_per_img = (FLOPS_PER_IMG[model_name]
                     * (image_size / NATIVE_IMG_SIZE[model_name]) ** 2)
    total_flops = _compiled_flops(compiled)
    xla_flops_per_img = (total_flops / global_batch
                         if total_flops is not None else None)

    # warmup; forced readback (see _sane_rates)
    params, batch_stats, opt_state, loss = compiled(
        params, batch_stats, opt_state)
    float(loss)

    img_secs = []
    for _ in range(num_iters):
        t0 = time.perf_counter()
        params, batch_stats, opt_state, loss = compiled(
            params, batch_stats, opt_state)
        float(loss)  # readback, not block_until_ready (early returns)
        dt = time.perf_counter() - t0
        img_secs.append(global_batch * num_batches_per_iter / dt)

    img_secs, suspect = _sane_rates(img_secs, flops_per_item=flops_per_img,
                                    n_chips=n)
    img_sec_mean = float(np.mean(img_secs))
    img_sec_std = float(np.std(img_secs))
    return (img_sec_mean / n, img_sec_mean, img_sec_std, flops_per_img,
            xla_flops_per_img, float(loss), suspect)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet101", "resnet152",
                            "vgg16", "inception_v3", "gpt"])
    p.add_argument("--n-kv-heads", type=int, default=None,
                   help="gpt: grouped-query attention K/V head count "
                        "(default: n_heads=12, i.e. standard MHA; must "
                        "divide 12)")
    p.add_argument("--seq-len", type=int, default=1024,
                   help="sequence length for --model gpt")
    p.add_argument("--batch-size", type=int, default=None,
                   help="per-chip batch size. Defaults per model: 256 for "
                        "resnet (measured best on v5-lite: MFU 0.38 vs "
                        "0.34 at 128; bs 512 re-measured worse), 64 for "
                        "vgg16 and 128 for inception_v3 (HBM fit at "
                        "224/299), 8 for gpt (8x1024 tokens/chip/step)")
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--num-batches-per-iter", type=int, default=32,
                   help="training steps compiled into ONE program per "
                        "timed iter. Bigger amortizes the serialized "
                        "parameter-copy critical path across steps "
                        "(measured sweep, v5-lite: resnet50 2466 img/s "
                        "@10 -> 2574 @32 -> 2612 @128; gpt 83.3k tok/s "
                        "@10 -> 87.4k @32 -> 88.5k @64; 32 balances "
                        "gain vs runtime)")
    p.add_argument("--fp32", action="store_true",
                   help="use float32 instead of bfloat16")
    p.add_argument("--image-size", type=int, default=None,
                   help="square input resolution (default: the model's "
                        "native size — 224, or 299 for inception_v3; "
                        "smaller for CPU harness validation)")
    p.add_argument("--no-scaling", action="store_true",
                   help="skip the 1→N chip scaling sweep")
    p.add_argument("--flash", action="store_true",
                   help="gpt: pallas fused attention instead of the "
                        "einsum-softmax path")
    p.add_argument("--chunked-ce", action="store_true",
                   help="gpt: sequence-chunked fused cross-entropy — the "
                        "[B,S,V] logits tensor is never materialized "
                        "(ops/losses.py); frees HBM for larger batches")
    p.add_argument("--conv0-s2d", action="store_true",
                   help="resnet: numerically-identical space-to-depth "
                        "stem (224x224x3 7x7/2 conv -> 112x112x12 4x4/1; "
                        "the 3-channel stem starves the MXU contraction "
                        "lanes — classic public-MLPerf TPU fix)")
    p.add_argument("--unroll", type=int, default=1,
                   help="unroll factor for the steps-per-iter fori_loop: "
                        ">1 removes the while-loop barrier between steps "
                        "so XLA can overlap step i's optimizer/BN-stats "
                        "tail with step i+1's matmuls (compile time "
                        "grows with the factor)")
    p.add_argument("--bn-impl", default="tpu", choices=["tpu", "flax"],
                   help="resnet batch norm: 'tpu' = bf16-traffic "
                        "fp32-accumulated TpuBatchNorm (default), 'flax' "
                        "= stock nn.BatchNorm (fp32 statistics AND "
                        "normalization passes) for A/B comparison")
    p.add_argument("--force-cpu", nargs="?", const=2, default=None,
                   type=int, metavar="N",
                   help="run on an N-device virtual CPU mesh (default 2; "
                        "harness validation, and with N=8 the 1→2→4→8 "
                        "scaling-efficiency sweep exercises the metric of "
                        "record's full shape. CPU-mesh numbers are "
                        "RELATIVE-SHAPE-ONLY: virtual devices share one "
                        "host's cores, so per-chip efficiency conflates "
                        "collective overhead with core contention. The "
                        "JAX_PLATFORMS env var alone does not override "
                        "platform-pinning site plugins)")
    args = p.parse_args()

    import os

    if args.force_cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.force_cpu}")
    elif os.environ.get("HVT_SKIP_DEVICE_PROBE"):
        pass  # an outer pipeline (capture_r04.sh wait_sane) already gated
    else:
        # Tunneled TPU backends can wedge (jax.devices() then blocks
        # forever, and nothing downstream would ever report). Probe the
        # backend in a throwaway subprocess with a timeout, retrying a
        # few times, so a flaky tunnel either recovers or the bench fails
        # FAST with a diagnosable message instead of hanging the driver.
        # Skip when this process already initialized a backend (e.g. the
        # test harness pinning the CPU platform) — the probe subprocess
        # would see a different platform than the one in use.
        import subprocess

        try:
            from jax._src import xla_bridge as _xb
            already_up = bool(getattr(_xb, "_backends", None))
        except Exception:
            already_up = False
        # END-TO-END probe (compile + execute + readback), not a device
        # listing: during the round-3/4 outages jax.devices() kept
        # answering while every data-plane RPC blocked forever, so a
        # listing probe passed and the bench then hung for the driver's
        # whole timeout. benchmarks/tpu_sanity.py is the single home of
        # that probe (incl. the silent-CPU-fallback guard and the
        # rc=2 deterministic-vs-retryable taxonomy); the inline fallback
        # covers a bench.py copied out of the repo.
        sanity = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "benchmarks", "tpu_sanity.py")
        if os.path.exists(sanity):
            probe_cmd = [sys.executable, sanity]
        else:
            probe_cmd = [
                sys.executable, "-c",
                "import sys, jax, jax.numpy as jnp; "
                "d = jax.devices(); "
                "sys.exit(1) if d[0].platform == 'cpu' else None; "
                "float(jax.jit(lambda x: (x @ x).sum())("
                "jnp.ones((256, 256)))); "
                "print(len(d))"]
        for attempt in range(3 if not already_up else 0):
            try:
                probe = subprocess.run(probe_cmd, capture_output=True,
                                       text=True, timeout=150)
                if probe.returncode == 0:
                    break
                err = ((probe.stdout or "") + (probe.stderr or ""))\
                    .strip()[-500:]
                if probe.returncode == 2 or "ModuleNotFoundError" in err \
                        or "ImportError" in err:
                    # deterministic (broken install) — retrying can't help
                    sys.exit("device probe failed: " + err)
                # anything else (gRPC UNAVAILABLE, backend init error,
                # cpu fallback) is treated as transient and retried
            except subprocess.TimeoutExpired:
                err = "data-plane probe timed out after 150 s"
            print(f"# device probe attempt {attempt + 1}/3 failed: {err}",
                  file=sys.stderr)
            if attempt == 2:
                sys.exit(f"accelerator backend unreachable after 3 probes "
                         f"({err}); rerun when the TPU tunnel is back, or "
                         f"use --force-cpu for harness validation")
            time.sleep(30)

    import jax

    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvt

    hvt.init()
    devices = jax.devices()
    n = len(devices)
    platform = devices[0].platform
    dtype_name = "fp32" if args.fp32 else "bf16"

    gpt = args.model == "gpt"
    unit_item = "tok" if gpt else "img"

    def run_measure(devs, iters, bs):
        if gpt:
            return measure_gpt(devs, bs, iters, args.num_batches_per_iter,
                               dtype_name, args.seq_len,
                               use_flash=args.flash,
                               chunked_ce=args.chunked_ce,
                               n_kv_heads=args.n_kv_heads,
                               unroll=args.unroll)
        return measure(args.model, devs, bs, iters,
                       args.num_batches_per_iter, dtype_name,
                       args.image_size, norm_impl=args.bn_impl,
                       conv0_s2d=args.conv0_s2d, unroll=args.unroll)

    if not gpt and args.image_size is None:
        args.image_size = NATIVE_IMG_SIZE[args.model]
    bs = args.batch_size
    if bs is None:
        # per-model defaults; user values win. vgg16's early 224x64
        # activation maps are ~4x resnet's per image, inception runs at
        # 299 - both need smaller per-chip batches to fit HBM.
        bs = {"gpt": 8, "vgg16": 64, "inception_v3": 128}.get(
            args.model, 256)

    # Interleaved calibration: the in-harness matmul ceiling on a tunneled
    # rig drifts run-to-run (76 vs 111 TFLOP/s observed half an hour
    # apart), so one sample is not a ceiling — it's a coin flip. Bracket
    # the measurement with ≥3 calibration blocks, use the MEDIAN as the
    # MFU denominator, and report the spread so a drifting rig is visible
    # in the record instead of silently skewing the metric.
    calib_samples = [calibrate_matmul_tflops(platform)]

    (per_chip, rate_mean, rate_std, flops_per_item, xla_flops_per_img,
     loss, suspect) = run_measure(devices, args.num_iters, bs)
    print(f"# {args.model} bs={bs}/chip chips={n} "
          f"dtype={dtype_name}: "
          f"{rate_mean:.1f} +- {rate_std:.1f} {unit_item}/sec total, "
          f"{per_chip:.1f} {unit_item}/sec/chip, final loss {loss:.3f}",
          file=sys.stderr)

    calib_samples.append(calibrate_matmul_tflops(platform))

    # 1→N scaling sweep — metric of record (BASELINE.md): per-chip
    # throughput at n chips relative to 1 chip.
    sweep_n, sweep_eff = [1], [1.0]
    if not args.no_scaling and n > 1:
        sweep_n, per_chip_at = [], {}
        k = 1
        while k <= n:
            sweep_n.append(k)
            k *= 2
        if sweep_n[-1] != n:
            sweep_n.append(n)
        for k in sweep_n:
            if k == n:
                # headline measurement above already covers all chips
                per_chip_at[k] = per_chip
                continue
            sweep_res = run_measure(devices[:k],
                                    max(2, args.num_iters // 2), bs)
            pc = sweep_res[0]
            # a wedged sweep run must poison the whole record, not just
            # the headline (the efficiency ratios are built from it)
            suspect = suspect or sweep_res[6]
            per_chip_at[k] = pc
            print(f"# scaling: {k} chips → {pc:.1f} {unit_item}/sec/chip",
                  file=sys.stderr)
        sweep_eff = [round(per_chip_at[k] / per_chip_at[1], 4)
                     for k in sweep_n]

    if len(sweep_n) <= 1:
        # no sweep ran to separate samples 2 and 3 in time; pause so the
        # third sample still measures a distinct instant (median-of-3
        # rejects one drifted sample, median-of-2 cannot)
        time.sleep(10)
    calib_samples.append(calibrate_matmul_tflops(platform))
    import numpy as np

    # a 0.0 block means no rep survived the plausibility filter (wedged
    # probe) — exclude it from the median rather than dragging it down
    calib_valid = [c for c in calib_samples if c > 0] or [0.0]
    calib_tflops = float(np.median(calib_valid))
    # calibrate_matmul_tflops is >0 whenever the chain ran; a 0 can only
    # come from a stubbed harness — keep the record emittable anyway
    calib_spread = (float((max(calib_valid) - min(calib_valid))
                          / calib_tflops) if calib_tflops else None)
    achieved_tflops = per_chip * flops_per_item / 1e12
    mfu = achieved_tflops / calib_tflops if calib_tflops else None
    # Dual MFU (VERDICT r4 #3): `mfu` is utilization of the rig-local
    # MEASURED matmul ceiling (meaningful on a tunneled rig with dilated
    # wall clock); `mfu_vs_peak` divides by the chip's paper bf16 peak —
    # the conventional definition, comparable to external efficiency
    # tables (reference docs/benchmarks.rst:13-14). On this rig the two
    # differ ~2.6x; see BENCH_NOTES.md "Calibration-vs-paper gap".
    # A malformed/zero override must not crash here — this line runs
    # AFTER the whole measurement; losing the record to a bad env var
    # would discard a 40-minute TPU run. Fall back to the paper default.
    try:
        peak_tflops = float(os.environ.get("HVT_PEAK_TFLOPS",
                                           PAPER_PEAK_TFLOPS))
        if peak_tflops <= 0:
            raise ValueError("non-positive")
    except ValueError:
        print(f"# WARNING: bad HVT_PEAK_TFLOPS="
              f"{os.environ.get('HVT_PEAK_TFLOPS')!r}; using paper "
              f"default {PAPER_PEAK_TFLOPS}", file=sys.stderr)
        peak_tflops = PAPER_PEAK_TFLOPS
    mfu_vs_peak = (achieved_tflops / peak_tflops
                   if platform != "cpu" else None)

    # Telemetry snapshot embedded in the record (metrics subsystem): the
    # engine counters + dispatch histograms survive in the BENCH line
    # even when the driver's live probe fails. The eager allreduce of the
    # final loss is a real data-plane dispatch (multi-process: engine
    # ring; single-process: immediate path), so the record always carries
    # a populated hvt_collective_latency_seconds{op="allreduce"} series.
    from horovod_tpu import metrics as hvt_metrics

    try:
        loss = float(np.asarray(hvt.allreduce(
            np.float64(loss), name="bench_final_loss")))
        metrics_snapshot = hvt_metrics.json_snapshot()
    except Exception as e:  # telemetry must never cost us the record
        print(f"# WARNING: metrics snapshot failed: {e}", file=sys.stderr)
        metrics_snapshot = None
    print(f"# calib {calib_tflops:.1f} TFLOP/s/chip (median of "
          f"{len(calib_samples)} interleaved samples "
          f"{[round(c, 1) for c in calib_samples]}, spread "
          f"{'n/a' if calib_spread is None else format(calib_spread, '.1%')}"
          f"), achieved {achieved_tflops:.2f} "
          f"TFLOP/s/chip ({flops_per_item / 1e9:.2f} "
          f"GFLOP/{unit_item}), MFU "
          f"{'n/a' if mfu is None else format(mfu, '.3f')} vs measured "
          f"ceiling, "
          f"{'n/a' if mfu_vs_peak is None else format(mfu_vs_peak, '.3f')} "
          f"vs {peak_tflops:.0f} TFLOP/s paper peak",
          file=sys.stderr)

    print(json.dumps({
        "metric": f"{args.model}_synthetic_{unit_item}_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": f"{unit_item}/sec/chip",
        # Self-describing record (VERDICT r3 #4): without the config
        # echoed INSIDE the metric line, numbers from different harness
        # configurations look comparable when they are not (the r01 66.8k
        # vs r02 2.4k img/sec discontinuity — see BASELINE.md).
        "config": {
            "harness": HARNESS_VERSION,
            "model": args.model,
            "dtype": dtype_name,
            "batch_per_chip": bs,
            "steps_per_iter": args.num_batches_per_iter,
            "chips": n,
            "platform": platform,
            "unroll": args.unroll,
            **({"seq_len": args.seq_len, "flash": bool(args.flash),
                "chunked_ce": bool(args.chunked_ce),
                "n_kv_heads": args.n_kv_heads} if gpt else
               {"image_size": args.image_size, "bn_impl": args.bn_impl,
                "conv0_s2d": bool(args.conv0_s2d)}),
        },
        # GPT has no reference-published absolute number; the ResNet
        # baseline stays the reference's 103.55 img/s/device
        "vs_baseline": (round(per_chip / BASELINE_IMG_SEC_PER_DEVICE, 3)
                        if not gpt else None),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_vs_peak": (round(mfu_vs_peak, 4)
                        if mfu_vs_peak is not None else None),
        "peak_tflops": peak_tflops if platform != "cpu" else None,
        # True when every timing iter tripped the 1000-TFLOP/s/chip
        # plausibility bound: the value is NOT a measurement (wedged
        # backend); consumers must discard it (ADVICE r4, bench.py:105)
        "suspect": bool(suspect),
        "calib_tflops": round(calib_tflops, 2),
        "calib_spread": (round(calib_spread, 3)
                         if calib_spread is not None else None),
        "achieved_tflops": round(achieved_tflops, 3),
        f"flops_per_{unit_item}": round(flops_per_item / 1e9, 3),
        "xla_flops_per_img": (round(xla_flops_per_img / 1e9, 3)
                              if xla_flops_per_img is not None else None),
        # Registry snapshot (horovod_tpu.metrics): engine counters
        # (hvt_engine_cycles_total, hvt_cache_hits_total, ...) + dispatch
        # histograms ride inside the record — perf data keeps its
        # telemetry even when the live /metrics endpoint is unreachable
        "engine_metrics": metrics_snapshot,
        "scaling": {"n": sweep_n, "efficiency": sweep_eff,
                    # the sweep path itself is the metric of record
                    # (BASELINE.md, reference docs/benchmarks.rst:13);
                    # on a virtual CPU mesh the ratios conflate
                    # collective overhead with host-core contention
                    **({"caveat": "virtual CPU mesh: relative shape "
                                  "only, devices share one host's cores"}
                       if platform == "cpu" else {})},
    }))


# ---------------------------------------------------------------------------
# wire-codec convergence A/B (`bench.py --codec-ab`)
# ---------------------------------------------------------------------------
#
# Small REAL-training probe for the quantized wire codecs: 2 ranks run
# SGD on a least-squares problem whose gradient buffer carries a large
# constant "loss-scale" slot in its first 256-elem block — the fused-
# buffer shape real jobs put on the wire (tensor fusion mixes tensors
# of wildly different magnitudes into shared quantization blocks). That
# slot pins the block's absmax at every quantization stage (per-rank
# send, per-hop partial sums, the owner's roundtrip), so the true
# gradient components sharing its block sit permanently below the int8
# threshold: without error feedback they are zeroed EVERY step and
# their weights never train; the residual carry recovers them. The
# second block has no such slot and is the in-test control. Three
# configs share the identical deterministic problem: fp32 (codec off),
# int8+EF, int8−EF. The committed acceptance
# (benchmarks/r09_codec_sweep.json --check): int8+EF's final loss
# within noise of fp32, int8−EF measurably biased.


def _codec_ab_worker():
    import numpy as np

    import horovod_tpu as hvt

    hvt.init()
    r = hvt.rank()
    steps = int(os.environ.get("HVT_BENCH_AB_STEPS", "150"))
    d = 512                                  # 2 quantization blocks
    w_true = np.full(d, 0.15, np.float32)    # below the pinned threshold
    rng = np.random.RandomState(1000 + r)
    y = (w_true + rng.randn(d).astype(np.float32) * 0.01)  # rank's data
    w = np.zeros(d, np.float32)
    lr = 0.1
    aux = 100.0  # fused telemetry slot: pins block 0's absmax
    for _ in range(steps):
        g_local = (w - y).astype(np.float32)
        buf = np.concatenate(([np.float32(aux)], g_local))
        out = np.asarray(hvt.allreduce(buf, op=hvt.Average, name="grad"))
        g = out[1:]
        w = (w - lr * g).astype(np.float32)
    local_loss = 0.5 * float(np.mean((w - y) ** 2))
    losses = np.asarray(hvt.allgather(
        np.array([local_loss], np.float64), name="ab_loss"))
    if r == 0:
        print("HVT_AB_RESULT " + json.dumps(
            {"final_loss": float(losses.mean()),
             "pinned_block_coord": float(w[0]),
             "control_block_coord": float(w[400]),
             "steps": steps}), flush=True)
    hvt.shutdown()


def codec_ab_main(argv):
    """Drive the three-config A/B; prints one JSON line and optionally
    writes it (`--out`). CPU-only, ~seconds per config."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))

    def argval(flag, dflt):
        return argv[argv.index(flag) + 1] if flag in argv else dflt

    steps = argval("--steps", "150")
    out_path = argval("--out", "")
    configs = {
        "fp32": {},
        "int8_ef": {"HVT_WIRE_COMPRESSION": "int8",
                    "HVT_ERROR_FEEDBACK": "1"},
        "int8_noef": {"HVT_WIRE_COMPRESSION": "int8",
                      "HVT_ERROR_FEEDBACK": "0"},
    }
    record = {"harness": "codec_ab r1", "steps": int(steps),
              "configs": {}}
    for name, extra in configs.items():
        env = dict(os.environ)
        # the fp32 reference must actually be fp32: an ambient
        # HVT_WIRE_COMPRESSION / HVT_ERROR_FEEDBACK in the caller's
        # shell would leak into the baseline arm and collapse the A/B
        # deltas toward zero
        env.pop("HVT_WIRE_COMPRESSION", None)
        env.pop("HVT_ERROR_FEEDBACK", None)
        env.update({"HVT_BENCH_CODEC_AB": "1",
                    "HVT_BENCH_AB_STEPS": steps,
                    "HVT_SHM_ALLREDUCE": "0",  # the wire is under test
                    "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                    "XLA_FLAGS": "",
                    "PYTHONPATH": repo + os.pathsep
                    + env.get("PYTHONPATH", "") if env.get("PYTHONPATH")
                    else repo})
        env.update(extra)
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner.launch", "-np",
             "2", sys.executable, os.path.abspath(__file__)],
            env=env, cwd=repo, capture_output=True, text=True,
            timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(f"codec-ab config {name} failed:\n"
                               f"{proc.stdout}\n{proc.stderr}")
        for line in proc.stdout.splitlines():
            if "HVT_AB_RESULT" in line:
                record["configs"][name] = json.loads(
                    line.split("HVT_AB_RESULT ", 1)[1])
                break
        else:
            raise RuntimeError(f"no result line for {name}:\n"
                               f"{proc.stdout}")
        print(f"codec-ab {name}: "
              f"{record['configs'][name]['final_loss']:.6f}", flush=True)
    base = record["configs"]["fp32"]["final_loss"]
    record["delta_int8_ef"] = record["configs"]["int8_ef"][
        "final_loss"] - base
    record["delta_int8_noef"] = record["configs"]["int8_noef"][
        "final_loss"] - base
    print(json.dumps(record))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
    return record


if __name__ == "__main__":
    if os.environ.get("HVT_BENCH_CODEC_AB"):
        _codec_ab_worker()
    elif "--codec-ab" in sys.argv:
        codec_ab_main(sys.argv)
    else:
        main()
