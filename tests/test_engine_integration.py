"""Multi-process engine integration tests — the analog of the reference's
``test/parallel`` suite run under ``horovodrun -np 2`` on loopback
(``test/integration/test_static_run.py:182``). Each test launches real
processes through the hvtrun launcher and asserts on their exits/output."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")

# Per-pytest-process port base: two concurrent pytest invocations (e.g. a
# stress loop alongside a normal run) must not race for the same master
# port — rank 0's control/coordinator listener binds it exclusively. The
# pid spreads bases apart; _next_port() additionally probe-binds so a
# base collision degrades to a skipped port, not a failed test.
_PORT = [20000 + (os.getpid() * 641) % 10000]


def _next_port():
    import socket
    while True:
        _PORT[0] += 1
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", _PORT[0]))
                return _PORT[0]
            except OSError:
                continue


def run_workers(body, np=2, timeout=90, extra_env=None, expect_rc=0,
                launcher_args=()):
    """Write a worker script and launch it with hvtrun -np N."""
    _next_port()
    script = textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvt
        hvt.init()
        r, n = hvt.rank(), hvt.size()
        {textwrap.indent(textwrap.dedent(body), '        ').strip()}
        print(f"WORKER-{{r}}-DONE", flush=True)
        hvt.shutdown()
    """)
    path = f"/tmp/hvt_itest_{os.getpid()}_{_PORT[0]}.py"
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": ""})
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", str(np),
         "--master-port", str(_PORT[0]), *launcher_args,
         sys.executable, path],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == expect_rc, \
        f"rc={proc.returncode}\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    return proc.stdout + proc.stderr


def test_allreduce_average_2proc():
    out = run_workers("""
        x = np.full((5,), float(r + 1), np.float32)
        res = np.asarray(hvt.allreduce(x, name="t"))
        np.testing.assert_allclose(res, (1 + n) / 2.0)
    """)
    assert "WORKER-0-DONE" in out and "WORKER-1-DONE" in out


def test_dtypes_roundtrip_2proc():
    run_workers("""
        for dt in (np.float32, np.float64, np.int32, np.int64, np.float16):
            x = (np.arange(6) + r).astype(dt)
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"d{dt.__name__}"))
            expected = sum((np.arange(6) + i).astype(dt) for i in range(n))
            np.testing.assert_allclose(res.astype(np.float64),
                                       expected.astype(np.float64))
    """)


def test_allgather_uneven_2proc():
    run_workers("""
        rows = r + 1
        res = np.asarray(hvt.allgather(np.full((rows, 3), float(r),
                                       np.float32), name="ag"))
        assert res.shape == (3, 3), res.shape
        np.testing.assert_allclose(res[0], 0.0)
        np.testing.assert_allclose(res[1:], 1.0)
    """)


def test_alltoall_splits_2proc():
    run_workers("""
        splits = [1, 2]
        payload = np.asarray([[float(r)], [float(r) + 10], [float(r) + 10]],
                             np.float32)
        out, rsplits = hvt.alltoall(payload, splits=splits, name="a2a")
        out = np.asarray(out)
        if r == 0:
            assert list(rsplits) == [1, 1]
            np.testing.assert_allclose(out[:, 0], [0.0, 1.0])
        else:
            assert list(rsplits) == [2, 2]
            np.testing.assert_allclose(out[:, 0], [10.0, 10.0, 11.0, 11.0])
    """)


def test_consistency_error_not_hang_2proc():
    # reference behavior: cross-rank shape mismatch → per-tensor error
    # delivered to the caller, not a deadlock (controller.cc:481-706)
    run_workers("""
        try:
            hvt.allreduce(np.zeros((r + 2,), np.float32), name="bad")
            raise SystemExit("expected ValueError")
        except ValueError as e:
            assert "mismatched shape" in str(e)
    """)


def test_adasum_2proc():
    run_workers("""
        if r == 0:
            x = np.asarray([1.0, 0.0], np.float32)
        else:
            x = np.asarray([0.0, 1.0], np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Adasum, name="ada"))
        np.testing.assert_allclose(res, [1.0, 1.0], rtol=1e-5)
    """)


def test_adasum_start_level_2proc():
    """HVT_ADASUM_START_LEVEL: levels below it average instead of
    adasum-combining (reference GPU composition, adasum.h:177-183) — with
    2 ranks and start level 2, the result is the plain mean."""
    run_workers("""
        x = np.asarray([4.0, 0.0], np.float32) if r == 0 else \
            np.asarray([0.0, 2.0], np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Adasum, name="asl"))
        np.testing.assert_allclose(res, [2.0, 1.0], rtol=1e-6)
    """, extra_env={"HVT_ADASUM_START_LEVEL": "2"})


def test_join_with_cached_hit_does_not_starve_2proc():
    """Liveness pin: a rank announcing a CACHED HIT while the peer joins
    must still complete. The all-ranks-hit fast path can never fire once
    a rank is joined (it will never announce), so the coordinator must
    fold outstanding hits into slow-path negotiation whose required count
    excludes joined ranks (engine.cc Coordinate else-branch). Before that
    fold existed this wedged deterministically: step 1 caches 'g', rank 1
    joins, rank 0's second submit of 'g' is a hit that waits forever for
    a peer hit that cannot come."""
    run_workers("""
        # step 1: negotiate + cache 'g' on both ranks
        res = np.asarray(hvt.allreduce(np.ones((3,), np.float32),
                                       op=hvt.Sum, name="g"))
        np.testing.assert_allclose(res, 2.0)
        if r == 0:
            # step 2: identical params → cache hit, peer joined → zeros
            res = np.asarray(hvt.allreduce(np.ones((3,), np.float32),
                                           op=hvt.Sum, name="g"))
            np.testing.assert_allclose(res, 1.0)
        last = hvt.join()
        assert last == 0, last
    """)


def test_async_submit_then_join_pairs_with_late_peer_2proc():
    """Correctness pin (round-4 review finding): an announcement from a
    since-joined rank must NOT stand in for an active rank that never
    announced. Rank 1 submits 'g' async then joins; rank 0 submits 'g'
    later. The collective must pair BOTH submissions (each rank sees the
    full sum), not fire per-rank half-results: completion requires every
    ACTIVE participant individually seen (engine.cc slow-path all_seen),
    not a raw announcement count."""
    run_workers("""
        import time
        # step 1: negotiate + cache 'g' so rank 1's re-submit is a hit
        res = np.asarray(hvt.allreduce(np.ones((4,), np.float32),
                                       op=hvt.Sum, name="g"))
        np.testing.assert_allclose(res, 2.0)
        if r == 1:
            h = hvt.allreduce_async(np.full((4,), 5.0, np.float32),
                                    op=hvt.Sum, name="g")
            last = hvt.join()
            res = np.asarray(hvt.synchronize(h))
            np.testing.assert_allclose(res, 8.0)  # 5 (self) + 3 (rank 0)
        else:
            time.sleep(0.5)  # let rank 1's announce + join land first
            res = np.asarray(hvt.allreduce(np.full((4,), 3.0, np.float32),
                                           op=hvt.Sum, name="g"))
            np.testing.assert_allclose(res, 8.0)
            last = hvt.join()
        assert last == 0, last
    """)


def test_join_uneven_steps_2proc():
    # rank 1 runs fewer steps then joins; rank 0 keeps reducing
    # (reference Join semantics, operations.cc:1164)
    run_workers("""
        steps = 4 if r == 0 else 2
        for i in range(steps):
            res = np.asarray(hvt.allreduce(np.ones((3,), np.float32),
                                           op=hvt.Sum, name=f"step{i}"))
            if i < 2:
                np.testing.assert_allclose(res, 2.0)
            else:
                np.testing.assert_allclose(res, 1.0)  # peer joined → zeros
        last = hvt.join()
        assert last == 0, last  # rank 0 ran more steps → joined last
    """)


def test_broadcast_object_and_state_sync_2proc():
    run_workers("""
        obj = hvt.broadcast_object({"epoch": 3} if r == 0 else None,
                                   root_rank=0)
        assert obj == {"epoch": 3}
        objs = hvt.allgather_object(("rank", r))
        assert objs == [("rank", 0), ("rank", 1)]
    """)


def test_stall_inspector_warns():
    # rank 1 never submits "lonely"; rank 0 should see a stall warning, then
    # both proceed after rank 1 submits late
    out = run_workers("""
        import time
        if r == 0:
            h = hvt.allreduce_async(np.ones((2,), np.float32), name="lonely")
        time.sleep(2.5)
        if r == 1:
            h = hvt.allreduce_async(np.ones((2,), np.float32), name="lonely")
        res = np.asarray(hvt.synchronize(h))
        np.testing.assert_allclose(res, 1.0)
    """, launcher_args=("--stall-warning-sec", "1"))
    assert "possible stall" in out


def test_worker_crash_fails_job():
    # a worker exiting mid-collective must fail the whole job, not hang —
    # the engine surfaces peer loss as an error (HorovodInternalError path)
    out = run_workers("""
        if r == 1:
            os._exit(17)
        try:
            hvt.allreduce(np.ones((2,), np.float32), name="x")
        except Exception as e:
            print("GOT-ERROR", type(e).__name__, flush=True)
            raise SystemExit(1)
    """, expect_rc=1, timeout=60)
    assert "GOT-ERROR" in out or "ranks failed" in out


def test_allreduce_dtype_matrix_2proc():
    """Every wire dtype allreduces correctly (the reference sweeps dtypes
    across its parallel suites, e.g. test_torch.py/test_tensorflow.py)."""
    out = run_workers("""
        import ml_dtypes
        cases = [
            ("float32", np.float32, 1e-6),
            ("float64", np.float64, 1e-12),
            ("float16", np.float16, 1e-2),
            ("bfloat16", ml_dtypes.bfloat16, 1e-1),
            ("int32", np.int32, 0),
            ("int64", np.int64, 0),
            ("uint8", np.uint8, 0),
        ]
        for dname, dt, tol in cases:
            x = (np.arange(8) % 4 + r + 1).astype(dt)
            res = np.asarray(hvt.allreduce(x, name=f"dt.{dname}",
                                           average=False))
            expect = sum((np.arange(8) % 4 + rr + 1).astype(np.float64)
                         for rr in range(n))
            np.testing.assert_allclose(
                np.asarray(res, np.float64), expect, atol=float(tol),
                err_msg=dname)
            assert res.dtype == np.dtype(dt), (dname, res.dtype)
        print(f"DTYPES-OK-{r}", flush=True)
    """)
    assert "DTYPES-OK-0" in out and "DTYPES-OK-1" in out


def _run_raw(script_body, np_=4, extra_env=None, timeout=120):
    """Launch a raw worker script (no run_workers template) — for tests
    that must set per-rank env before hvt.init()."""
    _PORT[0] += 1
    path = f"/tmp/hvt_raw_{os.getpid()}_{_PORT[0]}.py"
    with open(path, "w") as f:
        f.write(script_body)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": ""})
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np",
         str(np_), "--master-port", str(_PORT[0]), sys.executable, path],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    return proc.stdout + proc.stderr


_HIER_BODY = f"""
import os, sys
sys.path.insert(0, {REPO!r})
rank = int(os.environ["HVT_PROCESS_ID"])
os.environ["HVT_TOPO_HOST"] = "hostA" if rank < 2 else "hostB"
import numpy as np
import horovod_tpu as hvt
hvt.init()
r, n = hvt.rank(), hvt.size()
assert n == 4
# integer payloads are exact in fp32: hierarchical must match the flat
# ring (and the analytic expectation) bitwise
for name, count in [("a", 1), ("b", 5), ("c", 64), ("d", 1000)]:
    x = (np.arange(count) % 7 + r + 1).astype(np.float32)
    res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=name))
    expect = sum(
        (np.arange(count) % 7 + rr + 1) for rr in range(n)).astype(
        np.float32)
    np.testing.assert_array_equal(res, expect)
# fused unit (several tensors in one cycle) through the same path
hs = [hvt.allreduce_async(np.full((16,), float(r + 1 + i), np.float32),
                          op=hvt.Sum, name=f"f{{i}}") for i in range(3)]
for i, h in enumerate(hs):
    np.testing.assert_array_equal(
        np.asarray(hvt.synchronize(h)),
        np.full((16,), float(sum(rr + 1 + i for rr in range(n))),
                np.float32))
mx = np.asarray(hvt.allreduce(np.float32([r]), op=hvt.Max, name="mx"))
np.testing.assert_array_equal(mx, [3.0])
avg = np.asarray(hvt.allreduce(np.full((8,), float(r + 1), np.float32),
                               name="avg"))
np.testing.assert_allclose(avg, 2.5)
d64 = np.asarray(hvt.allreduce(np.arange(10, dtype=np.float64) + r,
                               op=hvt.Sum, name="d64"))
np.testing.assert_array_equal(d64, np.arange(10, dtype=np.float64) * 4 + 6)
print(f"HIER-OK-{{r}}", flush=True)
hvt.shutdown()
"""


def test_hierarchical_allreduce_2x2_topology():
    """Faked 2-host x 2-slot topology (HVT_TOPO_HOST): the hierarchical
    backend (local reduce-scatter -> cross allreduce -> local allgather,
    reference nccl_operations.cc:188-350) must engage and produce results
    identical to the flat ring's."""
    out = _run_raw(_HIER_BODY, extra_env={"HVT_LOG_LEVEL": "info"})
    assert "hierarchical allreduce (2x2)" in out, out
    for r in range(4):
        assert f"HIER-OK-{r}" in out


def test_hierarchical_serves_reducescatter_2x2():
    """Reducescatter lowers to allreduce at the engine, so on a faked
    2-host topology it must ride the hierarchical decomposition and slice
    the right shard."""
    extra = """
rs = np.asarray(hvt.reducescatter(
    (np.arange(8, dtype=np.float32) + r).reshape(8, 1), op=hvt.Sum,
    name="hier.rs"))
full = sum((np.arange(8, dtype=np.float32) + rr).reshape(8, 1)
           for rr in range(n))
np.testing.assert_array_equal(rs, full[r * 2:(r + 1) * 2])
print(f"HIER-RS-OK-{r}", flush=True)
"""
    body = _HIER_BODY.replace("hvt.shutdown()", extra + "hvt.shutdown()")
    out = _run_raw(body, extra_env={"HVT_LOG_LEVEL": "info"})
    assert "hierarchical allreduce (2x2)" in out, out
    for r in range(4):
        assert f"HIER-RS-OK-{r}" in out


def test_hierarchical_disabled_falls_back_to_ring():
    """HVT_HIERARCHICAL_ALLREDUCE=0 keeps the ordered backend list on the
    ring fallback; results unchanged."""
    out = _run_raw(_HIER_BODY, extra_env={
        "HVT_LOG_LEVEL": "info", "HVT_HIERARCHICAL_ALLREDUCE": "0"})
    assert "hierarchical allreduce" not in out, out
    for r in range(4):
        assert f"HIER-OK-{r}" in out


def test_grouped_allreduce_single_ring_op_2proc():
    """A 3-tensor group must fuse into ONE data-plane collective even when
    the fusion threshold is too small for threshold-based fusion
    (deterministic group fusion, reference controller.cc:199-223)."""
    out = run_workers("""
        from horovod_tpu.engine import native
        base = native.engine_data_ops()
        xs = [np.full((4,), float(r + 1 + i), np.float32) for i in range(3)]
        res = hvt.grouped_allreduce(xs, op=hvt.Sum, name="grp")
        for i, t in enumerate(res):
            expect = sum(float(rr + 1 + i) for rr in range(n))
            np.testing.assert_allclose(np.asarray(t), expect)
        ops = native.engine_data_ops() - base
        assert ops == 1, f"expected 1 fused ring op for the group, got {ops}"
        print(f"GROUP-OK-{r}", flush=True)
    """, extra_env={"HVT_FUSION_THRESHOLD": "1"})
    assert "GROUP-OK-0" in out and "GROUP-OK-1" in out


def test_grouped_allreduce_disable_group_fusion_2proc():
    """HVT_DISABLE_GROUP_FUSION keeps group members un-merged (3 ring ops)
    while negotiation stays atomic."""
    out = run_workers("""
        from horovod_tpu.engine import native
        base = native.engine_data_ops()
        xs = [np.full((4,), float(i + 1), np.float32) for i in range(3)]
        res = hvt.grouped_allreduce(xs, op=hvt.Sum, name="grp")
        for i, t in enumerate(res):
            np.testing.assert_allclose(np.asarray(t), float(i + 1) * n)
        ops = native.engine_data_ops() - base
        assert ops == 3, f"expected 3 unmerged ring ops, got {ops}"
        print(f"NOFUSE-OK-{r}", flush=True)
    """, extra_env={"HVT_FUSION_THRESHOLD": "1",
                    "HVT_DISABLE_GROUP_FUSION": "1"})
    assert "NOFUSE-OK-0" in out and "NOFUSE-OK-1" in out


def test_grouped_member_mismatch_poisons_group_2proc():
    """A cross-rank shape mismatch on ONE member must error the WHOLE
    group (all-or-nothing), not deadlock the remaining members."""
    run_workers("""
        xs = [np.zeros((2,), np.float32),
              np.zeros((r + 2,), np.float32),   # mismatched across ranks
              np.zeros((2,), np.float32)]
        try:
            hvt.grouped_allreduce(xs, op=hvt.Sum, name="badgrp")
            raise SystemExit("expected ValueError")
        except ValueError as e:
            assert "mismatched shape" in str(e) or "aborted" in str(e), e
    """)


def test_process_sets_4proc():
    """Eager collectives over process subsets (later-lineage horovod
    ProcessSet semantics on the engine path): disjoint sets run
    concurrently; allgather/broadcast/alltoall/reducescatter follow the
    set's positional layout; non-members must not call."""
    out = run_workers("""
        from horovod_tpu.common.process_sets import ProcessSet
        evens = ProcessSet([0, 2])
        odds = ProcessSet([1, 3])
        mine = evens if r % 2 == 0 else odds

        # disjoint subset allreduces proceed concurrently
        x = np.full((4,), float(r + 1), np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="ps",
                                       process_set=mine))
        expect = (1 + 3) if r % 2 == 0 else (2 + 4)
        np.testing.assert_allclose(res, float(expect))

        # average divides by the SET size, not the world size
        avg = np.asarray(hvt.allreduce(x, name="psavg", process_set=mine))
        np.testing.assert_allclose(avg, expect / 2.0)

        # broadcast from a set-internal root (global rank id)
        root = 2 if r % 2 == 0 else 1
        b = np.full((3,), float(r), np.float32)
        bres = np.asarray(hvt.broadcast(b, root_rank=root, name="psb",
                                        process_set=mine))
        np.testing.assert_allclose(bres, float(root))

        # uneven allgather within the set (rows by set position)
        rows = (r // 2) + 1 if r % 2 == 0 else (r // 2) + 2
        g = np.full((rows, 2), float(r), np.float32)
        gres = np.asarray(hvt.allgather(g, name="psg", process_set=mine))
        if r % 2 == 0:
            assert gres.shape == (3, 2)   # ranks 0 (1 row) + 2 (2 rows)
            np.testing.assert_allclose(gres[:1], 0.0)
            np.testing.assert_allclose(gres[1:], 2.0)
        else:
            assert gres.shape == (5, 2)   # ranks 1 (2 rows) + 3 (3 rows)
            np.testing.assert_allclose(gres[:2], 1.0)
            np.testing.assert_allclose(gres[2:], 3.0)

        # non-member call is a loud local error
        other = odds if r % 2 == 0 else evens
        try:
            hvt.allreduce(x, name="bad", process_set=other)
            raise SystemExit("expected ValueError for non-member")
        except ValueError as e:
            assert "not in process set" in str(e)
        print(f"PS-OK-{r}", flush=True)
    """, np=4)
    for i in range(4):
        assert f"PS-OK-{i}" in out


def test_process_set_mismatch_errors_4proc():
    """Ranks disagreeing on a tensor's process set get a per-tensor
    ERROR (consistency check), not a hang. Sets [0,1,2] vs [1,2,3]
    overlap, so neither negotiation can ever complete — the conflict
    check must fire deterministically."""
    run_workers("""
        from horovod_tpu.common.process_sets import ProcessSet
        ps = ProcessSet([0, 1, 2]) if r < 2 else ProcessSet([1, 2, 3])
        try:
            hvt.allreduce(np.ones((2,), np.float32), name="mm",
                          process_set=ps)
            raise SystemExit("expected ValueError")
        except ValueError as e:
            assert "process set" in str(e), e
    """, np=4)


def test_process_set_conflict_spares_disjoint_set_5proc():
    """A cross-set conflict errors exactly its participants; a disjoint
    set legitimately reusing the tensor name completes normally."""
    out = run_workers("""
        from horovod_tpu.common.process_sets import ProcessSet
        if r == 0:
            ps = ProcessSet([0, 1])
        elif r == 1:
            ps = ProcessSet([1, 2])
        elif r == 2:
            ps = ProcessSet([0, 2])
        else:
            ps = ProcessSet([3, 4])
        if r < 3:
            try:
                hvt.allreduce(np.ones((2,), np.float32), name="t",
                              process_set=ps)
                raise SystemExit("expected ValueError")
            except ValueError as e:
                assert "conflicting process sets" in str(e), e
        else:
            res = np.asarray(hvt.allreduce(
                np.full((2,), float(r), np.float32), op=hvt.Sum,
                name="t", process_set=ps))
            np.testing.assert_allclose(res, 7.0)  # 3 + 4
        print(f"SPARE-OK-{r}", flush=True)
    """, np=5)
    for i in range(5):
        assert f"SPARE-OK-{i}" in out


def test_intra_set_error_spares_disjoint_set_4proc():
    """A consistency ERROR inside one process set (shape mismatch) must
    be member-targeted: a disjoint set reusing the name completes with
    correct data — regression for the untargeted-ERROR corruption."""
    out = run_workers("""
        import time
        from horovod_tpu.common.process_sets import ProcessSet
        if r < 2:
            ps = ProcessSet([0, 1])
            try:
                # shapes differ across ranks 0/1 → per-tensor ERROR
                hvt.allreduce(np.zeros((r + 2,), np.float32), name="t",
                              process_set=ps)
                raise SystemExit("expected ValueError")
            except ValueError as e:
                assert "mismatched shape" in str(e), e
        else:
            ps = ProcessSet([2, 3])
            if r == 3:
                time.sleep(0.3)   # straggler: entry pends while the
                                  # other set errors
            res = np.asarray(hvt.allreduce(
                np.full((2,), float(r), np.float32), op=hvt.Sum,
                name="t", process_set=ps))
            np.testing.assert_allclose(res, 5.0)  # 2 + 3, NOT zeroed
        print(f"SPARED-{r}", flush=True)
    """, np=4)
    for i in range(4):
        assert f"SPARED-{i}" in out


def test_grouped_conflicted_process_set_errors_not_hangs_4proc():
    """A fusion group containing a tensor with conflicting process sets
    must dissolve with errors on every member, not hold siblings
    forever."""
    run_workers("""
        from horovod_tpu.common.process_sets import ProcessSet
        ps = ProcessSet([0, 1, 2]) if r < 2 else ProcessSet([1, 2, 3])
        try:
            hvt.grouped_allreduce(
                [np.ones((2,), np.float32), np.ones((3,), np.float32)],
                op=hvt.Sum, name="gg", process_set=ps)
            raise SystemExit("expected ValueError")
        except ValueError as e:
            assert "process set" in str(e) or "aborted" in str(e), e
    """, np=4, timeout=60)


def test_tf_binding_tape_and_optimizer_2proc():
    """The TF binding's gradient plumbing over the real engine: tape
    gradients average across ranks; the optimizer wrapper applies reduced
    grads (numpy fakes stand in for tf objects — TF absent in image)."""
    out = run_workers("""
        import horovod_tpu.tensorflow as hvt_tf

        class FakeTape:
            def gradient(self, target, sources, output_gradients=None):
                return [np.full((4,), float(r + 1), np.float32), None]

        tape = hvt_tf.DistributedGradientTape(FakeTape())
        g0, g1 = tape.gradient("loss", ["w", "b"])
        np.testing.assert_allclose(np.asarray(g0), (1 + n) / 2.0)
        assert g1 is None

        class FakeOpt:
            applied = []
            def apply_gradients(self, gv, **kw):
                self.applied.append(list(gv))

        opt = hvt_tf.DistributedOptimizer(FakeOpt(),
                                          backward_passes_per_step=2)
        gr = np.full((3,), float(r), np.float32)
        assert opt.apply_gradients([(gr, "v")]) is None
        opt.apply_gradients([(gr, "v")])
        (applied,) = FakeOpt.applied
        # local sum over 2 passes, then cross-rank average: 2*mean(ranks)
        np.testing.assert_allclose(applied[0][0], 2 * (0 + 1) / 2.0)
        print(f"TF-OK-{r}", flush=True)
    """)
    assert "TF-OK-0" in out and "TF-OK-1" in out


def test_tf_real_tape_2proc():
    """Real tf.GradientTape through DistributedGradientTape over the
    engine: gradients average across ranks (requires tensorflow)."""
    import importlib.util

    if importlib.util.find_spec("tensorflow") is None:
        import pytest

        pytest.skip("tensorflow not installed")
    out = run_workers("""
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvt_tf

        w = tf.Variable([1.0, 2.0])
        with hvt_tf.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.reduce_sum(w * w) * float(r + 1)
        (g,) = tape.gradient(loss, [w])
        # local grad = 2w(r+1); average over ranks = 2w * mean(r+1)
        np.testing.assert_allclose(
            np.asarray(g), 2 * np.array([1.0, 2.0]) * (1 + n) / 2.0,
            rtol=1e-6)
        print(f"TFREAL-OK-{r}", flush=True)
    """, timeout=180)
    assert "TFREAL-OK-0" in out and "TFREAL-OK-1" in out


def test_tf_sync_batch_norm_global_stats_2proc():
    """TF SyncBatchNormalization over the engine: each rank's
    normalization must use the GLOBAL batch statistics (requires
    tensorflow)."""
    import importlib.util

    if importlib.util.find_spec("tensorflow") is None:
        import pytest

        pytest.skip("tensorflow not installed")
    out = run_workers("""
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvt_tf

        # UNEVEN batches: rank 0 has 2 rows of 0, rank 1 has 6 rows of
        # 8 → count-weighted global mean 6, var = 48/8·... E[x²]=48 →
        # var = 48 - 36 = 12 (equal-weight averaging would give mean 4)
        rows = 2 if r == 0 else 6
        x = tf.constant(np.full((rows, 3), float(r * 8), np.float32))
        bn = hvt_tf.SyncBatchNormalization(epsilon=1e-5)
        y = bn(x, training=True)
        expect = (r * 8 - 6.0) / np.sqrt(12.0 + 1e-5)
        np.testing.assert_allclose(y.numpy(), expect, rtol=1e-4)
        print(f"SBN-OK-{r}", flush=True)
    """, timeout=180)
    assert "SBN-OK-0" in out and "SBN-OK-1" in out


def test_sparse_allreduce_unequal_nnz_2proc():
    """Regression: average must divide by world size on every rank even
    when ranks contribute different row counts (allgatherv)."""
    out = run_workers("""
        from horovod_tpu.ops.sparse import sparse_allreduce
        if r == 0:
            idx = np.array([0], np.int32)
            vals = np.full((1, 2), 10.0, np.float32)
        else:
            idx = np.array([1, 2, 3], np.int32)
            vals = np.full((3, 2), 20.0, np.float32)
        gi, gv = sparse_allreduce(idx, vals, average=True, name="uneq")
        gi, gv = np.asarray(gi), np.asarray(gv)
        assert gi.shape[0] == 4
        np.testing.assert_allclose(gv[gi == 0], 5.0)
        np.testing.assert_allclose(gv[gi == 2], 10.0)
        print(f"UNEQ-OK-{r}", flush=True)
    """)
    assert "UNEQ-OK-0" in out and "UNEQ-OK-1" in out


def test_stall_inspector_warns_then_recovers_2proc():
    """Rank-0 stall watchdog (reference stall_inspector.h:30-96 /
    test_stall.py intent): when one rank lags on a tensor past
    HVT_STALL_WARN_SEC, rank 0 logs which ranks are missing; the
    collective still completes once the laggard submits."""
    out = run_workers("""
        import time
        if r == 1:
            time.sleep(2.5)   # rank 0 announces; rank 1 lags past warn
        res = np.asarray(hvt.allreduce(
            np.full((3,), float(r + 1), np.float32), name="laggy"))
        np.testing.assert_allclose(res, (1 + n) / 2.0)
    """, launcher_args=("--stall-warning-sec", "1"))
    assert "laggy" in out and "possible stall" in out, out[-2000:]
    assert "not by ranks [ 1 ]" in out, out[-2000:]


def test_shm_allreduce_single_host_2proc():
    """Single-host jobs pick the shared-memory data plane for allreduce
    (backend priority list: shm → hierarchical → ring); results match the
    ring exactly across dtypes."""
    out = run_workers("""
        for dt in (np.float32, np.float64, np.int32, np.float16):
            x = (np.arange(7) * (r + 1)).astype(dt)
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum,
                                           name=f"shm.{dt.__name__}"))
            expected = sum((np.arange(7) * (i + 1)).astype(dt)
                           for i in range(n))
            np.testing.assert_allclose(res.astype(np.float64),
                                       expected.astype(np.float64))
        # average path (postscale applied after the backend)
        a = np.asarray(hvt.allreduce(np.full(5, float(r + 1), np.float32),
                                     name="shm.avg"))
        np.testing.assert_allclose(a, (1 + n) / 2.0)
        # full-world broadcast rides the shm plane too (root publishes
        # once; non-members path still uses the ring)
        b = np.asarray(hvt.broadcast(np.full(6, float(r * 7 + 3),
                                             np.float32),
                                     root_rank=1, name="shm.bc"))
        np.testing.assert_allclose(b, 10.0)
        big = np.arange(1 << 20, dtype=np.float32) * (r + 1)
        bb = np.asarray(hvt.broadcast(big, root_rank=0, name="shm.bcbig"))
        np.testing.assert_allclose(bb, np.arange(1 << 20,
                                                 dtype=np.float32))
        # scalar (0-d) allgather: one row per rank, not garbage
        s = np.asarray(hvt.allgather(np.float32(r + 0.5), name="shm.sc"))
        np.testing.assert_allclose(s, [i + 0.5 for i in range(n)])
        # uneven allgather rides shm (single-copy concat from slots)
        g = np.asarray(hvt.allgather(np.full((r + 2, 3), float(r),
                                             np.float32), name="shm.ag"))
        assert g.shape == (2 * n + 1, 3), g.shape
        np.testing.assert_allclose(g[:2], 0.0)
        np.testing.assert_allclose(g[2:], 1.0)
        # reducescatter rides the shm allreduce (engine slices locally)
        rs = np.asarray(hvt.reducescatter(
            (np.arange(8, dtype=np.float32) + r).reshape(4, 2),
            op=hvt.Sum, name="shm.rs"))
        full = sum((np.arange(8, dtype=np.float32) + i).reshape(4, 2)
                   for i in range(n))
        np.testing.assert_allclose(rs, full[r * 2:(r + 1) * 2])
        # uneven alltoall rides shm (direct slot addressing)
        payload = np.asarray([[float(r)], [float(r) + 10],
                              [float(r) + 10]], np.float32)
        out2, rsp = hvt.alltoall(payload, splits=[1, 2], name="shm.a2a")
        out2 = np.asarray(out2)
        if r == 0:
            assert list(rsp) == [1, 1]
            np.testing.assert_allclose(out2[:, 0], [0.0, 1.0])
        else:
            assert list(rsp) == [2, 2]
            np.testing.assert_allclose(out2[:, 0],
                                       [10.0, 10.0, 11.0, 11.0])
    """, extra_env={"HVT_LOG_LEVEL": "debug"})
    assert "shm local data plane up" in out, out[-2000:]
    assert "shm allreduce engaged" in out, out[-2000:]
    assert "shm broadcast engaged" in out, out[-2000:]
    assert "shm allgather engaged" in out, out[-2000:]
    assert "shm alltoall engaged" in out, out[-2000:]


def test_shm_disabled_falls_back_to_ring_2proc():
    out = run_workers("""
        res = np.asarray(hvt.allreduce(np.full(4, float(r + 1),
                                               np.float32), name="noshm"))
        np.testing.assert_allclose(res, (1 + n) / 2.0)
    """, extra_env={"HVT_LOG_LEVEL": "debug", "HVT_SHM_ALLREDUCE": "0"})
    assert "shm local data plane up" not in out, out[-2000:]


def test_shm_allreduce_4proc_grouped_and_large():
    """4 ranks through the shm plane: grouped fusion + a payload big
    enough to span chunk boundaries."""
    run_workers("""
        big = (np.arange(100003) % 97).astype(np.float32) + r
        res = np.asarray(hvt.allreduce(big, op=hvt.Sum, name="shm.big"))
        expected = sum((np.arange(100003) % 97).astype(np.float32) + i
                       for i in range(n))
        np.testing.assert_allclose(res, expected)
        outs = hvt.grouped_allreduce(
            [np.full(3, float(r), np.float32),
             np.full(2, float(10 * r), np.float32)], op=hvt.Sum,
            name="shm.grp")
        np.testing.assert_allclose(np.asarray(outs[0]),
                                   sum(range(n)))
        np.testing.assert_allclose(np.asarray(outs[1]),
                                   10.0 * sum(range(n)))
    """, np=4)


_SHM_SUBSET_BODY = """
    from horovod_tpu.common.process_sets import ProcessSet
    evens, odds = ProcessSet([0, 2]), ProcessSet([1, 3])
    mine = evens if r % 2 == 0 else odds
    pos = mine.ranks.index(r)

    # subset allreduce (disjoint sets concurrent — distinct barrier cells)
    x = np.full((5,), float(r + 1), np.float32)
    res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="sshm.ar",
                                   process_set=mine))
    np.testing.assert_allclose(res, float(sum(i + 1 for i in mine.ranks)))

    # subset broadcast (root = global rank)
    b = np.asarray(hvt.broadcast(np.full(4, float(r), np.float32),
                                 root_rank=mine.ranks[1], name="sshm.bc",
                                 process_set=mine))
    np.testing.assert_allclose(b, float(mine.ranks[1]))

    # subset uneven allgather (rows by set position)
    g = np.asarray(hvt.allgather(np.full((pos + 1, 2), float(r),
                                         np.float32), name="sshm.ag",
                                 process_set=mine))
    assert g.shape == (3, 2), g.shape
    np.testing.assert_allclose(g[:1], float(mine.ranks[0]))
    np.testing.assert_allclose(g[1:], float(mine.ranks[1]))

    # subset uneven alltoall (splits by set position)
    payload = np.asarray([[float(10 * r)], [float(10 * r) + 1],
                          [float(10 * r) + 1]], np.float32)
    out2, rsp = hvt.alltoall(payload, splits=[1, 2], name="sshm.a2a",
                             process_set=mine)
    out2 = np.asarray(out2)
    peers = mine.ranks
    if pos == 0:
        assert list(rsp) == [1, 1], rsp
        np.testing.assert_allclose(out2[:, 0],
                                   [10.0 * peers[0], 10.0 * peers[1]])
    else:
        assert list(rsp) == [2, 2], rsp
        np.testing.assert_allclose(
            out2[:, 0], [10.0 * peers[0] + 1, 10.0 * peers[0] + 1,
                         10.0 * peers[1] + 1, 10.0 * peers[1] + 1])

    # subset reducescatter (native chunk reduce on the shm plane)
    rs = np.asarray(hvt.reducescatter(
        (np.arange(8, dtype=np.float32) + r).reshape(4, 2), op=hvt.Sum,
        name="sshm.rs", process_set=mine))
    full = sum((np.arange(8, dtype=np.float32) + i).reshape(4, 2)
               for i in mine.ranks)
    np.testing.assert_allclose(rs, full[pos * 2:(pos + 1) * 2])

    # full-world reducescatter also runs the native chunk path
    rsw = np.asarray(hvt.reducescatter(
        (np.arange(8, dtype=np.float32) * (r + 1)).reshape(4, 2),
        op=hvt.Sum, name="sshm.rsw"))
    fullw = sum((np.arange(8, dtype=np.float32) * (i + 1)).reshape(4, 2)
                for i in range(n))
    np.testing.assert_allclose(rsw, fullw[r:r + 1])
"""


def test_shm_serves_subsets_and_native_reducescatter_4proc():
    """Process-subset collectives and reduce-scatter ride the shm plane
    (VERDICT r2 #6; reference operation_manager.cc serves every op from
    the selected backend): per-group barrier cells, direct slot reads,
    native chunk reduce for reducescatter."""
    out = run_workers(_SHM_SUBSET_BODY, np=4,
                      extra_env={"HVT_LOG_LEVEL": "debug"})
    assert "shm local data plane up" in out, out[-2000:]
    assert "shm subset collective engaged" in out, out[-2000:]
    assert "shm reducescatter engaged (native chunk" in out, out[-2000:]


def test_subset_collectives_identical_without_shm_4proc():
    """Same program with the shm plane disabled: the ring group paths must
    produce identical results (backend choice is invisible to callers)."""
    out = run_workers(_SHM_SUBSET_BODY, np=4,
                      extra_env={"HVT_LOG_LEVEL": "debug",
                                 "HVT_SHM_ALLREDUCE": "0"})
    assert "shm local data plane up" not in out, out[-2000:]


def test_shm_subset_full_world_interleaved_4proc():
    """Stress the progress-word barrier: odd ranks skip the even-subset
    response and run ahead into the next full-world collective while the
    subset is still in flight — a shared-counter barrier would be
    polluted (premature release / lost arrivals); progress words keyed
    to the global response sequence stay sound."""
    run_workers("""
        from horovod_tpu.common.process_sets import ProcessSet
        evens = ProcessSet([0, 2])
        for i in range(30):
            if r % 2 == 0:
                x = np.full((257,), float(r + i), np.float32)
                res = np.asarray(hvt.allreduce(x, op=hvt.Sum,
                                               name=f"il.e.{i}",
                                               process_set=evens))
                np.testing.assert_allclose(res, 2.0 * i + 2.0)
            w = np.asarray(hvt.allreduce(
                np.full((64,), float(r + 1), np.float32), op=hvt.Sum,
                name=f"il.w.{i}"))
            np.testing.assert_allclose(
                w, float(sum(k + 1 for k in range(n))))
    """, np=4, extra_env={"HVT_LOG_LEVEL": "debug"})
