"""DistributedOptimizer tests — gradient reduction semantics
(reference ``test/parallel/test_torch.py`` optimizer tests +
``tensorflow/gradient_aggregation.py`` behavior)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.ops.compression import Compression
from horovod_tpu.parallel.mesh import WORLD_AXIS

N = 8


def _sgd_step_fn(tx, mesh, params_shape=(3,)):
    # check_vma=False: the aggregation cond mixes varying/invariant values
    # (see DistributedGradientTransformation docstring).
    def step(params, opt_state, grads_per_rank):
        def inner(p, s, g):
            updates, new_s = tx.update(g[0], s, p)
            new_p = optax.apply_updates(p, updates)
            return new_p, new_s

        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(), P(WORLD_AXIS)),
            out_specs=(P(), P()), check_vma=False)(
                params, opt_state, grads_per_rank)

    return jax.jit(step)


def test_distributed_sgd_averages_gradients(world_mesh):
    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name=WORLD_AXIS)
    params = jnp.zeros((3,))
    opt_state = tx.init(params)
    rng = np.random.RandomState(0)
    grads = rng.randn(N, 3).astype(np.float32)
    step = _sgd_step_fn(tx, world_mesh)
    new_params, _ = step(params, opt_state, grads)
    np.testing.assert_allclose(np.asarray(new_params),
                               -grads.mean(axis=0), rtol=1e-5)


def test_distributed_sgd_sum_op(world_mesh):
    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name=WORLD_AXIS,
                                  op=hvt.Sum)
    params = jnp.zeros((3,))
    opt_state = tx.init(params)
    grads = np.ones((N, 3), np.float32)
    step = _sgd_step_fn(tx, world_mesh)
    new_params, _ = step(params, opt_state, grads)
    np.testing.assert_allclose(np.asarray(new_params), -N * np.ones(3),
                               rtol=1e-5)


def test_gradient_predivide_factor(world_mesh):
    # predivide splits the averaging between pre and post scaling
    # (reference tensorflow/__init__.py:578-590); result == plain average
    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name=WORLD_AXIS,
                                  gradient_predivide_factor=2.0)
    params = jnp.zeros((3,))
    opt_state = tx.init(params)
    rng = np.random.RandomState(1)
    grads = rng.randn(N, 3).astype(np.float32)
    step = _sgd_step_fn(tx, world_mesh)
    new_params, _ = step(params, opt_state, grads)
    np.testing.assert_allclose(np.asarray(new_params),
                               -grads.mean(axis=0), rtol=1e-5)


def test_compression_roundtrip(world_mesh):
    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name=WORLD_AXIS,
                                  compression=Compression.fp16)
    params = jnp.zeros((3,))
    opt_state = tx.init(params)
    grads = np.full((N, 3), 0.5, np.float32)
    step = _sgd_step_fn(tx, world_mesh)
    new_params, _ = step(params, opt_state, grads)
    assert new_params.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(new_params), -0.5 * np.ones(3),
                               rtol=1e-3)


def test_backward_passes_per_step(world_mesh):
    # accumulate 2 steps locally, apply on the 2nd
    # (reference gradient_aggregation.py:16)
    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name=WORLD_AXIS,
                                  backward_passes_per_step=2)
    params = jnp.zeros((3,))
    opt_state = tx.init(params)
    grads = np.ones((N, 3), np.float32)
    step = _sgd_step_fn(tx, world_mesh)
    p1, s1 = step(params, opt_state, grads)
    # first call: held — no update applied
    np.testing.assert_allclose(np.asarray(p1), 0.0)
    p2, s2 = step(p1, s1, grads)
    # second call: sum of 2 accumulated unit grads, averaged over ranks = 2
    np.testing.assert_allclose(np.asarray(p2), -2.0 * np.ones(3), rtol=1e-5)
    # counter keeps cycling
    p3, s3 = step(p2, s2, grads)
    np.testing.assert_allclose(np.asarray(p3), np.asarray(p2))


def test_backward_passes_average_aggregated(world_mesh):
    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name=WORLD_AXIS,
                                  backward_passes_per_step=2,
                                  average_aggregated_gradients=True)
    params = jnp.zeros((3,))
    opt_state = tx.init(params)
    grads = np.ones((N, 3), np.float32)
    step = _sgd_step_fn(tx, world_mesh)
    p1, s1 = step(params, opt_state, grads)
    p2, _ = step(p1, s1, grads)
    np.testing.assert_allclose(np.asarray(p2), -1.0 * np.ones(3), rtol=1e-5)


def test_adam_state_held_between_aggregation_steps(world_mesh):
    # the inner optimizer state must NOT advance on held steps
    tx = hvt.DistributedOptimizer(optax.adam(0.1), axis_name=WORLD_AXIS,
                                  backward_passes_per_step=3)
    params = jnp.zeros((2,))
    opt_state = tx.init(params)
    grads = np.ones((N, 2), np.float32)
    step = _sgd_step_fn(tx, world_mesh, params_shape=(2,))
    p, s = step(params, opt_state, grads)
    inner_count_after_1 = int(np.asarray(
        jax.tree.leaves(s.inner_state)[0]))
    p, s = step(p, s, grads)
    p, s = step(p, s, grads)
    # after 3 calls exactly one inner update happened
    counts = [x for x in jax.tree.leaves(s.inner_state)
              if np.asarray(x).ndim == 0]
    assert inner_count_after_1 == 0
    assert int(np.asarray(counts[0])) == 1


def test_partial_distributed_optimizer(world_mesh):
    tx = hvt.PartialDistributedGradientTransformation(
        optax.sgd(1.0), local_layers=("local",), axis_name=WORLD_AXIS)
    params = {"shared": jnp.zeros((2,)), "local": jnp.zeros((2,))}
    opt_state = tx.init(params)

    def step(params, opt_state, grads_per_rank):
        def inner(p, s, g):
            updates, new_s = tx.update(
                jax.tree.map(lambda x: x[0], g), s, p)
            new_p = optax.apply_updates(p, updates)
            # local params legitimately differ per shard → per-shard output
            return new_p["shared"], new_p["local"][None]

        return jax.shard_map(
            inner, mesh=world_mesh,
            in_specs=(P(), P(), {"shared": P(WORLD_AXIS),
                                 "local": P(WORLD_AXIS)}),
            out_specs=(P(), P(WORLD_AXIS)),
            check_vma=False)(params, opt_state, grads_per_rank)

    grads = {"shared": np.ones((N, 2), np.float32),
             "local": np.arange(2 * N, dtype=np.float32).reshape(N, 2)}
    shared_p, local_p = jax.jit(step)(params, opt_state, grads)
    # shared: averaged (= 1); local: each shard applied its own grad
    np.testing.assert_allclose(np.asarray(shared_p), -1.0)
    np.testing.assert_allclose(np.asarray(local_p), -grads["local"],
                               rtol=1e-6)


def test_grad_of_replicated_params_not_double_counted(world_mesh):
    # Under default shard_map (check_vma=True) AD already psums grads of
    # replicated params; the optimizer must divide, not re-reduce.
    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name=WORLD_AXIS)
    rng = np.random.RandomState(5)
    X = rng.randn(N, 4).astype(np.float32)

    def per_shard(p, s, x):
        loss_fn = lambda p: jnp.mean((p * x[0]) ** 2)
        g = jax.grad(loss_fn)(p)
        updates, s = tx.update(g, s, p)
        return optax.apply_updates(p, updates), s

    params = jnp.asarray(2.0)
    opt_state = tx.init(params)
    f = jax.jit(jax.shard_map(per_shard, mesh=world_mesh,
                              in_specs=(P(), P(), P(WORLD_AXIS)),
                              out_specs=(P(), P())))
    new_p, _ = f(params, opt_state, X)
    per_shard_grads = np.array([np.mean(2 * 2.0 * x * x) for x in X])
    np.testing.assert_allclose(float(new_p),
                               2.0 - per_shard_grads.mean(), rtol=1e-5)


def test_grad_predivide_with_vma_reduced_grads(world_mesh):
    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name=WORLD_AXIS,
                                  gradient_predivide_factor=4.0)
    rng = np.random.RandomState(6)
    X = rng.randn(N, 4).astype(np.float32)

    def per_shard(p, s, x):
        g = jax.grad(lambda p: jnp.mean((p * x[0]) ** 2))(p)
        updates, s = tx.update(g, s, p)
        return optax.apply_updates(p, updates), s

    params = jnp.asarray(1.0)
    opt_state = tx.init(params)
    f = jax.jit(jax.shard_map(per_shard, mesh=world_mesh,
                              in_specs=(P(), P(), P(WORLD_AXIS)),
                              out_specs=(P(), P())))
    new_p, _ = f(params, opt_state, X)
    per_shard_grads = np.array([np.mean(2 * 1.0 * x * x) for x in X])
    np.testing.assert_allclose(float(new_p),
                               1.0 - per_shard_grads.mean(), rtol=1e-5)


def test_allreduce_gradients_no_axis_is_local():
    from horovod_tpu.jax import allreduce_gradients

    g = {"w": jnp.ones((2, 2))}
    out = jax.jit(lambda g: allreduce_gradients(g, axis_name=None))(g)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
