"""Topology / lifecycle tests (reference test lineage:
``test/parallel/test_tensorflow.py`` rank/size tests)."""

import jax
import pytest

import horovod_tpu as hvt


def test_initialized():
    assert hvt.is_initialized()


def test_size_is_device_count():
    assert hvt.size() == jax.device_count() == 8


def test_local_size():
    assert hvt.local_size() == jax.local_device_count() == 8


def test_rank_is_first_local_slot():
    assert hvt.rank() == 0


def test_cross_topology():
    assert hvt.cross_size() == 1
    assert hvt.cross_rank() == 0
    assert hvt.process_size() == 1
    assert hvt.process_rank() == 0


def test_homogeneous():
    assert hvt.is_homogeneous()


def test_build_info():
    # TPU build: XLA data plane always present; GPU/vendor backends absent.
    from horovod_tpu.common import basics

    assert basics.xla_built()
    assert not hvt.nccl_built()
    assert not hvt.cuda_built() if hasattr(hvt, "cuda_built") else True
    assert not hvt.mpi_built()
    assert isinstance(hvt.gloo_built(), bool)


def test_init_with_comm_rejected():
    with pytest.raises(ValueError):
        hvt.init(comm=[0, 1])


def test_process_sets():
    ps = hvt.add_process_set([0, 2])
    assert ps.process_set_id is not None
    assert hvt.process_set_included_ranks(ps.process_set_id) == [0, 2]
    assert ps.size() == 2
    assert ps.rank_in_set(2) == 1
    groups = ps.axis_index_groups(8)
    assert groups[0] == [0, 2]
    assert sorted(groups[0] + groups[1]) == list(range(8))
    hvt.remove_process_set(ps)


def test_global_process_set():
    assert hvt.global_process_set.process_set_id == 0
    assert hvt.global_process_set.included()
    assert hvt.global_process_set.size() == 8


def test_auto_name_counter_resets_for_elastic_rounds():
    """Survivors of an elastic round re-init through shutdown(); the
    auto-name counter must restart with them or their anonymous
    collectives can never pair with a respawned worker's (observed live
    as `hvt.allreduce.7` vs `hvt.allreduce.1` stalling a recovered
    gang)."""
    from horovod_tpu.engine import api

    before = api._name_seq
    assert api._auto_name("allreduce", None) == \
        f"hvt.allreduce.{before + 1}"
    api._group_seq += 1
    api.reset_auto_names()
    assert api._name_seq == 0 and api._group_seq == 0
    assert api._auto_name("allreduce", None) == "hvt.allreduce.1"
