"""Distributed flight recorder tests: engine event ring → per-rank
timeline shards → cross-rank merge, plus the stall-diagnostics surfaces
(``hvt.diagnostics()`` / ``GET /debugz``).

The gang tests launch real 2-process jobs through hvtrun (same harness
as ``test_engine_integration``); the unit tests cover shard parsing,
merging, and the rendezvous endpoints in-process.
"""

import json
import os

import pytest

import horovod_tpu as hvt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

needs_engine = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


# ------------------------------------------------------------- gang tests

@needs_engine
def test_gang_timeline_merge(tmp_path):
    """hvtrun -np 2 --timeline out.json produces ONE loadable chrome
    trace with distinct pids and engine-sourced EXEC events from both
    ranks (ISSUE 2 acceptance criterion)."""
    from tests.test_engine_integration import run_workers

    out = str(tmp_path / "out.json")
    run_workers("""
        for i in range(3):
            x = np.full((4,), float(r + 1), np.float32)
            res = np.asarray(hvt.allreduce(x, name=f"t{i}", average=True))
            np.testing.assert_allclose(res, (1 + n) / 2.0)
    """, launcher_args=("--timeline", out))

    with open(out) as f:
        events = json.load(f)
    assert events, "merged timeline is empty"
    pids = {e.get("pid") for e in events if "pid" in e}
    assert {0, 1} <= pids, f"expected both ranks in merged trace: {pids}"
    # engine-thread EXEC events (ring-sourced) from EVERY rank
    exec_pids = {e["pid"] for e in events
                 if e.get("ph") == "B" and e.get("name") == "ALLREDUCE"}
    assert exec_pids == {0, 1}, exec_pids
    # per-tensor engine lanes + eager dispatch lanes both present
    lanes = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert "t0 (engine)" in lanes and "t0" in lanes, lanes
    assert any(e.get("name", "").startswith("EAGER_ALLREDUCE")
               for e in events)
    # negotiation happens on the coordinator
    assert any(e.get("name") == "NEGOTIATE_ALLREDUCE" and e["pid"] == 0
               for e in events)
    # every pid is named for the chrome process selector
    named = {e["pid"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert {0, 1} <= named


@needs_engine
def test_gang_stall_diagnostics():
    """A deliberately stalled gang: the tensor is submitted only on rank
    0; diagnostics() on the coordinator must name it, its missing rank,
    and the wait — and the hvt_stall_missing_ranks metric must carry it
    (ISSUE 2 acceptance criterion)."""
    from tests.test_engine_integration import run_workers

    out = run_workers("""
        import time
        if r == 0:
            h = hvt.allreduce_async(np.ones(4, np.float32), name="stalled")
            deadline = time.time() + 30
            d = None
            while time.time() < deadline:
                d = hvt.diagnostics()
                stalls = d.get("stalls") or []
                hit = [s for s in stalls if s["tensor"] == "stalled"]
                if hit and hit[0]["missing_ranks"] == [1] \\
                        and hit[0]["arrived_ranks"] == [0] \\
                        and hit[0]["waiting_sec"] > 1.0:
                    break
                time.sleep(0.25)
            else:
                raise AssertionError(f"stall not diagnosed: {d}")
            assert any(p["tensor"] == "stalled" for p in d["pending"]), d
            from horovod_tpu import metrics
            text = metrics.prometheus_text()
            assert 'hvt_stall_missing_ranks{tensor="stalled"} 1' in text, \\
                text
            print("STALL-DIAG-OK", flush=True)
            res = np.asarray(hvt.synchronize(h))
        else:
            time.sleep(8)  # past the 1 s stall threshold + rank 0's check
            res = np.asarray(hvt.allreduce(np.ones(4, np.float32),
                                           name="stalled"))
        np.testing.assert_allclose(res, 1.0)
    """, timeout=120, launcher_args=("--stall-warning-sec", "1"))
    assert "STALL-DIAG-OK" in out


@needs_engine
def test_gang_analyzer_breakdown(tmp_path):
    """Critical-path analyzer on a REAL 2-proc flight-recorded gang
    (ISSUE 7 acceptance criterion): the report must carry a positive
    queue/wire/reduce/exec breakdown whose total engine-execution time
    fits the measured wall time, a straggler ranking, and per-lane
    percentiles. shm is disabled so the TCP duplex pump records WIRE
    spans; mark-cycles is on so control-plane bytes land in the trace."""
    from horovod_tpu.tools import hvt_analyze
    from tests.test_engine_integration import run_workers

    out = str(tmp_path / "out.json")
    run_workers("""
        x = np.arange(1 << 12, dtype=np.float32)
        for i in range(10):
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="ana.hot"))
        np.testing.assert_allclose(res, x * n)
    """, launcher_args=("--timeline", out),
        extra_env={"HVT_SHM_ALLREDUCE": "0",
                   "HVT_TIMELINE_MARK_CYCLES": "1"})

    rep = hvt_analyze.analyze_paths([out])
    assert rep["ranks"] == [0, 1]
    assert rep["instances"] >= 16  # ~10 per rank, truncation-tolerant
    ph = rep["phases"]
    for phase in ("queue", "wire", "reduce", "exec", "e2e"):
        assert phase in ph, f"phase {phase} missing: {sorted(ph)}"
        assert ph[phase]["p50"] >= 0
        assert ph[phase]["max"] > 0 or phase == "reduce"
    assert ph["exec"]["p50"] > 0 and ph["wire"]["p50"] > 0
    # durations are real time, not fabrications: the summed engine
    # execution cannot exceed the measured wall time per rank
    wall = rep["wall_us"]
    assert wall > 0
    exec_total = ph["exec"]["mean"] * ph["exec"]["count"]
    assert exec_total <= wall * len(rep["ranks"]) * 1.05
    # per-instance physics: wire fits inside exec (reduce = exec − wire)
    assert ph["wire"]["p50"] <= ph["exec"]["max"]
    # straggler ranking exists (cold negotiations of the first submits)
    assert rep["negotiations_scored"] >= 1
    assert rep["stragglers"] and "rank" in rep["stragglers"][0]
    # per-lane percentiles: only the global lane in this gang
    assert rep["lanes"]["0"]["count"] == ph["exec"]["count"]
    # mark-cycles shards carry the control-plane byte instants
    assert rep["cycles"]["ctrl_tx_bytes"] > 0
    assert rep["metrics"]["exec_us_p50"] > 0


@needs_engine
def test_gang_debugz_pending_lane():
    """The diagnostics pending table names the engine lane of each
    stuck entry (PR 6 serving lanes are otherwise unattributable from
    a stall snapshot)."""
    from tests.test_engine_integration import run_workers

    out = run_workers("""
        import time
        if r == 0:
            h = hvt.allreduce_async(np.ones(4, np.float32), name="lstall")
            deadline = time.time() + 30
            while time.time() < deadline:
                d = hvt.diagnostics()
                hit = [p for p in d.get("pending", [])
                       if p["tensor"] == "lstall"]
                if hit:
                    assert hit[0]["lane"] == 0, hit  # global set
                    print("PENDING-LANE-OK", flush=True)
                    break
                time.sleep(0.1)
            else:
                raise AssertionError(f"pending entry never surfaced: {d}")
            res = np.asarray(hvt.synchronize(h))
        else:
            time.sleep(3)
            res = np.asarray(hvt.allreduce(np.ones(4, np.float32),
                                           name="lstall"))
        np.testing.assert_allclose(res, 1.0)
    """, timeout=120)
    assert "PENDING-LANE-OK" in out


# ------------------------------------------------------------- unit tests

def test_diagnostics_shape_without_gang():
    d = hvt.diagnostics()
    assert "engine" in d and "process_rank" in d
    assert isinstance(d.get("pending", []), list)
    assert isinstance(d.get("stalls", []), list)


def test_parse_trace_tolerates_truncation(tmp_path):
    """Crash-safety: a SIGKILLed writer leaves no closing ']' and
    possibly a torn last line; the loader must keep every intact
    event."""
    from horovod_tpu.utils import timeline as tl

    good = [{"ph": "B", "pid": 0, "tid": 0, "ts": 1.0, "name": "X"},
            {"ph": "E", "pid": 0, "tid": 0, "ts": 2.0}]
    # no closing bracket, trailing comma
    truncated = "[\n" + ",\n".join(json.dumps(e) for e in good) + ",\n"
    assert tl.parse_trace(truncated) == good
    # torn final line
    torn = truncated + '{"ph": "B", "pid": 0, "ti'
    assert tl.parse_trace(torn) == good
    p = tmp_path / "shard.json"
    p.write_text(torn)
    assert tl.load_trace(str(p)) == good


def test_merge_traces_pids_and_order():
    from horovod_tpu.utils import timeline as tl

    s0 = [{"name": "process_name", "ph": "M", "pid": 0,
           "args": {"name": "rank 0"}},
          {"ph": "B", "pid": 0, "tid": 0, "ts": 10.0, "name": "A"}]
    s1 = [{"ph": "B", "pid": 1, "tid": 0, "ts": 5.0, "name": "B"}]
    merged = tl.merge_traces([s0, s1])
    # metadata first; pid 1 got a synthesized process_name
    metas = [e for e in merged if e.get("ph") == "M"]
    assert {e["pid"] for e in metas} == {0, 1}
    rest = [e for e in merged if e.get("ph") != "M"]
    assert [e["ts"] for e in rest] == [5.0, 10.0]


def test_merge_cli(tmp_path):
    from horovod_tpu.utils import timeline as tl

    shards = []
    for r in range(2):
        p = tmp_path / f"shard{r}.json"
        p.write_text(json.dumps(
            [{"ph": "i", "pid": r, "tid": 0, "ts": float(r), "name": "E",
              "s": "t"}]))
        shards.append(str(p))
    out = str(tmp_path / "merged.json")
    assert tl._main(["merge", "-o", out] + shards) == 0
    merged = json.load(open(out))
    assert {e.get("pid") for e in merged} == {0, 1}


def test_rendezvous_clock_and_debugz():
    import time
    import urllib.request

    from horovod_tpu.runner.http_client import get_json, put_bytes
    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.runner.hosts import HostInfo, get_host_assignments

    srv = RendezvousServer()
    srv.init(get_host_assignments([HostInfo("localhost", 2)], 2))
    port = srv.start(0)
    try:
        clock = get_json(f"127.0.0.1:{port}", "/clock")
        assert abs(clock["epoch_us"] - time.time_ns() / 1e3) < 60e6
        put_bytes(f"127.0.0.1:{port}", "/kv/debugz/1",
                  json.dumps({"stalls": [{"tensor": "g",
                                          "missing_ranks": [0]}]}).encode())
        put_bytes(f"127.0.0.1:{port}", "/kv/timeline/1", b"[]")
        dz = get_json(f"127.0.0.1:{port}", "/debugz")
        assert dz["world"]["size"] == 2
        assert dz["ranks"]["1"]["stalls"][0]["tensor"] == "g"
        assert dz["timeline_shards"] == ["1"]
    finally:
        srv.stop()


def test_clock_offset_handshake():
    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.runner.hosts import HostInfo, get_host_assignments
    from horovod_tpu.utils import timeline as tl

    srv = RendezvousServer()
    srv.init(get_host_assignments([HostInfo("localhost", 1)], 1))
    port = srv.start(0)
    try:
        off = tl.measure_clock_offset_us(f"127.0.0.1:{port}", samples=3)
        # same host, same clock: the offset is bounded by the RTT
        assert abs(off) < 1e6, off
    finally:
        srv.stop()


def test_engine_event_abi():
    """The ctypes mirror of hvt::EventView must match the C struct size
    (a silent drift would scramble every drained event)."""
    import ctypes

    from horovod_tpu.engine import native

    assert ctypes.sizeof(native.EngineEvent) == 96
    assert native.EVENT_KINDS[0] == "ENQUEUED"
    assert native.EVENT_KINDS[9] == "STALL"
    # drain on an idle/uninitialized engine is safe and empty-ish
    assert isinstance(native.drain_events(16), list)
    assert native.events_dropped() >= 0
