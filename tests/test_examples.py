"""Example smoke tests — the analog of the reference CI running its
examples as smoke jobs (``.buildkite/gen-pipeline.sh:135-173``). Each
example runs as a real subprocess with tiny shapes on the CPU platform;
the multi-process ones go through the actual ``hvtrun`` launcher."""

import os
import subprocess
import sys

import pytest

from tests.test_engine_integration import LIB, REPO, _PORT

TF_OPS_LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build",
                          "libhvt_tf_ops.so")


def _run_example(argv, timeout=300, np_procs=None, extra_env=None):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": "", "TF_CPP_MIN_LOG_LEVEL": "3",
                "PYTHONPATH": REPO + os.pathsep
                + env.get("PYTHONPATH", "")})
    env.update(extra_env or {})
    if np_procs:
        _PORT[0] += 1
        cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
               "-np", str(np_procs), "--master-port", str(_PORT[0]),
               sys.executable, *argv]
    else:
        cmd = [sys.executable, *argv]
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"{argv}\nrc={proc.returncode}\nstdout:\n{proc.stdout[-3000:]}\n" \
        f"stderr:\n{proc.stderr[-3000:]}"
    return proc.stdout + proc.stderr


def test_jax_synthetic_benchmark_smoke():
    out = _run_example(
        ["examples/jax/jax_synthetic_benchmark.py", "--batch-size", "2",
         "--num-iters", "1", "--num-batches-per-iter", "1",
         "--image-size", "32", "--fp32"])
    assert "img/sec" in out, out[-1000:]


def test_jax_gpt_train_smoke_dp_tp():
    out = _run_example(
        ["examples/jax/jax_gpt_train.py", "--dp", "2", "--tp", "2",
         "--steps", "2", "--batch", "2", "--seq", "32"],
        extra_env={"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=4"})
    assert "loss" in out.lower(), out[-1000:]


@pytest.mark.skipif(not os.path.exists(LIB),
                    reason="C++ engine not built")
def test_torch_synthetic_benchmark_smoke_2proc():
    out = _run_example(
        ["examples/torch/pytorch_synthetic_benchmark.py",
         "--batch-size", "4", "--num-iters", "1",
         "--num-batches-per-iter", "2"], np_procs=2)
    assert "img/sec" in out or "sec" in out, out[-1000:]


@pytest.mark.skipif(not os.path.exists(TF_OPS_LIB),
                    reason="TF op library not built")
def test_tf_function_train_smoke_2proc():
    out = _run_example(["examples/tensorflow/tf_function_train.py"],
                       np_procs=2, timeout=420)
    assert "loss" in out, out[-1000:]


@pytest.mark.skipif(not os.path.exists(TF_OPS_LIB),
                    reason="TF op library not built")
def test_tf_tape_train_smoke_2proc():
    out = _run_example(["examples/tensorflow/tf_tape_train.py"],
                       np_procs=2, timeout=420)
    assert "loss" in out, out[-1000:]


@pytest.mark.skipif(not os.path.exists(TF_OPS_LIB),
                    reason="TF op library not built")
def test_tf_elastic_train_smoke_2proc():
    out = _run_example(["examples/tensorflow/tf_elastic_train.py"],
                       np_procs=2, timeout=420)
    assert "epoch 4" in out, out[-1500:]


@pytest.mark.skipif(not os.path.exists(LIB),
                    reason="C++ engine not built")
def test_torch_imagenet_resnet50_smoke_2proc():
    # fp16 compression + grouped fusion + local aggregation — the
    # BASELINE.json torch-ImageNet config (reference
    # examples/pytorch/pytorch_imagenet_resnet50.py) at smoke scale
    out = _run_example(
        ["examples/torch/pytorch_imagenet_resnet50.py", "--width", "8",
         "--image-size", "32", "--batch-size", "4", "--epochs", "1",
         "--steps-per-epoch", "2", "--batches-per-allreduce", "2"],
        np_procs=2, timeout=420)
    assert "img/sec" in out, out[-1000:]


@pytest.mark.skipif(not os.path.exists(LIB),
                    reason="C++ engine not built")
def test_torch_elastic_train_smoke_2proc():
    # reference examples/elastic/pytorch analog: TorchState +
    # @hvd.elastic.run over the real launcher in elastic mode
    _PORT[0] += 1
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": "",
                "PYTHONPATH": REPO + os.pathsep
                + env.get("PYTHONPATH", "")})
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "-np", "2", "--min-np", "2", "--max-np", "3",
           "--master-port", str(_PORT[0]), sys.executable,
           "examples/elastic/pytorch_elastic_train.py",
           "--epochs", "3", "--batches-per-epoch", "2"]
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=420)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    assert "done: epochs=3" in proc.stdout + proc.stderr


@pytest.mark.skipif(not os.path.exists(TF_OPS_LIB),
                    reason="TF op library not built")
def test_keras_mnist_smoke_2proc():
    # BASELINE.json Keras-MNIST config (reference
    # examples/tensorflow2/tensorflow2_keras_mnist.py): full callback
    # set through real model.fit
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        out = _run_example(
            ["examples/keras/keras_mnist.py", "--epochs", "2",
             "--batch-size", "8", "--steps-per-epoch", "2",
             "--checkpoint-dir", d],
            np_procs=2, timeout=420)
    assert "final loss" in out, out[-1500:]


@pytest.mark.skipif(not os.path.exists(TF_OPS_LIB),
                    reason="TF op library not built")
def test_keras_synthetic_benchmark_smoke_2proc():
    # reference tensorflow2_keras_synthetic_benchmark.py analog:
    # tape + DistributedOptimizer.apply_gradients throughput loop
    out = _run_example(
        ["examples/keras/keras_synthetic_benchmark.py", "--small",
         "--batch-size", "4", "--image-size", "32",
         "--num-warmup-batches", "1", "--num-batches-per-iter", "2",
         "--num-iters", "1"],
        np_procs=2, timeout=420)
    assert "Img/sec" in out, out[-1500:]


def test_jax_long_context_train_smoke():
    out = _run_example(
        ["examples/jax/jax_long_context_train.py", "--sp", "4", "--seq",
         "128", "--steps", "4", "--batch", "1", "--fp32"],
        extra_env={"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=4"})
    assert "final loss" in out and "flash=on" in out, out[-1000:]
