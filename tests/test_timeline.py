"""Timeline tests (reference ``test/parallel/test_timeline.py`` runs a job
with HOROVOD_TIMELINE and validates the JSON)."""

import json

import numpy as np

import horovod_tpu as hvt
from horovod_tpu.utils import timeline


def test_timeline_produces_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "timeline.json")
    hvt.start_timeline(path, mark_cycles=True)
    timeline.negotiate_start("grad/w", "ALLREDUCE")
    timeline.negotiate_end("grad/w")
    timeline.activity_start("grad/w", "MEMCPY_IN_FUSION_BUFFER")
    timeline.activity_end("grad/w")
    timeline.activity_start("grad/b", "XLA_ALLREDUCE")
    timeline.activity_end("grad/b")
    timeline.mark_cycle()
    hvt.stop_timeline()

    with open(path) as f:
        events = json.load(f)
    assert isinstance(events, list) and events
    names = {e.get("args", {}).get("name") for e in events
             if e.get("ph") == "M"}
    assert {"grad/w", "grad/b"} <= names
    assert any(e.get("name") == "NEGOTIATE_ALLREDUCE" for e in events)
    assert any(e.get("name") == "CYCLE_START" for e in events)
    # B/E events must balance per lane
    for tid in {e["tid"] for e in events if e.get("ph") in "BE"}:
        b = sum(1 for e in events if e.get("tid") == tid and e["ph"] == "B")
        e_ = sum(1 for e in events if e.get("tid") == tid and e["ph"] == "E")
        assert b == e_


def test_timeline_arms_xla_profiler_session(tmp_path):
    """SURVEY §5.1: start_timeline() must also open an XLA/PJRT profiler
    session so compiled-path device activity is captured alongside the
    engine control-plane trace — one command, both views."""
    import glob

    import jax
    import jax.numpy as jnp

    path = str(tmp_path / "tl.json")
    hvt.start_timeline(path)
    # run a compiled step inside the session so the xplane has content
    jax.jit(lambda x: (x @ x).sum())(jnp.ones((64, 64))).block_until_ready()
    hvt.stop_timeline()

    # chrome trace written...
    with open(path) as f:
        json.load(f)
    # ...and a populated xplane trace directory next to it
    produced = glob.glob(str(tmp_path / "tl.json.xplane") + "/**/*",
                         recursive=True)
    assert any(p.endswith(".xplane.pb") or "trace" in p.lower()
               for p in produced), produced


def test_mark_cycle_dedicated_lane(tmp_path):
    """Cycle instants must live on their own metadata-named lane with
    the rank's pid — not collide with tensor lane 0 (ISSUE 2
    satellite)."""
    path = str(tmp_path / "cyc.json")
    timeline.start(path, mark_cycles=True, xla_profiler=False, pid=3)
    timeline.activity_start("tensor0", "WORK")
    timeline.activity_end("tensor0")
    timeline.mark_cycle()
    timeline.stop()

    with open(path) as f:
        events = json.load(f)
    lanes = {e["tid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    cycle = [e for e in events if e.get("name") == "CYCLE_START"]
    assert cycle, events
    assert lanes[cycle[0]["tid"]] == "CYCLE"
    assert lanes[cycle[0]["tid"]] != lanes[
        [e for e in events if e.get("name") == "WORK"][0]["tid"]]
    # every event carries the rank's pid
    assert {e["pid"] for e in events} == {3}
    # the process is named for chrome's process selector
    assert any(e.get("name") == "process_name"
               and e["args"]["name"] == "rank 3" for e in events)


def test_timeline_flushes_before_close(tmp_path):
    """Crash-safety: events must be readable from the shard while the
    timeline is still recording (periodic flush), so a SIGKILLed worker
    loses at most the last unflushed batch."""
    import time

    path = str(tmp_path / "flush.json")
    timeline.start(path, xla_profiler=False)
    timeline.activity_start("t", "STEP")
    timeline.activity_end("t")
    deadline = time.time() + 5
    events = []
    while time.time() < deadline:
        with open(path) as f:
            events = timeline.parse_trace(f.read())
        if any(e.get("name") == "STEP" for e in events):
            break
        time.sleep(0.05)
    timeline.stop()
    assert any(e.get("name") == "STEP" for e in events), events


def test_timeline_start_stop_idempotent(tmp_path):
    path = str(tmp_path / "t2.json")
    hvt.start_timeline(path)
    hvt.start_timeline(path)  # second start is a no-op (ref: returns DUPLICATE)
    hvt.stop_timeline()
    hvt.stop_timeline()
    assert not timeline.active()


def test_engine_timeline_chrome_trace(tmp_path):
    """2-process engine job with HVT_TIMELINE: the coordinator writes a
    valid chrome trace containing the per-tensor NEGOTIATE and execute
    lifecycle (reference test/parallel/test_timeline.py)."""
    import os

    import pytest

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lib = os.path.join(REPO, "horovod_tpu", "csrc", "build",
                       "libhvt_core.so")
    if not os.path.exists(lib):
        pytest.skip("C++ engine not built")
    from tests.test_engine_integration import run_workers

    tl_path = str(tmp_path / "engine_timeline.json")
    run_workers("""
        for i in range(3):
            x = np.full((4,), float(r + 1), np.float32)
            res = np.asarray(hvt.allreduce(x, name=f"t{i}", average=True))
            np.testing.assert_allclose(res, (1 + n) / 2.0)
    """, extra_env={"HVT_TIMELINE": tl_path,
                    "HVT_TIMELINE_MARK_CYCLES": "1"})
    with open(tl_path) as f:
        events = json.load(f)
    assert events, "engine timeline is empty"
    lane_names = {e.get("args", {}).get("name") for e in events
                  if e.get("ph") == "M"}
    assert {"t0", "t1", "t2"} <= lane_names
    assert any(e.get("name") == "NEGOTIATE_ALLREDUCE" for e in events)
    assert any(e.get("name") == "ALLREDUCE" for e in events)
    assert any(e.get("name", "").startswith("RANK_READY_")
               for e in events)
    assert any(e.get("name") == "CYCLE_START" for e in events)
    for tid in {e["tid"] for e in events if e.get("ph") in "BE"}:
        b = sum(1 for e in events if e.get("tid") == tid
                and e["ph"] == "B")
        e_ = sum(1 for e in events if e.get("tid") == tid
                 and e["ph"] == "E")
        assert b == e_, f"unbalanced B/E in lane {tid}"
