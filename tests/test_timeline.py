"""Timeline tests (reference ``test/parallel/test_timeline.py`` runs a job
with HOROVOD_TIMELINE and validates the JSON)."""

import json

import numpy as np

import horovod_tpu as hvt
from horovod_tpu.utils import timeline


def test_timeline_produces_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "timeline.json")
    hvt.start_timeline(path, mark_cycles=True)
    timeline.negotiate_start("grad/w", "ALLREDUCE")
    timeline.negotiate_end("grad/w")
    timeline.activity_start("grad/w", "MEMCPY_IN_FUSION_BUFFER")
    timeline.activity_end("grad/w")
    timeline.activity_start("grad/b", "XLA_ALLREDUCE")
    timeline.activity_end("grad/b")
    timeline.mark_cycle()
    hvt.stop_timeline()

    with open(path) as f:
        events = json.load(f)
    assert isinstance(events, list) and events
    names = {e.get("args", {}).get("name") for e in events
             if e.get("ph") == "M"}
    assert {"grad/w", "grad/b"} <= names
    assert any(e.get("name") == "NEGOTIATE_ALLREDUCE" for e in events)
    assert any(e.get("name") == "CYCLE_START" for e in events)
    # B/E events must balance per lane
    for tid in {e["tid"] for e in events if e.get("ph") in "BE"}:
        b = sum(1 for e in events if e.get("tid") == tid and e["ph"] == "B")
        e_ = sum(1 for e in events if e.get("tid") == tid and e["ph"] == "E")
        assert b == e_


def test_timeline_start_stop_idempotent(tmp_path):
    path = str(tmp_path / "t2.json")
    hvt.start_timeline(path)
    hvt.start_timeline(path)  # second start is a no-op (ref: returns DUPLICATE)
    hvt.stop_timeline()
    hvt.stop_timeline()
    assert not timeline.active()
