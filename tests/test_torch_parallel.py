"""Multi-process PyTorch binding tests over the C++ engine — the analog of
reference ``test/parallel/test_torch.py`` run under ``horovodrun -np 2``."""

import os

import pytest

from tests.test_engine_integration import LIB, run_workers

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


def run_torch_workers(body, np=2, **kw):
    import textwrap

    return run_workers(
        "import torch\nimport horovod_tpu.torch as hvd\n"
        + textwrap.dedent(body), np=np, **kw)


def test_torch_allreduce_average():
    run_torch_workers("""
        x = torch.full((4,), float(r + 1))
        y = hvd.allreduce(x, name="t")
        assert torch.allclose(y, torch.full((4,), (1 + n) / 2.0)), y
    """)


def test_torch_allreduce_autograd_backward_is_allreduce():
    run_torch_workers("""
        x = torch.ones(3, requires_grad=True)
        y = hvd.allreduce(x * (r + 1), name="t", op=hvd.Sum)
        y.sum().backward()
        # d(sum over ranks)/dx on each rank = n * (r+1)
        assert torch.allclose(x.grad, torch.full((3,), float(n * (r + 1)))), x.grad
    """)


def test_torch_allgather_uneven():
    run_torch_workers("""
        x = torch.full((r + 1, 2), float(r))
        y = hvd.allgather(x, name="g")
        assert y.shape[0] == sum(i + 1 for i in range(n)), y.shape
        off = 0
        for i in range(n):
            assert torch.allclose(y[off:off + i + 1], torch.full((i + 1, 2), float(i)))
            off += i + 1
    """)


def test_torch_broadcast():
    run_torch_workers("""
        x = torch.full((3,), float(r + 7))
        y = hvd.broadcast(x, root_rank=0, name="b")
        assert torch.allclose(y, torch.full((3,), 7.0)), y
    """)


def test_torch_alltoall():
    run_torch_workers("""
        x = torch.arange(n, dtype=torch.float32) + r * 10
        y = hvd.alltoall(x, name="a")
        expect = torch.tensor([float(i * 10 + r) for i in range(n)])
        assert torch.allclose(y, expect), (y, expect)
    """)


def test_torch_distributed_optimizer_averages_grads():
    run_torch_workers("""
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 2, bias=False)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=1.0),
            named_parameters=model.named_parameters())
        w0 = model.weight.detach().clone()
        # rank-dependent input => rank-dependent local grads
        x = torch.full((2, 4), float(r + 1))
        model(x).sum().backward()
        opt.step()
        # grad of sum wrt W is x^T-ish: each row grad = sum over batch of x
        # local grad value = 2*(r+1); averaged = mean over ranks
        avg = sum(2.0 * (i + 1) for i in range(n)) / n
        expect = w0 - avg
        assert torch.allclose(model.weight.detach(), expect, atol=1e-5), \
            (model.weight, expect)
    """)


def test_torch_broadcast_object_and_allgather_object():
    run_torch_workers("""
        obj = hvd.broadcast_object({"epoch": r + 5}, root_rank=0)
        assert obj == {"epoch": 5}, obj
        objs = hvd.allgather_object(("rank", r))
        assert objs == [("rank", i) for i in range(n)], objs
    """)


def test_torch_sync_batch_norm_global_stats():
    run_torch_workers("""
        sbn = hvd.SyncBatchNorm(1, momentum=1.0)
        sbn.train()
        # rank r contributes constant (r+1); global mean = (1+..+n)/n
        x = torch.full((2, 1, 2), float(r + 1))
        out = sbn(x)
        gmean = sum(i + 1 for i in range(n)) / n
        assert abs(sbn.running_mean.item() - gmean) < 1e-4, sbn.running_mean
    """)


def test_torch_elastic_state_sync_from_root():
    run_torch_workers("""
        torch.manual_seed(r)  # deliberately different weights per rank
        model = torch.nn.Linear(3, 3)
        state = hvd.elastic.TorchState(model=model, epoch=r)
        state.sync()
        assert state.epoch == 0, state.epoch
        ws = hvd.allgather(model.weight.detach().reshape(1, -1), name="wg")
        assert torch.allclose(ws[0], ws[1]), "weights not synced"
    """)


def test_torch_broadcast_optimizer_state_asymmetric():
    """Root has stepped (non-empty state), workers are fresh — the exact
    scenario that deadlocks if ranks branch on local state emptiness."""
    run_torch_workers("""
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 2)
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        if r == 0:  # only root materializes optimizer state
            model(torch.randn(2, 4)).sum().backward()
            opt.step()
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        sd = opt.state_dict()
        assert sd["state"], "worker did not receive optimizer state"
        step0 = sd["state"][0]["step"]
        steps = hvd.allgather(torch.as_tensor(step0).reshape(1), name="st")
        assert torch.allclose(steps[0], steps[1]), steps
    """, timeout=120)


def test_torch_adasum_optimizer_converges_across_ranks():
    run_torch_workers("""
        torch.manual_seed(0)
        model = torch.nn.Linear(2, 1, bias=False)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1), op=hvd.Adasum)
        x = torch.full((2, 2), float(r + 1))
        model(x).pow(2).mean().backward()
        opt.step()  # must not hang: adasum names identical across ranks
        ws = hvd.allgather(model.weight.detach().reshape(1, -1), name="w")
        assert torch.allclose(ws[0], ws[1]), ws
    """, timeout=120)


def test_torch_join_with_allgather_trailing_dims():
    """Joined rank has no entry; transfer sizes must still match (the
    coordinator now ships `trailing` in the Response)."""
    run_torch_workers("""
        if r == 0:
            x = torch.arange(8, dtype=torch.float32).reshape(2, 4)
            y = hvd.allgather(x, name="jg")
            assert y.shape == (2, 4), y.shape
            assert torch.allclose(y, x)
        joined = hvd.join()
        assert joined >= 0
    """, timeout=120)


def test_torch_elastic_sampler_shards_across_ranks():
    run_torch_workers("""
        sampler = hvd.elastic.ElasticSampler(list(range(12)), shuffle=False)
        mine = torch.tensor(sorted(iter(sampler)))
        all_idx = hvd.allgather(mine, name="idx")
        assert sorted(all_idx.tolist()) == list(range(12)), all_idx
    """)


def test_torch_allreduce_process_set_4proc():
    """Torch collectives honor process subsets end to end over the
    engine (per-set negotiation + sub-ring data plane)."""
    run_torch_workers("""
        from horovod_tpu.common.process_sets import ProcessSet
        mine = ProcessSet([0, 2]) if r % 2 == 0 else ProcessSet([1, 3])
        x = torch.full((3,), float(r + 1))
        y = hvd.allreduce(x, name="pst", op=hvd.Sum, process_set=mine)
        expect = (1 + 3) if r % 2 == 0 else (2 + 4)
        assert torch.allclose(y, torch.full((3,), float(expect))), y
    """, np=4)


def test_torch_grouped_allreduce_inplace():
    # reference torch/mpi_ops.py:361-392 grouped_allreduce_(_async_):
    # each tensor is overwritten with its reduced value
    run_torch_workers("""
        ts = [torch.full((3,), float(r + 1)),
              torch.full((2,), float(10 * (r + 1)))]
        out = hvd.grouped_allreduce_(ts, name="gip", op=hvd.Sum)
        exp0 = float(sum(i + 1 for i in range(n)))
        assert torch.allclose(ts[0], torch.full((3,), exp0)), ts[0]
        assert torch.allclose(ts[1], torch.full((2,), 10 * exp0)), ts[1]
        assert out[0] is ts[0] and out[1] is ts[1]  # in-place contract
    """)


def test_torch_broadcast_object_fn():
    run_torch_workers("""
        bcast = hvd.broadcast_object_fn(root_rank=1, name="bofn")
        got = bcast({"v": r * 10} if r == 1 else None)
        assert got == {"v": 10}, got
    """)
