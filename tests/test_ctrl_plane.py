"""Gang tests for the control-plane scale-out (PR 8): hierarchical
tree negotiation (``HVT_CTRL_TOPOLOGY=tree``), the steady-state
cache-hit bypass (bitmask announce votes + positions-form responses),
eviction-broadcast position sync under tree mode, coordinated-abort
fan-out when a LEADER dies, and the idle-gang traffic reduction at
rank 0.

Every test launches REAL multi-process engine gangs over loopback, but
through the featherweight ctypes harness of
``benchmarks/ctrl_plane_scaling.py`` (no jax/numpy import per worker),
so a 16-rank gang costs seconds, not minutes. ``HVT_TOPO_HOST`` fakes
the multi-host layout the leader election keys on.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build",
                   "libhvt_core.so")

sys.path.insert(0, REPO)
from benchmarks import ctrl_plane_scaling as cps  # noqa: E402

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


def run_gang(body, np=4, hosts=2, topology="tree", timeout=120,
             extra_env=None, expect_rc=0):
    """Spawn np featherweight workers running `body` with ``eng``
    (an initialized MiniEngine), ``r``, ``n`` in scope. Workers write
    ``OUT`` (a JSON-able dict) to a per-rank file; returns
    {rank: out_dict}. Ranks pack contiguously onto `hosts` fake
    hosts."""
    port = cps._next_port()
    import tempfile
    outdir = tempfile.mkdtemp(prefix=f"hvt_cptest_{port}_")
    script = textwrap.dedent(f"""
        import json, os, sys, time, zlib
        sys.path.insert(0, {REPO!r})
        from benchmarks.ctrl_plane_scaling import MiniEngine
        r = int(os.environ["HVT_CP_RANK"])
        n = {np}
        eng = MiniEngine()
        eng.init(r, n, port={port}, cycle_ms=1)
        OUT = {{}}
        {textwrap.indent(textwrap.dedent(body), '        ').strip()}
        with open(os.path.join({outdir!r}, f"rank{{r}}.json"), "w") as f:
            json.dump(OUT, f)
        eng.shutdown()
        print(f"WORKER-{{r}}-DONE", flush=True)
    """)
    path = os.path.join(outdir, "worker.py")
    with open(path, "w") as f:
        f.write(script)
    per_host = max(1, np // hosts)
    procs = []
    try:
        for r in range(np):
            env = dict(os.environ)
            env.update({
                "HVT_CP_RANK": str(r),
                "HVT_CTRL_TOPOLOGY": topology,
                "HVT_HOSTNAME": "127.0.0.1",
                "HVT_TOPO_HOST": f"h{min(r // per_host, hosts - 1)}",
                "HVT_LOG_LEVEL": "error",
                "PYTHONUNBUFFERED": "1",
            })
            env.update(extra_env or {})
            procs.append(subprocess.Popen(
                [sys.executable, path], env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = {}
        deadline = time.monotonic() + timeout
        for r, p in enumerate(procs):
            try:
                out, _ = p.communicate(
                    timeout=max(1.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                raise AssertionError(
                    f"rank {r} timed out after {timeout}s:\n{out}")
            outs[r] = out
            if expect_rc is not None:
                assert p.returncode == expect_rc, \
                    f"rank {r} rc={p.returncode} (want {expect_rc}):" \
                    f"\n{out}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = {}
    for r in range(np):
        rp = os.path.join(outdir, f"rank{r}.json")
        if os.path.exists(rp):
            with open(rp) as f:
                results[r] = json.load(f)
    return results, outs


# The body every bit-identity gang runs: a spread of ops, dtypes, and
# reduce kinds, digested per rank with crc32 so star and tree runs can
# be compared byte-for-byte.
_IDENTITY_BODY = """
import struct
digests = []
def digest(tag, vals, fmt):
    digests.append((tag, zlib.crc32(struct.pack(f"<{len(vals)}{fmt}",
                                                *vals))))
for dtype, fmt in (("float32", "f"), ("float64", "d"), ("int32", "i"),
                   ("int64", "q"), ("uint8", "B")):
    base = [(i % 23 + r + 1) % (120 if fmt == "B" else 10**6)
            for i in range(257)]
    out = eng.collective(f"ar.{dtype}", base, dtype=dtype)
    digest(f"ar.{dtype}", out, fmt)
for red in ("min", "max", "prod"):
    vals = [float((i * (r + 3)) % 7 + 1) for i in range(65)]
    out = eng.collective(f"ar.{red}", vals, reduce=red)
    digest(f"ar.{red}", out, "f")
out = eng.collective("bc", [float(r * 100 + i) for i in range(33)],
                     op="broadcast", root=1)
digest("bc", out, "f")
out = eng.collective("ag", [float(r + i) for i in range(9)],
                     op="allgather")
digest("ag", out, "f")
# repeated-name traffic: steady-state cycles ride the bypass
for step in range(6):
    out = eng.collective("hot", [float(r + 1)] * 129)
    digest(f"hot.{step}", out, "f")
# subset collectives: two disjoint lanes reusing one name
half = [0, 1] if r < 2 else [2, 3]
out = eng.collective("lane", [float(r + 1)] * 17, members=half)
digest("lane", out, "f")
st = eng.stats()
OUT = {"digests": digests, "cache_hits": st["cache_hits"],
       "bypass_cycles": st["ctrl_bypass_cycles"],
       "ctrl_peers": st["ctrl_peers"]}
"""


def test_star_tree_bit_identity():
    """The tree control plane must produce bit-identical collective
    results to the star baseline — same ops, same dtypes, same reduce
    kinds, including cache-hit steady-state traffic and subset lanes."""
    star, _ = run_gang(_IDENTITY_BODY, np=4, hosts=2, topology="star")
    tree, _ = run_gang(_IDENTITY_BODY, np=4, hosts=2, topology="tree")
    assert set(star) == set(tree) == {0, 1, 2, 3}
    for r in range(4):
        assert star[r]["digests"] == tree[r]["digests"], \
            f"rank {r} results diverge between star and tree"
    # the steady-state phase really did ride the cache + bypass
    assert tree[0]["cache_hits"] > 0
    assert tree[0]["bypass_cycles"] > 0
    # fan-in: star root serves world-1 peers, tree root one per host
    assert star[0]["ctrl_peers"] == 3
    assert tree[0]["ctrl_peers"] == 2


def test_bitmask_vote_mixed_cycles_and_lanes():
    """Cache-bitmask votes must stay correct when hit and miss traffic
    land in the same cycle, and per-lane (process-set) positions must
    not cross-talk — each lane's steady state bypasses independently."""
    body = """
    half = [0, 1] if r < 2 else [2, 3]
    expect_half = 3.0 if r < 2 else 7.0
    # lane-specific names: the response cache is keyed by NAME, so two
    # lanes sharing one steady-state name would thrash each other's
    # entry (documented; fine for correctness, fatal for hit rate)
    lane_nm = f"lane.h{half[0]}"
    eng.collective(lane_nm, [float(r + 1)] * 33, members=half)
    eng.collective("glob.a", [float(r + 1)] * 33)
    errs = []
    for step in range(8):
        hs = []
        # pure-hit submissions (bitmask-vote eligible)...
        hs.append(eng.submit(lane_nm, [float(r + 1)] * 33,
                             members=half))
        hs.append(eng.submit("glob.a", [float(r + 1)] * 33))
        # ...plus, on some steps, a fresh miss in the same cycle
        if step % 3 == 0:
            hs.append(eng.submit(f"fresh.{step}", [2.0] * 9))
        outs = [eng.wait(h) for h in hs]
        if abs(outs[0][0] - expect_half) > 1e-6:
            errs.append(("lane", step, outs[0][0]))
        if abs(outs[1][0] - 10.0) > 1e-6:
            errs.append(("glob", step, outs[1][0]))
        if len(outs) > 2 and abs(outs[2][0] - 8.0) > 1e-6:
            errs.append(("fresh", step, outs[2][0]))
    # cross-lane SAME-name correctness (cache-thrash case): both lanes
    # reuse one name; values must still come out right every time
    for step in range(3):
        out = eng.collective("shared.nm", [float(r + 1)] * 5,
                             members=half)
        if abs(out[0] - expect_half) > 1e-6:
            errs.append(("shared", step, out[0]))
    st = eng.stats()
    OUT = {"errs": errs, "cache_hits": st["cache_hits"],
           "bypass_cycles": st["ctrl_bypass_cycles"]}
    """
    results, _ = run_gang(body, np=4, hosts=2, topology="tree")
    for r, out in results.items():
        assert out["errs"] == [], f"rank {r}: {out['errs']}"
    assert results[0]["cache_hits"] > 0
    # pure-hit cycles rode the positions-form bypass on every rank
    for r in range(4):
        assert results[r]["bypass_cycles"] > 0, results[r]


def test_eviction_broadcast_position_sync_tree():
    """Re-submitting a cached name with changed params must evict the
    position on EVERY rank (broadcast through the tree) and renegotiate
    cleanly — positions drifting across ranks would corrupt later
    cache-hit traffic."""
    body = """
    errs = []
    for round_ in range(3):
        # cache under shape A, hit it, then change shape -> kInvalid
        for step in range(3):
            out = eng.collective("ev", [float(r + 1)] * 40)
            if abs(out[0] - 10.0) > 1e-6:
                errs.append(("A", round_, step, out[0]))
        for step in range(2):
            out = eng.collective("ev", [float(r + 2)] * 72)
            if abs(out[0] - 14.0) > 1e-6:
                errs.append(("B", round_, step, out[0]))
        # a second cached name keeps its (synced) position throughout
        out = eng.collective("stable", [1.0] * 16)
        if abs(out[0] - 4.0) > 1e-6:
            errs.append(("stable", round_, out[0]))
    OUT = {"errs": errs}
    """
    results, _ = run_gang(body, np=4, hosts=2, topology="tree")
    for r, out in results.items():
        assert out["errs"] == [], f"rank {r}: {out['errs']}"


def test_leader_death_aborts_gang_within_deadline():
    """SIGKILL the LEADER of host h1 (rank 2) mid-run: every survivor
    — its member (behind the dead leader), the other host, and the
    root — must error out within ~one op deadline, not hang."""
    body = """
    t0 = time.monotonic()
    aborted = None
    try:
        for step in range(200):
            eng.collective(f"work.{step % 4}", [float(r)] * 257)
    except RuntimeError as e:
        aborted = time.monotonic() - t0
        msg = str(e)
    OUT = {"aborted_sec": aborted,
           "msg": msg[:200] if aborted else ""}
    """
    timeout_ms = 4000
    results, outs = run_gang(
        body, np=4, hosts=2, topology="tree", timeout=90,
        extra_env={
            "HVT_FAULT_INJECT": "kill:rank=2:after_ops=20",
            "HVT_OP_TIMEOUT_MS": str(timeout_ms),
            "HVT_HEARTBEAT_MS": str(timeout_ms),
        },
        expect_rc=None)  # rank 2 dies by SIGKILL; checked below
    # rank 2 was killed before writing its OUT file
    assert 2 not in results, "the fault never fired"
    for r in (0, 1, 3):
        assert r in results, f"survivor {r} wrote no result:\\n{outs[r]}"
        took = results[r]["aborted_sec"]
        assert took is not None, f"survivor {r} never aborted"
        # containment bound: ~one deadline + fan-out slack (the
        # existing chaos suite uses the same 2x bound)
        assert took < 2.5 * timeout_ms / 1e3, \
            f"survivor {r} took {took:.1f}s to abort: {results[r]}"


def test_idle_16rank_rank0_traffic_drops_under_tree():
    """The idle-gang keepalive exchange routes through leaders in tree
    mode: a parked 16-rank gang on 4 simulated hosts must cost rank 0
    a fraction of the star's control bytes (15 direct peers -> 4)."""
    spec = {"tensors": 2, "numel": 16,
            "phases": [{"name": "idle", "sleep": 2.0}]}
    star = cps.run_config(16, 4, "star", spec, cps._next_port(),
                          timeout=180)
    tree = cps.run_config(16, 4, "tree", spec, cps._next_port(),
                          timeout=180)
    assert star["ctrl_peers"] == 15
    assert tree["ctrl_peers"] == 4
    # per-CYCLE bytes: wall-clock rates skew with box load, the bytes a
    # keepalive cycle moves do not. 15 -> 4 peers cuts ~2.5x (aggregate
    # keepalives carry a per-rank roster, so not the full 3.75x).
    sph, tph = star["phases"]["idle"], tree["phases"]["idle"]
    sb = (sph["ctrl_tx_bytes"] + sph["ctrl_rx_bytes"]) \
        / max(sph["cycles"], 1)
    tb = (tph["ctrl_tx_bytes"] + tph["ctrl_rx_bytes"]) \
        / max(tph["cycles"], 1)
    assert sb > tb * 2, (sb, tb)
