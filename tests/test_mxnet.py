"""MXNet binding tests — the gated analog of reference
``test/parallel/test_mxnet.py``. MXNet itself is absent from the image,
so the duck-typed core (numpy NDArray stand-ins) is exercised
single-process and over real multi-process engines, the same pattern as
the Ray/Spark/TF gated suites."""

import os

import numpy as np
import pytest

from tests.test_engine_integration import LIB, run_workers


def test_split_list_shapes():
    from horovod_tpu.mxnet import _split_list

    assert _split_list(list(range(7)), 3) == [[0, 1, 2], [3, 4], [5, 6]]
    assert _split_list(list(range(4)), 2) == [[0, 1], [2, 3]]
    assert _split_list([1], 3) == [[1]]


def test_single_process_allreduce_identity():
    import horovod_tpu.mxnet as mx_hvt

    x = np.arange(4, dtype=np.float32)
    out = mx_hvt.allreduce(x, average=True, name="mx1")
    np.testing.assert_allclose(np.asarray(out), x)
    # in-place variant writes back
    y = np.arange(4, dtype=np.float32)
    mx_hvt.allreduce_(y, average=False, name="mx2")
    np.testing.assert_allclose(y, np.arange(4))


def test_ndarray_ducktype_roundtrip():
    from horovod_tpu.mxnet.mpi_ops import _assign, _like, _to_numpy

    class FakeND:
        def __init__(self, arr):
            self.arr = np.asarray(arr, np.float32)

        def asnumpy(self):
            return self.arr

        @classmethod
        def from_numpy(cls, arr):
            return cls(arr)

        def __setitem__(self, k, v):
            self.arr[k] = v.arr if isinstance(v, FakeND) else v

    t = FakeND([1.0, 2.0])
    assert _to_numpy(t).tolist() == [1.0, 2.0]
    back = _like(np.asarray([3.0, 4.0], np.float32), t)
    assert isinstance(back, FakeND) and back.asnumpy().tolist() == [3.0, 4.0]
    _assign(t, np.asarray([5.0, 6.0], np.float32))
    assert t.asnumpy().tolist() == [5.0, 6.0]


def test_distributed_optimizer_rescale_and_update_single():
    import horovod_tpu.mxnet as mx_hvt

    class FakeOpt:
        def __init__(self):
            self.rescale_grad = 1.0
            self.updates = []

        def update(self, index, weight, grad, state):
            self.updates.append((index, np.array(grad, copy=True)))

    inner = FakeOpt()
    opt = mx_hvt.DistributedOptimizer(inner,
                                      gradient_predivide_factor=2.0)
    # rescale folds predivide / world size (8-chip test mesh)
    assert inner.rescale_grad == pytest.approx(2.0 / mx_hvt.size())
    g = np.ones(3, np.float32)
    opt.update(0, np.zeros(3), g, None)
    assert inner.updates[0][0] == 0
    # passthrough of inner attributes
    assert opt.updates is inner.updates


def test_distributed_trainer_gated_message():
    import horovod_tpu.mxnet as mx_hvt

    if not mx_hvt._MX_AVAILABLE:
        with pytest.raises(ImportError, match="mxnet is not installed"):
            mx_hvt.DistributedTrainer([], None)


_PAR = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


def run_mx_workers(body, np=2, **kw):
    import textwrap

    return run_workers("import horovod_tpu.mxnet as mx_hvt\n"
                       + textwrap.dedent(body), np=np, **kw)


@_PAR
def test_mx_allreduce_inplace_2proc():
    run_mx_workers("""
        g = np.full((4,), float(r + 1), np.float32)
        mx_hvt.allreduce_(g, average=False, name="mx.g")
        np.testing.assert_allclose(g, sum(i + 1 for i in range(n)))
    """)


@_PAR
def test_mx_optimizer_sums_grads_and_rescales_2proc():
    # reference semantics: wire op is SUM; averaging folded into the
    # inner optimizer's rescale_grad (gradient_predivide_factor / size)
    run_mx_workers("""
        class FakeOpt:
            def __init__(self):
                self.rescale_grad = 1.0
                self.seen = None
            def update(self, index, weight, grad, state):
                self.seen = np.array(grad, copy=True)
                weight -= self.rescale_grad * self.seen

        inner = FakeOpt()
        opt = mx_hvt.DistributedOptimizer(inner)
        assert abs(inner.rescale_grad - 1.0 / n) < 1e-12
        w = np.zeros(3, np.float32)
        g = np.full(3, float(r + 1), np.float32)
        opt.update(0, w, g, None)
        total = sum(i + 1 for i in range(n))
        np.testing.assert_allclose(inner.seen, total)      # summed
        np.testing.assert_allclose(w, -total / n)          # averaged step
        # list-of-grads path with grouped fusion
        gs = [np.full(2, float(r), np.float32),
              np.full(2, float(r) + 5, np.float32)]
        opt2 = mx_hvt.DistributedOptimizer(FakeOpt(), num_groups=1)
        opt2._do_allreduce([0, 1], gs)
        np.testing.assert_allclose(gs[0], sum(range(n)))
        np.testing.assert_allclose(gs[1], sum(i + 5 for i in range(n)))
    """)


@_PAR
def test_mx_trainer_grads_and_broadcast_parameters_2proc():
    run_mx_workers("""
        class FakeParam:
            def __init__(self, grad, grad_req="write"):
                self.grad_req = grad_req
                self._g = grad
            def list_grad(self):
                return [self._g]

        from horovod_tpu.mxnet import _allreduce_trainer_grads
        params = [FakeParam(np.full(2, float(r + 1), np.float32)),
                  FakeParam(np.zeros(2, np.float32), grad_req="null"),
                  FakeParam(np.full(2, float(10 * r), np.float32))]
        _allreduce_trainer_grads(params, num_groups=2)
        np.testing.assert_allclose(params[0].list_grad()[0],
                                   sum(i + 1 for i in range(n)))
        np.testing.assert_allclose(params[1].list_grad()[0], 0.0)  # null
        np.testing.assert_allclose(params[2].list_grad()[0],
                                   sum(10 * i for i in range(n)))

        ps = {"w": np.full(3, float(r), np.float32),
              "b": np.full(1, float(-r), np.float32)}
        mx_hvt.broadcast_parameters(ps, root_rank=1)
        np.testing.assert_allclose(ps["w"], 1.0)
        np.testing.assert_allclose(ps["b"], -1.0)
    """)
