"""Unit tests for the critical-path analyzer
(``horovod_tpu/tools/hvt_analyze.py``).

Synthetic chrome-trace shards with known phase durations pin the
breakdown math exactly; truncation-damaged shards pin the crash-safe
parse path (documented flight-recorder behavior); the ``--diff`` tests
pin the perf-gate verdict on a seeded 2x-slower report. The real
2-proc flight-recorded gang test lives in ``test_flight_recorder.py``
(the module that already owns the slow gang fixtures).
"""

import json

import pytest

from horovod_tpu.tools import hvt_analyze as A


def _meta(pid, tid, name):
    return {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name}}


def _engine_lane_events(pid, tid, enq, neg=None, readies=(),
                        exec_span=None, wires=(), done=None, lane=0):
    """One tensor instance on one engine lane, in engine emit order."""
    evs = [{"ph": "i", "pid": pid, "tid": tid, "ts": enq,
            "name": "ENQUEUED", "s": "t", "args": {"lane": lane}}]
    if neg:
        evs.append({"ph": "B", "pid": pid, "tid": tid, "ts": neg[0],
                    "name": "NEGOTIATE_ALLREDUCE"})
        for ts, r in readies:
            evs.append({"ph": "i", "pid": pid, "tid": tid, "ts": ts,
                        "name": f"RANK_READY_{r}", "s": "t"})
        evs.append({"ph": "E", "pid": pid, "tid": tid, "ts": neg[1]})
    if exec_span:
        evs.append({"ph": "B", "pid": pid, "tid": tid,
                    "ts": exec_span[0], "name": "ALLREDUCE",
                    "args": {"lane": lane}})
        for wb, we in wires:
            evs.append({"ph": "B", "pid": pid, "tid": tid, "ts": wb,
                        "name": "WIRE_ALLREDUCE",
                        "args": {"lane": lane, "bytes": 1024}})
            evs.append({"ph": "E", "pid": pid, "tid": tid, "ts": we})
        if done is not None:
            # engine ordering: DONE (CompleteEntry inside the response
            # execution) lands BEFORE the EXEC_END event — the analyzer
            # must not finalize the instance at DONE
            evs.append({"ph": "i", "pid": pid, "tid": tid, "ts": done,
                        "name": "DONE", "s": "t"})
        evs.append({"ph": "E", "pid": pid, "tid": tid,
                    "ts": exec_span[1]})
    elif done is not None:
        evs.append({"ph": "i", "pid": pid, "tid": tid, "ts": done,
                    "name": "DONE", "s": "t"})
    return evs


def _synthetic_trace():
    """2 ranks, tensor t0: rank 1 is always the 400 µs straggler."""
    evs = [_meta(0, 0, "t0 (engine)"), _meta(1, 0, "t0 (engine)"),
           _meta(0, 9, "CYCLE")]
    # two instances on each rank with identical known phases
    for k, base in enumerate((0, 10_000)):
        evs += _engine_lane_events(
            0, 0, enq=base,
            neg=(base + 100, base + 500),
            readies=((base + 100, 0), (base + 500, 1)),
            exec_span=(base + 600, base + 1600),
            wires=((base + 650, base + 1050),),
            done=base + 1590)
        evs += _engine_lane_events(
            1, 0, enq=base + 50,
            exec_span=(base + 620, base + 1620),
            wires=((base + 660, base + 1060),),
            done=base + 1610)
        evs.append({"ph": "i", "pid": 0, "tid": 9, "ts": base + 590,
                    "name": "ENGINE_CYCLE(1 responses)", "s": "p"})
        evs.append({"ph": "i", "pid": 0, "tid": 9, "ts": base + 590,
                    "name": "CTRL(150 B tx, 80 B rx)", "s": "p"})
    return evs


def test_phase_breakdown_exact():
    rep = A.analyze(_synthetic_trace())
    assert rep["ranks"] == [0, 1]
    assert rep["instances"] == 4
    ph = rep["phases"]
    # rank 0: queue 600, rank 1: 570 → p50 picks one of them
    assert ph["queue"]["p50"] in (570, 600)
    assert ph["negotiate"] == {"count": 2, "p50": 400, "p90": 400,
                               "p99": 400, "mean": 400.0, "max": 400}
    assert ph["wire"]["p50"] == 400 and ph["wire"]["count"] == 4
    assert ph["exec"]["p50"] == 1000
    assert ph["reduce"]["p50"] == 600  # exec 1000 − wire 400
    assert ph["e2e"]["count"] == 4
    assert rep["per_tensor"]["t0"]["exec"]["count"] == 4
    # metrics block mirrors the p50s for --diff
    assert rep["metrics"]["exec_us_p50"] == 1000
    assert rep["metrics"]["wire_us_p50"] == 400


def test_straggler_ranking():
    rep = A.analyze(_synthetic_trace())
    assert rep["negotiations_scored"] == 2
    top = rep["stragglers"][0]
    assert top["rank"] == 1
    assert top["times_last"] == 2 and top["share"] == 1.0
    assert top["mean_margin_us"] == 400.0


def test_lane_percentiles_and_cycles():
    rep = A.analyze(_synthetic_trace())
    assert rep["lanes"]["0"]["count"] == 4
    assert rep["lanes"]["0"]["p50"] == 1000
    assert rep["cycles"]["count"] == 2
    assert rep["cycles"]["mean_responses"] == 1.0
    assert rep["cycles"]["ctrl_tx_bytes"] == 300
    assert rep["cycles"]["ctrl_rx_bytes"] == 160
    # role-less CTRL instants (pre-tree shards) attribute as "member"
    assert rep["cycles"]["ctrl_by_role"] == {
        "member": {"instants": 2, "tx_bytes": 300, "rx_bytes": 160}}


def test_tree_mode_ctrl_role_breakdown():
    """Tree-mode CTRL attribution: the leader hop shows up as its own
    role row, counted once (at the leader), never re-counted at the
    members whose announces it batched — and the totals stay
    phase-complete (sum of roles == gang-wide ctrl bytes)."""
    evs = [_meta(0, 9, "CYCLE"), _meta(1, 9, "CYCLE"),
           _meta(2, 9, "CYCLE")]

    def ctrl(pid, ts, tx, rx, role):
        return {"ph": "i", "pid": pid, "tid": 9, "ts": ts,
                "name": f"CTRL({tx} B tx, {rx} B rx)", "s": "p",
                "args": {"role": role}}

    # one negotiation cycle on a 3-rank tree: member -> leader -> root
    evs += [ctrl(2, 100, 50, 200, "member"),   # announce up, resp down
            ctrl(1, 120, 300, 250, "leader"),  # aggregate up + relay
            ctrl(0, 140, 200, 300, "root")]    # fan-in/out at rank 0
    # a second cycle where only root+leader exchange (member idle-ish)
    evs += [ctrl(1, 300, 60, 40, "leader"),
            ctrl(0, 320, 40, 60, "root")]
    rep = A.analyze(evs)
    br = rep["cycles"]["ctrl_by_role"]
    assert br["root"] == {"instants": 2, "tx_bytes": 240,
                          "rx_bytes": 360}
    assert br["leader"] == {"instants": 2, "tx_bytes": 360,
                            "rx_bytes": 290}
    assert br["member"] == {"instants": 1, "tx_bytes": 50,
                            "rx_bytes": 200}
    # phase-complete: per-role rows sum to the gang totals
    assert sum(d["tx_bytes"] for d in br.values()) == \
        rep["cycles"]["ctrl_tx_bytes"] == 650
    assert sum(d["rx_bytes"] for d in br.values()) == \
        rep["cycles"]["ctrl_rx_bytes"] == 850


def test_recovery_section_from_reconnect_events():
    """RECONNECT/REPLAY cycle-lane instants (self-healing links) sum
    into the recovery section: reconnect count, replay volume, and the
    RECONNECTING stall time, attributed per plane."""
    evs = [_meta(0, 9, "CYCLE"), _meta(1, 9, "CYCLE")]

    def rec(pid, ts, plane, retries, dur_us):
        return {"ph": "i", "pid": pid, "tid": 9, "ts": ts,
                "name": f"RECONNECT(rank 1, {plane})", "s": "g",
                "args": {"plane": plane, "peer": "rank 1",
                         "retries": retries, "duration_us": dur_us}}

    def rep_ev(pid, ts, plane, frames, nbytes):
        return {"ph": "i", "pid": pid, "tid": 9, "ts": ts,
                "name": f"REPLAY(rank 1, {plane})", "s": "g",
                "args": {"plane": plane, "peer": "rank 1",
                         "frames": frames, "bytes": nbytes}}

    evs += [rec(0, 100, "data", 2, 4000),
            rec(0, 9000, "ctrl", 1, 1500),
            rep_ev(0, 150, "data", 0, 65536),
            rec(1, 120, "data", 0, 3500),
            rep_ev(1, 170, "ctrl", 3, 96)]
    rep = A.analyze(evs)
    rc = rep["recovery"]
    assert rc["reconnects"] == 3
    assert rc["frames_replayed"] == 3
    assert rc["replay_bytes"] == 65536 + 96
    assert rc["stall_us_total"] == 4000 + 1500 + 3500
    assert rc["by_plane"]["data"] == {
        "reconnects": 2, "replay_bytes": 65536, "stall_us": 7500}
    assert rc["by_plane"]["ctrl"]["reconnects"] == 1
    assert rc["by_plane"]["ctrl"]["replay_bytes"] == 96


def test_recovery_section_zero_on_clean_trace():
    rep = A.analyze(_synthetic_trace())
    assert rep["recovery"]["reconnects"] == 0
    assert rep["recovery"]["replay_bytes"] == 0
    assert rep["recovery"]["by_plane"] == {}


def test_overlap_efficiency_serial_vs_inflight():
    # serial instances → 0 overlap on both ranks
    rep = A.analyze(_synthetic_trace())
    assert rep["overlap_efficiency"]["0"] == 0.0
    # two tensors in flight simultaneously → exec fully covered by the
    # other's enq→done window
    evs = [_meta(0, 0, "a (engine)"), _meta(0, 1, "b (engine)")]
    evs += _engine_lane_events(0, 0, enq=0, exec_span=(100, 200),
                               done=190)
    evs += _engine_lane_events(0, 1, enq=10, exec_span=(220, 320),
                               done=310)
    rep2 = A.analyze(evs)
    # a's exec (100-200) is inside b's window (10-310) and vice versa
    # for b's exec (220-320) vs a's window (0-190): only a overlaps
    assert rep2["overlap_efficiency"]["0"] == 0.5


def test_truncated_shards_analyze(tmp_path):
    """Crash-damaged shards (no closing bracket, torn tail) go through
    the documented truncation-tolerant parse and still produce a
    report from the intact prefix."""
    evs = _synthetic_trace()
    text = "[\n" + ",\n".join(json.dumps(e) for e in evs) + ",\n"
    torn = text + '{"ph": "B", "pid": 0, "ti'
    p = tmp_path / "shard0.json"
    p.write_text(torn)
    rep = A.analyze_paths([str(p)])
    assert rep["instances"] == 4
    assert rep["phases"]["exec"]["p50"] == 1000


def test_unterminated_spans_are_dropped():
    """A shard cut mid-execution (open exec span, no DONE) must not
    fabricate durations."""
    evs = [_meta(0, 0, "t0 (engine)"),
           {"ph": "i", "pid": 0, "tid": 0, "ts": 0, "name": "ENQUEUED",
            "s": "t", "args": {"lane": 0}},
           {"ph": "B", "pid": 0, "tid": 0, "ts": 100,
            "name": "ALLREDUCE", "args": {"lane": 0}}]
    rep = A.analyze(evs)
    assert "exec" not in rep["phases"]
    assert "e2e" not in rep["phases"]


def test_merge_of_raw_shards(tmp_path):
    evs = _synthetic_trace()
    s0 = [e for e in evs if e.get("pid") == 0]
    s1 = [e for e in evs if e.get("pid") == 1]
    p0, p1 = tmp_path / "r0.json", tmp_path / "r1.json"
    p0.write_text(json.dumps(s0))
    p1.write_text(json.dumps(s1))
    rep = A.analyze_paths([str(p0), str(p1)])
    assert rep["ranks"] == [0, 1]
    assert rep["instances"] == 4


# ------------------------------------------------------------------ diff

def _report(**metrics):
    return {"schema": "x", "metrics": metrics}


def test_diff_seeded_2x_regression_fails(tmp_path, capsys):
    """The perf-gate acceptance pin: a synthetic report 2x slower than
    baseline must fail the diff; within-band drift must not."""
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_report(exec_us_p50=1000.0,
                                       sweep_16MB_p50_ms=30.0)))
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(_report(exec_us_p50=2500.0,
                                       sweep_16MB_p50_ms=31.0)))
    rc = A.run_diff(str(base), str(slow), max_ratio=2.0,
                    min_base_us=200.0)
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION exec_us_p50" in out
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_report(exec_us_p50=1500.0,
                                     sweep_16MB_p50_ms=45.0)))
    assert A.run_diff(str(base), str(ok), max_ratio=2.0,
                      min_base_us=200.0) == 0


def test_diff_floor_skips_noise_metrics():
    regs, _, skipped, _ = A.diff_metrics(
        {"tiny_us_p50": 50.0}, {"tiny_us_p50": 500.0},
        max_ratio=2.0, min_base_us=200.0)
    assert regs == []
    assert skipped and skipped[0][0] == "tiny_us_p50"


def test_diff_only_p50_keys_gate():
    regs, _, _, _ = A.diff_metrics(
        {"exec_us_p99": 1000.0}, {"exec_us_p99": 9000.0},
        max_ratio=2.0, min_base_us=200.0)
    assert regs == []


def test_diff_ms_keys_normalized_for_floor():
    # 0.1 ms baseline = 100 µs < 200 µs floor → skipped
    regs, _, skipped, _ = A.diff_metrics(
        {"x_p50_ms": 0.1}, {"x_p50_ms": 1.0},
        max_ratio=2.0, min_base_us=200.0)
    assert regs == [] and skipped
    # 1 ms baseline gates
    regs, _, _, _ = A.diff_metrics(
        {"x_p50_ms": 1.0}, {"x_p50_ms": 3.0},
        max_ratio=2.0, min_base_us=200.0)
    assert regs and regs[0][0] == "x_p50_ms"


def test_diff_missing_gated_metric_fails(tmp_path, capsys):
    """A regression severe enough to delete a whole phase from the
    current report (e.g. wire spans no longer recorded) must FAIL the
    gate, not pass by shrinking the key intersection."""
    regs, _, _, missing = A.diff_metrics(
        {"gang_wire_us_p50": 610.0, "gang_exec_us_p50": 695.0},
        {"gang_exec_us_p50": 700.0},
        max_ratio=2.0, min_base_us=200.0)
    assert regs == [] and missing == ["gang_wire_us_p50"]
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_report(gang_wire_us_p50=610.0,
                                       gang_exec_us_p50=695.0)))
    cur = tmp_path / "cur.json"
    cur.write_text(json.dumps(_report(gang_exec_us_p50=700.0)))
    assert A.run_diff(str(base), str(cur), 2.0, 200.0) == 1
    assert "MISSING    gang_wire_us_p50" in capsys.readouterr().out
    # below-floor baselines may vanish without failing (they never gated)
    regs, _, _, missing = A.diff_metrics(
        {"tiny_us_p50": 50.0}, {}, max_ratio=2.0, min_base_us=200.0)
    assert regs == [] and missing == []


# ------------------------------------------------------------------- CLI

def test_cli_report_and_diff_roundtrip(tmp_path, capsys):
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps(_synthetic_trace()))
    rep_path = tmp_path / "report.json"
    assert A.main([str(trace), "-o", str(rep_path), "--quiet"]) == 0
    rep = json.loads(rep_path.read_text())
    assert rep["schema"] == A.SCHEMA
    # self-diff is always clean
    assert A.main(["--diff", str(rep_path), str(rep_path)]) == 0
    capsys.readouterr()


def test_cli_usage_errors(tmp_path):
    with pytest.raises(SystemExit):
        A.main([])  # no traces, no --diff
    trace = tmp_path / "t.json"
    trace.write_text("[]")
    with pytest.raises(SystemExit):
        A.main(["--diff", "a", "b", str(trace)])  # diff + traces


# ------------------------------------------- recovery p50 gating (PR 13)

def _reconnect_trace(durs_us):
    evs = [_meta(0, 9, "CYCLE")]
    for i, d in enumerate(durs_us):
        evs.append({"ph": "i", "pid": 0, "tid": 9, "ts": 100 + i * 1000,
                    "name": "RECONNECT(rank 1, data)", "s": "g",
                    "args": {"plane": "data", "peer": "rank 1",
                             "retries": 0, "duration_us": d}})
    return evs


def test_recovery_stall_p50_in_gated_metrics():
    """Traces with reconnects emit recovery_stall_us_p50 into the
    gated metrics block (PR 10 recovery section joins the perf-gate
    set); clean traces emit no recovery keys, so the standard perfgate
    baseline is unaffected."""
    rep = A.analyze(_reconnect_trace([4000, 1000, 9000]))
    assert rep["recovery"]["stall_us"]["p50"] == 4000
    assert rep["metrics"]["recovery_stall_us_p50"] == 4000
    clean = A.analyze(_synthetic_trace())
    assert "recovery_stall_us_p50" not in clean["metrics"]
    assert "stall_us" not in clean["recovery"]


def test_diff_fails_when_recovery_section_vanishes(tmp_path, capsys):
    """The satellite pin: a chaos/soak baseline carrying the recovery
    p50 must FAIL --diff against a report that silently stopped
    recording RECONNECT events — not pass by key-intersection
    shrink."""
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(
        {"metrics": {"recovery_stall_us_p50": 4000.0}}))
    cur.write_text(json.dumps({"metrics": {}}))
    assert A.run_diff(str(base), str(cur), 2.0, 200.0) == 1
    assert "MISSING    recovery_stall_us_p50" in capsys.readouterr().out
    # same recovery shape in both → clean
    cur.write_text(json.dumps(
        {"metrics": {"recovery_stall_us_p50": 5000.0}}))
    assert A.run_diff(str(base), str(cur), 2.0, 200.0) == 0
    capsys.readouterr()
