"""Regression pin for the eager data-plane scaling work
(benchmarks/engine_scaling.py, docs/performance.md): the shm plane must
not lose to the loopback TCP ring at the 16 MB payload where its
single-copy design wins by design.

Timing on a shared 1-core box is noisy, so the comparison interleaves
shm/ring pairs and compares MEDIANS with headroom — a real regression
(shm slower than ring by design, as a naive barrier bug would cause)
clears the margin; scheduler noise does not.
"""

import importlib.util
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "engine_scaling", os.path.join(REPO, "benchmarks", "engine_scaling.py"))
engine_scaling = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(engine_scaling)


@pytest.mark.timeout(1500)
def test_shm_not_slower_than_ring_at_16mb_2proc():
    def measure_once():
        shm_ms, ring_ms = [], []
        for _ in range(3):  # interleaved pairs: noise hits both alike
            shm_ms.append(engine_scaling.run_job(
                2, True, {"16MB": 1 << 22}, 4, REPO)["16MB"]["hit_ms"])
            ring_ms.append(engine_scaling.run_job(
                2, False, {"16MB": 1 << 22}, 4, REPO)["16MB"]["hit_ms"])
        return (float(np.median(shm_ms)), float(np.median(ring_ms)),
                shm_ms, ring_ms)

    # shm is ~25-35% faster here when the box is quiet (round-2 and
    # round-3 measurements); 1.2x headroom absorbs scheduler noise while
    # still catching a plane that actually lost its advantage. One
    # re-measure: a single noisy window (CI shares one core) must not
    # fail the build; a REAL regression fails both rounds.
    attempts = []
    for _ in range(2):
        shm, ring, shm_ms, ring_ms = measure_once()
        attempts.append((shm, ring, shm_ms, ring_ms))
        if shm <= ring * 1.2:
            return
    raise AssertionError(
        f"shm 16MB allreduce lost to loopback TCP in both rounds — the "
        f"single-copy shm plane should not lose: {attempts}")
