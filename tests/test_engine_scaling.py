"""Regression pin for the eager data-plane scaling work
(benchmarks/engine_scaling.py, docs/performance.md): the shm plane must
not lose to the loopback TCP ring at the 16 MB payload where its
single-copy design wins by design.

Timing on a shared 1-core box is noisy, so the comparison interleaves
shm/ring pairs and compares MEDIANS with headroom — a real regression
(shm slower than ring by design, as a naive barrier bug would cause)
clears the margin; scheduler noise does not.
"""

import importlib.util
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "engine_scaling", os.path.join(REPO, "benchmarks", "engine_scaling.py"))
engine_scaling = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(engine_scaling)


@pytest.mark.timeout(1500)
def test_shm_not_slower_than_ring_at_16mb_and_64mb_2proc():
    """No retry loop (round-4): the historical flake source was the shm
    barrier's FIXED 50µs nap stealing quanta from the working rank on
    the oversubscribed 1-core box — worst exactly at big payloads (the
    round-3 '64 MB cliff': shm 1024 ms vs ring 391 ms hit). With
    exponential backoff (backends.cc Barrier) five interleaved rounds
    measured shm >= ring at BOTH sizes (medians 39.7 vs 42.3 ms at
    16 MB, 370.7 vs 391.2 ms at 64 MB), so the pin now covers both and
    a single interleaved-median round suffices."""
    sizes = {"16MB": 1 << 22, "64MB": 1 << 24}
    shm_ms = {k: [] for k in sizes}
    ring_ms = {k: [] for k in sizes}
    for _ in range(3):  # interleaved pairs: noise hits both alike
        r_shm = engine_scaling.run_job(2, True, sizes, 4, REPO)
        r_ring = engine_scaling.run_job(2, False, sizes, 4, REPO)
        for k in sizes:
            shm_ms[k].append(r_shm[k]["hit_ms"])
            ring_ms[k].append(r_ring[k]["hit_ms"])
    for k in sizes:
        shm = float(np.median(shm_ms[k]))
        ring = float(np.median(ring_ms[k]))
        assert shm <= ring * 1.25, (
            f"shm {k} allreduce lost to loopback TCP (median {shm:.1f} "
            f"vs {ring:.1f} ms; raw {shm_ms[k]} vs {ring_ms[k]}) — the "
            f"single-copy shm plane should not lose")
