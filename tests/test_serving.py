"""Serving-gang subsystem tests (ISSUE 6).

Three layers:

- **units** (no engine): replica partitioning, deterministic
  shed-on-backlog window accounting, loadtest artifact schema, the
  shared waiter pool (no thread churn), autoscaler policy decisions;
- **driver integration** (fake workers, real ElasticDriver +
  RendezvousServer): scale-out on sustained backlog via zero-downtime
  re-rendezvous, shed-and-blacklist on a failure report naming a killed
  rank;
- **gangs** (real multi-process engines on loopback): two disjoint
  2-rank sets with independent allreduce streams — bit-exact per set,
  per-set cache lanes engaged, mixed set+global traffic in one cycle,
  lane isolation under saturation — and a loopback ReplicaGang loadgen
  replay producing a schema-valid artifact with aligned shed counts.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from horovod_tpu.runner.elastic.autoscaler import (Autoscaler,
                                                   AutoscalePolicy,
                                                   maybe_start_autoscaler)
from horovod_tpu.serving import loadgen
from horovod_tpu.serving.replica_gang import partition_replicas

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

_PORT = [26000 + (os.getpid() * 389) % 9000]


def _next_port():
    import socket
    while True:
        _PORT[0] += 1
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", _PORT[0]))
                return _PORT[0]
            except OSError:
                continue


def run_workers(body, np_=4, timeout=180, extra_env=None):
    _next_port()
    script = textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvt
        hvt.init()
        r, n = hvt.rank(), hvt.size()
        {textwrap.indent(textwrap.dedent(body), '        ').strip()}
        print(f"WORKER-{{r}}-DONE", flush=True)
        hvt.shutdown()
    """)
    path = f"/tmp/hvt_servtest_{os.getpid()}_{_PORT[0]}.py"
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": ""})
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np",
         str(np_), "--master-port", str(_PORT[0]), sys.executable, path],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    out = proc.stdout + proc.stderr
    for i in range(np_):
        assert f"WORKER-{i}-DONE" in out
    return out


# ----------------------------------------------------------------- units

def test_partition_replicas():
    assert partition_replicas(4, 2) == [[0, 1], [2, 3]]
    assert partition_replicas(5, 2) == [[0, 1, 2], [3, 4]]
    assert partition_replicas(4, 4) == [[0], [1], [2], [3]]
    assert partition_replicas(3, 1) == [[0, 1, 2]]
    with pytest.raises(ValueError):
        partition_replicas(2, 3)
    with pytest.raises(ValueError):
        partition_replicas(2, 0)


def test_replica_gang_shed_is_deterministic_single_proc():
    """Shed decisions depend only on the aligned submit/reap history:
    with the window full, every further submit sheds — no timing enters
    the decision (the property that keeps replica members aligned)."""
    from horovod_tpu.serving import ReplicaGang

    gang = ReplicaGang(1, admission_timeout=0.5, max_backlog=4)
    x = np.ones(8, np.float32)
    handles = [gang.submit_request(x) for _ in range(10)]
    assert [h is not None for h in handles] == [True] * 4 + [False] * 6
    assert gang.stats.admitted == 4 and gang.stats.shed == 6
    assert gang.backlog() == 4
    # reaping frees the window; admission resumes at exactly that point
    assert gang.reap() is not None
    assert gang.submit_request(x) is not None
    gang.drain()
    assert gang.backlog() == 0
    snap = gang.snapshot()
    assert snap["completed"] == 5 and snap["deadline_miss"] == 0
    assert snap["p99_ms"] >= 0

    # the admission deadline runs from SUBMIT time: with a zero budget
    # every reap is a miss even though the handles complete instantly
    strict = ReplicaGang(1, admission_timeout=0.0, max_backlog=4)
    for _ in range(3):
        strict.submit_request(x)
    strict.drain()
    assert strict.stats.deadline_miss == 3


class _RecordingEngine:
    """Single-rank engine seam stub recording every submission name/op;
    handles complete instantly with the input (world of one)."""

    def __init__(self):
        self.submits = []  # (name, n_tensors, op)

    def rank(self):
        return 0

    def size(self):
        return 1

    def submit(self, name, tensor, members, op="sum"):
        self.submits.append((name, 1, op))
        return [tensor]

    def submit_batch(self, name, tensors, members, op="sum"):
        self.submits.append((name, len(tensors), op))
        return list(tensors)

    def wait(self, handle, timeout=None):
        return handle if len(handle) > 1 else handle[0]


def test_batch_op_change_closes_the_open_batch():
    """A fused batch submission carries ONE reduce op: a request with a
    different op must close the open batch first (an aligned-history
    boundary, so members stay in lockstep) instead of silently riding
    the first request's op."""
    from horovod_tpu.serving import ReplicaGang

    eng = _RecordingEngine()
    gang = ReplicaGang(1, admission_timeout=1.0, max_backlog=8,
                       batch_window=4, engine=eng)
    x = np.ones(8, np.float32)
    gang.submit_request(x, op="sum")
    gang.submit_request(x, op="sum")
    gang.submit_request(x, op="avg")   # boundary: flushes the 2 sums
    gang.submit_request(x, op="avg")
    gang.drain()
    assert [(n, o) for _, n, o in eng.submits] == [(2, "sum"), (2, "avg")]
    assert [d for d in gang.decisions if d[0] == "batch"] == \
        [("batch", 0, 2), ("batch", 2, 2)]


def test_opname_maps_reduce_ops_and_rejects_unknown():
    """collective_ops ReduceOp INSTANCES (no __name__) must map by
    their .name — Average silently coerced to "sum" would inflate
    results by the lane size — and an op the seam cannot express
    raises instead of riding as sum."""
    from horovod_tpu.ops import collective_ops as co
    from horovod_tpu.serving import ReplicaGang

    gang = ReplicaGang(1, admission_timeout=1.0, max_backlog=4,
                       engine=_RecordingEngine())
    assert gang._opname(None) == "sum"
    assert gang._opname("avg") == "avg"
    assert gang._opname(co.Average) == "avg"
    assert gang._opname(co.Sum) == "sum"
    assert gang._opname(co.Min) == "min"
    assert gang._opname(co.Max) == "max"
    assert gang._opname(co.Product) == "prod"
    with pytest.raises(ValueError, match="unsupported"):
        gang._opname("xor")


def test_batched_reap_slo_is_per_request():
    """One slot-level wait timeout (the OLDEST request's blown budget)
    must not mark batch-mates admitted later — whose own latency sits
    inside the deadline — as misses too."""
    from horovod_tpu.serving import ReplicaGang

    gang = ReplicaGang(1, admission_timeout=0.05, max_backlog=8,
                       batch_window=2, engine=_RecordingEngine())
    x = np.ones(4, np.float32)
    gang.submit_request(x)
    time.sleep(0.08)             # first request blows its own budget
    gang.submit_request(x)       # second flushes the batch, fresh clock
    gang.drain()
    assert gang.stats.completed == 2
    assert gang.stats.deadline_miss == 1, gang.stats.deadline_miss


def test_batch_slot_names_unique_across_partial_flush_window():
    """Partial flushes (explicit flush()/op changes) can put up to
    max_backlog single-request slots in flight at once — batch names
    must not cycle back onto a slot that is still pending. Regression:
    the cycle was 2*ceil(backlog/window), which collided from the
    (2*ceil+1)th unreaped partial flush on."""
    from horovod_tpu.serving import ReplicaGang

    eng = _RecordingEngine()
    gang = ReplicaGang(1, admission_timeout=1.0, max_backlog=8,
                       batch_window=8, engine=eng)
    x = np.ones(4, np.float32)
    for _ in range(gang.max_backlog):   # fill the window, never reap
        gang.submit_request(x)
        gang.flush()
    names = [n for n, _, _ in eng.submits]
    assert len(names) == gang.max_backlog
    assert len(set(names)) == len(names), names


def test_replica_stats_reservoir_keeps_tracking_after_cap():
    """The latency reservoir must keep sampling the whole stream once
    full — a frozen early-life p99 would blind the SLO signal the
    autoscaler scales on."""
    from horovod_tpu.serving.replica_gang import ReplicaStats

    st = ReplicaStats(max_samples=64)
    for _ in range(200):
        st.observe(1.0, True)
    assert st.percentile(99) == pytest.approx(1.0)
    for _ in range(2000):
        st.observe(9.0, True)
    # ~94% of the stream is 9.0 by now; a frozen reservoir would still
    # report 1.0
    assert st.percentile(50) == pytest.approx(9.0)
    assert st.completed == 2200


def test_loadgen_artifact_schema_roundtrip(tmp_path):
    snaps = {
        "baseline": [
            {"rank": r, "replica": r // 2, "admitted": 10, "shed": 2,
             "completed": 10, "deadline_miss": 1, "p50_ms": 1.0,
             "p99_ms": 2.0, "throughput_rps": 5.0} for r in range(4)],
        "contended": [
            {"rank": r, "replica": r // 2, "admitted": 10, "shed": 4,
             "completed": 10, "deadline_miss": 2, "p50_ms": 1.1,
             "p99_ms": 2.2, "throughput_rps": 5.0} for r in range(4)],
    }
    config = {"saturate_replica": 0}
    doc = loadgen.build_artifact(config, snaps)
    assert loadgen.validate_artifact(doc) == []
    iso = doc["isolation"]
    assert iso["observed_replica"] == 1
    assert iso["ratio"] == pytest.approx(2.2 / 2.0, rel=1e-3)
    # --check CLI path
    p = tmp_path / "art.json"
    p.write_text(json.dumps(doc))
    assert loadgen.main(["--check", str(p)]) == 0
    bad = dict(doc)
    bad.pop("phases")
    p.write_text(json.dumps(bad))
    assert loadgen.main(["--check", str(p)]) == 1


def test_combine_handles_waiter_pool_no_thread_growth():
    """Grouped calls must not spawn a thread per call (satellite): the
    shared waiter pool scales with peak CONCURRENCY (bounded), never
    with call count, and reuses its threads across waves."""
    from horovod_tpu.engine import api

    combos = []
    for i in range(50):
        hs = [api.Handle() for _ in range(3)]
        combos.append((i, hs, api._combine_handles(hs)))
        for h in hs:
            h._set_result(i)
    for i, hs, c in combos:
        assert c.wait(timeout=10) == [i, i, i]
    waiters = [t for t in threading.enumerate()
               if t.name == "hvt-waiter"]
    # 50 sequential calls: far fewer threads than calls, under the cap
    assert 0 < len(waiters) <= api._waiters._max_threads
    assert len(waiters) < 25
    # a second sequential wave reuses the pool — no per-call growth
    for i in range(50):
        hs = [api.Handle() for _ in range(2)]
        c = api._combine_handles(hs)
        for h in hs:
            h._set_result(i)
        assert c.wait(timeout=10) == [i, i]
    after = [t for t in threading.enumerate() if t.name == "hvt-waiter"]
    assert len(after) <= len(waiters) + 2


def test_combine_handles_no_head_of_line_blocking():
    """A stalled lane's grouped waits must not freeze an unrelated
    group's completion: the pool grows with outstanding jobs, so a
    fast group resolves while several slow ones are still blocked."""
    from horovod_tpu.engine import api

    slow = [[api.Handle() for _ in range(2)] for _ in range(6)]
    slow_combined = [api._combine_handles(hs) for hs in slow]
    fast = [api.Handle(), api.Handle()]
    fast_combined = api._combine_handles(fast)
    for h in fast:
        h._set_result(7)
    # the fast group resolves while all six slow groups stay blocked
    assert fast_combined.wait(timeout=10) == [7, 7]
    assert not any(c.done() for c in slow_combined)
    for hs in slow:
        for h in hs:
            h._set_result(0)
    for c in slow_combined:
        assert c.wait(timeout=10) == [0, 0]


# ------------------------------------------------------- autoscaler units

class FakeStore:
    def __init__(self):
        self._scopes = {}

    def put(self, scope, key, value):
        self._scopes.setdefault(scope, {})[key] = value

    def get(self, scope, key):
        return self._scopes.get(scope, {}).get(key)

    def keys(self, scope):
        return list(self._scopes.get(scope, {}))


class FakeDriver:
    def __init__(self, world=2, avail=4, hosts=None):
        self._world = world
        self._avail = avail
        self.notifications = 0
        self.host_manager = SimpleNamespace(
            current_hosts=SimpleNamespace(
                count_available_slots=lambda: self._avail),
            blacklist=lambda host: self.blacklisted.append(host))
        self.blacklisted = []
        self.failure_reports = []
        self._assignments = {
            (h, s): SimpleNamespace(rank=r, hostname=h)
            for r, (h, s) in enumerate(hosts or [("a", 0), ("b", 0)])}
        self._lock = threading.Lock()

    def world_size(self):
        return self._world

    def _notify_workers_host_changes(self):
        self.notifications += 1

    def _on_failure_report(self, key, value):
        self.failure_reports.append((key, value))

    def finished(self):
        return False


def _scaler(driver, store=None, **policy):
    rdv = SimpleNamespace(store=store or FakeStore())
    defaults = dict(backlog_threshold=8, sustain_sec=5,
                    cooldown_sec=100, interval_sec=1)
    defaults.update(policy)
    return Autoscaler(driver, rdv, AutoscalePolicy(**defaults)), rdv.store


def test_autoscaler_scale_out_needs_sustained_backlog_and_cooldown():
    drv = FakeDriver(world=2, avail=4)
    scaler, store = _scaler(drv)
    store.put("serving", "1", json.dumps({"inflight": 12}).encode())
    scaler.step(now=100.0)
    scaler.step(now=103.0)
    assert drv.notifications == 0          # not sustained yet
    scaler.step(now=106.0)
    assert drv.notifications == 1          # sustained ≥ 5 s → scale out
    assert [a for _, a, _ in scaler.decisions] == ["scale_out"]
    scaler.step(now=108.0)
    scaler.step(now=120.0)
    assert drv.notifications == 1          # cooldown holds


def test_autoscaler_backlog_clears_resets_sustain_window():
    drv = FakeDriver(world=2, avail=4)
    scaler, store = _scaler(drv, sustain_sec=4)
    store.put("serving", "0", json.dumps({"inflight": 9}).encode())
    scaler.step(now=0.0)
    store.put("serving", "0", json.dumps({"inflight": 0}).encode())
    scaler.step(now=3.0)                   # backlog gone → window resets
    store.put("serving", "0", json.dumps({"inflight": 9}).encode())
    scaler.step(now=5.0)
    scaler.step(now=8.0)                   # only 3 s sustained
    assert drv.notifications == 0


def test_autoscaler_no_scale_out_without_spare_slots():
    drv = FakeDriver(world=4, avail=4)
    scaler, store = _scaler(drv, sustain_sec=0, cooldown_sec=0)
    store.put("serving", "2", json.dumps({"inflight": 99}).encode())
    for t in range(5):
        scaler.step(now=float(t))
    assert drv.notifications == 0
    assert scaler.decisions == []


def test_autoscaler_reads_engine_queue_depth_from_debugz():
    drv = FakeDriver()
    scaler, store = _scaler(drv)
    store.put("debugz", "1",
              json.dumps({"engine": {"queue_depth": 11}}).encode())
    store.put("serving", "1", json.dumps({"inflight": 2}).encode())
    assert scaler.read_backlog() == 11


def test_autoscaler_ignores_stale_and_out_of_world_snapshots():
    """The serving/debugz scopes survive round resets by design, so a
    shed rank's final push must not drive scale decisions forever: a
    payload that stops CHANGING goes stale on the driver's monotonic
    clock (no cross-host wall clocks involved), and rank ids beyond the
    current world are discarded outright."""
    drv = FakeDriver(world=2)
    scaler, store = _scaler(drv)
    store.put("serving", "1", json.dumps({"inflight": 64}).encode())
    store.put("debugz", "0",
              json.dumps({"engine": {"queue_depth": 40}}).encode())
    # rank ids from a bigger previous round → ignored regardless of age
    store.put("serving", "5", json.dumps({"inflight": 99}).encode())
    store.put("debugz", "7",
              json.dumps({"engine": {"queue_depth": 50}}).encode())
    assert scaler.read_backlog(mono_now=100.0) == 64.0
    # unchanged payloads 60 s later = dead ranks → both scopes age out
    assert scaler.read_backlog(mono_now=160.0) == 0.0
    # a changed (live) payload is fresh again
    store.put("serving", "1", json.dumps({"inflight": 12}).encode())
    assert scaler.read_backlog(mono_now=161.0) == 12.0


def test_autoscaler_failed_notify_keeps_sustain_window_armed():
    """A transient notify failure must not consume the sustain window:
    the scale-out retries on the next step, and no decision is recorded
    until the notification actually went out."""
    drv = FakeDriver(world=2, avail=4)

    calls = {"n": 0}

    def flaky_notify():
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("worker endpoint down")

    drv._notify_workers_host_changes = flaky_notify
    scaler, store = _scaler(drv, sustain_sec=0, cooldown_sec=0)
    store.put("serving", "0", json.dumps({"inflight": 30}).encode())
    scaler.step(now=1.0)
    assert calls["n"] == 1 and scaler.decisions == []
    scaler.step(now=2.0)       # retried immediately, now succeeds
    assert calls["n"] == 2
    assert [a for _, a, _ in scaler.decisions] == ["scale_out"]


def test_autoscaler_shed_delegates_to_driver_failure_handler():
    """Shed routes every unseen failure report through the driver's own
    ``_on_failure_report`` — one home for the blacklist policy (the
    guard semantics themselves are pinned by the driver's tests and the
    real-driver integration test below) — exactly once per report."""
    drv = FakeDriver(hosts=[("a", 0), ("b", 0)])
    scaler, store = _scaler(drv)
    report = json.dumps({"failed_ranks": [1], "error": "peer_lost: "
                         "control connection to rank 1 lost"}).encode()
    store.put("failure", "a/0", report)
    scaler.step(now=0.0)
    assert drv.failure_reports == [("a/0", report)]
    assert [a for _, a, _ in scaler.decisions] == ["shed"]
    scaler.step(now=1.0)                   # report already seen
    assert len(scaler.decisions) == 1
    assert len(drv.failure_reports) == 1
    # a LATER ROUND's genuinely-new report may reuse the key (the
    # failure scope is cleared at round resets) — dedup is by payload
    report2 = json.dumps({"failed_ranks": [0], "error": "x"}).encode()
    store.put("failure", "a/0", report2)
    scaler.step(now=2.0)
    assert drv.failure_reports[-1] == ("a/0", report2)
    assert len(scaler.decisions) == 2


def test_autoscaler_spare_slots_capped_by_max_np():
    """Slots beyond the driver's max_np are not scalable capacity — a
    'scale-out' onto them would re-rendezvous the gang for an unchanged
    world, every cooldown, forever."""
    drv = FakeDriver(world=4, avail=6)
    drv._settings = SimpleNamespace(max_np=4)
    scaler, store = _scaler(drv, sustain_sec=0, cooldown_sec=0)
    store.put("serving", "0", json.dumps({"inflight": 99}).encode())
    assert scaler.spare_slots() == 0
    scaler.step(now=1.0)
    assert drv.notifications == 0 and scaler.decisions == []
    drv._settings = SimpleNamespace(max_np=6)
    assert scaler.spare_slots() == 2


def test_autoscaler_survives_non_dict_kv_payloads():
    """Valid-JSON-but-not-an-object KV payloads (buggy/old pushers,
    manual curl) must be skipped, not abort every step() forever —
    the serving scope is kept across rounds, so a poison key would
    otherwise disable the autoscaler until launcher restart."""
    drv = FakeDriver(world=4, avail=4)
    scaler, store = _scaler(drv)
    store.put("serving", "0", b"[1, 2, 3]")
    store.put("debugz", "1", b"\"a string\"")
    store.put("failure", "a/0", b"42")
    store.put("serving", "1", json.dumps({"inflight": 7}).encode())
    assert scaler.read_backlog(mono_now=1.0) == 7.0
    assert scaler.read_failed_ranks() == {}
    assert scaler.read_failed_ranks() == {}  # bad key seen once, skipped
    scaler.step(now=0.0)                     # whole step stays alive


def test_autoscaler_scale_out_waits_for_notify_endpoints():
    """The driver's notify is a silent no-op with no registered worker
    endpoints; the autoscaler must not burn the sustain window +
    cooldown on a notification nobody heard."""
    drv = FakeDriver(world=2, avail=4)
    drv._worker_notify_addrs = lambda: []
    scaler, store = _scaler(drv, sustain_sec=0, cooldown_sec=0)
    store.put("serving", "0", json.dumps({"inflight": 30}).encode())
    scaler.step(now=1.0)
    assert drv.notifications == 0 and scaler.decisions == []
    drv._worker_notify_addrs = lambda: ["127.0.0.1:1"]
    scaler.step(now=2.0)                     # retries next poll
    assert drv.notifications == 1
    assert [a for _, a, _ in scaler.decisions] == ["scale_out"]


def test_maybe_start_autoscaler_env_gated(monkeypatch):
    drv = FakeDriver()
    rdv = SimpleNamespace(store=FakeStore())
    monkeypatch.delenv("HVT_AUTOSCALE", raising=False)
    assert maybe_start_autoscaler(drv, rdv) is None
    monkeypatch.setenv("HVT_AUTOSCALE", "1")
    scaler = maybe_start_autoscaler(drv, rdv)
    assert scaler is not None
    scaler.stop()


# ------------------------------------------- real-driver integration

def _wait_until(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def _make_driver(discovery, min_np, max_np, worker_fn):
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.settings import ElasticSettings
    from horovod_tpu.runner.http_server import RendezvousServer

    settings = ElasticSettings(min_np=min_np, max_np=max_np,
                               elastic_timeout=5.0,
                               discovery_interval=0.01)
    rendezvous = RendezvousServer()
    driver = ElasticDriver(rendezvous, discovery, settings,
                           create_worker_fn=worker_fn)
    return driver, rendezvous


def test_autoscaler_scale_out_grows_world_with_real_driver():
    """Backlog → notify → workers re-rendezvous → the next round runs
    on every available slot: the zero-downtime scale-out path."""
    from horovod_tpu.runner.elastic.discovery import FixedHostDiscovery
    from horovod_tpu.runner.elastic.notification import \
        WorkerNotificationManager

    sizes = []
    release = threading.Event()
    updates = []

    class RecordingState:
        def on_hosts_updated(self, ts, res):
            updates.append((ts, res))

    def worker(slot):
        sizes.append((slot.rank, slot.size))
        if slot.size == 1:
            # round 1: the lone serving worker waits for the
            # autoscaler's host-update notification, then reports READY
            # (the elastic @run wrapper's commit-point behavior)
            if not _wait_until(lambda: updates, timeout=8):
                return 1
            driver.record_ready(slot.hostname, slot.local_rank)
            release.wait(8)
        else:
            release.set()
        return 0

    driver, rendezvous = _make_driver(FixedHostDiscovery({"host-1": 2}),
                                      min_np=1, max_np=2,
                                      worker_fn=worker)
    rendezvous.start()
    mgr = WorkerNotificationManager()
    mgr.start_server()
    mgr.register_state(RecordingState())
    rendezvous.store.put(
        "workers", "0",
        json.dumps({"host": "127.0.0.1", "port": mgr.port}).encode())
    # heavy serving backlog reported by the worker
    rendezvous.store.put("serving", "0",
                         json.dumps({"inflight": 64}).encode())
    scaler = Autoscaler(driver, rendezvous,
                        AutoscalePolicy(backlog_threshold=8,
                                        sustain_sec=0, cooldown_sec=0,
                                        interval_sec=0.05))
    try:
        driver.start(1)
        assert driver.world_size() == 1
        assert scaler.spare_slots() == 1
        scaler.step(now=1.0)
        assert [a for _, a, _ in scaler.decisions] == ["scale_out"]
        assert driver.wait(15)
        assert driver.error is None, driver.error
        assert driver.world_size() == 2      # scaled onto the spare slot
        assert (0, 1) in sizes and any(s == 2 for _, s in sizes)
    finally:
        driver.stop()
        rendezvous.stop()


def test_autoscaler_shed_and_blacklist_rejoins_without_killed_host():
    """A survivor's failure report (what the elastic @run wrapper PUTs
    after an HVT_FAULT_INJECT kill) sheds the killed rank's host via the
    autoscaler; the barrier's re-rendezvous then runs on the survivors
    with stable ranks and the job finishes clean — zero downtime."""
    rounds = []
    killed_once = threading.Event()

    def worker(slot):
        rounds.append((slot.hostname, slot.local_rank, slot.rank,
                       slot.size))
        if slot.hostname == "host-2" and not killed_once.is_set():
            killed_once.set()
            # stand-in for the SIGKILL the chaos harness raises
            # (HVT_FAULT_INJECT=kill:rank=2:after_ops=N)
            return 137
        return 0

    class SeqDiscovery:
        def find_available_hosts_and_slots(self):
            return {"host-1": 2, "host-2": 1}

    driver, rendezvous = _make_driver(SeqDiscovery(), min_np=2, max_np=3,
                                      worker_fn=worker)
    scaler = Autoscaler(driver, rendezvous,
                        AutoscalePolicy(backlog_threshold=1e9,
                                        sustain_sec=0, cooldown_sec=0))
    try:
        driver.start(3)
        # the survivor's report lands before host-2's exit trickles in
        rendezvous.store.put(
            "failure", "host-1/0",
            json.dumps({"round": 0,
                        "error": "hvt engine aborted (peer_lost: data "
                                 "connection to rank 2 lost)",
                        "failed_ranks": [2]}).encode())
        scaler.step(now=0.0)
        assert [a for _, a, _ in scaler.decisions] == ["shed"]
        assert driver.host_manager.blacklisted_count() >= 1
        assert driver.wait(15)
        assert driver.error is None, driver.error
        r1 = {(h, s): r for h, s, r, _ in rounds[:3]}
        r2 = {(h, s): r for h, s, r, _ in rounds[3:]}
        assert set(r2) == {("host-1", 0), ("host-1", 1)}  # host-2 shed
        for key in r2:
            assert r2[key] == r1[key]                     # ranks stable
    finally:
        driver.stop()
        rendezvous.stop()


# ------------------------------------------------------------- gang tests

needs_engine = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


@needs_engine
def test_concurrent_disjoint_sets_4proc():
    """Two disjoint 2-rank sets run independent allreduce streams:
    bit-exact per-set results, per-set cache lanes engaged (steady-state
    hits on set traffic), mixed set+global traffic in one cycle, and a
    saturated set not inflating the idle set's p99 (lane isolation)."""
    out = run_workers("""
        from horovod_tpu.common.process_sets import ProcessSet, add_process_set
        from horovod_tpu.engine import native
        from horovod_tpu.ops import collective_ops as C

        setA = add_process_set(ProcessSet([0, 1]))
        setB = add_process_set(ProcessSet([2, 3]))
        g = r // 2
        mine, other = (setA, setB) if g == 0 else (setB, setA)
        assert mine.included() and not other.included()
        try:
            C.allreduce(np.ones(4, np.float32), op=C.Sum, name="bad",
                        process_set=other)
            raise SystemExit(f"rank {r}: non-member submit did not raise")
        except ValueError:
            pass

        # independent per-set streams, distinct values/shapes per set —
        # results must be bit-exact sums over exactly the set's members
        numel = 96 if g == 0 else 160
        base = np.arange(numel, dtype=np.float32) * (g + 1)
        for k in range(12):
            x = base + np.float32(r % 2 + k)
            res = np.asarray(C.allreduce(x, op=C.Sum, name=f"st.{g}.{k}",
                                         process_set=mine))
            exp = 2 * base + np.float32(0 + k) + np.float32(1 + k)
            np.testing.assert_array_equal(res, exp)

        # steady-state lane cache: the SAME set tensor resubmitted must
        # produce cache hits (set-scoped responses are cacheable now).
        # 2-D on purpose: rank 0 is a NON-member of set B's lane, and
        # its cache copy must carry the true dims (a flattened stand-in
        # would poison the coordinator's hit-fold path)
        hot = np.ones((16, 16), np.float32) * (r + 1)
        for k in range(6):
            res = np.asarray(C.allreduce(hot, op=C.Sum, name=f"hot.{g}",
                                         process_set=mine))
            lo = 2 * g
            np.testing.assert_array_equal(
                res, np.ones((16, 16), np.float32) * ((lo + 1) + (lo + 2)))
        st = native.engine_stats()
        assert st["cache_hits"] > 0, f"rank {r}: no lane cache hits: {st['cache_hits']}"
        assert st["lanes_active"] >= 1, st["lanes_active"]
        assert sum(st["lane_exec_count"]) > 0

        # mixed set+global traffic in one cycle: async set op + global
        # op submitted back-to-back, both land
        ha = C.allreduce_async(np.full(32, np.float32(r % 2 + 1)),
                               op=C.Sum, name=f"mix.{g}",
                               process_set=mine)
        res_g = np.asarray(C.allreduce(np.full(8, np.float32(r + 1)),
                                       op=C.Sum, name="mix.global"))
        np.testing.assert_array_equal(res_g, np.full(8, np.float32(1+2+3+4)))
        np.testing.assert_array_equal(np.asarray(C.synchronize(ha)),
                                      np.full(32, np.float32(3)))

        # lane isolation: set B measures its latency twice — idle gang,
        # then with set A saturating its own lane. One engine thread per
        # PROCESS means B's ranks never execute A's responses; the
        # shared cost is only rank 0's coordination.
        C.barrier()
        def measure(tag, nops=25):
            lat = []
            y = np.ones(64, np.float32)
            for k in range(nops):
                t0 = time.perf_counter()
                C.allreduce(y, op=C.Sum, name=f"p99.{tag}.{k}",
                            process_set=setB)
                lat.append(time.perf_counter() - t0)
            return np.percentile(np.asarray(lat), 99)
        idle_p99 = measure("idle") if g == 1 else None
        C.barrier()
        if g == 0:
            z = np.ones(2048, np.float32)
            for k in range(120):
                C.allreduce(z, op=C.Sum, name=f"sat.{k}", process_set=setA)
        else:
            busy_p99 = measure("busy")
        C.barrier()
        if g == 1 and r == 2:
            ratio = busy_p99 / max(idle_p99, 1e-9)
            print(f"P99-RATIO {ratio:.3f} idle={idle_p99*1e3:.2f}ms "
                  f"busy={busy_p99*1e3:.2f}ms", flush=True)
            # generous CI bound; the committed benchmark artifact pins
            # the 25% isolation claim under controlled load
            assert busy_p99 < max(8 * idle_p99, idle_p99 + 0.25), \
                (idle_p99, busy_p99)
    """, np_=4, timeout=240)
    assert "P99-RATIO" in out


@needs_engine
def test_batching_determinism_under_clock_skew():
    """ISSUE 15 satellite: every replica member computes the identical
    (admitted, shed, batch-boundary) tuple sequence under bursty load
    even when HVT_FAULT_INJECT=delay_ms skews one member's clock — the
    decisions are pure functions of the aligned call history, never of
    timing — and the batched path's results are bit-identical to the
    unbatched path's. Replicas are 2-wide, so fp32 addition is
    commutative-exact and bitwise comparison is safe for arbitrary
    floats."""
    out = run_workers("""
        import zlib
        from horovod_tpu.ops.functions import allgather_object
        from horovod_tpu.serving import ReplicaGang

        rng = np.random.default_rng(7)
        payloads = [rng.standard_normal(192).astype(np.float32)
                    for _ in range(36)]

        def drive(gang):
            outs = []
            k = 0
            # bursty: 7 submits back-to-back (window 5 → sheds), then
            # reap down; the SEQUENCE is identical on every member
            while k < len(payloads):
                for _ in range(min(7, len(payloads) - k)):
                    gang.submit_request(payloads[k])
                    k += 1
                while gang.backlog() > 2:
                    res = gang.reap()
                    outs.extend(res if isinstance(res, list) else [res])
            gang.flush()
            while gang.backlog():
                res = gang.reap()
                outs.extend(res if isinstance(res, list) else [res])
            return outs

        batched = ReplicaGang(2, admission_timeout=2.0, max_backlog=5,
                              batch_window=3, name="bd.b")
        outs_b = drive(batched)
        hvt.barrier()
        unbatched = ReplicaGang(2, admission_timeout=2.0, max_backlog=5,
                                batch_window=1, name="bd.u")
        outs_u = drive(unbatched)
        hvt.barrier()

        # decision tuples member-identical (delay_ms skews rank 1's
        # clock; see extra_env), batch boundaries included
        recs = allgather_object(
            {"rank": r, "replica": batched.replica_id,
             "decisions": list(batched.decisions),
             "admitted": batched.stats.admitted,
             "shed": batched.stats.shed,
             "batches": batched.stats.batches},
            name="bd.gather")
        if r == 0:
            by_rep = {}
            for rec in recs:
                by_rep.setdefault(rec["replica"], []).append(rec)
            for rep, members in by_rep.items():
                base = members[0]
                for mbr in members[1:]:
                    assert mbr["decisions"] == base["decisions"], \
                        (rep, mbr["rank"])
                    assert (mbr["admitted"], mbr["shed"],
                            mbr["batches"]) == (base["admitted"],
                                                base["shed"],
                                                base["batches"])
            assert base["shed"] > 0, "burst 7 > window 5 must shed"
            assert base["batches"] < base["admitted"], \
                "batching must coalesce requests into fewer submissions"
        # bit-identity: batched results == unbatched results, in order
        assert len(outs_b) == len(outs_u) == batched.stats.completed
        crc_b = zlib.crc32(b"".join(np.asarray(o).tobytes()
                                    for o in outs_b))
        crc_u = zlib.crc32(b"".join(np.asarray(o).tobytes()
                                    for o in outs_u))
        assert crc_b == crc_u, (crc_b, crc_u)
        if r == 0:
            print("BATCH-DETERMINISM-OK", flush=True)
    """, np_=4, timeout=240,
        extra_env={"HVT_FAULT_INJECT": "delay_ms:rank=1:20"})
    assert "BATCH-DETERMINISM-OK" in out


def _uring_ok():
    try:
        from horovod_tpu.engine import native
        return native.uring_supported()
    except Exception:
        return False


@needs_engine
@pytest.mark.parametrize("backend", [
    "tcp", pytest.param("io_uring", marks=pytest.mark.skipif(
        not _uring_ok(), reason="io_uring kernel probe failed"))])
def test_lane_pool_parity_and_engagement(backend):
    """HVT_LANE_WORKERS A/B on a real 3-rank gang with two overlapping
    lanes ({0,1} hot, {0,2} idle — they share only rank 0, so the pool
    may run them concurrently): results are bit-identical to the
    single-thread engine, and the pool actually executed tasks (the
    isolation RATIO is pinned by benchmarks/serving_soak.py under
    controlled load, not by this CI box). Parameterized over link
    backends — concurrent lane workers pumping overlapping links is
    the owner-token-claim contract (ProbeAndRepair must SKIP a link
    another thread drives), which each backend's pump must honor."""
    body = """
        import zlib
        from horovod_tpu.common.process_sets import ProcessSet, add_process_set
        from horovod_tpu.engine import native
        from horovod_tpu.ops import collective_ops as C

        laneA = add_process_set(ProcessSet([0, 1]))
        laneB = add_process_set(ProcessSet([0, 2]))
        crc = 0
        for k in range(30):
            hs = []
            if r in (0, 1):
                hs.append(C.allreduce_async(
                    np.full(4096, np.float32(r + 1 + k % 3)), op=C.Sum,
                    name=f"lp.a.{k % 6}", process_set=laneA))
            if r in (0, 2):
                hs.append(C.allreduce_async(
                    np.full(64, np.float32(r + 2)), op=C.Sum,
                    name=f"lp.b.{k % 6}", process_set=laneB))
            for h in hs:
                crc = zlib.crc32(np.asarray(C.synchronize(h)).tobytes(),
                                 crc)
        res = np.asarray(C.allreduce(np.float32([crc % 65521]),
                                     op=C.Sum, name="lp.fin"))
        st = native.engine_stats()
        print(f"LANE-CRC rank={r} crc={crc} pool={st['lane_pool_tasks']}"
              f" workers={st['lane_workers']}", flush=True)
    """
    env0 = {"HVT_LANE_WORKERS": "0", "HVT_SHM_ALLREDUCE": "0",
            "HVT_LINK_BACKEND": backend}
    env2 = {"HVT_LANE_WORKERS": "2", "HVT_SHM_ALLREDUCE": "0",
            "HVT_LINK_BACKEND": backend}
    out0 = run_workers(body, np_=3, timeout=240, extra_env=env0)
    out2 = run_workers(body, np_=3, timeout=240, extra_env=env2)

    def crcs(out):
        found = {}
        for line in out.splitlines():
            if "LANE-CRC" not in line:
                continue  # launcher prefixes "[rank] " to worker lines
            fields = line[line.index("LANE-CRC"):].split()[1:]
            parts = dict(p.split("=") for p in fields)
            found[int(parts["rank"])] = (parts["crc"],
                                         int(parts["pool"]),
                                         int(parts["workers"]))
        return found

    c0, c2 = crcs(out0), crcs(out2)
    assert set(c0) == set(c2) == {0, 1, 2}
    for rank in c0:
        assert c0[rank][0] == c2[rank][0], \
            f"rank {rank}: pool changed results"  # bit-identical
    assert all(v[1] == 0 and v[2] == 0 for v in c0.values())
    assert all(v[2] == 2 for v in c2.values())
    assert c2[0][1] > 0, "pool never engaged on the shared rank"


@needs_engine
def test_replica_gang_loadgen_artifact_4proc(tmp_path):
    """Loopback ReplicaGang replay end to end: artifact schema-valid,
    shed-on-backlog exercised (burst > window) with IDENTICAL admission
    accounting on every member of a replica — the alignment property
    that keeps a shed from wedging the lane."""
    out = run_workers("""
        from horovod_tpu.serving import loadgen as lg
        args = lg._parser().parse_args([
            "--replicas", "2", "--requests", "18", "--bytes", "2048",
            "--burst", "6", "--window", "4", "--admission-ms", "500",
            "--gap-ms", "0.5", "--sync-every", "6",
            "--saturate-factor", "2"])
        doc = lg.run_loadtest(args)
        if r == 0:
            errs = lg.validate_artifact(doc)
            assert errs == [], errs
            assert set(doc["phases"]) == {"baseline", "contended"}
            total_shed = sum(s["shed"]
                             for p in doc["phases"].values()
                             for s in p["ranks"])
            assert total_shed > 0, "burst 6 > window 4 must shed"
            for pname, phase in doc["phases"].items():
                by_rep = {}
                for s in phase["ranks"]:
                    by_rep.setdefault(s["replica"], set()).add(
                        (s["admitted"], s["shed"]))
                for rep, states in by_rep.items():
                    assert len(states) == 1, (pname, rep, states)
            iso = doc["isolation"]
            assert iso["idle_p99_ms"] > 0 and iso["contended_p99_ms"] > 0
            print("ARTIFACT-OK", flush=True)
    """, np_=4, timeout=240)
    assert "ARTIFACT-OK" in out
