"""Fleet telemetry plane (PR 13): merge/push/rollup units, the
rendezvous KV staleness hygiene, the /statusz health rules, hvt_top,
and real fault-injected MiniEngine gangs.

The gang tests drive the acceptance pins: an injected straggler
(``delay_ms``) and a ``flaky_conn`` flap each surface in ``/statusz``
alerts within one push window; ``hvt_top --once --json`` round-trips
the same view; and a clean gang raises NO alerts with the rules at
their most trigger-happy thresholds (the false-positive pin). Workers
are the featherweight ctypes MiniEngines of
``benchmarks/telemetry_scaling.py`` (no jax/numpy per worker), so a
4-rank gang costs seconds.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build",
                   "libhvt_core.so")

sys.path.insert(0, REPO)
from benchmarks import ctrl_plane_scaling as cps  # noqa: E402
from benchmarks import telemetry_scaling as ts  # noqa: E402

from horovod_tpu.metrics import telemetry as T  # noqa: E402
from horovod_tpu.runner.http_server import RendezvousServer  # noqa: E402,F401

# module-wide: the gang tests need the engine .so; the units share the
# mark for uniformity with test_ctrl_plane (conftest builds it anyway)
pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


# ------------------------------------------------------------------- units

def test_interval_env_and_jitter(monkeypatch):
    monkeypatch.delenv("HVT_DEBUGZ_INTERVAL_MS", raising=False)
    assert T.interval_sec() == 5.0
    monkeypatch.setenv("HVT_DEBUGZ_INTERVAL_MS", "800")
    assert T.interval_sec() == 0.8
    vals = {T.jittered(4.0) for _ in range(200)}
    assert all(3.0 <= v <= 5.0 for v in vals), "±25% jitter band"
    assert len(vals) > 100, "jitter must actually vary"


def test_role_matrix(monkeypatch):
    for var in ("HVT_TELEMETRY_ROLE", "HVT_TELEMETRY_AGG",
                "HVT_CTRL_TOPOLOGY", "HVT_LOCAL_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    # star topology (default) → direct, regardless of local id
    monkeypatch.setenv("HVT_LOCAL_PROCESS_ID", "0")
    assert T.telemetry_role() == "direct"
    # tree topology → leader/member split by local process id
    monkeypatch.setenv("HVT_CTRL_TOPOLOGY", "tree")
    assert T.telemetry_role() == "leader"
    monkeypatch.setenv("HVT_LOCAL_PROCESS_ID", "2")
    assert T.telemetry_role() == "member"
    # forced off under tree → star fallback
    monkeypatch.setenv("HVT_TELEMETRY_AGG", "0")
    assert T.telemetry_role() == "direct"
    # forced on under star
    monkeypatch.setenv("HVT_TELEMETRY_AGG", "1")
    monkeypatch.setenv("HVT_CTRL_TOPOLOGY", "star")
    assert T.telemetry_role() == "member"
    # unknown or malformed local id → direct is the only safe answer
    # (a raise here would silently kill the daemon push thread)
    monkeypatch.delenv("HVT_LOCAL_PROCESS_ID")
    assert T.telemetry_role() == "direct"
    monkeypatch.setenv("HVT_LOCAL_PROCESS_ID", "not-a-number")
    assert T.telemetry_role() == "direct"
    # explicit override beats everything
    monkeypatch.setenv("HVT_TELEMETRY_ROLE", "leader")
    assert T.telemetry_role() == "leader"


def test_normalize_stats_flat_manifest_form():
    flat = {"cycles": 7, "ctrl_tx_bytes": 9,
            "lane_depth[0]": 1, "lane_depth[2]": 5,
            "wire_tx_bytes[allreduce]": 10,
            "wire_tx_bytes[allgather]": 3,
            "link_reconnects[ctrl]": 1, "link_reconnects[data]": 2}
    out = T._normalize_stats(flat)
    assert out["lane_depth"] == [1, 0, 5]
    assert out["wire_tx_bytes"] == {"allreduce": 10, "allgather": 3}
    assert out["link_reconnects"] == {"ctrl": 1, "data": 2}
    assert out["cycles"] == 7
    # decoded input passes through untouched
    dec = {"lane_depth": [1, 2], "cycles": 3}
    assert T._normalize_stats(dec) == dec


def _diag(rank=0, queue=2, negotiations=None, links=None):
    return {
        "engine": {"running": True, "rank": rank, "size": 4,
                   "cycles": 10, "queue_depth": queue,
                   "wire": {"intra": "none", "inter": "int8",
                            "auto": False},
                   "broken": False},
        "pending": [{"tensor": "t", "age_sec": 0.1, "lane": 0}],
        "links": links or [],
        "negotiations": negotiations or [],
        "stalls": [],
    }


def test_build_snapshot_compact_and_counters():
    stats = {"cycles": 10, "cache_hits": 1, "ctrl_tx_bytes": 100,
             "ctrl_rx_bytes": 60, "wire_tx_bytes": {"allreduce": 40},
             "lane_depth": [2, 0, 0, 0, 0, 0, 0, 0],
             "link_reconnects": {"ctrl": 0, "data": 1},
             "ef_residual_bytes": 8}
    links = [{"peer": 1, "plane": "data", "state": "healthy",
              "retries": 0, "epoch": 0, "in_state_sec": 1.0},
             {"peer": 2, "plane": "data", "state": "reconnecting",
              "retries": 1, "epoch": 1, "in_state_sec": 0.2}]
    neg = [{"tensor": "x", "waiting_sec": 0.9, "missing_ranks": [3],
            "arrived_ranks": [0, 1, 2]},
           {"tensor": "y", "waiting_sec": 0.1, "missing_ranks": [],
            "arrived_ranks": [0, 1, 2, 3]}]
    snap = T.build_snapshot(0, "h0", _diag(negotiations=neg,
                                           links=links), stats)
    tel = snap["telemetry"]
    assert tel["queue_depth"] == 2 and tel["pending"] == 1
    assert tel["links"]["reconnecting"] == [2]
    assert tel["links"]["healthy"] == 1
    assert tel["bytes"] == {"ctrl_tx": 100, "ctrl_rx": 60,
                            "wire_tx": 40, "ef_residual": 8}
    # only negotiations with missing ranks are straggler evidence
    assert [n["tensor"] for n in tel["negotiations"]] == ["x"]
    assert "stats" not in snap  # raw stats never ride the wire
    from horovod_tpu.metrics import merge as M
    assert M.counter_total(snap["metrics"],
                           "hvt_ctrl_tx_bytes_total") == 100


def test_host_frame_merge_is_sum_identical():
    from horovod_tpu.metrics import merge as M

    members, ages = {}, {}
    for r, ctrl in ((0, 100), (1, 250), (2, 13)):
        members[r] = T.build_snapshot(
            r, "h0", _diag(rank=r), {"ctrl_tx_bytes": ctrl})
        ages[r] = 0.1 * r
    frame = T.build_host_frame("h0", 0, members, ages, 5.0)
    assert sorted(int(r) for r in frame["ranks"]) == [0, 1, 2]
    assert M.counter_total(frame["metrics"],
                           "hvt_ctrl_tx_bytes_total") == 363
    assert frame["metrics"]["ranks"] == [0, 1, 2]


def test_host_aggregator_http_ingest():
    agg = T.HostAggregator()
    port = agg.start()
    try:
        from horovod_tpu.runner.http_client import put_bytes
        put_bytes(f"127.0.0.1:{port}", "/push/3",
                  json.dumps({"rank": 3, "x": 1}).encode(), retries=0)
        snaps, ages = agg.members()
        assert snaps[3]["x"] == 1 and ages[3] < 5
        # garbage body → 400, not a crash
        import urllib.request, urllib.error
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/push/4", data=b"{nope",
            method="PUT")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req, timeout=5)
        # stale members drop out of the fold
        snaps, _ = agg.members(now=time.monotonic() + 1e4,
                               max_age_sec=60)
        assert snaps == {}
    finally:
        agg.stop()


def _mk_server(np_=2, hosts=1):
    server, addr = ts.start_driver(np_, hosts)
    return server, addr


def test_pusher_direct_and_leader_member_roundtrip():
    server, addr = _mk_server(np_=3)
    stop = threading.Event()
    try:
        snap_of = lambda r: (lambda: T.build_snapshot(
            r, "h0", _diag(rank=r), {"ctrl_tx_bytes": 10 * (r + 1)}))
        # leader on h0
        leader = T.TelemetryPusher(addr, 0, snap_of(0), stop,
                                   host="h0", role="leader",
                                   period_sec=0.2)
        assert leader.step()
        ep = json.loads(server.store.get("telemetry", "ep/h0"))
        assert ep["rank"] == 0 and ep["addr"].startswith("127.0.0.1:")
        # member discovers the endpoint from the KV and lands in the
        # leader's next frame
        member = T.TelemetryPusher(addr, 1, snap_of(1), stop,
                                   host="h0", role="member",
                                   period_sec=0.2)
        assert member.step()
        assert leader.step()
        frame = json.loads(server.store.get("telemetry", "host/h0"))
        assert sorted(frame["ranks"]) == ["0", "1"]
        from horovod_tpu.metrics import merge as M
        assert M.counter_total(frame["metrics"],
                               "hvt_ctrl_tx_bytes_total") == 30
        # direct role writes the legacy per-rank key
        direct = T.TelemetryPusher(addr, 2, snap_of(2), stop,
                                   host="h0", role="direct",
                                   period_sec=0.2)
        assert direct.step()
        assert server.store.get("debugz", "2") is not None
    finally:
        stop.set()
        leader.close()
        server.stop()


def test_member_falls_back_to_direct_when_leader_dies():
    server, addr = _mk_server(np_=2)
    stop = threading.Event()
    try:
        member = T.TelemetryPusher(
            addr, 1, lambda: T.build_snapshot(1, "h0", _diag(rank=1),
                                              {}),
            stop, host="h0", role="member", period_sec=0.1)
        # no leader endpoint exists at all → discovery fails, and after
        # _FALLBACK_AFTER ticks the push degrades to the direct key
        for _ in range(member._FALLBACK_AFTER - 1):
            assert not member.step()
        assert member.step()  # fallback push succeeded
        assert server.store.get("debugz", "1") is not None
    finally:
        stop.set()
        server.stop()


# ------------------------------------------ leader-routed KV relay (r14)

def test_relay_put_direct_when_disabled(monkeypatch):
    monkeypatch.setenv("HVT_KV_RELAY", "0")
    server, addr = _mk_server(np_=1)
    try:
        assert T.relay_put(addr, "failure", "h0/0", {"round": 1})
        assert json.loads(server.store.get("failure", "h0/0")) == \
            {"round": 1}
        assert server.store.ingest_stats()["put_requests"]["failure"] \
            == 1
    finally:
        server.stop()


def test_relay_routes_through_leader_and_kvbulk(monkeypatch):
    """Member envelopes land via the leader's ONE /kvbulk request:
    same (scope, key, value) in the store, but per-request accounting
    counts the batch once — the O(hosts) fan-in mechanism."""
    monkeypatch.setenv("HVT_KV_RELAY", "1")
    monkeypatch.setenv("HVT_TOPO_HOST", "h0")
    T._relay_ep_cache.clear()
    server, addr = _mk_server(np_=2)
    stop = threading.Event()
    leader = T.TelemetryPusher(addr, 0, lambda: {"rank": 0}, stop,
                               host="h0", role="leader",
                               period_sec=0.2)
    try:
        leader.step()  # publish the endpoint
        hook_hits = []
        server.set_put_hook(
            lambda scope, key, val: hook_hits.append((scope, key)))
        # urgent envelopes: debounce-flushed as one bulk request
        assert T.relay_put(addr, "failure", "h0/0",
                           {"failed_ranks": [1]}, urgent=True)
        assert T.relay_put(addr, "state", "h0/0",
                           {"state": "READY", "round": 1}, urgent=True)
        deadline = time.monotonic() + 5
        while server.store.get("state", "h0/0") is None:
            assert time.monotonic() < deadline, "bulk flush never landed"
            time.sleep(0.02)
        assert json.loads(server.store.get("failure", "h0/0")) == \
            {"failed_ranks": [1]}
        # the put hook fired per entry (driver semantics preserved)
        assert ("failure", "h0/0") in hook_hits
        assert ("state", "h0/0") in hook_hits
        reqs = server.store.ingest_stats()["put_requests"]
        # both envelopes coalesced into one debounced batch
        assert reqs.get("failure", 0) == 1
        assert reqs.get("state", 0) == 1
        # non-urgent rides the next pusher tick
        assert T.relay_put(addr, "recovery", "h0/0",
                           {"phase": "rebuild", "outcome": "ok"})
        assert server.store.get("recovery", "h0/0") is None
        leader.step()
        assert server.store.get("recovery", "h0/0") is not None
    finally:
        stop.set()
        leader.close()
        server.stop()
        T._relay_ep_cache.clear()


def test_relay_falls_back_to_direct_without_leader(monkeypatch):
    monkeypatch.setenv("HVT_KV_RELAY", "1")
    monkeypatch.setenv("HVT_TOPO_HOST", "h9")
    T._relay_ep_cache.clear()
    server, addr = _mk_server(np_=1)
    try:
        # no leader endpoint published for h9 → the PUT still lands
        assert T.relay_put(addr, "failure", "h9/0", {"round": 2},
                           urgent=True)
        assert json.loads(server.store.get("failure", "h9/0")) == \
            {"round": 2}
    finally:
        server.stop()
        T._relay_ep_cache.clear()


def test_kvbulk_endpoint_validates_and_counts():
    import base64
    import urllib.error
    import urllib.request

    from horovod_tpu.runner.http_client import put_bytes

    server, addr = _mk_server(np_=1)
    try:
        envs = [{"scope": "serving", "key": str(i),
                 "value_b64": base64.b64encode(
                     json.dumps({"i": i}).encode()).decode()}
                for i in range(5)]
        put_bytes(addr, "/kvbulk", json.dumps(envs).encode(),
                  retries=0)
        assert sorted(server.store.keys("serving")) == \
            sorted(str(i) for i in range(5))
        # 5 entries, ONE request
        assert server.store.ingest_stats()["put_requests"]["serving"] \
            == 1
        req = urllib.request.Request(f"http://{addr}/kvbulk",
                                     data=b"{not-a-list", method="PUT")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req, timeout=5)
        # malformed entries are skipped, valid ones land
        mixed = [{"nope": 1}, {"scope": "serving", "key": "ok",
                 "value_b64": base64.b64encode(b"1").decode()}]
        put_bytes(addr, "/kvbulk", json.dumps(mixed).encode(),
                  retries=0)
        assert server.store.get("serving", "ok") == b"1"
    finally:
        server.stop()


def test_statusz_recovery_rows():
    server, addr = _mk_server(np_=1)
    try:
        server.store.put("recovery", "h0/0", json.dumps(
            {"phase": "rebuild", "outcome": "peer", "seconds": 0.4,
             "round": 2}).encode())
        server.store.put("recovery", "h1/0", json.dumps(
            {"phase": "recovered", "outcome": "ok", "seconds": 2.2,
             "round": 2}).encode())
        doc = server.statusz_snapshot()
        rec = doc["recovery"]
        assert rec["reports"] == 2
        assert rec["by_phase"] == {"rebuild": 1, "recovered": 1}
        assert rec["by_outcome"] == {"peer": 1, "ok": 1}
        assert rec["max_seconds"] == 2.2
        assert rec["ranks"]["h0/0"]["phase"] == "rebuild"
    finally:
        server.stop()


# ------------------------------------------------- KV staleness (satellite)

def test_store_timestamps_and_ttl_sweep():
    from horovod_tpu.runner.http_server import _Store

    st = _Store()
    st.put("debugz", "0", b"x" * 10, now=100.0)
    st.put("telemetry", "host/h0", b"y" * 20, now=150.0)
    st.put("timeline", "0", b"shard", now=0.0)
    assert st.age("debugz", "0", now=103.0) == 3.0
    assert st.age("debugz", "missing") is None
    assert st.ingest_stats()["put_bytes"] == {
        "debugz": 10, "telemetry": 20, "timeline": 5}
    # sweep prunes only expired telemetry-stream entries...
    removed = st.sweep(60.0, now=170.0)
    assert removed == [("debugz", "0")]
    assert st.get("debugz", "0") is None
    assert st.get("telemetry", "host/h0") is not None
    # ...and NEVER timeline/workers scopes, however old
    assert st.sweep(0.001, now=1e9,
                    scopes=("serving", "debugz", "telemetry")) == [
        ("telemetry", "host/h0")]
    assert st.get("timeline", "0") == b"shard"
    # ttl 0 disables
    st.put("debugz", "1", b"z", now=0.0)
    assert st.sweep(0, now=1e9) == []


def test_clear_keeps_meta_in_sync():
    from horovod_tpu.runner.http_server import _Store

    st = _Store()
    st.put("debugz", "0", b"x", now=1.0)
    st.put("scratch", "k", b"y", now=1.0)
    st.clear(keep_scopes=("debugz",))
    assert st.age("debugz", "0", now=2.0) == 1.0
    assert st.age("scratch", "k") is None


def test_statusz_stale_records_feed_no_straggler_evidence(monkeypatch):
    """A dead pusher's frozen arrival table must NOT re-feed the same
    transient negotiation every build — stale sources are excluded
    from straggler evidence, so a healthy rank can't accumulate a
    false persistence alert off one frozen snapshot."""
    monkeypatch.setenv("HVT_KV_TTL_SEC", "1000")
    server, addr = _mk_server(np_=2)
    try:
        neg = [{"tensor": "x", "waiting_sec": 0.9,
                "missing_ranks": [1], "arrived_ranks": [0]}]
        snap = T.build_snapshot(0, "h0", _diag(rank=0,
                                               negotiations=neg), {})
        base = 1000.0  # synthetic clock shared by puts and builds
        server.store.put("debugz", "0", json.dumps(snap).encode(),
                         now=base - 100)  # long dead
        builder = T.StatuszBuilder(T.HealthEngine(
            straggler_windows=1, alert_counter=False))
        for i in range(3):
            doc = builder.build(server.store, {"size": 2}, 1,
                                now=base + 10 * i)
        assert doc["stragglers"] == []
        assert not any(a["rule"] == "straggler" for a in doc["alerts"])
        # the same blob, FRESH, is evidence (control case)
        server.store.put("debugz", "0", json.dumps(snap).encode(),
                         now=base + 30)
        doc = builder.build(server.store, {"size": 2}, 1,
                            now=base + 30)
        assert any(a["rule"] == "straggler" for a in doc["alerts"])
    finally:
        server.stop()


def test_statusz_marks_stale_before_ttl_drops(monkeypatch):
    monkeypatch.setenv("HVT_KV_TTL_SEC", "1000")
    server, addr = _mk_server(np_=1)
    try:
        snap = T.build_snapshot(0, "h0", _diag(rank=0), {})
        server.store.put("debugz", "0", json.dumps(snap).encode(),
                         now=time.monotonic() - 100)
        doc = server.statusz_snapshot()
        assert doc["ranks"]["0"]["stale"] is True
        assert any(a["rule"] == "push_stale" for a in doc["alerts"])
    finally:
        server.stop()


def test_statusz_serving_excludes_ghost_lanes(monkeypatch):
    """A dead rank's final serving snapshot (kept across round resets,
    not yet TTL-swept) and an out-of-world rank id from a re-shard must
    NOT feed the live backlog signal — the serving fold applies the
    same last-write-timestamp staleness as the rank records, so
    /statusz, hvt_top and the autoscaler's alert feed stop displaying
    the ghost lane."""
    monkeypatch.setenv("HVT_KV_TTL_SEC", "1000")
    server, addr = _mk_server(np_=2)
    try:
        base = 1000.0
        live = {"rank": 0, "replica": 0, "inflight": 2, "shed": 1,
                "p99_ms": 3.0}
        ghost = {"rank": 1, "replica": 1, "inflight": 99, "shed": 50,
                 "p99_ms": 9.0}
        shrunk = {"rank": 7, "replica": 3, "inflight": 88, "shed": 10,
                  "p99_ms": 9.0}
        server.store.put("serving", "0", json.dumps(live).encode(),
                         now=base - 1)
        server.store.put("serving", "1", json.dumps(ghost).encode(),
                         now=base - 500)  # long dead, inside the TTL
        server.store.put("serving", "7", json.dumps(shrunk).encode(),
                         now=base - 1)    # fresh but outside the world
        builder = T.StatuszBuilder(T.HealthEngine(alert_counter=False))
        doc = builder.build(server.store, {"size": 2}, 1, now=base)
        serving = doc["serving"]
        assert serving["ranks"] == 1
        assert serving["stale_ranks"] == 2
        assert serving["inflight_max"] == 2      # ghost 99/88 excluded
        assert serving["shed_total"] == 1
        assert set(serving["lanes"]) == {"0"}    # only the live lane
        assert serving["lanes"]["0"]["p99_ms_max"] == 3.0
        # hvt_top renders the stale count, not a live ghost lane
        from horovod_tpu.tools import hvt_top

        text = hvt_top.render(doc)
        assert "+2 stale" in text and "backlog max 2" in text
    finally:
        server.stop()


# ---------------------------------------------------------- health engine

def test_health_rules_fire_and_clear():
    he = T.HealthEngine(straggler_windows=2, reconnect_storm=2,
                        stale_intervals=3, backlog_windows=2,
                        alert_counter=False)
    base = {"interval_sec": 1.0, "reconnect_total": 0,
            "rank_ages": {0: 0.1}, "backlog": 0}
    assert he.observe(dict(base), now=0.0) == []
    a = he.observe(dict(base, reconnect_total=3, backlog=1,
                        stragglers={2: ["t"]}), now=1.0)
    assert [x["rule"] for x in a] == ["reconnect_storm"]
    a = he.observe(dict(base, reconnect_total=3, backlog=2,
                        rank_ages={0: 0.1, 1: 9.9},
                        stragglers={2: ["t"]}), now=2.0)
    assert sorted(x["rule"] for x in a) == [
        "push_stale", "reconnect_storm", "serving_backlog",
        "straggler"]
    straggler = next(x for x in a if x["rule"] == "straggler")
    assert straggler["subject"] == "rank 2" and straggler["windows"] == 2
    # a 10 Hz dashboard cannot fast-forward the windows
    a2 = he.observe(dict(base, reconnect_total=99), now=2.05)
    assert a2 == a and he.windows == 3
    # conditions clear → alerts clear (storm drains its lookback)
    for i in range(4):
        a = he.observe(dict(base, reconnect_total=3), now=3.0 + i)
    assert a == []
    assert he.straggler_ranking()[0] == {
        "rank": 2, "windows": 2, "consecutive": 0, "tensors": ["t"]}


def test_health_alert_counter_increments_once_per_activation():
    fired = []

    class FakeCounter:
        def labels(self, rule):
            fired.append(rule)
            return self

        def inc(self):
            pass

    he = T.HealthEngine(straggler_windows=1, reconnect_storm=1,
                        stale_intervals=3, backlog_windows=2,
                        alert_counter=FakeCounter())
    he.observe({"interval_sec": 1.0, "stragglers": {1: ["t"]}},
               now=0.0)
    he.observe({"interval_sec": 1.0, "stragglers": {1: ["t"]}},
               now=1.0)
    assert fired == ["straggler"], "active alert must not re-count"


# ----------------------------------------------------------- statusz modes

def _put_frame(server, host, members, now=None):
    frame = T.build_host_frame(
        host, min(members),
        {r: T.build_snapshot(r, host, _diag(rank=r),
                             {"ctrl_tx_bytes": 100})
         for r in members},
        {r: 0.0 for r in members}, 1.0)
    server.store.put("telemetry", f"host/{host}",
                     json.dumps(frame).encode(),
                     now=now if now is not None else time.monotonic())


def test_statusz_leader_direct_and_mixed_modes():
    server, addr = _mk_server(np_=5, hosts=2)
    try:
        _put_frame(server, "h0", [0, 1])
        doc = server.statusz_snapshot()
        assert doc["mode"] == "leader"
        assert doc["ranks_covered"] == 2
        assert doc["missing_ranks"] == [2, 3, 4]
        # a direct rank joins → mixed
        snap = T.build_snapshot(4, "h1", _diag(rank=4), {})
        server.store.put("debugz", "4", json.dumps(snap).encode())
        doc = server.statusz_snapshot()
        assert doc["mode"] == "mixed"
        assert doc["ranks_covered"] == 3
        assert doc["hosts"]["h0"]["ranks"] == [0, 1]
        assert doc["totals"]["ctrl_bytes"] == 200  # leader ranks only
    finally:
        server.stop()


def test_statusz_rates_from_successive_builds():
    server, addr = _mk_server(np_=2, hosts=1)
    try:
        now = time.monotonic()
        _put_frame(server, "h0", [0, 1], now=now)
        server.statusz_snapshot(now=now)

        def frame_with(ctrl):
            return T.build_host_frame(
                "h0", 0,
                {r: T.build_snapshot(r, "h0", _diag(rank=r),
                                     {"ctrl_tx_bytes": ctrl})
                 for r in (0, 1)}, {0: 0.0, 1: 0.0}, 1.0)

        server.store.put("telemetry", "host/h0",
                         json.dumps(frame_with(600)).encode(),
                         now=now + 10)
        doc = server.statusz_snapshot(now=now + 10)
        # 2 ranks × (600-100) ctrl_tx over 10 s = 100 B/s
        assert doc["rates"]["window_sec"] == 10.0
        assert doc["rates"]["ctrl_bytes_per_sec"] == 100.0
    finally:
        server.stop()


def test_statusz_http_route_and_ingest_accounting():
    server, addr = _mk_server(np_=1)
    try:
        snap = T.build_snapshot(0, "h0", _diag(rank=0), {})
        from horovod_tpu.runner.http_client import put_bytes, get_json
        put_bytes(addr, "/kv/debugz/0", json.dumps(snap).encode(),
                  retries=0)
        doc = get_json(addr, "/statusz", retries=0)
        assert doc["schema"] == "hvt-statusz-r1"
        assert doc["ranks_covered"] == 1 and doc["mode"] == "direct"
        assert doc["ingest"]["put_count"]["debugz"] == 1
        assert doc["ingest"]["put_bytes"]["debugz"] == len(
            json.dumps(snap).encode())
        # /debugz still serves and now names telemetry hosts
        dz = get_json(addr, "/debugz", retries=0)
        assert "telemetry_hosts" in dz and dz["ranks"]["0"]
    finally:
        server.stop()


# ------------------------------------------------------ autoscaler feeds

def test_autoscaler_reads_backlog_from_host_frames():
    from horovod_tpu.runner.elastic.autoscaler import (Autoscaler,
                                                       AutoscalePolicy)

    server, addr = _mk_server(np_=4, hosts=1)
    try:
        frame = T.build_host_frame(
            "h0", 0,
            {r: T.build_snapshot(r, "h0", _diag(rank=r, queue=5 + r),
                                 {}) for r in range(4)},
            {r: 0.0 for r in range(4)}, 1.0)
        server.store.put("telemetry", "host/h0",
                         json.dumps(frame).encode())

        class Driver:
            def world_size(self):
                return 4

        sc = Autoscaler(Driver(), server, policy=AutoscalePolicy(
            backlog_threshold=8, sustain_sec=10, cooldown_sec=0,
            interval_sec=1))
        assert sc.read_backlog() == 8.0  # max queue_depth across ranks
        # out-of-world ranks in a stale frame are ignored
        class SmallDriver:
            def world_size(self):
                return 2

        sc2 = Autoscaler(SmallDriver(), server)
        assert sc2.read_backlog() == 6.0
    finally:
        server.stop()


def test_autoscaler_serving_backlog_alert_bypasses_sustain():
    from horovod_tpu.runner.elastic.autoscaler import (Autoscaler,
                                                       AutoscalePolicy)

    notified = []

    class Driver:
        def world_size(self):
            return 2

        def _worker_notify_addrs(self):
            return ["w0"]

        def _notify_workers_host_changes(self):
            notified.append(1)

        class host_manager:
            class current_hosts:
                @staticmethod
                def count_available_slots():
                    return 4

    class Rdv:
        def __init__(self, store, alerts):
            self.store = store
            self._alerts = alerts

        def statusz_snapshot(self):
            return {"alerts": self._alerts}

    from horovod_tpu.runner.http_server import _Store

    st = _Store()
    st.put("serving", "0", json.dumps({"inflight": 99}).encode())
    alert = [{"rule": "serving_backlog", "severity": "warn",
              "detail": "grew"}]
    policy = AutoscalePolicy(backlog_threshold=8, sustain_sec=1e6,
                             cooldown_sec=0, interval_sec=1)
    sc = Autoscaler(Driver(), Rdv(st, alert), policy=policy)
    sc.step(now=0.0)
    assert notified, "alert-sustained backlog must scale out"
    # without the alert, the absurd sustain window blocks
    notified.clear()
    sc2 = Autoscaler(Driver(), Rdv(st, []), policy=policy)
    sc2.step(now=0.0)
    assert not notified


# ------------------------------------------------------------------ hvt_top

def test_hvt_top_render_and_grid():
    from horovod_tpu.tools import hvt_top

    doc = {"schema": "hvt-statusz-r1", "world": {"size": 4},
           "round": 1, "mode": "leader", "ranks_expected": 4,
           "ranks_covered": 3, "missing_ranks": [3],
           "hosts": {"h0": {"ranks": [0, 1, 2]}},
           "ranks": {"0": {"queue_depth": 0, "pending": 0,
                           "links": {}},
                     "1": {"queue_depth": 3, "pending": 1,
                           "links": {}},
                     "2": {"stale": True, "links": {}}},
           "stragglers": [{"rank": 1, "windows": 2}],
           "rates": {"window_sec": 5.0, "ctrl_bytes_per_sec": 2048,
                     "wire_bytes_per_sec": 0, "ef_residual_bytes": 0},
           "codecs": {"intra": ["none"], "inter": ["int8"]},
           "serving": {"ranks": 1, "inflight_max": 2, "shed_total": 0},
           "alerts": [{"rule": "straggler", "severity": "warn",
                       "subject": "rank 1", "detail": "rank 1 late"}]}
    text = hvt_top.render(doc)
    assert "3/4 ranks" in text
    assert "!" in text and "s" in text and "_" in text  # grid states
    assert "[warn] straggler: rank 1 late" in text
    assert "stragglers: rank 1 (2 win)" in text
    assert "2.0 KB/s" in text
    assert "missing ranks: 3" in text


def test_hvt_top_once_json_roundtrip_in_process(capsys):
    from horovod_tpu.tools import hvt_top

    server, addr = _mk_server(np_=1)
    try:
        snap = T.build_snapshot(0, "h0", _diag(rank=0), {})
        server.store.put("debugz", "0", json.dumps(snap).encode())
        assert hvt_top.main(["--addr", addr, "--once", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert ts.check_statusz_doc(doc, 1) == []
        # human frame renders from the same endpoint
        assert hvt_top.main(["--addr", addr, "--once"]) == 0
        assert "hvt_top" in capsys.readouterr().out
    finally:
        server.stop()
    assert hvt_top.main(["--addr", "127.0.0.1:1", "--once"]) == 2
    capsys.readouterr()


# --------------------------------------------------------- fault gangs

# trigger-happy thresholds for BOTH the fault gangs and the clean pin:
# the pin is only meaningful if the clean gang survives the same
# hair-trigger settings that let the fault surface within one window
_GANG_HEALTH_ENV = {
    "HVT_HEALTH_STRAGGLER_WINDOWS": "1",
    "HVT_HEALTH_RECONNECT_STORM": "1",
    "HVT_HEALTH_STALE_INTERVALS": "8",
    "HVT_KV_TTL_SEC": "300",
    # the driver's statusz must use the gang's real push interval for
    # its window/staleness math in direct mode too (leader frames
    # carry it; direct snapshots don't)
    "HVT_DEBUGZ_INTERVAL_MS": "700",
}

_GANG_SPEC = {"interval_sec": 0.7, "work_sec": 18.0, "tensors": 2,
              "numel": 16, "step_sleep": 0.25, "cycle_ms": 2}


def _poll_gang(np_, hosts, mode, fault_env, predicate, on_hit=None,
               timeout=60, health_env=_GANG_HEALTH_ENV,
               spec=_GANG_SPEC):
    """Spawn a MiniEngine gang with live telemetry pushers, poll the
    in-process /statusz until ``predicate(doc)`` or timeout, run
    ``on_hit(server, addr, doc)`` while the gang is still alive, then
    tear everything down. Returns (hit_doc_or_None, last_doc,
    on_hit_result)."""
    old = {k: os.environ.get(k) for k in health_env}
    os.environ.update(health_env)
    server, kv = ts.start_driver(np_, hosts)
    procs = []
    hit = last = extra = None
    try:
        procs = ts.spawn_workers(
            np_, hosts, mode, spec, cps._next_port(), kv,
            extra_env=dict(health_env, **(fault_env or {})))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            last = server.statusz_snapshot()
            if predicate(last):
                hit = last
                if on_hit is not None:
                    extra = on_hit(server, kv, hit)
                break
            time.sleep(0.35)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return hit, last, extra


def test_gang_straggler_alert_and_hvt_top_roundtrip(capsys):
    """Acceptance: an injected straggler surfaces in /statusz alerts
    within one push window of the evidence, naming the rank — and
    hvt_top --once --json round-trips the same view.

    Rank 3 carries BOTH a delay_ms engine fault and a submit-side lag.
    The announce-visible evidence comes from the submit lag: an engine
    delay_ms alone sleeps between negotiation and the (gang-
    synchronous) ring transfer, so it slows every rank in lockstep and
    never skews rank 0's arrival table — which is itself a finding
    about what a straggler *is* at this layer."""
    def has_straggler(doc):
        return any(a["rule"] == "straggler" and a["subject"] == "rank 3"
                   for a in doc.get("alerts") or ())

    def roundtrip(server, addr, doc):
        from horovod_tpu.tools import hvt_top

        capsys.readouterr()
        assert hvt_top.main(["--addr", addr, "--once", "--json"]) == 0
        return json.loads(capsys.readouterr().out)

    hit, last, top_doc = _poll_gang(
        4, 2, "direct",
        {"HVT_FAULT_INJECT": "delay_ms:rank=3:200"}, has_straggler,
        on_hit=roundtrip,
        spec=dict(_GANG_SPEC, straggler_rank=3,
                  straggler_sleep_sec=1.5, steps=60))
    assert hit is not None, f"no straggler alert; last={last}"
    assert hit["ranks_covered"] == 4
    assert any(s["rank"] == 3 for s in hit["stragglers"])
    # the tool saw the same live view: schema-valid, alert present
    assert ts.check_statusz_doc(top_doc, 4) == []
    assert has_straggler(top_doc), "tool view must carry the alert"


def test_gang_flaky_conn_reconnect_storm_alert():
    """Acceptance: a flaky_conn flap surfaces as a reconnect_storm
    alert (and in the gang-wide reconnect counter) within one push
    window of the reconnect delta — through LEADER-aggregated frames."""
    def has_storm(doc):
        return any(a["rule"] == "reconnect_storm"
                   for a in doc.get("alerts") or ())

    hit, last, _ = _poll_gang(
        4, 2, "leader",
        {"HVT_FAULT_INJECT": "flaky_conn:rank=1:count=2:after_ops=6"},
        has_storm)
    assert hit is not None, f"no reconnect_storm alert; last={last}"
    assert hit["reconnect_total"] >= 1
    assert hit["mode"] == "leader"


def test_gang_clean_no_alerts_false_positive_pin():
    """Acceptance: NO alerts on a clean gang across several push
    windows — with the same hair-trigger thresholds the fault tests
    use — and the leader-merged counters stay sum-identical to the
    per-rank records."""
    seen = []

    def four_quiet_windows(doc):
        if doc.get("alerts"):
            seen.append(doc["alerts"])
            return True  # bail out: the pin already failed
        return (doc.get("health_windows", 0) >= 4
                and doc.get("ranks_covered") == 4)

    hit, last, _ = _poll_gang(4, 2, "leader", None, four_quiet_windows)
    assert hit is not None, f"gang never reached 4 windows: {last}"
    assert not seen, f"alerts on a clean gang: {seen}"
    assert hit["alerts"] == []
    assert hit["ranks_covered"] == 4
    assert hit["mode"] == "leader"
    cons = ts._consistency(hit)
    assert cons["identical"], cons
