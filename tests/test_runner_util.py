"""Runner utility tests — mpirun command construction, config file,
secret/codec/host-hash, probe services, and the programmatic run() API
(the reference's ``test/single/test_run.py`` — 58 tests of CLI parsing
and mpirun command construction with mocks — and ``test_service.py``)."""

import os
import sys

import pytest

from horovod_tpu.runner import codec, host_hash, network, secret
from horovod_tpu.runner.config_parser import apply_config, load_config
from horovod_tpu.runner.launch import parse_args
from horovod_tpu.runner.mpi_run import (MPICH, OPENMPI, build_mpirun_command,
                                        env_forward_args, env_from_mpi)
from horovod_tpu.runner.js_run import build_jsrun_command, lsf_hosts
from horovod_tpu.runner.probe import DriverProbe, TaskService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")


# -------------------------------------------------------------- secret

def test_secret_roundtrip():
    key = secret.make_secret_key()
    payload = b"host-update:1"
    digest = secret.compute_digest(key, payload)
    assert secret.check_digest(key, payload, digest)
    assert not secret.check_digest(key, b"tampered", digest)
    assert not secret.check_digest(secret.make_secret_key(), payload,
                                   digest)


def test_codec_roundtrip_closure():
    base = 10
    fn = lambda x: x + base  # noqa: E731
    encoded = codec.dumps_base64((fn, (5,)))
    fn2, args = codec.loads_base64(encoded)
    assert fn2(*args) == 15


def test_host_hash_stable_and_salted():
    assert host_hash.host_hash() == host_hash.host_hash()
    assert host_hash.host_hash("a") != host_hash.host_hash("b")
    assert host_hash.hosts_equivalent("localhost", "127.0.0.1")
    assert not host_hash.hosts_equivalent("localhost",
                                          "definitely-not-a-host-xyz")


# ------------------------------------------------------------- mpi_run

def test_mpirun_command_openmpi():
    cmd = build_mpirun_command(
        4, "h1:2,h2:2", ["python", "train.py"],
        {"HVT_MASTER_ADDR": "h1", "PATH": "/bin", "SECRET": "x"},
        impl=OPENMPI, ssh_port=2222)
    s = " ".join(cmd)
    assert cmd[0] == "mpirun" and "-np 4" in s
    assert "-H h1:2,h2:2" in s
    assert "--tag-output" in s
    assert "-x HVT_MASTER_ADDR" in s and "-x PATH" in s
    assert "SECRET" not in s          # only HVT_*/PATH/PYTHONPATH forwarded
    assert "plm_rsh_args" in s and "-p 2222" in s
    assert cmd[-2:] == ["python", "train.py"]


def test_mpirun_command_mpich():
    cmd = build_mpirun_command(2, "h1:1,h2:1", ["python", "t.py"],
                               {"HVT_MASTER_ADDR": "h1"}, impl=MPICH)
    s = " ".join(cmd)
    assert "-hosts h1,h2" in s
    assert "-genvlist" in s and "HVT_MASTER_ADDR" in s


def test_mpirun_large_cluster_flags():
    hosts = ",".join(f"h{i}:1" for i in range(80))
    cmd = build_mpirun_command(80, hosts, ["x"], {}, impl=OPENMPI)
    assert "plm_rsh_no_tree_spawn" in " ".join(cmd)


def test_env_forward_args():
    assert env_forward_args(OPENMPI, ["A", "B"]) == ["-x", "A", "-x", "B"]
    assert env_forward_args(MPICH, ["A", "B"]) == ["-genvlist", "A,B"]


def test_env_from_mpi_openmpi():
    derived = env_from_mpi({"OMPI_COMM_WORLD_RANK": "3",
                            "OMPI_COMM_WORLD_SIZE": "8",
                            "OMPI_COMM_WORLD_LOCAL_RANK": "1",
                            "OMPI_COMM_WORLD_LOCAL_SIZE": "4"})
    assert derived == {"HVT_PROCESS_ID": "3", "HVT_NUM_PROCESSES": "8",
                       "HVT_LOCAL_PROCESS_ID": "1", "HVT_LOCAL_SIZE": "4"}


def test_env_from_mpi_does_not_override():
    derived = env_from_mpi({"HVT_PROCESS_ID": "0",
                            "OMPI_COMM_WORLD_RANK": "3"})
    assert "HVT_PROCESS_ID" not in derived


# -------------------------------------------------------------- js_run

def test_lsf_hosts():
    hosts = lsf_hosts({"LSB_MCPU_HOSTS": "launcher1 1 node1 4 node2 4"})
    assert hosts == {"node1": 4, "node2": 4}
    # compute nodes named batch* must NOT be filtered; only the first
    # (launcher) entry is dropped, by position
    hosts = lsf_hosts({"LSB_MCPU_HOSTS": "launcher1 1 batch01 4 batch02 4"})
    assert hosts == {"batch01": 4, "batch02": 4}
    hosts = lsf_hosts({"LSB_HOSTS": "launcher node1 node1 node2"})
    assert hosts == {"node1": 2, "node2": 1}


def test_jsrun_command():
    cmd = build_jsrun_command(8, ["python", "t.py"])
    assert cmd[:2] == ["jsrun", "-n8"]
    assert cmd[-2:] == ["python", "t.py"]


# --------------------------------------------------------- config file

def test_config_file_fills_defaults(tmp_path):
    cfg = tmp_path / "hvt.yaml"
    cfg.write_text("fusion-threshold-mb: 128\nautotune: true\n"
                   "min-np: 2\n")
    args = parse_args(["-np", "4", "--config-file", str(cfg),
                       "python", "t.py"])
    assert args.fusion_threshold_mb == 128
    assert args.autotune is True
    assert args.min_np == 2


def test_config_file_cli_wins(tmp_path):
    cfg = tmp_path / "hvt.yaml"
    cfg.write_text("fusion-threshold-mb: 128\n")
    args = parse_args(["-np", "2", "--fusion-threshold-mb", "32",
                       "--config-file", str(cfg), "python", "t.py"])
    assert args.fusion_threshold_mb == 32


def test_config_file_rejects_unknown_keys(tmp_path):
    cfg = tmp_path / "hvt.yaml"
    cfg.write_text("not-a-real-knob: 1\n")
    with pytest.raises(ValueError, match="unknown config keys"):
        load_config(str(cfg))


# ------------------------------------------------------------ probe

def test_probe_services_ring():
    """Two task services on localhost: driver collects info, runs the
    ring probe, and the loopback address must come out as common."""
    key = secret.make_secret_key()
    t0 = TaskService(0, key, salt="0")
    t1 = TaskService(1, key, salt="1")
    p0, p1 = t0.start(), t1.start()
    try:
        driver = DriverProbe(key)
        addrs = [f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"]
        infos = driver.collect_info(addrs)
        assert infos[0]["host_hash"] != infos[1]["host_hash"]
        # common NICs by NAME (hosts have different IPs in general)
        common = driver.common_interfaces(addrs)
        assert "lo" in common
        reachable = driver.reachable_addresses(addrs)
        assert all("127.0.0.1" in v for v in reachable.values())
    finally:
        t0.stop()
        t1.stop()


def test_probe_rejects_bad_signature():
    import urllib.error

    key = secret.make_secret_key()
    t = TaskService(0, key)
    port = t.start()
    try:
        bad = DriverProbe(secret.make_secret_key())
        with pytest.raises(urllib.error.HTTPError):
            bad.collect_info([f"127.0.0.1:{port}"])
    finally:
        t.stop()


def test_network_interfaces():
    ifaces = network.get_local_interfaces()
    assert any("127.0.0.1" in ips for ips in ifaces.values())
    assert "127.0.0.1" in network.local_addresses()


# --------------------------------------------------------- run() API

@pytest.mark.skipif(not os.path.exists(LIB),
                    reason="C++ engine not built")
def test_programmatic_run():
    from horovod_tpu.runner import run

    def train(scale):
        import numpy as np

        import horovod_tpu as hvt

        val = hvt.allreduce(np.array([float(hvt.rank() + 1)]),
                            name="r", average=False)
        return float(np.asarray(val)[0]) * scale, hvt.rank(), hvt.size()

    results = run(train, args=(10,), np=2, master_port=29935)
    assert len(results) == 2
    # ranks ordered; allreduce sum = 1+2 = 3 → scaled 30
    assert results[0] == (30.0, 0, 2)
    assert results[1] == (30.0, 1, 2)
