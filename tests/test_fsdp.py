"""FSDP/ZeRO tests: parameters and optimizer moments actually shard over
the fsdp axis, training matches the replicated baseline."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.fsdp import (fsdp_partition_spec,
                                       init_sharded_state, shard_pytree)
from horovod_tpu.parallel.mesh import make_parallel_mesh


def _params(d=32, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "dense": {"kernel": jnp.asarray(rs.randn(d, 4 * d)
                                        .astype(np.float32)),
                  "bias": jnp.asarray(rs.randn(4 * d)
                                      .astype(np.float32))},
        "head": {"kernel": jnp.asarray(rs.randn(4 * d, d)
                                       .astype(np.float32)),
                 "scale": jnp.asarray(np.float32(1.0))},
    }


def test_spec_shards_large_replicates_small():
    mesh = make_parallel_mesh(fsdp=8)
    params = _params()
    specs = fsdp_partition_spec(params, mesh, min_shard_elements=256)
    # large 2-D leaves: largest divisible dim sharded
    assert specs["dense"]["kernel"] == P(None, "fsdp")
    assert specs["head"]["kernel"] == P("fsdp", None)
    # small leaves replicated
    assert specs["head"]["scale"] == P()
    # bias: 128 elements < min_shard_elements → replicated
    assert specs["dense"]["bias"] == P()


def test_spec_skips_indivisible_dims():
    mesh = make_parallel_mesh(fsdp=8)
    params = {"odd": jnp.zeros((7, 9000), jnp.float32)}
    specs = fsdp_partition_spec(params, mesh)
    assert specs["odd"] == P(None, "fsdp")
    params = {"never": jnp.zeros((7, 9001), jnp.float32)}
    assert fsdp_partition_spec(params, mesh)["never"] == P()


def test_fsdp_training_matches_replicated():
    """Sharded params + sharded adam moments produce the same training
    trajectory as fully replicated training."""
    mesh = make_parallel_mesh(fsdp=8)
    params = _params(d=16, seed=1)
    rs = np.random.RandomState(2)
    X = jnp.asarray(rs.randn(32, 16).astype(np.float32))
    Y = jnp.asarray(rs.randn(32, 16).astype(np.float32))
    tx = optax.adam(1e-2)

    def loss_fn(p):
        h = jnp.tanh(X @ p["dense"]["kernel"] + p["dense"]["bias"])
        out = h @ p["head"]["kernel"] * p["head"]["scale"]
        return ((out - Y) ** 2).mean()

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p)
        updates, s = tx.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    # replicated baseline
    p_ref = params
    s_ref = tx.init(p_ref)
    for _ in range(5):
        p_ref, s_ref, loss_ref = step(p_ref, s_ref)

    # fsdp-sharded run
    specs = fsdp_partition_spec(params, mesh, min_shard_elements=256)
    p_sh = shard_pytree(params, specs, mesh)
    with jax.set_mesh(mesh):
        s_sh = init_sharded_state(tx, p_sh, mesh)
        # adam moments inherit the parameter shardings (ZeRO-1/2):
        # each device holds only a shard, not the full moment
        mu_kernel = s_sh[0].mu["dense"]["kernel"]
        shard_shape = mu_kernel.addressable_shards[0].data.shape
        assert shard_shape != mu_kernel.shape, \
            f"moment not sharded: {mu_kernel.sharding}"
        for _ in range(5):
            p_sh, s_sh, loss_sh = step(p_sh, s_sh)
    # params stay sharded through the step
    assert "fsdp" in str(p_sh["dense"]["kernel"].sharding.spec)
    np.testing.assert_allclose(float(loss_sh), float(loss_ref),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_sh), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
