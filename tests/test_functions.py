"""Object/parameter collectives + compression unit tests
(reference ``torch/functions.py`` / ``tensorflow/functions.py`` suites)."""

import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvt
from horovod_tpu.ops.compression import Compression


def test_allgather_object_single_process():
    out = hvt.allgather_object({"a": 1, "b": [2, 3]})
    assert out == [{"a": 1, "b": [2, 3]}]


def test_broadcast_object_single_process():
    obj = ("epoch", 7)
    assert hvt.broadcast_object(obj, root_rank=0) == obj


def test_broadcast_parameters_pytree():
    params = {"w": jnp.ones((2, 2)), "b": np.zeros(3)}
    out = hvt.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    np.testing.assert_allclose(np.asarray(out["b"]), 0.0)


def test_broadcast_optimizer_state():
    import optax

    tx = optax.adam(1e-3)
    state = tx.init({"w": jnp.ones((2,))})
    out = hvt.broadcast_optimizer_state(state, root_rank=0)
    assert len(out) == len(state)


def test_fp16_compressor():
    x = jnp.asarray(np.random.RandomState(0).randn(16).astype(np.float32))
    c, ctx = Compression.fp16.compress(x)
    assert c.dtype == jnp.float16
    d = Compression.fp16.decompress(c, ctx)
    assert d.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(d), np.asarray(x), rtol=1e-3)


def test_bf16_compressor():
    x = jnp.asarray(np.random.RandomState(1).randn(16).astype(np.float32))
    c, ctx = Compression.bf16.compress(x)
    assert c.dtype == jnp.bfloat16
    d = Compression.bf16.decompress(c, ctx)
    assert d.dtype == jnp.float32


def test_compressor_skips_ints():
    x = jnp.arange(4)
    c, ctx = Compression.fp16.compress(x)
    assert c.dtype == x.dtype and ctx is None
    assert Compression.none.compress(x)[0] is x


def test_sparse_allreduce_single_process():
    """Sparse path semantics (reference IndexedSlices → allgather,
    tensorflow/__init__.py:92-108): duplicate indices accumulate on
    apply; averaging divides by world size."""
    from horovod_tpu.ops.sparse import (apply_sparse, sparse_allreduce,
                                        sparse_allreduce_apply)

    idx = np.array([0, 2, 2], np.int32)
    vals = np.array([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]], np.float32)
    gi, gv = sparse_allreduce(idx, vals, average=True, name="sp")
    # size 1: identity exchange
    np.testing.assert_array_equal(np.asarray(gi), idx)
    np.testing.assert_allclose(np.asarray(gv), vals)

    dense = np.zeros((4, 2), np.float32)
    out = apply_sparse(dense, gi, gv)
    np.testing.assert_allclose(np.asarray(out)[0], [1.0, 1.0])
    np.testing.assert_allclose(np.asarray(out)[2], [5.0, 5.0])  # 2+3
    np.testing.assert_allclose(np.asarray(out)[1], 0.0)

    out2 = sparse_allreduce_apply(dense, idx, vals, name="sp2")
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out))
