"""Gang-wide failure containment (PR 4): deadline-bounded data plane,
coordinated abort, heartbeats, timed waits, and the fault-injection
harness.

The gang tests spawn RAW worker processes (no hvtrun) so each worker's
exit code is observable independently: survivors of an injected failure
must catch ``HorovodInternalError`` within the containment bound and
exit 0, while the injected rank dies by SIGKILL. Every subprocess wait
carries a hard timeout — a containment regression fails the test
instead of stalling CI.
"""

import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")

_PORT = [26000 + (os.getpid() * 389) % 9000]


def _next_port():
    while True:
        _PORT[0] += 1
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", _PORT[0]))
                return _PORT[0]
            except OSError:
                continue


_PRELUDE = """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import horovod_tpu as hvt
    from horovod_tpu.engine import native
    hvt.init()
    r, n = hvt.rank(), hvt.size()
"""


def spawn_gang(body, np=2, extra_env=None, tmp_path="/tmp"):
    """Start np raw worker processes running ``body`` (after the hvt
    prelude); returns the list of Popen objects plus the per-rank log
    paths."""
    port = _next_port()
    script = textwrap.dedent(_PRELUDE.format(repo=REPO)) + \
        textwrap.dedent(body)
    path = os.path.join(str(tmp_path),
                        f"hvt_fc_{os.getpid()}_{port}.py")
    with open(path, "w") as f:
        f.write(script)
    procs, logs = [], []
    for rank in range(np):
        env = dict(os.environ)
        env.update({
            "HVT_MASTER_ADDR": "127.0.0.1",
            "HVT_MASTER_PORT": str(port),
            "HVT_PROCESS_ID": str(rank),
            "HVT_NUM_PROCESSES": str(np),
            "HVT_SHM_ALLREDUCE": "0",  # the TCP plane is under test
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "PALLAS_AXON_POOL_IPS": "",
        })
        env.update(extra_env or {})
        log = open(os.path.join(str(tmp_path),
                                f"hvt_fc_{port}_r{rank}.log"), "w+")
        procs.append(subprocess.Popen(
            [sys.executable, path], env=env, cwd=REPO, stdout=log,
            stderr=subprocess.STDOUT))
        logs.append(log)
    return procs, logs


def finish_gang(procs, logs, timeout):
    """Hard-timeout join: SIGKILL stragglers (a containment regression
    must fail, never stall CI). Returns (exit codes, per-rank output)."""
    deadline = time.time() + timeout
    codes = []
    for p in procs:
        left = max(0.1, deadline - time.time())
        try:
            codes.append(p.wait(timeout=left))
        except subprocess.TimeoutExpired:
            p.kill()
            codes.append(p.wait())
    outs = []
    for log in logs:
        log.flush()
        log.seek(0)
        outs.append(log.read())
        log.close()
    return codes, outs


# ------------------------------------------------------------- chaos gang

def test_chaos_kill_mid_allreduce(tmp_path):
    """The acceptance gang: HVT_FAULT_INJECT SIGKILLs rank 2 after 5
    data ops on a 4-proc gang. Every survivor must raise
    HorovodInternalError within 2x HVT_OP_TIMEOUT_MS, see the sticky
    broken state + ABORT flight-recorder event + aborts counter, fail
    fast on the next submit, and exit cleanly (no hang, no uncaught C++
    exception)."""
    op_timeout_ms = 5000
    body = """
    x = np.arange(4096, dtype=np.float32) + r
    t0 = time.monotonic()
    try:
        for i in range(30):
            hvt.allreduce(x, op=hvt.Sum, name=f"chaos.{i}")
        print("NO-ERROR", flush=True)
    except hvt.HorovodInternalError:
        dt = time.monotonic() - t0
        broken, info = native.engine_broken()
        assert broken, "broken flag not sticky"
        st = native.engine_stats()
        assert sum(st["aborts"].values()) == 1, st["aborts"]
        kinds = {e["kind_name"] for e in native.drain_events(8192)}
        assert "ABORT" in kinds, kinds
        t1 = time.monotonic()
        try:
            hvt.allreduce(x, op=hvt.Sum, name="post")
            print("POST-NO-ERROR", flush=True)
        except hvt.HorovodInternalError:
            pass
        fast = time.monotonic() - t1
        assert fast < 1.0, f"fail-fast took {fast:.2f}s"
        print(f"CAUGHT {dt:.3f} {info}", flush=True)
    hvt.shutdown()
    print("EXITED", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=4, tmp_path=tmp_path,
        extra_env={"HVT_FAULT_INJECT": "kill:rank=2:after_ops=5",
                   "HVT_OP_TIMEOUT_MS": str(op_timeout_ms)})
    codes, outs = finish_gang(procs, logs,
                              timeout=4 * op_timeout_ms / 1000 + 60)
    assert codes[2] == -signal.SIGKILL, (codes, outs[2])
    for rank in (0, 1, 3):
        assert codes[rank] == 0, \
            f"survivor {rank} rc={codes[rank]}\n{outs[rank]}"
        assert "CAUGHT" in outs[rank], f"rank {rank}:\n{outs[rank]}"
        assert "EXITED" in outs[rank], f"rank {rank}:\n{outs[rank]}"
        assert "POST-NO-ERROR" not in outs[rank]
        caught = [ln for ln in outs[rank].splitlines()
                  if ln.startswith("CAUGHT")][0]
        elapsed = float(caught.split()[1])
        assert elapsed < 2 * op_timeout_ms / 1000, \
            f"rank {rank} took {elapsed:.1f}s (> 2x op timeout)"


def test_chaos_disabled_is_identical(tmp_path):
    """The same worker body with fault injection DISABLED must complete
    every op with bit-exact results — containment machinery off the
    failure path costs nothing and changes nothing."""
    body = """
    x = np.arange(4096, dtype=np.float32) + r
    exp = sum(np.arange(4096, dtype=np.float32) + i for i in range(n))
    for i in range(30):
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"chaos.{i}"))
        np.testing.assert_array_equal(res, exp)
    broken, _ = native.engine_broken()
    assert not broken
    st = native.engine_stats()
    assert sum(st["aborts"].values()) == 0, st["aborts"]
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    procs, logs = spawn_gang(body, np=4, tmp_path=tmp_path)
    codes, outs = finish_gang(procs, logs, timeout=120)
    for rank in range(4):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank]


def test_heartbeat_detects_silent_peer(tmp_path):
    """With NO collective outstanding, a silently dead peer (SIGSTOP —
    sockets stay open, no FIN) must trip the idle heartbeat on the
    survivors within ~2x HVT_HEARTBEAT_MS, and the next submit must
    raise HorovodInternalError instead of hanging."""
    hb_ms = 2000
    body = """
    x = np.ones(16, np.float32)
    hvt.allreduce(x, op=hvt.Sum, name="warm")
    if r == 2:
        import signal as _sig
        os.kill(os.getpid(), _sig.SIGSTOP)  # silent death
        time.sleep(120)
        os._exit(7)
    t0 = time.monotonic()
    while time.monotonic() - t0 < {bound}:
        broken, info = native.engine_broken()
        if broken:
            break
        time.sleep(0.1)
    assert broken, "heartbeat did not trip"
    assert "heartbeat" in info or "peer" in info, info
    print(f"DETECTED {{time.monotonic() - t0:.3f}}", flush=True)
    try:
        hvt.allreduce(x, op=hvt.Sum, name="post")
        raise SystemExit("post-abort submit did not raise")
    except hvt.HorovodInternalError:
        pass
    hvt.shutdown()
    print("EXITED", flush=True)
    """.format(bound=4 * hb_ms / 1000)
    procs, logs = spawn_gang(
        body, np=3, tmp_path=tmp_path,
        extra_env={"HVT_HEARTBEAT_MS": str(hb_ms)})
    try:
        codes = []
        for rank, p in enumerate(procs):
            if rank == 2:
                codes.append(None)
                continue
            try:
                codes.append(p.wait(timeout=5 * hb_ms / 1000 + 60))
            except subprocess.TimeoutExpired:
                p.kill()
                codes.append(p.wait())
    finally:
        procs[2].kill()  # SIGKILL works on a stopped process
        procs[2].wait()
    outs = []
    for log in logs:
        log.flush()
        log.seek(0)
        outs.append(log.read())
        log.close()
    for rank in (0, 1):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "DETECTED" in outs[rank], f"rank {rank}\n{outs[rank]}"
        det = [ln for ln in outs[rank].splitlines()
               if ln.startswith("DETECTED")][0]
        assert float(det.split()[1]) < 2 * hb_ms / 1000 + 1.0, det


def test_wait_timeout_raises_then_completes(tmp_path):
    """Handle.wait(timeout=...) (previously ignored) must raise
    HorovodTimeoutError while the peer is absent, leave the handle
    waitable, and deliver the result once the peer arrives."""
    go = os.path.join(str(tmp_path), "tw_go")
    body = """
    from horovod_tpu.engine import api as eapi
    x = np.ones(8, np.float32)
    if r == 0:
        h = eapi.allreduce(x, op=hvt.Sum, name="lone")
        t0 = time.monotonic()
        try:
            h.wait(timeout=0.4)
            raise SystemExit("timed wait did not raise")
        except hvt.HorovodTimeoutError:
            dt = time.monotonic() - t0
            assert 0.3 < dt < 5.0, dt
        assert isinstance(hvt.HorovodTimeoutError(), TimeoutError)
        open({go!r}, "w").close()
        res = np.asarray(h.wait(timeout=30))
        assert res[0] == 2.0, res[0]
        print("TIMED-OK", flush=True)
    else:
        while not os.path.exists({go!r}):
            time.sleep(0.05)
        res = np.asarray(eapi.allreduce(x, op=hvt.Sum,
                                        name="lone").wait(timeout=30))
        assert res[0] == 2.0
        print("PEER-OK", flush=True)
    hvt.shutdown()
    """.format(go=go)
    procs, logs = spawn_gang(body, np=2, tmp_path=tmp_path)
    codes, outs = finish_gang(procs, logs, timeout=90)
    assert codes == [0, 0], outs
    assert "TIMED-OK" in outs[0]
    assert "PEER-OK" in outs[1]


def test_connect_timeout_is_bounded(tmp_path):
    """A worker dialing a rank 0 that never comes up must fail init
    within the HVT_CONNECT_TIMEOUT budget (backoff + jitter, not the
    old fixed 60 s spin)."""
    port = _next_port()
    script = textwrap.dedent(f"""
        import sys, time
        sys.path.insert(0, {REPO!r})
        from horovod_tpu.engine import native
        from horovod_tpu.common.exceptions import HorovodInternalError
        t0 = time.monotonic()
        try:
            native.init_engine(rank=1, size=2,
                               master_addr="127.0.0.1",
                               master_port={port})
            raise SystemExit("init unexpectedly succeeded")
        except HorovodInternalError:
            print(f"INIT-FAILED {{time.monotonic() - t0:.2f}}",
                  flush=True)
    """)
    path = os.path.join(str(tmp_path), "connect_timeout.py")
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.update({"HVT_CONNECT_TIMEOUT": "2", "JAX_PLATFORMS": "cpu"})
    proc = subprocess.run([sys.executable, path], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    elapsed = float(proc.stdout.split()[-1])
    assert elapsed < 15, f"connect gave up only after {elapsed:.1f}s"


# --------------------------------------------------------- http retries

def _serve_after(port, delay_sec, payload=b'{"ok": 1}'):
    """Start an HTTP server on ``port`` after ``delay_sec`` — the
    'rendezvous still binding' scenario."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_PUT(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    box = {}

    def run():
        time.sleep(delay_sec)
        srv = http.server.HTTPServer(("127.0.0.1", port), H)
        box["srv"] = srv
        srv.serve_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return box


def test_http_client_retries_connection_refused():
    from horovod_tpu.runner import http_client

    port = _next_port()
    # min total backoff across 4 retries is 0.05+0.1+0.2+0.4 = 0.75 s
    box = _serve_after(port, 0.5)
    try:
        t0 = time.monotonic()
        obj = http_client.get_json(f"127.0.0.1:{port}", "/anything",
                                   timeout=2)
        assert obj == {"ok": 1}
        assert time.monotonic() - t0 < 10
        assert http_client.put_json(f"127.0.0.1:{port}", "/kv/x/y",
                                    {"a": 1}, timeout=2) == 200
    finally:
        srv = box.get("srv")
        if srv is not None:
            srv.shutdown()


def test_http_client_no_retry_fails_fast():
    from horovod_tpu.runner import http_client

    port = _next_port()  # nothing listens here
    t0 = time.monotonic()
    with pytest.raises(OSError):
        http_client.get_json(f"127.0.0.1:{port}", "/x", timeout=1,
                             retries=0)
    assert time.monotonic() - t0 < 2.0


def test_http_client_404_is_not_retried():
    import http.server

    hits = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            hits.append(1)
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    port = _next_port()
    srv = http.server.HTTPServer(("127.0.0.1", port), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        from horovod_tpu.runner import http_client
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            http_client.get_json(f"127.0.0.1:{port}", "/missing",
                                 timeout=2)
        assert len(hits) == 1, "4xx must not be retried"
    finally:
        srv.shutdown()


# ---------------------------------------------------- elastic attribution

def test_driver_blacklists_reported_failure_and_rerendezvous():
    """A survivor's /kv/failure report naming a dead rank blacklists
    that rank's host immediately, and the registry barrier then drives
    a new rendezvous round that excludes it (blacklist +
    re-rendezvous)."""
    import json

    from horovod_tpu.runner.elastic.discovery import HostDiscovery
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.settings import ElasticSettings
    from horovod_tpu.runner.http_server import RendezvousServer

    class TwoHosts(HostDiscovery):
        def find_available_hosts_and_slots(self):
            return {"hostA": 1, "hostB": 1}

    settings = ElasticSettings(min_np=1, max_np=None,
                               elastic_timeout=5.0, reset_limit=None,
                               discovery_interval=0.01)
    rendezvous = RendezvousServer()
    driver = ElasticDriver(rendezvous, TwoHosts(), settings,
                           create_worker_fn=None)
    try:
        driver.start(np=2)
        assert driver.world_size() == 2
        # a report naming a rank on the REPORTER's own host must not
        # blacklist it (process crash != lost host; keeps single-host
        # jobs recoverable)
        self_report = {"round": 1, "error": "x", "failed_ranks": [0]}
        driver._on_kv_put("failure", "hostA/0",
                          json.dumps(self_report).encode())
        assert driver.host_manager.blacklisted_count() == 0
        # hostB's worker (rank 1) dies; hostA's survivor reports it
        report = {"round": 1, "error": "hvt engine aborted (peer_lost)",
                  "failed_ranks": [1]}
        driver._on_kv_put("failure", "hostA/0",
                          json.dumps(report).encode())
        assert driver.host_manager.blacklisted_count() == 1
        # barrier: survivor READY + dead worker's exit → new round
        driver.record_ready("hostA", 0)
        driver._handle_worker_exit("hostB", 0, exit_code=137)
        deadline = time.time() + 5
        while time.time() < deadline and driver.world_size() != 1:
            time.sleep(0.02)
        assert driver.world_size() == 1
        slot = driver.get_slot_info("hostA", 0)
        assert slot is not None and slot.rank == 0
        assert driver.get_slot_info("hostB", 0) is None
    finally:
        driver.stop()


def test_failed_ranks_parsed_from_broken_reason(monkeypatch):
    import importlib

    # the elastic package re-exports the run() decorator under the
    # module's name, so attribute access yields the function — import
    # the module itself
    elastic_run = importlib.import_module("horovod_tpu.elastic.run")
    from horovod_tpu.engine import native

    monkeypatch.setattr(
        native, "engine_broken",
        lambda: (True, "peer_lost: control connection to rank 3 lost"))
    assert elastic_run._failed_ranks_from_engine() == [3]
    # remote_abort reasons name the (surviving) ORIGINATOR of the abort
    # frame, not the dead peer — they must never be reported as failed
    monkeypatch.setattr(
        native, "engine_broken",
        lambda: (True,
                 "remote_abort: abort from rank 2: hvt: recv failed "
                 "(peer lost)"))
    assert elastic_run._failed_ranks_from_engine() == []
    monkeypatch.setattr(native, "engine_broken", lambda: (False, ""))
    assert elastic_run._failed_ranks_from_engine() == []


def test_task_runner_fault_timer_arming():
    from horovod_tpu.runner.task_runner import maybe_arm_fault_timer

    # wrong rank / no after_sec / engine-owned specs never arm
    assert maybe_arm_fault_timer(0, "kill:rank=1:after_sec=5") is None
    assert maybe_arm_fault_timer(2, "kill:rank=2:after_ops=5") is None
    assert maybe_arm_fault_timer(2, "drop_conn:rank=2") is None
    assert maybe_arm_fault_timer(0, None) is None
    t = maybe_arm_fault_timer(1, "kill:rank=1:after_sec=600")
    assert t is not None
    t.cancel()
