"""PyTorch binding tests — single-process semantics + autograd + optimizer
(the analog of reference ``test/parallel/test_torch.py``'s np=1 coverage;
multi-process coverage lives in ``test_torch_parallel.py``)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu.torch as hvd  # noqa: E402


def test_rank_size_single_process():
    assert hvd.size() == 1
    assert hvd.rank() == 0


def test_allreduce_identity():
    x = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    y = hvd.allreduce(x, name="t0")
    assert torch.allclose(y, x)


def test_allreduce_bf16():
    x = torch.ones(4, dtype=torch.bfloat16)
    y = hvd.allreduce(x, name="t_bf16")
    assert y.dtype == torch.bfloat16
    assert torch.allclose(y.float(), x.float())


def test_allreduce_inplace():
    x = torch.ones(3)
    out = hvd.allreduce_(x, name="t1")
    assert out is x


def test_allreduce_autograd():
    x = torch.ones(3, requires_grad=True)
    y = hvd.allreduce(x * 2, name="t2", op=hvd.Sum)
    y.sum().backward()
    assert torch.allclose(x.grad, torch.full((3,), 2.0))


def test_allgather_single():
    x = torch.arange(4).reshape(2, 2).float()
    y = hvd.allgather(x, name="g0")
    assert torch.allclose(y, x)


def test_allgather_autograd():
    x = torch.ones(2, 2, requires_grad=True)
    y = hvd.allgather(x * 3, name="g1")
    y.sum().backward()
    assert torch.allclose(x.grad, torch.full((2, 2), 3.0))


def test_broadcast_single():
    x = torch.randn(5)
    y = hvd.broadcast(x, root_rank=0, name="b0")
    assert torch.allclose(y, x)


def test_broadcast_autograd_root():
    x = torch.ones(3, requires_grad=True)
    y = hvd.broadcast(x * 2, root_rank=0, name="b1")
    y.sum().backward()
    # single process is the root: gradient flows through
    assert torch.allclose(x.grad, torch.full((3,), 2.0))


def test_alltoall_single():
    x = torch.arange(6).float()
    y = hvd.alltoall(x, name="a0")
    assert torch.allclose(y, x)


def test_alltoall_with_splits():
    x = torch.arange(4).float()
    y, recv = hvd.alltoall(x, splits=[4], name="a1")
    assert torch.allclose(y, x)
    assert recv.tolist() == [4]


def test_reducescatter_single():
    x = torch.randn(4, 2)
    y = hvd.reducescatter(x, op=hvd.Sum, name="rs0")
    assert torch.allclose(y, x)


def test_grouped_allreduce():
    xs = [torch.ones(2), torch.full((3,), 2.0)]
    ys = hvd.grouped_allreduce(xs, name="ga0")
    assert torch.allclose(ys[0], xs[0])
    assert torch.allclose(ys[1], xs[1])


def test_poll_synchronize():
    h = hvd.allreduce_async(torch.ones(2), name="p0")
    assert hvd.poll(h)
    out = hvd.synchronize(h)
    assert torch.allclose(out, torch.ones(2))


def test_join_and_barrier():
    assert hvd.join() == 0
    hvd.barrier()


# -- compression -------------------------------------------------------------

def test_fp16_compression_roundtrip():
    t = torch.randn(8)
    c, ctx = hvd.Compression.fp16.compress(t)
    assert c.dtype == torch.float16
    d = hvd.Compression.fp16.decompress(c, ctx)
    assert d.dtype == torch.float32
    assert torch.allclose(d, t, atol=1e-3)


def test_bf16_compression_roundtrip():
    t = torch.randn(8)
    c, ctx = hvd.Compression.bf16.compress(t)
    assert c.dtype == torch.bfloat16
    d = hvd.Compression.bf16.decompress(c, ctx)
    assert d.dtype == torch.float32


def test_compression_passes_ints():
    t = torch.arange(4)
    c, ctx = hvd.Compression.fp16.compress(t)
    assert c.dtype == t.dtype


# -- optimizer ---------------------------------------------------------------

def _tiny_model():
    torch.manual_seed(0)
    return torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.ReLU(),
                               torch.nn.Linear(8, 2))


def test_distributed_optimizer_step(monkeypatch):
    monkeypatch.setenv("HVT_FORCE_DISTRIBUTED_HOOKS", "1")
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    x = torch.randn(16, 4)
    before = [p.detach().clone() for p in model.parameters()]
    loss = model(x).pow(2).mean()
    loss.backward()
    opt.step()
    after = list(model.parameters())
    assert any(not torch.allclose(b, a) for b, a in zip(before, after))


def test_distributed_optimizer_matches_local(monkeypatch):
    """With one process the distributed step must equal a plain step."""
    monkeypatch.setenv("HVT_FORCE_DISTRIBUTED_HOOKS", "1")
    x = torch.randn(8, 4)

    def train(dist):
        model = _tiny_model()
        base = torch.optim.SGD(model.parameters(), lr=0.05)
        opt = hvd.DistributedOptimizer(
            base, named_parameters=model.named_parameters()) if dist \
            else base
        for _ in range(3):
            opt.zero_grad()
            model(x).pow(2).mean().backward()
            opt.step()
        return [p.detach().clone() for p in model.parameters()]

    for pd, pl in zip(train(True), train(False)):
        assert torch.allclose(pd, pl, atol=1e-6)


def test_backward_passes_per_step(monkeypatch):
    monkeypatch.setenv("HVT_FORCE_DISTRIBUTED_HOOKS", "1")
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2)
    x = torch.randn(4, 4)
    model(x).pow(2).mean().backward()
    model(x).pow(2).mean().backward()  # second pass completes the delay
    opt.step()
    opt.zero_grad()


def test_num_groups(monkeypatch):
    monkeypatch.setenv("HVT_FORCE_DISTRIBUTED_HOOKS", "1")
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(), num_groups=2)
    model(torch.randn(4, 4)).pow(2).mean().backward()
    opt.step()


def test_duplicate_parameter_names_rejected():
    model = _tiny_model()
    params = list(model.named_parameters())
    dup = [("x", params[0][1]), ("x", params[1][1])]
    with pytest.raises(ValueError, match="unique"):
        hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=dup)


def test_zero_grad_guard(monkeypatch):
    monkeypatch.setenv("HVT_FORCE_DISTRIBUTED_HOOKS", "1")
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    model(torch.randn(2, 4)).pow(2).mean().backward()
    with pytest.raises(AssertionError, match="zero_grad"):
        opt.zero_grad()
    opt.step()  # drain handles


def test_adasum_optimizer_single():
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1), op=hvd.Adasum)
    model(torch.randn(4, 4)).pow(2).mean().backward()
    opt.step()


# -- functions ---------------------------------------------------------------

def test_broadcast_parameters_state_dict():
    model = _tiny_model()
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)


def test_broadcast_optimizer_state():
    model = _tiny_model()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    model(torch.randn(2, 4)).sum().backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)


def test_broadcast_optimizer_state_empty():
    model = _tiny_model()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    hvd.broadcast_optimizer_state(opt, root_rank=0)


def test_broadcast_object():
    obj = {"a": 1, "b": [2, 3]}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_allgather_object():
    assert hvd.allgather_object({"r": 0}) == [{"r": 0}]


# -- sync batch norm ---------------------------------------------------------

def test_sync_batch_norm_matches_bn_single_process():
    torch.manual_seed(0)
    x = torch.randn(8, 3, 4, 4)
    sbn = hvd.SyncBatchNorm(3)
    bn = torch.nn.BatchNorm2d(3)
    bn.load_state_dict(sbn.state_dict())
    sbn.train()
    bn.train()
    assert torch.allclose(sbn(x), bn(x), atol=1e-5)
    assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-5)


def test_sync_batch_norm_eval():
    sbn = hvd.SyncBatchNorm(3)
    sbn.eval()
    x = torch.randn(2, 3, 4)
    assert sbn(x).shape == x.shape


# -- elastic -----------------------------------------------------------------

def test_torch_state_commit_restore():
    model = _tiny_model()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    state = hvd.elastic.TorchState(model=model, optimizer=opt, epoch=3)
    state.commit()
    with torch.no_grad():
        for p in model.parameters():
            p.add_(1.0)
    state.epoch = 7
    state.restore()
    assert state.epoch == 3
    fresh = _tiny_model()
    for p, q in zip(model.parameters(), fresh.parameters()):
        assert torch.allclose(p, q)


def test_torch_state_sync_single():
    model = _tiny_model()
    state = hvd.elastic.TorchState(model=model, epoch=1)
    state.sync()
    assert state.epoch == 1


def test_elastic_sampler_covers_dataset():
    data = list(range(10))
    sampler = hvd.elastic.ElasticSampler(data, shuffle=False)
    assert sorted(iter(sampler)) == data
    assert len(sampler) == 10


def test_elastic_sampler_record_and_reset():
    data = list(range(10))
    sampler = hvd.elastic.ElasticSampler(data, shuffle=False)
    sampler.record_batch(0, 4)  # first 4 indices processed
    sampler.reset()
    remaining = list(iter(sampler))
    assert len(remaining) == 6
    assert set(remaining).isdisjoint(set(range(4)) & set(remaining) - set(remaining))
    assert set(remaining) == set(range(4, 10))


def test_elastic_sampler_state_roundtrip():
    sampler = hvd.elastic.ElasticSampler(list(range(8)), shuffle=False)
    sampler.record_batch(0, 3)
    sd = sampler.state_dict()
    other = hvd.elastic.ElasticSampler(list(range(8)), shuffle=False)
    other.load_state_dict(sd)
    assert set(iter(other)) == set(range(3, 8))


def test_elastic_sampler_epoch_clears():
    sampler = hvd.elastic.ElasticSampler(list(range(6)), shuffle=True)
    sampler.record_batch(0, 6)
    sampler.set_epoch(1)
    assert len(list(iter(sampler))) == 6
