"""TPU preemption hook (SURVEY §5.3): SIGTERM / maintenance notices become
HostsUpdatedInterrupt at the next commit, driving the elastic reset path.

Unit tests exercise the watcher directly; the integration test delivers a
real SIGTERM to a worker mid-epoch under an elastic hvtrun launch and
asserts commit→interrupt→reset→resume with stable ranks."""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

import horovod_tpu as hvt
from horovod_tpu.elastic import ObjectState, preemption
from horovod_tpu.elastic.preemption import PreemptionWatcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")


@pytest.fixture(autouse=True)
def _clean_watcher():
    preemption._reset_for_tests()
    yield
    preemption._reset_for_tests()


def fields_of(line):
    """Parse 'BATCH slot=.. rank=..'-style worker lines (launcher output
    prefixes each line with '[rank] ', which carries no '=')."""
    return dict(kv.split("=") for kv in line.split() if "=" in kv)


def wait_until(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_sigterm_flags_states_and_commit_raises():
    state = ObjectState(epoch=0)
    w = PreemptionWatcher()
    w.watch(state)
    prev = signal.getsignal(signal.SIGTERM)
    w.install()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert wait_until(lambda: w.triggered)
        with pytest.raises(hvt.HostsUpdatedInterrupt):
            state.commit()
        state.commit()  # notice consumed
    finally:
        w.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_maintenance_poll_fn_triggers():
    state = ObjectState(epoch=0)
    pending = {"flag": False}
    w = PreemptionWatcher(poll_fn=lambda: pending["flag"],
                          poll_interval=0.01)
    w.watch(state)
    w.install()
    try:
        time.sleep(0.05)
        assert not w.triggered
        pending["flag"] = True
        assert wait_until(lambda: w.triggered)
        with pytest.raises(hvt.HostsUpdatedInterrupt):
            state.commit()
    finally:
        w.uninstall()


def test_elastic_run_resumes_after_preemption_notice():
    calls = {"n": 0}
    w = PreemptionWatcher()

    @hvt.elastic.run
    def train(state):
        calls["n"] += 1
        if calls["n"] == 1:
            w.trigger("maintenance-event")
            state.commit()  # raises HostsUpdatedInterrupt
        return state.epoch

    s = ObjectState(epoch=4)
    w.watch(s)
    assert train(s) == 4
    assert calls["n"] == 2


def test_watch_state_gating(monkeypatch):
    s = ObjectState(epoch=0)
    monkeypatch.delenv("HVT_RENDEZVOUS_ADDR", raising=False)
    monkeypatch.delenv("HVT_PREEMPTION_WATCH", raising=False)
    assert preemption.watch_state(s) is None  # not an elastic launch
    monkeypatch.setenv("HVT_PREEMPTION_WATCH", "1")
    w = preemption.watch_state(s)
    assert w is not None and w.installed
    monkeypatch.setenv("HVT_PREEMPTION_WATCH", "0")
    preemption._reset_for_tests()
    assert preemption.watch_state(s) is None  # explicit opt-out


def test_watcher_reports_driver_kv(monkeypatch):
    """The preempt notice lands in the rendezvous KV and the driver hook
    broadcasts a host-update to registered workers."""
    from horovod_tpu.runner.elastic.notification import \
        WorkerNotificationManager
    from horovod_tpu.runner.http_server import RendezvousServer

    rendezvous = RendezvousServer()
    rendezvous.start()
    notified = []

    class FakeDriver:
        def _on_kv_put(self, scope, key, value):
            if scope == "preempt":
                notified.append(key)

    rendezvous.set_put_hook(FakeDriver()._on_kv_put)
    try:
        monkeypatch.setenv("HVT_RENDEZVOUS_ADDR",
                           f"127.0.0.1:{rendezvous.port}")
        monkeypatch.setenv("HVT_HOSTNAME", "host-a")
        monkeypatch.setenv("HVT_LOCAL_PROCESS_ID", "1")
        w = PreemptionWatcher()
        w.trigger("signal:15")
        assert wait_until(lambda: notified)
        assert notified[0] == "host-a/1"
    finally:
        rendezvous.stop()


@pytest.mark.skipif(not os.path.exists(LIB),
                    reason="C++ engine not built (make -C horovod_tpu/csrc)")
def test_sigterm_worker_midepoch_resumes_with_stable_ranks(tmp_path):
    """End-to-end: elastic 2-proc job, SIGTERM one worker mid-epoch →
    both workers interrupt at commit, re-rendezvous, resume from the
    committed batch with the same (slot → rank) mapping, and finish."""
    marker_dir = str(tmp_path)
    script = textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import horovod_tpu as hvt
        from horovod_tpu.elastic import ObjectState

        TMP = {marker_dir!r}
        TOTAL = 6

        # spawn slot, captured BEFORE the elastic runner rewrites
        # HVT_LOCAL_PROCESS_ID per round — labels the PROCESS, so the
        # slot→rank stability assertion is real
        slot = os.environ.get("HVT_LOCAL_PROCESS_ID", "0")

        @hvt.elastic.run
        def train(state):
            with open(f"{{TMP}}/pid_{{slot}}", "w") as f:
                f.write(str(os.getpid()))
            while state.batch < TOTAL:
                hvt.allreduce(np.float32(1.0), name=f"b{{state.batch}}")
                print(f"BATCH slot={{slot}} rank={{hvt.process_rank()}}"
                      f" size={{hvt.process_size()}}"
                      f" batch={{state.batch}}", flush=True)
                open(f"{{TMP}}/progress_{{slot}}_{{state.batch}}",
                     "w").close()
                state.batch += 1
                time.sleep(0.25)
                state.commit()
            print(f"DONE slot={{slot}} rank={{hvt.process_rank()}}"
                  f" batch={{state.batch}}", flush=True)

        hvt.init()
        train(ObjectState(batch=0))
        hvt.shutdown()
    """)
    path = os.path.join(marker_dir, "worker.py")
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": ""})
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--min-np", "2", "--master-port", "29810",
         sys.executable, path],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        # wait until both workers committed a couple of batches
        assert wait_until(
            lambda: os.path.exists(f"{marker_dir}/progress_0_1")
            and os.path.exists(f"{marker_dir}/progress_1_1"), timeout=60), \
            "workers never reached batch 1"
        with open(f"{marker_dir}/pid_1") as f:
            pid = int(f.read())
        os.kill(pid, signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    except Exception:
        proc.kill()
        out = proc.stdout.read() if proc.stdout else ""
        raise AssertionError(f"elastic job did not complete:\n{out}")
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out}"
    # both workers finished all batches
    assert "DONE slot=0" in out and "DONE slot=1" in out, out
    # ranks stayed stable across the preemption round: every slot keeps
    # one rank for the whole job
    slot_ranks = {}
    batches_1 = []
    for line in out.splitlines():
        if "BATCH " in line or "DONE " in line:
            fields = fields_of(line)
            slot_ranks.setdefault(fields["slot"], set()).add(fields["rank"])
            if "BATCH " in line and fields["slot"] == "1":
                batches_1.append(int(fields["batch"]))
    assert set(slot_ranks) == {"0", "1"}, out
    for slot, ranks in slot_ranks.items():
        assert len(ranks) == 1, f"slot {slot} changed rank: {ranks}\n{out}"
    # the signaled worker went through interrupt → reset → resume: its
    # batch counter must not restart from 0 after the first commit
    assert sorted(set(batches_1)) == list(range(6)), batches_1


@pytest.mark.skipif(not os.path.exists(LIB),
                    reason="C++ engine not built (make -C horovod_tpu/csrc)")
def test_worker_death_restores_tf_keras_state(tmp_path):
    """Elastic TF job: kill a worker mid-run; survivors restore from
    their commit, the respawned worker syncs weights from rank 0, and
    the final model state is exactly TOTAL deterministic updates on
    every rank (reference tensorflow/elastic.py semantics)."""
    marker_dir = str(tmp_path)
    script = textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import horovod_tpu as hvt
        hvt.init()
        import tensorflow as tf
        import horovod_tpu.tensorflow.elastic as tfe

        TMP = {marker_dir!r}
        TOTAL = 6
        v = tf.Variable([100.0])
        model = tf.keras.Sequential()  # state rides the explicit var list
        state = tfe.TensorFlowState([v], batch=0)

        # spawn slot, captured BEFORE the elastic runner rewrites
        # HVT_LOCAL_PROCESS_ID per round — labels the PROCESS, so the
        # slot→rank stability assertion is real
        slot = os.environ.get("HVT_LOCAL_PROCESS_ID", "0")

        @hvt.elastic.run
        def train(state):
            with open(f"{{TMP}}/pid_{{slot}}", "w") as f:
                f.write(str(os.getpid()))
            while state.batch < TOTAL:
                hvt.allreduce(np.float32(1.0), name=f"b{{state.batch}}")
                v.assign_sub([1.0])       # deterministic update per batch
                state.batch += 1
                open(f"{{TMP}}/tfprog_{{slot}}_{{state.batch}}",
                     "w").close()
                time.sleep(0.25)
                state.commit()
            print(f"TFDONE slot={{slot}} w={{float(v.numpy()[0])}}",
                  flush=True)

        train(state)
        hvt.shutdown()
    """)
    path = os.path.join(marker_dir, "tf_worker.py")
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": "", "TF_CPP_MIN_LOG_LEVEL": "3"})
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--min-np", "2", "--master-port", "29812",
         sys.executable, path],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        assert wait_until(
            lambda: os.path.exists(f"{marker_dir}/tfprog_0_2")
            and os.path.exists(f"{marker_dir}/tfprog_1_2"), timeout=120), \
            "workers never reached batch 2"
        with open(f"{marker_dir}/pid_1") as f:
            pid = int(f.read())
        os.kill(pid, signal.SIGTERM)
        out, _ = proc.communicate(timeout=240)
    except Exception:
        proc.kill()
        out = proc.stdout.read() if proc.stdout else ""
        raise AssertionError(f"elastic TF job did not complete:\n{out}")
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out}"
    # both finished with EXACTLY TOTAL applied updates — rollback/sync
    # must not lose or double-apply any
    finals = [line for line in out.splitlines() if "TFDONE" in line]
    assert len(finals) == 2, out
    for line in finals:
        assert "w=94.0" in line, line


@pytest.mark.skipif(not os.path.exists(LIB),
                    reason="C++ engine not built (make -C horovod_tpu/csrc)")
def test_grown_host_gets_worker_at_next_rendezvous(tmp_path):
    """End-to-end growth (VERDICT r2 #8; reference
    elastic_common.py:34-60): a discovery script flips localhost:2 →
    localhost:3 mid-job. Running workers interrupt at the next commit,
    re-rendezvous, the NEW slot receives a worker in that round, the
    surviving slots keep their ranks, and everyone finishes with
    size == 3."""
    marker_dir = str(tmp_path)
    disc = os.path.join(marker_dir, "discover.sh")
    with open(disc, "w") as f:
        f.write(textwrap.dedent(f"""\
            #!/bin/sh
            if [ -f {marker_dir}/grow ]; then
                echo localhost:3
            else
                echo localhost:2
            fi
        """))
    os.chmod(disc, 0o755)
    script = textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import horovod_tpu as hvt
        from horovod_tpu.elastic import ObjectState

        TMP = {marker_dir!r}
        TOTAL = 8

        # spawn slot, captured BEFORE the elastic runner rewrites
        # HVT_LOCAL_PROCESS_ID per round (labels the process, not the
        # round's local rank)
        slot = os.environ.get("HVT_LOCAL_PROCESS_ID", "0")

        @hvt.elastic.run
        def train(state):
            while state.batch < TOTAL:
                hvt.allreduce(np.float32(1.0), name=f"b{{state.batch}}")
                print(f"BATCH slot={{slot}} rank={{hvt.process_rank()}}"
                      f" size={{hvt.process_size()}}"
                      f" batch={{state.batch}}", flush=True)
                open(f"{{TMP}}/progress_{{slot}}_{{state.batch}}",
                     "w").close()
                state.batch += 1
                time.sleep(0.3)
                state.commit()
            print(f"DONE slot={{slot}} rank={{hvt.process_rank()}}"
                  f" size={{hvt.process_size()}}", flush=True)

        hvt.init()
        train(ObjectState(batch=0))
        hvt.shutdown()
    """)
    path = os.path.join(marker_dir, "worker.py")
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": ""})
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--min-np", "2", "--max-np", "3",
         "--host-discovery-script", disc, "--master-port", "29814",
         sys.executable, path],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        assert wait_until(
            lambda: os.path.exists(f"{marker_dir}/progress_0_1")
            and os.path.exists(f"{marker_dir}/progress_1_1"), timeout=60), \
            "workers never reached batch 1"
        open(f"{marker_dir}/grow", "w").close()  # flip discovery 2 → 3
        out, _ = proc.communicate(timeout=150)
    except Exception:
        proc.kill()
        out = proc.stdout.read() if proc.stdout else ""
        raise AssertionError(f"elastic growth job did not complete:\n{out}")
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out}"

    slot_ranks, sizes_by_slot = {}, {}
    for line in out.splitlines():
        if "BATCH " in line or "DONE " in line:
            fields = fields_of(line)
            slot_ranks.setdefault(fields["slot"], set()).add(fields["rank"])
            if "size" in fields:
                sizes_by_slot.setdefault(fields["slot"], []).append(
                    int(fields["size"]))
    # the grown slot actually received a worker at the next round
    assert "2" in slot_ranks, f"new slot never started: {slot_ranks}\n{out}"
    assert "DONE slot=2" in out, out
    # every slot finished at world size 3
    for slot, sizes in sizes_by_slot.items():
        assert sizes[-1] == 3, f"slot {slot} final size {sizes[-1]}\n{out}"
    # surviving slots kept their ranks across the growth round
    for slot in ("0", "1"):
        assert len(slot_ranks[slot]) == 1, \
            f"slot {slot} changed rank: {slot_ranks[slot]}\n{out}"
