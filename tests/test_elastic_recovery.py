"""Checkpointless-recovery gang tests: a REAL ElasticDriver +
RendezvousServer + MiniEngine worker gang (no jax in the workers) where
a rank is killed by the engine's own fault injection
(``HVT_FAULT_INJECT=kill:rank=R:after_ops=N``), the driver respawns the
slot, and the fresh worker rebuilds the dead rank's state from its
replication-group peers — plus schema checks of the committed r14
artifact. Reuses the ``benchmarks/elastic_recovery.py`` harness
(``ci.sh --elastic`` drives the same machinery at 16 ranks)."""

import json
import os
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks import elastic_recovery as er  # noqa: E402


def _spec(**over):
    spec = {"np": 4, "hosts": 2, "numel": 32, "total_steps": 30,
            "kill_at_step": 2, "ckpt_every": 10, "replicas": 2,
            "step_sleep": 0.03, "cycle_ms": 2, "push_sec": 0.5}
    spec.update(over)
    return spec


def test_kill_a_rank_peer_rebuild_4proc_gang():
    """The satellite gang: 4 MiniEngine workers on 2 fake hosts; the
    engine fault injection SIGKILLs rank 3 mid-training; the driver
    respawns the slot (its host survives — only one of its two slots
    died) and the fresh worker rebuilds owner 3's state from its
    cross-host replication peer. Final state of EVERY lineage must be
    bit-identical to the uninterrupted reference."""
    spec = _spec(fault_inject={
        "rank": 3, "spec": "kill:rank=3:after_ops=40"})
    res = er.run_arm("peer", spec, timeout=300)
    assert res.get("ok"), res.get("error")
    assert res["bit_identical"], res
    assert res["lineages_reported"] == 4
    assert res["lineages_missing"] == []
    assert res["lineages_mismatched"] == []
    # the relay held the driver's per-round report wave to O(hosts)
    kr = res["kv_requests_recovery"]
    assert kr.get("failure", 0) + kr.get("state", 0) \
        <= 6 * spec["hosts"]
    # rank 0's recovery report carries the phase breakdown
    assert res["recovery_phases_rank0"].get("total") is not None


def test_kill_a_host_restore_baseline_4proc_gang():
    """The restart-from-checkpoint baseline on the same harness: a
    whole host SIGKILLed, the world shrinks, every rank restarts from
    the last checkpoint and replays — still bit-identical, but the KV
    reports go direct (the O(ranks) contrast the artifact gates)."""
    spec = _spec(kill_at_step=16,
                 ckpt_dir=tempfile.mkdtemp(prefix="hvt_er_test_"))
    res = er.run_arm("restore", spec, timeout=300)
    assert res.get("ok"), res.get("error")
    assert res["bit_identical"], res
    assert res["lineages_reported"] == 4


def test_committed_artifact_schema_and_claims():
    """The committed r14 artifact must stay schema-valid and keep its
    gated claims (the same --check ci.sh --elastic runs)."""
    path = os.path.join(REPO, "benchmarks",
                        "r14_elastic_recovery.json")
    assert os.path.exists(path), "committed r14 artifact missing"
    assert er.check(path) == 0
    with open(path) as f:
        rec = json.load(f)
    assert rec["mode"] == "full"
    assert rec["claims"]["ranks"] == 128
    assert rec["claims"]["hosts"] == 16
    assert rec["claims"]["speedup_x"] >= 3.0


def test_reference_simulation_matches_manual_trajectory():
    finals = er.simulate_reference(2, numel=4, total_steps=3)
    params, moment = [0.0] * 4, 0.0
    for step in range(3):
        moment = er.apply_step(params, moment, 1, step,
                               er.grad_value(step))
    assert finals[1] == er.lineage_crc(params, moment, 3)
    assert finals[0] != finals[1]  # lineages are distinguishable


def test_check_rejects_bad_artifacts(tmp_path):
    bad = {"schema": er.SCHEMA, "mode": "full",
           "configs": [{"arm": "peer", "ok": True,
                        "time_to_recovered_sec": 1.0,
                        "bit_identical": True,
                        "kv_requests_recovery_total": 5},
                       {"arm": "restore", "ok": True,
                        "time_to_recovered_sec": 2.0,
                        "bit_identical": True,
                        "kv_requests_recovery_total": 50}],
           "claims": {"recovered_both": True, "speedup_x": 2.0,
                      "bit_identical_peer": True,
                      "bit_identical_restore": True,
                      "kv_requests_o_hosts": True,
                      "kv_requests_o_ranks_direct": True,
                      "statusz_recovery_rows": True}}
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    assert er.check(str(p)) == 1     # full mode gates speedup >= 3x
    bad["claims"]["speedup_x"] = 3.4
    bad["claims"]["bit_identical_peer"] = False
    p.write_text(json.dumps(bad))
    assert er.check(str(p)) == 1     # bit-identity is non-negotiable
    bad["claims"]["bit_identical_peer"] = True
    p.write_text(json.dumps(bad))
    assert er.check(str(p)) == 0


@pytest.mark.slow
def test_smoke_capture_end_to_end(tmp_path):
    """The full ci.sh --elastic smoke (both arms + claims) — slow, so
    the tier-1 run takes the single-arm gangs above instead."""
    out = tmp_path / "er.json"
    rec = er.capture(str(out), smoke=True)
    assert rec["claims"].get("recovered_both"), rec
    assert er.check(str(out)) == 0
