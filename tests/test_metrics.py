"""horovod_tpu.metrics — registry semantics, exposition formats, the
engine-stats bridge (including over a real 2-process gang), the scrape
endpoints, and the instrumentation overhead bound.

Everything here is deliberately quick (auto-marked via conftest) so the
telemetry plane is validated by ``ci.sh --fast`` — observability is the
harness's own eye on the data plane, so it must be covered by the inner
loop, not just the round gate."""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request

import pytest

from horovod_tpu.metrics.registry import (DEFAULT_LATENCY_BUCKETS,
                                          MetricError, MetricRegistry)
from horovod_tpu.metrics import exposition

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")


# ------------------------------------------------------------------ registry

def test_counter_inc_and_negative_rejected():
    reg = MetricRegistry()
    c = reg.counter("c_total", "help")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(MetricError):
        c.inc(-1)


def test_gauge_set_inc_dec():
    reg = MetricRegistry()
    g = reg.gauge("g", "help")
    g.set(10)
    g.inc(5)
    g.dec(2)
    assert g.value == 13


def test_labels_distinct_children_and_validation():
    reg = MetricRegistry()
    c = reg.counter("req_total", "help", ("op", "process_set"))
    c.labels(op="allreduce", process_set="global").inc(3)
    c.labels("allreduce", "0,1").inc(1)
    assert c.labels(op="allreduce", process_set="global").value == 3
    assert c.labels(op="allreduce", process_set="0,1").value == 1
    with pytest.raises(MetricError):
        c.labels(op="allreduce")  # missing label
    with pytest.raises(MetricError):
        c.labels(op="allreduce", process_set="global", extra="x")
    with pytest.raises(MetricError):
        c.inc()  # labeled metric needs .labels(...)


def test_registry_get_or_create_and_schema_conflict():
    reg = MetricRegistry()
    a = reg.counter("x_total", "help")
    assert reg.counter("x_total") is a
    with pytest.raises(MetricError):
        reg.gauge("x_total")
    with pytest.raises(MetricError):
        reg.counter("x_total", labelnames=("op",))
    with pytest.raises(MetricError):
        reg.counter("9bad")  # leading digit
    with pytest.raises(MetricError):
        reg.counter("bad-name")  # invalid char


def test_histogram_bucket_assignment():
    reg = MetricRegistry()
    h = reg.histogram("lat_seconds", "help", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    cum, s, c = h.labels().snapshot()
    # cumulative per the Prometheus convention: le=0.1 → 1, le=1 → 3,
    # le=10 → 4, +Inf → 5
    assert cum == [1, 3, 4, 5]
    assert c == 5
    assert s == pytest.approx(56.05)


def test_default_latency_buckets_are_log_scale():
    bs = DEFAULT_LATENCY_BUCKETS
    assert bs[0] == pytest.approx(1e-6)
    ratios = {round(b2 / b1, 6) for b1, b2 in zip(bs, bs[1:])}
    assert ratios == {4.0}
    assert bs[-1] > 60  # spans loopback-eager to behind-a-stall


def test_concurrent_increments_are_exact():
    reg = MetricRegistry()
    c = reg.counter("n_total", "help")
    h = reg.histogram("h_seconds", "help")
    n_threads, per_thread = 8, 2000

    def hammer():
        for _ in range(per_thread):
            c.inc()
            h.observe(1e-5)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread
    _, _, count = h.labels().snapshot()
    assert count == n_threads * per_thread


# ---------------------------------------------------------------- exposition

def _golden_registry():
    reg = MetricRegistry()
    c = reg.counter("hvt_demo_total", "demo counter", ("op",))
    c.labels(op="allreduce").inc(3)
    g = reg.gauge("hvt_demo_gauge", 'help with "quotes" and \\slash')
    g.set(2.5)
    h = reg.histogram("hvt_demo_seconds", "demo latency",
                      buckets=(0.001, 1.0))
    h.observe(0.0009765625)  # 2^-10: exact in binary → stable golden sum
    h.observe(0.5)
    h.observe(2.0)
    return reg


def test_prometheus_text_golden():
    text = exposition.prometheus_text(_golden_registry())
    assert text == textwrap.dedent("""\
        # HELP hvt_demo_total demo counter
        # TYPE hvt_demo_total counter
        hvt_demo_total{op="allreduce"} 3
        # HELP hvt_demo_gauge help with "quotes" and \\\\slash
        # TYPE hvt_demo_gauge gauge
        hvt_demo_gauge 2.5
        # HELP hvt_demo_seconds demo latency
        # TYPE hvt_demo_seconds histogram
        hvt_demo_seconds_bucket{le="0.001"} 1
        hvt_demo_seconds_bucket{le="1"} 2
        hvt_demo_seconds_bucket{le="+Inf"} 3
        hvt_demo_seconds_sum 2.5009765625
        hvt_demo_seconds_count 3
        """)


def test_json_snapshot_golden():
    snap = exposition.json_snapshot(_golden_registry())
    assert snap["hvt_demo_total"]["type"] == "counter"
    assert snap["hvt_demo_total"]["samples"] == [
        {"labels": {"op": "allreduce"}, "value": 3.0}]
    hist = snap["hvt_demo_seconds"]["samples"][0]
    assert hist["buckets"] == {"0.001": 1, "1": 2, "+Inf": 3}
    assert hist["count"] == 3
    assert hist["sum"] == pytest.approx(2.5009765625)
    json.dumps(snap)  # must be JSON-serializable as-is


def test_label_value_escaping():
    reg = MetricRegistry()
    reg.counter("e_total", "h", ("k",)).labels(k='a"b\\c\nd').inc()
    text = exposition.prometheus_text(reg)
    assert r'e_total{k="a\"b\\c\nd"} 1' in text


# -------------------------------------------------------------- engine bridge

def test_default_registry_emits_engine_series_without_engine():
    """The hvt_engine_* series must exist (zeros) even when no engine is
    running — BENCH records and dashboards need a stable schema."""
    from horovod_tpu import metrics

    text = metrics.prometheus_text()
    assert "hvt_engine_cycles_total 0" in text
    assert "hvt_cache_hits_total 0" in text
    assert 'hvt_engine_exec_seconds_total{op="allreduce"} 0' in text
    snap = metrics.json_snapshot()
    assert snap["hvt_engine_cycles_total"]["samples"][0]["value"] == 0
    assert snap["hvt_engine_up"]["samples"][0]["value"] == 0


def test_native_engine_stats_layout():
    from horovod_tpu.engine import native

    if not native.available():
        pytest.skip("C++ engine not built")
    stats = native.engine_stats()
    for key in native.STATS_SCALARS:
        assert key in stats
    assert set(stats["exec_ns"]) == set(native.STATS_OPS)
    assert set(stats["exec_count"]) == set(native.STATS_OPS)


@pytest.mark.skipif(not os.path.exists(LIB),
                    reason="C++ engine not built")
def test_engine_stats_bridge_2proc_gang_and_scrape():
    """Acceptance pin: during a real 2-process CPU-ring run, each worker's
    GET /metrics returns Prometheus text with live engine counters and
    the per-op latency histogram."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    body = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvt
        hvt.init()
        r = hvt.rank()
        for i in range(8):
            np.testing.assert_allclose(
                np.asarray(hvt.allreduce(
                    np.full((64,), float(r + 1), np.float32),
                    name=f"t{{i}}")),
                1.5)
        from horovod_tpu import metrics
        import re, urllib.request
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{{metrics.server_port()}}/metrics",
            timeout=10).read().decode()
        for needle in (
                "hvt_cache_hits_total",
                'hvt_collective_latency_seconds_bucket{{op="allreduce"'
                ',process_set="global",le="+Inf"}} 8',
                'hvt_engine_exec_seconds_total{{op="allreduce"}}'):
            assert needle in text, text[:3000]
        cyc = float(re.search(
            r"^hvt_engine_cycles_total (\\S+)$", text, re.M).group(1))
        assert cyc > 0
        stats = metrics.json_snapshot()
        assert stats["hvt_engine_up"]["samples"][0]["value"] == 1
        print(f"METRICS-OK-{{r}}", flush=True)
        hvt.shutdown()
    """)
    path = f"/tmp/hvt_metrics_gang_{os.getpid()}.py"
    with open(path, "w") as f:
        f.write(body)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": ""})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--master-port", str(port), "--metrics-port", "0",
         sys.executable, path],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=90)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert "METRICS-OK-0" in out and "METRICS-OK-1" in out


# ------------------------------------------------------------------ endpoints

def test_standalone_serve_routes():
    reg_port = None
    from horovod_tpu.metrics.exposition import MetricsServer

    reg = MetricRegistry()
    reg.counter("served_total", "h").inc(7)
    srv = MetricsServer(reg)
    try:
        reg_port = srv.start(0, addr="127.0.0.1")
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{reg_port}/metrics", timeout=5)
        assert text.headers["Content-Type"].startswith("text/plain")
        assert b"served_total 7" in text.read()
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{reg_port}/metrics.json",
            timeout=5).read())
        assert snap["served_total"]["samples"][0]["value"] == 7
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{reg_port}/nope", timeout=5)
    finally:
        srv.stop()


def test_rendezvous_server_metrics_route():
    """The elastic rendezvous server exposes the same scrape surface."""
    from horovod_tpu.runner.http_server import RendezvousServer

    srv = RendezvousServer()
    port = srv.start()
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "hvt_engine_cycles_total" in text
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=5).read())
        assert "hvt_engine_cycles_total" in snap
    finally:
        srv.stop()


def test_hvtrun_metrics_port_env_plumbing():
    from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
    from horovod_tpu.runner.launch import parse_args, slot_env

    args = parse_args(["-np", "1", "--metrics-port", "9090", "true"])
    slots = get_host_assignments(parse_hosts("localhost:1"), 1)
    env = slot_env({}, slots[0], args, "127.0.0.1")
    assert env["HVT_METRICS_PORT"] == "9090"
    # without the flag the env var must be absent (no accidental server)
    args = parse_args(["-np", "1", "true"])
    env = slot_env({}, slots[0], args, "127.0.0.1")
    assert "HVT_METRICS_PORT" not in env


# ------------------------------------------------------------------ callbacks

def test_jax_metrics_callback_publishes_gauges():
    from horovod_tpu.jax.callbacks import MetricsCallback

    reg = MetricRegistry()
    cb = MetricsCallback(registry=reg)
    out = cb.on_epoch_end(0, {"loss": 0.5, "acc": 0.9, "note": "skip-me"})
    assert out == {"loss": 0.5, "acc": 0.9, "note": "skip-me"}
    cb.on_epoch_end(1, {"loss": 0.25})
    g = reg.get("hvt_train_metric")
    assert g.labels(metric="loss").value == 0.25
    assert g.labels(metric="acc").value == 0.9
    assert reg.get("hvt_train_epochs_total").value == 2


def test_eager_dispatch_instrumentation_single_process():
    """A single-process eager allreduce still lands in the dispatch
    histogram/byte counter (the immediate path is instrumented too)."""
    import numpy as np

    import horovod_tpu as hvt
    from horovod_tpu import metrics

    hist = metrics.registry().get("hvt_collective_latency_seconds")
    before = 0
    if hist is not None:
        _, _, before = hist.labels(
            op="allreduce", process_set="global").snapshot()
    hvt.allreduce(np.ones(4, np.float32), name="metrics_probe")
    hist = metrics.registry().get("hvt_collective_latency_seconds")
    _, _, after = hist.labels(
        op="allreduce", process_set="global").snapshot()
    assert after == before + 1
    assert metrics.registry().get(
        "hvt_collective_bytes_total").labels(
            op="allreduce", process_set="global").value >= 16


# ------------------------------------------------------------------- overhead

def test_observe_overhead_bound():
    """Acceptance: registry overhead < 2% of step time. The CPU bench
    step is ≥ 10 ms and each step does ONE dispatch observation, so the
    per-observe budget is 200 µs; require 20 µs mean (10x margin) to
    keep the bound meaningful and non-flaky on a loaded 1-core host."""
    reg = MetricRegistry()
    h = reg.histogram("bench_seconds", "h", ("op", "process_set"))
    c = reg.counter("bench_total", "h", ("op", "process_set"))
    n = 10_000
    t0 = time.perf_counter()
    for _ in range(n):
        h.labels(op="allreduce", process_set="global").observe(1e-4)
        c.labels(op="allreduce", process_set="global").inc(1024)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 20e-6, f"observe+inc cost {per_call * 1e6:.1f} µs"


# ------------------------------------------- exposition conformance (PR 13)
# The fleet telemetry plane ships merged host frames to external scrape
# agents, so the text format must stay strictly Prometheus-0.0.4
# conformant: HELP/TYPE per family, ascending `le` bounds with +Inf
# last, cumulative bucket counts, escaped label values.

def _conformance_registry():
    reg = MetricRegistry()
    reg.counter("conf_total", "a counter", ("op",)).labels(
        op="allreduce").inc(2)
    h = reg.histogram("conf_seconds", "a histogram")
    for v in (1e-6, 1e-3, 5.0, 1e9):
        h.observe(v)
    reg.gauge("conf_gauge", "line1\nline2").set(1)
    return reg


def test_exposition_help_and_type_for_every_family():
    reg = _conformance_registry()
    text = exposition.prometheus_text(reg)
    for name, mtype in (("conf_total", "counter"),
                        ("conf_seconds", "histogram"),
                        ("conf_gauge", "gauge")):
        assert f"# TYPE {name} {mtype}" in text
        assert f"# HELP {name} " in text
        # HELP must precede TYPE which must precede the samples
        assert text.index(f"# HELP {name}") < text.index(
            f"# TYPE {name}") < text.index(f"\n{name}")
    # newlines in help text are escaped, never literal
    assert r"line1\nline2" in text and "line1\nline2" not in text


def test_exposition_bucket_ordering_and_inf():
    text = exposition.prometheus_text(_conformance_registry())
    bounds, counts = [], []
    for line in text.splitlines():
        if line.startswith("conf_seconds_bucket"):
            le = line.split('le="', 1)[1].split('"', 1)[0]
            bounds.append(le)
            counts.append(int(line.rsplit(" ", 1)[1]))
    assert bounds[-1] == "+Inf"
    finite = [float(b) for b in bounds[:-1]]
    assert finite == sorted(finite), "le bounds must ascend"
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert counts[-1] == 4  # +Inf covers every observation
    # sum/count close the series
    assert "conf_seconds_sum" in text and "conf_seconds_count 4" in text


def test_exposition_label_escaping_roundtrip_chars():
    reg = MetricRegistry()
    reg.counter("esc_total", "h", ("k",)).labels(
        k='q"uote\\slash\nnl').inc()
    text = exposition.prometheus_text(reg)
    assert 'esc_total{k="q\\"uote\\\\slash\\nnl"} 1' in text


# ------------------------------------------------------ merge algebra (PR 13)

def _frame_of(rank, ctrl=0, depth=0, lat=()):
    """A rank's snapshot frame via the real json_snapshot path — merge
    is pinned against the exposition format, not a hand-rolled dict."""
    from horovod_tpu.metrics import merge as M

    reg = MetricRegistry()
    reg.counter("m_ctrl_total", "c").inc(ctrl)
    reg.gauge("m_depth", "g", ("lane",)).labels(lane="0").set(depth)
    h = reg.histogram("m_lat_seconds", "h", buckets=(0.001, 1.0))
    for v in lat:
        h.observe(v)
    return M.frame(rank, exposition.json_snapshot(reg))


def test_merge_semantics_per_type():
    from horovod_tpu.metrics import merge as M

    a = _frame_of(0, ctrl=100, depth=3, lat=(0.0005,))
    b = _frame_of(1, ctrl=40, depth=7, lat=(0.5, 2.0))
    m = M.merge(a, b)
    assert m["ranks"] == [0, 1]
    assert M.counter_total(m, "m_ctrl_total") == 140  # counters sum
    depth = m["metrics"]["m_depth"]["samples"][0]
    assert depth["labels"] == {"lane": "0"} and depth["value"] == 7
    hist = m["metrics"]["m_lat_seconds"]["samples"][0]
    # bucket-wise ADD of the (cumulative) per-rank snapshots
    assert hist["buckets"] == {"0.001": 1, "1": 2, "+Inf": 3}
    assert hist["count"] == 3


def test_merge_histogram_associativity():
    # binary-exact observation values: float addition is associative
    # only up to rounding, and the pin is about the ALGEBRA (bucket
    # unions, sample keying), not about fp arithmetic
    from horovod_tpu.metrics import merge as M

    a = _frame_of(0, ctrl=1, lat=(0.0009765625, 0.5))
    b = _frame_of(1, ctrl=2, lat=(2.0,))
    c = _frame_of(2, ctrl=4, lat=(0.5, 0.5, 8.0))
    assert M.merge(a, M.merge(b, c)) == M.merge(M.merge(a, b), c)
    # and commutative
    assert M.merge(a, b) == M.merge(b, a)


def test_merge_histogram_layout_mismatch_raises():
    # snapshot buckets are CUMULATIVE: unioning different bound sets
    # would credit counts to the wrong bounds (le=1 missing an
    # observation at 0.5 counted only under a coarser layout) — a
    # layout mismatch must refuse, like a type mismatch
    from horovod_tpu.metrics import merge as M

    def hist_frame(rank, bounds, obs):
        reg = MetricRegistry()
        h = reg.histogram("hm_seconds", "h", buckets=bounds)
        for v in obs:
            h.observe(v)
        return M.frame(rank, exposition.json_snapshot(reg))

    a = hist_frame(0, (0.001, 1.0), (0.5,))
    b = hist_frame(1, (1.0,), (0.5,))
    with pytest.raises(MetricError):
        M.merge(a, b)
    # identical layouts still fold
    c = hist_frame(2, (0.001, 1.0), (2.0,))
    assert M.merge(a, c)["metrics"]["hm_seconds"]["samples"][0][
        "buckets"] == {"0.001": 0, "1": 1, "+Inf": 2}


def test_merge_type_conflict_raises():
    from horovod_tpu.metrics import merge as M

    a = M.frame(0, {"x": {"type": "counter", "help": "",
                          "samples": [{"labels": {}, "value": 1}]}})
    b = M.frame(1, {"x": {"type": "gauge", "help": "",
                          "samples": [{"labels": {}, "value": 1}]}})
    with pytest.raises(MetricError):
        M.merge(a, b)


def test_merge_does_not_mutate_inputs():
    from horovod_tpu.metrics import merge as M

    a = _frame_of(0, ctrl=5, lat=(0.5,))
    b = _frame_of(1, ctrl=7, lat=(0.5,))
    a_before = json.dumps(a, sort_keys=True)
    M.merge(a, b)
    assert json.dumps(a, sort_keys=True) == a_before
