"""Pipeline-parallelism tests: the GPipe schedule must reproduce the
sequential composition of stages, end to end including gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.parallel.mesh import make_parallel_mesh
from horovod_tpu.parallel.pipeline import (merge_microbatches, pipeline,
                                           split_microbatches,
                                           stage_partition_spec)


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make(n_stages, d, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rs.randn(n_stages, d, d).astype(np.float32) * 0.5),
        "b": jnp.asarray(rs.randn(n_stages, d).astype(np.float32) * 0.1),
    }


def _sequential(params, x):
    for i in range(params["w"].shape[0]):
        x = _stage_fn({"w": params["w"][i], "b": params["b"][i]}, x)
    return x


def test_microbatch_roundtrip():
    x = jnp.arange(24, dtype=jnp.float32).reshape(12, 2)
    mb = split_microbatches(x, 4)
    assert mb.shape == (4, 3, 2)
    np.testing.assert_array_equal(np.asarray(merge_microbatches(mb)),
                                  np.asarray(x))
    with pytest.raises(ValueError, match="not divisible"):
        split_microbatches(x, 5)


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (2, 6),
                                              (8, 8)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    mesh = make_parallel_mesh(
        devices=jax.devices()[:n_stages], pp=n_stages)
    d = 8
    params = _make(n_stages, d)
    x = jnp.asarray(np.random.RandomState(1).randn(n_micro * 2, d)
                    .astype(np.float32))
    out = pipeline(_stage_fn, params, x, n_micro, mesh)
    expect = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential():
    n_stages, n_micro, d = 4, 4, 6
    mesh = make_parallel_mesh(devices=jax.devices()[:n_stages],
                              pp=n_stages)
    params = _make(n_stages, d, seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(8, d)
                    .astype(np.float32))

    def piped_loss(p):
        return (pipeline(_stage_fn, p, x, n_micro, mesh) ** 2).mean()

    def seq_loss(p):
        return (_sequential(p, x) ** 2).mean()

    gp = jax.grad(piped_loss)(params)
    gs = jax.grad(seq_loss)(params)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_under_jit_and_device_put():
    """Pre-sharding stage params with stage_partition_spec and jitting
    the pipelined step compiles and matches."""
    n_stages, d = 4, 8
    mesh = make_parallel_mesh(devices=jax.devices()[:n_stages],
                              pp=n_stages)
    params = _make(n_stages, d, seed=4)
    from jax.sharding import NamedSharding

    specs = stage_partition_spec(params)
    params_sharded = jax.tree.map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        params, specs)
    x = jnp.asarray(np.random.RandomState(5).randn(8, d)
                    .astype(np.float32))

    @jax.jit
    def step(p, xs):
        return pipeline(_stage_fn, p, xs, 4, mesh)

    out = step(params_sharded, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-5, atol=1e-5)
