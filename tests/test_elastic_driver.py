"""Elastic driver unit tests — fake discovery + simulated worker exits,
no real processes (the reference's ``test/single/test_elastic_driver.py``
strategy)."""

import threading
import time

import pytest

from horovod_tpu.runner.elastic.discovery import (DiscoveredHosts,
                                                  FixedHostDiscovery,
                                                  HostDiscovery,
                                                  HostDiscoveryScript,
                                                  HostManager)
from horovod_tpu.runner.elastic.driver import ElasticDriver
from horovod_tpu.runner.elastic.registration import (FAILURE, READY, SUCCESS,
                                                     WorkerStateRegistry)
from horovod_tpu.runner.elastic.settings import ElasticSettings
from horovod_tpu.runner.http_server import RendezvousServer


class SequenceDiscovery(HostDiscovery):
    """Yields scripted host sets; the last entry repeats forever."""

    def __init__(self, *host_sets):
        self._sets = list(host_sets)
        self._i = 0

    def find_available_hosts_and_slots(self):
        hosts = self._sets[min(self._i, len(self._sets) - 1)]
        self._i += 1
        return dict(hosts)


def make_driver(discovery, min_np=1, max_np=None, reset_limit=None,
                interval=0.01, worker_fn=None):
    settings = ElasticSettings(min_np=min_np, max_np=max_np,
                               elastic_timeout=5.0,
                               reset_limit=reset_limit,
                               discovery_interval=interval)
    rendezvous = RendezvousServer()
    driver = ElasticDriver(rendezvous, discovery, settings,
                           create_worker_fn=worker_fn)
    return driver, rendezvous


def wait_until(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


# --------------------------------------------------------------- discovery

def test_discovery_script_parses_host_slots(tmp_path):
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho host-1:2\necho host-2\necho '  '\n")
    script.chmod(0o755)
    d = HostDiscoveryScript(str(script), default_slots=4)
    assert d.find_available_hosts_and_slots() == {"host-1": 2, "host-2": 4}


def test_discovery_script_failure_raises_and_keeps_previous_view(tmp_path):
    # A failing script must raise (not return {}), and a HostManager poll
    # over it must keep the previous host view — a transient discovery
    # blip is not "all hosts gone" (ADVICE r1; reference driver.py
    # _discover_hosts semantics).
    import pytest
    import subprocess

    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho host-1:2\n")
    script.chmod(0o755)
    d = HostDiscoveryScript(str(script))
    mgr = HostManager(d)
    assert mgr.update_available_hosts()
    assert mgr.current_hosts.host_slots == {"host-1": 2}

    script.write_text("#!/bin/sh\nexit 1\n")
    with pytest.raises(subprocess.CalledProcessError):
        d.find_available_hosts_and_slots()
    with pytest.raises(subprocess.CalledProcessError):
        mgr.update_available_hosts()
    # previous view retained
    assert mgr.current_hosts.host_slots == {"host-1": 2}


def test_host_manager_stable_order():
    mgr = HostManager(SequenceDiscovery({"a": 2, "b": 2},
                                        {"b": 2, "c": 2, "a": 2}))
    assert mgr.update_available_hosts()
    first = mgr.current_hosts.host_assignment_order
    assert mgr.update_available_hosts()
    second = mgr.current_hosts.host_assignment_order
    # surviving hosts keep relative order; new hosts append
    assert second[:2] == first
    assert second[-1] == "c"


def test_host_manager_blacklist():
    mgr = HostManager(FixedHostDiscovery({"a": 2, "b": 2}))
    mgr.update_available_hosts()
    mgr.blacklist("b")
    assert mgr.is_blacklisted("b")
    assert mgr.current_hosts.host_slots == {"a": 2}
    # blacklisted hosts do not come back on re-poll
    mgr.update_available_hosts()
    assert "b" not in mgr.current_hosts.host_slots


def test_host_manager_blacklist_cooldown():
    mgr = HostManager(FixedHostDiscovery({"a": 1}),
                      cooldown_range=(0.01, 0.02))
    mgr.update_available_hosts()
    mgr.blacklist("a")
    assert mgr.is_blacklisted("a")
    time.sleep(0.05)
    assert not mgr.is_blacklisted("a")
    mgr.update_available_hosts()
    assert mgr.current_hosts.host_slots == {"a": 1}


def test_discovered_hosts_count():
    h = DiscoveredHosts({"a": 2, "b": 3}, ["a", "b"])
    assert h.count_available_slots() == 5


# ---------------------------------------------------------------- registry

class FakeDriver:
    def __init__(self):
        self.resumed = 0
        self.stopped = None

    def resume(self):
        self.resumed += 1

    def stop(self, error=False, reason=None):
        self.stopped = (error, reason)


def test_registry_all_success_stops_cleanly():
    drv = FakeDriver()
    mgr = HostManager(FixedHostDiscovery({"a": 2}))
    reg = WorkerStateRegistry(drv, mgr)
    reg.reset(2)
    reg.record_success("a", 0)
    assert drv.stopped is None
    reg.record_success("a", 1)
    assert drv.stopped == (False, None)
    assert drv.resumed == 0


def test_registry_failure_triggers_resume_and_blacklist():
    drv = FakeDriver()
    mgr = HostManager(FixedHostDiscovery({"a": 1, "b": 1}))
    mgr.update_available_hosts()
    reg = WorkerStateRegistry(drv, mgr)
    reg.reset(2)
    reg.record_failure("b", 0)
    reg.record_success("a", 0)
    assert drv.resumed == 1
    assert mgr.is_blacklisted("b")
    assert not mgr.is_blacklisted("a")


def test_registry_ready_counts_toward_barrier():
    drv = FakeDriver()
    mgr = HostManager(FixedHostDiscovery({"a": 2}))
    reg = WorkerStateRegistry(drv, mgr)
    reg.reset(2)
    reg.record_ready("a", 0)
    reg.record_ready("a", 1)
    # READY workers want a new round, not shutdown
    assert drv.resumed == 1
    assert drv.stopped is None
    # host with READY (not all-FAILURE) slots must not be blacklisted
    assert not mgr.is_blacklisted("a")


def test_registry_reset_limit():
    drv = FakeDriver()
    mgr = HostManager(FixedHostDiscovery({"a": 1}))
    reg = WorkerStateRegistry(drv, mgr, reset_limit=1)
    reg.reset(1)
    reg.record_failure("a", 0)
    assert drv.resumed == 1           # first reset allowed
    reg.reset(1)
    reg.record_failure("a", 0)
    assert drv.stopped is not None and drv.stopped[0] is True
    assert "reset count" in drv.stopped[1]


def test_registry_first_terminal_state_wins():
    drv = FakeDriver()
    mgr = HostManager(FixedHostDiscovery({"a": 2}))
    reg = WorkerStateRegistry(drv, mgr)
    reg.reset(2)
    reg.record_failure("a", 0)
    reg.record_success("a", 0)        # must not overwrite FAILURE
    assert reg.count(FAILURE) == 1
    assert reg.count(SUCCESS) == 0


# ------------------------------------------------------------------ driver

def test_driver_initial_assignment_and_success():
    done = threading.Event()

    def worker(slot):
        done.wait(2)
        return 0

    driver, _ = make_driver(FixedHostDiscovery({"host-1": 2, "host-2": 2}),
                            min_np=4, worker_fn=worker)
    driver.start(4)
    assert driver.world_size() == 4
    for host in ("host-1", "host-2"):
        for slot in range(2):
            assert driver.has_rank_assignment(host, slot)
    info = driver.get_slot_info("host-1", 0)
    assert info.rank == 0 and info.size == 4 and info.cross_size == 2
    done.set()
    assert driver.wait(5)
    assert driver.error is None
    assert set(driver.get_results().values()) == {0}


def test_driver_rank_stability_across_rounds():
    """When a host dies mid-job, surviving (host, slot) pairs keep their
    ranks in the next round (reference driver.py:228 stable ranks)."""
    rounds = []
    fail_first = threading.Event()
    fail_first.set()

    def worker(slot):
        rounds.append((slot.hostname, slot.local_rank, slot.rank,
                       slot.size))
        if slot.hostname == "host-2" and fail_first.is_set():
            fail_first.clear()
            return 1          # host-2 dies in round 1
        return 0

    driver, _ = make_driver(
        SequenceDiscovery({"host-1": 2, "host-2": 2}, {"host-1": 2}),
        min_np=2, max_np=4, worker_fn=worker)
    driver.start(4)
    assert driver.wait(10)
    assert driver.error is None
    r1 = {(h, s): r for h, s, r, _ in rounds[:4]}
    r2 = {(h, s): r for h, s, r, _ in rounds[4:]}
    assert set(r2) == {("host-1", 0), ("host-1", 1)}
    for key in r2:
        assert r2[key] == r1[key]


def test_driver_stops_when_below_min_np():
    def worker(slot):
        return 1 if slot.hostname == "host-2" else 0

    driver, _ = make_driver(FixedHostDiscovery({"host-1": 1, "host-2": 1}),
                            min_np=2, worker_fn=worker)
    driver.start(2)
    assert driver.wait(10)
    # host-2 blacklisted → 1 slot < min_np=2 → error stop
    assert driver.error is not None
    assert "min_np" in driver.error


def test_driver_wait_for_available_slots_timeout():
    driver, _ = make_driver(FixedHostDiscovery({}), min_np=1)
    with pytest.raises(TimeoutError):
        driver.wait_for_available_slots(1, timeout=0.2)


def test_driver_discovery_notifies_workers():
    """A host-set change is PUT to every registered worker notification
    endpoint (reference driver.py:198-226)."""
    from horovod_tpu.runner.elastic.notification import \
        WorkerNotificationManager

    class RecordingState:
        def __init__(self):
            self.updates = []

        def on_hosts_updated(self, ts, res):
            self.updates.append((ts, res))

    hold = threading.Event()

    def worker(slot):
        hold.wait(5)
        return 0

    driver, rendezvous = make_driver(
        SequenceDiscovery({"localhost": 2}, {"localhost": 2},
                          {"localhost": 2, "host-x": 2}),
        min_np=2, max_np=4, worker_fn=worker)
    rendezvous.start()
    mgr = WorkerNotificationManager()
    mgr.start_server()
    state = RecordingState()
    mgr.register_state(state)
    rendezvous.store.put(
        "workers", "0",
        ('{"host": "127.0.0.1", "port": %d}' % mgr.port).encode())
    driver.start(2)
    assert wait_until(lambda: state.updates, timeout=5)
    hold.set()
    driver.stop()
    rendezvous.stop()


def test_driver_folds_concurrent_blacklist_into_inflight_round():
    """A host blacklisted while a re-rendezvous is already activating
    must fold into that activation loop (one worker restart), not
    trigger a second back-to-back round."""
    import json

    hold = threading.Event()

    def worker(slot):
        hold.wait(5)
        return 0

    driver, _ = make_driver(
        FixedHostDiscovery({"host-1": 1, "host-2": 1, "host-3": 1}),
        min_np=1, max_np=3, worker_fn=worker)
    driver.start(3)

    activations = []
    in_first = threading.Event()
    release = threading.Event()
    real_activate = driver._activate_round

    def slow_activate(np_):
        activations.append(np_)
        out = real_activate(np_)
        if len(activations) == 1:
            # the first round is PUBLISHED (assignment snapshot taken)
            # before the concurrent blacklist lands below — the fold
            # loop must then re-activate, not leave a stale round up
            in_first.set()
            release.wait(5)
        return out

    driver._activate_round = slow_activate
    t = threading.Thread(target=driver.resume, daemon=True)
    t.start()
    assert wait_until(in_first.is_set)
    # while the first activation is mid-flight: a survivor's failure
    # report names rank 2 (host-3) -> blacklist + fold
    driver._on_kv_put("failure", "host-1/0", json.dumps(
        {"round": 1, "failed_ranks": [2]}).encode())
    assert driver._resume_pending            # folded, not queued-behind
    # a second resume() while one is in flight returns immediately
    driver.resume()
    release.set()
    t.join(timeout=5)
    assert not t.is_alive()
    # exactly one extra activation folded in; host-3 is out of it
    assert len(activations) == 2
    assert driver.host_manager.is_blacklisted("host-3")
    assert not driver.has_rank_assignment("host-3", 0)
    hold.set()
    driver.stop()


def test_driver_preempt_notice_drains_host_gracefully():
    """/kv/failure/<host>/preempt marks the host DRAINING: it leaves
    the next assignment when capacity allows, is never blacklisted as
    a crash, and stays assigned when dropping it would fall below
    min_np (single-host jobs survive a notice the platform may not
    honor)."""
    import json

    hold = threading.Event()

    def worker(slot):
        hold.wait(5)
        return 0

    driver, _ = make_driver(
        FixedHostDiscovery({"host-1": 1, "host-2": 1}),
        min_np=1, max_np=2, worker_fn=worker)
    driver.start(2)
    driver._on_kv_put("failure", "host-2/preempt", json.dumps(
        {"reason": "signal:15", "graceful": True}).encode())
    assert "host-2" in driver._active_draining()
    assert not driver.host_manager.is_blacklisted("host-2")
    slots = driver._update_host_assignments(2)
    assert {s.hostname for s in slots} == {"host-1"}
    # thin capacity: draining host-1 too would drop below min_np, so
    # the assignment keeps it
    driver._on_kv_put("failure", "host-1/preempt", json.dumps(
        {"reason": "signal:15", "graceful": True}).encode())
    slots = driver._update_host_assignments(2)
    assert len(slots) >= 1
    hold.set()
    driver.stop()


def test_driver_grow_on_resume():
    """After a failure round, newly discovered hosts are folded into the
    next assignment up to max_np."""
    sizes = []
    failed_once = threading.Event()

    def worker(slot):
        sizes.append(slot.size)
        if not failed_once.is_set():
            failed_once.set()
            return 1
        return 0

    driver, _ = make_driver(
        SequenceDiscovery({"host-1": 2}, {"host-1": 2, "host-2": 2}),
        min_np=2, max_np=4, worker_fn=worker)
    driver.host_manager.update_available_hosts()  # consume first set
    driver.start(2)
    assert driver.wait(10)
    assert driver.error is None
    assert max(sizes) == 4
