"""Tier-1 wire-grammar regression tests (hvt_proto).

Replays the committed frame corpus (``tests/corpus/proto_frames.jsonl``
— grammar seeds plus the first fuzzer-found rejection per mutation
class) through ``hvt_decode_probe`` and runs a small deterministic
campaign per decoder family. The full ≥10k-per-family campaign runs in
the ``ci.sh --fuzz`` lane and, via ``tests/test_sanitizers.py``, under
ASan/UBSan builds; this file is the quick always-on slice.
"""

import json
import os
from pathlib import Path

import pytest

from horovod_tpu.engine import native
from horovod_tpu.tools import hvt_fuzz

REPO_ROOT = Path(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CORPUS = REPO_ROOT / "tests" / "corpus" / "proto_frames.jsonl"

pytestmark = pytest.mark.skipif(
    native.decode_probe(0, b"") is None,
    reason="libhvt_core.so not built (make -C horovod_tpu/csrc)")


def test_corpus_replays_exactly():
    """Every committed frame classifies exactly as recorded — a drifted
    outcome means the wire grammar changed without regenerating the
    corpus (hvt_fuzz --write-corpus) and bumping the protocol notes."""
    total, mismatches = hvt_fuzz.replay_corpus(str(CORPUS))
    assert total >= 40  # seeds + at least one rejection per family
    assert mismatches == [], mismatches[:10]


def test_corpus_covers_every_family_both_ways():
    families = {}
    with open(CORPUS) as f:
        for line in f:
            e = json.loads(line)
            families.setdefault(e["name"], set()).add(e["expect"])
    assert set(families) == set(hvt_fuzz.FAMILIES)
    for fam, outcomes in families.items():
        # at least one accepted seed and one typed rejection per family
        # (the dup_rank aggregate seed is itself the rejection seed)
        assert 1 in outcomes, fam
    accepted = {fam for fam, o in families.items() if 0 in o}
    assert accepted == set(hvt_fuzz.FAMILIES)


def test_quick_campaign_has_no_containment_escapes():
    """300 grammar-derived mutants per family: every outcome must be
    ok (0) or typed rejection (1) — outcome 2 is an exception class
    escaping the TruncatedFrameError containment path."""
    total, failures = hvt_fuzz.run_campaign(
        sorted(hvt_fuzz.FAMILIES), 300, seed=20, verbose=False)
    assert total >= 300 * len(hvt_fuzz.FAMILIES)
    assert failures == [], failures[:5]


def test_campaign_is_deterministic():
    """Same seed → byte-identical mutant stream (what makes the CI
    campaign and the sanitizer replays reproducible)."""
    def stream(seed):
        out = []
        for fam in sorted(hvt_fuzz.FAMILIES):
            rng = hvt_fuzz.Random(f"{seed}:{fam}")
            bases = [bytes(w.buf) for _, w, _ in hvt_fuzz.seeds(fam)]
            for _, w, _ in hvt_fuzz.seeds(fam):
                out.extend(m for _, m in hvt_fuzz.structured_mutations(w))
            out.extend(hvt_fuzz.random_mutation(rng, rng.choice(bases))
                       for _ in range(50))
        return out

    assert stream(20) == stream(20)
    assert stream(20) != stream(21)


def test_known_malformed_frames_reject_typed():
    """Hand-written malformations per ISSUE 20's mutation classes land
    on the typed-rejection path (probe outcome 1, never 2)."""
    import struct

    magic = struct.pack("<i", 0x4856524C)
    cases = [
        # truncation at a field boundary
        (3, magic + struct.pack("<i", 1)),
        # length-field inflation: announce hits vector claims 2^31-1
        (0, bytes([0]) + struct.pack("<i", 0x7FFFFFFF)),
        # count overflow: response list one past remaining/min
        (7, struct.pack("<i", 1)),
        # duplicate roster ranks (PR 8 rejection, via the fuzzer seed)
        (1, bytes(hvt_fuzz._seed_aggregate(dup_rank=True).buf)),
        # codec block with impossible stream size
        (5, bytes([2]) + b"\x00" * 3),
        # negative i64vec length inside a request list
        (6, struct.pack("<i", 1) + struct.pack("<i", 0)
            + bytes([0, 0]) + struct.pack("<i", -5)),
    ]
    for family, frame in cases:
        assert native.decode_probe(family, frame) == 1, (family,
                                                        frame.hex())
