"""Ring attention and Ulysses sequence parallelism vs. dense reference.

Runs on the 8-device virtual CPU mesh (conftest). Reference behavior:
the reference framework has no SP/CP (SURVEY.md §5.7); these tests define
the TPU framework's own correctness bar: sharded attention must match the
single-device dense computation to fp32 tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.mesh import make_parallel_mesh
from horovod_tpu.parallel.sequence import (
    ring_attention,
    ulysses_attention,
)


def _dense_reference(q, k, v, causal, scale=None):
    b, s, h, d = q.shape
    scale = scale or d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        pos = jnp.arange(s)
        mask = (pos[None, :] <= pos[:, None])[None, None]
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _qkv(b=2, s=64, h=4, d=16, dtype=jnp.float32):
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    q, k, v = _qkv()
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh=mesh, causal=causal)
    ref = _dense_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_auto_resolves_per_shard(monkeypatch):
    """``use_flash="auto"`` is resolved INSIDE the shard function from
    its local block length — never by dividing a trace-time shape by a
    mesh factor at the call site, which double-divides when the caller
    is already inside its own shard_map (ADVICE r4). Pinned by spying
    on the resolver: with sp=8 over seq 64 it must see 8, not 1."""
    from horovod_tpu.ops import flash_attention as fa
    from horovod_tpu.parallel import sequence as seq_mod

    seen = []
    real = fa.resolve_flash

    def spy(use_flash, local_seq):
        seen.append(local_seq)
        return real(use_flash, local_seq)

    monkeypatch.setattr(fa, "resolve_flash", spy)
    q, k, v = _qkv()
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh=mesh, causal=True,
                         use_flash="auto")
    ref = _dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert seen and all(s == 64 // 8 for s in seen), seen
    del seq_mod  # imported to make the monkeypatch target explicit


@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_attention_gqa_circulates_small_kv(use_flash):
    """GQA K/V enter the ring UN-repeated (h_kv=2 circulating buffers
    for h=4 query heads — half the ICI payload); output must equal
    dense attention over locally-repeated K/V, on both the einsum and
    flash block paths."""
    rng = np.random.RandomState(1)
    b, s, h, h_kv, d = 2, 64, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h_kv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h_kv, d)), jnp.float32)
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh=mesh, causal=True,
                         use_flash=use_flash)
    kr = jnp.repeat(k, h // h_kv, axis=2)
    vr = jnp.repeat(v, h // h_kv, axis=2)
    ref = _dense_reference(q, kr, vr, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_gqa_ppermute_payload_is_small_kv():
    """The claim behind the GQA ring optimization, pinned at the IR
    level: the circulating ppermute buffers carry h_kv heads, not the
    query head count (the broadcast happens locally per block)."""
    from horovod_tpu.parallel.sequence import ring_attention_shard

    b, s_shard, h, h_kv, d = 1, 8, 4, 2, 16

    def shard_fn(q, k, v):
        return ring_attention_shard(q, k, v, axis_name="sp",
                                    causal=True)

    mesh = make_parallel_mesh(sp=8)
    from horovod_tpu.parallel.sequence import _shard_map

    spec = P(None, "sp", None, None)
    wrapped = _shard_map(shard_fn, mesh=mesh,
                         in_specs=(spec,) * 3, out_specs=spec,
                         check_vma=False)
    q = jnp.zeros((b, s_shard * 8, h, d), jnp.float32)
    k = jnp.zeros((b, s_shard * 8, h_kv, d), jnp.float32)
    jaxpr = jax.make_jaxpr(wrapped)(q, k, k)
    # walk the whole tree: the ppermutes live inside the scan eqn that
    # wraps the ring's fori_loop body, nested under the shard_map eqn
    perm_shapes = []

    def walk(jx):
        for e in jx.eqns:
            if e.primitive.name == "ppermute":
                perm_shapes.append(e.invars[0].aval.shape)
            for sub in e.params.values():
                if hasattr(sub, "eqns"):
                    walk(sub)
                elif hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)

    walk(jaxpr.jaxpr)
    assert perm_shapes, "no ppermute found in the ring jaxpr"
    for shape in perm_shapes:
        assert shape[-2] == h_kv, (
            f"ring circulates {shape[-2]} heads; expected the small "
            f"K/V head count {h_kv}")


def test_ulysses_attention_gqa():
    """Ulysses with GQA: K/V heads exchange on their own (smaller)
    head axis; consecutive-query-head grouping survives the a2a."""
    rng = np.random.RandomState(2)
    b, s, h, h_kv, d = 2, 64, 16, 8, 8
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h_kv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h_kv, d)), jnp.float32)
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, mesh=mesh, causal=True)
    kr = jnp.repeat(k, h // h_kv, axis=2)
    vr = jnp.repeat(v, h // h_kv, axis=2)
    ref = _dense_reference(q, kr, vr, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_gqa_indivisible_kv_heads_raises():
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.normal(size=(1, 64, 16, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 64, 2, 8)), jnp.float32)
    mesh = make_parallel_mesh(sp=8)
    with pytest.raises(ValueError, match="K/V heads"):
        ulysses_attention(q, k, k, mesh=mesh)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(causal):
    q, k, v = _qkv(h=8)
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, mesh=mesh, causal=causal)
    ref = _dense_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_2d_mesh_dp_sp():
    """Ring attention composed with data parallelism on a dp×sp mesh."""
    q, k, v = _qkv(b=4, s=32)
    mesh = make_parallel_mesh(dp=2, sp=4)
    spec = P("dp", "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh=mesh, seq_specs=spec, causal=True)
    ref = _dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_jit_under_mesh():
    """ring attention shard fn embedded in a jitted program compiles once
    and matches; exercises the collective-inside-fori_loop path."""
    q, k, v = _qkv(s=32)
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))

    @jax.jit
    def step(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, causal=True) * 2.0

    out = step(qs, ks, vs)
    ref = _dense_reference(q, k, v, True) * 2.0
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_flash_matches_dense():
    """use_flash routes the post-exchange local attention through the
    pallas kernel; must be numerically identical to the dense path."""
    q, k, v = _qkv(h=8)
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, mesh=mesh, causal=True,
                            use_flash=True)
    ref = _dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_dense(causal):
    """use_flash on the RING path: per-block pallas kernel + lse combine
    must match the dense computation."""
    q, k, v = _qkv(s=64)
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh=mesh, causal=causal,
                         use_flash=True)
    ref = _dense_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_gradients_match_dense():
    """The composition must be differentiable end-to-end: gradients flow
    through the kernel's lse output, the logaddexp combine, the masked
    branch of lax.switch, and ppermute."""
    q, k, v = _qkv(s=32)
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))

    def loss_flash(q, k, v):
        o = ring_attention(q, k, v, mesh=mesh, causal=True,
                           use_flash=True)
        return (o.astype(jnp.float32) ** 2).mean()

    def loss_dense(q, k, v):
        return (_dense_reference(q, k, v, True).astype(jnp.float32)
                ** 2).mean()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(qs, ks, vs)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ring_flash_bf16_matches_plain_ring_bf16():
    """In the production dtype the flash ring path must track the einsum
    ring path: both carry fp32 accumulators into the combine (the kernel
    writes out_dtype=fp32 for blockwise consumers)."""
    q, k, v = _qkv(s=64, dtype=jnp.bfloat16)
    mesh = make_parallel_mesh(sp=8)
    spec = P(None, "sp", None, None)
    qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                  for x in (q, k, v))
    o_flash = ring_attention(qs, ks, vs, mesh=mesh, causal=True,
                             use_flash=True)
    o_plain = ring_attention(qs, ks, vs, mesh=mesh, causal=True,
                             use_flash=False)
    assert o_flash.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(o_flash, np.float32), np.asarray(o_plain, np.float32),
        rtol=2e-2, atol=2e-2)
