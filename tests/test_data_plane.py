"""Gang tests for the eager data plane: event-driven cycle draining
(small-tensor latency well under ``cycle_ms``), the pipelined chunked
ring's numerics at chunk-boundary sizes across dtypes/ReduceKinds, and
the negotiated wire-codec family (bf16/int8/fp8 tolerance, exact wire
byte counters, cross-rank bit-identity per codec, chunk/block boundary
decode, error feedback, topology-aware {intra, inter} selection on the
PR 6 lane machinery, default-off exactness).

Every test launches a real multi-process gang through hvtrun on
loopback, with ``HVT_SHM_ALLREDUCE=0`` so the TCP ring — the code under
test — serves the collectives.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")

_PORT = [24000 + (os.getpid() * 613) % 10000]


def _next_port():
    import socket
    while True:
        _PORT[0] += 1
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", _PORT[0]))
                return _PORT[0]
            except OSError:
                continue


def run_workers(body, np=2, timeout=120, extra_env=None, pre=""):
    """Launch an np-proc gang running `body` after hvt.init(). `pre`
    runs BEFORE init — e.g. setting a per-rank HVT_TOPO_HOST off
    HVT_PROCESS_ID to fake a multi-host layout on loopback."""
    _next_port()
    script = textwrap.dedent(f"""
        import os, sys, time, zlib
        sys.path.insert(0, {REPO!r})
        import numpy as np
        {textwrap.indent(textwrap.dedent(pre), '        ').strip() or 'pass'}
        import horovod_tpu as hvt
        hvt.init()
        r, n = hvt.rank(), hvt.size()
        {textwrap.indent(textwrap.dedent(body), '        ').strip()}
        print(f"WORKER-{{r}}-DONE", flush=True)
        hvt.shutdown()
    """)
    path = f"/tmp/hvt_dptest_{os.getpid()}_{_PORT[0]}.py"
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": "", "HVT_SHM_ALLREDUCE": "0"})
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", str(np),
         "--master-port", str(_PORT[0]), sys.executable, path],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    out = proc.stdout + proc.stderr
    for i in range(np):
        assert f"WORKER-{i}-DONE" in out
    return out


def test_event_driven_drains_back_to_back():
    """With cycle_ms cranked to 200, a sleep-paced loop needs ≥ one full
    sleep per op (10 hot ops ≥ 2 s); the event-driven loop must clear
    all 10 in a fraction of that. Also pins the observability satellite:
    WAKEUP events in the ring and both new histograms populated."""
    out = run_workers("""
        from horovod_tpu.engine import native
        x = np.arange(1024, dtype=np.float32)
        hvt.allreduce(x, op=hvt.Sum, name="hot")  # prime the cache
        t0 = time.perf_counter()
        for _ in range(10):
            hvt.allreduce(x, op=hvt.Sum, name="hot")
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.0, f"10 hot 4KB ops took {elapsed:.2f}s " \
            "with cycle_ms=200 — event-driven draining is not engaging"
        st = native.engine_stats()
        assert st["wakeup_hist"]["count"] > 0, "no wakeups observed"
        assert st["cycle_hist"]["count"] > 0, "no cycle durations"
        kinds = {e["kind_name"] for e in native.drain_events()}
        assert "WAKEUP" in kinds, f"no WAKEUP events (saw {kinds})"
        if r == 0:
            print("ELAPSED", round(elapsed, 3), flush=True)
    """, extra_env={"HVT_CYCLE_TIME_MS": "200"})
    assert "ELAPSED" in out


def test_pipelined_ring_numerics_at_chunk_boundaries():
    """Chunk size forced to 4 KB (1024 fp32 elems) so payloads cross
    chunk boundaries: below, at, just past, several-chunks+remainder,
    and count < ranks. All dtypes, all elementwise ReduceKinds."""
    run_workers("""
        sizes = [1, 2, 3, 1023, 1024, 1025, 4103]
        dtypes = [np.float32, np.float64, np.float16, np.int32,
                  np.int64, np.uint8, np.int8]
        try:
            import ml_dtypes
            dtypes.append(np.dtype("bfloat16"))
        except Exception:
            pass
        for numel in sizes:
            for dt in dtypes:
                base = (np.arange(numel) % 5 + 1)
                x = (base + r).astype(dt)
                nm = f"s.{numel}.{np.dtype(dt).name}"
                res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=nm))
                exp = sum((base + i).astype(dt) for i in range(n))
                np.testing.assert_array_equal(
                    res.astype(np.float64), exp.astype(np.float64),
                    err_msg=nm)
        # other ReduceKinds at a boundary-crossing size
        numel = 1025
        base = np.arange(numel) % 7 + 1
        for op, fn in ((hvt.Min, np.minimum), (hvt.Max, np.maximum)):
            x = ((base + 11 * r) % 13).astype(np.float32)
            res = np.asarray(hvt.allreduce(x, op=op, name=f"mm.{op.name}"))
            exp = ((base + 0) % 13).astype(np.float32)
            for i in range(1, n):
                exp = fn(exp, ((base + 11 * i) % 13).astype(np.float32))
            np.testing.assert_array_equal(res, exp)
        x = np.where(base % 2 == 0, 2.0, 1.0).astype(np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Product, name="prod"))
        np.testing.assert_array_equal(res, x ** n)
        # Average exercises the postscale fold (scale rides the ring's
        # allgather pass); ints now round rather than truncate
        x = np.full((numel,), float(r + 1), np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Average, name="avgf"))
        np.testing.assert_allclose(res, (1 + n) / 2.0)
        xi = np.full((numel,), r + 1, np.int32)
        res = np.asarray(hvt.allreduce(xi, op=hvt.Average, name="avgi"))
        # llround semantics: positive halves round AWAY from zero
        exp_avg = int(np.floor((n * (n + 1) / 2) / n + 0.5))
        np.testing.assert_array_equal(res, exp_avg)
    """, extra_env={"HVT_RING_CHUNK_BYTES": "4096"}, timeout=180)


def test_bf16_wire_allreduce_4proc():
    """HVT_WIRE_COMPRESSION=bf16 on a 4-proc gang: fp32 results within
    bf16 tolerance, bit-identical across ranks, and exactly half the
    raw plane's wire bytes (counted by the per-op tx counters)."""
    run_workers("""
        from horovod_tpu.engine import native
        assert hvt.wire_compression() == ("bf16", "bf16")
        numel = 1 << 16
        x = (np.arange(numel, dtype=np.float32) % 997) * 0.123 + r
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="c"))
        exp = sum((np.arange(numel, dtype=np.float32) % 997) * 0.123 + i
                  for i in range(n))
        # documented tolerance: bf16 has an 8-bit mantissa → relative
        # error ≤ ~2^-7 per wire hop (docs/performance.md)
        np.testing.assert_allclose(res, exp, rtol=1e-2)
        st = native.engine_stats()
        tx = st["wire_tx_bytes"]["allreduce"]
        txc = st["wire_tx_comp_bytes"]["allreduce"]
        # ring sends 2(n-1)/n of the payload per rank; compressed form
        # halves it, and every allreduce byte went out compressed
        raw_wire = 2 * (n - 1) * numel * 4 // n
        assert tx == raw_wire // 2, (tx, raw_wire)
        assert txc == tx > 0
        # all ranks end bit-identical (owners round-trip through bf16)
        crcs = hvt.allgather(
            np.array([zlib.crc32(res.tobytes())], np.int64), name="crc")
        assert len(set(int(c) for c in np.asarray(crcs))) == 1
    """, np=4, extra_env={"HVT_WIRE_COMPRESSION": "bf16"}, timeout=180)


def test_wire_default_off_exact_and_uncompressed():
    """Without HVT_WIRE_COMPRESSION the plane must be bit-exact (integer
    payloads sum exactly in fp32) and count zero compressed bytes."""
    run_workers("""
        from horovod_tpu.engine import native
        assert hvt.wire_compression() == ("none", "none")
        numel = 1 << 16
        x = (np.arange(numel) % 1001 + r).astype(np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="exact"))
        exp = sum((np.arange(numel) % 1001 + i).astype(np.float32)
                  for i in range(n))
        np.testing.assert_array_equal(res, exp)
        st = native.engine_stats()
        assert st["wire_tx_comp_bytes"]["allreduce"] == 0
        assert st["wire_tx_bytes"]["allreduce"] == \
            2 * (n - 1) * numel * 4 // n
    """)


# Per-256-elem block: 4-byte in-band scale + 1 byte per elem.
_BLOCK_WIRE = "lambda n: (n // 256) * 260 + (4 + n % 256 if n % 256 else 0)"


def test_block_codec_crc_identity_and_exact_bytes_4proc():
    """int8/fp8 on a 4-proc ring: results within the documented block
    tolerance, bit-identical across ranks (owner roundtrip), and the
    per-op + per-codec tx counters equal to the EXACT wire formula —
    ≥3.5x under raw for int8 (the r09 headline)."""
    for codec in ("int8", "fp8"):
        out = run_workers(f"""
            from horovod_tpu.engine import native
            codec = {codec!r}
            assert hvt.wire_compression() == (codec, codec)
            numel = 1 << 16
            x = (np.arange(numel, dtype=np.float32) % 997) * 0.123 + r
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="c"))
            exp = sum((np.arange(numel, dtype=np.float32) % 997) * 0.123
                      + i for i in range(n))
            # block-scaled error: ~blockmax/254 (int8) / ~blockmax/16
            # (fp8) per quantization, a few quantizations deep
            np.testing.assert_allclose(
                res, exp, rtol=0.02 if codec == "int8" else 0.2,
                atol=np.abs(exp).max() * 0.02)
            st = native.engine_stats()
            tx = st["wire_tx_bytes"]["allreduce"]
            seg = numel // n
            wire = {_BLOCK_WIRE}
            # 2(n-1) segments per rank, each compressed independently
            assert tx == 2 * (n - 1) * wire(seg), (tx, wire(seg))
            assert st["wire_tx_comp_bytes"]["allreduce"] == tx
            assert st["codec_tx_bytes"][codec]["allreduce"] == tx
            raw = 2 * (n - 1) * seg * 4
            if codec == "int8":
                assert raw / tx >= 3.5, (raw, tx)
            crcs = hvt.allgather(
                np.array([zlib.crc32(res.tobytes())], np.int64),
                name="crc")
            assert len(set(int(c) for c in np.asarray(crcs))) == 1
        """, np=4, extra_env={"HVT_WIRE_COMPRESSION": codec},
            timeout=180)
        assert "WORKER-3-DONE" in out


def test_block_codec_chunk_boundary_numerics():
    """HVT_RING_CHUNK_BYTES=4096 forces blocks to straddle pipeline
    chunk edges (a 260-byte wire block never divides 4096): sizes
    below/at/past block and chunk boundaries must decode identically to
    the unchunked path, non-fp32 dtypes must stay exact (codecs gate on
    fp32), and Average must ride the postscale fold."""
    run_workers("""
        from horovod_tpu.engine import native
        sizes = [1, 255, 256, 257, 1023, 1024, 1025, 4103, 16384]
        for numel in sizes:
            x = ((np.arange(numel) % 997) * 0.37 + r).astype(np.float32)
            nm = f"cb.{numel}"
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=nm))
            exp = sum(((np.arange(numel) % 997) * 0.37 + i)
                      .astype(np.float32) for i in range(n))
            np.testing.assert_allclose(res, exp, rtol=0.02,
                                       atol=np.abs(exp).max() * 0.02,
                                       err_msg=nm)
        # non-fp32 payloads move raw and stay EXACT under the codec env
        for dt in (np.int32, np.float64, np.float16):
            numel = 1025
            x = (np.arange(numel) % 5 + 1 + r).astype(dt)
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum,
                                           name=f"ex.{np.dtype(dt).name}"))
            exp = sum((np.arange(numel) % 5 + 1 + i).astype(dt)
                      for i in range(n))
            np.testing.assert_array_equal(res.astype(np.float64),
                                          exp.astype(np.float64))
        # Average: postscale folds in before the owner roundtrip
        x = np.full((4103,), float(r + 1), np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Average, name="avg"))
        np.testing.assert_allclose(res, (1 + n) / 2.0, rtol=0.01)
    """, extra_env={"HVT_RING_CHUNK_BYTES": "4096",
                    "HVT_WIRE_COMPRESSION": "int8"}, timeout=180)


def test_error_feedback_unbiases_repeated_allreduce():
    """Repeated int8 allreduce-average of a constant tensor whose small
    entries sit far below the block quantization threshold: without EF
    they are zeroed every step (running mean stays 0); with EF the
    residual carries until it crosses the threshold and the running
    mean converges to the exact average."""
    for ef, expect_biased in (("1", False), ("0", True)):
        out = run_workers("""
            from horovod_tpu.engine import native
            x = np.full(256, 0.01, np.float32)
            x[0] = 100.0  # pins the block scale at ~0.79 >> 0.01
            steps = 120
            acc = np.zeros(256)
            for t in range(steps):
                acc += np.asarray(
                    hvt.allreduce(x, op=hvt.Average, name="ef"))
            mean = acc / steps
            st = native.engine_stats()
            if r == 0:
                print("EF-RESULT", mean[1], mean[0],
                      st["ef_residual_bytes"], flush=True)
        """, extra_env={"HVT_WIRE_COMPRESSION": "int8",
                        "HVT_ERROR_FEEDBACK": ef}, timeout=240)
        line = [ln for ln in out.splitlines() if "EF-RESULT" in ln][0]
        small, big, ef_bytes = line.split("EF-RESULT", 1)[1].split()
        small, big = float(small), float(big)
        assert abs(big - 100.0) < 0.5
        if expect_biased:
            assert small == 0.0, f"no-EF mean should be zeroed: {small}"
            assert int(ef_bytes) == 0
        else:
            assert abs(small - 0.01) < 0.005, \
                f"EF mean should approach 0.01: {small}"
            assert int(ef_bytes) >= 256 * 4


_FAKE_2HOSTS = """
import os
os.environ["HVT_TOPO_HOST"] = (
    "hostA" if int(os.environ.get("HVT_PROCESS_ID", "0")) < 2 else "hostB")
"""


def test_topology_pair_mixed_lanes():
    """EQuARX selection on the PR 6 lane machinery: with the pair
    `none,int8` on a faked 2x2-host layout, a same-host lane moves raw
    bytes (exact results) while a cross-host lane moves int8 — two
    lanes, two codecs, one gang. The global allreduce rides the
    hierarchical backend (intra phases raw, cross phase int8)."""
    run_workers("""
        from horovod_tpu.engine import native
        from horovod_tpu.common.process_sets import (ProcessSet,
                                                     add_process_set)
        assert hvt.wire_compression() == ("none", "int8")
        intra_set = add_process_set(ProcessSet([0, 1]))   # one host
        inter_set = add_process_set(ProcessSet([0, 2]))   # spans hosts
        numel = 1 << 12
        base = (np.arange(numel) % 997).astype(np.float32) * 0.61
        # same-host lane: intra codec "none" → bit-exact
        if r in (0, 1):
            res = np.asarray(hvt.allreduce(base + r, op=hvt.Sum,
                                           name="laneA",
                                           process_set=intra_set))
            np.testing.assert_array_equal(res, (base + 0) + (base + 1))
        # cross-host lane: inter codec int8 → lossy but close, and the
        # int8 tx counter moves on its members
        if r in (0, 2):
            res = np.asarray(hvt.allreduce(base + r, op=hvt.Sum,
                                           name="laneB",
                                           process_set=inter_set))
            exp = (base + 0) + (base + 2)
            np.testing.assert_allclose(res, exp, rtol=0.02,
                                       atol=np.abs(exp).max() * 0.02)
            assert not np.array_equal(res, exp), \
                "cross-host lane should be quantized"
        # global allreduce: hierarchical (2 hosts x 2 ranks) — works and
        # stays within int8 tolerance (cross phase only is lossy)
        res = np.asarray(hvt.allreduce(base + r, op=hvt.Sum, name="g"))
        exp = sum(base + i for i in range(n))
        np.testing.assert_allclose(res, exp, rtol=0.02,
                                   atol=np.abs(exp).max() * 0.02)
        st = native.engine_stats()
        ctx = st["codec_tx_bytes"]
        if r in (0, 2):
            assert ctx["int8"]["allreduce"] > 0, ctx
        assert ctx["none"]["allreduce"] > 0, ctx
        # cross-gang agreement on the pair even though only rank 0's
        # stamps matter
        crcs = hvt.allgather(np.array([zlib.crc32(res.tobytes())],
                                      np.int64), name="crcg")
        assert len(set(int(c) for c in np.asarray(crcs))) == 1
    """, np=4, pre=_FAKE_2HOSTS,
        extra_env={"HVT_WIRE_COMPRESSION": "none,int8"}, timeout=240)


def test_auto_mode_explores_and_converges():
    """HVT_WIRE_COMPRESSION=auto on a faked 2-host pair (auto quantizes
    only inter-host links, so a genuinely single-host gang correctly
    stays raw): rank 0's tuner rotates raw/bf16/int8 on live traffic
    (several codecs' tx counters move during exploration), results stay
    within the loosest candidate's tolerance, and the gang never
    wedges."""
    run_workers("""
        from horovod_tpu.engine import native
        numel = 1 << 14
        x = (np.arange(numel, dtype=np.float32) % 997) * 0.5 + r
        exp = sum((np.arange(numel, dtype=np.float32) % 997) * 0.5 + i
                  for i in range(n))
        for t in range(30):
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="auto"))
            np.testing.assert_allclose(res, exp, rtol=0.02,
                                       atol=np.abs(exp).max() * 0.02)
        intra, inter, auto = native.wire_compression()
        assert auto and intra == 0
        st = native.engine_stats()
        moved = [c for c, ops in st["codec_tx_bytes"].items()
                 if ops["allreduce"] > 0]
        assert len(moved) >= 2, f"tuner never explored: {moved}"
    """, pre="""
        import os
        os.environ["HVT_TOPO_HOST"] = \
            "h" + os.environ.get("HVT_PROCESS_ID", "0")
    """, extra_env={"HVT_WIRE_COMPRESSION": "auto"}, timeout=240)


def test_auto_mode_single_host_stays_raw():
    """auto on a genuinely single-host gang: no group has an inter-host
    hop, so the tuner must never be consulted — the stamped/reported
    inter codec stays raw at every step (a rotating exploration pick
    here would report phantom codecs and break bypass uniformity),
    results are bit-exact, and only the `none` tx counter moves."""
    run_workers("""
        from horovod_tpu.engine import native
        numel = 1 << 12
        x = (np.arange(numel, dtype=np.float32) % 997) * 0.5 + r
        exp = sum((np.arange(numel, dtype=np.float32) % 997) * 0.5 + i
                  for i in range(n))
        for t in range(20):
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="raw1h"))
            np.testing.assert_array_equal(res, exp)
            intra, inter, auto = native.wire_compression()
            assert auto and intra == 0 and inter == 0, \\
                (t, intra, inter, auto)
        st = native.engine_stats()
        moved = [c for c, ops in st["codec_tx_bytes"].items()
                 if ops["allreduce"] > 0]
        assert moved == ["none"], moved
    """, extra_env={"HVT_WIRE_COMPRESSION": "auto"}, timeout=240)


def test_auto_mode_mixed_workload_keeps_bypass():
    """auto on a faked 2-host gang with a MIXED per-step workload: a
    single-host process-set allreduce (link intra, inter pick forced
    raw) co-scheduled with a global cross-host allreduce (tuner-picked
    inter). The intra-only response's forced-raw stamp sits outside the
    bypass uniformity accounting — while the tuner explores nonzero
    codecs (trials 6..15 are deterministically bf16/int8), the
    steady-state positions-form bypass must still engage. Async submits
    put both announces in one control frame per rank, so the two
    responses land in the same cycle by construction (the root ingests
    exactly one frame per child per cycle)."""
    run_workers("""
        from horovod_tpu.engine import native
        from horovod_tpu.common.process_sets import (ProcessSet,
                                                     add_process_set)
        lane = add_process_set(ProcessSet([0, 1]))  # hostA only
        numel = 1 << 12
        x = (np.arange(numel, dtype=np.float32) % 997) * 0.5 + r
        gexp = sum((np.arange(numel, dtype=np.float32) % 997) * 0.5 + i
                   for i in range(n))
        lexp = sum((np.arange(numel, dtype=np.float32) % 997) * 0.5 + i
                   for i in range(2))

        def step():
            hs = []
            if r in (0, 1):
                hs.append(("lane", hvt.allreduce_async(
                    x, op=hvt.Sum, name="mlane", process_set=lane)))
            hs.append(("g", hvt.allreduce_async(x, op=hvt.Sum,
                                                name="mglob")))
            for kind, h in hs:
                res = np.asarray(h.wait())
                if kind == "lane":  # intra link stays raw → bit-exact
                    np.testing.assert_array_equal(res, lexp)
                else:  # rotating inter codec → loosest-candidate tol
                    np.testing.assert_allclose(
                        res, gexp, rtol=0.02,
                        atol=np.abs(gexp).max() * 0.02)

        for t in range(6):   # cache warm + the 5 raw-trial steps
            step()
        b0 = native.engine_stats()["ctrl_bypass_cycles"]
        for t in range(10):  # bf16/int8 exploration: picks nonzero
            step()
        delta = native.engine_stats()["ctrl_bypass_cycles"] - b0
        assert delta >= 6, \\
            f"mixed cycles stopped bypassing under auto: delta={delta}"
    """, np=4, pre=_FAKE_2HOSTS,
        extra_env={"HVT_WIRE_COMPRESSION": "auto"}, timeout=240)


def test_pair_spec_intra_codec_with_auto_inter():
    """`bf16,auto` honors the configured intra codec: on a single-host
    gang the in-host links actually move bf16 (tx counter proves it,
    and the stamped pair reports it) while the auto inter side stays
    raw for lack of inter-host hops."""
    run_workers("""
        from horovod_tpu.engine import native
        numel = 1 << 12
        x = (np.arange(numel, dtype=np.float32) % 997) * 0.5 + r
        exp = sum((np.arange(numel, dtype=np.float32) % 997) * 0.5 + i
                  for i in range(n))
        for t in range(10):
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="bfa"))
            np.testing.assert_allclose(res, exp, rtol=0.01,
                                       atol=np.abs(exp).max() * 0.01)
        intra, inter, auto = native.wire_compression()
        assert auto and intra == 1 and inter == 0, (intra, inter, auto)
        st = native.engine_stats()
        assert st["codec_tx_bytes"]["bf16"]["allreduce"] > 0, \\
            st["codec_tx_bytes"]
    """, extra_env={"HVT_WIRE_COMPRESSION": "bf16,auto"}, timeout=240)
