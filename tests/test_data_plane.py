"""Gang tests for the PR-3 eager data-plane overhaul: event-driven
cycle draining (small-tensor latency well under ``cycle_ms``), the
pipelined chunked ring's numerics at chunk-boundary sizes across
dtypes/ReduceKinds, and the negotiated bf16 wire codec (tolerance,
halved wire bytes, cross-rank bit-identity, default-off exactness).

Every test launches a real multi-process gang through hvtrun on
loopback, with ``HVT_SHM_ALLREDUCE=0`` so the TCP ring — the code under
test — serves the collectives.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")

_PORT = [24000 + (os.getpid() * 613) % 10000]


def _next_port():
    import socket
    while True:
        _PORT[0] += 1
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", _PORT[0]))
                return _PORT[0]
            except OSError:
                continue


def run_workers(body, np=2, timeout=120, extra_env=None):
    _next_port()
    script = textwrap.dedent(f"""
        import os, sys, time, zlib
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvt
        hvt.init()
        r, n = hvt.rank(), hvt.size()
        {textwrap.indent(textwrap.dedent(body), '        ').strip()}
        print(f"WORKER-{{r}}-DONE", flush=True)
        hvt.shutdown()
    """)
    path = f"/tmp/hvt_dptest_{os.getpid()}_{_PORT[0]}.py"
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": "", "HVT_SHM_ALLREDUCE": "0"})
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", str(np),
         "--master-port", str(_PORT[0]), sys.executable, path],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    out = proc.stdout + proc.stderr
    for i in range(np):
        assert f"WORKER-{i}-DONE" in out
    return out


def test_event_driven_drains_back_to_back():
    """With cycle_ms cranked to 200, a sleep-paced loop needs ≥ one full
    sleep per op (10 hot ops ≥ 2 s); the event-driven loop must clear
    all 10 in a fraction of that. Also pins the observability satellite:
    WAKEUP events in the ring and both new histograms populated."""
    out = run_workers("""
        from horovod_tpu.engine import native
        x = np.arange(1024, dtype=np.float32)
        hvt.allreduce(x, op=hvt.Sum, name="hot")  # prime the cache
        t0 = time.perf_counter()
        for _ in range(10):
            hvt.allreduce(x, op=hvt.Sum, name="hot")
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.0, f"10 hot 4KB ops took {elapsed:.2f}s " \
            "with cycle_ms=200 — event-driven draining is not engaging"
        st = native.engine_stats()
        assert st["wakeup_hist"]["count"] > 0, "no wakeups observed"
        assert st["cycle_hist"]["count"] > 0, "no cycle durations"
        kinds = {e["kind_name"] for e in native.drain_events()}
        assert "WAKEUP" in kinds, f"no WAKEUP events (saw {kinds})"
        if r == 0:
            print("ELAPSED", round(elapsed, 3), flush=True)
    """, extra_env={"HVT_CYCLE_TIME_MS": "200"})
    assert "ELAPSED" in out


def test_pipelined_ring_numerics_at_chunk_boundaries():
    """Chunk size forced to 4 KB (1024 fp32 elems) so payloads cross
    chunk boundaries: below, at, just past, several-chunks+remainder,
    and count < ranks. All dtypes, all elementwise ReduceKinds."""
    run_workers("""
        sizes = [1, 2, 3, 1023, 1024, 1025, 4103]
        dtypes = [np.float32, np.float64, np.float16, np.int32,
                  np.int64, np.uint8, np.int8]
        try:
            import ml_dtypes
            dtypes.append(np.dtype("bfloat16"))
        except Exception:
            pass
        for numel in sizes:
            for dt in dtypes:
                base = (np.arange(numel) % 5 + 1)
                x = (base + r).astype(dt)
                nm = f"s.{numel}.{np.dtype(dt).name}"
                res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=nm))
                exp = sum((base + i).astype(dt) for i in range(n))
                np.testing.assert_array_equal(
                    res.astype(np.float64), exp.astype(np.float64),
                    err_msg=nm)
        # other ReduceKinds at a boundary-crossing size
        numel = 1025
        base = np.arange(numel) % 7 + 1
        for op, fn in ((hvt.Min, np.minimum), (hvt.Max, np.maximum)):
            x = ((base + 11 * r) % 13).astype(np.float32)
            res = np.asarray(hvt.allreduce(x, op=op, name=f"mm.{op.name}"))
            exp = ((base + 0) % 13).astype(np.float32)
            for i in range(1, n):
                exp = fn(exp, ((base + 11 * i) % 13).astype(np.float32))
            np.testing.assert_array_equal(res, exp)
        x = np.where(base % 2 == 0, 2.0, 1.0).astype(np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Product, name="prod"))
        np.testing.assert_array_equal(res, x ** n)
        # Average exercises the postscale fold (scale rides the ring's
        # allgather pass); ints now round rather than truncate
        x = np.full((numel,), float(r + 1), np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Average, name="avgf"))
        np.testing.assert_allclose(res, (1 + n) / 2.0)
        xi = np.full((numel,), r + 1, np.int32)
        res = np.asarray(hvt.allreduce(xi, op=hvt.Average, name="avgi"))
        # llround semantics: positive halves round AWAY from zero
        exp_avg = int(np.floor((n * (n + 1) / 2) / n + 0.5))
        np.testing.assert_array_equal(res, exp_avg)
    """, extra_env={"HVT_RING_CHUNK_BYTES": "4096"}, timeout=180)


def test_bf16_wire_allreduce_4proc():
    """HVT_WIRE_COMPRESSION=bf16 on a 4-proc gang: fp32 results within
    bf16 tolerance, bit-identical across ranks, and exactly half the
    raw plane's wire bytes (counted by the per-op tx counters)."""
    run_workers("""
        from horovod_tpu.engine import native
        assert hvt.wire_compression() == "bf16"
        numel = 1 << 16
        x = (np.arange(numel, dtype=np.float32) % 997) * 0.123 + r
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="c"))
        exp = sum((np.arange(numel, dtype=np.float32) % 997) * 0.123 + i
                  for i in range(n))
        # documented tolerance: bf16 has an 8-bit mantissa → relative
        # error ≤ ~2^-7 per wire hop (docs/performance.md)
        np.testing.assert_allclose(res, exp, rtol=1e-2)
        st = native.engine_stats()
        tx = st["wire_tx_bytes"]["allreduce"]
        txc = st["wire_tx_comp_bytes"]["allreduce"]
        # ring sends 2(n-1)/n of the payload per rank; compressed form
        # halves it, and every allreduce byte went out compressed
        raw_wire = 2 * (n - 1) * numel * 4 // n
        assert tx == raw_wire // 2, (tx, raw_wire)
        assert txc == tx > 0
        # all ranks end bit-identical (owners round-trip through bf16)
        crcs = hvt.allgather(
            np.array([zlib.crc32(res.tobytes())], np.int64), name="crc")
        assert len(set(int(c) for c in np.asarray(crcs))) == 1
    """, np=4, extra_env={"HVT_WIRE_COMPRESSION": "bf16"}, timeout=180)


def test_wire_default_off_exact_and_uncompressed():
    """Without HVT_WIRE_COMPRESSION the plane must be bit-exact (integer
    payloads sum exactly in fp32) and count zero compressed bytes."""
    run_workers("""
        from horovod_tpu.engine import native
        assert hvt.wire_compression() == "none"
        numel = 1 << 16
        x = (np.arange(numel) % 1001 + r).astype(np.float32)
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="exact"))
        exp = sum((np.arange(numel) % 1001 + i).astype(np.float32)
                  for i in range(n))
        np.testing.assert_array_equal(res, exp)
        st = native.engine_stats()
        assert st["wire_tx_comp_bytes"]["allreduce"] == 0
        assert st["wire_tx_bytes"]["allreduce"] == \
            2 * (n - 1) * numel * 4 // n
    """)
