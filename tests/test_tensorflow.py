"""TF-binding tests with numpy-level fakes — the binding's core is
framework-agnostic, so these run even without a TF install (the gated
pattern the Ray/Spark suites use). Real-TF coverage lives in
``test_tensorflow_real.py``. Reference API under test:
``tensorflow/__init__.py:396-742`` DistributedOptimizer /
_DistributedGradientTape."""

import numpy as np

import horovod_tpu.tensorflow as hvt_tf
from horovod_tpu.tensorflow.compression import Compression


class FakeTape:
    """Quacks like tf.GradientTape for .gradient()."""

    def __init__(self, grads):
        self.grads = grads
        self.calls = 0

    def gradient(self, target, sources, output_gradients=None):
        self.calls += 1
        return self.grads


class FakeIndexedSlices:
    def __init__(self, values, indices):
        self.values = np.asarray(values)
        self.indices = np.asarray(indices)


class FakeOptimizer:
    def __init__(self):
        self.applied = []
        self.lr = 0.125  # arbitrary attribute for passthrough checks

    def apply_gradients(self, grads_and_vars, **kwargs):
        self.applied.append(list(grads_and_vars))
        return "applied"


def test_tape_dense_grads_single_process():
    grads = [np.full((3,), 4.0, np.float32), None,
             np.arange(4, dtype=np.float32)]
    tape = hvt_tf.DistributedGradientTape(FakeTape(grads))
    out = tape.gradient("loss", ["a", "b", "c"])
    assert out[1] is None
    np.testing.assert_allclose(out[0], grads[0])  # avg over 1 process
    np.testing.assert_allclose(out[2], grads[2])
    assert tape._tape.calls == 1


def test_tape_single_tensor_and_fp16_compression():
    g = np.full((8,), 3.0, np.float32)
    tape = hvt_tf.DistributedGradientTape(FakeTape(g),
                                          compression=Compression.fp16)
    out = tape.gradient("loss", "w")
    assert not isinstance(out, list)
    assert out.dtype == np.float32  # decompressed back
    np.testing.assert_allclose(out, 3.0)


def test_tape_sparse_grads_roundtrip():
    g = FakeIndexedSlices(np.full((2, 3), 6.0, np.float32), [1, 4])
    out = tape_out = hvt_tf.DistributedGradientTape(
        FakeTape([g])).gradient("loss", ["emb"])[0]
    assert isinstance(tape_out, FakeIndexedSlices)
    np.testing.assert_array_equal(out.indices, [1, 4])
    np.testing.assert_allclose(out.values, 6.0)  # avg over 1 process


def test_optimizer_applies_reduced_grads_and_delegates():
    inner = FakeOptimizer()
    opt = hvt_tf.DistributedOptimizer(inner)
    assert opt.lr == 0.125  # attribute passthrough
    g = np.ones((2,), np.float32)
    r = opt.apply_gradients([(g, "var0"), (None, "var1")])
    assert r == "applied"
    (applied,) = inner.applied
    np.testing.assert_allclose(applied[0][0], 1.0)
    assert applied[0][1] == "var0" and applied[1] == (None, "var1")


def test_optimizer_backward_passes_per_step_aggregates():
    inner = FakeOptimizer()
    opt = hvt_tf.DistributedOptimizer(inner, backward_passes_per_step=3)
    g = np.ones((2,), np.float32)
    assert opt.apply_gradients([(g, "v")]) is None
    assert opt.apply_gradients([(2 * g, "v")]) is None
    assert inner.applied == []  # no update during aggregation
    opt.apply_gradients([(3 * g, "v")])
    (applied,) = inner.applied
    np.testing.assert_allclose(applied[0][0], 6.0)  # local sum 1+2+3
    # next cycle starts fresh
    assert opt.apply_gradients([(g, "v")]) is None


def test_optimizer_average_aggregated_gradients():
    inner = FakeOptimizer()
    opt = hvt_tf.DistributedOptimizer(inner, backward_passes_per_step=2,
                                      average_aggregated_gradients=True)
    g = np.ones((2,), np.float32)
    opt.apply_gradients([(g, "v")])
    opt.apply_gradients([(3 * g, "v")])
    (applied,) = inner.applied
    np.testing.assert_allclose(applied[0][0], 2.0)  # (1+3)/2


def test_optimizer_rejects_sparse_with_aggregation():
    import pytest

    opt = hvt_tf.DistributedOptimizer(FakeOptimizer(),
                                      backward_passes_per_step=2)
    s = FakeIndexedSlices(np.ones((1, 2), np.float32), [0])
    with pytest.raises(ValueError, match="sparse"):
        opt.apply_gradients([(s, "emb")])


def test_compression_fp16_roundtrip_and_passthrough():
    c = Compression.fp16
    x = np.linspace(-2, 2, 7, dtype=np.float32)
    comp, ctx = c.compress(x)
    assert comp.dtype == np.float16 and ctx == np.float32
    back = c.decompress(comp, ctx)
    assert back.dtype == np.float32
    np.testing.assert_allclose(back, x, atol=1e-2)
    ints = np.arange(4, dtype=np.int64)
    comp, ctx = c.compress(ints)
    assert comp.dtype == np.int64 and ctx is None


def test_keras_distributed_optimizer_delegates():
    """keras.DistributedOptimizer routes through the eager TF wrapper
    (Keras 3 drives updates through apply_gradients)."""
    import horovod_tpu.keras as hvt_keras

    inner = FakeOptimizer()
    opt = hvt_keras.DistributedOptimizer(inner, backward_passes_per_step=2)
    g = np.ones((2,), np.float32)
    assert opt.apply_gradients([(g, "v")]) is None
    opt.apply_gradients([(g, "v")])
    (applied,) = inner.applied
    np.testing.assert_allclose(applied[0][0], 2.0)
