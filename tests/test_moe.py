"""MoE / expert-parallelism tests: routing math, capacity, and compiled
execution on a dp×ep mesh (XLA inserts the all-to-alls)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.models.moe import MoEMlp, Router, moe_param_partition_spec
from horovod_tpu.parallel.mesh import make_parallel_mesh


def test_router_dispatch_is_permutation():
    """With ample capacity every token lands in exactly one (expert, slot)
    and the combine weights equal the chosen gate values."""
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16)
                    .astype(np.float32))
    router = Router(n_experts=4, capacity_factor=4.0)
    vars_ = router.init(jax.random.PRNGKey(0), x)
    dispatch, combine, aux = router.apply(vars_, x)
    assert dispatch.shape == (2, 8, 4, 8)
    # each token dispatched exactly once
    np.testing.assert_allclose(
        np.asarray(dispatch.sum(axis=(2, 3))), 1.0, atol=1e-6)
    # each (expert, slot) holds at most one token
    assert float(dispatch.sum(axis=1).max()) <= 1.0 + 1e-6
    # combine weight ≤ gate ≤ 1, positive where dispatched
    c = np.asarray(combine.sum(axis=(2, 3)))
    assert (c > 0).all() and (c <= 1.0 + 1e-6).all()
    assert float(aux) > 0


def test_router_capacity_drops_overflow():
    """With capacity 1 and tokens forced to one expert, only the first
    token per batch row survives."""
    x = jnp.ones((1, 6, 8), jnp.float32)     # identical tokens → same expert
    router = Router(n_experts=4, capacity_factor=4 / 6)
    vars_ = router.init(jax.random.PRNGKey(1), x)
    dispatch, _, _ = router.apply(vars_, x)
    # capacity = int(4/6 * 6 / 4) = 1 slot per expert
    assert float(dispatch.sum()) == pytest.approx(1.0)


def test_moe_mlp_forward_matches_manual_expert():
    """Full-capacity MoE output equals routing each token through its
    argmax expert's FFN scaled by the gate value."""
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(2, 4, 8).astype(np.float32))
    moe = MoEMlp(n_experts=2, d_ff=16, capacity_factor=2.0,
                 dtype=jnp.float32)
    vars_ = moe.init(jax.random.PRNGKey(3), x)
    out, aux = moe.apply(vars_, x)
    assert out.shape == x.shape and np.isfinite(np.asarray(out)).all()

    params = vars_["params"]
    logits = np.asarray(x, np.float32) @ np.asarray(
        params["router_block"]["router"]["kernel"], np.float32)
    gates = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    idx = np.argmax(np.asarray(gates), axis=-1)
    wi = np.asarray(params["wi"], np.float32)
    wo = np.asarray(params["wo"], np.float32)
    expect = np.zeros_like(np.asarray(x))
    for b in range(x.shape[0]):
        for s in range(x.shape[1]):
            e = idx[b, s]
            h = np.asarray(jax.nn.gelu(
                jnp.asarray(np.asarray(x)[b, s] @ wi[e])))
            expect[b, s] = (h @ wo[e]) * float(gates[b, s, e])
    np.testing.assert_allclose(np.asarray(out), expect, atol=1e-4)


def test_moe_compiles_on_dp_ep_mesh():
    """dp=2 × ep=4: tokens batch-sharded, experts ep-sharded; the jitted
    step must compile and run (XLA emits the dispatch all-to-alls)."""
    mesh = make_parallel_mesh(dp=2, ep=4)
    moe = MoEMlp(n_experts=4, d_ff=32, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(4).randn(4, 16, 8)
                    .astype(np.float32))
    vars_ = moe.init(jax.random.PRNGKey(5), x)
    pspecs = moe_param_partition_spec(vars_["params"])
    params = jax.tree.map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        vars_["params"], pspecs, is_leaf=lambda v: isinstance(v, P))
    x = jax.device_put(x, NamedSharding(mesh, P("dp")))

    @jax.jit
    def step(params, x):
        out, aux = moe.apply({"params": params}, x)
        return out.sum() + 0.01 * aux

    # grads too: EP backward = reverse all-to-alls
    val, grads = jax.value_and_grad(
        lambda p: step(p, x))(params)
    jax.block_until_ready(val)
    assert np.isfinite(float(val))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree.leaves(grads))
    # expert weights keep their ep sharding through the step
    assert "ep" in str(grads["wi"].sharding.spec)
