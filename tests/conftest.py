"""Test harness: force an 8-device virtual CPU platform so multi-chip
sharding/collectives are exercised without TPU hardware (the same trick the
driver's dryrun uses: ``--xla_force_host_platform_device_count``)."""

import os
import sys

# Must happen before the first jax backend initialization.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The axon sitecustomize pins jax_platforms to the TPU plugin; tests run on
# the virtual CPU mesh regardless.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Build artifacts are not committed; (re)build the C++ engine once per test
# session so the multi-process suites run.
_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "horovod_tpu", "csrc")


def _ensure_engine_built():
    import subprocess

    lib = os.path.join(_CSRC, "build", "libhvt_core.so")
    stamp = os.path.getmtime(lib) if os.path.exists(lib) else 0
    sources = [os.path.join(_CSRC, f) for f in os.listdir(_CSRC)
               if f.endswith((".cc", ".h")) or f == "Makefile"]
    if sources and stamp < max(os.path.getmtime(s) for s in sources):
        result = subprocess.run(["make", "-C", _CSRC, "-j"],
                                capture_output=True, text=True)
        if result.returncode != 0:
            raise RuntimeError(
                f"C++ engine build failed:\n{result.stdout}\n{result.stderr}")
    # TF custom-op library (optional; skipped inside make when TF absent).
    # Worth the one-time compile: it unlocks the in-graph TF parallel suite.
    tf_lib = os.path.join(_CSRC, "build", "libhvt_tf_ops.so")
    tf_src = os.path.join(_CSRC, "tf_ops.cc")
    if os.path.exists(tf_src) and (
            not os.path.exists(tf_lib)
            or os.path.getmtime(tf_lib) < os.path.getmtime(tf_src)):
        subprocess.run(["make", "-C", _CSRC, "tf_ops"],
                       capture_output=True, text=True)


_ensure_engine_built()


# ---------------------------------------------------------------- quick set
# Inner-loop marker (VERDICT r4 #8): the full suite is ~37 min on this
# 1-core host, dominated by the modules below (multi-subprocess gangs,
# TF imports per worker, pallas interpret mode, heavy 8-device
# compiles). Everything NOT in this list is auto-marked `quick`;
# `./ci.sh --fast` runs `-m quick` (~minutes). The full suite stays
# the round gate. Classification is by module because the cost is
# dominated by per-module fixtures (subprocess spawns, TF import,
# first-compile), not individual test bodies.
_SLOW_MODULES = {
    "test_engine_integration",   # real 2/4/5-process engine gangs
    "test_data_plane",           # 2/4-process ring/wire-codec gangs
    "test_flight_recorder",      # 2-process timeline/stall gangs
    "test_multiprocess_jit",     # jax.distributed subprocess pairs
    "test_engine_scaling",       # timed eager-plane benchmarks
    "test_adasum",               # multi-process numeric cross-checks
    "test_autotune",             # engine cycles to convergence
    "test_tensorflow",           # TF import + eager engine paths
    "test_tensorflow_native",    # TF custom-op gangs (20 s import/worker)
    "test_tensorflow_real",      # real keras fits
    "test_torch_parallel",       # multi-process torch gangs
    "test_examples",             # every example as a subprocess
    "test_ctrl_plane",           # 4/16-process tree/star control gangs
    "test_failure_containment",  # chaos gangs (SIGKILL/SIGSTOP + deadlines)
    "test_elastic_driver",       # launcher + failure/growth scenarios
    "test_elastic_recovery",     # kill-a-rank MiniEngine recovery gangs
    "test_runner",               # launcher subprocesses
    "test_preemption",           # signal/recovery scenarios
    "test_flash_attention",      # pallas interpret mode is slow on CPU
    "test_sequence_parallel",    # ring/ulysses 8-device compiles
    "test_serving",              # 4-proc serving gangs + loadgen replay
    "test_serving_soak",         # mixed-tenant MiniEngine soak smoke
    "test_models",               # GPT/ResNet init + flash paths
    "test_sanitizers",           # TSAN/ASAN rebuilds
    "test_self_healing",         # reconnect/replay chaos gangs
    "test_telemetry",            # fault-injected telemetry gangs
    "test_bench",                # full harness runs
    "test_integrations",         # real gang + HTTP-store suites
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "quick: fast inner-loop subset (auto-applied to "
                   "modules outside the known-slow list; run with "
                   "`pytest -m quick` or `./ci.sh --fast`)")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` "
                   "verify run (multi-minute captures; the full "
                   "./ci.sh suite still runs them)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__.rsplit(".", 1)[-1] not in _SLOW_MODULES:
            item.add_marker(pytest.mark.quick)


@pytest.fixture(scope="session", autouse=True)
def _hvt_init():
    import horovod_tpu as hvt

    hvt.init()
    yield


@pytest.fixture()
def world_mesh():
    from horovod_tpu.parallel import mesh

    return mesh.global_mesh()
