"""Test harness: force an 8-device virtual CPU platform so multi-chip
sharding/collectives are exercised without TPU hardware (the same trick the
driver's dryrun uses: ``--xla_force_host_platform_device_count``)."""

import os
import sys

# Must happen before the first jax backend initialization.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The axon sitecustomize pins jax_platforms to the TPU plugin; tests run on
# the virtual CPU mesh regardless.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Build artifacts are not committed; (re)build the C++ engine once per test
# session so the multi-process suites run.
_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "horovod_tpu", "csrc")


def _ensure_engine_built():
    import subprocess

    lib = os.path.join(_CSRC, "build", "libhvt_core.so")
    stamp = os.path.getmtime(lib) if os.path.exists(lib) else 0
    sources = [os.path.join(_CSRC, f) for f in os.listdir(_CSRC)
               if f.endswith((".cc", ".h")) or f == "Makefile"]
    if sources and stamp < max(os.path.getmtime(s) for s in sources):
        result = subprocess.run(["make", "-C", _CSRC, "-j"],
                                capture_output=True, text=True)
        if result.returncode != 0:
            raise RuntimeError(
                f"C++ engine build failed:\n{result.stdout}\n{result.stderr}")
    # TF custom-op library (optional; skipped inside make when TF absent).
    # Worth the one-time compile: it unlocks the in-graph TF parallel suite.
    tf_lib = os.path.join(_CSRC, "build", "libhvt_tf_ops.so")
    tf_src = os.path.join(_CSRC, "tf_ops.cc")
    if os.path.exists(tf_src) and (
            not os.path.exists(tf_lib)
            or os.path.getmtime(tf_lib) < os.path.getmtime(tf_src)):
        subprocess.run(["make", "-C", _CSRC, "tf_ops"],
                       capture_output=True, text=True)


_ensure_engine_built()


@pytest.fixture(scope="session", autouse=True)
def _hvt_init():
    import horovod_tpu as hvt

    hvt.init()
    yield


@pytest.fixture()
def world_mesh():
    from horovod_tpu.parallel import mesh

    return mesh.global_mesh()
