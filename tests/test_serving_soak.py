"""Serving-soak harness tests (ISSUE 15 tentpole c).

Quick units pin the lane plans, the op-count fault-arming formula, and
the artifact claim gates on synthetic records; the slow-marked smoke
runs the real 8-rank mixed-tenant soak (MiniEngine workers + chaos +
host kill + autoscaler re-shard) — the same run ``ci.sh --servesoak``
drives. The module is in conftest's slow list so tier-1 stays inside
its window."""

import copy
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks import serving_soak as ssk  # noqa: E402

LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build",
                   "libhvt_core.so")
needs_engine = pytest.mark.skipif(not os.path.exists(LIB),
                                  reason="engine .so not built")


def test_lane_partitions_share_exactly_one_rank():
    """The mixed-tenant grid: any row lane and any column lane
    intersect in exactly ONE rank — the shape the per-lane execution
    pool can isolate (two or more shared ranks would share a socket
    pair and must serialize)."""
    rows = ssk.row_partition(64, 8)
    cols = ssk.col_partition(64, 8)
    assert sorted(r for g in rows for r in g) == list(range(64))
    assert sorted(r for g in cols for r in g) == list(range(64))
    for row in rows:
        for col in cols:
            assert len(set(row) & set(col)) == 1, (row, col)


def test_fault_arming_lands_inside_its_phase():
    spec = ssk._spec(smoke=True)
    ssk._fill_fault_ops(spec)
    f = spec["faults"]
    assert f["flaky_after_ops"] > ssk._ops_before(spec, "fire")
    fire_end = ssk._ops_before(spec, "fire") + \
        2 * (spec["phases"]["fire"] // spec["batch"]) + 1
    assert f["flaky_after_ops"] < fire_end
    assert f["partition"]["after_ops"] > ssk._ops_before(spec, "storm")


def _synthetic_record():
    lanes = {
        "row:0": {"tenant": "row", "members": [0, 1],
                  "member_identical": True, "admitted": 48, "shed": 4,
                  "batches": 12, "p99_ms_max": 2.0},
        "col:0": {"tenant": "col", "members": [0, 2, 4, 6],
                  "member_identical": True, "admitted": 48, "shed": 0,
                  "batches": 12, "p99_ms_max": 2.0},
    }

    def phase(col_p99):
        p = copy.deepcopy(lanes)
        p["col:0"]["p99_ms_max"] = col_p99
        return {"lanes": p,
                "engine": {"aborts": 0, "pool_tasks": 10,
                           "reconnects": 2, "lane_workers": 4},
                "ranks": 8}

    soak = {
        "arm": "soak", "np": 8, "hosts": 4, "lane_workers": 4,
        "phases": {"warm": phase(2.0), "baseline": phase(2.0),
                   "fire": phase(2.2), "storm": phase(2.4)},
        "alerts_by_phase": {
            "fire": {"reconnect_storm": ["links"]},
            "recovered": {"push_stale": ["rank 6", "rank 7"]}},
        "killed_host": "h3", "world_after": 6,
        "autoscaler_decisions": ["shed"],
        "time_to_recovered_sec": 5.0,
    }
    iso_pool = copy.deepcopy(soak)
    iso_pool["arm"] = "iso_pool"
    iso_nopool = copy.deepcopy(soak)
    iso_nopool["arm"] = "iso_nopool"
    iso_nopool["phases"]["fire"] = phase(6.0)
    return {
        "schema": ssk.SCHEMA, "mode": "smoke",
        "config": {"per_host": 2, "np": 8,
                   "faults": {"flaky_rank": 3}},
        "arms": {"soak": soak, "iso_pool": iso_pool,
                 "iso_nopool": iso_nopool},
        "claims": {
            # the gated isolation pair: idle-lane exec-start overlap
            # with the hot lane's open exec span, pool vs nopool (the
            # nopool arm must be exactly 0 — single-thread engines
            # cannot hold two spans open)
            "idle_col_overlap_frac_pool": 0.8,
            "idle_col_overlap_frac_nopool": 0.0,
            "idle_col_hol_us_fire_pool": 40.0,
            "idle_col_hol_us_fire_nopool": 900.0,
            "nopool_hol_over_pool_hol": 22.5,
            "hot_row_exec_us_fire_pool": 1000.0,
            "hot_row_exec_us_fire_nopool": 1000.0,
            # report-only wall-clock ratios
            "idle_col_exec_fire_over_baseline_pool": 1.1,
            "idle_col_exec_fire_over_baseline_nopool": 3.0,
            "idle_col_p50_fire_over_baseline_pool": 1.1,
            "idle_col_p50_fire_over_baseline_nopool": 1.9,
            "nopool_over_pool": 1.7,
            "idle_col_p99_fire_over_baseline_pool": 1.4,
            "idle_col_p99_fire_over_baseline_nopool": 3.2,
            "soak_col_exec_fire_over_baseline": 1.3,
            "zero_aborts_transient": True,
            "pool_engaged_tasks": 10,
            "iso_pool_engaged_tasks": 10,
            "member_identical_decisions": True,
            "batching_coalesced": True,
            "baseline_alert_rules": [],
            "observed_alert_rules": ["push_stale", "reconnect_storm"],
            "push_stale_subjects_killed_only": True,
            "reconnect_storm_seen": True,
            "push_stale_seen": True,
            "autoscaler_shed": True,
            "reshard_world": 6, "reshard_expected": 6,
            "time_to_recovered_sec": 5.0,
        },
    }


def test_check_passes_clean_record_and_fails_each_gate(capsys):
    rec = _synthetic_record()
    assert ssk.check_record(rec) == 0
    for mutate, _why in (
            (lambda r: r["claims"].__setitem__(
                "idle_col_overlap_frac_pool", 0.05), "pool isolation"),
            (lambda r: r["claims"].__setitem__(
                "idle_col_overlap_frac_nopool", 0.2),
             "nopool structural zero"),
            (lambda r: r["claims"].__setitem__(
                "nopool_hol_over_pool_hol", 1.2), "hol A/B bound"),
            (lambda r: r["claims"].__setitem__(
                "zero_aborts_transient", False), "aborts"),
            (lambda r: r["claims"].__setitem__(
                "member_identical_decisions", False), "identity"),
            (lambda r: r["claims"].__setitem__(
                "baseline_alert_rules", ["straggler"]), "clean gang"),
            (lambda r: r["claims"].__setitem__(
                "observed_alert_rules", ["weird_rule"]), "rule set"),
            (lambda r: r["claims"].__setitem__(
                "push_stale_subjects_killed_only", False), "subjects"),
            (lambda r: r["claims"].__setitem__("reshard_world", 99),
             "reshard"),
            (lambda r: r["claims"].__setitem__("autoscaler_shed",
                                               False), "autoscaler"),
            (lambda r: r["arms"].pop("iso_nopool"), "arms"),
    ):
        bad = _synthetic_record()
        mutate(bad)
        assert ssk.check_record(bad) == 1, _why
    # capture mode tightens the pool overlap floor 0.15 → 0.3 and the
    # hol A/B bound 2x → 4x; the nopool structural zero stays exact in
    # both modes
    cap = _synthetic_record()
    cap["mode"] = "capture"
    assert ssk.check_record(cap) == 0
    cap["claims"]["idle_col_overlap_frac_pool"] = 0.2
    assert ssk.check_record(cap) == 1
    cap = _synthetic_record()
    cap["mode"] = "capture"
    cap["claims"]["nopool_hol_over_pool_hol"] = 3.0
    assert ssk.check_record(cap) == 1
    capsys.readouterr()


def test_committed_artifact_passes_check():
    path = os.path.join(REPO, "benchmarks", "r15_serving_soak.json")
    if not os.path.exists(path):
        pytest.skip("committed r15 artifact not present")
    assert ssk.check(path) == 0


@pytest.mark.slow
@needs_engine
def test_serving_soak_smoke_end_to_end(tmp_path):
    """The full 8-rank mixed-tenant soak (both arms): chaos, host
    kill, re-shard, claims — the exact run ``ci.sh --servesoak``
    gates on."""
    out = tmp_path / "soak.json"
    rec, rc = ssk.capture(str(out), smoke=True)
    assert rc == 0, json.dumps(rec.get("claims"), indent=1)
    assert ssk.check(str(out)) == 0
