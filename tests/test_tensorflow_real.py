"""TF-binding tests against REAL TensorFlow/Keras objects (tf 2.21 /
Keras 3 are present in this image; these complement the numpy-fake suite
in test_tensorflow.py and exercise actual tf.Tensor / tf.GradientTape /
keras optimizer round trips — the reference's test_tensorflow.py
territory)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as hvt_tf  # noqa: E402


def test_allreduce_real_tensor_roundtrip():
    x = tf.constant([[1.0, 2.0], [3.0, 4.0]])
    out = hvt_tf.allreduce(x, name="real.t", average=True)
    assert isinstance(out, tf.Tensor)
    np.testing.assert_allclose(out.numpy(), x.numpy())  # 1 process: avg=id
    s = hvt_tf.allreduce(x, name="real.s", average=False,
                         prescale_factor=2.0)
    np.testing.assert_allclose(s.numpy(), 2 * x.numpy())


def test_allgather_broadcast_real_tensors():
    g = hvt_tf.allgather(tf.constant([[1.0, 2.0]]), name="real.g")
    np.testing.assert_allclose(g.numpy(), [[1.0, 2.0]])
    b = hvt_tf.broadcast(tf.constant([5, 6]), root_rank=0, name="real.b")
    assert b.numpy().tolist() == [5, 6]


def test_distributed_gradient_tape_real():
    w = tf.Variable([1.0, 2.0, 3.0])
    with hvt_tf.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(w * w)
    (grad,) = tape.gradient(loss, [w])
    np.testing.assert_allclose(np.asarray(grad), 2 * w.numpy())


def test_distributed_gradient_tape_sparse_real():
    emb = tf.Variable(tf.ones((4, 3)))
    with hvt_tf.DistributedGradientTape(tf.GradientTape()) as tape:
        rows = tf.gather(emb, [1, 3])
        loss = tf.reduce_sum(rows) * 2.0
    (grad,) = tape.gradient(loss, [emb])
    assert isinstance(grad, tf.IndexedSlices)
    np.testing.assert_array_equal(np.sort(np.asarray(grad.indices)), [1, 3])
    np.testing.assert_allclose(np.asarray(grad.values), 2.0)


def test_distributed_optimizer_real_keras_training():
    """Custom loop with a real keras optimizer wrapped by the TF
    DistributedOptimizer converges (single process: reduction is
    identity, the wrapper plumbing is what is under test)."""
    rs = np.random.RandomState(0)
    W_true = rs.randn(4, 1).astype(np.float32)
    X = rs.randn(256, 4).astype(np.float32)
    y = X @ W_true

    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(1, use_bias=False, input_shape=(4,))])
    opt = hvt_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
    losses = []
    for _ in range(100):
        with tf.GradientTape() as tape:
            pred = model(X, training=True)
            loss = tf.reduce_mean((pred - y) ** 2)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 1e-3, (losses[0], losses[-1])


def test_adasum_delta_optimizer_single_process_is_local_step():
    """op=Adasum returns the delta optimizer (reference
    tensorflow/__init__.py:471-567); with one process the combine is a
    no-op and the result must be EXACTLY the wrapped optimizer's local
    update (momentum statistics intact)."""
    from horovod_tpu.tensorflow import _DistributedAdasumOptimizer

    v = tf.Variable([1.0, 2.0, 3.0])
    v_ref = tf.Variable([1.0, 2.0, 3.0])
    opt = hvt_tf.DistributedOptimizer(
        tf.keras.optimizers.SGD(0.5, momentum=0.9), op=hvt_tf.Adasum)
    assert isinstance(opt, _DistributedAdasumOptimizer)
    ref = tf.keras.optimizers.SGD(0.5, momentum=0.9)
    for _ in range(3):
        g = tf.constant([0.1, -0.2, 0.3])
        opt.apply_gradients([(g, v)])
        ref.apply_gradients([(g, v_ref)])
    np.testing.assert_allclose(v.numpy(), v_ref.numpy(), rtol=1e-6)


def test_adasum_delta_optimizer_aggregation_and_guards():
    v = tf.Variable([0.0, 0.0])
    opt = hvt_tf.DistributedOptimizer(tf.keras.optimizers.SGD(1.0),
                                      op=hvt_tf.Adasum,
                                      backward_passes_per_step=2)
    g = tf.constant([1.0, 2.0])
    assert opt.apply_gradients([(g, v)]) is None       # aggregate only
    np.testing.assert_allclose(v.numpy(), 0.0)          # no update yet
    opt.apply_gradients([(g, v)])
    np.testing.assert_allclose(v.numpy(), [-2.0, -4.0])  # summed grads

    with pytest.raises(ValueError, match="process_set"):
        from horovod_tpu.ops.collective_ops import global_process_set
        ps = type(global_process_set)([0])
        hvt_tf.DistributedOptimizer(tf.keras.optimizers.SGD(1.0),
                                    op=hvt_tf.Adasum, process_set=ps)
    with pytest.raises(ValueError, match="prescale"):
        hvt_tf.DistributedOptimizer(tf.keras.optimizers.SGD(1.0),
                                    op=hvt_tf.Adasum, prescale_factor=2.0)


def test_distributed_optimizer_aggregation_with_real_optimizer():
    v = tf.Variable([0.0, 0.0])
    opt = hvt_tf.DistributedOptimizer(tf.keras.optimizers.SGD(1.0),
                                      backward_passes_per_step=2)
    g = tf.constant([1.0, 2.0])
    assert opt.apply_gradients([(g, v)]) is None       # aggregate only
    np.testing.assert_allclose(v.numpy(), 0.0)          # no update yet
    opt.apply_gradients([(g, v)])
    np.testing.assert_allclose(v.numpy(), [-2.0, -4.0])  # sum of 2 passes


def test_broadcast_variables_real_model():
    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(2, input_shape=(3,))])
    before = [w.numpy().copy() for w in model.weights]
    hvt_tf.broadcast_variables(model.weights, root_rank=0)
    for b, w in zip(before, model.weights):
        np.testing.assert_allclose(w.numpy(), b)  # 1 process: unchanged


def test_keras_lr_warmup_callback_real_fit():
    """keras.LearningRateWarmupCallback drives the real optimizer's lr
    through model.fit."""
    import horovod_tpu.keras as hvt_keras

    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(1, input_shape=(2,))])
    model.compile(optimizer=tf.keras.optimizers.SGD(0.4), loss="mse")
    cb = hvt_keras.LearningRateWarmupCallback(initial_lr=0.4,
                                              warmup_epochs=4)
    X = np.random.RandomState(0).randn(32, 2).astype(np.float32)
    y = np.zeros((32, 1), np.float32)
    seen = []

    class Probe(tf.keras.callbacks.Callback):
        def on_epoch_begin(self, epoch, logs=None):
            seen.append(float(model.optimizer.learning_rate))

    model.fit(X, y, epochs=4, batch_size=16, verbose=0,
              callbacks=[cb, Probe()])
    # warmup ramps from lr/size toward lr over warmup_epochs (size is
    # the session world size — 8 virtual chips in the test harness)
    import horovod_tpu as hvt

    n = hvt.size()
    expect = [0.4 / n * (e * (n - 1) / 4 + 1) for e in range(4)]
    assert len(seen) == 4
    np.testing.assert_allclose(seen, expect, rtol=1e-6)


def test_keras_broadcast_global_variables_real_model():
    import horovod_tpu.keras as hvt_keras

    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(2, input_shape=(3,))])
    before = [w.numpy().copy() for w in model.weights]
    # Keras 3: pass the model (the legacy global registry is gone)
    hvt_keras.broadcast_global_variables(0, model=model)
    for b, w in zip(before, model.weights):
        np.testing.assert_allclose(w.numpy(), b)
    if not hasattr(tf.keras.backend, "_get_variables"):
        # no model/variables and no legacy registry → actionable error
        with pytest.raises(ValueError, match="model"):
            hvt_keras.broadcast_global_variables(0)


def test_keras_lr_warmup_with_steps_per_epoch_ramps():
    """Regression: with steps_per_epoch (non-staircase path) the adapter
    must evaluate the schedule at each epoch's first step, not step 0 —
    the LR has to RAMP, not freeze at initial_lr/size."""
    import horovod_tpu.keras as hvt_keras

    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(1, input_shape=(2,))])
    model.compile(optimizer=tf.keras.optimizers.SGD(0.4), loss="mse")
    cb = hvt_keras.LearningRateWarmupCallback(
        initial_lr=0.4, warmup_epochs=4, steps_per_epoch=2)
    X = np.random.RandomState(0).randn(32, 2).astype(np.float32)
    y = np.zeros((32, 1), np.float32)
    seen = []

    class Probe(tf.keras.callbacks.Callback):
        def on_epoch_begin(self, epoch, logs=None):
            seen.append(float(model.optimizer.learning_rate))

    model.fit(X, y, epochs=4, batch_size=16, verbose=0,
              callbacks=[cb, Probe()])
    assert len(seen) == 4
    assert seen[-1] > seen[0], seen  # ramping, not frozen
    import horovod_tpu as hvt

    n = hvt.size()
    expect = [0.4 / n * (e * (n - 1) / 4 + 1) for e in range(4)]
    np.testing.assert_allclose(seen, expect, rtol=1e-6)


def test_sync_batch_norm_single_process_matches_plain_bn():
    """Single process: SyncBatchNormalization == plain batch norm over
    the local batch (training mode, then moving stats in inference)."""
    rs = np.random.RandomState(3)
    x = tf.constant(rs.randn(16, 5).astype(np.float32) * 2 + 1)
    bn = hvt_tf.SyncBatchNormalization(momentum=0.0, epsilon=1e-5)
    out = bn(x, training=True)
    mean = x.numpy().mean(0)
    var = x.numpy().var(0)
    expect = (x.numpy() - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-4)
    # momentum 0 → moving stats == batch stats; inference reproduces
    out2 = bn(x, training=False)
    np.testing.assert_allclose(out2.numpy(), expect, rtol=1e-3, atol=1e-3)


def test_sync_batch_norm_inside_fit():
    """The py_function stats exchange must survive model.fit's compiled
    train step."""
    model = tf.keras.Sequential([
        tf.keras.Input((4,)),
        hvt_tf.SyncBatchNormalization(),
        tf.keras.layers.Dense(1),
    ])
    model.compile(optimizer="sgd", loss="mse")
    X = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    y = np.zeros((32, 1), np.float32)
    hist = model.fit(X, y, epochs=2, batch_size=16, verbose=0)
    assert np.isfinite(hist.history["loss"]).all()


def test_sync_batch_norm_gradient_matches_plain_bn():
    """Regression: gradient must flow through the synced statistics —
    single-process sync BN gradients must equal plain batch-norm
    gradients (the py_function exchange is gradient-transparent via the
    local-share surrogate)."""
    rs = np.random.RandomState(11)
    xv = rs.randn(12, 4).astype(np.float32)
    wv = rs.randn(12, 4).astype(np.float32)  # fixed loss projection

    def grads(layer):
        x = tf.constant(xv)
        with tf.GradientTape() as tape:
            tape.watch(x)
            y = layer(x, training=True)
            loss = tf.reduce_sum(y * tf.constant(wv))
        return tape.gradient(loss, x).numpy()

    g_sync = grads(hvt_tf.SyncBatchNormalization(epsilon=1e-5))
    g_ref = grads(tf.keras.layers.BatchNormalization(
        momentum=0.99, epsilon=1e-5))
    np.testing.assert_allclose(g_sync, g_ref, rtol=1e-3, atol=1e-5)


def test_sync_batch_norm_serialization_roundtrip():
    layer = hvt_tf.SyncBatchNormalization(momentum=0.9, epsilon=1e-4,
                                          axis=-1)
    cfg = layer.get_config()
    rebuilt = type(layer).from_config(cfg)
    assert rebuilt.momentum == 0.9 and rebuilt.epsilon == 1e-4
    # full-kwarg reference calls are accepted (GPU knobs ignored)
    hvt_tf.SyncBatchNormalization(beta_initializer="zeros", fused=False)


def test_tensorflow_keras_state_commit_restore_sync(tmp_path):
    import horovod_tpu.tensorflow.elastic as tfe

    model = tf.keras.Sequential([tf.keras.layers.Dense(3)])
    model(tf.zeros([1, 4]))
    opt = tf.keras.optimizers.SGD(0.1)
    opt.build(model.trainable_variables)
    state = tfe.TensorFlowKerasState(model, opt, epoch=0, batch=0)

    committed = [np.array(w, copy=True) for w in model.get_weights()]
    state.commit()
    # mutate, then restore → back to the commit
    model.set_weights([w + 1.0 for w in model.get_weights()])
    state.epoch = 7
    state.restore()
    for a, b in zip(model.get_weights(), committed):
        np.testing.assert_allclose(a, b)
    assert state.epoch == 0
    # sync (1 process): broadcast keeps values, save() refreshes commit
    state.sync()
    for a, b in zip(model.get_weights(), committed):
        np.testing.assert_allclose(a, b)


def test_tensorflow_state_variables_restore():
    import horovod_tpu.tensorflow.elastic as tfe

    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable(5.0)
    state = tfe.TensorFlowState([v1, v2], step=3)
    state.commit()
    v1.assign([9.0, 9.0])
    v2.assign(-1.0)
    state.step = 99
    state.restore()
    np.testing.assert_allclose(v1.numpy(), [1.0, 2.0])
    assert float(v2.numpy()) == 5.0 and state.step == 3


def test_keras_load_model_rewraps_optimizer(tmp_path):
    import horovod_tpu.keras as hvt_keras

    model = tf.keras.Sequential([tf.keras.layers.Dense(2)])
    model.compile(optimizer=tf.keras.optimizers.Adam(1e-3), loss="mse")
    model.fit(np.random.randn(8, 4).astype(np.float32),
              np.random.randn(8, 2).astype(np.float32),
              epochs=1, verbose=0)
    path = str(tmp_path / "m.keras")
    model.save(path)

    loaded = hvt_keras.load_model(path)
    # optimizer came back distributed: a dynamic Keras-native subclass
    # of Adam (compile()-compatible, unlike the bare TF wrapper) whose
    # apply_gradients routes through the collective exchange
    assert getattr(loaded.optimizer, "_hvt_distributed", False)
    assert isinstance(loaded.optimizer, tf.keras.optimizers.Adam)
    pred = loaded.predict(np.zeros((1, 4), np.float32), verbose=0)
    assert pred.shape == (1, 2)
    # retraining through the wrapped optimizer still works under fit
    loaded.fit(np.random.randn(8, 4).astype(np.float32),
               np.random.randn(8, 2).astype(np.float32),
               epochs=1, verbose=0)


def test_graph_mode_backward_passes_per_step_single_process():
    import horovod_tpu.tensorflow as hvt_tf2

    v = tf.Variable([10.0, 20.0])
    opt = hvt_tf2.DistributedOptimizer(
        tf.keras.optimizers.SGD(1.0), backward_passes_per_step=2,
        average_aggregated_gradients=True)

    @tf.function
    def step(g):
        return opt.apply_gradients([(g, v)])

    applied1 = step(tf.constant([1.0, 2.0]))
    assert not bool(applied1)            # accumulation only
    np.testing.assert_allclose(v.numpy(), [10.0, 20.0])
    applied2 = step(tf.constant([3.0, 4.0]))
    assert bool(applied2)                # flush: avg of the two grads
    np.testing.assert_allclose(v.numpy(), [10.0 - 2.0, 20.0 - 3.0])
    # next cycle starts clean
    assert not bool(step(tf.constant([0.0, 0.0])))
    np.testing.assert_allclose(v.numpy(), [8.0, 17.0])


def test_tensorflow_keras_state_unbuilt_optimizer_errors():
    import horovod_tpu.tensorflow.elastic as tfe

    model = tf.keras.Sequential([tf.keras.layers.Dense(2)])
    model(tf.zeros([1, 3]))
    opt = tf.keras.optimizers.SGD(0.1)
    opt.build(model.trainable_variables)
    state = tfe.TensorFlowKerasState(model, opt)
    state.commit()
    # a later restore against an optimizer whose variable count changed
    # must fail loudly, not silently drop slot state
    state._saved_opt = state._saved_opt[:-1]
    with pytest.raises(RuntimeError, match="variables"):
        state.restore()


def test_keras_load_model_custom_optimizer_class(tmp_path):
    import keras

    import horovod_tpu.keras as hvt_keras

    @keras.saving.register_keras_serializable(package="hvt_test")
    class MySGD(tf.keras.optimizers.SGD):
        pass

    model = tf.keras.Sequential([tf.keras.layers.Dense(2)])
    model.compile(optimizer=MySGD(0.01), loss="mse")
    model.fit(np.random.randn(4, 3).astype(np.float32),
              np.random.randn(4, 2).astype(np.float32),
              epochs=1, verbose=0)
    path = str(tmp_path / "c.keras")
    model.save(path)

    loaded = hvt_keras.load_model(path, custom_optimizers=[MySGD])
    assert getattr(loaded.optimizer, "_hvt_distributed", False)
    assert isinstance(loaded.optimizer, MySGD)


def test_keras_commit_state_callback_with_tf_keras_state():
    """CommitStateCallback commits TensorFlowKerasState every N batches
    during a real model.fit (reference _keras/elastic.py wiring)."""
    import horovod_tpu.keras as hvt_keras
    import horovod_tpu.tensorflow.elastic as tfe

    model = tf.keras.Sequential([tf.keras.layers.Dense(1)])
    model.compile(optimizer=tf.keras.optimizers.SGD(0.05), loss="mse")
    model(tf.zeros([1, 3]))
    state = tfe.TensorFlowKerasState(model, model.optimizer, batch=0,
                                     epoch=0)
    commits = []
    orig_commit = state.commit
    state.commit = lambda: (commits.append(1), orig_commit())[1]

    X = np.random.RandomState(0).randn(32, 3).astype(np.float32)
    y = np.zeros((32, 1), np.float32)
    model.fit(X, y, epochs=1, batch_size=8, verbose=0,
              callbacks=[hvt_keras.CommitStateCallback(
                  state, batches_per_commit=2),
                  hvt_keras.UpdateBatchStateCallback(state)])
    assert len(commits) == 2          # 4 batches / commit every 2
    assert state.batch == 0 and state.epoch == 1  # epoch rolled over
    # the last commit snapshot restores cleanly
    state.restore()


def test_graph_mode_aggregation_rejects_changed_variable_list():
    """The in-graph aggregation helper closes over per-variable collective
    names from the call that built it; a later call with a same-length but
    DIFFERENT variable list must raise, not silently reuse stale names."""
    import horovod_tpu.tensorflow as hvt_tf2

    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable([3.0, 4.0])
    opt = hvt_tf2.DistributedOptimizer(
        tf.keras.optimizers.SGD(1.0), backward_passes_per_step=2)

    @tf.function
    def step_a(g):
        return opt.apply_gradients([(g, v1)])

    @tf.function
    def step_b(g):
        return opt.apply_gradients([(g, v2)])

    step_a(tf.constant([1.0, 1.0]))
    with pytest.raises(Exception, match="different variable list"):
        step_b(tf.constant([1.0, 1.0]))


def test_keras_best_model_checkpoint(tmp_path):
    """BestModelCheckpoint parity (reference keras/callbacks.py:151):
    saves only when the monitored metric improves."""
    import horovod_tpu.keras as hvt_keras

    path = str(tmp_path / "best.keras")
    model = tf.keras.Sequential([tf.keras.layers.Dense(1)])
    model.compile(optimizer="sgd", loss="mse")
    cb = hvt_keras.BestModelCheckpoint(monitor="loss", filepath=path)
    X = np.random.RandomState(0).randn(64, 3).astype(np.float32)
    y = (X @ np.asarray([1.0, -1.0, 0.5], np.float32))
    model.fit(X, y, epochs=3, verbose=0, callbacks=[cb])
    assert tf.io.gfile.exists(path)
    with pytest.raises(ValueError, match="filepath"):
        hvt_keras.BestModelCheckpoint(monitor="loss")


def test_keras_distributed_optimizer_preserves_built_slot_state():
    """Wrapping a BUILT optimizer must keep the instance (and its slot
    variables — Adam m/v, iterations) instead of rebuilding via
    from_config, which silently reset momentum on load_model restores."""
    import horovod_tpu.keras as hvt_keras

    opt = tf.keras.optimizers.Adam(0.01)
    model = tf.keras.Sequential([tf.keras.layers.Dense(2)])
    model.compile(optimizer=opt, loss="mse")
    X = np.random.RandomState(0).randn(8, 3).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
    model.fit(X, y, epochs=1, verbose=0)  # builds + populates slots
    before = [v.numpy().copy() for v in opt.variables]
    assert int(opt.iterations.numpy()) > 0

    wrapped = hvt_keras.DistributedOptimizer(opt)
    assert wrapped is opt  # the instance survives (class swap, not copy)
    assert getattr(wrapped, "_hvt_distributed", False)
    after = [v.numpy() for v in wrapped.variables]
    assert len(before) == len(after)
    for a, b in zip(before, after):
        np.testing.assert_allclose(a, b)
    # double-wrapping must be a no-op, not a second exchange layer
    assert hvt_keras.DistributedOptimizer(wrapped) is wrapped


def test_keras_wrapped_optimizer_save_load_roundtrip(tmp_path):
    """A model COMPILED with the wrapper round-trips through
    model.save()/load_model: the dynamic subclass serializes under the
    base optimizer's module/name, and slot state survives the reload."""
    import horovod_tpu.keras as hvt_keras

    model = tf.keras.Sequential([tf.keras.layers.Dense(2)])
    model.compile(
        optimizer=hvt_keras.DistributedOptimizer(
            tf.keras.optimizers.Adam(0.01)),
        loss="mse")
    X = np.random.RandomState(0).randn(8, 3).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
    model.fit(X, y, epochs=1, verbose=0)
    pre = [v.numpy().copy() for v in model.optimizer.variables]

    path = str(tmp_path / "wrapped.keras")
    model.save(path)  # failed pre-fix: unresolvable dynamic class
    loaded = hvt_keras.load_model(path)
    assert getattr(loaded.optimizer, "_hvt_distributed", False)
    assert isinstance(loaded.optimizer, tf.keras.optimizers.Adam)
    post = [v.numpy() for v in loaded.optimizer.variables]
    assert len(pre) == len(post)
    for a, b in zip(pre, post):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    loaded.fit(X, y, epochs=1, verbose=0)  # retraining still works
