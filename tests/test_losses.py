"""Chunked LM cross-entropy vs the naive materialized computation
(ops/losses.py — value and gradients must match exactly; the chunking is
a pure memory optimization)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


def _naive(hidden, emb, targets):
    logits = jnp.einsum("bsd,vd->bsv", hidden.astype(jnp.float32),
                        emb.astype(jnp.float32))
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, targets).mean()


@pytest.mark.parametrize("chunk", [4, 16, 64])
def test_fused_ce_matches_naive_value_and_grads(chunk):
    from horovod_tpu.ops.losses import softmax_cross_entropy_fused

    rng = np.random.RandomState(0)
    b, s, d, v = 2, 16, 8, 37
    hidden = jnp.asarray(rng.randn(b, s, d), jnp.float32)
    emb = jnp.asarray(rng.randn(v, d) * 0.1, jnp.float32)
    targets = jnp.asarray(rng.randint(0, v, (b, s)))

    l0, (gh0, ge0) = jax.value_and_grad(_naive, argnums=(0, 1))(
        hidden, emb, targets)
    l1, (gh1, ge1) = jax.value_and_grad(
        lambda h, e: softmax_cross_entropy_fused(h, e, targets,
                                                 chunk=chunk),
        argnums=(0, 1))(hidden, emb)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh0),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(ge1), np.asarray(ge0),
                               rtol=1e-5, atol=1e-7)


def test_fused_ce_bf16_hidden():
    from horovod_tpu.ops.losses import softmax_cross_entropy_fused

    rng = np.random.RandomState(1)
    hidden = jnp.asarray(rng.randn(2, 8, 16), jnp.bfloat16)
    emb = jnp.asarray(rng.randn(33, 16) * 0.1, jnp.float32)
    targets = jnp.asarray(rng.randint(0, 33, (2, 8)))
    l_f = softmax_cross_entropy_fused(hidden, emb, targets, chunk=4)
    l_n = _naive(hidden, emb, targets)
    np.testing.assert_allclose(np.asarray(l_f), np.asarray(l_n),
                               rtol=1e-6)


@pytest.mark.parametrize("s", [10, 31, 127])
def test_fused_ce_non_divisible_seq_pads_and_masks(s):
    """Odd sequence lengths (the bench call site slices to seq-1 = odd!)
    must keep the REQUESTED chunk via pad+mask — value and grads still
    exact, never a degenerate chunk=1 scan."""
    from horovod_tpu.ops.losses import softmax_cross_entropy_fused

    rng = np.random.RandomState(2)
    hidden = jnp.asarray(rng.randn(2, s, 8), jnp.float32)
    emb = jnp.asarray(rng.randn(21, 8) * 0.1, jnp.float32)
    targets = jnp.asarray(rng.randint(0, 21, (2, s)))
    l0, g0 = jax.value_and_grad(_naive)(hidden, emb, targets)
    l1, g1 = jax.value_and_grad(
        lambda h: softmax_cross_entropy_fused(h, emb, targets,
                                              chunk=8))(hidden)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=1e-5, atol=1e-7)


def test_fused_ce_rejects_bad_chunk():
    from horovod_tpu.ops.losses import softmax_cross_entropy_fused

    with pytest.raises(ValueError, match="chunk"):
        softmax_cross_entropy_fused(jnp.zeros((1, 4, 2)),
                                    jnp.zeros((5, 2)),
                                    jnp.zeros((1, 4), jnp.int32), chunk=0)


def test_gpt_chunked_ce_trains_identically():
    """GPT(return_hidden) + fused CE must produce the same loss and
    gradients as the logits path (a pure memory optimization)."""
    from horovod_tpu.models import GPT, GPTConfig
    from horovod_tpu.ops.losses import softmax_cross_entropy_fused

    cfg = GPTConfig(vocab_size=64, n_layers=1, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    model = GPT(cfg)
    tokens = jnp.asarray(np.random.RandomState(3).randint(0, 64, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    targets = jnp.roll(tokens, -1, axis=-1)

    def loss_logits(p):
        logits = model.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], targets[:, :-1]).mean()

    def loss_fused(p):
        hidden = model.apply({"params": p}, tokens, return_hidden=True)
        return softmax_cross_entropy_fused(
            hidden[:, :-1], p["embedding"], targets[:, :-1], chunk=5)

    l0, g0 = jax.value_and_grad(loss_logits)(params)
    l1, g1 = jax.value_and_grad(loss_fused)(params)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
