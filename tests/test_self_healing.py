"""Self-healing links (transport.h): transparent reconnect with
sequence-numbered replay, the abort/recovery boundary, and the
transient-fault chaos specs.

Gang tests reuse the raw-Popen harness of test_failure_containment
(independent exit codes, hard timeouts). The invariant under every
TRANSIENT fault: the run completes **bit-identically** to an
injection-off run with ≥1 recorded reconnect and ZERO aborts; the
invariant at the boundary: exhausted budgets escalate into the PR 4
coordinated abort with a reason naming the peer and the budget.
"""

import os
import signal

import pytest

from test_failure_containment import LIB, finish_gang, spawn_gang

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


def _uring_ok():
    try:
        from horovod_tpu.engine import native
        return native.uring_supported()
    except Exception:
        return False


# The session-layer contracts (replay after drop, epoch handshake,
# abort/recovery boundary, shutdown-during-reconnect) must hold
# verbatim under every link backend — IoUringLink swaps only the byte
# movement under PumpDuplex, so the specs below run per backend, with
# io_uring skipped cleanly where the kernel probe fails.
BACKENDS = ["tcp", pytest.param("io_uring", marks=pytest.mark.skipif(
    not _uring_ok(), reason="io_uring kernel probe failed"))]


# ------------------------------------------------------- transient heals

@pytest.mark.parametrize("backend", BACKENDS)
def test_flaky_conn_heals_bit_identical(tmp_path, backend):
    """The acceptance gang: flaky_conn cuts rank 1's links mid-allreduce
    (tx- and rx-side, twice). Every rank must finish all ops with
    bit-exact results, ≥1 RECONNECT event recorded on the cut ranks,
    and zero ABORT events / abort counters anywhere."""
    body = """
    x = np.arange(262144, dtype=np.float32) + r
    exp = sum(np.arange(262144, dtype=np.float32) + i for i in range(n))
    for i in range(10):
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"fl.{i}"))
        np.testing.assert_array_equal(res, exp)
    st = native.engine_stats()
    broken, info = native.engine_broken()
    assert not broken, info
    assert sum(st["aborts"].values()) == 0, st["aborts"]
    kinds = [e["kind_name"] for e in native.drain_events(8192)]
    assert "ABORT" not in kinds
    rec = sum(st["link_reconnects"].values())
    print(f"RECONNECTS {rec} REPLAY {st['replay_bytes']}", flush=True)
    if r == 1:
        assert rec >= 1, st["link_reconnects"]
        assert "RECONNECT" in kinds, sorted(set(kinds))
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=4, tmp_path=tmp_path,
        extra_env={"HVT_FAULT_INJECT": "flaky_conn:rank=1:count=2:after_ops=3",
                   "HVT_LINK_BACKEND": backend,
                   "HVT_OP_TIMEOUT_MS": "30000"})
    codes, outs = finish_gang(procs, logs, timeout=150)
    for rank in range(4):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank], f"rank {rank}\n{outs[rank]}"


def test_reset_storm_survives(tmp_path):
    """reset_storm resets one data link every 3 data ops on every rank —
    sustained connection churn must still produce bit-exact results
    with zero aborts."""
    body = """
    x = np.arange(16384, dtype=np.float32) * (r + 1)
    exp = sum(np.arange(16384, dtype=np.float32) * (i + 1)
              for i in range(n))
    for i in range(12):
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"rs.{i}"))
        np.testing.assert_array_equal(res, exp)
    st = native.engine_stats()
    assert sum(st["aborts"].values()) == 0, st["aborts"]
    print(f"RECONNECTS {sum(st['link_reconnects'].values())}", flush=True)
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=4, tmp_path=tmp_path,
        extra_env={"HVT_FAULT_INJECT": "reset_storm:every_ops=3",
                   "HVT_OP_TIMEOUT_MS": "30000"})
    codes, outs = finish_gang(procs, logs, timeout=150)
    recon = 0
    for rank in range(4):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank], f"rank {rank}\n{outs[rank]}"
        recon += sum(int(ln.split()[1]) for ln in outs[rank].splitlines()
                     if ln.startswith("RECONNECTS"))
    assert recon >= 1, f"storm never cut a link\n{outs}"


def test_partition_heals_after_hold(tmp_path):
    """partition:hosts=A|B:ms=300 cuts the cross-'host' links (faked
    topology on loopback) and holds reconnects 300 ms; the gang must
    heal by itself — zero aborts, results exact, and the RECONNECT
    event's duration reflects the hold."""
    body = """
    x = np.arange(32768, dtype=np.float32) + 3 * r
    exp = sum(np.arange(32768, dtype=np.float32) + 3 * i
              for i in range(n))
    for i in range(8):
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"pt.{i}"))
        np.testing.assert_array_equal(res, exp)
    st = native.engine_stats()
    assert sum(st["aborts"].values()) == 0, st["aborts"]
    evs = [e for e in native.drain_events(8192)
           if e["kind_name"] == "RECONNECT"]
    print(f"RECONNECTS {sum(st['link_reconnects'].values())} "
          f"DUR {max([e['arg2'] for e in evs], default=0)}", flush=True)
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    extra = {"HVT_FAULT_INJECT": "partition:hosts=hA|hB:ms=300",
             "HVT_OP_TIMEOUT_MS": "30000"}
    procs, logs = [], []
    # per-rank env: fake ranks 0-1 onto host hA, ranks 2-3 onto hB
    import test_failure_containment as fc
    port = fc._next_port()
    import sys
    import textwrap
    script = textwrap.dedent(fc._PRELUDE.format(repo=fc.REPO)) + \
        textwrap.dedent(body)
    path = os.path.join(str(tmp_path), f"hvt_part_{port}.py")
    with open(path, "w") as f:
        f.write(script)
    import subprocess
    for rank in range(4):
        env = dict(os.environ)
        env.update({
            "HVT_MASTER_ADDR": "127.0.0.1",
            "HVT_MASTER_PORT": str(port),
            "HVT_PROCESS_ID": str(rank),
            "HVT_NUM_PROCESSES": "4",
            "HVT_SHM_ALLREDUCE": "0",
            "HVT_HIERARCHICAL_ALLREDUCE": "0",  # flat ring across "hosts"
            "HVT_TOPO_HOST": "hA" if rank < 2 else "hB",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "PALLAS_AXON_POOL_IPS": "",
        })
        env.update(extra)
        log = open(os.path.join(str(tmp_path),
                                f"hvt_part_{port}_r{rank}.log"), "w+")
        procs.append(subprocess.Popen(
            [sys.executable, path], env=env, cwd=fc.REPO, stdout=log,
            stderr=subprocess.STDOUT))
        logs.append(log)
    codes, outs = finish_gang(procs, logs, timeout=150)
    durs = []
    for rank in range(4):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank], f"rank {rank}\n{outs[rank]}"
        for ln in outs[rank].splitlines():
            if ln.startswith("RECONNECTS"):
                durs.append(int(ln.split()[3]))
    # at least one rank's heal waited out the (ranks-local) 300 ms hold
    assert max(durs) >= 200_000, durs


def test_tree_mode_member_link_heals_via_leader_reaccept(tmp_path):
    """HVT_CTRL_TOPOLOGY=tree: flaky_conn on a MEMBER cuts its link to
    the host leader; the leader must RE-ACCEPT on its (kept-open) tree
    listener and the negotiation stream must resume — zero aborts,
    exact results, ≥1 ctrl-plane reconnect on the member."""
    body = """
    x = np.arange(65536, dtype=np.float32) + r
    exp = sum(np.arange(65536, dtype=np.float32) + i for i in range(n))
    for i in range(10):
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"tr.{i}"))
        np.testing.assert_array_equal(res, exp)
    st = native.engine_stats()
    assert sum(st["aborts"].values()) == 0, st["aborts"]
    if r == 3:  # the cut member: its tree-parent link must have healed
        assert st["link_reconnects"]["ctrl"] >= 1, st["link_reconnects"]
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    import subprocess
    import sys
    import textwrap
    import test_failure_containment as fc
    port = fc._next_port()
    script = textwrap.dedent(fc._PRELUDE.format(repo=fc.REPO)) + \
        textwrap.dedent(body)
    path = os.path.join(str(tmp_path), f"hvt_tree_{port}.py")
    with open(path, "w") as f:
        f.write(script)
    procs, logs = [], []
    for rank in range(4):
        env = dict(os.environ)
        env.update({
            "HVT_MASTER_ADDR": "127.0.0.1",
            "HVT_MASTER_PORT": str(port),
            "HVT_PROCESS_ID": str(rank),
            "HVT_NUM_PROCESSES": "4",
            "HVT_SHM_ALLREDUCE": "0",
            "HVT_HIERARCHICAL_ALLREDUCE": "0",
            "HVT_CTRL_TOPOLOGY": "tree",
            # hosts hA={0,1}, hB={2,3}: rank 2 leads hB, rank 3 is its
            # member — the rank the fault cuts
            "HVT_TOPO_HOST": "hA" if rank < 2 else "hB",
            "HVT_FAULT_INJECT": "flaky_conn:rank=3:count=2:after_ops=3",
            "HVT_OP_TIMEOUT_MS": "30000",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "PALLAS_AXON_POOL_IPS": "",
        })
        log = open(os.path.join(str(tmp_path),
                                f"hvt_tree_{port}_r{rank}.log"), "w+")
        procs.append(subprocess.Popen(
            [sys.executable, path], env=env, cwd=fc.REPO, stdout=log,
            stderr=subprocess.STDOUT))
        logs.append(log)
    codes, outs = finish_gang(procs, logs, timeout=150)
    for rank in range(4):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank], f"rank {rank}\n{outs[rank]}"


# ------------------------------------------------- abort/recovery boundary

@pytest.mark.parametrize("backend", BACKENDS)
def test_replay_budget_exhaustion_escalates(tmp_path, backend):
    """An rx-side cut mid-4MB-transfer loses far more than a 256-byte
    replay ring can cover: the link must ESCALATE into the coordinated
    abort with a reason naming the peer and HVT_REPLAY_BUDGET_BYTES —
    never hang, never deliver wrong data."""
    body = """
    x = np.arange(1 << 20, dtype=np.float32) + r
    try:
        for i in range(10):
            hvt.allreduce(x, op=hvt.Sum, name=f"bx.{i}")
        print("NO-ERROR", flush=True)
    except hvt.HorovodInternalError:
        broken, info = native.engine_broken()
        assert broken
        print(f"CAUGHT {info}", flush=True)
    hvt.shutdown()
    print("EXITED", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=4, tmp_path=tmp_path,
        extra_env={"HVT_FAULT_INJECT": "flaky_conn:rank=1:count=2:after_ops=2",
                   "HVT_LINK_BACKEND": backend,
                   "HVT_REPLAY_BUDGET_BYTES": "256",
                   "HVT_SOCK_BUF": "262144",
                   "HVT_OP_TIMEOUT_MS": "15000",
                   "HVT_LINK_RETRY_WINDOW_MS": "4000"})
    codes, outs = finish_gang(procs, logs, timeout=150)
    blob = "\n".join(outs)
    for rank in range(4):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "EXITED" in outs[rank], f"rank {rank}\n{outs[rank]}"
    # the cut rank (or its peer) must have named the budget in the abort
    assert "replay budget exhausted" in blob, blob
    assert "HVT_REPLAY_BUDGET_BYTES=256" in blob, blob


def test_reconnect_disabled_restores_pr4_abort(tmp_path):
    """HVT_LINK_RECONNECT=0: the same transient cut becomes a
    coordinated peer_lost abort on the PR 4 path — the parity
    baseline."""
    body = """
    x = np.arange(65536, dtype=np.float32) + r
    try:
        for i in range(10):
            hvt.allreduce(x, op=hvt.Sum, name=f"nr.{i}")
        print("NO-ERROR", flush=True)
    except hvt.HorovodInternalError:
        st = native.engine_stats()
        assert st["aborts"]["peer_lost"] + st["aborts"]["remote_abort"] \
            >= 1, st["aborts"]
        print("CAUGHT", flush=True)
    hvt.shutdown()
    print("EXITED", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=4, tmp_path=tmp_path,
        extra_env={"HVT_FAULT_INJECT": "flaky_conn:rank=1:count=1:after_ops=2",
                   "HVT_LINK_RECONNECT": "0",
                   "HVT_OP_TIMEOUT_MS": "10000"})
    codes, outs = finish_gang(procs, logs, timeout=120)
    caught = 0
    for rank in range(4):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "EXITED" in outs[rank], f"rank {rank}\n{outs[rank]}"
        caught += outs[rank].count("CAUGHT")
    assert caught >= 1, outs


@pytest.mark.parametrize("backend", BACKENDS)
def test_shutdown_during_inflight_reconnect_exits_cleanly(tmp_path, backend):
    """A partition with a long hold parks the engine thread inside a
    reconnect episode; hvt.shutdown() must cut it short (the hub stop
    gate) and the process must exit 0 promptly — no join hang, no
    crash."""
    body = """
    import threading
    x = np.arange(32768, dtype=np.float32) + r
    h = hvt.allreduce_async(x, op=hvt.Sum, name="sd.0")
    time.sleep(1.5)  # the partition fires on op 1 and holds 60 s
    t0 = time.monotonic()
    hvt.shutdown()
    dt = time.monotonic() - t0
    assert dt < 20, f"shutdown took {dt:.1f}s"
    print(f"SHUTDOWN {dt:.2f}", flush=True)
    """
    import subprocess
    import sys
    import textwrap
    import test_failure_containment as fc
    port = fc._next_port()
    script = textwrap.dedent(fc._PRELUDE.format(repo=fc.REPO)) + \
        textwrap.dedent(body)
    path = os.path.join(str(tmp_path), f"hvt_sd_{port}.py")
    with open(path, "w") as f:
        f.write(script)
    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "HVT_MASTER_ADDR": "127.0.0.1",
            "HVT_MASTER_PORT": str(port),
            "HVT_PROCESS_ID": str(rank),
            "HVT_NUM_PROCESSES": "2",
            "HVT_SHM_ALLREDUCE": "0",
            "HVT_HIERARCHICAL_ALLREDUCE": "0",
            "HVT_TOPO_HOST": "hA" if rank == 0 else "hB",
            "HVT_FAULT_INJECT": "partition:hosts=hA|hB:ms=60000",
            "HVT_LINK_BACKEND": backend,
            "HVT_OP_TIMEOUT_MS": "30000",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "PALLAS_AXON_POOL_IPS": "",
        })
        log = open(os.path.join(str(tmp_path),
                                f"hvt_sd_{port}_r{rank}.log"), "w+")
        procs.append(subprocess.Popen(
            [sys.executable, path], env=env, cwd=fc.REPO, stdout=log,
            stderr=subprocess.STDOUT))
        logs.append(log)
    codes, outs = finish_gang(procs, logs, timeout=90)
    for rank in range(2):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "SHUTDOWN" in outs[rank], f"rank {rank}\n{outs[rank]}"


def test_sigkill_still_converges_one_deadline(tmp_path):
    """The PR 4 acceptance boundary with self-healing ON: a SIGKILLed
    rank must still turn into HorovodInternalError on every survivor
    within ~2x the op deadline (dead-peer dials are refused fast; the
    aborting ranks close their listeners so the cascade stays on the
    PR 4 clock)."""
    op_timeout_ms = 6000
    body = """
    x = np.arange(4096, dtype=np.float32) + r
    t0 = time.monotonic()
    try:
        for i in range(30):
            hvt.allreduce(x, op=hvt.Sum, name=f"sk.{i}")
        print("NO-ERROR", flush=True)
    except hvt.HorovodInternalError:
        dt = time.monotonic() - t0
        print(f"CAUGHT {dt:.3f}", flush=True)
    hvt.shutdown()
    print("EXITED", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=4, tmp_path=tmp_path,
        extra_env={"HVT_FAULT_INJECT": "kill:rank=2:after_ops=5",
                   "HVT_OP_TIMEOUT_MS": str(op_timeout_ms)})
    codes, outs = finish_gang(procs, logs,
                              timeout=4 * op_timeout_ms / 1000 + 60)
    assert codes[2] == -signal.SIGKILL, (codes, outs[2])
    for rank in (0, 1, 3):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CAUGHT" in outs[rank], f"rank {rank}\n{outs[rank]}"
        caught = [ln for ln in outs[rank].splitlines()
                  if ln.startswith("CAUGHT")][0]
        elapsed = float(caught.split()[1])
        assert elapsed < 2 * op_timeout_ms / 1000, \
            f"rank {rank} took {elapsed:.1f}s (> 2x op timeout)"


# --------------------------------------------------------- observability

@pytest.mark.parametrize("backend", BACKENDS)
def test_diagnostics_reports_link_state(tmp_path, backend):
    """hvt.diagnostics()['links'] / debugz: every link carries
    peer/plane/state/retries/epoch/in_state_sec, and a healed link
    shows a bumped session epoch."""
    body = """
    x = np.arange(65536, dtype=np.float32) + r
    for i in range(8):
        hvt.allreduce(x, op=hvt.Sum, name=f"dg.{i}")
    time.sleep(0.3)  # let UpdateDiag refresh past its 10 Hz throttle
    hvt.allreduce(x, op=hvt.Sum, name="dg.9")
    time.sleep(0.3)
    d = native.diagnostics()
    links = d.get("links") or []
    n_ctrl = (n - 1) if r == 0 else 1
    n_data = n - 1
    assert len(links) == n_ctrl + n_data, (r, d)
    for l in links:
        assert l["plane"] in ("ctrl", "data"), l
        assert l["state"] in ("healthy", "reconnecting", "dead"), l
        assert l["in_state_sec"] >= 0, l
        assert "retries" in l and "epoch" in l, l
    if r == 1:
        assert any(l["epoch"] >= 1 for l in links), links
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=3, tmp_path=tmp_path,
        extra_env={"HVT_FAULT_INJECT": "flaky_conn:rank=1:count=1:after_ops=3",
                   "HVT_LINK_BACKEND": backend,
                   "HVT_OP_TIMEOUT_MS": "30000"})
    codes, outs = finish_gang(procs, logs, timeout=120)
    for rank in range(3):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank], f"rank {rank}\n{outs[rank]}"
