"""Autotuner tests — Gaussian process regression, Bayesian optimization
(expected improvement), and the engine-integrated ParameterManager
(reference ``horovod/common/parameter_manager.cc``,
``common/optim/bayesian_optimization.cc``, ``gaussian_process.cc``;
fed from the cycle loop at ``operations.cc:610-642``)."""

import ctypes
import math
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


def lib():
    l = ctypes.CDLL(LIB)
    l.hvt_gp_fit_predict.restype = ctypes.c_int
    l.hvt_bo_suggest.restype = ctypes.c_int
    return l


def gp_fit_predict(X, y, Xq):
    X = np.ascontiguousarray(X, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    Xq = np.ascontiguousarray(Xq, dtype=np.float64)
    n, d = X.shape
    nq = Xq.shape[0]
    mean = np.zeros(nq)
    var = np.zeros(nq)
    rc = lib().hvt_gp_fit_predict(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n, d,
        Xq.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        nq,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        var.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0
    return mean, var


def bo_suggest(X, y):
    X = np.ascontiguousarray(X, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    n, d = X.shape
    out = np.zeros(d)
    rc = lib().hvt_bo_suggest(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n, d,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0
    return out


# ------------------------------------------------------------------- GP

def test_gp_interpolates_observed_points():
    X = np.array([[0.0], [0.25], [0.5], [0.75], [1.0]])
    y = np.sin(2 * math.pi * X[:, 0])
    mean, var = gp_fit_predict(X, y, X)
    np.testing.assert_allclose(mean, y, atol=0.05)
    # posterior variance collapses at observed points
    assert np.all(var < 0.05 * np.var(y) + 1e-6)


def test_gp_predicts_between_points():
    X = np.array([[0.0], [0.2], [0.4], [0.6], [0.8], [1.0]])
    y = X[:, 0] ** 2
    Xq = np.array([[0.3], [0.5], [0.7]])
    mean, var = gp_fit_predict(X, y, Xq)
    np.testing.assert_allclose(mean, Xq[:, 0] ** 2, atol=0.05)
    # mid-gap variance exceeds on-point variance
    _, var_on = gp_fit_predict(X, y, X[2:3])
    assert var[1] > var_on[0]


def test_gp_2d():
    rs = np.random.RandomState(0)
    X = rs.uniform(size=(25, 2))
    y = -((X[:, 0] - 0.5) ** 2 + (X[:, 1] - 0.5) ** 2)
    Xq = np.array([[0.5, 0.5], [0.1, 0.9]])
    mean, _ = gp_fit_predict(X, y, Xq)
    assert mean[0] > mean[1]  # center scores higher than corner


# ------------------------------------------------------------------- BO

def test_bo_suggestion_in_unit_box():
    X = np.array([[0.1, 0.1], [0.9, 0.9], [0.5, 0.2]])
    y = np.array([1.0, 2.0, 1.5])
    s = bo_suggest(X, y)
    assert s.shape == (2,)
    assert np.all(s >= 0.0) and np.all(s <= 1.0)


def test_bo_deterministic():
    X = np.array([[0.2, 0.8], [0.6, 0.4], [0.9, 0.1], [0.3, 0.3]])
    y = np.array([0.5, 1.5, 0.7, 1.0])
    np.testing.assert_array_equal(bo_suggest(X, y), bo_suggest(X, y))


def test_bo_converges_toward_optimum():
    """Simulated BO loop on a concave objective: later suggestions should
    cluster near the optimum (0.7, 0.3)."""
    def f(x):
        return -((x[0] - 0.7) ** 2 + (x[1] - 0.3) ** 2)

    rs = np.random.RandomState(1)
    X = list(rs.uniform(size=(4, 2)))
    y = [f(x) for x in X]
    last = None
    for _ in range(12):
        s = bo_suggest(np.array(X), np.array(y))
        X.append(s)
        y.append(f(s))
        last = s
    best = X[int(np.argmax(y))]
    assert f(best) > -0.02, f"best {best} score {f(best)}"
    assert last is not None


# ----------------------------------------------------- engine integration

def test_autotune_engine_integration():
    """2-process engine job with HVT_AUTOTUNE=1: after enough collectives
    the coordinator must have recorded samples and still produce correct
    results (tuning must never affect numerics)."""
    from tests.test_engine_integration import run_workers

    out = run_workers("""
        import ctypes
        for step in range(120):
            x = np.full((256,), float(r + 1), np.float32)
            res = np.asarray(hvt.allreduce(x, name=f"g{step % 4}",
                                           average=True))
            np.testing.assert_allclose(res, (1 + n) / 2.0)
        if r == 0:
            lib = ctypes.CDLL(
                os.path.join({REPO!r}, "horovod_tpu", "csrc", "build",
                             "libhvt_core.so"))
            st = (ctypes.c_longlong * 4)()
            lib.hvt_autotune_state(st)
            assert st[3] == 1, "autotune not active"
            assert st[2] >= 1, f"no autotune samples recorded: {list(st)}"
            print(f"AUTOTUNE-SAMPLES-{st[2]}", flush=True)
    """.replace("{REPO!r}", repr(REPO)),
        extra_env={"HVT_AUTOTUNE": "1",
                   "HVT_AUTOTUNE_WARMUP_SAMPLES": "1",
                   "HVT_AUTOTUNE_CYCLES_PER_SAMPLE": "5",
                   "HVT_AUTOTUNE_MAX_SAMPLES": "50"})
    assert "AUTOTUNE-SAMPLES-" in out


def test_autotune_cache_flip_race_stress_2proc():
    """Liveness pin for the mixed hit/miss wedge (round-3 CI flake):
    with the tuner flipping cache-enabled as often as every cycle and
    rank-staggered submission jitter, two ranks routinely announce the
    SAME tensor in frames on opposite sides of a flip — one as a cached
    hit, one as a plain miss. Pre-fold coordinators starved both paths
    (rank 0 wedged 60 s on g1, then the 90 s timeout). The coordinator
    now folds hits into the slow-path negotiation (Engine::HitToArrival),
    so this must complete every step regardless of flip timing."""
    from tests.test_engine_integration import run_workers

    out = run_workers("""
        import time
        for step in range(200):
            # stagger ranks into different engine frames so hit/miss
            # announcements straddle tuner flips
            time.sleep(0.0015 * ((r + step) % 3))
            x = np.full((128,), float(r + 1), np.float32)
            res = np.asarray(hvt.allreduce(x, name=f"g{step % 4}",
                                           average=True))
            np.testing.assert_allclose(res, (1 + n) / 2.0)
    """,
        timeout=150,
        extra_env={"HVT_AUTOTUNE": "1",
                   "HVT_AUTOTUNE_WARMUP_SAMPLES": "1",
                   "HVT_AUTOTUNE_CYCLES_PER_SAMPLE": "1",
                   "HVT_AUTOTUNE_MAX_SAMPLES": "500",
                   "HVT_CYCLE_TIME_MS": "1"})
    assert "WORKER-0-DONE" in out and "WORKER-1-DONE" in out


def test_autotune_four_knobs_converge_and_stay_synchronized_4proc():
    """Widened tuning surface (reference parameter_manager.h:60-78):
    {fusion threshold, cycle time, cache enabled, backend preference}.
    The BO's space-filling start genuinely toggles the cache and
    flat-ring flags, so this pins three things at once: numerics stay
    correct while the knobs move, the tuner reaches its sample budget and
    freezes on the best point, and the frame-broadcast keeps cycle_ms and
    the flags identical on every rank."""
    from tests.test_engine_integration import run_workers

    out = run_workers("""
        import ctypes
        lib = ctypes.CDLL(
            os.path.join({REPO!r}, "horovod_tpu", "csrc", "build",
                         "libhvt_core.so"))
        for step in range(400):
            x = np.full((512,), float(r + 1 + step % 3), np.float32)
            res = np.asarray(hvt.allreduce(x, op=hvt.Sum,
                                           name=f"k{step % 4}"))
            np.testing.assert_allclose(
                res, float(sum(i + 1 + step % 3 for i in range(n))))
        # one more collective so every rank has passed a frame boundary
        # AFTER the tuner froze, then compare the synchronized state
        hvt.allreduce(np.zeros(4, np.float32), op=hvt.Sum, name="fin")
        st = (ctypes.c_longlong * 4)()
        lib.hvt_autotune_state(st)
        flags = lib.hvt_engine_flags()
        states = hvt.allgather_object({"rank": r, "flags": flags,
                                       "cycle": int(st[1])})
        base = {k: v for k, v in states[0].items() if k != "rank"}
        for s in states:
            assert {k: v for k, v in s.items() if k != "rank"} == base, \
                f"tuned state diverged across ranks: {states}"
        if r == 0:
            assert st[3] == 1, "autotune not active"
            assert st[2] >= 6, f"tuner did not finish: {list(st)}"
            print(f"AUTOTUNE4-DONE samples={st[2]} flags={flags} "
                  f"cycle={int(st[1])}", flush=True)
    """.replace("{REPO!r}", repr(REPO)),
        np=4,
        extra_env={"HVT_AUTOTUNE": "1",
                   "HVT_AUTOTUNE_WARMUP_SAMPLES": "1",
                   "HVT_AUTOTUNE_CYCLES_PER_SAMPLE": "3",
                   "HVT_AUTOTUNE_MAX_SAMPLES": "6"})
    assert "AUTOTUNE4-DONE" in out, out[-2000:]
