"""Callback + SyncBatchNorm + compatibility-binding tests (reference
``test/parallel/test_keras.py`` callback coverage and
``tensorflow/sync_batch_norm.py`` semantics)."""

import numpy as np
import pytest

import horovod_tpu as hvt
from horovod_tpu.jax.callbacks import (BroadcastGlobalVariablesCallback,
                                       CallbackList,
                                       LearningRateScheduleCallback,
                                       LearningRateWarmupCallback,
                                       MetricAverageCallback,
                                       exponential_schedule,
                                       warmup_schedule)


# ------------------------------------------------------------ LR math

def test_warmup_multiplier_reaches_target():
    cb = LearningRateWarmupCallback(initial_lr=0.4, warmup_epochs=5,
                                    steps_per_epoch=10, size=4)
    cb.on_epoch_begin(0)
    lr0 = cb.learning_rate(0)
    assert lr0 == pytest.approx(0.4 / 4)            # starts at lr/size
    # just before the warmup boundary the lr approaches the target
    lr_end = cb.learning_rate(49)
    assert lr_end == pytest.approx(0.4, rel=0.1)
    # after warmup the callback holds the target lr
    assert cb.learning_rate(50) == pytest.approx(0.4)
    assert cb.learning_rate(500) == pytest.approx(0.4)


def test_warmup_size1_is_identity():
    cb = LearningRateWarmupCallback(initial_lr=0.1, warmup_epochs=5,
                                    steps_per_epoch=10, size=1)
    cb.on_epoch_begin(0)
    assert cb.learning_rate(0) == pytest.approx(0.1)


def test_schedule_staircase_and_range():
    cb = LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.1 ** e,
        start_epoch=1, end_epoch=3, staircase=True)
    cb.on_epoch_begin(0)
    assert cb.learning_rate(0) is None              # before start
    cb.on_epoch_begin(1)
    assert cb.learning_rate(10) == pytest.approx(0.1)
    cb.on_epoch_begin(2)
    assert cb.learning_rate(20) == pytest.approx(0.01)
    cb.on_epoch_begin(3)
    assert cb.learning_rate(30) is None             # past end


def test_optax_schedules():
    sched = warmup_schedule(0.8, warmup_steps=10, size=4)
    assert float(sched(0)) == pytest.approx(0.2)
    assert float(sched(10)) == pytest.approx(0.8)
    assert float(sched(100)) == pytest.approx(0.8)
    exp = exponential_schedule(1.0, decay=0.5, steps_per_epoch=10)
    assert float(exp(0)) == pytest.approx(1.0)
    assert float(exp(25)) == pytest.approx(0.25)


# ---------------------------------------------------------- callbacks

def test_broadcast_and_metric_average_callbacks():
    cbs = CallbackList([BroadcastGlobalVariablesCallback(0),
                        MetricAverageCallback()])
    state = {"w": np.ones((3,), np.float32)}
    state = cbs.on_train_begin(state)
    np.testing.assert_allclose(np.asarray(state["w"]), 1.0)
    metrics = cbs.on_epoch_end(0, {"loss": 2.0, "acc": 0.5})
    # single process: averaging is identity
    assert metrics["loss"] == pytest.approx(2.0)
    assert metrics["acc"] == pytest.approx(0.5)


def test_callback_list_lr_priority():
    class Fixed:
        def on_train_begin(self, s):
            return s

        def on_epoch_begin(self, e):
            pass

        def on_epoch_end(self, e, m=None):
            return m

        def learning_rate(self, step):
            return 0.5

    cb = LearningRateScheduleCallback(initial_lr=1.0, multiplier=2.0,
                                      start_epoch=0)
    cbs = CallbackList([Fixed(), cb])
    cbs.on_epoch_begin(0)
    # later callbacks win when they provide a value
    assert cbs.learning_rate(0) == pytest.approx(2.0)


# ------------------------------------------------------ SyncBatchNorm

def test_sync_batch_norm_syncs_stats(world_mesh):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.jax.sync_batch_norm import SyncBatchNorm
    from horovod_tpu.parallel.mesh import WORLD_AXIS

    n = len(jax.devices())
    # per-device batches with very different means
    x = np.concatenate([np.full((2, 4), float(i), np.float32)
                        for i in range(n)])
    model = SyncBatchNorm(use_running_average=False, momentum=0.9)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))

    def step(xs):
        out, updated = model.apply(variables, xs, mutable=["batch_stats"])
        return out, updated["batch_stats"]

    sharded = shard_map(step, mesh=world_mesh,
                        in_specs=P(WORLD_AXIS),
                        out_specs=(P(WORLD_AXIS), P()),
                        check_vma=False)
    out, stats = sharded(jnp.asarray(x))
    # synced mean must equal the GLOBAL batch mean on every device
    global_mean = x.mean(axis=0)
    got_mean = np.asarray(jax.tree.leaves(stats)[0]).reshape(-1, 4)[0]
    expect = 0.1 * global_mean       # momentum 0.9, init 0
    np.testing.assert_allclose(got_mean, expect, rtol=1e-5)
    # normalized output: per-device output differs from local-only BN
    # (which would normalize each identical-valued shard to zeros)
    assert float(np.abs(np.asarray(out)).max()) > 0.5


def test_sync_batch_norm_no_axis_fallback():
    import jax
    import jax.numpy as jnp

    from horovod_tpu.jax.sync_batch_norm import SyncBatchNorm

    x = jnp.asarray(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    model = SyncBatchNorm(use_running_average=False)
    variables = model.init(jax.random.PRNGKey(0), x)
    out, _ = model.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(out).mean(axis=0), 0.0,
                               atol=1e-5)


# ------------------------------------------- compatibility bindings

def test_tensorflow_binding_gated():
    import horovod_tpu.tensorflow as hvt_tf

    assert hvt_tf.rank() == hvt.rank()
    try:
        import tensorflow  # noqa: F401

        has_tf = True
    except ImportError:
        has_tf = False
    if not has_tf:
        with pytest.raises(ImportError, match="horovod_tpu.jax"):
            hvt_tf.allreduce(np.ones(3))


def test_mxnet_binding_surface():
    # the binding is real (gated): collective surface + optimizer wrapper
    # exist; only the Gluon trainer needs an actual mxnet install
    import horovod_tpu.mxnet as hvt_mx

    for fn in ("allreduce", "allreduce_", "grouped_allreduce", "allgather",
               "broadcast", "broadcast_", "alltoall",
               "broadcast_parameters", "DistributedOptimizer"):
        assert hasattr(hvt_mx, fn), fn
    if not hvt_mx._MX_AVAILABLE:
        with pytest.raises(ImportError, match="horovod_tpu.jax"):
            hvt_mx.DistributedTrainer([], None)


def test_keras_binding_gated():
    import horovod_tpu.keras as hvt_keras

    assert hvt_keras.size() == hvt.size()
    try:
        import tensorflow.keras  # noqa: F401

        has = True
    except ImportError:
        has = False
    if not has:
        with pytest.raises(ImportError, match="horovod_tpu.jax"):
            hvt_keras.MetricAverageCallback()
