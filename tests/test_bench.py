"""bench.py harness validation on the virtual CPU mesh.

The real numbers come from the driver's TPU run; these tests pin the
harness semantics — measure() produces sane throughput/FLOP estimates on
a multi-device mesh, and main()'s scaling sweep computes per-chip
efficiency relative to the 1-chip run (the BASELINE.md metric of record).
"""

import json
import sys

import numpy as np
import pytest

import bench


def test_measure_multidevice_smoke():
    import jax

    (per_chip, total, std, flops_per_img, xla_flops, loss,
     suspect) = bench.measure(
        "resnet50", jax.devices()[:2], per_chip_batch=1, num_iters=1,
        num_batches_per_iter=1, dtype_name="fp32", image_size=32)
    assert per_chip > 0
    assert suspect is False
    assert total == pytest.approx(per_chip * 2)
    assert np.isfinite(loss)
    # 32px analytic value: 12.3 GFLOP * (32/224)^2 ≈ 0.25 GFLOP
    assert flops_per_img == pytest.approx(12.3e9 * (32 / 224.0) ** 2)
    # XLA's own count (body once, its conv accounting) lands in the same
    # order of magnitude — a cross-check that the harness wiring is sane
    if xla_flops is not None:
        assert 0.3 * flops_per_img < xla_flops < 10 * flops_per_img


def test_main_scaling_sweep_and_json_schema(monkeypatch, capsys):
    per_chip_by_n = {1: 100.0, 2: 95.0, 4: 90.0, 8: 85.0}

    def fake_measure(model_name, devices, per_chip_batch, num_iters,
                     num_batches_per_iter, dtype_name, image_size=224,
                     norm_impl="tpu", conv0_s2d=False, unroll=1):
        pc = per_chip_by_n[len(devices)]
        return pc, pc * len(devices), 0.0, 12.3e9, 23.5e9, 1.23, False

    monkeypatch.setattr(bench, "measure", fake_measure)
    monkeypatch.setattr(bench, "calibrate_matmul_tflops", lambda p: 100.0)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(out)

    assert rec["metric"] == "resnet50_synthetic_img_sec_per_chip"
    # headline number is the all-chips (8-device) per-chip throughput
    assert rec["value"] == 85.0
    assert rec["unit"] == "img/sec/chip"
    assert rec["vs_baseline"] == pytest.approx(
        85.0 / bench.BASELINE_IMG_SEC_PER_DEVICE, rel=1e-3)
    assert rec["calib_tflops"] == 100.0
    # 3 identical interleaved samples → zero spread
    assert rec["calib_spread"] == 0.0
    assert rec["achieved_tflops"] == pytest.approx(
        85.0 * 12.3e9 / 1e12, rel=1e-3)
    assert rec["mfu"] == pytest.approx(rec["achieved_tflops"] / 100.0,
                                       rel=1e-2)
    # 8 virtual devices → sweep over powers of two, efficiency vs n=1
    assert rec["scaling"]["n"] == [1, 2, 4, 8]
    assert rec["scaling"]["efficiency"] == [1.0, 0.95, 0.9, 0.85]
    # r5.0 record fields: suspect flag always present; mfu_vs_peak is
    # null on cpu (paper peak is a TPU spec)
    assert rec["suspect"] is False
    assert "mfu_vs_peak" in rec and rec["mfu_vs_peak"] is None


def test_calibration_runs_on_cpu():
    tflops = bench.calibrate_matmul_tflops("cpu")
    assert tflops > 0
