"""Sanitizer smoke over the C++ engine (SURVEY §5.2: sanitizers as a CI
matrix choice; one-command wrapper: ``./ci.sh --sanitize``). Each test
builds the engine with a sanitizer (`make tsan` / `make ubsan`), then
drives a real 2-proc job that hammers the engine from multiple
submitter threads, with the sanitizer runtime preloaded and findings
fatal.

- TSan: any data race in the engine-thread/submitter/waiter interplay
  fails the job via TSAN_OPTIONS exitcode. Cross-PROCESS shm
  synchronization is outside TSan's model; the progress-word design +
  interleave stress tests cover that.
- UBSan: undefined behavior in the wire codec / reduce kernels
  (misaligned loads, overflow, bad enum casts) aborts the job via
  halt_on_error.
- ASan/UBSan fuzz replay: the committed wire-frame corpus
  (tests/corpus/proto_frames.jsonl) plus a deterministic mini-campaign
  runs through hvt_decode_probe under each instrumented build, so a
  decoder bounds bug the grammar fuzzer can reach fails here too.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from tests.test_engine_integration import REPO, _PORT


def _gcc_lib(name):
    try:
        p = subprocess.run(["gcc", "-print-file-name=" + name],
                           capture_output=True, text=True).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        return ""
    return p if os.path.isabs(p) and os.path.exists(p) else ""


TSAN_LIB = _gcc_lib("libtsan.so")
UBSAN_LIB = _gcc_lib("libubsan.so")
ASAN_LIB = _gcc_lib("libasan.so")
# co-preloaded with libasan for the fuzz replay: python itself is not
# linked against libstdc++, so without it in the initial library list
# ASan's __cxa_throw interceptor finds no real symbol and aborts on the
# first TruncatedFrameError ("real___cxa_throw != 0" CHECK)
STDCXX_LIB = _gcc_lib("libstdc++.so.6")


def _gcc_major():
    try:
        v = subprocess.run(["gcc", "-dumpversion"], capture_output=True,
                           text=True).stdout.strip()
        return int(v.split(".")[0])
    except (OSError, ValueError, subprocess.SubprocessError):
        return 0


# gcc-10's libtsan mis-tracks mutex lifetime on this image ("mutex is
# already destroyed" on a live, never-destroyed engine mutex), then
# reports every queue_mu_-protected submit/drain access as a race even
# while printing that BOTH threads hold the same write lock. Verified
# pre-existing: the identical report family reproduces on the unmodified
# parent tree. Run the TSan gang only on a libtsan new enough to trust.
TSAN_TRUSTWORTHY = _gcc_major() >= 11

WORKER = textwrap.dedent("""
    import sys, threading
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import horovod_tpu as hvt
    hvt.init()
    r, n = hvt.rank(), hvt.size()

    def worker(tid):
        for i in range(25):
            res = np.asarray(hvt.allreduce(
                np.full((64,), float(r + 1), np.float32), op=hvt.Sum,
                name=f"t{{tid}}.{{i}}"))
            np.testing.assert_allclose(
                res, float(sum(k + 1 for k in range(n))))

    ths = [threading.Thread(target=worker, args=(t,)) for t in range(2)]
    [t.start() for t in ths]
    [t.join() for t in ths]
    print(f"rank {{r}}: SANITIZER OK")
""").format(repo=REPO)


def _build_sanitized(target):
    rc = subprocess.run(["make", "-C",
                         os.path.join(REPO, "horovod_tpu", "csrc"),
                         target], capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr[-2000:]
    return os.path.join(REPO, "horovod_tpu", "csrc",
                        f"build-{target}", "libhvt_core.so")


def _run_sanitized_gang(tmp_path, target, preload, extra_env):
    """Build `make -C csrc <target>` and drive the 2-proc multi-threaded
    gang against it; returns (proc, report_files).

    The sanitizer runtime is preloaded ONLY into the worker processes
    (via an `env LD_PRELOAD=…` wrapper in the worker argv), never into
    the launcher: libtsan's fork interceptors deadlock the launcher's
    multi-threaded spawn path, wedging the whole gang before any worker
    runs — and the launcher is not what the test instruments anyway."""
    core = _build_sanitized(target)
    worker = tmp_path / "w.py"
    worker.write_text(WORKER)
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO,
        "HVT_CORE_LIB": core,
        "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",
    })
    env.update(extra_env)
    _PORT[0] += 1
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--master-port", str(_PORT[0]),
         "/usr/bin/env", f"LD_PRELOAD={preload}",
         sys.executable, str(worker)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    reports = [f for f in os.listdir(tmp_path)
               if f.startswith("sanitizer_report")]
    return proc, reports


@pytest.mark.skipif(not TSAN_LIB, reason="libtsan not available")
@pytest.mark.skipif(not TSAN_TRUSTWORTHY,
                    reason="gcc<11 libtsan: known destroyed-mutex "
                           "false positives (see TSAN_TRUSTWORTHY note)")
@pytest.mark.timeout(600)
def test_engine_threading_clean_under_tsan(tmp_path):
    report = str(tmp_path / "sanitizer_report")
    # halt_on_error off: collect everything, judge by report files +
    # forced exitcode on any finding
    proc, reports = _run_sanitized_gang(
        tmp_path, "tsan", TSAN_LIB,
        {"TSAN_OPTIONS": f"exitcode=66 log_path={report}"})
    assert proc.returncode == 0 and not reports, (
        f"rc={proc.returncode} reports={reports}\n{proc.stdout[-2000:]}"
        f"\n{proc.stderr[-2000:]}")
    assert proc.stdout.count("SANITIZER OK") == 2, proc.stdout[-1000:]


def _run_sanitized_fuzz(tmp_path, target, preload, extra_env):
    """Build `make -C csrc <target>` and replay the committed wire-frame
    corpus — plus a small deterministic grammar-derived campaign — with
    the sanitizer runtime preloaded into hvt_fuzz's decode process.
    Single-process (no gang): every frame goes straight into the decoder
    families via hvt_decode_probe, which is exactly the surface the
    fuzzer exercises."""
    core = _build_sanitized(target)
    env = dict(os.environ)
    env.update({"PYTHONPATH": REPO, "HVT_CORE_LIB": core,
                "LD_PRELOAD": preload})
    env.update(extra_env)
    corpus = os.path.join(REPO, "tests", "corpus", "proto_frames.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.tools.hvt_fuzz",
         "--replay", corpus, "--campaign", "500", "--seed", "20", "-q"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    reports = [f for f in os.listdir(tmp_path)
               if f.startswith("sanitizer_report")]
    return proc, reports


@pytest.mark.slow  # cold `make asan` is a multi-minute build; the
#                    UBSan twin below shares its build with the engine
#                    gang and stays in the tier-1 window
@pytest.mark.skipif(not ASAN_LIB or not STDCXX_LIB,
                    reason="libasan/libstdc++ not available")
@pytest.mark.timeout(600)
def test_fuzz_corpus_clean_under_asan(tmp_path):
    report = str(tmp_path / "sanitizer_report")
    # detect_leaks off: CPython itself leaks by LSan's definition; the
    # target here is heap overflow/UAF in the decoders, not leaks
    proc, reports = _run_sanitized_fuzz(
        tmp_path, "asan", f"{ASAN_LIB} {STDCXX_LIB}",
        {"ASAN_OPTIONS": f"detect_leaks=0:halt_on_error=1:"
                         f"log_path={report}"})
    assert proc.returncode == 0 and not reports, (
        f"rc={proc.returncode} reports={reports}\n{proc.stdout[-2000:]}"
        f"\n{proc.stderr[-2000:]}")


@pytest.mark.skipif(not UBSAN_LIB, reason="libubsan not available")
@pytest.mark.timeout(600)
def test_fuzz_corpus_clean_under_ubsan(tmp_path):
    report = str(tmp_path / "sanitizer_report")
    proc, reports = _run_sanitized_fuzz(
        tmp_path, "ubsan", UBSAN_LIB,
        {"UBSAN_OPTIONS": f"halt_on_error=1 print_stacktrace=1 "
                          f"log_path={report}"})
    assert proc.returncode == 0 and not reports, (
        f"rc={proc.returncode} reports={reports}\n{proc.stdout[-2000:]}"
        f"\n{proc.stderr[-2000:]}")


@pytest.mark.skipif(not UBSAN_LIB, reason="libubsan not available")
@pytest.mark.timeout(600)
def test_engine_clean_under_ubsan(tmp_path):
    report = str(tmp_path / "sanitizer_report")
    # halt_on_error: any UB report (normally print-and-continue) aborts
    # the worker, which the launcher surfaces as a nonzero exit
    proc, reports = _run_sanitized_gang(
        tmp_path, "ubsan", UBSAN_LIB,
        {"UBSAN_OPTIONS": f"halt_on_error=1 print_stacktrace=1 "
                          f"log_path={report}"})
    assert proc.returncode == 0 and not reports, (
        f"rc={proc.returncode} reports={reports}\n{proc.stdout[-2000:]}"
        f"\n{proc.stderr[-2000:]}")
    assert proc.stdout.count("SANITIZER OK") == 2, proc.stdout[-1000:]
