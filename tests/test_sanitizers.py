"""Sanitizer smoke over the C++ engine (SURVEY §5.2: sanitizers as a CI
matrix choice). Builds the engine with -fsanitize=thread, then drives a
2-proc job that hammers the engine from multiple submitter threads —
any data race in the engine-thread/submitter/waiter interplay fails the
job via TSAN_OPTIONS exitcode. Cross-PROCESS shm synchronization is
outside TSAN's model; the progress-word design + interleave stress
tests cover that."""

import os
import subprocess
import sys
import textwrap

import pytest

from tests.test_engine_integration import REPO, _PORT

try:
    TSAN_LIB = subprocess.run(["gcc", "-print-file-name=libtsan.so"],
                              capture_output=True, text=True
                              ).stdout.strip()
except (OSError, subprocess.SubprocessError):  # no gcc → skip below
    TSAN_LIB = ""

pytestmark = pytest.mark.skipif(
    not os.path.isabs(TSAN_LIB) or not os.path.exists(TSAN_LIB),
    reason="libtsan not available")

WORKER = textwrap.dedent("""
    import sys, threading
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import horovod_tpu as hvt
    hvt.init()
    r, n = hvt.rank(), hvt.size()

    def worker(tid):
        for i in range(25):
            res = np.asarray(hvt.allreduce(
                np.full((64,), float(r + 1), np.float32), op=hvt.Sum,
                name=f"t{{tid}}.{{i}}"))
            np.testing.assert_allclose(
                res, float(sum(k + 1 for k in range(n))))

    ths = [threading.Thread(target=worker, args=(t,)) for t in range(2)]
    [t.start() for t in ths]
    [t.join() for t in ths]
    print(f"rank {{r}}: TSAN OK")
""").format(repo=REPO)


@pytest.mark.timeout(600)
def test_engine_threading_clean_under_tsan(tmp_path):
    rc = subprocess.run(["make", "-C",
                         os.path.join(REPO, "horovod_tpu", "csrc"),
                         "tsan"], capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr[-2000:]
    worker = tmp_path / "w.py"
    worker.write_text(WORKER)
    report = str(tmp_path / "tsan_report")
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO,
        "HVT_CORE_LIB": os.path.join(REPO, "horovod_tpu", "csrc",
                                     "build-tsan", "libhvt_core.so"),
        "LD_PRELOAD": TSAN_LIB,
        # halt_on_error off: collect everything, judge by report files +
        # forced exitcode on any finding
        "TSAN_OPTIONS": f"exitcode=66 log_path={report}",
        "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",
    })
    _PORT[0] += 1
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--master-port", str(_PORT[0]), sys.executable, str(worker)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    reports = [f for f in os.listdir(tmp_path) if f.startswith("tsan_report")]
    assert proc.returncode == 0 and not reports, (
        f"rc={proc.returncode} reports={reports}\n{proc.stdout[-2000:]}"
        f"\n{proc.stderr[-2000:]}")
    assert proc.stdout.count("TSAN OK") == 2, proc.stdout[-1000:]
