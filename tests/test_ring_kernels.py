"""Process-local units for the data-plane kernels: ScaleBuffer integer
rounding (via the ``hvt_scale_buffer`` test entry point), the
block-scaled wire codecs (``hvt_codec_roundtrip`` /
``hvt_codec_wire_bytes`` — block independence, idempotence, exact wire
sizes, error-feedback math), the extended ``hvt_engine_stats`` layout,
the new C API symbols, and the bridged-histogram ``set_state`` path in
the metrics registry. Gang-level behavior (event-driven latency,
pipelined-ring numerics, compressed wire) lives in
``tests/test_data_plane.py``.
"""

import ctypes
import os

import numpy as np
import pytest

from horovod_tpu.engine import native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


def _lib():
    lib = ctypes.CDLL(LIB)
    lib.hvt_scale_buffer.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                     ctypes.c_int, ctypes.c_double]
    lib.hvt_codec_roundtrip.argtypes = [ctypes.c_void_p,
                                        ctypes.c_longlong, ctypes.c_int]
    lib.hvt_codec_wire_bytes.argtypes = [ctypes.c_longlong, ctypes.c_int]
    lib.hvt_codec_wire_bytes.restype = ctypes.c_longlong
    return lib


def _roundtrip(arr, codec_id):
    lib = _lib()
    out = np.ascontiguousarray(arr, dtype=np.float32).copy()
    rc = lib.hvt_codec_roundtrip(out.ctypes.data_as(ctypes.c_void_p),
                                 len(out), codec_id)
    assert rc == 0
    return out


def _scale(arr, factor):
    lib = _lib()
    dtype_id = {"int32": 4, "int64": 5, "float32": 7,
                "float64": 8}[arr.dtype.name]
    rc = lib.hvt_scale_buffer(arr.ctypes.data_as(ctypes.c_void_p),
                              len(arr), dtype_id, factor)
    assert rc == 0
    return arr


# ---------------------------------------------------------------- scale


def test_scale_int32_rounds_not_truncates():
    # 3 * 0.5 = 1.5 → 2 (truncation would give 1); half rounds away
    # from zero, matching llround
    arr = np.array([3, 5, -3, -5, 4, 0], dtype=np.int32)
    _scale(arr, 0.5)
    np.testing.assert_array_equal(arr, [2, 3, -2, -3, 2, 0])


def test_scale_int64_rounds_not_truncates():
    arr = np.array([3, -3, 10**12 + 3], dtype=np.int64)
    _scale(arr, 0.5)
    np.testing.assert_array_equal(
        arr, [2, -2, (10**12 + 3 + 1) // 2])


def test_scale_int_average_divide_unbiased():
    # averaging [1, 1] over 2 ranks: sum 2 * (1/2) = 1.0 exactly; and
    # sum 3 * (1/2) rounds to 2, not down to 1
    arr = np.array([2, 3], dtype=np.int32)
    _scale(arr, 0.5)
    np.testing.assert_array_equal(arr, [1, 2])


def test_scale_float_paths_unchanged():
    arr = np.array([1.5, -2.25, 0.0], dtype=np.float32)
    _scale(arr, 2.0)
    np.testing.assert_allclose(arr, [3.0, -4.5, 0.0])
    arr64 = np.array([1.5, -2.25], dtype=np.float64)
    _scale(arr64, -1.0)
    np.testing.assert_allclose(arr64, [-1.5, 2.25])


def test_scale_rejects_unsupported_dtype():
    lib = _lib()
    arr = np.zeros(4, dtype=np.uint8)
    rc = lib.hvt_scale_buffer(arr.ctypes.data_as(ctypes.c_void_p),
                              4, 0, 0.5)  # dtype 0 = uint8: unsupported
    assert rc == -1


# ---------------------------------------------------------------- C API


def test_new_c_api_symbols_exported():
    lib = _lib()
    for sym in ("hvt_wire_compression", "hvt_scale_buffer",
                "hvt_engine_stats", "hvt_events_drain"):
        assert getattr(lib, sym, None) is not None, f"missing {sym}"


def test_wire_compression_defaults_off():
    intra, inter, auto = native.wire_compression()
    assert 0 <= intra < len(native.WIRE_CODECS)
    assert 0 <= inter < len(native.WIRE_CODECS)
    # in the test session HVT_WIRE_COMPRESSION is not set → raw pair
    if not os.environ.get("HVT_WIRE_COMPRESSION"):
        assert (intra, inter, auto) == (0, 0, False)


def test_wire_compression_stale_so_decodes_single_mode(monkeypatch):
    """A pre-registry .so (no hvt_codec_roundtrip export) returns the
    single-codec mode scalar, which applied to EVERY link — it must
    decode as (id, id), not as a packed pair that would misreport
    inter-host traffic as raw while the old engine compresses it."""
    class _StaleLib:
        hvt_codec_roundtrip = None

        @staticmethod
        def hvt_wire_compression():
            return 1  # old-world "bf16 on every link"

    monkeypatch.setattr(native, "_load", lambda: _StaleLib())
    assert native.wire_compression() == (1, 1, False)


# ---------------------------------------------------------------- codecs


CODECS = {"bf16": 1, "int8": 2, "fp8": 3}


def test_codec_wire_bytes_exact():
    lib = _lib()
    # raw/unknown: 4 bytes per elem; bf16: 2; block codecs: 260 per
    # 256-elem block, partial tail pays 4 + rem
    assert lib.hvt_codec_wire_bytes(1000, 0) == 4000
    assert lib.hvt_codec_wire_bytes(1000, 1) == 2000
    for cid in (2, 3):
        assert lib.hvt_codec_wire_bytes(256, cid) == 260
        assert lib.hvt_codec_wire_bytes(512, cid) == 520
        assert lib.hvt_codec_wire_bytes(300, cid) == 260 + 4 + 44
        assert lib.hvt_codec_wire_bytes(1, cid) == 5
    # the headline ratio the r09 sweep pins: ≥3.5x for int8 on fp32
    n = 1 << 18
    assert 4 * n / lib.hvt_codec_wire_bytes(n, 2) >= 3.5


def test_codec_roundtrip_error_bounds():
    rng = np.random.RandomState(7)
    x = (rng.randn(4096).astype(np.float32)
         * np.logspace(-2, 2, 4096).astype(np.float32))
    for name, cid in CODECS.items():
        y = _roundtrip(x, cid)
        blocks = np.abs(x.reshape(-1, 256)).max(axis=1)
        err = np.abs(y - x).reshape(-1, 256).max(axis=1)
        # documented bounds: bf16 ~2^-8 relative, int8 blockmax/254,
        # fp8 (e4m3) ~1/16 relative of blockmax
        bound = {"bf16": 1 / 128, "int8": 1.01 / 254,
                 "fp8": 1 / 14}[name]
        assert (err <= blocks * bound + 1e-12).all(), name


def test_codec_roundtrip_idempotent():
    # roundtripped values lie exactly on the codec's own grid: a second
    # roundtrip is the identity — the property that makes the engine's
    # EF pre-quantization of inputs lossless on the first wire hop
    rng = np.random.RandomState(11)
    x = rng.randn(1000).astype(np.float32) * 37.5
    for cid in CODECS.values():
        y = _roundtrip(x, cid)
        np.testing.assert_array_equal(_roundtrip(y, cid), y)


def test_codec_blocks_self_contained():
    # a 300-elem stream = one full block + a 44-elem tail; each must
    # quantize independently (in-band scales) — the invariant chunked
    # pipelined decode relies on
    rng = np.random.RandomState(3)
    x = rng.randn(300).astype(np.float32)
    for cid in (2, 3):
        whole = _roundtrip(x, cid)
        np.testing.assert_array_equal(whole[:256], _roundtrip(x[:256], cid))
        np.testing.assert_array_equal(whole[256:], _roundtrip(x[256:], cid))


def test_codec_zero_and_constant_blocks_exact():
    for cid in (2, 3):
        np.testing.assert_array_equal(
            _roundtrip(np.zeros(256, np.float32), cid), np.zeros(256))
        # a constant block quantizes exactly (absmax maps onto the grid)
        c = np.full(256, 3.25, np.float32)
        np.testing.assert_array_equal(_roundtrip(c, cid), c)


def test_codec_nonfinite_saturates_without_poisoning_block():
    """An Inf element must not poison its block: the scale clamps to
    FLT_MAX, so the non-finite element saturates to a large finite
    value while its 255 finite block-mates decode ~0 — not 0·inf = NaN
    (which error feedback would then re-add forever)."""
    for cid in (2, 3):
        for bad in (np.inf, -np.inf, np.nan):
            x = np.full(256, 0.01, np.float32)
            x[7] = bad
            out = _roundtrip(x, cid)
            assert np.all(np.isfinite(out)), (cid, bad)
            # the transient stays confined to its own element
            mates = np.delete(out, 7)
            assert np.all(np.abs(mates) <= 0.02), (cid, bad, mates.max())
            if np.isinf(bad):  # Inf rides the clamped FLT_MAX scale
                assert abs(out[7]) > 1e30, (cid, bad, out[7])
            # NaN doesn't enter the absmax (max() ignores it), so it
            # saturates onto the block's own finite grid instead


def test_error_feedback_unbiases_quantizer():
    # the engine's EF recurrence, run through the real codec: with
    # residual carry the TIME-AVERAGE of quantized outputs converges to
    # the true value even for components far below the quantization
    # threshold; without it they are zeroed forever
    x = np.full(256, 0.01, np.float32)
    x[0] = 100.0  # pins the block scale at 100/127 ≈ 0.79 ≫ 0.01
    steps = 400
    acc_ef = np.zeros(256)
    r = np.zeros(256, np.float32)
    acc_plain = np.zeros(256)
    for _ in range(steps):
        comp = x + r
        q = _roundtrip(comp, 2)
        r = comp - q
        acc_ef += q
        acc_plain += _roundtrip(x, 2)
    mean_ef = acc_ef / steps
    mean_plain = acc_plain / steps
    # plain quantization: the small entries round to 0 every step
    assert mean_plain[1] == 0.0
    # EF: the running mean recovers them within a few quanta / steps
    np.testing.assert_allclose(mean_ef[1:], 0.01, rtol=0.25)
    np.testing.assert_allclose(mean_ef[0], 100.0, rtol=1e-3)


def test_engine_stats_extended_layout():
    st = native.engine_stats()
    assert st, "engine stats unavailable with a built .so"
    for key in ("wire_tx_bytes", "wire_tx_comp_bytes"):
        assert set(st[key]) == set(native.STATS_OPS)
        for v in st[key].values():
            assert v >= 0
    for key in ("cycle_hist", "wakeup_hist"):
        h = st[key]
        assert len(h["buckets"]) == native.STATS_LAT_BUCKETS + 1
        # count and buckets are copied non-atomically while a live
        # engine may be observing → allow a few in-flight observations
        assert abs(h["count"] - sum(h["buckets"])) <= 4
        assert h["sum_ns"] >= 0


def test_event_kinds_include_wakeup():
    assert native.EVENT_KINDS[10] == "WAKEUP"


# ------------------------------------------------------- metrics bridge


def test_histogram_set_state_bridges_buckets():
    from horovod_tpu.metrics.registry import MetricRegistry

    reg = MetricRegistry()
    h = reg.histogram("t_bridge_seconds", "t")
    n_buckets = len(h.buckets) + 1
    counts = [0] * n_buckets
    counts[0], counts[3], counts[-1] = 5, 2, 1
    h.labels().set_state(counts, 1.25, 8)
    cum, s, c = h.labels().snapshot()
    assert s == 1.25 and c == 8
    assert cum[-1] == 8 and cum[0] == 5 and cum[3] == 7
    # short input zero-fills; long input truncates
    h.labels().set_state([1], 0.5, 1)
    cum, s, c = h.labels().snapshot()
    assert cum[-1] == 1 and s == 0.5
    h.labels().set_state(list(range(n_buckets + 5)), 0.0, 0)
    cum, _, _ = h.labels().snapshot()
    assert cum[-1] == sum(range(n_buckets))


def test_poll_engine_stats_emits_new_series():
    from horovod_tpu.common.basics import poll_engine_stats
    from horovod_tpu.metrics.registry import MetricRegistry

    reg = MetricRegistry()
    poll_engine_stats(reg)
    for name in ("hvt_wire_tx_bytes_total",
                 "hvt_wire_tx_compressed_bytes_total",
                 "hvt_cycle_duration_seconds",
                 "hvt_engine_wakeup_latency_seconds",
                 "hvt_ef_residual_bytes",
                 "hvt_ef_residuals_dropped_total"):
        assert reg.get(name) is not None, f"missing series {name}"
    # the mode gauge is gone: per-codec labels on the tx counter
    # replaced it (one series per (op, codec) pair)
    assert reg.get("hvt_wire_compression_mode") is None
    labels = {tuple(sorted(lbl.items()))
              for lbl, _ in reg.get("hvt_wire_tx_bytes_total").samples()}
    for codec in native.WIRE_CODECS:
        assert (("codec", codec), ("op", "allreduce")) in labels
    # histogram bridge plumbs the engine buckets through (a live engine
    # keeps observing between the two reads, so compare with slack)
    st = native.engine_stats()
    if st:
        hist = reg.get("hvt_cycle_duration_seconds").labels()
        _, _, count = hist.snapshot()
        assert 0 <= count <= st["cycle_hist"]["count"] + 4
