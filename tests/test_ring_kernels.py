"""Process-local units for the PR-3 data-plane overhaul: ScaleBuffer
integer rounding (via the ``hvt_scale_buffer`` test entry point), the
extended ``hvt_engine_stats`` layout (wire byte counters + engine-side
latency histograms), the new C API symbols, and the bridged-histogram
``set_state`` path in the metrics registry. Gang-level behavior
(event-driven latency, pipelined-ring numerics, bf16 wire) lives in
``tests/test_data_plane.py``.
"""

import ctypes
import os

import numpy as np
import pytest

from horovod_tpu.engine import native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build", "libhvt_core.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


def _lib():
    lib = ctypes.CDLL(LIB)
    lib.hvt_scale_buffer.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                     ctypes.c_int, ctypes.c_double]
    return lib


def _scale(arr, factor):
    lib = _lib()
    dtype_id = {"int32": 4, "int64": 5, "float32": 7,
                "float64": 8}[arr.dtype.name]
    rc = lib.hvt_scale_buffer(arr.ctypes.data_as(ctypes.c_void_p),
                              len(arr), dtype_id, factor)
    assert rc == 0
    return arr


# ---------------------------------------------------------------- scale


def test_scale_int32_rounds_not_truncates():
    # 3 * 0.5 = 1.5 → 2 (truncation would give 1); half rounds away
    # from zero, matching llround
    arr = np.array([3, 5, -3, -5, 4, 0], dtype=np.int32)
    _scale(arr, 0.5)
    np.testing.assert_array_equal(arr, [2, 3, -2, -3, 2, 0])


def test_scale_int64_rounds_not_truncates():
    arr = np.array([3, -3, 10**12 + 3], dtype=np.int64)
    _scale(arr, 0.5)
    np.testing.assert_array_equal(
        arr, [2, -2, (10**12 + 3 + 1) // 2])


def test_scale_int_average_divide_unbiased():
    # averaging [1, 1] over 2 ranks: sum 2 * (1/2) = 1.0 exactly; and
    # sum 3 * (1/2) rounds to 2, not down to 1
    arr = np.array([2, 3], dtype=np.int32)
    _scale(arr, 0.5)
    np.testing.assert_array_equal(arr, [1, 2])


def test_scale_float_paths_unchanged():
    arr = np.array([1.5, -2.25, 0.0], dtype=np.float32)
    _scale(arr, 2.0)
    np.testing.assert_allclose(arr, [3.0, -4.5, 0.0])
    arr64 = np.array([1.5, -2.25], dtype=np.float64)
    _scale(arr64, -1.0)
    np.testing.assert_allclose(arr64, [-1.5, 2.25])


def test_scale_rejects_unsupported_dtype():
    lib = _lib()
    arr = np.zeros(4, dtype=np.uint8)
    rc = lib.hvt_scale_buffer(arr.ctypes.data_as(ctypes.c_void_p),
                              4, 0, 0.5)  # dtype 0 = uint8: unsupported
    assert rc == -1


# ---------------------------------------------------------------- C API


def test_new_c_api_symbols_exported():
    lib = _lib()
    for sym in ("hvt_wire_compression", "hvt_scale_buffer",
                "hvt_engine_stats", "hvt_events_drain"):
        assert getattr(lib, sym, None) is not None, f"missing {sym}"


def test_wire_compression_defaults_off():
    assert native.wire_compression() in (0, 1)
    # in the test session HVT_WIRE_COMPRESSION is not set → raw
    if not os.environ.get("HVT_WIRE_COMPRESSION"):
        assert native.wire_compression() == 0


def test_engine_stats_extended_layout():
    st = native.engine_stats()
    assert st, "engine stats unavailable with a built .so"
    for key in ("wire_tx_bytes", "wire_tx_comp_bytes"):
        assert set(st[key]) == set(native.STATS_OPS)
        for v in st[key].values():
            assert v >= 0
    for key in ("cycle_hist", "wakeup_hist"):
        h = st[key]
        assert len(h["buckets"]) == native.STATS_LAT_BUCKETS + 1
        # count and buckets are copied non-atomically while a live
        # engine may be observing → allow a few in-flight observations
        assert abs(h["count"] - sum(h["buckets"])) <= 4
        assert h["sum_ns"] >= 0


def test_event_kinds_include_wakeup():
    assert native.EVENT_KINDS[10] == "WAKEUP"


# ------------------------------------------------------- metrics bridge


def test_histogram_set_state_bridges_buckets():
    from horovod_tpu.metrics.registry import MetricRegistry

    reg = MetricRegistry()
    h = reg.histogram("t_bridge_seconds", "t")
    n_buckets = len(h.buckets) + 1
    counts = [0] * n_buckets
    counts[0], counts[3], counts[-1] = 5, 2, 1
    h.labels().set_state(counts, 1.25, 8)
    cum, s, c = h.labels().snapshot()
    assert s == 1.25 and c == 8
    assert cum[-1] == 8 and cum[0] == 5 and cum[3] == 7
    # short input zero-fills; long input truncates
    h.labels().set_state([1], 0.5, 1)
    cum, s, c = h.labels().snapshot()
    assert cum[-1] == 1 and s == 0.5
    h.labels().set_state(list(range(n_buckets + 5)), 0.0, 0)
    cum, _, _ = h.labels().snapshot()
    assert cum[-1] == sum(range(n_buckets))


def test_poll_engine_stats_emits_new_series():
    from horovod_tpu.common.basics import poll_engine_stats
    from horovod_tpu.metrics.registry import MetricRegistry

    reg = MetricRegistry()
    poll_engine_stats(reg)
    for name in ("hvt_wire_tx_bytes_total",
                 "hvt_wire_tx_compressed_bytes_total",
                 "hvt_cycle_duration_seconds",
                 "hvt_engine_wakeup_latency_seconds",
                 "hvt_wire_compression_mode"):
        assert reg.get(name) is not None, f"missing series {name}"
    # histogram bridge plumbs the engine buckets through (a live engine
    # keeps observing between the two reads, so compare with slack)
    st = native.engine_stats()
    if st:
        hist = reg.get("hvt_cycle_duration_seconds").labels()
        _, _, count = hist.snapshot()
        assert 0 <= count <= st["cycle_hist"]["count"] + 4
