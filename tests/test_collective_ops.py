"""Traced-path collective tests over an 8-device mesh — the analog of the
reference's ``test/parallel/test_tensorflow.py`` allreduce/allgather/
broadcast/alltoall suites (78 fns), executed as one SPMD program per case."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.parallel.mesh import WORLD_AXIS

N = 8


def shmap(f, mesh, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS)):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs))


def per_rank(shape=(4, 3), dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(N, *shape).astype(dtype)


# --------------------------------------------------------------------------
# allreduce
# --------------------------------------------------------------------------

def test_allreduce_average(world_mesh):
    x = per_rank()
    f = shmap(lambda t: hvt.allreduce(t[0])[None], world_mesh)
    out = np.asarray(f(x))
    expected = x.mean(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)


def test_allreduce_sum(world_mesh):
    x = per_rank(seed=1)
    f = shmap(lambda t: hvt.allreduce(t[0], op=hvt.Sum)[None], world_mesh)
    out = np.asarray(f(x))
    np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=1e-5)


def test_allreduce_average_flag(world_mesh):
    # deprecated average= flag kept for parity (torch/mpi_ops.py:85-129)
    x = per_rank(seed=2)
    f = shmap(lambda t: hvt.allreduce(t[0], average=False)[None], world_mesh)
    np.testing.assert_allclose(np.asarray(f(x))[0], x.sum(axis=0), rtol=1e-5)


def test_allreduce_min_max_product(world_mesh):
    x = per_rank(seed=3)
    for op, ref in [(hvt.Min, x.min(axis=0)), (hvt.Max, x.max(axis=0)),
                    (hvt.Product, x.prod(axis=0))]:
        f = shmap(lambda t, op=op: hvt.allreduce(t[0], op=op)[None],
                  world_mesh)
        np.testing.assert_allclose(np.asarray(f(x))[0], ref, rtol=1e-4)


def test_allreduce_prescale_postscale(world_mesh):
    # reference applies prescale before, postscale after (operations.cc:941)
    x = per_rank(seed=4)
    f = shmap(lambda t: hvt.allreduce(t[0], op=hvt.Sum, prescale_factor=2.0,
                                      postscale_factor=0.25)[None],
              world_mesh)
    np.testing.assert_allclose(np.asarray(f(x))[0],
                               0.25 * (2.0 * x).sum(axis=0), rtol=1e-5)


def test_allreduce_bfloat16(world_mesh):
    x = per_rank(dtype=np.float32, seed=5)
    xb = jnp.asarray(x, jnp.bfloat16)
    f = shmap(lambda t: hvt.allreduce(t[0], op=hvt.Sum)[None], world_mesh)
    out = np.asarray(f(xb).astype(jnp.float32))
    np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=5e-2, atol=0.3)


def test_allreduce_process_set(world_mesh):
    ps = hvt.add_process_set([0, 1, 2, 3])
    x = per_rank(seed=6)
    f = shmap(lambda t: hvt.allreduce(t[0], op=hvt.Sum,
                                      process_set=ps)[None], world_mesh)
    out = np.asarray(f(x))
    np.testing.assert_allclose(out[0], x[:4].sum(axis=0), rtol=1e-5)
    np.testing.assert_allclose(out[7], x[4:].sum(axis=0), rtol=1e-5)
    hvt.remove_process_set(ps)


def test_grouped_allreduce(world_mesh):
    x = per_rank(seed=7)
    y = per_rank(shape=(2,), seed=8)

    def step(tx, ty):
        a, b = hvt.grouped_allreduce([tx[0], ty[0]], op=hvt.Sum)
        return a[None], b[None]

    f = jax.jit(jax.shard_map(step, mesh=world_mesh,
                              in_specs=(P(WORLD_AXIS), P(WORLD_AXIS)),
                              out_specs=(P(WORLD_AXIS), P(WORLD_AXIS))))
    a, b = f(x, y)
    np.testing.assert_allclose(np.asarray(a)[0], x.sum(axis=0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(b)[0], y.sum(axis=0), rtol=1e-5)


# --------------------------------------------------------------------------
# allgather / broadcast / alltoall / reducescatter
# --------------------------------------------------------------------------

def test_allgather(world_mesh):
    x = per_rank(shape=(2, 3), seed=9)
    f = shmap(lambda t: hvt.allgather(t[0])[None], world_mesh)
    out = np.asarray(f(x))
    expected = x.reshape(N * 2, 3)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


def test_broadcast(world_mesh):
    x = per_rank(seed=10)
    for root in (0, 3, 7):
        f = shmap(lambda t, root=root:
                  hvt.broadcast(t[0], root_rank=root)[None], world_mesh)
        out = np.asarray(f(x))
        for r in range(N):
            np.testing.assert_allclose(out[r], x[root], rtol=1e-6)


def test_alltoall(world_mesh):
    x = per_rank(shape=(N, 5), seed=11)  # dim0 divisible by N
    f = shmap(lambda t: hvt.alltoall(t[0])[None], world_mesh)
    out = np.asarray(f(x))
    # after alltoall, rank r holds slice r of every rank, concatenated
    for r in range(N):
        expected = np.concatenate([x[s, r:r + 1] for s in range(N)], axis=0)
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


def test_alltoall_uneven_splits_rejected_in_trace(world_mesh):
    x = per_rank(shape=(N,), seed=12)
    with pytest.raises(ValueError, match="uneven"):
        f = shmap(lambda t: hvt.alltoall(t[0], splits=[1] * N)[None],
                  world_mesh)
        f(x)


def test_reducescatter(world_mesh):
    x = per_rank(shape=(N * 2, 3), seed=13)
    f = shmap(lambda t: hvt.reducescatter(t[0], op=hvt.Sum)[None],
              world_mesh)
    out = np.asarray(f(x))
    summed = x.sum(axis=0)  # [N*2, 3]
    for r in range(N):
        np.testing.assert_allclose(out[r], summed[r * 2:(r + 1) * 2],
                                   rtol=1e-5)


def test_reducescatter_average(world_mesh):
    x = per_rank(shape=(N, 3), seed=14)
    f = shmap(lambda t: hvt.reducescatter(t[0])[None], world_mesh)
    out = np.asarray(f(x))
    mean = x.mean(axis=0)
    np.testing.assert_allclose(out[2], mean[2:3], rtol=1e-5)


def test_reducescatter_process_set_average(world_mesh):
    # regression: Average over a 4-rank set must divide by 4, not world (8)
    ps = hvt.add_process_set([0, 1, 2, 3])
    x = per_rank(shape=(8, 2), seed=20)
    f = shmap(lambda t: hvt.reducescatter(t[0], process_set=ps)[None],
              world_mesh)
    out = np.asarray(f(x))
    set_mean = x[:4].mean(axis=0)  # [8, 2]
    np.testing.assert_allclose(out[1], set_mean[2:4], rtol=1e-5)
    hvt.remove_process_set(ps)


def test_alltoall_process_set(world_mesh):
    # regression: alltoall must exchange only within the set
    ps = hvt.add_process_set([0, 1, 2, 3])
    x = per_rank(shape=(4, 3), seed=21)
    f = shmap(lambda t: hvt.alltoall(t[0], process_set=ps)[None], world_mesh)
    out = np.asarray(f(x))
    for r in range(4):
        expected = np.stack([x[s, r] for s in range(4)])
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)
    hvt.remove_process_set(ps)


def test_uneven_process_set_rejected_for_shape_changing_ops(world_mesh):
    # regression: uneven set+complement used to crash XLA lowering with
    # 'Invalid replica id -1'; must raise an actionable error instead
    ps = hvt.add_process_set([0, 1, 2])
    x = per_rank(shape=(6, 2), seed=22)
    for fn in (lambda t: hvt.allgather(t, process_set=ps),
               lambda t: hvt.reducescatter(t, process_set=ps),
               lambda t: hvt.alltoall(t, process_set=ps)):
        with pytest.raises(ValueError, match="equal size"):
            shmap(lambda t, fn=fn: fn(t[0])[None], world_mesh)(x)
    hvt.remove_process_set(ps)


# --------------------------------------------------------------------------
# eager path (single process)
# --------------------------------------------------------------------------

def test_eager_allreduce_identity():
    # one contribution per process; single-process job reduces to itself
    # (matches a world-size-1 reference job)
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = hvt.allreduce(x)
    np.testing.assert_allclose(np.asarray(out), x)


def test_eager_allreduce_scaling():
    x = np.ones((4,), np.float32)
    out = hvt.allreduce(x, op=hvt.Sum, prescale_factor=3.0,
                        postscale_factor=0.5)
    np.testing.assert_allclose(np.asarray(out), 1.5 * x)


def test_eager_async_handles():
    x = np.ones((4,), np.float32)
    h = hvt.allreduce_async(x, op=hvt.Sum)
    assert hvt.poll(h)
    np.testing.assert_allclose(np.asarray(hvt.synchronize(h)), x)


def test_eager_allgather_broadcast_alltoall():
    x = np.arange(4, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(hvt.allgather(x)), x)
    np.testing.assert_allclose(np.asarray(hvt.broadcast(x, root_rank=0)), x)
    out, splits = hvt.alltoall(x)
    np.testing.assert_allclose(np.asarray(out), x)
    assert list(splits) == [4]


def test_eager_jax_array_roundtrip():
    x = jnp.ones((3,))
    out = hvt.allreduce(x)
    assert isinstance(out, jax.Array)


def test_eager_join_barrier():
    assert hvt.join() == 0
    hvt.barrier()


def test_grouped_allreduce_eager():
    xs = [np.ones((2,), np.float32), np.full((3,), 2.0, np.float32)]
    out = hvt.grouped_allreduce(xs, op=hvt.Sum)
    np.testing.assert_allclose(np.asarray(out[0]), xs[0])
    np.testing.assert_allclose(np.asarray(out[1]), xs[1])
