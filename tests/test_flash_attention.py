"""Pallas flash attention vs. dense reference — forward and gradients.

Runs in interpret mode on the CPU test platform; the same kernels compile
for TPU (the driver's bench path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.flash_attention import flash_attention


def _dense(q, k, v, causal, scale=None):
    d = q.shape[-1]
    scale = scale or d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = q.shape[1]
        pos = jnp.arange(s)
        scores = jnp.where((pos[None, :] <= pos[:, None])[None, None],
                           scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _qkv(b=2, s=128, h=2, d=32, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32),
                             dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = _dense(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_forward_uneven_blocks():
    q, k, v = _qkv(s=96)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = _dense(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(s=64)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o * jnp.cos(o))

    def loss_dense(q, k, v):
        o = _dense(q, k, v, causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_bf16_forward():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = _dense(q.astype(jnp.float32), k.astype(jnp.float32),
                 v.astype(jnp.float32), True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_jit_compiles_once():
    q, k, v = _qkv(s=64)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    o1 = f(q, k, v)
    o2 = f(q * 1.0, k, v)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-6)


def test_lse_output_matches_dense_logsumexp():
    from horovod_tpu.ops.flash_attention import flash_attention_with_lse

    q, k, v = _qkv(s=64)
    b, s, h, d = q.shape
    o, lse = flash_attention_with_lse(q, k, v, causal=True)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * d ** -0.5
    pos = jnp.arange(s)
    mask = (pos[None, :] <= pos[:, None])[None, None]
    scores = jnp.where(mask, scores, -1e30)
    ref_lse = jax.scipy.special.logsumexp(scores, axis=-1)  # [B,H,S]
    np.testing.assert_allclose(np.asarray(lse),
                               np.asarray(ref_lse.transpose(0, 2, 1)),
                               rtol=1e-5, atol=1e-5)


def test_lse_gradient_flows():
    """The lse output carries its own gradient (delta := delta − dlse in
    the backward kernels): a loss on lse alone must match the autodiff
    gradient of the dense logsumexp."""
    from horovod_tpu.ops.flash_attention import flash_attention_with_lse

    q, k, v = _qkv(s=32)
    b, s, h, d = q.shape

    def loss_flash(q, k, v):
        _, lse = flash_attention_with_lse(q, k, v, causal=True)
        return (lse ** 2).mean()

    def loss_dense(q, k, v):
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * d ** -0.5
        pos = jnp.arange(s)
        mask = (pos[None, :] <= pos[:, None])[None, None]
        scores = jnp.where(mask, scores, -1e30)
        lse = jax.scipy.special.logsumexp(scores, axis=-1)
        return (lse.transpose(0, 2, 1) ** 2).mean()

    gf = jax.grad(loss_flash, argnums=(0, 1))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_combined_o_and_lse_gradient():
    """Joint cotangents on (o, lse) — the exact pattern the ring combine
    produces — against the dense computation."""
    from horovod_tpu.ops.flash_attention import flash_attention_with_lse

    q, k, v = _qkv(s=32)
    b, s, h, d = q.shape

    def loss_flash(q, k, v):
        o, lse = flash_attention_with_lse(q, k, v, causal=False)
        return (o.astype(jnp.float32) ** 2).mean() + (lse ** 2).mean()

    def loss_dense(q, k, v):
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * d ** -0.5
        p = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                       preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(scores, axis=-1)
        return ((o ** 2).mean()
                + (lse.transpose(0, 2, 1) ** 2).mean())

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_seq_tile_divisibility_invariants():
    """Round-4 review pin: the streamed tile must divide the sequence
    AND be a multiple of both block sizes — the kernels walk
    ``tile // block`` sub-blocks, so a remainder would silently drop
    sequence positions (wrong results, no error)."""
    from horovod_tpu.ops.flash_attention import _seq_tile

    for s, bq, bk in [(768, 384, 256), (1024, 128, 128),
                      (8192, 128, 128), (384, 96, 128), (256, 256, 128),
                      (6144, 128, 512)]:
        t = _seq_tile(s, bq, bk)
        assert s % t == 0 and t % bq == 0 and t % bk == 0, (s, bq, bk, t)


def test_seq_tile_cap_bounds_the_dkv_tile():
    """The dkv backward streams Q AND dO tiles together and blows the
    16 MB scoped-VMEM limit one tile size earlier than fwd/dq (measured
    r4, v5-lite): a user-requested HVT_FLASH_SEQ_TILE=8192 must degrade
    only dkv, to _DKV_TILE_CAP, while still satisfying the
    divisibility invariants."""
    import os

    from horovod_tpu.ops.flash_attention import _DKV_TILE_CAP, _seq_tile

    os.environ["HVT_FLASH_SEQ_TILE"] = "8192"
    try:
        full = _seq_tile(8192, 128, 128)
        capped = _seq_tile(8192, 128, 128, cap=_DKV_TILE_CAP)
        assert full == 8192
        assert capped == _DKV_TILE_CAP == 4096
        assert 8192 % capped == 0 and capped % 128 == 0
        # cap interacts with odd block sizes without breaking invariants
        t = _seq_tile(6144, 128, 512, cap=4096)
        assert t <= 4096 and 6144 % t == 0 and t % 512 == 0
    finally:
        del os.environ["HVT_FLASH_SEQ_TILE"]


def test_flash_grads_match_dense_when_fwd_and_dkv_tiles_differ(
        monkeypatch):
    """Gradient correctness when the fwd/dq streaming tile differs from
    the capped dkv tile (the seq-8192 + HVT_FLASH_SEQ_TILE=8192 shape,
    shrunk: fwd tile 512, dkv capped at 256)."""
    from horovod_tpu.ops import flash_attention as fa

    monkeypatch.setenv("HVT_FLASH_SEQ_TILE", "512")
    monkeypatch.setattr(fa, "_DKV_TILE_CAP", 256)
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.randn(1, 512, 2, 32), jnp.float32)
    k = jnp.asarray(rs.randn(1, 512, 2, 32), jnp.float32)
    v = jnp.asarray(rs.randn(1, 512, 2, 32), jnp.float32)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True).sum()

    def loss_dense(q, k, v):
        return _dense(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-2)


def test_flash_multi_tile_matches_dense_768_mixed_blocks():
    """The review's concrete miss case: s=768, block_q=384, block_k=256
    forces a tile that is a multiple of both; fwd AND grads must match
    the dense reference (pre-fix, dq dropped K positions 256..383)."""
    import os

    os.environ["HVT_FLASH_SEQ_TILE"] = "256"  # force multi-tile paths
    try:
        rs = np.random.RandomState(3)
        q = jnp.asarray(rs.randn(1, 768, 2, 32), jnp.float32)
        k = jnp.asarray(rs.randn(1, 768, 2, 32), jnp.float32)
        v = jnp.asarray(rs.randn(1, 768, 2, 32), jnp.float32)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, causal=True,
                                   block_q=384, block_k=256).sum()

        def loss_dense(q, k, v):
            return _dense(q, k, v, causal=True).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-2, atol=2e-2)
    finally:
        del os.environ["HVT_FLASH_SEQ_TILE"]


@pytest.mark.parametrize("h,h_kv", [(4, 2), (4, 1), (6, 3)])
def test_flash_gqa_matches_dense_repeat(h, h_kv):
    """Grouped-query attention: the kernel reads shared K/V heads
    zero-copy (index-map aliasing); output AND all grads — including
    dk/dv through the per-query-head group-sum — must equal dense
    attention over repeat-expanded K/V."""
    rs = np.random.RandomState(7)
    S, D = 256, 16
    q = jnp.asarray(rs.randn(2, S, h, D), jnp.float32)
    k = jnp.asarray(rs.randn(2, S, h_kv, D), jnp.float32)
    v = jnp.asarray(rs.randn(2, S, h_kv, D), jnp.float32)
    g = h // h_kv

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True) ** 2).sum()

    def loss_dense(q, k, v):
        kr = jnp.repeat(k, g, axis=-2)
        vr = jnp.repeat(v, g, axis=-2)
        return (_dense(q, kr, vr, causal=True) ** 2).sum()

    lf, gf = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    ld, gd = jax.value_and_grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-4)
    for a, b in zip(gf, gd):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_flash_gqa_rejects_indivisible_heads():
    q = jnp.zeros((1, 128, 4, 8), jnp.float32)
    kv = jnp.zeros((1, 128, 3, 8), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, kv, kv)
