"""Multi-process COMPILED-path validation (VERDICT r3 missing #2).

Everything else in the suite exercises the compiled path single-process
(virtual multi-device meshes); the reference's core scenario is N
*processes*, one per accelerator, initialized per process
(reference ``horovod/common/basics.py:33-65``). Here ``hvtrun --backend
jax`` launches 2 real CPU processes; ``hvt.init()`` joins them into one
JAX cluster via ``jax.distributed.initialize``
(``common/basics.py:87``), and a jit-compiled training step runs over a
mesh spanning BOTH processes — multi-controller SPMD, the exact
architecture of a real multi-host TPU pod, with XLA inserting the
gradient psum across the process boundary. Each worker asserts parity
with a numpy computation of the full global batch.
"""

import os

from tests.test_engine_integration import run_workers


def test_jax_distributed_jit_train_step_2proc():
    out = run_workers("""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        # hvt.init() already ran jax.distributed.initialize: the two
        # processes now form one cluster with one global device list
        assert jax.process_count() == 2, jax.process_count()
        devs = jax.devices()
        assert len(devs) == 2, devs

        mesh = Mesh(np.array(devs), ("dp",))
        batch_sh = NamedSharding(mesh, P("dp"))
        repl_sh = NamedSharding(mesh, P())

        # deterministic global batch; each process hosts its half
        GB, D = 8, 4
        rs = np.random.RandomState(7)
        X = rs.randn(GB, D).astype(np.float32)
        y = rs.randn(GB).astype(np.float32)
        w0 = rs.randn(D).astype(np.float32)

        half = GB // n
        Xg = jax.make_array_from_process_local_data(
            batch_sh, X[r * half:(r + 1) * half], (GB, D))
        yg = jax.make_array_from_process_local_data(
            batch_sh, y[r * half:(r + 1) * half], (GB,))
        wg = jax.device_put(jnp.asarray(w0), repl_sh)

        @jax.jit
        def step(w, Xb, yb):
            def loss_fn(w):
                return jnp.mean((Xb @ w - yb) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(w)
            # XLA's autodiff of the batch-sharded mean inserts the
            # cross-PROCESS psum here — the compiled analog of the
            # reference's per-gradient allreduce
            return w - 0.1 * g, loss, g

        w1, loss, g = step(wg, Xg, yg)

        # numpy ground truth on the full global batch
        resid = X @ w0 - y
        exp_loss = float(np.mean(resid ** 2))
        exp_g = 2.0 / GB * (X.T @ resid)
        np.testing.assert_allclose(float(loss), exp_loss, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g), exp_g, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(w1), w0 - 0.1 * exp_g,
                                   rtol=1e-4)

        # second step on the updated params: the cluster survives
        # repeated dispatch (compiled executable reuse across processes)
        w2, loss2, _ = step(w1, Xg, yg)
        assert float(loss2) < float(loss)
        print(f"JIT-2PROC-OK loss {float(loss):.6f}", flush=True)
    """, launcher_args=("--backend", "jax"))
    assert out.count("JIT-2PROC-OK") == 2, out[-2000:]


def test_jax_distributed_optimizer_parity_2proc():
    """hvt's DistributedOptimizer on the pjit path (axis_name=None: XLA
    already summed the grads) across 2 real processes must match a
    single-process optax run on the full batch."""
    out = run_workers("""
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        import horovod_tpu.jax as hvt_jax

        devs = jax.devices()
        mesh = Mesh(np.array(devs), ("dp",))
        batch_sh = NamedSharding(mesh, P("dp"))
        repl_sh = NamedSharding(mesh, P())

        GB, D = 8, 3
        rs = np.random.RandomState(11)
        X = rs.randn(GB, D).astype(np.float32)
        y = rs.randn(GB).astype(np.float32)
        w0 = rs.randn(D).astype(np.float32)

        half = GB // n
        Xg = jax.make_array_from_process_local_data(
            batch_sh, X[r * half:(r + 1) * half], (GB, D))
        yg = jax.make_array_from_process_local_data(
            batch_sh, y[r * half:(r + 1) * half], (GB,))

        opt = hvt_jax.DistributedOptimizer(optax.sgd(0.05),
                                           axis_name=None)
        params = jax.device_put({"w": jnp.asarray(w0)}, repl_sh)
        state = jax.jit(opt.init)(params)

        @jax.jit
        def step(params, state, Xb, yb):
            def loss_fn(p):
                return jnp.mean((Xb @ p["w"] - yb) ** 2)
            g = jax.grad(loss_fn)(params)
            updates, state2 = opt.update(g, state, params)
            return optax.apply_updates(params, updates), state2

        for _ in range(3):
            params, state = step(params, state, Xg, yg)

        # single-process reference: plain optax on the full batch
        ref_opt = optax.sgd(0.05)
        ref_p = {"w": jnp.asarray(w0)}
        ref_s = ref_opt.init(ref_p)
        for _ in range(3):
            g = jax.grad(
                lambda p: jnp.mean((jnp.asarray(X) @ p["w"]
                                    - jnp.asarray(y)) ** 2))(ref_p)
            u, ref_s = ref_opt.update(g, ref_s, ref_p)
            ref_p = optax.apply_updates(ref_p, u)

        np.testing.assert_allclose(np.asarray(params["w"]),
                                   np.asarray(ref_p["w"]), rtol=1e-4)
        print("OPT-2PROC-OK", flush=True)
    """, launcher_args=("--backend", "jax"))
    assert out.count("OPT-2PROC-OK") == 2, out[-2000:]
