"""Adasum numerical tests (reference ``test/parallel/test_adasum_pytorch.py``
/ ``test_adasum_tensorflow.py`` check the VHDD math against a host-side
model; same approach here)."""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.parallel.mesh import WORLD_AXIS

N = 8


def np_adasum_pair(a, b):
    dot = float((a * b).sum())
    asq = float((a * a).sum())
    bsq = float((b * b).sum())
    ca = 1.0 - dot / (2 * asq) if asq > 0 else 1.0
    cb = 1.0 - dot / (2 * bsq) if bsq > 0 else 1.0
    return ca * a + cb * b


def np_adasum(vs):
    """Host model of the recursive pairing: level k pairs rank i with
    i^2^k (reference ``adasum.h:194-336`` recursion order)."""
    vs = [v.astype(np.float64) for v in vs]
    n = len(vs)
    stride = 1
    while stride < n:
        out = list(vs)
        for base in range(0, n, 2 * stride):
            for off in range(stride):
                i, j = base + off, base + off + stride
                c = np_adasum_pair(vs[i], vs[j])
                out[i] = c
                out[j] = c
        vs = out
        stride *= 2
    return vs[0]


def _run_adasum(x, mesh):
    f = jax.jit(jax.shard_map(
        lambda t: hvt.allreduce(t[0], op=hvt.Adasum)[None],
        mesh=mesh, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS)))
    return np.asarray(f(x))


def test_adasum_pairwise_identical_grads(world_mesh):
    # identical gradients: adasum(a, a) = a (scale invariance sanity)
    x = np.broadcast_to(np.linspace(1, 2, 6, dtype=np.float32),
                        (N, 6)).copy()
    out = _run_adasum(x, world_mesh)
    for r in range(N):
        np.testing.assert_allclose(out[r], x[0], rtol=1e-5)


def test_adasum_orthogonal_grads(world_mesh):
    # orthogonal gradients: dot = 0 → adasum degenerates to a + b
    x = np.zeros((N, N), np.float32)
    for r in range(N):
        x[r, r] = 1.0
    out = _run_adasum(x, world_mesh)
    np.testing.assert_allclose(out[0], np.ones(N), rtol=1e-5)


def test_adasum_matches_host_model(world_mesh):
    rng = np.random.RandomState(42)
    x = rng.randn(N, 5).astype(np.float32)
    out = _run_adasum(x, world_mesh)
    expected = np_adasum(list(x))
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-4, atol=1e-5)


def test_adasum_zero_grads(world_mesh):
    # all-zero input must not NaN (reference guards norm>0, adasum.h:372)
    x = np.zeros((N, 4), np.float32)
    out = _run_adasum(x, world_mesh)
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out, 0)


def test_pairwise_helper():
    from horovod_tpu.ops.adasum import pairwise_adasum

    a = np.array([1.0, 0.0], np.float32)
    b = np.array([0.0, 1.0], np.float32)
    np.testing.assert_allclose(np.asarray(pairwise_adasum(a, b)),
                               [1.0, 1.0], rtol=1e-6)


def test_adasum_process_subset(world_mesh):
    """Adasum over a strict process subset (traced path): members combine
    within the set; complement shards pass through unchanged."""
    sub = hvt.add_process_set([0, 1, 2, 3])
    rng = np.random.RandomState(7)
    x = rng.randn(N, 5).astype(np.float32)
    f = jax.jit(jax.shard_map(
        lambda t: hvt.allreduce(t[0], op=hvt.Adasum,
                                process_set=sub)[None],
        mesh=world_mesh, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS)))
    out = np.asarray(f(x))
    expected = np_adasum(list(x[:4]))
    for r in range(4):
        np.testing.assert_allclose(out[r], expected, rtol=1e-4, atol=1e-5)
    # complement untouched
    np.testing.assert_allclose(out[4:], x[4:], rtol=1e-6)
    hvt.remove_process_set(sub)


def np_adasum_start_level(vs, start_level):
    """Host model with the GPU start_level composition: levels below
    start_level average, the rest adasum-combine (adasum.h:177-183)."""
    vs = [v.astype(np.float64) for v in vs]
    n = len(vs)
    stride = 1
    while stride < n:
        out = list(vs)
        for base in range(0, n, 2 * stride):
            for off in range(stride):
                i, j = base + off, base + off + stride
                if stride < start_level:
                    c = 0.5 * (vs[i] + vs[j])
                else:
                    c = np_adasum_pair(vs[i], vs[j])
                out[i] = c
                out[j] = c
        vs = out
        stride *= 2
    return vs[0]


def test_adasum_start_level_hierarchical(world_mesh):
    """start_level=4 (e.g. 4 chips per host on an 8-chip world): local
    levels average, only the cross-host level runs the adasum combine."""
    from horovod_tpu.ops.adasum import adasum_reduce

    rng = np.random.RandomState(13)
    x = rng.randn(N, 6).astype(np.float32)
    f = jax.jit(jax.shard_map(
        lambda t: adasum_reduce(t[0], WORLD_AXIS, start_level=4)[None],
        mesh=world_mesh, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS)))
    out = np.asarray(f(x))
    expected = np_adasum_start_level(list(x), 4)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-4, atol=1e-5)
    # sanity: start_level >= n degenerates to the plain mean
    g = jax.jit(jax.shard_map(
        lambda t: adasum_reduce(t[0], WORLD_AXIS, start_level=N)[None],
        mesh=world_mesh, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS)))
    np.testing.assert_allclose(np.asarray(g(x))[0], x.mean(0),
                               rtol=1e-4, atol=1e-5)


def test_adasum_subset_with_start_level(world_mesh):
    """Process subset + start_level compose: the 4-member set averages at
    level 0 then adasum-combines at level 1; complement untouched."""
    from horovod_tpu.ops.adasum import adasum_reduce

    sub_groups = [[0, 1, 2, 3], [4], [5], [6], [7]]
    rng = np.random.RandomState(21)
    x = rng.randn(N, 4).astype(np.float32)
    f = jax.jit(jax.shard_map(
        lambda t: adasum_reduce(t[0], WORLD_AXIS,
                                axis_index_groups=sub_groups,
                                start_level=2)[None],
        mesh=world_mesh, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS)))
    out = np.asarray(f(x))
    expected = np_adasum_start_level(list(x[:4]), 2)
    for r in range(4):
        np.testing.assert_allclose(out[r], expected, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out[4:], x[4:], rtol=1e-6)


def test_tf_adasum_delta_optimizer_matches_torch_2proc():
    """The TF Adasum delta-optimizer (tensorflow/__init__.py
    _DistributedAdasumOptimizer, reference tensorflow/__init__.py:471-567)
    must produce bit-comparable results to the torch delta optimizer
    (torch/optimizer.py:248) on the same arrays: same start, same
    per-rank gradients, same wrapped-SGD step, both deltas combined by
    the engine's Adasum operator."""
    import importlib.util

    import pytest

    if importlib.util.find_spec("tensorflow") is None:
        pytest.skip("tensorflow not installed")
    from tests.test_engine_integration import run_workers

    out = run_workers("""
        import torch
        import horovod_tpu.torch as hvt_torch
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvt_tf

        start = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        grad = (np.array([1.0, 0.0, 2.0, -1.0], np.float32) if r == 0
                else np.array([0.5, 1.0, -1.0, 2.0], np.float32))

        p = torch.nn.Parameter(torch.tensor(start))
        topt = hvt_torch.DistributedOptimizer(
            torch.optim.SGD([p], lr=0.5), op=hvt_torch.Adasum)
        p.grad = torch.tensor(grad)
        topt.step()
        torch_result = p.detach().numpy()

        v = tf.Variable(start)
        fopt = hvt_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.5), op=hvt_tf.Adasum)
        fopt.apply_gradients([(tf.constant(grad), v)])
        tf_result = v.numpy()

        np.testing.assert_allclose(tf_result, torch_result,
                                   rtol=1e-5, atol=1e-6)
        # both moved off the local-only update (the combine did run)
        local_only = start - 0.5 * grad
        assert not np.allclose(tf_result, local_only)
        print(f"TF-TORCH-ADASUM-OK-{r}", flush=True)
    """, timeout=240)
    assert "TF-TORCH-ADASUM-OK-0" in out and "TF-TORCH-ADASUM-OK-1" in out
