"""Multi-process tests of the native TF custom-op path — the analog of
reference ``test/parallel/test_tensorflow.py`` (allreduce/allgather/
broadcast/alltoall across ranks, grad correctness, error cases) run over
real processes + the C++ engine, exercising eager AND ``tf.function``
graph mode (the reference's custom ops are graph ops;
``tensorflow/mpi_ops.cc:374``)."""

import os

import pytest

from tests.test_engine_integration import REPO, run_workers

TF_OPS_LIB = os.path.join(REPO, "horovod_tpu", "csrc", "build",
                          "libhvt_tf_ops.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(TF_OPS_LIB),
    reason="TF op library not built (make -C horovod_tpu/csrc tf_ops)")


def run_tf_workers(body, np=2, timeout=240, **kw):
    import textwrap

    env = dict(kw.pop("extra_env", None) or {})
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    return run_workers(
        "import tensorflow as tf\nimport horovod_tpu.tensorflow as hvd\n"
        "assert hvd._native() is not None, 'native op path not active'\n"
        + textwrap.dedent(body), np=np, timeout=timeout, extra_env=env,
        **kw)


def test_native_allreduce_eager_average():
    run_tf_workers("""
        x = tf.fill([4], float(r + 1))
        res = hvd.allreduce(x, name="t")
        assert isinstance(res, tf.Tensor)
        np.testing.assert_allclose(res.numpy(), (1 + n) / 2.0)
    """)


def test_native_allreduce_inside_tf_function():
    # collectives traced INTO the graph — impossible on the numpy bridge
    run_tf_workers("""
        @tf.function
        def step(x):
            return hvd.allreduce(x, name="graph.t", average=False) * 2.0

        out = step(tf.fill([3], float(r + 1)))
        np.testing.assert_allclose(out.numpy(), 2.0 * sum(
            i + 1 for i in range(n)))
        # second call reuses the traced graph (same tensor name, engine
        # cache hit path)
        out2 = step(tf.fill([3], float(r + 1)))
        np.testing.assert_allclose(out2.numpy(), out.numpy())
    """)


def test_native_allreduce_dtypes():
    run_tf_workers("""
        for dt in (tf.float32, tf.float64, tf.int32, tf.int64,
                   tf.float16, tf.bfloat16):
            x = tf.cast(tf.range(6) + r, dt)
            res = hvd.allreduce(x, name=f"d{dt.name}", average=False)
            expected = sum((np.arange(6) + i) for i in range(n))
            np.testing.assert_allclose(
                tf.cast(res, tf.float64).numpy(), expected)
    """)


def test_native_allgather_uneven_rows():
    run_tf_workers("""
        rows = r + 1
        res = hvd.allgather(tf.fill([rows, 3], float(r)), name="ag")
        assert res.shape == (n * (n + 1) // 2, 3), res.shape
        np.testing.assert_allclose(res.numpy()[0], 0.0)
        np.testing.assert_allclose(res.numpy()[1:], 1.0)
    """)


def test_native_broadcast_and_alltoall():
    run_tf_workers("""
        b = hvd.broadcast(tf.fill([4], float(r + 7)), root_rank=1,
                          name="bc")
        np.testing.assert_allclose(b.numpy(), 8.0)

        # default (even) splits: row d of each rank's payload goes to
        # rank d, so rank r receives [r_row from rank 0, r_row from 1, ..]
        ev, evr = hvd.alltoall(
            tf.constant([[10.0 * r + d] for d in range(n)]),
            name="a2a.even")
        np.testing.assert_allclose(
            ev.numpy().ravel(), [10.0 * s + r for s in range(n)])
        assert list(evr.numpy()) == [1] * n

        payload = tf.constant([[float(r)], [float(r) + 10.0],
                               [float(r) + 10.0]])
        out, recv = hvd.alltoall(payload, splits=[1, 2], name="a2a")
        if r == 0:
            np.testing.assert_allclose(out.numpy().ravel(), [0.0, 1.0])
            np.testing.assert_allclose(recv.numpy(), [1, 1])
        else:
            np.testing.assert_allclose(out.numpy().ravel(),
                                       [10.0, 10.0, 11.0, 11.0])
            np.testing.assert_allclose(recv.numpy(), [2, 2])
    """)


def test_native_tape_gradient_is_allreduced():
    # gradient of allreduce = allreduce of gradient (registered grad fn,
    # reference tensorflow/mpi_ops.py:116)
    run_tf_workers("""
        v = tf.Variable(tf.fill([3], float(r + 1)))
        with tf.GradientTape() as tape:
            y = hvd.allreduce(v, name="g", average=False)
            loss = tf.reduce_sum(y) * (r + 1.0)
        g = tape.gradient(loss, v)
        # upstream grad on rank i is (i+1); summed across ranks
        np.testing.assert_allclose(g.numpy(), float(sum(
            i + 1 for i in range(n))))
    """)


def test_native_distributed_gradient_tape_in_tf_function():
    run_tf_workers("""
        v = tf.Variable([float(r + 1), 2.0 * (r + 1)])

        @tf.function
        def step():
            with tf.GradientTape() as tape:
                loss = tf.reduce_sum(v * v)
            dtape = hvd.DistributedGradientTape(tape)
            return dtape.gradient(loss, v)

        g = step()
        expected = np.mean([[2.0 * (i + 1), 4.0 * (i + 1)]
                            for i in range(n)], axis=0)
        np.testing.assert_allclose(g.numpy(), expected)
    """)


def test_native_size_rank_ops_dynamic():
    run_tf_workers("""
        assert int(hvd.size_op()) == n
        assert int(hvd.rank_op()) == r
    """)


def test_native_shape_mismatch_errors_not_hangs():
    # cross-rank shape mismatch → per-tensor ERROR response surfaced as a
    # TF error on every rank (reference controller.cc:481-706 semantics)
    run_tf_workers("""
        x = tf.fill([3 + r], 1.0)
        try:
            hvd.allreduce(x, name="bad")
        except Exception as e:
            assert "bad" in str(e) or "mismatch" in str(e).lower(), str(e)
        else:
            raise AssertionError("mismatched allreduce did not error")
    """)


def test_native_graph_backward_passes_per_step():
    # in-graph aggregation (tf.Variables + tf.cond) composed with the
    # native allreduce: 2 accumulation passes, then one averaged update
    run_tf_workers("""
        v = tf.Variable([0.0, 0.0])
        opt = hvd.DistributedOptimizer(
            tf.keras.optimizers.SGD(1.0), backward_passes_per_step=2)

        @tf.function
        def step(g):
            return opt.apply_gradients([(g, v)])

        a1 = step(tf.constant([float(r + 1), 1.0]))
        assert not bool(a1)
        np.testing.assert_allclose(v.numpy(), 0.0)   # accumulating
        a2 = step(tf.constant([float(r + 1), 1.0]))
        assert bool(a2)
        # per-rank sum over 2 passes = 2*(r+1); averaged across ranks
        exp0 = -2.0 * np.mean([i + 1 for i in range(n)])
        np.testing.assert_allclose(v.numpy(), [exp0, -2.0], rtol=1e-6)
    """)


def test_native_process_set_allreduce_4proc():
    # subset collective over the native op path: members reduce among
    # themselves; non-members run a disjoint set concurrently
    run_tf_workers("""
        from horovod_tpu.common.process_sets import ProcessSet
        even = ProcessSet([0, 2])
        odd = ProcessSet([1, 3])
        mine = even if r % 2 == 0 else odd
        x = tf.fill([3], float(r + 1))
        res = hvd.allreduce(x, name="ps.even" if r % 2 == 0 else "ps.odd",
                            average=False, process_set=mine)
        expected = sum(i + 1 for i in mine.ranks)
        np.testing.assert_allclose(res.numpy(), float(expected))

        # unnamed eager subset collectives raise with guidance
        try:
            hvd.allreduce(x, process_set=mine)
        except ValueError as e:
            assert "name" in str(e), e
        else:
            raise AssertionError("unnamed process-set allreduce passed")
    """, np=4)


def test_native_reducescatter_2proc():
    run_tf_workers("""
        # 4 rows, 2 ranks: each keeps 2 reduced rows
        x = tf.reshape(tf.range(8, dtype=tf.float32), [4, 2]) + float(r)
        out = hvd.reducescatter(x, name="rs")
        full = sum(np.arange(8, dtype=np.float32).reshape(4, 2) + i
                   for i in range(n))
        np.testing.assert_allclose(out.numpy(), full[r * 2:(r + 1) * 2])

        # in-graph with gradient: grad of reduce-scatter = allgather
        v = tf.Variable(tf.ones([4, 2]) * (r + 1.0))

        @tf.function
        def step():
            with tf.GradientTape() as tape:
                y = hvd.reducescatter(v, name="rs.g")
                loss = tf.reduce_sum(y) * (r + 1.0)
            return tape.gradient(loss, v)

        g = step()
        # each rank's shard contributes its owner's upstream factor
        expect = np.concatenate([np.full((2, 2), float(i + 1))
                                 for i in range(n)])
        np.testing.assert_allclose(g.numpy(), expect)

        # AVERAGE: forward divides by n, so must the gradient
        from horovod_tpu.ops import collective_ops as C
        w = tf.Variable(tf.ones([4, 2]))
        with tf.GradientTape() as tape:
            y = hvd.reducescatter(w, name="rs.avg", op=C.Average)
            loss = tf.reduce_sum(y)
        ga = tape.gradient(loss, w)
        np.testing.assert_allclose(ga.numpy(), 1.0 / n)
    """)


def test_tf_join_uneven_steps_2proc():
    # reference HorovodJoinOp semantics: rank 1 joins early; rank 0's
    # later collectives proceed with zero stand-ins
    run_tf_workers("""
        steps = 3 if r == 0 else 1
        for i in range(steps):
            res = hvd.allreduce(tf.ones([2]), name=f"j{i}", average=False)
            if i < 1:
                np.testing.assert_allclose(res.numpy(), float(n))
            else:
                np.testing.assert_allclose(res.numpy(), 1.0)
        last = hvd.join()
        assert last == 0, last  # rank 0 ran more steps → joined last
    """)


def test_native_alltoall_gradient_2proc():
    # grad of alltoall routes each received block's gradient back to its
    # sender via the forward's negotiated received_splits (reference
    # tensorflow/mpi_ops.py alltoall gradient)
    run_tf_workers("""
        splits = [1, 2] if r == 0 else [2, 1]
        v = tf.Variable(
            tf.reshape(tf.range(3, dtype=tf.float32) + 10.0 * r, [3, 1]))

        @tf.function
        def step():
            with tf.GradientTape() as tape:
                out, recv = hvd.alltoall(v, splits=splits, name="a2a.g")
                loss = tf.reduce_sum(out) * (r + 1.0)
            return tape.gradient(loss, v)

        g = step()
        # rank 0 kept row 0 (factor 1), sent rows 1-2 to rank 1 (factor 2)
        # rank 1 sent rows 0-1 to rank 0 (factor 1), kept row 2 (factor 2)
        expect = [[1.0], [2.0], [2.0]] if r == 0 else [[1.0], [1.0], [2.0]]
        np.testing.assert_allclose(g.numpy(), expect)
    """)


def test_native_zero_width_rows_keep_true_row_count():
    # trailing dim 0 → row_bytes 0; dim 0 must come from the negotiated
    # splits, not result_bytes/row_bytes
    run_tf_workers("""
        rows = r + 1
        res = hvd.allgather(tf.zeros([rows, 0]), name="agz")
        assert tuple(res.shape) == (n * (n + 1) // 2, 0), res.shape

        out, recv = hvd.alltoall(tf.zeros([n, 0]), name="a2az")
        assert tuple(out.shape) == (n, 0), out.shape
        assert list(recv.numpy()) == [1] * n
    """)


def test_native_local_ops_and_grouped_allreduce():
    run_tf_workers("""
        assert int(hvd.local_size_op()) == n     # single host: local == world
        assert int(hvd.local_rank_op()) == r
        outs = hvd.grouped_allreduce(
            [tf.fill([2], float(r + 1)), tf.fill([3], float(2 * (r + 1)))],
            name="ga", average=False)
        s = sum(i + 1 for i in range(n))
        np.testing.assert_allclose(outs[0].numpy(), float(s))
        np.testing.assert_allclose(outs[1].numpy(), float(2 * s))
    """)


def test_native_two_unnamed_grouped_allreduces_in_one_tf_function():
    # two name=None groups traced into ONE step must land on distinct
    # per-node names (a baked default would collide and mis-pair)
    run_tf_workers("""
        @tf.function
        def step(a, b):
            g1 = hvd.grouped_allreduce([a], average=False)
            g2 = hvd.grouped_allreduce([b], average=False)
            return g1[0], g2[0]

        o1, o2 = step(tf.fill([2], float(r + 1)),
                      tf.fill([2], float(100 * (r + 1))))
        s = sum(i + 1 for i in range(n))
        np.testing.assert_allclose(o1.numpy(), float(s))
        np.testing.assert_allclose(o2.numpy(), float(100 * s))
    """)
