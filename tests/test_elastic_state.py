"""Elastic state machine tests (reference ``test/single/test_torch_elastic.py``
TorchState semantics, ``common/elastic.py`` commit/restore)."""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvt
from horovod_tpu.elastic import JaxState, ObjectState


def test_object_state_commit_restore():
    s = ObjectState(epoch=0, batch=0)
    s.epoch = 5
    s.batch = 3
    s.commit()
    s.epoch = 9
    s.restore()
    assert s.epoch == 5 and s.batch == 3


def test_object_state_sync_single_process():
    s = ObjectState(epoch=2)
    s.sync()
    assert s.epoch == 2


def test_jax_state_snapshot():
    params = {"w": jnp.ones((2, 2))}
    s = JaxState(params=params, opt_state=None, epoch=1)
    s.params = {"w": jnp.zeros((2, 2))}
    s.restore()
    np.testing.assert_allclose(np.asarray(s.params["w"]), 1.0)


def test_host_update_raises_at_commit():
    s = ObjectState(epoch=0)
    s.on_hosts_updated(123.0, 1)
    with pytest.raises(hvt.HostsUpdatedInterrupt):
        s.commit()
    # messages are consumed
    s.commit()


def test_elastic_run_restores_on_internal_error():
    calls = {"n": 0}

    @hvt.elastic.run
    def train(state):
        calls["n"] += 1
        if calls["n"] == 1:
            state.epoch = 99  # uncommitted progress, must roll back
            raise hvt.HorovodInternalError("simulated peer loss")
        return state.epoch

    s = ObjectState(epoch=7)
    assert train(s) == 7
    assert calls["n"] == 2


def test_elastic_run_handles_hosts_updated():
    calls = {"n": 0}

    @hvt.elastic.run
    def train(state):
        calls["n"] += 1
        if calls["n"] == 1:
            state.on_hosts_updated(1.0, 0)
            state.commit()  # raises HostsUpdatedInterrupt
        return "done"

    s = ObjectState(epoch=0)
    assert train(s) == "done"
    assert calls["n"] == 2


def test_reset_callbacks():
    fired = []
    s = ObjectState(epoch=0)
    s.register_reset_callbacks([lambda: fired.append(1)])
    s.on_reset()
    assert fired == [1]
