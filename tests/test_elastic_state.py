"""Elastic state machine tests (reference ``test/single/test_torch_elastic.py``
TorchState semantics, ``common/elastic.py`` commit/restore) plus the
checkpointless-recovery layer: shard framing, replica-group planning,
and the ReplicatedState commit/sync protocol over an in-process
thread-gang collectives backend (the real engine path is exercised by
tests/test_elastic_recovery.py and benchmarks/elastic_recovery.py)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvt
from horovod_tpu.elastic import JaxState, ObjectState
from horovod_tpu.elastic.state import (ReplicaUnavailableError,
                                       ReplicatedState,
                                       ShardCorruptError,
                                       build_replica_groups,
                                       decode_shard, encode_shard)


def test_object_state_commit_restore():
    s = ObjectState(epoch=0, batch=0)
    s.epoch = 5
    s.batch = 3
    s.commit()
    s.epoch = 9
    s.restore()
    assert s.epoch == 5 and s.batch == 3


def test_object_state_sync_single_process():
    s = ObjectState(epoch=2)
    s.sync()
    assert s.epoch == 2


def test_jax_state_snapshot():
    params = {"w": jnp.ones((2, 2))}
    s = JaxState(params=params, opt_state=None, epoch=1)
    s.params = {"w": jnp.zeros((2, 2))}
    s.restore()
    np.testing.assert_allclose(np.asarray(s.params["w"]), 1.0)


def test_host_update_raises_at_commit():
    s = ObjectState(epoch=0)
    s.on_hosts_updated(123.0, 1)
    with pytest.raises(hvt.HostsUpdatedInterrupt):
        s.commit()
    # messages are consumed
    s.commit()


def test_elastic_run_restores_on_internal_error():
    calls = {"n": 0}

    @hvt.elastic.run
    def train(state):
        calls["n"] += 1
        if calls["n"] == 1:
            state.epoch = 99  # uncommitted progress, must roll back
            raise hvt.HorovodInternalError("simulated peer loss")
        return state.epoch

    s = ObjectState(epoch=7)
    assert train(s) == 7
    assert calls["n"] == 2


def test_elastic_run_handles_hosts_updated():
    calls = {"n": 0}

    @hvt.elastic.run
    def train(state):
        calls["n"] += 1
        if calls["n"] == 1:
            state.on_hosts_updated(1.0, 0)
            state.commit()  # raises HostsUpdatedInterrupt
        return "done"

    s = ObjectState(epoch=0)
    assert train(s) == "done"
    assert calls["n"] == 2


def test_reset_callbacks():
    fired = []
    s = ObjectState(epoch=0)
    s.register_reset_callbacks([lambda: fired.append(1)])
    s.on_reset()
    assert fired == [1]


# ---------------------------------------------------------------------------
# checkpointless recovery: shards / groups / ReplicatedState
# ---------------------------------------------------------------------------

def test_shard_roundtrip_bit_identity():
    payload = b"\x00\x01binary state \xff" * 100
    blob = encode_shard(owner=7, version=42, payload=payload)
    owner, version, out = decode_shard(blob)
    assert (owner, version) == (7, 42)
    assert out == payload                 # byte-for-byte


def test_shard_corruption_detected():
    blob = encode_shard(3, 5, b"hello shard")
    # payload bit-flip -> CRC mismatch
    bad = bytearray(blob)
    bad[-3] ^= 0x40
    with pytest.raises(ShardCorruptError, match="CRC"):
        decode_shard(bytes(bad))
    with pytest.raises(ShardCorruptError, match="truncated"):
        decode_shard(blob[:10])
    with pytest.raises(ShardCorruptError, match="magic"):
        decode_shard(b"X" * len(blob))
    with pytest.raises(ShardCorruptError, match="length"):
        decode_shard(blob + b"extra")


def test_build_replica_groups_cross_host():
    hosts = ["h0", "h0", "h1", "h1", "h2", "h2", "h3", "h3"]
    groups = build_replica_groups(hosts, 2)
    assert sorted(r for g in groups for r in g) == list(range(8))
    for g in groups:
        assert len(g) == 2
        assert len({hosts[r] for r in g}) == 2, f"group {g} same-host"


def test_build_replica_groups_rack_aware_placement():
    """Topology-weighted planning (ROADMAP 5b): with a rack dimension
    in HVT_TOPO_HOST ("rack/host"), groups prefer same-rack peers on
    DIFFERENT hosts — replication stays rack-local while a host
    SIGKILL can never take a lineage and all of its replicas."""
    hosts = ["r0/h0", "r0/h0", "r0/h1", "r0/h1",
             "r1/h2", "r1/h2", "r1/h3", "r1/h3"]
    groups = build_replica_groups(hosts, 2)
    assert sorted(r for g in groups for r in g) == list(range(8))
    for g in groups:
        assert len(g) == 2
        # the SIGKILL safety: never two members on one host
        assert len({hosts[r] for r in g}) == 2, f"group {g} same-host"
        # the rack preference: both members in one rack
        assert len({hosts[r].split("/")[0] for r in g}) == 1, \
            f"group {g} crosses racks"

    # a rack with a single host cannot satisfy cross-host placement
    # alone — its ranks pool globally and still land cross-host
    hosts2 = ["r0/h0", "r0/h0", "r1/h1", "r1/h1"]
    groups2 = build_replica_groups(hosts2, 2)
    assert sorted(r for g in groups2 for r in g) == list(range(4))
    for g in groups2:
        assert len({hosts2[r] for r in g}) == 2, f"group {g} same-host"

    # no rack separator anywhere → exactly the flat-topology plan
    flat = ["h0", "h0", "h1", "h1", "h2", "h2", "h3", "h3"]
    assert build_replica_groups(flat, 2) == \
        [[0, 2], [4, 6], [1, 3], [5, 7]]


def test_build_replica_groups_skewed_hosts_stay_cross_host():
    """Host-count skew folds round-robin chunks onto one host (three
    ranks on h0 + one on h1 interleave to [0,3,1,2]; chunk [1,2] is
    all-h0) — such a chunk must never be kept as a replica group while
    a cross-host group exists to absorb its ranks, or a host SIGKILL
    takes a lineage and all of its replicas."""
    # rack form: r0 is skewed 3:1, r1 balanced
    hosts = ["r0/h0", "r0/h0", "r0/h0", "r0/h1",
             "r1/h2", "r1/h2", "r1/h3", "r1/h3"]
    groups = build_replica_groups(hosts, 2)
    assert sorted(r for g in groups for r in g) == list(range(8))
    for g in groups:
        assert len({hosts[r] for r in g}) > 1, f"group {g} same-host"

    # flat form with the same skew
    flat = ["h0", "h0", "h0", "h1"]
    for g in build_replica_groups(flat, 2):
        assert len({flat[r] for r in g}) > 1, f"group {g} same-host"

    # single-host world: nowhere cross-host to spill — groups are
    # kept (within-host replication beats none)
    one = build_replica_groups(["h0"] * 4, 2)
    assert sorted(r for g in one for r in g) == list(range(4))


def test_build_replica_groups_remainder_and_clamp():
    # 5 ranks, k=2: the trailing singleton merges into its predecessor
    groups = build_replica_groups(["h0", "h1", "h2", "h0", "h1"], 2)
    assert sorted(len(g) for g in groups) == [2, 3]
    # k larger than the world clamps to one group
    assert build_replica_groups(["h0", "h1"], 5) == [[0, 1]]


class _ThreadWorld:
    """Barrier-based allgather shared by N in-process 'ranks' — just
    enough collectives to drive the ReplicatedState protocol without an
    engine (the engine path is covered by the recovery gang tests)."""

    def __init__(self, n):
        self.n = n
        self.cond = threading.Condition()
        self.boxes = {}
        self.seqs = {}

    def collectives(self, rank, host):
        return _ThreadCollectives(self, rank, host)


class _ThreadCollectives:
    def __init__(self, world, rank, host):
        self.w, self._rank, self._host = world, rank, host

    def rank(self):
        return self._rank

    def size(self):
        return self.w.n

    def host(self):
        return self._host

    def allgather(self, obj, name, ranks=None):
        ranks = sorted(ranks) if ranks is not None \
            else list(range(self.w.n))
        seq = self.w.seqs.get((self._rank, name), 0)
        self.w.seqs[(self._rank, name)] = seq + 1
        key = (name, seq, tuple(ranks))
        with self.w.cond:
            self.w.boxes.setdefault(key, {})[self._rank] = obj
            self.w.cond.notify_all()
            deadline = 10.0
            while len(self.w.boxes[key]) < len(ranks):
                if not self.w.cond.wait(deadline):
                    raise RuntimeError(f"allgather {key} timed out")
        return [self.w.boxes[key][r] for r in ranks]


_HOSTS4 = ["h0", "h0", "h1", "h1"]


def _gang(fn, n):
    """Run fn(rank) on n threads; re-raise the first failure."""
    errs = []

    def body(r):
        try:
            fn(r)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errs.append((r, e))

    threads = [threading.Thread(target=body, args=(r,))
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    if errs:
        raise AssertionError(f"rank {errs[0][0]}: {errs[0][1]!r}") \
            from errs[0][1]


def _committed_gang(steps=3, n=4):
    """A 4-rank gang that committed ``steps`` times; returns states."""
    w = _ThreadWorld(n)
    states = [None] * n

    def run_rank(r):
        s = ReplicatedState(collectives=w.collectives(r, _HOSTS4[r]),
                            x=0, series=[])
        states[r] = s
        for step in range(steps):
            s.x = step
            s.series.append((r, step))
            s.commit()

    _gang(run_rank, n)
    return states


def test_commit_replicates_versioned_shards():
    states = _committed_gang(steps=3)
    for r, s in enumerate(states):
        info = s.replica_info()
        assert info["version"] == 3
        assert s.owner == r
        # every group member's lineage is held at the committed version
        assert all(3 in vs for vs in info["held"].values())
        assert len(info["held"]) == 2      # K=2 group: self + 1 peer
        # groups span hosts
        assert len({_HOSTS4[m] for m in info["group"]}) == 2


def test_sync_rebuilds_lost_rank_from_peers_and_adopts_orphan():
    states = _committed_gang(steps=3)
    # rank 3 dies; the world shrinks to 3
    w2 = _ThreadWorld(3)

    def resync(r):
        states[r]._collectives = w2.collectives(r, _HOSTS4[r])
        states[r].sync()

    _gang(resync, 3)
    for r in range(3):
        assert states[r].x == 2            # last committed value
        assert states[r].owner == r
    adopted = {o: snap for s in states[:3]
               for o, snap in s.adopted.items()}
    assert list(adopted) == [3]
    assert adopted[3]["series"] == [(3, 0), (3, 1), (3, 2)]


def test_adopted_orphan_shards_retire_and_cut_advances():
    """A leftover-adopted lineage's frozen shard must leave every
    shard store: its live data rides inside the adopter's own snapshot
    from then on, and a lingering copy would drag a FUTURE recovery
    cut down to its ancient version and fail the gang over state
    nobody needs."""
    states = _committed_gang(steps=3)
    w2 = _ThreadWorld(3)

    def resync(r):
        states[r]._collectives = w2.collectives(r, _HOSTS4[r])
        states[r].sync()

    _gang(resync, 3)
    for s in states[:3]:
        assert 3 not in s._peer_shards, "orphan shard must retire"

    # keep training, then recover again: the cut must track the LIVE
    # lineages' versions, not the dead owner's frozen one
    def more_commits(r):
        s = states[r]
        for step in range(5):
            s.x = 100 + step
            s.commit()

    _gang(more_commits, 3)
    w3 = _ThreadWorld(3)

    def resync2(r):
        states[r]._collectives = w3.collectives(r, _HOSTS4[r])
        states[r].sync()

    _gang(resync2, 3)
    assert all(s.x == 104 for s in states[:3])
    assert all(s.last_recovery["outcome"] in ("ok", "rollback")
               for s in states[:3])


def test_fresh_rank_never_collides_with_shifted_sticky_owner():
    """After a shrink, a survivor's sticky owner id can equal a fresh
    replacement's RANK id; the fresh rank must start a brand-new
    lineage (bootstrap), never claim the survivor's."""
    states = _committed_gang(steps=2, n=4)
    # survivors are ranks 1..3 of the old world, re-formed as ranks
    # 0..2 (sticky owners 1..3); a fresh worker joins at rank 3 —
    # which collides with the survivor now holding owner 3
    w2 = _ThreadWorld(4)
    survivors = [states[1], states[2], states[3]]
    fresh = ReplicatedState(collectives=w2.collectives(3, _HOSTS4[3]),
                            x=0, series=[])

    def resync(r):
        if r == 3:
            fresh.sync()
        else:
            survivors[r]._collectives = w2.collectives(r, _HOSTS4[r])
            survivors[r].sync()

    _gang(resync, 4)
    owners = sorted([s.owner for s in survivors])
    # one orphan (old owner 0) goes to the fresh rank; had there been
    # none, it would have minted a brand-new id past every known owner
    assert owners == [1, 2, 3]
    assert fresh.owner == 0
    assert fresh.x == 1                    # owner 0's committed value
    assert len({fresh.owner, *owners}) == 4, "owner ids must be unique"


def test_sync_rolls_back_version_skew_to_consistent_cut():
    # groups are [0, 2] and [1, 3] under _HOSTS4; let group [0, 2]
    # commit one step further (the torn-commit shape a mid-commit host
    # loss produces), then resync the survivors
    w = _ThreadWorld(4)
    states = [None] * 4

    def run_rank(r):
        s = ReplicatedState(collectives=w.collectives(r, _HOSTS4[r]),
                            x=0)
        states[r] = s
        for step in range(2 + (1 if r in (0, 2) else 0)):
            s.x = step
            s.commit()

    _gang(run_rank, 4)
    assert [s.version for s in states] == [3, 2, 3, 2]
    w2 = _ThreadWorld(3)

    def resync(r):
        states[r]._collectives = w2.collectives(r, _HOSTS4[r])
        states[r].sync()

    _gang(resync, 3)
    # the cut is version 2 (x == 1): ranks 0/2 rolled BACK a generation
    assert [s.x for s in states[:3]] == [1, 1, 1]
    assert states[0].last_recovery["outcome"] == "rollback"
    assert states[1].last_recovery["outcome"] == "ok"


def test_fresh_respawn_rebuilds_from_peer_shard():
    states = _committed_gang(steps=2)
    # rank 3's process is replaced by a fresh spawn at the same rank
    w2 = _ThreadWorld(4)
    fresh = ReplicatedState(collectives=w2.collectives(3, _HOSTS4[3]),
                            x=0, series=[])

    def resync(r):
        if r == 3:
            fresh.sync()
        else:
            states[r]._collectives = w2.collectives(r, _HOSTS4[r])
            states[r].sync()

    _gang(resync, 4)
    assert fresh.owner == 3
    assert fresh.x == 1                    # rank 3's committed value
    assert fresh.series == [(3, 0), (3, 1)]
    assert fresh.last_recovery["outcome"] == "peer"


def test_stale_shard_version_rejected():
    states = _committed_gang(steps=3)
    s = states[0]
    peer_owner = [o for o in s.replica_info()["held"] if o != 0][0]
    old = encode_shard(peer_owner, 1, b"ancient")
    s._ingest(old)
    assert 1 not in dict(s.replica_info()["held"])[peer_owner]
    # a corrupt incoming copy never evicts the good one either
    good_versions = s.replica_info()["held"][peer_owner]
    bad = bytearray(encode_shard(peer_owner, 9, b"corrupt"))
    bad[-1] ^= 0xFF
    s._ingest(bytes(bad))
    assert s.replica_info()["held"][peer_owner] == good_versions


def test_crc_mismatch_falls_back_per_lineage():
    """Per-lineage blast radius (ROADMAP 5d): a corrupt replica sends
    ONLY the lost lineage to the application restore — intact lineages
    keep their peer-rebuilt state at the cut, and the fallback ranks
    surface in last_recovery["fallback_ranks"] on every member."""
    states = _committed_gang(steps=2)
    # corrupt owner 3's shard everywhere it is held, then replace rank
    # 3 with a fresh spawn; every rank has an application fallback
    for s in states:
        gens = s._peer_shards.get(3)
        if gens:
            s._peer_shards[3] = [
                (v, b[:-1] + bytes([b[-1] ^ 0xFF])) for v, b in gens]
    w2 = _ThreadWorld(4)
    fellback = []

    def fallback(st):
        fellback.append(True)
        st.x = 99
        st.series = ["from-checkpoint"]

    fresh = ReplicatedState(collectives=w2.collectives(3, _HOSTS4[3]),
                            fallback=fallback, x=0, series=[])
    survivors_x = [states[r].x for r in range(3)]
    for s in states:
        s._fallback = fallback

    def resync(r):
        if r == 3:
            fresh.sync()
        else:
            states[r]._collectives = w2.collectives(r, _HOSTS4[r])
            states[r].sync()

    _gang(resync, 4)
    assert len(fellback) == 1              # ONLY the lost lineage
    assert fresh.x == 99
    assert fresh.last_recovery["outcome"] == "fallback"
    for r, s in enumerate(states[:3]):
        assert s.x == survivors_x[r]       # peer-rebuilt state kept
        assert s.last_recovery["fallback_ranks"] == [3]


def test_crc_mismatch_gang_wide_fallback_when_disabled(monkeypatch):
    """HVT_PARTIAL_FALLBACK=0 restores the pre-r15 all-or-nothing
    semantics: one lost lineage sends EVERY rank to the application
    restore together (gang-replicated application state)."""
    monkeypatch.setenv("HVT_PARTIAL_FALLBACK", "0")
    states = _committed_gang(steps=2)
    for s in states:
        gens = s._peer_shards.get(3)
        if gens:
            s._peer_shards[3] = [
                (v, b[:-1] + bytes([b[-1] ^ 0xFF])) for v, b in gens]
    w2 = _ThreadWorld(4)
    fellback = []

    def fallback(st):
        fellback.append(True)
        st.x = 99
        st.series = ["from-checkpoint"]

    fresh = ReplicatedState(collectives=w2.collectives(3, _HOSTS4[3]),
                            fallback=fallback, x=0, series=[])
    for s in states:
        s._fallback = fallback

    def resync(r):
        if r == 3:
            fresh.sync()
        else:
            states[r]._collectives = w2.collectives(r, _HOSTS4[r])
            states[r].sync()

    _gang(resync, 4)
    assert len(fellback) == 4              # the gang restores TOGETHER
    assert fresh.x == 99
    for s in states[:3]:
        assert s.x == 99


def test_partial_loss_two_lineages_fallback_only_those():
    """The satellite pin: TWO lineages lose every intact replica; both
    (and only both) checkpoint-restore while the intact lineages
    re-enter peer rebuild — member-identical fallback_ranks everywhere."""
    states = _committed_gang(steps=3)
    for s in states:
        for owner in (2, 3):
            gens = s._peer_shards.get(owner)
            if gens:
                s._peer_shards[owner] = [
                    (v, b[:-1] + bytes([b[-1] ^ 0xFF])) for v, b in gens]
    w2 = _ThreadWorld(4)
    fellback = []

    def fallback(st):
        fellback.append(True)
        st.x = 77
        st.series = ["from-checkpoint"]

    fresh = {
        r: ReplicatedState(collectives=w2.collectives(r, _HOSTS4[r]),
                           fallback=fallback, x=0, series=[])
        for r in (2, 3)}
    survivors_x = {r: states[r].x for r in (0, 1)}
    for s in states:
        s._fallback = fallback

    def resync(r):
        if r in fresh:
            fresh[r].sync()
        else:
            states[r]._collectives = w2.collectives(r, _HOSTS4[r])
            states[r].sync()

    _gang(resync, 4)
    assert len(fellback) == 2              # exactly the lost lineages
    for r in (2, 3):
        assert fresh[r].x == 77
        assert fresh[r].last_recovery["outcome"] == "fallback"
        assert fresh[r].last_recovery["fallback_ranks"] == [2, 3]
    for r in (0, 1):
        assert states[r].x == survivors_x[r]
        assert states[r].last_recovery["fallback_ranks"] == [2, 3]


def test_replica_unavailable_without_fallback_raises():
    states = _committed_gang(steps=2)
    # owner 3's replicas survive only as corrupt bytes (so the lineage
    # is still KNOWN — a total loss with no record degrades to the
    # bootstrap path instead) and the fresh spawn has no fallback
    for s in states:
        gens = s._peer_shards.get(3)
        if gens:
            s._peer_shards[3] = [
                (v, b[:-1] + bytes([b[-1] ^ 0xFF])) for v, b in gens]
    w2 = _ThreadWorld(4)
    fresh = ReplicatedState(collectives=w2.collectives(3, _HOSTS4[3]),
                            x=0, series=[])
    failed = []

    def resync(r):
        s = fresh if r == 3 else states[r]
        if r != 3:
            s._collectives = w2.collectives(r, _HOSTS4[r])
        try:
            s.sync()
        except ReplicaUnavailableError:
            failed.append(r)

    _gang(resync, 4)
    # gang-wide consensus: EVERY rank falls back together — partial
    # recovery would be an inconsistent cut
    assert sorted(failed) == [0, 1, 2, 3]


def test_grow_bootstraps_new_lineage_from_peer():
    states = _committed_gang(steps=2, n=2)
    w2 = _ThreadWorld(3)
    hosts3 = ["h0", "h0", "h1"]
    new = ReplicatedState(collectives=w2.collectives(2, hosts3[2]),
                          x=0, series=[])

    def resync(r):
        if r == 2:
            new.sync()
        else:
            states[r]._collectives = w2.collectives(r, hosts3[r])
            states[r].sync()

    _gang(resync, 3)
    assert new.last_recovery["outcome"] == "bootstrap"
    assert new.x == 1                      # copied the cut-version state


def test_replication_disabled_env(monkeypatch):
    monkeypatch.setenv("HVT_STATE_REPLICATION", "0")
    calls = []

    class NoCollectives:
        def rank(self):
            return 0

        def size(self):
            return 4

        def host(self):
            return "h0"

        def allgather(self, obj, name, ranks=None):
            calls.append(name)
            raise AssertionError("disabled replication must not "
                                 "exchange")

    s = ReplicatedState(collectives=NoCollectives(), x=1)
    s.commit()
    assert calls == []
    assert s.version == 0
