"""Ray / Spark integration tests driven by fakes — no ray or pyspark
installed (the reference's ``test/single/test_ray*.py`` use a local ray
cluster; the pure-logic cores here are testable without one)."""

import os

import pytest

from horovod_tpu.ray.elastic import ElasticRayExecutor, RayHostDiscovery
from horovod_tpu.ray.runner import Coordinator, RayExecutor
from horovod_tpu.runner.elastic.discovery import FixedHostDiscovery
from horovod_tpu.spark.runner import slot_envs_from_task_infos


# -------------------------------------------------------- Coordinator

def test_coordinator_single_node():
    c = Coordinator("10.0.0.1", 29560)
    for _ in range(4):
        c.register("nodeA")
    envs = c.slot_envs()
    assert [e["HVT_PROCESS_ID"] for e in envs] == ["0", "1", "2", "3"]
    assert all(e["HVT_LOCAL_SIZE"] == "4" for e in envs)
    assert [e["HVT_LOCAL_PROCESS_ID"] for e in envs] == \
        ["0", "1", "2", "3"]
    assert all(e["HVT_CROSS_SIZE"] == "1" for e in envs)
    assert all(e["HVT_MASTER_ADDR"] == "10.0.0.1" for e in envs)


def test_coordinator_multi_node_grouping():
    """Workers registered interleaved across nodes still get consecutive
    ranks per node (reference Coordinator:178 groups by hostname)."""
    c = Coordinator("m", 1)
    order = ["A", "B", "A", "B"]          # registration order interleaved
    for h in order:
        c.register(h)
    envs = c.slot_envs()
    # envs are indexed by registration order
    byhost = {}
    for reg_idx, env in enumerate(envs):
        byhost.setdefault(order[reg_idx], []).append(
            (int(env["HVT_PROCESS_ID"]),
             int(env["HVT_LOCAL_PROCESS_ID"]),
             int(env["HVT_CROSS_RANK"])))
    assert byhost["A"] == [(0, 0, 0), (1, 1, 0)]
    assert byhost["B"] == [(2, 0, 1), (3, 1, 1)]


# ---------------------------------------------------- RayHostDiscovery

def _node(host, cpu=0, gpu=0, alive=True):
    return {"Alive": alive, "NodeManagerHostname": host,
            "Resources": {"CPU": cpu, "GPU": gpu}}


def test_ray_discovery_cpu_slots():
    d = RayHostDiscovery(cpus_per_slot=2, nodes_fn=lambda: [
        _node("a", cpu=8), _node("b", cpu=3),
        _node("dead", cpu=8, alive=False)])
    assert d.find_available_hosts_and_slots() == {"a": 4, "b": 1}


def test_ray_discovery_gpu_slots():
    d = RayHostDiscovery(use_gpu=True, nodes_fn=lambda: [
        _node("a", cpu=8, gpu=2), _node("b", cpu=8, gpu=0)])
    assert d.find_available_hosts_and_slots() == {"a": 2}


def test_elastic_ray_executor_with_fake_cluster():
    ex = ElasticRayExecutor(
        min_np=2, max_np=2,
        override_discovery=FixedHostDiscovery({"localhost": 2}))
    ex.start()
    try:
        results = ex.run(lambda slot: 0, np=2)
        assert set(results.values()) == {0}
    finally:
        ex.shutdown()


def test_elastic_ray_executor_propagates_failure():
    ex = ElasticRayExecutor(
        min_np=2, max_np=2, reset_limit=0,
        override_discovery=FixedHostDiscovery({"localhost": 2}))
    ex.start()
    try:
        with pytest.raises(RuntimeError, match="reset count|min_np"):
            ex.run(lambda slot: 1 if slot.rank == 1 else 0, np=2)
    finally:
        ex.shutdown()


# -------------------------------------------------------------- Spark

def test_spark_slot_envs_multi_host():
    envs = slot_envs_from_task_infos(
        ["hostA:123", "hostA:124", "hostB:125"], master_port=29570)
    assert [e["HVT_PROCESS_ID"] for e in envs] == ["0", "1", "2"]
    assert [e["HVT_LOCAL_PROCESS_ID"] for e in envs] == ["0", "1", "0"]
    assert [e["HVT_LOCAL_SIZE"] for e in envs] == ["2", "2", "1"]
    assert [e["HVT_CROSS_RANK"] for e in envs] == ["0", "0", "1"]
    # local_rank 0 exists on both hosts; local_rank 1 only on hostA
    assert envs[0]["HVT_CROSS_SIZE"] == "2"
    assert envs[1]["HVT_CROSS_SIZE"] == "1"
    assert all(e["HVT_MASTER_ADDR"] == "hostA" for e in envs)


# -------------------------------------------------------------- gating

def test_ray_executor_gated():
    try:
        import ray  # noqa: F401

        pytest.skip("ray installed; gating not applicable")
    except ImportError:
        pass
    ex = RayExecutor(num_workers=2)
    with pytest.raises(ImportError, match="hvtrun"):
        ex.start()


def test_spark_run_gated():
    try:
        import pyspark  # noqa: F401

        pytest.skip("pyspark installed; gating not applicable")
    except ImportError:
        pass
    from horovod_tpu.spark import run

    with pytest.raises(ImportError, match="pyspark"):
        run(lambda: None, num_proc=2)


# ---------------------------------------------------- Spark store/estimator

def _linreg_train_fn(X, y, epochs):
    """Module-level so stdlib pickle can ship it (cloudpickle-free rig)."""
    import numpy as np

    import horovod_tpu as hvt

    W = np.zeros((X.shape[1],), np.float32)
    for _ in range(epochs * 200):
        g = 2 * X.T @ (X @ W - y) / len(X)
        W = W - 0.05 * np.asarray(hvt.allreduce(g, op=hvt.Average))
    return W, _linreg_predict


def _linreg_predict(params, X):
    return X @ params


def test_filesystem_store_layout_and_roundtrip(tmp_path):
    from horovod_tpu.spark import Store

    store = Store.create(str(tmp_path / "st"))
    assert store.get_train_data_path(2).endswith(
        "intermediate_train_data.2")
    assert store.get_checkpoint_path("r1").endswith(
        "runs/r1/checkpoint.bin")
    ck = store.get_checkpoint_path("r1")
    assert not store.exists(ck)
    store.write(ck, b"abc")
    assert store.exists(ck) and store.read(ck) == b"abc"
    # local scratch + sync publishes into the run path
    with store.get_local_output_dir_fn("r1")() as d:
        with open(f"{d}/epoch-0.pt", "wb") as f:
            f.write(b"ck0")
        store.sync_fn("r1")(d)
    assert store.read(
        store.get_run_path("r1") + "/epoch-0.pt") == b"ck0"


def test_store_create_dispatch(tmp_path):
    from horovod_tpu.spark import (DBFSLocalStore, FilesystemStore, Store)

    assert isinstance(Store.create(str(tmp_path)), FilesystemStore)
    assert isinstance(Store.create("dbfs:/x"), DBFSLocalStore)
    assert DBFSLocalStore._localize("dbfs:/a/b") == "/dbfs/a/b"
    assert FilesystemStore._localize("file:///a/b") == "/a/b"


def test_store_create_dispatch_remote_schemes():
    from horovod_tpu.spark import HTTPStore, RemoteStore, Store

    s = Store.create("http://127.0.0.1:1/base")
    assert isinstance(s, HTTPStore) and isinstance(s, RemoteStore)
    # gs:// dispatches to GCSStore. Environment-dependent outcome:
    # with the library + ambient credentials (a real GCP TPU VM) it
    # constructs; without either it must fail LOUDLY (gated
    # ImportError, or the client's credentials error) — never a
    # silently broken store.
    from horovod_tpu.spark import GCSStore

    try:
        s = Store.create("gs://bucket/prefix")
    except Exception as e:
        assert ("google-cloud-storage" in str(e)
                or "credential" in str(e).lower()
                or type(e).__name__ == "DefaultCredentialsError"), e
    else:
        assert isinstance(s, GCSStore)


def test_http_store_roundtrip_over_real_kv_server():
    """Remote-store IO through the actual rendezvous HTTP KV server —
    every byte over the wire (VERDICT r4 #6: the reference selects
    LocalStore/HDFSStore by scheme, spark/common/store.py)."""
    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.spark import Store

    srv = RendezvousServer()
    port = srv.start()
    try:
        store = Store.create(f"http://127.0.0.1:{port}/teamA")
        assert store.get_checkpoint_path("r1") \
            == f"http://127.0.0.1:{port}/teamA/runs/r1/checkpoint.bin"
        ck = store.get_checkpoint_path("r1")
        assert not store.exists(ck)
        store.write(ck, b"remote-bytes")
        assert store.exists(ck) and store.read(ck) == b"remote-bytes"
        # scratch-dir sync publishes every file into the run path
        with store.get_local_output_dir_fn("r1")() as d:
            os.makedirs(f"{d}/sub", exist_ok=True)
            with open(f"{d}/epoch-0.pt", "wb") as f:
                f.write(b"ck0")
            with open(f"{d}/sub/log.txt", "wb") as f:
                f.write(b"line")
            store.sync_fn("r1")(d)
        assert store.read(store.get_run_path("r1") + "/epoch-0.pt") \
            == b"ck0"
        assert store.read(store.get_run_path("r1") + "/sub/log.txt") \
            == b"line"
    finally:
        srv.stop()


def test_gcs_store_io_with_fake_client():
    """GCSStore's key mapping + IO against a dict-backed fake client
    (the real google-cloud-storage is uninstallable here; the fake
    mirrors Bucket.blob().exists/download_as_bytes/upload_from_string)."""
    from horovod_tpu.spark import GCSStore

    blobs = {}

    class FakeBlob:
        def __init__(self, key):
            self.key = key

        def exists(self):
            return self.key in blobs

        def download_as_bytes(self):
            return blobs[self.key]

        def upload_from_string(self, data):
            blobs[self.key] = (data.encode()
                               if isinstance(data, str) else data)

    class FakeBucket:
        def blob(self, key):
            return FakeBlob(key)

    class FakeClient:
        def bucket(self, name):
            assert name == "my-bucket"
            return FakeBucket()

    store = GCSStore("gs://my-bucket/ckpts", client=FakeClient())
    ck = store.get_checkpoint_path("r9")
    assert ck == "gs://my-bucket/ckpts/runs/r9/checkpoint.bin"
    assert not store.exists(ck)
    store.write(ck, b"gcs-bytes")
    assert store.exists(ck) and store.read(ck) == b"gcs-bytes"
    # keys are bucket-relative
    assert "ckpts/runs/r9/checkpoint.bin" in blobs


def test_jax_estimator_roundtrip_through_http_store():
    """Full estimator fit → checkpoint-publish → load → predict with the
    store served remotely (VERDICT r4 #6 'done' criterion)."""
    import numpy as np

    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.spark import JaxEstimator, JaxModel, Store

    srv = RendezvousServer()
    port = srv.start()
    try:
        store = Store.create(f"http://127.0.0.1:{port}/est")
        rng = np.random.RandomState(3)
        Wt = np.asarray([1.5, -2.0], np.float32)
        X = rng.randn(64, 2).astype(np.float32)
        y = X @ Wt
        est = JaxEstimator(_linreg_train_fn, feature_cols=["a", "b"],
                           label_col="y", epochs=1, store=store,
                           run_id="runH")
        model = est._fit_arrays(X, y)
        assert store.exists(store.get_checkpoint_path("runH"))
        loaded = JaxModel.load(store, "runH")
        np.testing.assert_allclose(loaded._predict_arrays(X),
                                   model._predict_arrays(X))
    finally:
        srv.stop()


def test_sharded_dataset_streams_through_http_store():
    """Out-of-core shard write + streaming read composes with the
    remote store: every .npz shard and the manifest travel over the
    wire (data.py touches stores only via the read/write bytes API)."""
    import numpy as np

    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.spark import Store
    from horovod_tpu.spark.data import (ShardedDataset,
                                        write_dataframe_shards)

    srv = RendezvousServer()
    port = srv.start()
    try:
        store = Store.create(f"http://127.0.0.1:{port}/ooc")
        rng = np.random.RandomState(5)
        X = rng.randn(30, 2).astype(np.float32)
        y = rng.randn(30).astype(np.float32)
        manifest = write_dataframe_shards(
            _df_from_xy(X, y, n_parts=3), store, ["a", "b"], "y",
            idx="mh")
        assert len(manifest["files"]) == 3
        ds = ShardedDataset(store, idx="mh")
        assert ds.global_rows == 30
        # the PUBLIC streaming path over the wire: one rank, one epoch
        steps = ds.lockstep_steps(1, 10)
        got = np.sort(np.concatenate(
            [yb for _, yb in ds.iter_batches(0, 1, 10, steps)]))
        np.testing.assert_allclose(got, np.sort(y), rtol=1e-6)
    finally:
        srv.stop()


def test_jax_estimator_fit_save_load_predict(tmp_path):
    import numpy as np

    from horovod_tpu.spark import JaxEstimator, JaxModel, Store

    rng = np.random.RandomState(3)
    Wt = np.asarray([1.5, -2.0], np.float32)
    X = rng.randn(64, 2).astype(np.float32)
    y = X @ Wt
    store = Store.create(str(tmp_path / "st"))
    est = JaxEstimator(_linreg_train_fn, feature_cols=["a", "b"],
                       label_col="y", epochs=1, store=store, run_id="run1")
    model = est._fit_arrays(X, y)
    np.testing.assert_allclose(model._predict_arrays(X), y, atol=1e-2)
    assert store.exists(store.get_checkpoint_path("run1"))
    # restore from the store and get identical predictions
    loaded = JaxModel.load(store, "run1")
    assert loaded.feature_cols == ["a", "b"]
    np.testing.assert_allclose(loaded._predict_arrays(X),
                               model._predict_arrays(X))


class _EpochRecorder:
    def __init__(self):
        self.epochs = []

    def on_epoch_end(self, epoch, logs):
        self.epochs.append((epoch, logs["loss"]))


def test_torch_estimator_fit_checkpoints_callbacks_load(tmp_path):
    import numpy as np
    import torch

    from horovod_tpu.spark import Store, TorchEstimator, TorchModel

    rng = np.random.RandomState(5)
    X = rng.randn(96, 3).astype(np.float32)
    y = (X @ np.asarray([0.5, -1.0, 2.0], np.float32))
    store = Store.create(str(tmp_path / "st"))
    rec = _EpochRecorder()
    est = TorchEstimator(
        model=torch.nn.Linear(3, 1), optimizer_fn=lambda p:
        torch.optim.SGD(p, lr=0.1), feature_cols=["a", "b", "c"],
        label_col="y", epochs=6, batch_size=8, store=store,
        run_id="trun", callbacks=[rec])
    model = est._fit_arrays(X, y)
    # converged + callbacks saw decreasing loss each epoch
    assert [e for e, _ in rec.epochs] == list(range(6))
    assert rec.epochs[-1][1] < rec.epochs[0][1]
    preds = model._predict_arrays(X)
    assert np.mean((preds - y) ** 2) < 0.1
    # per-epoch checkpoints were published through the sync contract
    for ep in range(6):
        assert store.exists(
            store.get_run_path("trun") + f"/checkpoint-{ep}.pt")
    # history logs + final checkpoint + restore round trip
    assert store.exists(store.get_logs_path("trun") + "/history.json")
    loaded = TorchModel.load(store, "trun", torch.nn.Linear(3, 1),
                             feature_cols=["a", "b", "c"])
    np.testing.assert_allclose(loaded._predict_arrays(X), preds,
                               rtol=1e-6)


def test_keras_estimator_fit_checkpoints_load(tmp_path):
    import pytest

    tf = pytest.importorskip("tensorflow")
    import numpy as np

    from horovod_tpu.spark import KerasEstimator, KerasModel, Store

    rs = np.random.RandomState(9)
    X = rs.randn(128, 3).astype(np.float32)
    y = (X @ np.asarray([1.0, -0.5, 2.0], np.float32))
    store = Store.create(str(tmp_path / "st"))
    rec = _EpochRecorder()
    model = tf.keras.Sequential(
        [tf.keras.Input((3,)), tf.keras.layers.Dense(1, use_bias=False)])
    est = KerasEstimator(model, feature_cols=["a", "b", "c"],
                         label_col="y",
                         optimizer=tf.keras.optimizers.SGD(0.1),
                         loss="mse", epochs=10, batch_size=16,
                         store=store, run_id="krun", callbacks=[rec])
    fitted = est._fit_arrays(X, y)
    assert [e for e, _ in rec.epochs] == list(range(10))
    assert rec.epochs[-1][1] < rec.epochs[0][1]
    preds = fitted._predict_arrays(X)
    assert np.mean((preds - y) ** 2) < 0.1
    for ep in range(10):
        assert store.exists(store.get_run_path("krun")
                            + f"/checkpoint-{ep}.weights.h5")
    loaded = KerasModel.load(store, "krun")
    assert loaded.feature_cols == ["a", "b", "c"]
    np.testing.assert_allclose(loaded._predict_arrays(X), preds,
                               rtol=1e-5)


def test_steps_per_epoch_lockstep():
    from horovod_tpu.spark.estimator import _steps_per_epoch

    # 33 rows over 2 procs, batch 16: shards are 17/16 rows — both ranks
    # must run ceil(17/16) = 2 steps
    assert _steps_per_epoch(33, 2, 16) == 2
    assert _steps_per_epoch(32, 2, 16) == 1
    assert _steps_per_epoch(5, 8, 4) == 1     # more procs than rows
    assert _steps_per_epoch(100, 1, 10) == 10


def test_shard_rows_never_empty():
    # every rank must get >=1 row or the lockstep per-step collectives
    # desynchronize (ranks with empty shards would crash out of the loop)
    import numpy as np

    from horovod_tpu.spark.estimator import _shard_rows

    for total, n in [(5, 8), (1, 4), (8, 8), (33, 2), (3, 3)]:
        for r in range(n):
            rows = _shard_rows(total, r, n)
            assert rows.size >= 1, (total, r, n)
            assert (rows < total).all()
    # normal case unchanged: strided, disjoint, complete
    got = np.sort(np.concatenate([_shard_rows(33, r, 2) for r in range(2)]))
    np.testing.assert_array_equal(got, np.arange(33))


def test_estimator_validation_split_val_loss(tmp_path):
    """validation= holds out a deterministic fraction; callbacks carry
    per-epoch val_loss (reference estimator param)."""
    import numpy as np
    import torch

    from horovod_tpu.spark import TorchEstimator
    from horovod_tpu.spark.estimator import _train_val_split

    class FullRecorder:
        def __init__(self):
            self.logs = []

        def on_epoch_end(self, epoch, logs):
            self.logs.append(dict(logs))

    rng = np.random.RandomState(6)
    X = rng.randn(80, 2).astype(np.float32)
    y = (X @ np.asarray([1.0, -2.0], np.float32))
    rec = FullRecorder()
    est = TorchEstimator(
        model=torch.nn.Linear(2, 1),
        optimizer_fn=lambda p: torch.optim.SGD(p, lr=0.1),
        feature_cols=["a", "b"], label_col="y", epochs=5, batch_size=8,
        callbacks=[rec], validation=0.25)
    model = est._fit_arrays(X, y)
    assert all("val_loss" in l for l in rec.logs), rec.logs
    assert rec.logs[-1]["val_loss"] < rec.logs[0]["val_loss"]
    preds = model._predict_arrays(X)
    assert np.mean((preds - y) ** 2) < 0.2
    # split invariants: deterministic, disjoint, complete
    t1, v1 = _train_val_split(80, 0.25)
    t2, v2 = _train_val_split(80, 0.25)
    np.testing.assert_array_equal(t1, t2)
    np.testing.assert_array_equal(v1, v2)
    assert len(v1) == 20 and len(t1) == 60
    assert not set(t1) & set(v1)
    with pytest.raises(ValueError, match="validation"):
        _train_val_split(10, 1.5)
    with pytest.raises(ValueError, match="validation"):
        _train_val_split(10, -0.25)


def test_keras_estimator_validation(tmp_path):
    tf = pytest.importorskip("tensorflow")
    import numpy as np

    from horovod_tpu.spark import KerasEstimator

    class FullRecorder:
        def __init__(self):
            self.logs = []

        def on_epoch_end(self, epoch, logs):
            self.logs.append(dict(logs))

    rng = np.random.RandomState(7)
    X = rng.randn(64, 2).astype(np.float32)
    y = (X @ np.asarray([2.0, 1.0], np.float32))
    rec = FullRecorder()
    model = tf.keras.Sequential([tf.keras.layers.Dense(1, use_bias=False)])
    est = KerasEstimator(model=model, feature_cols=["a", "b"],
                         label_col="y",
                         optimizer=tf.keras.optimizers.SGD(0.1),
                         epochs=4, batch_size=8, callbacks=[rec],
                         validation=0.25)
    est._fit_arrays(X, y)
    assert all("val_loss" in l for l in rec.logs), rec.logs
    assert rec.logs[-1]["val_loss"] < rec.logs[0]["val_loss"]


# --------------------------------------- barrier-API conformance (r4 #9)

# The slice of pyspark's DOCUMENTED API that spark/runner.py relies on,
# with arities (excluding self). Source: pyspark.BarrierTaskContext /
# RDD / SparkSession docs (pyspark 3.x). If runner.py starts using a
# method not listed here, the fake below lacks it and the execution
# test fails loudly — instead of the env-blocked code rotting silently
# against a drifted fake (VERDICT r4 #9). If pyspark ever changes this
# surface, THIS table is the single place to re-verify against the
# real docs.
_PYSPARK_DOCUMENTED_SURFACE = {
    "BarrierTaskContext.get": 0,          # classmethod
    "BarrierTaskContext.getTaskInfos": 0,  # -> [BarrierTaskInfo(address)]
    "BarrierTaskContext.partitionId": 0,
    "BarrierTaskContext.barrier": 0,
    "SparkSession.builder.getOrCreate": 0,
    "SparkContext.parallelize": 2,        # (iterable, numSlices)
    "SparkContext.broadcast": 1,
    "SparkContext.defaultParallelism": 0,  # property
    "RDD.barrier": 0,
    "RDDBarrier.mapPartitions": 1,
    "RDD.collect": 0,
}


def _install_fake_pyspark(monkeypatch, num_proc):
    """Inject a sys.modules pyspark whose surface is EXACTLY
    _PYSPARK_DOCUMENTED_SURFACE — nothing more, so undocumented-API use
    in runner.py breaks here rather than on a real cluster."""
    import sys
    import types

    class _TaskInfo:
        def __init__(self, address):
            self.address = address

    class BarrierTaskContext:
        _current = None

        @classmethod
        def get(cls):
            return cls._current

        def __init__(self, rank):
            self._rank = rank

        def getTaskInfos(self):
            return [_TaskInfo(f"127.0.0.1:{40000 + i}")
                    for i in range(num_proc)]

        def partitionId(self):
            return self._rank

        def barrier(self):
            pass  # single-gang fake: tasks run sequentially

    class _BarrierRDD:
        def __init__(self, parts):
            self._parts = parts

        def mapPartitions(self, fn):
            out = []
            for i, p in enumerate(self._parts):
                BarrierTaskContext._current = BarrierTaskContext(i)
                try:
                    out.extend(fn(iter(p)))
                finally:
                    BarrierTaskContext._current = None
            return _CollectedRDD(out)

    class _CollectedRDD:
        def __init__(self, rows):
            self._rows = rows

        def collect(self):
            return list(self._rows)

    class _SC:
        defaultParallelism = num_proc

        def parallelize(self, it, numSlices):
            items = list(it)
            return _PlainRDD([items[i::numSlices]
                              for i in range(numSlices)])

        def broadcast(self, v):
            return FakeBroadcast(v)

    class _PlainRDD:
        def __init__(self, parts):
            self._parts = parts

        def barrier(self):
            return _BarrierRDD(self._parts)

    class _Builder:
        def getOrCreate(self):
            s = types.SimpleNamespace()
            s.sparkContext = _SC()
            return s

    class SparkSession:
        builder = _Builder()

    mod = types.ModuleType("pyspark")
    mod.BarrierTaskContext = BarrierTaskContext
    sql = types.ModuleType("pyspark.sql")
    sql.SparkSession = SparkSession
    mod.sql = sql
    # expose every fake class for the conformance test — ALL rows of
    # _PYSPARK_DOCUMENTED_SURFACE must be checkable, not just the
    # BarrierTaskContext ones
    mod._conformance_targets = {
        "BarrierTaskContext": BarrierTaskContext,
        "SparkContext": _SC,
        "RDD": _PlainRDD,
        "RDDBarrier": _BarrierRDD,
        "CollectedRDD": _CollectedRDD,
        "Builder": _Builder,
    }
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    monkeypatch.setitem(sys.modules, "pyspark.sql", sql)
    return mod


def test_fake_barrier_context_matches_documented_surface(monkeypatch):
    """Method-name/arity conformance of the fake vs the documented
    pyspark surface — EVERY row of the table, so the fake can only rot
    in a way this catches."""
    import inspect

    mod = _install_fake_pyspark(monkeypatch, 2)
    t = mod._conformance_targets
    # dotted surface name -> (fake class, method) it must conform on
    fake_for = {
        "BarrierTaskContext.get": (t["BarrierTaskContext"], "get"),
        "BarrierTaskContext.getTaskInfos":
            (t["BarrierTaskContext"], "getTaskInfos"),
        "BarrierTaskContext.partitionId":
            (t["BarrierTaskContext"], "partitionId"),
        "BarrierTaskContext.barrier":
            (t["BarrierTaskContext"], "barrier"),
        "SparkSession.builder.getOrCreate": (t["Builder"], "getOrCreate"),
        "SparkContext.parallelize": (t["SparkContext"], "parallelize"),
        "SparkContext.broadcast": (t["SparkContext"], "broadcast"),
        "SparkContext.defaultParallelism":
            (t["SparkContext"], "defaultParallelism"),
        "RDD.barrier": (t["RDD"], "barrier"),
        "RDDBarrier.mapPartitions": (t["RDDBarrier"], "mapPartitions"),
        "RDD.collect": (t["CollectedRDD"], "collect"),
    }
    assert set(fake_for) == set(_PYSPARK_DOCUMENTED_SURFACE), \
        "surface table and fake mapping drifted apart"
    for dotted, arity in _PYSPARK_DOCUMENTED_SURFACE.items():
        cls, name = fake_for[dotted]
        attr = inspect.getattr_static(cls, name)
        assert attr is not None, f"fake lacks {dotted}"
        if dotted == "SparkContext.defaultParallelism":
            # documented as a property/attribute, not a callable
            assert not callable(attr)
            continue
        raw = attr.__func__ if isinstance(attr, classmethod) else attr
        params = [p for p in
                  inspect.signature(raw).parameters.values()
                  if p.name not in ("self", "cls")]
        assert len(params) == arity, (dotted, params)


def test_spark_run_executes_through_documented_barrier_api(monkeypatch):
    """spark.run() END-TO-END through the fake barrier gang (1 task:
    threads would fight over os.environ): env derivation from task
    addresses, barrier before init, hvt runtime up inside the task,
    results ordered by rank. Previously run() was only gating-tested —
    this pins the whole documented-API interaction."""
    import jax

    import horovod_tpu as hvt

    _install_fake_pyspark(monkeypatch, 1)
    from horovod_tpu.spark import runner as spark_runner

    def train_fn(a, b=0):
        # the fake gang runs in-process, where the pytest session's
        # runtime is already up — assert on the env the barrier task
        # derived from the task addresses, not on ambient hvt state
        assert os.environ["HVT_NUM_PROCESSES"] == "1"
        assert os.environ["HVT_PROCESS_ID"] == "0"
        assert os.environ["HVT_HOSTNAME"] == "127.0.0.1"
        return a + b + int(os.environ["HVT_PROCESS_ID"])

    # In-process isolation: the barrier task calls hvt.init() AND
    # hvt.shutdown() (correct on a real executor, fatal to the pytest
    # session's runtime here — shutdown would tear down the session
    # fixture's engine for every later test) and os.environ.update()s
    # the slot identity. Neuter init/shutdown and restore the env.
    monkeypatch.setattr(hvt, "init", lambda *a, **k: None)
    monkeypatch.setattr(hvt, "shutdown", lambda *a, **k: None)
    env_before = dict(os.environ)
    try:
        jax.config.update("jax_platforms", "cpu")
        out = spark_runner.run(train_fn, args=(40,), kwargs={"b": 2},
                               num_proc=1, force_cpu_jax=True)
    finally:
        for k in set(os.environ) - set(env_before):
            del os.environ[k]
        os.environ.update(env_before)
    assert out == [42]

class FakeBroadcast:
    def __init__(self, v):
        self.value = v


class _FakeSC:
    def broadcast(self, v):
        return FakeBroadcast(v)


class _FakeSession:
    sparkContext = _FakeSC()


class _FakeCollected:
    def __init__(self, parts):
        self._parts = parts

    def collect(self):
        return [x for p in self._parts for x in p]


class _FakeRDD:
    def __init__(self, parts):
        self._parts = parts

    def mapPartitionsWithIndex(self, fn):
        return _FakeCollected(
            [list(fn(i, iter(p))) for i, p in enumerate(self._parts)])


class FakeDataFrame:
    """Quacks like the slice of pyspark.sql.DataFrame the estimators'
    DataFrame half touches: select/collect, rdd.mapPartitionsWithIndex,
    sparkSession.sparkContext.broadcast."""

    def __init__(self, partitions):
        self._parts = partitions  # list of lists of dict rows

    def select(self, *cols):
        return self

    def collect(self):
        return [r for p in self._parts for r in p]

    @property
    def rdd(self):
        return _FakeRDD(self._parts)

    @property
    def sparkSession(self):
        return _FakeSession()


def _df_from_xy(X, y, n_parts=3):
    rows = [{"a": float(x[0]), "b": float(x[1]), "y": float(t)}
            for x, t in zip(X, y)]
    parts = [rows[i::n_parts] for i in range(n_parts)]
    return FakeDataFrame(parts)


def test_fit_dataframe_collect_broadcast_path(tmp_path):
    """The DataFrame half of fit() (collect → broadcast → _fit_arrays) —
    the coverage _fit_arrays alone skips (VERDICT r2 #10)."""
    import numpy as np

    from horovod_tpu.spark import JaxEstimator

    rng = np.random.RandomState(11)
    X = rng.randn(48, 2).astype(np.float32)
    y = X @ np.asarray([2.0, -1.0], np.float32)
    est = JaxEstimator(_linreg_train_fn, feature_cols=["a", "b"],
                       label_col="y", epochs=1)
    model = est._fit_dataframe(_df_from_xy(X, y))
    np.testing.assert_allclose(model._predict_arrays(X), y, atol=1e-2)


def test_write_dataframe_shards_and_streaming_reader(tmp_path):
    """Out-of-core materialization (reference Petastorm-store analog,
    VERDICT r2 missing #3): per-partition .npz shards + manifest in the
    store; the reader streams file-granular rank shards with a lockstep
    step count and wrap-around padding."""
    import numpy as np

    from horovod_tpu.spark import Store
    from horovod_tpu.spark.data import (ShardedDataset,
                                        write_dataframe_shards)

    rng = np.random.RandomState(7)
    X = rng.randn(50, 2).astype(np.float32)
    y = rng.randn(50).astype(np.float32)
    store = Store.create(str(tmp_path / "st"))
    manifest = write_dataframe_shards(_df_from_xy(X, y, n_parts=4), store,
                                      ["a", "b"], "y", idx="m1")
    assert len(manifest["files"]) == 4
    assert sum(f["rows"] for f in manifest["files"]) == 50

    ds = ShardedDataset(store, idx="m1")
    assert ds.global_rows == 50
    # file-granular strided assignment covers every file exactly once
    names = [f["name"] for r in range(2) for f in ds.rank_files(r, 2)]
    assert sorted(names) == sorted(f["name"] for f in ds.files)
    # more ranks than files: wrap-around keeps every rank non-empty
    for r in range(6):
        assert ds.rank_files(r, 6), f"rank {r} got no files"

    # streaming batches reconstruct exactly this rank's rows (one epoch,
    # no wrap): batch_size divides the rank rows for rank 0 with size 1
    steps = ds.lockstep_steps(1, 10)
    seen_x = np.concatenate([bx for bx, _ in
                             ds.iter_batches(0, 1, 10, steps, seed=3)])
    assert seen_x.shape == (50, 2)
    # same multiset of rows as the source (order shuffled)
    np.testing.assert_allclose(
        np.sort(seen_x.sum(axis=1)), np.sort(X.sum(axis=1)), rtol=1e-5)
    # a rank with fewer rows wraps to reach the lockstep step count
    steps2 = ds.lockstep_steps(2, 8)
    got = list(ds.iter_batches(1, 2, 8, steps2, seed=0))
    assert len(got) == steps2
    assert all(bx.shape == (8, 2) for bx, _ in got)


def test_torch_estimator_out_of_core_fit(tmp_path):
    """End-to-end out-of-core fit(df): materialize shards through the
    store, stream them in the training loop, converge, checkpoint."""
    import numpy as np
    import torch

    from horovod_tpu.spark import Store, TorchEstimator

    rng = np.random.RandomState(13)
    X = rng.randn(120, 2).astype(np.float32)
    y = X @ np.asarray([1.0, -0.5], np.float32)
    store = Store.create(str(tmp_path / "st"))
    rec = _EpochRecorder()
    est = TorchEstimator(
        model=torch.nn.Linear(2, 1),
        optimizer_fn=lambda p: torch.optim.SGD(p, lr=0.1),
        feature_cols=["a", "b"], label_col="y", epochs=6, batch_size=16,
        store=store, run_id="ooc1", callbacks=[rec], out_of_core=True)
    model = est._fit_dataframe(_df_from_xy(X, y, n_parts=5))
    assert rec.epochs[-1][1] < rec.epochs[0][1]
    np.testing.assert_allclose(model._predict_arrays(X), y, atol=0.15)
    # shards landed under the store's train data path
    assert store.exists(store.get_train_data_path("ooc1")
                        + "/manifest.json")
    assert store.exists(store.get_checkpoint_path("ooc1"))


def test_torch_out_of_core_rejects_validation():
    import torch

    import pytest as _pytest

    from horovod_tpu.spark import TorchEstimator

    with _pytest.raises(ValueError, match="out_of_core"):
        TorchEstimator(model=torch.nn.Linear(2, 1),
                       optimizer_fn=lambda p: torch.optim.SGD(p, lr=0.1),
                       feature_cols=["a", "b"], label_col="y",
                       validation=0.2, out_of_core=True)


def test_keras_estimator_out_of_core_fit(tmp_path):
    """Keras flavor of the streaming path: same store/shard contract as
    the Torch estimator."""
    import numpy as np

    tf = pytest.importorskip("tensorflow")

    from horovod_tpu.spark import KerasEstimator, Store

    rng = np.random.RandomState(17)
    X = rng.randn(100, 2).astype(np.float32)
    y = X @ np.asarray([0.8, -0.6], np.float32)
    store = Store.create(str(tmp_path / "st"))
    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(1, use_bias=False)])
    rec = _EpochRecorder()
    est = KerasEstimator(
        model, feature_cols=["a", "b"], label_col="y",
        optimizer=tf.keras.optimizers.SGD(0.1), epochs=6, batch_size=20,
        store=store, run_id="kooc", callbacks=[rec], out_of_core=True)
    fitted = est._fit_dataframe(_df_from_xy(X, y, n_parts=4))
    assert rec.epochs[-1][1] < rec.epochs[0][1]
    np.testing.assert_allclose(fitted._predict_arrays(X), y, atol=0.2)
    assert store.exists(store.get_train_data_path("kooc")
                        + "/manifest.json")


def test_keras_out_of_core_rejects_validation():
    tf = pytest.importorskip("tensorflow")

    from horovod_tpu.spark import KerasEstimator

    with pytest.raises(ValueError, match="out_of_core"):
        KerasEstimator(
            tf.keras.Sequential([tf.keras.layers.Dense(1)]),
            feature_cols=["a"], label_col="y", validation=0.2,
            out_of_core=True)


# --------------------------------------------------- spark run_elastic

def test_elastic_attempt_loop_resizes_and_recovers():
    """Gang fails once → world re-sized from the (shrunken) slot pool
    and retried; attempt indices advance (reference run_elastic
    reset-and-resume at stage boundaries)."""
    from horovod_tpu.spark.runner import _elastic_attempt_loop

    calls = []
    pool = [4, 2]  # 4 slots at first, 2 after the failure

    def attempt(world, idx):
        calls.append((world, idx))
        if idx == 0:
            raise RuntimeError("executor lost")
        return [f"r{i}" for i in range(world)]

    out = _elastic_attempt_loop(attempt, lambda: pool.pop(0),
                                min_np=2, max_np=4, reset_limit=2)
    assert calls == [(4, 0), (2, 1)]
    assert out == ["r0", "r1"]


def test_elastic_attempt_loop_min_np_violation_raises():
    from horovod_tpu.spark.runner import _elastic_attempt_loop

    def attempt(world, idx):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="min_np=3"):
        _elastic_attempt_loop(attempt, lambda: 2, min_np=3,
                              reset_limit=2, elastic_timeout=0.0)


def test_elastic_attempt_loop_waits_out_transient_min_np_dip():
    """A momentary dip below min_np (executor replacement in flight) is
    waited out instead of killing the job."""
    from horovod_tpu.spark.runner import _elastic_attempt_loop

    pool = [1, 1, 3]  # dips below min_np=2, then recovers

    def attempt(world, idx):
        return ["ok"] * world

    clock = [0.0]
    out = _elastic_attempt_loop(
        attempt, lambda: pool.pop(0) if pool else 3, min_np=2,
        elastic_timeout=60.0, _sleep=lambda s: clock.__setitem__(
            0, clock[0] + s), _monotonic=lambda: clock[0])
    assert out == ["ok"] * 3


def test_elastic_attempt_loop_min_gt_max_rejected_upfront():
    from horovod_tpu.spark.runner import _elastic_attempt_loop

    with pytest.raises(ValueError, match="min_np"):
        _elastic_attempt_loop(lambda w, i: [], lambda: 16, min_np=4,
                              max_np=2)


def test_elastic_attempt_loop_retries_capped_at_num_proc():
    """With no explicit max_np, a reset must not outgrow the requested
    world (launch.py convention: max_np defaults to num_proc)."""
    from horovod_tpu.spark.runner import _elastic_attempt_loop

    seen = []

    def attempt(world, idx):
        seen.append(world)
        if idx == 0:
            raise RuntimeError("lost")
        return ["ok"] * world

    _elastic_attempt_loop(attempt, lambda: 64, num_proc=2,
                          reset_limit=1)
    assert seen == [2, 2]


def test_elastic_attempt_loop_reset_limit_exhausted():
    from horovod_tpu.spark.runner import _elastic_attempt_loop

    n = [0]

    def attempt(world, idx):
        n[0] += 1
        raise RuntimeError("persistent failure")

    with pytest.raises(RuntimeError, match="after 3 attempts"):
        _elastic_attempt_loop(attempt, lambda: 2, reset_limit=2)
    assert n[0] == 3


def test_elastic_attempt_loop_first_attempt_prefers_num_proc():
    from horovod_tpu.spark.runner import _elastic_attempt_loop

    seen = []

    def attempt(world, idx):
        seen.append(world)
        return ["ok"] * world

    _elastic_attempt_loop(attempt, lambda: 8, num_proc=3, max_np=6)
    assert seen == [3]


def test_spark_run_elastic_gated():
    try:
        import pyspark  # noqa: F401
    except ImportError:
        pass
    else:
        pytest.skip("pyspark installed; gating not applicable")
    from horovod_tpu.spark import run_elastic

    with pytest.raises(ImportError, match="pyspark"):
        run_elastic(lambda: None, num_proc=2)


def test_elastic_attempt_loop_num_proc_below_min_rejected():
    from horovod_tpu.spark.runner import _elastic_attempt_loop

    with pytest.raises(ValueError, match="num_proc"):
        _elastic_attempt_loop(lambda w, i: [], lambda: 16, num_proc=2,
                              min_np=4, max_np=8)


def test_sharded_dataset_gang_lockstep_4proc(tmp_path):
    """VERDICT r3 stretch: the out-of-core manifest logic under a REAL
    4-rank launcher gang (no pyspark needed for the write/stream halves).
    Rank 0 materializes uneven shards via write_dataframe_shards; all
    ranks stream their file-granular assignment from the shared store,
    derive the SAME lockstep step count, and keep per-step gradient
    allreduces synchronized to an identical final model."""
    import numpy as np

    from tests.test_engine_integration import run_workers

    share = str(tmp_path / "store")
    out = run_workers("""
        from horovod_tpu.spark import Store
        from horovod_tpu.spark.data import (ShardedDataset,
                                            write_dataframe_shards)
        from tests.test_integrations import FakeDataFrame

        store = Store.create(os.environ["HVT_TEST_STORE"])
        rng = np.random.RandomState(5)
        X = rng.randn(20, 2).astype(np.float32)
        w_true = np.array([1.5, -2.0], np.float32)
        y = X @ w_true
        rows = [{"a": float(a), "b": float(b), "y": float(t)}
                for (a, b), t in zip(X, y)]
        # uneven partitions: 11/5/3/1 rows -> tail ranks wrap around
        parts = [rows[:11], rows[11:16], rows[16:19], rows[19:]]

        if r == 0:
            write_dataframe_shards(FakeDataFrame(parts), store,
                                   ["a", "b"], "y", idx="gang")
        hvt.allreduce(np.zeros(1, np.float32), name="shards.ready")

        ds = ShardedDataset(store, idx="gang")
        assert ds.global_rows == 20
        bs = 4
        steps = ds.lockstep_steps(n, bs)
        assert steps == 3, steps  # ceil(11 rows / 4)

        w = np.zeros(2, np.float32)
        produced = 0
        for bx, by in ds.iter_batches(r, n, bs, steps, seed=1):
            g = 2.0 / len(bx) * bx.T @ (bx @ w - by)
            g = np.asarray(hvt.allreduce(g.astype(np.float32),
                                         name="grad", average=True))
            w = w - 0.2 * g
            produced += 1
        assert produced == steps, (produced, steps)

        finals = hvt.allgather_object((r, produced, w.tolist()))
        ws = [tuple(f[2]) for f in finals]
        assert len(set(ws)) == 1, finals          # identical on every rank
        assert all(f[1] == steps for f in finals)
        print(f"GANG-OOC-OK-{r}", flush=True)
    """, np=4, timeout=150,
        extra_env={"HVT_TEST_STORE": share})
    for i in range(4):
        assert f"GANG-OOC-OK-{i}" in out
