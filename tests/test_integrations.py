"""Ray / Spark integration tests driven by fakes — no ray or pyspark
installed (the reference's ``test/single/test_ray*.py`` use a local ray
cluster; the pure-logic cores here are testable without one)."""

import pytest

from horovod_tpu.ray.elastic import ElasticRayExecutor, RayHostDiscovery
from horovod_tpu.ray.runner import Coordinator, RayExecutor
from horovod_tpu.runner.elastic.discovery import FixedHostDiscovery
from horovod_tpu.spark.runner import slot_envs_from_task_infos


# -------------------------------------------------------- Coordinator

def test_coordinator_single_node():
    c = Coordinator("10.0.0.1", 29560)
    for _ in range(4):
        c.register("nodeA")
    envs = c.slot_envs()
    assert [e["HVT_PROCESS_ID"] for e in envs] == ["0", "1", "2", "3"]
    assert all(e["HVT_LOCAL_SIZE"] == "4" for e in envs)
    assert [e["HVT_LOCAL_PROCESS_ID"] for e in envs] == \
        ["0", "1", "2", "3"]
    assert all(e["HVT_CROSS_SIZE"] == "1" for e in envs)
    assert all(e["HVT_MASTER_ADDR"] == "10.0.0.1" for e in envs)


def test_coordinator_multi_node_grouping():
    """Workers registered interleaved across nodes still get consecutive
    ranks per node (reference Coordinator:178 groups by hostname)."""
    c = Coordinator("m", 1)
    order = ["A", "B", "A", "B"]          # registration order interleaved
    for h in order:
        c.register(h)
    envs = c.slot_envs()
    # envs are indexed by registration order
    byhost = {}
    for reg_idx, env in enumerate(envs):
        byhost.setdefault(order[reg_idx], []).append(
            (int(env["HVT_PROCESS_ID"]),
             int(env["HVT_LOCAL_PROCESS_ID"]),
             int(env["HVT_CROSS_RANK"])))
    assert byhost["A"] == [(0, 0, 0), (1, 1, 0)]
    assert byhost["B"] == [(2, 0, 1), (3, 1, 1)]


# ---------------------------------------------------- RayHostDiscovery

def _node(host, cpu=0, gpu=0, alive=True):
    return {"Alive": alive, "NodeManagerHostname": host,
            "Resources": {"CPU": cpu, "GPU": gpu}}


def test_ray_discovery_cpu_slots():
    d = RayHostDiscovery(cpus_per_slot=2, nodes_fn=lambda: [
        _node("a", cpu=8), _node("b", cpu=3),
        _node("dead", cpu=8, alive=False)])
    assert d.find_available_hosts_and_slots() == {"a": 4, "b": 1}


def test_ray_discovery_gpu_slots():
    d = RayHostDiscovery(use_gpu=True, nodes_fn=lambda: [
        _node("a", cpu=8, gpu=2), _node("b", cpu=8, gpu=0)])
    assert d.find_available_hosts_and_slots() == {"a": 2}


def test_elastic_ray_executor_with_fake_cluster():
    ex = ElasticRayExecutor(
        min_np=2, max_np=2,
        override_discovery=FixedHostDiscovery({"localhost": 2}))
    ex.start()
    try:
        results = ex.run(lambda slot: 0, np=2)
        assert set(results.values()) == {0}
    finally:
        ex.shutdown()


def test_elastic_ray_executor_propagates_failure():
    ex = ElasticRayExecutor(
        min_np=2, max_np=2, reset_limit=0,
        override_discovery=FixedHostDiscovery({"localhost": 2}))
    ex.start()
    try:
        with pytest.raises(RuntimeError, match="reset count|min_np"):
            ex.run(lambda slot: 1 if slot.rank == 1 else 0, np=2)
    finally:
        ex.shutdown()


# -------------------------------------------------------------- Spark

def test_spark_slot_envs_multi_host():
    envs = slot_envs_from_task_infos(
        ["hostA:123", "hostA:124", "hostB:125"], master_port=29570)
    assert [e["HVT_PROCESS_ID"] for e in envs] == ["0", "1", "2"]
    assert [e["HVT_LOCAL_PROCESS_ID"] for e in envs] == ["0", "1", "0"]
    assert [e["HVT_LOCAL_SIZE"] for e in envs] == ["2", "2", "1"]
    assert [e["HVT_CROSS_RANK"] for e in envs] == ["0", "0", "1"]
    # local_rank 0 exists on both hosts; local_rank 1 only on hostA
    assert envs[0]["HVT_CROSS_SIZE"] == "2"
    assert envs[1]["HVT_CROSS_SIZE"] == "1"
    assert all(e["HVT_MASTER_ADDR"] == "hostA" for e in envs)


# -------------------------------------------------------------- gating

def test_ray_executor_gated():
    try:
        import ray  # noqa: F401

        pytest.skip("ray installed; gating not applicable")
    except ImportError:
        pass
    ex = RayExecutor(num_workers=2)
    with pytest.raises(ImportError, match="hvtrun"):
        ex.start()


def test_spark_run_gated():
    try:
        import pyspark  # noqa: F401

        pytest.skip("pyspark installed; gating not applicable")
    except ImportError:
        pass
    from horovod_tpu.spark import run

    with pytest.raises(ImportError, match="pyspark"):
        run(lambda: None, num_proc=2)
