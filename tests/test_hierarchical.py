"""Hierarchical collective tests: RS→AR→AG must equal a flat global
psum, including non-divisible (remainder) sizes — the semantics of the
reference's NCCLHierarchicalAllreduce (nccl_operations.cc:188-350)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops.hierarchical import (hierarchical_allgather,
                                          hierarchical_allreduce)
from horovod_tpu.parallel.mesh import CROSS_AXIS, LOCAL_AXIS, \
    make_parallel_mesh


def _mesh_2x4():
    return make_parallel_mesh(**{CROSS_AXIS: 2, LOCAL_AXIS: 4})


@pytest.mark.parametrize("shape", [(8, 16), (5, 7), (3,), (1,)])
def test_hierarchical_allreduce_equals_flat_psum(shape):
    mesh = _mesh_2x4()
    n = 8
    xs = np.random.RandomState(0).randn(n, *shape).astype(np.float32)

    def step(x):
        x = x.reshape(shape)            # drop leading shard dim
        hier = hierarchical_allreduce(x)
        flat = jax.lax.psum(x, (LOCAL_AXIS, CROSS_AXIS))
        return hier[None], flat[None]

    sharded = shard_map(step, mesh=mesh,
                        in_specs=P((CROSS_AXIS, LOCAL_AXIS)),
                        out_specs=(P((CROSS_AXIS, LOCAL_AXIS)),
                                   P((CROSS_AXIS, LOCAL_AXIS))),
                        check_vma=False)
    hier, flat = sharded(jnp.asarray(xs.reshape(n, *shape)))
    np.testing.assert_allclose(np.asarray(hier), np.asarray(flat),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hier)[0],
                               xs.sum(axis=0), rtol=1e-4, atol=1e-4)


def test_hierarchical_allreduce_average_pytree():
    mesh = _mesh_2x4()
    xs = np.arange(8, dtype=np.float32)

    def step(x):
        tree = {"a": x, "b": 2 * x}
        out = hierarchical_allreduce(tree, average=True)
        return out["a"][None], out["b"][None]

    sharded = shard_map(lambda x: step(x.reshape(())), mesh=mesh,
                        in_specs=P((CROSS_AXIS, LOCAL_AXIS)),
                        out_specs=(P((CROSS_AXIS, LOCAL_AXIS)),) * 2,
                        check_vma=False)
    a, b = sharded(jnp.asarray(xs))
    np.testing.assert_allclose(np.asarray(a), xs.mean())
    np.testing.assert_allclose(np.asarray(b), 2 * xs.mean())


def test_hierarchical_allgather_rank_order():
    mesh = _mesh_2x4()
    xs = np.arange(8, dtype=np.float32).reshape(8, 1)

    def step(x):
        return hierarchical_allgather(x)[None]

    sharded = shard_map(lambda x: step(x), mesh=mesh,
                        in_specs=P((CROSS_AXIS, LOCAL_AXIS)),
                        out_specs=P((CROSS_AXIS, LOCAL_AXIS)),
                        check_vma=False)
    out = sharded(jnp.asarray(xs))
    # every rank sees all rows in global rank order
    np.testing.assert_allclose(np.asarray(out)[0].reshape(-1),
                               np.arange(8, dtype=np.float32))
