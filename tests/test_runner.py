"""Launcher unit tests (reference ``test/single/test_run.py``: CLI parsing,
command construction, env plumbing — 58 tests there; the same concerns
covered here without mocks where possible)."""

import json
import urllib.request

import pytest

from horovod_tpu.runner.hosts import (HostInfo, get_host_assignments,
                                      parse_hosts)
from horovod_tpu.runner.launch import build_commands, parse_args, slot_env


def test_parse_hosts():
    hs = parse_hosts("a:2, b:4,c")
    assert [(h.hostname, h.slots) for h in hs] == [("a", 2), ("b", 4),
                                                   ("c", 1)]


def test_host_assignments_single_host():
    slots = get_host_assignments([HostInfo("localhost", 4)], 4)
    assert [s.rank for s in slots] == [0, 1, 2, 3]
    assert [s.local_rank for s in slots] == [0, 1, 2, 3]
    assert all(s.cross_size == 1 and s.cross_rank == 0 for s in slots)


def test_host_assignments_two_hosts():
    slots = get_host_assignments(
        [HostInfo("h1", 2), HostInfo("h2", 2)], 4)
    assert [(s.hostname, s.rank, s.local_rank, s.cross_rank)
            for s in slots] == [("h1", 0, 0, 0), ("h1", 1, 1, 0),
                                ("h2", 2, 0, 1), ("h2", 3, 1, 1)]
    assert all(s.local_size == 2 and s.cross_size == 2 for s in slots)


def test_host_assignments_uneven():
    slots = get_host_assignments([HostInfo("h1", 3), HostInfo("h2", 1)], 4)
    # h2 has no slot at local_rank 1,2 → cross_size differs per local_rank
    by_rank = {s.rank: s for s in slots}
    assert by_rank[0].cross_size == 2  # local_rank 0 exists on both
    assert by_rank[1].cross_size == 1  # local_rank 1 only on h1


def test_oversubscription_rejected():
    with pytest.raises(ValueError, match="exceeds available slots"):
        get_host_assignments([HostInfo("h1", 2)], 3)


def test_parse_args_basic():
    args = parse_args(["-np", "4", "python", "train.py", "--lr", "0.1"])
    assert args.num_proc == 4
    assert args.command == ["python", "train.py", "--lr", "0.1"]
    assert args.backend == "engine"


def test_slot_env_plumbing():
    args = parse_args(["-np", "2", "--timeline", "/tmp/t.json", "python",
                       "x.py"])
    slots = get_host_assignments([HostInfo("localhost", 2)], 2)
    env = slot_env({}, slots[1], args, "127.0.0.1")
    assert env["HVT_PROCESS_ID"] == "1"
    assert env["HVT_NUM_PROCESSES"] == "2"
    assert env["HVT_MASTER_ADDR"] == "127.0.0.1"
    assert env["HVT_TIMELINE"] == "/tmp/t.json"
    assert env["HVT_FUSION_THRESHOLD"] == str(64 << 20)


def test_build_commands_local_vs_ssh():
    args = parse_args(["-np", "2", "python", "x.py"])
    slots = get_host_assignments(
        [HostInfo("localhost", 1), HostInfo("farhost", 1)], 2)
    cmds = build_commands(args, slots, "localhost")
    assert cmds[0][0] == ["python", "x.py"]
    assert cmds[1][0][0] == "ssh"
    assert "farhost" in cmds[1][0]
    joined = " ".join(cmds[1][0])
    assert "HVT_PROCESS_ID=1" in joined


def test_jax_backend_env():
    args = parse_args(["-np", "2", "--backend", "jax", "python", "x.py"])
    slots = get_host_assignments([HostInfo("localhost", 2)], 2)
    env = slot_env({}, slots[0], args, "127.0.0.1")
    assert "HVT_COORDINATOR_ADDR" in env
    assert "HVT_MASTER_ADDR" not in env


def test_rendezvous_server_roundtrip():
    from horovod_tpu.runner.http_server import RendezvousServer

    slots = get_host_assignments([HostInfo("h1", 2)], 2)
    srv = RendezvousServer()
    srv.init(slots)
    port = srv.start(0)
    base = f"http://127.0.0.1:{port}"
    try:
        # slot info
        with urllib.request.urlopen(f"{base}/rendezvous/h1/1") as r:
            info = json.loads(r.read())
        assert info["rank"] == 1 and info["local_size"] == 2
        # world
        with urllib.request.urlopen(f"{base}/world") as r:
            world = json.loads(r.read())
        assert world["size"] == 2 and world["hosts"] == ["h1"]
        # scoped KV
        req = urllib.request.Request(f"{base}/kv/global/addr", data=b"x:1",
                                     method="PUT")
        urllib.request.urlopen(req)
        with urllib.request.urlopen(f"{base}/kv/global/addr") as r:
            assert r.read() == b"x:1"
        with urllib.request.urlopen(f"{base}/keys/global") as r:
            assert json.loads(r.read()) == ["addr"]
        # missing key → 404
        try:
            urllib.request.urlopen(f"{base}/kv/global/nope")
            assert False
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_launcher_sigkill_leaves_no_orphan_workers(tmp_path):
    """SIGKILL the launcher mid-job: workers must die via PDEATHSIG, not
    leak (reference safe_shell_exec.py:60-140 parent-death contract)."""
    import os
    import signal
    import subprocess
    import sys
    import time

    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os, sys, time\n"
        "print(f'WPID {os.getpid()}', flush=True)\n"
        "time.sleep(120)\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    launcher = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(worker)],
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    pids = []
    deadline = time.time() + 30
    while len(pids) < 2 and time.time() < deadline:
        line = launcher.stdout.readline()
        if "WPID" in line:
            pids.append(int(line.rsplit(" ", 1)[1]))
    assert len(pids) == 2, f"workers did not start (got {pids})"
    launcher.kill()  # SIGKILL: launcher gets NO chance to clean up
    launcher.wait()
    deadline = time.time() + 10
    alive = set(pids)
    while alive and time.time() < deadline:
        for pid in list(alive):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                alive.discard(pid)
        time.sleep(0.2)
    for pid in alive:  # cleanup before failing
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    assert not alive, f"orphan workers survived launcher SIGKILL: {alive}"


def test_check_build_reports_capabilities(capsys):
    """hvtrun --check-build (reference runner/launch.py:110): prints the
    capability table without requiring -np, exits 0."""
    from horovod_tpu.runner.launch import main

    assert main(["--check-build"]) == 0
    out = capsys.readouterr().out
    assert "Available Frameworks" in out
    assert "[X] JAX (core)" in out
    assert "XLA/ICI compiled collectives" in out
    # engine is built in this tree (conftest builds it)
    assert "[X] TCP control star" in out
