"""ProcessSet semantics units (ISSUE 6 satellite): ``included()`` is
EXACT rank membership, agreeing with the engine's submit-side check.

The old ``[rank, rank + local_size)`` slot-range heuristic reported
``included() == True`` for processes whose *neighbors'* ranks were in
the set — and the engine (``engine/native.py``) then rejected their
submit. Both paths are pinned here; the gang-level agreement (member
submits succeed, non-member submits raise) is pinned in
``tests/test_serving.py::test_concurrent_disjoint_sets_4proc``.
"""

import numpy as np
import pytest

from horovod_tpu.common import basics
from horovod_tpu.common.process_sets import ProcessSet


def test_included_is_exact_membership(monkeypatch):
    monkeypatch.setattr(basics, "rank", lambda: 1)
    monkeypatch.setattr(basics, "local_size", lambda: 4)
    assert ProcessSet([0, 1]).included()
    assert ProcessSet([1, 3]).included()
    assert ProcessSet(None).included()
    # the slot-range heuristic claimed all of these (1 <= r < 5):
    assert not ProcessSet([2, 3]).included()
    assert not ProcessSet([4]).included()
    assert not ProcessSet([2, 4, 9]).included()


def test_included_false_outside_any_range(monkeypatch):
    monkeypatch.setattr(basics, "rank", lambda: 6)
    monkeypatch.setattr(basics, "local_size", lambda: 1)
    assert not ProcessSet([0, 1]).included()
    assert ProcessSet([5, 6]).included()


def test_included_agrees_with_engine_submit_membership(monkeypatch):
    """included() must predict the engine's submit acceptance exactly:
    a rank for which included() is False gets a ValueError from
    native.submit, never a silent mispairing."""
    from horovod_tpu.engine import native

    monkeypatch.setattr(native, "engine_running", lambda: True)
    monkeypatch.setattr(native, "engine_size", lambda: 4)
    monkeypatch.setattr(native, "engine_rank", lambda: 3)
    monkeypatch.setattr(basics, "rank", lambda: 3)
    monkeypatch.setattr(basics, "local_size", lambda: 2)

    ps = ProcessSet([0, 1])
    assert not ps.included()
    with pytest.raises(ValueError, match="not in process set"):
        native.submit("allreduce", np.ones(4, np.float32), "numpy",
                      name="x", process_set=ps)

    # a member's included() is True and the same submit-side gate passes
    member = ProcessSet([1, 3])
    assert member.included()


def test_rank_in_set_and_size(monkeypatch):
    monkeypatch.setattr(basics, "rank", lambda: 2)
    ps = ProcessSet([0, 2, 5])
    assert ps.size() == 3
    assert ps.rank_in_set(2) == 1
    assert ps.included()
