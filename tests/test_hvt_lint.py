"""Unit tests for the cross-language contract linter
(horovod_tpu/tools/hvt_lint.py).

Strategy: build a MINIMAL but fully consistent fixture tree (tiny
c_api.cc / stats manifest / native.py / basics.py / events.h /
timeline.py / wire.h / docs), assert the lint passes it clean, then
seed one violation per test and assert the lint fails with a pointed
message. A final test asserts the REAL tree passes every pass — that
is the tier-1 contract gate itself.
"""

import os
import textwrap
from pathlib import Path

from horovod_tpu.tools import hvt_lint

REPO_ROOT = Path(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _write(root: Path, rel: str, text: str):
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(text))


def make_clean_tree(root: Path):
    """A consistent mini-repo: 2 C symbols, a 13-slot stats ABI
    (2 scalars, 1 op, 1+2-slot histograms, 1 abort cause), 2 event
    kinds, 2 frame flags, 1 documented env knob."""
    _write(root, hvt_lint.C_API_CC, """\
        #include "stats_slots.h"
        constexpr int kStatsScalars = 2;
        static_assert(13 == HVT_STATS_SLOT_COUNT, "slots are append-only");
        extern "C" {
        int hvt_init(int rank) { return rank; }
        int hvt_poll(int h) { return h; }
        }
        """)
    _write(root, hvt_lint.ENGINE_H, """\
        constexpr int kStatsOps = 1;
        constexpr int kLatBuckets = 0;
        constexpr int kAbortCauses = 1;
        """)
    _write(root, hvt_lint.STATS_SLOTS_H, """\
        #define HVT_STATS_SLOT_COUNT 13
        #define HVT_STATS_SLOTS(X) \\
          X(0, "a") \\
          X(1, "b") \\
          X(2, "exec_ns[allreduce]") \\
          X(3, "exec_count[allreduce]") \\
          X(4, "wire_tx_bytes[allreduce]") \\
          X(5, "wire_tx_comp_bytes[allreduce]") \\
          X(6, "cycle_hist.bucket[0]") \\
          X(7, "cycle_hist.sum_ns") \\
          X(8, "cycle_hist.count") \\
          X(9, "wakeup_hist.bucket[0]") \\
          X(10, "wakeup_hist.sum_ns") \\
          X(11, "wakeup_hist.count") \\
          X(12, "aborts[internal]")
        """)
    _write(root, hvt_lint.NATIVE_PY, """\
        STATS_SCALARS = ("a", "b")
        STATS_OPS = ("allreduce",)
        STATS_LAT_BUCKETS = 0
        ABORT_CAUSES = ("internal",)
        EVENT_KINDS = ("ENQUEUED", "DONE")


        def bind(lib):
            lib.hvt_init(0)
            return lib.hvt_poll(0)
        """)
    _write(root, hvt_lint.BASICS_PY, """\
        import os

        _KNOB = os.environ.get("HVT_FOO")


        def poll_engine_stats(stats):
            return [stats.get(k) for k in (
                "a", "b", "exec_ns", "exec_count", "wire_tx_bytes",
                "wire_tx_comp_bytes", "cycle_hist", "wakeup_hist",
                "aborts")]
        """)
    _write(root, hvt_lint.EVENTS_H, """\
        enum class EventKind : int32_t {
          ENQUEUED = 0,
          DONE = 1,
        };
        """)
    _write(root, hvt_lint.TIMELINE_PY, """\
        _ENQUEUED, _DONE = range(2)


        def drain(kind):
            if kind == _ENQUEUED:
                return "enqueued"
            if kind == _DONE:
                return "done"
            return None
        """)
    _write(root, hvt_lint.WIRE_H, """\
        constexpr uint8_t kCtrlFlagShutdown = 0x01;
        constexpr uint8_t kAbortFrameFlag = 0x80;
        """)
    _write(root, hvt_lint.ENGINE_CC, """\
        #include "wire.h"
        int use_flags() { return kCtrlFlagShutdown | kAbortFrameFlag; }
        """)
    _write(root, "docs/index.md", """\
        # Mini docs

        - `HVT_FOO`: the one knob of the fixture tree.
        """)


def test_fixture_tree_is_clean(tmp_path):
    make_clean_tree(tmp_path)
    assert hvt_lint.run(tmp_path) == []


# ---------------------------------------------------------------- capi

def test_unbound_c_symbol_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.C_API_CC
    p.write_text(p.read_text().replace(
        "int hvt_poll",
        "int hvt_orphan(int x) { return x; }\nint hvt_poll"))
    vios = hvt_lint.check_capi(tmp_path)
    assert any("hvt_orphan" in v and "bound nowhere" in v for v in vios), vios


def test_binding_unknown_symbol_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.NATIVE_PY
    p.write_text(p.read_text() + "\n\ndef bad(lib):\n"
                                 "    return lib.hvt_ghost()\n")
    vios = hvt_lint.check_capi(tmp_path)
    assert any("hvt_ghost" in v and "does not define" in v
               for v in vios), vios


def test_emit_symbols_lists_the_extern_c_surface(tmp_path):
    make_clean_tree(tmp_path)
    assert hvt_lint.c_api_symbols(tmp_path) == ["hvt_init", "hvt_poll"]


# --------------------------------------------------------------- slots

def test_reused_slot_index_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.STATS_SLOTS_H
    p.write_text(p.read_text().replace('X(1, "b")', 'X(0, "b")'))
    vios = hvt_lint.check_slots(tmp_path)
    assert any("never be reused" in v for v in vios), vios


def test_slot_count_drift_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.STATS_SLOTS_H
    p.write_text(p.read_text().replace(
        "#define HVT_STATS_SLOT_COUNT 13",
        "#define HVT_STATS_SLOT_COUNT 14"))
    vios = hvt_lint.check_slots(tmp_path)
    assert any("HVT_STATS_SLOT_COUNT" in v for v in vios), vios


def test_manifest_python_layout_mismatch_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.STATS_SLOTS_H
    p.write_text(p.read_text().replace('X(1, "b")', 'X(1, "renamed")'))
    vios = hvt_lint.check_slots(tmp_path)
    assert any("does not match" in v and "layout" in v for v in vios), vios


def _add_lane_slots(root: Path, n=2):
    """Extend the clean fixture with an n-bucket lane block (the PR-6
    per-set lane telemetry appendix): engine.h kLaneSlots, native.py
    STATS_LANE_SLOTS, manifest rows, and the bridge reads."""
    eh = root / hvt_lint.ENGINE_H
    eh.write_text(eh.read_text() + f"constexpr int kLaneSlots = {n};\n")
    np_ = root / hvt_lint.NATIVE_PY
    np_.write_text(f"STATS_LANE_SLOTS = {n}\n" + np_.read_text())
    rows = ['  X(13, "lanes_active")']
    idx = 14
    for grp in hvt_lint.SLOT_LANE_GROUPS:
        for i in range(n):
            rows.append(f'  X({idx}, "{grp}[{i}]")')
            idx += 1
    sl = root / hvt_lint.STATS_SLOTS_H
    sl.write_text(sl.read_text()
                  .replace("#define HVT_STATS_SLOT_COUNT 13",
                           f"#define HVT_STATS_SLOT_COUNT {idx}")
                  .rstrip("\n") + " \\\n" + " \\\n".join(rows) + "\n")
    ca = root / hvt_lint.C_API_CC
    ca.write_text(ca.read_text().replace(
        "static_assert(13 ==", f"static_assert({idx} =="))
    bp = root / hvt_lint.BASICS_PY
    bp.write_text(bp.read_text().replace(
        '"aborts")', '"aborts", "lanes_active", "lane_depth", '
                     '"lane_exec_ns", "lane_exec_count")'))


def test_lane_slot_fixture_is_clean(tmp_path):
    make_clean_tree(tmp_path)
    _add_lane_slots(tmp_path)
    assert hvt_lint.check_slots(tmp_path) == []


def test_lane_slot_count_mismatch_fails(tmp_path):
    """engine.h kLaneSlots drifting from native.py STATS_LANE_SLOTS
    would decode the lane blocks shifted — the lint must catch it."""
    make_clean_tree(tmp_path)
    _add_lane_slots(tmp_path)
    p = tmp_path / hvt_lint.NATIVE_PY
    p.write_text(p.read_text().replace("STATS_LANE_SLOTS = 2",
                                       "STATS_LANE_SLOTS = 3"))
    vios = hvt_lint.check_slots(tmp_path)
    assert any("kLaneSlots" in v for v in vios), vios


def _add_tail_scalars(root: Path):
    """Extend the clean fixture with a 1-slot trailing scalar block (the
    PR-7 ctrl-bytes appendix shape): c_api.cc kStatsTailScalars,
    native.py STATS_TAIL_SCALARS, a manifest row, and the bridge read."""
    ca = root / hvt_lint.C_API_CC
    ca.write_text(ca.read_text()
                  .replace("constexpr int kStatsScalars = 2;",
                           "constexpr int kStatsScalars = 2;\n"
                           "constexpr int kStatsTailScalars = 1;")
                  .replace("static_assert(13 ==", "static_assert(14 =="))
    np_ = root / hvt_lint.NATIVE_PY
    np_.write_text('STATS_TAIL_SCALARS = ("tail_z",)\n' + np_.read_text())
    sl = root / hvt_lint.STATS_SLOTS_H
    sl.write_text(sl.read_text()
                  .replace("#define HVT_STATS_SLOT_COUNT 13",
                           "#define HVT_STATS_SLOT_COUNT 14")
                  .rstrip("\n") + ' \\\n  X(13, "tail_z")\n')
    bp = root / hvt_lint.BASICS_PY
    bp.write_text(bp.read_text().replace('"aborts")', '"aborts", "tail_z")'))


def test_tail_scalar_fixture_is_clean(tmp_path):
    make_clean_tree(tmp_path)
    _add_tail_scalars(tmp_path)
    assert hvt_lint.check_slots(tmp_path) == []


def test_tail_scalar_count_mismatch_fails(tmp_path):
    """c_api.cc kStatsTailScalars drifting from native.py
    STATS_TAIL_SCALARS would decode the trailing block shifted."""
    make_clean_tree(tmp_path)
    _add_tail_scalars(tmp_path)
    p = tmp_path / hvt_lint.C_API_CC
    p.write_text(p.read_text().replace("kStatsTailScalars = 1",
                                       "kStatsTailScalars = 2"))
    vios = hvt_lint.check_slots(tmp_path)
    assert any("kStatsTailScalars" in v for v in vios), vios


def _add_link_slots(root: Path):
    """Extend the clean fixture with the self-healing link appendix
    (PR-10 shape): c_api.cc kStatsLinkPlanes/kStatsRecoveryScalars,
    native.py STATS_LINK_PLANES/STATS_RECOVERY_SCALARS, manifest rows,
    and the bridge reads."""
    ca = root / hvt_lint.C_API_CC
    ca.write_text(ca.read_text()
                  .replace("constexpr int kStatsScalars = 2;",
                           "constexpr int kStatsScalars = 2;\n"
                           "constexpr int kStatsLinkPlanes = 2;\n"
                           "constexpr int kStatsRecoveryScalars = 1;")
                  .replace("static_assert(13 ==", "static_assert(16 =="))
    np_ = root / hvt_lint.NATIVE_PY
    np_.write_text('STATS_LINK_PLANES = ("ctrl", "data")\n'
                   'STATS_RECOVERY_SCALARS = ("replay_z",)\n'
                   + np_.read_text())
    sl = root / hvt_lint.STATS_SLOTS_H
    sl.write_text(sl.read_text()
                  .replace("#define HVT_STATS_SLOT_COUNT 13",
                           "#define HVT_STATS_SLOT_COUNT 16")
                  .rstrip("\n") + ' \\\n  X(13, "link_reconnects[ctrl]")'
                  ' \\\n  X(14, "link_reconnects[data]")'
                  ' \\\n  X(15, "replay_z")\n')
    bp = root / hvt_lint.BASICS_PY
    bp.write_text(bp.read_text().replace(
        '"aborts")', '"aborts", "link_reconnects", "replay_z")'))


def test_link_slot_fixture_is_clean(tmp_path):
    make_clean_tree(tmp_path)
    _add_link_slots(tmp_path)
    assert hvt_lint.check_slots(tmp_path) == []


def test_link_plane_count_mismatch_fails(tmp_path):
    """c_api.cc kStatsLinkPlanes drifting from native.py
    STATS_LINK_PLANES would decode the reconnect block shifted."""
    make_clean_tree(tmp_path)
    _add_link_slots(tmp_path)
    p = tmp_path / hvt_lint.C_API_CC
    p.write_text(p.read_text().replace("kStatsLinkPlanes = 2",
                                       "kStatsLinkPlanes = 3"))
    vios = hvt_lint.check_slots(tmp_path)
    assert any("kStatsLinkPlanes" in v for v in vios), vios


def test_recovery_scalar_count_mismatch_fails(tmp_path):
    """c_api.cc kStatsRecoveryScalars drifting from native.py
    STATS_RECOVERY_SCALARS would decode the replay block shifted."""
    make_clean_tree(tmp_path)
    _add_link_slots(tmp_path)
    p = tmp_path / hvt_lint.C_API_CC
    p.write_text(p.read_text().replace("kStatsRecoveryScalars = 1",
                                       "kStatsRecoveryScalars = 2"))
    vios = hvt_lint.check_slots(tmp_path)
    assert any("kStatsRecoveryScalars" in v for v in vios), vios


def test_unread_link_slot_group_fails(tmp_path):
    """A manifest slot group (link_reconnects) nobody reads in
    poll_engine_stats is telemetry silently thrown away."""
    make_clean_tree(tmp_path)
    _add_link_slots(tmp_path)
    bp = tmp_path / hvt_lint.BASICS_PY
    bp.write_text(bp.read_text().replace('"link_reconnects"',
                                         '"link_ignored"'))
    vios = hvt_lint.check_slots(tmp_path)
    assert any('never reads "link_reconnects"' in v for v in vios), vios


def test_unread_slot_group_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.BASICS_PY
    p.write_text(p.read_text().replace('"aborts"', '"ignored"'))
    vios = hvt_lint.check_slots(tmp_path)
    assert any('never reads "aborts"' in v for v in vios), vios


# -------------------------------------------------------------- events

def test_undrained_event_kind_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.TIMELINE_PY
    body = p.read_text().replace(
        '    if kind == _DONE:\n        return "done"\n', "")
    p.write_text(body)
    vios = hvt_lint.check_events(tmp_path)
    assert any("DONE" in v and "never referenced by the drainer" in v
               for v in vios), vios


def test_event_kind_tuple_drift_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.NATIVE_PY
    p.write_text(p.read_text().replace(
        'EVENT_KINDS = ("ENQUEUED", "DONE")',
        'EVENT_KINDS = ("ENQUEUED",)'))
    vios = hvt_lint.check_events(tmp_path)
    assert any("EVENT_KINDS" in v for v in vios), vios


def test_frame_flag_bit_collision_fails(tmp_path):
    make_clean_tree(tmp_path)
    p = tmp_path / hvt_lint.WIRE_H
    p.write_text(p.read_text()
                 + "constexpr uint8_t kCtrlFlagJoin = 0x80;\n")
    cc = tmp_path / hvt_lint.ENGINE_CC
    cc.write_text(cc.read_text().replace(
        "kCtrlFlagShutdown |", "kCtrlFlagShutdown | kCtrlFlagJoin |"))
    vios = hvt_lint.check_events(tmp_path)
    assert any("both claim bit 0x80" in v for v in vios), vios


def test_flag_defined_outside_registry_fails(tmp_path):
    make_clean_tree(tmp_path)
    cc = tmp_path / hvt_lint.ENGINE_CC
    cc.write_text("constexpr uint8_t kAbortFrameFlag = 0x80;\n"
                  + cc.read_text())
    vios = hvt_lint.check_events(tmp_path)
    assert any("re-defines kAbortFrameFlag" in v for v in vios), vios


def _add_ctrl_roles(root: Path):
    """Extend the clean fixture with the control-plane role registry
    (PR-8 hierarchical negotiation): engine.h CtrlRole ↔ timeline.py
    CTRL_ROLES."""
    eh = root / hvt_lint.ENGINE_H
    eh.write_text(eh.read_text() + """\
enum class CtrlRole : int32_t {
  ROOT = 0,
  LEADER = 1,
  MEMBER = 2,
};
""")
    tl = root / hvt_lint.TIMELINE_PY
    tl.write_text('CTRL_ROLES = ("root", "leader", "member")\n'
                  + tl.read_text())


def test_ctrl_role_fixture_is_clean(tmp_path):
    make_clean_tree(tmp_path)
    _add_ctrl_roles(tmp_path)
    assert hvt_lint.check_events(tmp_path) == []


def test_ctrl_role_registry_drift_fails(tmp_path):
    """timeline.py CTRL_ROLES drifting from engine.h CtrlRole (here a
    reordered pair) must fail — CTRL instants would attribute control
    bytes to the wrong role."""
    make_clean_tree(tmp_path)
    _add_ctrl_roles(tmp_path)
    tl = tmp_path / hvt_lint.TIMELINE_PY
    tl.write_text(tl.read_text().replace(
        '("root", "leader", "member")', '("root", "member", "leader")'))
    vios = hvt_lint.check_events(tmp_path)
    assert any("CTRL_ROLES" in v and "wrong role" in v
               for v in vios), vios


def test_ctrl_role_one_sided_registry_fails(tmp_path):
    """CTRL_ROLES without the C++ enum (or vice versa) is a violation:
    the registry is a cross-language contract, not a constant."""
    make_clean_tree(tmp_path)
    tl = tmp_path / hvt_lint.TIMELINE_PY
    tl.write_text('CTRL_ROLES = ("root", "leader", "member")\n'
                  + tl.read_text())
    vios = hvt_lint.check_events(tmp_path)
    assert any("no enum class CtrlRole" in v for v in vios), vios


# ----------------------------------------------------------------- env

def test_undocumented_env_read_fails(tmp_path):
    make_clean_tree(tmp_path)
    _write(tmp_path, "horovod_tpu/runner/launch.py", """\
        import os

        SECRET = os.environ.get("HVT_SECRET")
        """)
    vios = hvt_lint.check_env(tmp_path)
    assert any("HVT_SECRET" in v and "documented nowhere" in v
               for v in vios), vios


def test_stale_env_doc_row_fails(tmp_path):
    make_clean_tree(tmp_path)
    _write(tmp_path, "docs/ghost.md", "`HVT_GHOST` does nothing now.\n")
    vios = hvt_lint.check_env(tmp_path)
    assert any("HVT_GHOST" in v and "no code reads it" in v
               for v in vios), vios


# ------------------------------------------------- codec registry pass

def make_codec_tree(root: Path):
    """Minimal consistent codec registry: 2 codecs across codecs.h, the
    compression name table, native.py WIRE_CODECS, and the docs codec
    table."""
    make_clean_tree(root)
    _write(root, hvt_lint.CODECS_H, """\
        #define HVT_WIRE_CODECS(X) \\
          X(0, "none")             \\
          X(1, "bf16")
        enum class WireCodec : uint8_t {
          RAW = 0,
          BF16 = 1,
        };
        constexpr int kWireCodecCount = 2;
        """)
    _write(root, hvt_lint.COMPRESSION_PY, """\
        CODEC_IDS = {"none": 0, "bf16": 1}
        """)
    _write(root, hvt_lint.NATIVE_PY, """\
        STATS_SCALARS = ("a", "b")
        STATS_OPS = ("allreduce",)
        STATS_LAT_BUCKETS = 0
        ABORT_CAUSES = ("internal",)
        EVENT_KINDS = ("ENQUEUED", "DONE")
        WIRE_CODECS = ("none", "bf16")


        def bind(lib):
            lib.hvt_init(0)
            return lib.hvt_poll(0)
        """)
    _write(root, hvt_lint.PERFORMANCE_MD, """\
        # Perf

        #### Codec table

        | codec | ratio |
        |---|---|
        | `none` | 1x |
        | `bf16` | 2x |
        """)


def test_codec_fixture_is_clean(tmp_path):
    make_codec_tree(tmp_path)
    # codec rows are absent from the fixture stats manifest, so run the
    # codecs pass alone (slots stays covered by its own fixtures)
    assert hvt_lint.check_codecs(tmp_path) == []


def test_codec_registry_absent_is_fine(tmp_path):
    make_clean_tree(tmp_path)
    assert hvt_lint.check_codecs(tmp_path) == []


def test_codec_python_table_drift_fails(tmp_path):
    make_codec_tree(tmp_path)
    _write(tmp_path, hvt_lint.COMPRESSION_PY, """\
        CODEC_IDS = {"none": 0, "bf16": 2}
        """)
    vios = hvt_lint.check_codecs(tmp_path)
    assert any("CODEC_IDS" in v and "does not match" in v
               for v in vios), vios


def test_codec_native_tuple_drift_fails(tmp_path):
    make_codec_tree(tmp_path)
    text = (tmp_path / hvt_lint.NATIVE_PY).read_text()
    _write(tmp_path, hvt_lint.NATIVE_PY,
           text.replace('WIRE_CODECS = ("none", "bf16")',
                        'WIRE_CODECS = ("none",)'))
    vios = hvt_lint.check_codecs(tmp_path)
    assert any("WIRE_CODECS" in v for v in vios), vios


def test_codec_id_renumber_fails(tmp_path):
    make_codec_tree(tmp_path)
    text = (tmp_path / hvt_lint.CODECS_H).read_text()
    _write(tmp_path, hvt_lint.CODECS_H,
           text.replace('X(1, "bf16")', 'X(2, "bf16")'))
    vios = hvt_lint.check_codecs(tmp_path)
    assert any("contiguous" in v for v in vios), vios


def test_codec_docs_table_drift_fails(tmp_path):
    make_codec_tree(tmp_path)
    text = (tmp_path / hvt_lint.PERFORMANCE_MD).read_text()
    # stale doc row for a codec the registry no longer lists
    _write(tmp_path, hvt_lint.PERFORMANCE_MD,
           text + "| `zstd` | 9x |\n")
    vios = hvt_lint.check_codecs(tmp_path)
    assert any("codec table rows" in v for v in vios), vios


def test_codec_enum_registry_mismatch_fails(tmp_path):
    make_codec_tree(tmp_path)
    text = (tmp_path / hvt_lint.CODECS_H).read_text()
    _write(tmp_path, hvt_lint.CODECS_H,
           text.replace("BF16 = 1,", "BF16 = 3,"))
    vios = hvt_lint.check_codecs(tmp_path)
    assert any("enum" in v and "registry" in v for v in vios), vios


# ---------------------------------------------------- proto pass


def make_proto_tree(root: Path):
    """Mini tree for the wire-grammar pass: one symmetric Encode/Decode
    pair (2 fixed fields + a counted str list, min element 4 bytes), a
    count()-routed list decoder, a clean transport.h, and the Python
    framing files."""
    make_clean_tree(root)
    _write(root, hvt_lint.WIRE_H, """\
        constexpr uint8_t kCtrlFlagShutdown = 0x01;
        constexpr uint8_t kAbortFrameFlag = 0x80;
        constexpr size_t kMinEncodedPingBytes = 16;

        class Writer {
         public:
          void append(const void* p, size_t n) { memcpy(0, p, n); }
        };
        class Reader {
         public:
          int32_t i32() { int32_t v; memcpy(&v, 0, 4); return v; }
        };

        inline void EncodePing(Writer& w, const Ping& p) {
          w.i32(p.rank);
          w.i64(p.epoch);
          w.i32(static_cast<int32_t>(p.tags.size()));
          for (auto& t : p.tags) w.str(t);
        }

        inline Ping DecodePing(Reader& rd) {
          Ping p;
          p.rank = rd.i32();
          p.epoch = rd.i64();
          size_t n = rd.count(4);
          p.tags.resize(n);
          for (auto& t : p.tags) t = rd.str();
          return p;
        }

        inline void EncodePingList(Writer& w, const std::vector<Ping>& ps) {
          w.i32(static_cast<int32_t>(ps.size()));
          for (auto& p : ps) EncodePing(w, p);
        }

        inline std::vector<Ping> DecodePingList(Reader& rd) {
          size_t n = rd.count(kMinEncodedPingBytes);
          std::vector<Ping> ps(n);
          for (auto& p : ps) p = DecodePing(rd);
          return ps;
        }
        """)
    _write(root, hvt_lint.TRANSPORT_H, """\
        #include "wire.h"
        inline bool ReadHello(Reader& rd) { return rd.i32() == 7; }
        """)
    _write(root, hvt_lint.STATE_PY, """\
        import struct
        from zlib import crc32

        _SHARD_MAGIC = b"HVTS"
        _SHARD_HEADER = struct.Struct("<4sqiIq")


        class ShardCorruptError(RuntimeError):
            pass


        def encode_shard(payload):
            return _SHARD_HEADER.pack(_SHARD_MAGIC, 1, 0,
                                      crc32(payload), len(payload)) + payload


        def decode_shard(blob):
            magic, _v, _o, crc, n = _SHARD_HEADER.unpack_from(blob)
            if magic != _SHARD_MAGIC:
                raise ShardCorruptError("bad magic")
            payload = blob[_SHARD_HEADER.size:_SHARD_HEADER.size + n]
            if crc32(payload) != crc:
                raise ShardCorruptError("bad crc")
            return payload
        """)
    _write(root, hvt_lint.TELEMETRY_PY, """\
        def envelope(scope, key, blob):
            return {"scope": scope, "key": key, "value_b64": blob}
        """)
    _write(root, hvt_lint.HTTP_SERVER_PY, """\
        def handle_kvbulk(envs):
            return [(e["scope"], e["key"], e["value_b64"]) for e in envs]
        """)


def test_proto_fixture_tree_is_clean(tmp_path):
    make_proto_tree(tmp_path)
    assert hvt_lint.check_proto(tmp_path) == []
    assert hvt_lint.run(tmp_path) == [], hvt_lint.run(tmp_path)


def test_proto_field_symmetry_drift_fails(tmp_path):
    # encoder grows a field the decoder never reads
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.WIRE_H).read_text()
    _write(tmp_path, hvt_lint.WIRE_H,
           text.replace("w.i64(p.epoch);",
                        "w.i64(p.epoch);\n  w.u8(p.plane);"))
    vios = hvt_lint.check_proto(tmp_path)
    assert any("field symmetry broken" in v and "EncodePing " in v
               for v in vios), vios


def test_proto_raw_count_resize_fails(tmp_path):
    # the DecodeResponse bug this pass was built to catch: a list
    # allocation sized straight from rd.i32()
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.WIRE_H).read_text()
    _write(tmp_path, hvt_lint.WIRE_H,
           text.replace("size_t n = rd.count(4);\n  p.tags.resize(n);",
                        "int32_t n = rd.i32();\n  p.tags.resize(n);"))
    vios = hvt_lint.check_proto(tmp_path)
    assert any("not routed through Reader::count" in v
               for v in vios), vios


def test_proto_stale_count_bound_fails(tmp_path):
    # a field lands in the encoder; the paired count() bound is stale
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.WIRE_H).read_text()
    _write(tmp_path, hvt_lint.WIRE_H,
           text.replace("w.i64(p.epoch);", "w.i64(p.epoch);\n  w.i64(p.t);")
               .replace("p.epoch = rd.i64();",
                        "p.epoch = rd.i64();\n  p.t = rd.i64();"))
    vios = hvt_lint.check_proto(tmp_path)
    assert any("occupies at least 24 bytes" in v for v in vios), vios


def test_proto_unresolvable_count_bound_fails(tmp_path):
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.WIRE_H).read_text()
    _write(tmp_path, hvt_lint.WIRE_H,
           text.replace("rd.count(kMinEncodedPingBytes)",
                        "rd.count(sizeof(Ping))"))
    vios = hvt_lint.check_proto(tmp_path)
    assert any("not resolvable" in v for v in vios), vios


def test_proto_reader_fork_fails(tmp_path):
    # the transport.h Reader2 this PR folded away must never come back
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.TRANSPORT_H).read_text()
    _write(tmp_path, hvt_lint.TRANSPORT_H, text + textwrap.dedent("""\
        struct Reader2 {
          size_t pos = 0;
          int32_t i32(const std::vector<uint8_t>& b) {
            int32_t v;
            memcpy(&v, b.data() + pos, 4);
            pos += 4;
            return v;
          }
        };
        """))
    vios = hvt_lint.check_proto(tmp_path)
    assert any("Reader2" in v and "wire.h ONLY" in v for v in vios), vios
    assert any("cursor-style" in v for v in vios), vios


def test_proto_memcpy_outside_reader_fails(tmp_path):
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.WIRE_H).read_text()
    _write(tmp_path, hvt_lint.WIRE_H, text + textwrap.dedent("""\
        inline int DecodePeek(const std::vector<uint8_t>& f) {
          int32_t v;
          memcpy(&v, f.data() + 1, 4);
          return v;
        }
        """))
    vios = hvt_lint.check_proto(tmp_path)
    assert any("outside the Writer/Reader" in v for v in vios), vios


def test_proto_flag_literal_fails(tmp_path):
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.ENGINE_CC).read_text()
    _write(tmp_path, hvt_lint.ENGINE_CC, text + textwrap.dedent("""\
        bool is_special(uint8_t first) { return first & 0x40; }
        """))
    vios = hvt_lint.check_proto(tmp_path)
    assert any("literal 0x40" in v and "registry" in v for v in vios), vios


def test_proto_shard_decode_validation_fails(tmp_path):
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.STATE_PY).read_text()
    _write(tmp_path, hvt_lint.STATE_PY,
           text.replace("    if crc32(payload) != crc:\n"
                        "        raise ShardCorruptError(\"bad crc\")\n",
                        ""))
    vios = hvt_lint.check_proto(tmp_path)
    assert any("verify the payload CRC" in v for v in vios), vios


def test_proto_kvbulk_key_drift_fails(tmp_path):
    # producer renames an envelope key the consumer still expects
    make_proto_tree(tmp_path)
    text = (tmp_path / hvt_lint.TELEMETRY_PY).read_text()
    _write(tmp_path, hvt_lint.TELEMETRY_PY,
           text.replace('"value_b64"', '"payload_b64"'))
    vios = hvt_lint.check_proto(tmp_path)
    assert any('"value_b64"' in v and "telemetry" in v for v in vios), vios


def test_proto_real_wire_minimums_match_grammar():
    """The pinned constants in the REAL wire.h equal what the pass
    derives from the real encoder bodies — the self-checking contract
    (add a Request field → this and the proto pass both fail until
    kMinEncodedRequestBytes moves)."""
    text = (REPO_ROOT / hvt_lint.WIRE_H).read_text()
    bodies = hvt_lint._proto_fn_bodies(text)
    mins = hvt_lint._min_encoded_sizes(bodies)
    assert mins["Request"] == 51
    assert mins["Response"] == 58


# ---------------------------------------------------- the real tree

def test_real_tree_passes_every_lint_pass():
    """The tier-1 contract gate: the actual repository must be clean
    under every pass (this is what `ci.sh --lint` runs)."""
    vios = hvt_lint.run(REPO_ROOT)
    assert vios == [], "\n".join(vios)


def test_real_tree_symbol_list_covers_the_bridge():
    syms = hvt_lint.c_api_symbols(REPO_ROOT)
    # spot-check the load-bearing names ci.sh's nm gate must see
    for must in ("hvt_init", "hvt_submit", "hvt_wait", "hvt_engine_stats",
                 "hvt_events_drain", "hvt_wait_timeout",
                 "hvt_engine_broken", "hvt_wire_compression"):
        assert must in syms
    assert len(syms) >= 29


def test_stats_slot_count_matches_python_bridge():
    """The manifest's count equals what the ctypes decoder sizes its
    buffer to — the same invariant the slots pass checks by text, here
    pinned against the imported module."""
    from horovod_tpu.engine import native

    text = (REPO_ROOT / hvt_lint.STATS_SLOTS_H).read_text()
    m = hvt_lint._SLOT_COUNT_RE.search(text)
    assert m and int(m.group(1)) == native.STATS_SLOT_COUNT == 161
