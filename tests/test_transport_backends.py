"""Link-backend seam (PR 18): HVT_LINK_BACKEND selection, the io_uring
data plane riding the unchanged session layer, and socket-option
continuity across transparent heals.

The continuity spec is the satellite pin for a real bug class: a
re-dialed/re-accepted socket that silently loses TCP_NODELAY or the
HVT_SOCK_BUF sizing degrades every op after the first heal while all
correctness tests stay green. ``hvt_link_sockopt_probe`` reads the
options straight off the live registered link's fd, after a fault
injection forced every reconnect path to run.

Gang tests reuse the raw-Popen harness of test_failure_containment.
"""

import os

import pytest

from test_failure_containment import LIB, finish_gang, spawn_gang

pytestmark = pytest.mark.skipif(
    not os.path.exists(LIB),
    reason="C++ engine not built (make -C horovod_tpu/csrc)")


def _uring_ok():
    try:
        from horovod_tpu.engine import native
        return native.uring_supported()
    except Exception:
        return False


BACKENDS = ["tcp", pytest.param("io_uring", marks=pytest.mark.skipif(
    not _uring_ok(), reason="io_uring kernel probe failed"))]


# ------------------------------------------------------ backend selection

@pytest.mark.parametrize("backend", BACKENDS)
def test_backend_selected_and_bit_exact(tmp_path, backend):
    """An explicit HVT_LINK_BACKEND must be honored (stats info gauge
    slot reports it) and produce bit-exact allreduce results; under
    io_uring the pump must actually run on the ring (enter calls
    recorded), not silently fall back to the generic loop."""
    body = """
    x = np.arange(262144, dtype=np.float32) * 0.5 + r
    exp = sum(np.arange(262144, dtype=np.float32) * 0.5 + i
              for i in range(n))
    for i in range(6):
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"bk.{i}"))
        np.testing.assert_array_equal(res, exp)
    st = native.engine_stats()
    want = native.LINK_BACKENDS.index(os.environ["HVT_LINK_BACKEND"])
    assert st["link_backend"] == want, (st["link_backend"], want)
    if want == 1:
        assert st["uring_enters"] > 0, st
        assert st["uring_cqes"] > 0, st
    print(f"BACKEND {st['link_backend']} ENTERS {st['uring_enters']}",
          flush=True)
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=2, tmp_path=tmp_path,
        extra_env={"HVT_LINK_BACKEND": backend,
                   "HVT_OP_TIMEOUT_MS": "30000"})
    codes, outs = finish_gang(procs, logs, timeout=90)
    for rank in range(2):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank], f"rank {rank}\n{outs[rank]}"


def test_auto_backend_matches_kernel_probe(tmp_path):
    """HVT_LINK_BACKEND=auto (the default) must resolve to io_uring
    exactly when the kernel capability probe passes, and to tcp
    otherwise — same probe the Python wrapper exposes."""
    body = """
    x = np.arange(4096, dtype=np.float32) + r
    for i in range(3):
        hvt.allreduce(x, op=hvt.Sum, name=f"au.{i}")
    st = native.engine_stats()
    want = 1 if native.uring_supported() else 0
    assert st["link_backend"] == want, (st["link_backend"], want)
    print(f"AUTO {st['link_backend']}", flush=True)
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=2, tmp_path=tmp_path,
        extra_env={"HVT_LINK_BACKEND": "auto",
                   "HVT_OP_TIMEOUT_MS": "30000"})
    codes, outs = finish_gang(procs, logs, timeout=90)
    for rank in range(2):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank], f"rank {rank}\n{outs[rank]}"


# ----------------------------------------- sockopt continuity across heal

@pytest.mark.parametrize("backend", BACKENDS)
def test_sockopts_survive_transparent_heal(tmp_path, backend):
    """After flaky_conn forces both the dial-side and accept-side
    reconnect paths to run, every live data link must still carry
    TCP_NODELAY=1 and >= the HVT_SOCK_BUF send/recv buffer sizing —
    the options are per-socket, so every heal must re-apply them."""
    body = """
    x = np.arange(65536, dtype=np.float32) + r
    exp = sum(np.arange(65536, dtype=np.float32) + i for i in range(n))
    for i in range(8):
        res = np.asarray(hvt.allreduce(x, op=hvt.Sum, name=f"sc.{i}"))
        np.testing.assert_array_equal(res, exp)
    st = native.engine_stats()
    rec = sum(st["link_reconnects"].values())
    probe = native.link_sockopt_probe(1, 1 - r)  # data plane, the peer
    assert probe is not None, "no live data link to probe"
    nodelay, sndbuf, rcvbuf = probe
    assert nodelay == 1, probe
    # Linux getsockopt reports the kernel-doubled value; >= the
    # requested size catches a heal that skipped ConfigureSockBufs
    # (fresh sockets default to ~64KB here)
    assert sndbuf >= 262144, probe
    assert rcvbuf >= 262144, probe
    print(f"PROBE {probe} RECONNECTS {rec}", flush=True)
    if r == 1:
        assert rec >= 1, st["link_reconnects"]
    hvt.shutdown()
    print("CLEAN", flush=True)
    """
    procs, logs = spawn_gang(
        body, np=2, tmp_path=tmp_path,
        extra_env={"HVT_FAULT_INJECT": "flaky_conn:rank=1:count=2:after_ops=3",
                   "HVT_LINK_BACKEND": backend,
                   "HVT_SOCK_BUF": "262144",
                   "HVT_OP_TIMEOUT_MS": "30000"})
    codes, outs = finish_gang(procs, logs, timeout=120)
    for rank in range(2):
        assert codes[rank] == 0, f"rank {rank}\n{outs[rank]}"
        assert "CLEAN" in outs[rank], f"rank {rank}\n{outs[rank]}"


# --------------------------------------------------------- wrapper edges

def test_probe_without_engine_returns_none():
    """link_sockopt_probe outside a live gang (empty link registry)
    degrades to None, never crashes — the probe is diagnostics-grade."""
    from horovod_tpu.engine import native

    assert native.link_sockopt_probe(1, 0) is None


def test_uring_supported_is_bool():
    from horovod_tpu.engine import native

    assert native.uring_supported() in (True, False)
